"""Mutation context: the write path of the frontend (ref frontend/context.js).

Accumulates ops for a change request while simultaneously applying an
equivalent local patch so the mutable proxies see their own writes.
"""

import datetime

from ..common import parse_op_id, uuid
from .apply_patch import interpret_patch, datetime_to_timestamp
from .values import Counter, WriteableCounter, Int, Uint, Float64, \
    MAX_SAFE_INTEGER, MIN_SAFE_INTEGER
from .text import Text
from .table import Table
from .views import MapView, ListView, get_object_id

PRIMITIVES = (str, bool, int, float, type(None))
WRAPPERS = (datetime.datetime, Counter, Int, Uint, Float64)


def _is_primitive(value):
    return isinstance(value, PRIMITIVES) or isinstance(value, WRAPPERS)


class Context:
    def __init__(self, doc, actor_id, apply_patch=None):
        self.actor_id = actor_id
        self.next_op_num = doc._state['maxOp'] + 1
        self.cache = doc._cache
        self.updated = {}
        self.ops = []
        self.apply_patch = apply_patch if apply_patch is not None else interpret_patch
        self.instantiate_object = None  # set by proxies.root_object_proxy

    def add_op(self, operation):
        self.ops.append(operation)
        if operation['action'] == 'set' and 'values' in operation:
            self.next_op_num += len(operation['values'])
        elif operation['action'] == 'del' and operation.get('multiOp'):
            self.next_op_num += operation['multiOp']
        else:
            self.next_op_num += 1

    def next_op_id(self):
        return f'{self.next_op_num}@{self.actor_id}'

    def get_value_description(self, value):
        """JS value -> typed patch description (ref context.js:51-93)."""
        if isinstance(value, datetime.datetime):
            return {'type': 'value', 'value': datetime_to_timestamp(value),
                    'datatype': 'timestamp'}
        if isinstance(value, Int):
            return {'type': 'value', 'value': value.value, 'datatype': 'int'}
        if isinstance(value, Uint):
            return {'type': 'value', 'value': value.value, 'datatype': 'uint'}
        if isinstance(value, Float64):
            return {'type': 'value', 'value': value.value, 'datatype': 'float64'}
        if isinstance(value, Counter):
            return {'type': 'value', 'value': value.value, 'datatype': 'counter'}
        if isinstance(value, bool) or value is None or isinstance(value, str):
            return {'type': 'value', 'value': value}
        if isinstance(value, int):
            if MIN_SAFE_INTEGER <= value <= MAX_SAFE_INTEGER:
                return {'type': 'value', 'value': value, 'datatype': 'int'}
            return {'type': 'value', 'value': float(value), 'datatype': 'float64'}
        if isinstance(value, float):
            if value.is_integer() and MIN_SAFE_INTEGER <= value <= MAX_SAFE_INTEGER:
                return {'type': 'value', 'value': int(value), 'datatype': 'int'}
            return {'type': 'value', 'value': value, 'datatype': 'float64'}
        # Nested object (map, list, text, or table)
        object_id = get_object_id(value)
        if not object_id:
            raise ValueError(f'Object {value!r} has no objectId')
        type_ = self.get_object_type(object_id)
        if type_ in ('list', 'text'):
            return {'objectId': object_id, 'type': type_, 'edits': []}
        return {'objectId': object_id, 'type': type_, 'props': {}}

    def get_values_descriptions(self, path, object, key):
        """(ref context.js:100-124)"""
        if isinstance(object, Table):
            value = Table.by_id(object, key)
            op_id = object.op_ids.get(key)
            return {op_id: self.get_value_description(value)} if value is not None else {}
        if isinstance(object, Text):
            if key >= len(object.elems):
                return {}
            value = object.elems[key]['value']
            elem_id = object.elems[key]['elemId']
            return {elem_id: self.get_value_description(value)} if value is not None else {}
        conflicts = object._conflicts[key] if isinstance(object, ListView) and \
            key < len(object._conflicts) else \
            (object._conflicts.get(key) if isinstance(object, MapView) else None)
        if conflicts is None:
            raise ValueError(f'No children at key {key} of path {path!r}')
        return {op_id: self.get_value_description(v) for op_id, v in conflicts.items()}

    def get_property_value(self, object, key, op_id):
        if isinstance(object, Table):
            return Table.by_id(object, key)
        if isinstance(object, Text):
            return object.elems[key]['value']
        return object._conflicts[key][op_id]

    def get_subpatch(self, patch, path):
        """(ref context.js:151-180)"""
        if not path:
            return patch
        subpatch = patch
        object = self.get_object('_root')
        for path_elem in path:
            key = path_elem['key']
            values = self.get_values_descriptions(path, object, key)
            if 'props' in subpatch:
                if key not in subpatch['props']:
                    subpatch['props'][key] = values
            elif 'edits' in subpatch:
                for op_id, value in values.items():
                    subpatch['edits'].append(
                        {'action': 'update', 'index': key, 'opId': op_id,
                         'value': value})
            next_op_id = None
            for op_id, value in values.items():
                if value.get('objectId') == path_elem['objectId']:
                    next_op_id = op_id
            if next_op_id is None:
                raise ValueError(
                    f"Cannot find path object with objectId {path_elem['objectId']}")
            subpatch = values[next_op_id]
            object = self.get_property_value(object, key, next_op_id)
        return subpatch

    def get_object(self, object_id):
        # Explicit None checks: an empty MapView/ListView/Table is falsy in
        # Python (unlike any JS object), so `updated.get(id) or cache.get(id)`
        # would wrongly fall through to the stale cache
        object = self.updated.get(object_id)
        if object is None:
            object = self.cache.get(object_id)
        if object is None:
            raise ValueError(f'Target object does not exist: {object_id}')
        return object

    def get_object_type(self, object_id):
        if object_id == '_root':
            return 'map'
        object = self.get_object(object_id)
        if isinstance(object, Text):
            return 'text'
        if isinstance(object, Table):
            return 'table'
        if isinstance(object, ListView):
            return 'list'
        return 'map'

    def get_object_field(self, path, object_id, key):
        """Returns the value at `key`, proxied if it is an object
        (ref context.js:198-216)."""
        object = self.get_object(object_id)
        try:
            value = object[key]
        except (KeyError, IndexError):
            return None
        if isinstance(value, Counter):
            return WriteableCounter(value.value, self, path, object_id, key)
        if isinstance(value, (MapView, ListView, Text, Table)):
            child_id = get_object_id(value)
            subpath = path + [{'key': key, 'objectId': child_id}]
            return self.instantiate_object(subpath, child_id)
        return value

    def create_nested_objects(self, obj, key, value, insert, pred, elem_id=None):
        """Recursively create Automerge objects for a nested value
        (ref context.js:230-273)."""
        if get_object_id(value):
            raise ValueError('Cannot create a reference to an existing document object')
        object_id = self.next_op_id()

        if isinstance(value, Text):
            op = {'action': 'makeText', 'obj': obj, 'insert': insert, 'pred': pred}
            op['elemId' if elem_id else 'key'] = elem_id if elem_id else key
            self.add_op(op)
            subpatch = {'objectId': object_id, 'type': 'text', 'edits': []}
            self.insert_list_items(subpatch, 0, list(value), True)
            return subpatch
        if isinstance(value, Table):
            if value.count > 0:
                raise ValueError('Assigning a non-empty Table object is not supported')
            op = {'action': 'makeTable', 'obj': obj, 'insert': insert, 'pred': pred}
            op['elemId' if elem_id else 'key'] = elem_id if elem_id else key
            self.add_op(op)
            return {'objectId': object_id, 'type': 'table', 'props': {}}
        if isinstance(value, (list, tuple, ListView)):
            op = {'action': 'makeList', 'obj': obj, 'insert': insert, 'pred': pred}
            op['elemId' if elem_id else 'key'] = elem_id if elem_id else key
            self.add_op(op)
            subpatch = {'objectId': object_id, 'type': 'list', 'edits': []}
            self.insert_list_items(subpatch, 0, list(value), True)
            return subpatch
        # Map object (anything else is not an assignable value,
        # ref context.js:88-91 "Unsupported type of value")
        if not hasattr(value, 'keys'):
            raise TypeError(
                f'Unsupported type of value: {type(value).__name__}')
        op = {'action': 'makeMap', 'obj': obj, 'insert': insert, 'pred': pred}
        op['elemId' if elem_id else 'key'] = elem_id if elem_id else key
        self.add_op(op)
        props = {}
        for nested in sorted(value.keys()):
            op_id = self.next_op_id()
            value_patch = self.set_value(object_id, nested, value[nested], False, [])
            props[nested] = {op_id: value_patch}
        return {'objectId': object_id, 'type': 'map', 'props': props}

    def set_value(self, object_id, key, value, insert, pred, elem_id=None):
        """(ref context.js:289-309)"""
        if not object_id:
            raise ValueError('setValue needs an objectId')
        if key == '':
            raise ValueError('The key of a map entry must not be an empty string')
        if not _is_primitive(value):
            return self.create_nested_objects(object_id, key, value, insert, pred,
                                              elem_id)
        description = self.get_value_description(value)
        op = {'action': 'set', 'obj': object_id, 'insert': insert,
              'value': description['value'], 'pred': pred}
        if elem_id:
            op['elemId'] = elem_id
        else:
            op['key'] = key
        if description.get('datatype'):
            op['datatype'] = description['datatype']
        self.add_op(op)
        return description

    def apply_at_path(self, path, callback):
        diff = {'objectId': '_root', 'type': 'map', 'props': {}}
        callback(self.get_subpatch(diff, path))
        self.apply_patch(diff, self.cache['_root'], self.updated)

    def set_map_key(self, path, key, value):
        """(ref context.js:325-348)"""
        if not isinstance(key, str):
            raise ValueError(f'The key of a map entry must be a string, not {type(key)}')
        object_id = '_root' if not path else path[-1]['objectId']
        object = self.get_object(object_id)
        if isinstance(object.get(key), Counter):
            raise ValueError('Cannot overwrite a Counter object; use .increment() or '
                             '.decrement() to change its value.')
        existing = object.get(key)
        conflicted = len(object._conflicts.get(key, {})) > 1
        if not self._values_equal(existing, value) or conflicted or \
                key not in object:
            def update(subpatch):
                pred = get_pred(object, key)
                op_id = self.next_op_id()
                value_patch = self.set_value(object_id, key, value, False, pred)
                subpatch['props'][key] = {op_id: value_patch}
            self.apply_at_path(path, update)

    def _values_equal(self, existing, value):
        """Mirror of the JS `object[key] !== value` no-op check: primitives
        compare by value (with JS-style type strictness), objects by identity."""
        prim = (str, int, float, type(None))
        if isinstance(existing, prim) and isinstance(value, prim):
            if isinstance(existing, bool) != isinstance(value, bool):
                return False
            if type(existing) is not type(value) and not (
                    isinstance(existing, (int, float)) and
                    isinstance(value, (int, float)) and
                    not isinstance(existing, bool) and not isinstance(value, bool)):
                return False
            return existing == value
        return existing is value

    def delete_map_key(self, path, key):
        object_id = '_root' if not path else path[-1]['objectId']
        object = self.get_object(object_id)
        if key in object:
            pred = get_pred(object, key)
            self.add_op({'action': 'del', 'obj': object_id, 'key': key,
                         'insert': False, 'pred': pred})
            self.apply_at_path(path, lambda subpatch: subpatch['props'].update({key: {}}))

    def insert_list_items(self, subpatch, index, values, new_object):
        """Multi-insert optimization: runs of same-datatype primitives become
        one set op with a values array (ref context.js:370-405)."""
        list_ = [] if new_object else self.get_object(subpatch['objectId'])
        if index < 0 or index > len(list_):
            raise IndexError(
                f'List index {index} is out of bounds for list of length {len(list_)}')
        if not values:
            return
        elem_id = get_elem_id(list_, index, insert=True)
        all_primitive = all(_is_primitive(v) for v in values)
        descriptions = [self.get_value_description(v) for v in values] \
            if all_primitive else []
        same_datatype = all(d.get('datatype') == descriptions[0].get('datatype')
                            for d in descriptions) if descriptions else False

        if all_primitive and same_datatype and len(values) > 1:
            next_elem_id = self.next_op_id()
            datatype = descriptions[0].get('datatype')
            plain_values = [d['value'] for d in descriptions]
            op = {'action': 'set', 'obj': subpatch['objectId'], 'elemId': elem_id,
                  'insert': True, 'values': plain_values, 'pred': []}
            edit = {'action': 'multi-insert', 'elemId': next_elem_id, 'index': index,
                    'values': plain_values}
            if datatype:
                op['datatype'] = datatype
                edit['datatype'] = datatype
            self.add_op(op)
            subpatch['edits'].append(edit)
        else:
            for offset, value in enumerate(values):
                next_elem_id = self.next_op_id()
                value_patch = self.set_value(subpatch['objectId'], index + offset,
                                             value, True, [], elem_id)
                elem_id = next_elem_id
                subpatch['edits'].append(
                    {'action': 'insert', 'index': index + offset, 'elemId': elem_id,
                     'opId': elem_id, 'value': value_patch})

    def set_list_index(self, path, index, value):
        """(ref context.js:411-435)"""
        object_id = '_root' if not path else path[-1]['objectId']
        list_ = self.get_object(object_id)
        if index >= len(list_):
            insertions = [None] * (index - len(list_))
            insertions.append(value)
            return self.splice(path, len(list_), 0, insertions)
        current = list_[index] if not isinstance(list_, Text) else \
            list_.elems[index]['value']
        if isinstance(current, Counter):
            raise ValueError('Cannot overwrite a Counter object; use .increment() or '
                             '.decrement() to change its value.')
        conflicted = isinstance(list_, ListView) and \
            len(list_._conflicts[index] or {}) > 1
        if not self._values_equal(current, value) or conflicted:
            def update(subpatch):
                pred = get_pred(list_, index)
                op_id = self.next_op_id()
                value_patch = self.set_value(object_id, index, value, False, pred,
                                             get_elem_id(list_, index))
                subpatch['edits'].append({'action': 'update', 'index': index,
                                          'opId': op_id, 'value': value_patch})
            self.apply_at_path(path, update)

    def splice(self, path, start, deletions, insertions):
        """Multi-delete run compression (ref context.js:441-502)."""
        object_id = '_root' if not path else path[-1]['objectId']
        list_ = self.get_object(object_id)
        length = len(list_)
        if start < 0 or deletions < 0 or start > length - deletions:
            raise IndexError(f'{deletions} deletions starting at index {start} are '
                             f'out of bounds for list of length {length}')
        if deletions == 0 and not insertions:
            return
        patch = {'diffs': {'objectId': '_root', 'type': 'map', 'props': {}}}
        subpatch = self.get_subpatch(patch['diffs'], path)

        if deletions > 0:
            op = None
            last_elem_parsed = last_pred_parsed = None
            for i in range(deletions):
                if isinstance(self.get_object_field(path, object_id, start + i),
                              Counter):
                    # Deleting counters from lists is unsupported
                    # (rationale: context.js:455-471)
                    raise TypeError(
                        'Unsupported operation: deleting a counter from a list')
                this_elem = get_elem_id(list_, start + i)
                this_elem_parsed = parse_op_id(this_elem)
                this_pred = get_pred(list_, start + i)
                this_pred_parsed = parse_op_id(this_pred[0]) \
                    if len(this_pred) == 1 else None
                if op is not None and last_elem_parsed and last_pred_parsed and \
                        this_pred_parsed and \
                        last_elem_parsed[1] == this_elem_parsed[1] and \
                        last_elem_parsed[0] + 1 == this_elem_parsed[0] and \
                        last_pred_parsed[1] == this_pred_parsed[1] and \
                        last_pred_parsed[0] + 1 == this_pred_parsed[0]:
                    op['multiOp'] = op.get('multiOp', 1) + 1
                else:
                    if op is not None:
                        self.add_op(op)
                    op = {'action': 'del', 'obj': object_id, 'elemId': this_elem,
                          'insert': False, 'pred': this_pred}
                last_elem_parsed = this_elem_parsed
                last_pred_parsed = this_pred_parsed
            self.add_op(op)
            subpatch['edits'].append({'action': 'remove', 'index': start,
                                      'count': deletions})

        if insertions:
            self.insert_list_items(subpatch, start, insertions, False)
        self.apply_patch(patch['diffs'], self.cache['_root'], self.updated)

    def add_table_row(self, path, row):
        """(ref context.js:508-527)"""
        if not isinstance(row, (dict, MapView)) or isinstance(row, (list, tuple)):
            raise TypeError('A table row must be an object')
        if get_object_id(row):
            raise TypeError('Cannot reuse an existing object as table row')
        if 'id' in row:
            raise TypeError('A table row must not have an "id" property; '
                            'it is generated automatically')
        id = uuid()
        value_patch = self.set_value(path[-1]['objectId'], id, dict(row), False, [])
        self.apply_at_path(path, lambda subpatch: subpatch['props'].update(
            {id: {value_patch['objectId']: value_patch}}))
        return id

    def delete_table_row(self, path, row_id, pred):
        object_id = path[-1]['objectId']
        table = self.get_object(object_id)
        if Table.by_id(table, row_id) is not None:
            self.add_op({'action': 'del', 'obj': object_id, 'key': row_id,
                         'insert': False, 'pred': [pred]})
            self.apply_at_path(path, lambda subpatch: subpatch['props'].update(
                {row_id: {}}))

    def increment(self, path, key, delta):
        """(ref context.js:546-573)"""
        object_id = '_root' if not path else path[-1]['objectId']
        object = self.get_object(object_id)
        if isinstance(object, Text):
            current = object.elems[key]['value']
        else:
            current = object[key] if not isinstance(object, Table) else None
        if not isinstance(current, Counter):
            raise TypeError('Only counter values can be incremented')
        type_ = self.get_object_type(object_id)
        value = current.value + delta
        op_id = self.next_op_id()
        pred = get_pred(object, key)
        if type_ in ('list', 'text'):
            elem_id = get_elem_id(object, key, False)
            self.add_op({'action': 'inc', 'obj': object_id, 'elemId': elem_id,
                         'value': delta, 'insert': False, 'pred': pred})
        else:
            self.add_op({'action': 'inc', 'obj': object_id, 'key': key,
                         'value': delta, 'insert': False, 'pred': pred})

        def update(subpatch):
            if type_ in ('list', 'text'):
                subpatch['edits'].append(
                    {'action': 'update', 'index': key, 'opId': op_id,
                     'value': {'value': value, 'datatype': 'counter'}})
            else:
                subpatch['props'][key] = {op_id: {'value': value,
                                                  'datatype': 'counter'}}
        self.apply_at_path(path, update)


def get_pred(object, key):
    """(ref context.js:576-586)"""
    if isinstance(object, Table):
        return [object.op_ids[key]]
    if isinstance(object, Text):
        return list(object.elems[key].get('pred', []))
    if isinstance(object, MapView):
        return list(object._conflicts.get(key, {}).keys())
    if isinstance(object, ListView):
        if key < len(object._conflicts) and object._conflicts[key]:
            return list(object._conflicts[key].keys())
        return []
    return []


def get_elem_id(list_, index, insert=False):
    """(ref context.js:588-596)"""
    if insert:
        if index == 0:
            return '_head'
        index -= 1
    if isinstance(list_, ListView):
        return list_._elem_ids[index]
    if isinstance(list_, Text):
        return list_.elems[index]['elemId']
    if hasattr(list_, 'get_elem_id'):
        return list_.get_elem_id(index)
    raise IndexError(f'Cannot find elemId at list index {index}')
