"""Patch interpreter: applies backend patches to the immutable document tree
(ref frontend/apply_patch.js)."""

import datetime

from ..common import parse_op_id
from .values import Counter
from .text import instantiate_text
from .table import instantiate_table
from .views import MapView, RootView, ListView, get_object_id


def timestamp_to_datetime(ms):
    return datetime.datetime.fromtimestamp(ms / 1000.0, datetime.timezone.utc)


def datetime_to_timestamp(dt):
    return int(round(dt.timestamp() * 1000))


def get_value(patch, obj, updated):
    """Reconstruct a value from a patch node (ref apply_patch.js:10-27)."""
    if patch.get('objectId'):
        if obj is not None and get_object_id(obj) != patch['objectId']:
            obj = None
        return interpret_patch(patch, obj, updated)
    if patch.get('datatype') == 'timestamp':
        return timestamp_to_datetime(patch['value'])
    if patch.get('datatype') == 'counter':
        return Counter(patch['value'])
    return patch.get('value')


def lamport_compare_key(ts):
    """Sort key for opId strings; plain strings sort as (0, string)
    (ref apply_patch.js:33-42)."""
    try:
        counter, actor = parse_op_id(ts)
        return (counter, actor)
    except ValueError:
        return (0, ts)


def apply_properties(props, object, conflicts, updated):
    """Per-key conflict resolution: the greatest opId in Lamport order wins,
    all values are kept in `conflicts[key]` (ref apply_patch.js:57-79)."""
    if not props:
        return
    for key, key_props in props.items():
        op_ids = sorted(key_props.keys(), key=lamport_compare_key, reverse=True)
        values = {}
        for op_id in op_ids:
            subpatch = key_props[op_id]
            existing = conflicts.get(key, {}).get(op_id) if isinstance(conflicts, dict) \
                else None
            values[op_id] = get_value(subpatch, existing, updated)
        if not op_ids:
            object.pop(key, None)
            conflicts.pop(key, None)
        else:
            object[key] = values[op_ids[0]]
            conflicts[key] = values


def _clone_map_object(original, object_id):
    data = dict(original._data) if original is not None else {}
    conflicts = dict(original._conflicts) if original is not None else {}
    if object_id == '_root':
        view = RootView(data, conflicts)
        if original is not None:
            view._options = getattr(original, '_options', None)
    else:
        view = MapView(object_id, data, conflicts)
    return view


def update_map_object(patch, obj, updated):
    object_id = patch['objectId']
    if object_id not in updated:
        updated[object_id] = _clone_map_object(obj, object_id)
    view = updated[object_id]
    apply_properties(patch.get('props'), view._data, view._conflicts, updated)
    return view


def update_table_object(patch, obj, updated):
    """(ref apply_patch.js:114-135)"""
    object_id = patch['objectId']
    if object_id not in updated:
        updated[object_id] = obj._clone() if obj is not None \
            else instantiate_table(object_id)
    table = updated[object_id]
    for key, key_props in (patch.get('props') or {}).items():
        op_ids = list(key_props.keys())
        if len(op_ids) == 0:
            table.remove(key)
        elif len(op_ids) == 1:
            subpatch = key_props[op_ids[0]]
            table._set(key, get_value(subpatch, table.by_id(key), updated), op_ids[0])
        else:
            raise ValueError('Conflicts are not supported on properties of a table')
    return table


def _clone_list_object(original, object_id):
    data = list(original._data) if original is not None else []
    conflicts = list(original._conflicts) if original is not None else []
    elem_ids = list(original._elem_ids) if original is not None else []
    return ListView(object_id, data, conflicts, elem_ids)


def update_list_object(patch, obj, updated):
    """(ref apply_patch.js:156-213)"""
    object_id = patch['objectId']
    if object_id not in updated:
        updated[object_id] = _clone_list_object(obj, object_id)
    view = updated[object_id]
    data, conflicts, elem_ids = view._data, view._conflicts, view._elem_ids
    edits = patch['edits']
    i = 0
    while i < len(edits):
        edit = edits[i]
        if edit['action'] in ('insert', 'update'):
            index = edit['index']
            old_value = conflicts[index].get(edit['opId']) \
                if edit['action'] == 'update' and index < len(conflicts) and \
                isinstance(conflicts[index], dict) else None
            last_value = get_value(edit['value'], old_value, updated)
            values = {edit['opId']: last_value}
            # Consecutive updates at the same index form a conflict set; the
            # last (greatest Lamport timestamp) is the default resolution
            while i < len(edits) - 1 and edits[i + 1].get('index') == index and \
                    edits[i + 1]['action'] == 'update':
                i += 1
                conflict = edits[i]
                old2 = conflicts[index].get(conflict['opId']) \
                    if index < len(conflicts) and isinstance(conflicts[index], dict) \
                    else None
                last_value = get_value(conflict['value'], old2, updated)
                values[conflict['opId']] = last_value
            if edit['action'] == 'insert':
                data.insert(index, last_value)
                conflicts.insert(index, values)
                elem_ids.insert(index, edit['elemId'])
            else:
                data[index] = last_value
                conflicts[index] = values
        elif edit['action'] == 'multi-insert':
            counter, actor = parse_op_id(edit['elemId'])
            datatype = edit.get('datatype')
            new_elems, new_values, new_conflicts = [], [], []
            for offset, value in enumerate(edit['values']):
                elem_id = f'{counter + offset}@{actor}'
                value = get_value({'value': value, 'datatype': datatype}, None, updated)
                new_values.append(value)
                new_conflicts.append({elem_id: value})
                new_elems.append(elem_id)
            index = edit['index']
            data[index:index] = new_values
            conflicts[index:index] = new_conflicts
            elem_ids[index:index] = new_elems
        elif edit['action'] == 'remove':
            index, count = edit['index'], edit['count']
            del data[index:index + count]
            del conflicts[index:index + count]
            del elem_ids[index:index + count]
        i += 1
    return view


def update_text_object(patch, obj, updated):
    """(ref apply_patch.js:220-259)"""
    object_id = patch['objectId']
    if object_id in updated:
        elems = updated[object_id].elems
    elif obj is not None:
        elems = list(obj.elems)
    else:
        elems = []
    for edit in patch['edits']:
        if edit['action'] == 'insert':
            value = get_value(edit['value'], None, updated)
            elems.insert(edit['index'],
                         {'elemId': edit['elemId'], 'pred': [edit['opId']],
                          'value': value})
        elif edit['action'] == 'multi-insert':
            counter, actor = parse_op_id(edit['elemId'])
            datatype = edit.get('datatype')
            new_elems = []
            for offset, value in enumerate(edit['values']):
                value = get_value({'datatype': datatype, 'value': value}, None, updated)
                elem_id = f'{counter + offset}@{actor}'
                new_elems.append({'elemId': elem_id, 'pred': [elem_id], 'value': value})
            elems[edit['index']:edit['index']] = new_elems
        elif edit['action'] == 'update':
            index = edit['index']
            elem_id = elems[index]['elemId']
            value = get_value(edit['value'], elems[index]['value'], updated)
            elems[index] = {'elemId': elem_id, 'pred': [edit['opId']], 'value': value}
        elif edit['action'] == 'remove':
            index, count = edit['index'], edit['count']
            del elems[index:index + count]
    updated[object_id] = instantiate_text(object_id, elems)
    return updated[object_id]


def interpret_patch(patch, obj, updated):
    """Apply a patch node to the (immutable) object `obj`, placing writable
    clones into `updated` (ref apply_patch.js:266-284)."""
    if obj is not None and not patch.get('props') and not patch.get('edits') and \
            patch['objectId'] not in updated:
        return obj
    if patch['type'] == 'map':
        return update_map_object(patch, obj, updated)
    if patch['type'] == 'table':
        return update_table_object(patch, obj, updated)
    if patch['type'] == 'list':
        return update_list_object(patch, obj, updated)
    if patch['type'] == 'text':
        return update_text_object(patch, obj, updated)
    raise TypeError(f"Unknown object type: {patch.get('type')}")


def clone_root_object(root):
    if get_object_id(root) != '_root':
        raise ValueError(f'Not the root object: {get_object_id(root)}')
    return _clone_map_object(root, '_root')
