"""Table: an unordered row collection keyed by UUID (ref frontend/table.js)."""

from .views import MapView, get_object_id


def _compare_rows(properties, row):
    key = []
    for prop in properties:
        v = row.get(prop) if hasattr(row, 'get') else None
        key.append((0, v) if isinstance(v, (int, float)) and
                   not isinstance(v, bool) else (1, str(v)))
    return key


class Table:
    """Rows are identified by unique IDs; rows get an auto-generated `id`
    property. Conflicts are impossible since row IDs are unique."""

    def __init__(self):
        self.entries = {}
        self.op_ids = {}
        self._object_id = None

    def by_id(self, id):
        return self.entries.get(id)

    @property
    def ids(self):
        return [key for key, entry in self.entries.items()
                if isinstance(entry, MapView) and entry.get('id') == key]

    @property
    def count(self):
        return len(self.ids)

    @property
    def rows(self):
        return [self.by_id(id) for id in self.ids]

    def filter(self, callback):
        return [row for row in self.rows if callback(row)]

    def find(self, callback):
        for row in self.rows:
            if callback(row):
                return row
        return None

    def map(self, callback):
        return [callback(row) for row in self.rows]

    def sort(self, arg=None):
        if callable(arg):
            import functools
            return sorted(self.rows, key=functools.cmp_to_key(arg))
        if isinstance(arg, str):
            return sorted(self.rows, key=lambda r: _compare_rows([arg], r))
        if isinstance(arg, (list, tuple)):
            return sorted(self.rows, key=lambda r: _compare_rows(list(arg), r))
        if arg is None:
            return sorted(self.rows, key=lambda r: _compare_rows(['id'], r))
        raise TypeError(f'Unsupported sorting argument: {arg}')

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return self.count

    def __eq__(self, other):
        if isinstance(other, Table):
            return {id: self.by_id(id) for id in self.ids} == \
                {id: other.by_id(id) for id in other.ids}
        return NotImplemented

    def __ne__(self, other):
        result = self.__eq__(other)
        return NotImplemented if result is NotImplemented else not result

    def _clone(self):
        if not self._object_id:
            raise ValueError('clone() requires the objectId to be set')
        return instantiate_table(self._object_id, dict(self.entries), dict(self.op_ids))

    def _set(self, id, value, op_id):
        # Rows get an automatically-generated `id` property (ref table.js:156-160)
        if isinstance(value, MapView):
            value._data['id'] = id
        self.entries[id] = value
        self.op_ids[id] = op_id

    def remove(self, id):
        # Tolerate missing ids like the JS `delete` operator does: a patch may
        # remove a row that was created and deleted within the same change
        self.entries.pop(id, None)
        self.op_ids.pop(id, None)

    def get_writeable(self, context, path):
        if not self._object_id:
            raise ValueError('get_writeable() requires the objectId to be set')
        instance = WriteableTable.__new__(WriteableTable)
        instance._object_id = self._object_id
        instance.context = context
        instance.entries = self.entries
        instance.op_ids = self.op_ids
        instance.path = path
        return instance

    def to_json(self):
        return {id: self.by_id(id).to_py() if hasattr(self.by_id(id), 'to_py')
                else self.by_id(id) for id in self.ids}


class WriteableTable(Table):
    """Table bound to a change context (ref frontend/table.js:217-249)."""

    def by_id(self, id):
        entry = self.entries.get(id)
        if isinstance(entry, MapView) and entry.get('id') == id:
            object_id = get_object_id(entry)
            return self.context.instantiate_object(
                self.path + [{'key': id, 'objectId': object_id}], object_id)
        return None

    def add(self, row):
        return self.context.add_table_row(self.path, row)

    def remove(self, id):
        entry = self.entries.get(id)
        if isinstance(entry, MapView) and entry.get('id') == id:
            self.context.delete_table_row(self.path, id, self.op_ids[id])
        else:
            raise ValueError(f'There is no row with ID {id} in this table')


def instantiate_table(object_id, entries=None, op_ids=None):
    if not object_id:
        raise ValueError('instantiate_table requires an objectId to be given')
    instance = Table.__new__(Table)
    instance._object_id = object_id
    instance.entries = entries if entries is not None else {}
    instance.op_ids = op_ids if op_ids is not None else {}
    return instance
