"""Text: a character-sequence CRDT view (ref frontend/text.js)."""

from .views import get_object_id


class Text:
    """A sequence of characters (or embedded objects) supporting concurrent
    editing. `elems` is a list of {'elemId', 'pred', 'value'} dicts."""

    def __init__(self, text=None):
        if isinstance(text, str):
            self.elems = [{'value': ch} for ch in text]
        elif isinstance(text, (list, tuple)):
            self.elems = [{'value': v} for v in text]
        elif text is None:
            self.elems = []
        else:
            raise TypeError(f'Unsupported initial value for Text: {text}')
        self._object_id = None
        self.context = None
        self.path = None

    @property
    def length(self):
        return len(self.elems)

    def __len__(self):
        return len(self.elems)

    def get(self, index):
        value = self.elems[index]['value']
        if self.context is not None and get_object_id(value):
            object_id = get_object_id(value)
            return self.context.instantiate_object(
                self.path + [{'key': index, 'objectId': object_id}], object_id)
        return value

    def __getitem__(self, index):
        if isinstance(index, slice):
            return [self.get(i) for i in range(*index.indices(len(self.elems)))]
        return self.get(index)

    def get_elem_id(self, index):
        return self.elems[index]['elemId']

    def __iter__(self):
        for elem in self.elems:
            yield elem['value']

    def __str__(self):
        return ''.join(e['value'] for e in self.elems if isinstance(e['value'], str))

    def __eq__(self, other):
        if isinstance(other, Text):
            return [e['value'] for e in self.elems] == \
                [e['value'] for e in other.elems]
        if isinstance(other, str):
            return str(self) == other
        return NotImplemented

    def __ne__(self, other):
        result = self.__eq__(other)
        return NotImplemented if result is NotImplemented else not result

    def __repr__(self):
        return f'Text({str(self)!r})'

    def to_spans(self):
        """The content as strings interleaved with non-character elements
        (ref frontend/text.js:78-96)."""
        spans = []
        chars = ''
        for elem in self.elems:
            if isinstance(elem['value'], str):
                chars += elem['value']
            else:
                if chars:
                    spans.append(chars)
                    chars = ''
                spans.append(elem['value'])
        if chars:
            spans.append(chars)
        return spans

    def to_json(self):
        return str(self)

    def get_writeable(self, context, path):
        if not self._object_id:
            raise ValueError('get_writeable() requires the objectId to be set')
        instance = instantiate_text(self._object_id, self.elems)
        instance.context = context
        instance.path = path
        return instance

    def set(self, index, value):
        if self.context is not None:
            self.context.set_list_index(self.path, index, value)
        elif self._object_id is None:
            self.elems[index] = {'value': value}
        else:
            raise TypeError(
                'Automerge.Text object cannot be modified outside of a change block')
        return self

    def __setitem__(self, index, value):
        self.set(index, value)

    def insert_at(self, index, *values):
        if self.context is not None:
            self.context.splice(self.path, index, 0, list(values))
        elif self._object_id is None:
            self.elems[index:index] = [{'value': v} for v in values]
        else:
            raise TypeError(
                'Automerge.Text object cannot be modified outside of a change block')
        return self

    def delete_at(self, index, num_delete=1):
        if self.context is not None:
            self.context.splice(self.path, index, num_delete, [])
        elif self._object_id is None:
            del self.elems[index:index + num_delete]
        else:
            raise TypeError(
                'Automerge.Text object cannot be modified outside of a change block')
        return self


def instantiate_text(object_id, elems):
    instance = Text.__new__(Text)
    instance._object_id = object_id
    instance.elems = elems
    instance.context = None
    instance.path = None
    return instance
