"""Immutable document views: the Python counterpart of the reference's
plain-JS-objects-with-hidden-Symbols document representation
(ref frontend/constants.js, frontend/apply_patch.js clone helpers).

A document is a tree of MapView / ListView / Text / Table objects plus
primitive values. Views compare equal to plain dicts/lists with the same
values, so tests and applications can treat them as ordinary data.
"""

from collections.abc import Mapping, Sequence


class MapView(Mapping):
    """Read-only map object; `_conflicts` maps key -> {opId: value}."""

    def __init__(self, object_id, data=None, conflicts=None):
        self._object_id = object_id
        self._data = data if data is not None else {}
        self._conflicts = conflicts if conflicts is not None else {}

    def __getitem__(self, key):
        return self._data[key]

    def __iter__(self):
        return iter(self._data)

    def __len__(self):
        return len(self._data)

    def __contains__(self, key):
        return key in self._data

    def get(self, key, default=None):
        return self._data.get(key, default)

    def __eq__(self, other):
        if isinstance(other, MapView):
            return self._data == other._data
        if isinstance(other, Mapping):
            return dict(self._data) == dict(other)
        return NotImplemented

    def __ne__(self, other):
        result = self.__eq__(other)
        return NotImplemented if result is NotImplemented else not result

    def __repr__(self):
        return f'MapView({self._data!r})'

    def to_py(self):
        return {k: _to_py(v) for k, v in self._data.items()}


class RootView(MapView):
    """The document root: a MapView carrying document-level hidden state."""

    def __init__(self, data=None, conflicts=None):
        super().__init__('_root', data, conflicts)
        self._options = None
        self._cache = None
        self._state = None
        self._change_context = None


class ListView(Sequence):
    """Read-only list object; `_conflicts` is a list of {opId: value} and
    `_elem_ids` the stable element identity of each index."""

    def __init__(self, object_id, data=None, conflicts=None, elem_ids=None):
        self._object_id = object_id
        self._data = data if data is not None else []
        self._conflicts = conflicts if conflicts is not None else []
        self._elem_ids = elem_ids if elem_ids is not None else []

    def __getitem__(self, index):
        return self._data[index]

    def __len__(self):
        return len(self._data)

    def __iter__(self):
        return iter(self._data)

    def __eq__(self, other):
        if isinstance(other, ListView):
            return self._data == other._data
        if isinstance(other, (list, tuple)):
            return self._data == list(other)
        return NotImplemented

    def __ne__(self, other):
        result = self.__eq__(other)
        return NotImplemented if result is NotImplemented else not result

    def __repr__(self):
        return f'ListView({self._data!r})'

    def index(self, value, *args):
        return self._data.index(value, *args)

    def to_py(self):
        return [_to_py(v) for v in self._data]


def _to_py(value):
    if isinstance(value, (MapView, ListView)):
        return value.to_py()
    if hasattr(value, 'to_json'):
        return value.to_json()
    return value


def get_object_id(obj):
    return getattr(obj, '_object_id', None)
