"""Flight recorder: a bounded ring of recent structured events plus the
forensic dump that fires when something goes wrong.

The health counters (metrics.py) can say "quarantined_docs moved by 1";
this module records WHICH doc, in WHAT phase, with WHAT typed error, and
what the surrounding events were. Event recording is always on — the
events are rare (quarantines, truncations, checkpoints, overflow) and an
append into a deque costs nothing against the faults they describe. The
event ring holds ONLY these fault/health events; a traced run's phase
timeline is read out of the span ring's tail at dump time, so thousands
of span closes can never evict the handful of fault events the dump
exists to preserve.

``dump_flight_record(trigger, detail)`` assembles the forensic report —
trigger, detail, the event ring, the most recent spans (when spans are
enabled), health-counter and histogram snapshots — keeps it in memory
(``last_flight_record()``) and, when a dump directory is configured
(``configure(dump_dir=...)`` or the ``AUTOMERGE_TPU_FLIGHT_DIR`` env
var), writes it as ``flight-<trigger>-<seq>.json``. The
fault-containment seams call it automatically: batched-apply quarantine
(fleet/backend.py), sync-receive quarantine (fleet/sync_driver.py),
recovery truncation/rot (fleet/durability.py), and multihost
SyncOverflow (fleet/exchange.py).
"""

import collections
import json
import os
import threading
import time

from . import spans as _spans
from .metrics import Counters, health_counts, register_health_source

__all__ = ['configure', 'record_event', 'recent_events', 'clear_events',
           'dump_flight_record', 'last_flight_record', 'flight_stats']

_events = collections.deque(maxlen=256)
_dump_dir = os.environ.get('AUTOMERGE_TPU_FLIGHT_DIR') or None
_dump_spans = 64             # newest spans included per forensic dump
# Disk-write rate limit: a quarantine STORM (thousands of poisoned docs
# in one incident) must not amplify into disk exhaustion — at most
# _dump_limit dump FILES land per _dump_window_s sliding window; excess
# dumps are still assembled in memory (last_flight_record keeps working)
# but the file write is suppressed and counted in 'dumps_suppressed'.
_dump_limit = int(os.environ.get('AUTOMERGE_TPU_FLIGHT_DUMP_LIMIT', 16))
_dump_window_s = float(os.environ.get('AUTOMERGE_TPU_FLIGHT_DUMP_WINDOW',
                                      60.0))
_dump_times = collections.deque()
_dump_lock = threading.Lock()   # the window check is check-then-append
_last = None
_stats = Counters({'flight_events': 0, 'flight_dumps': 0,
                   'dumps_suppressed': 0})
register_health_source('flight_events', lambda: _stats['flight_events'])
register_health_source('flight_dumps', lambda: _stats['flight_dumps'])
register_health_source('dumps_suppressed',
                       lambda: _stats['dumps_suppressed'])

_UNSET = object()


def configure(capacity=None, dump_dir=_UNSET, dump_spans=None,
              dump_limit=None, dump_window_s=None):
    """Adjust the recorder: ring capacity (the newest events are kept up
    to the new bound; call clear_events() for a fresh ring),
    forensic-dump directory (None = keep dumps in memory only), how
    many of the newest spans each dump includes, and the disk-write
    rate limit (`dump_limit` files per `dump_window_s` sliding window;
    limit <= 0 disables the cap)."""
    global _events, _dump_dir, _dump_spans, _dump_limit, _dump_window_s
    if capacity is not None:
        _events = collections.deque(_events, maxlen=int(capacity))
    if dump_dir is not _UNSET:
        _dump_dir = dump_dir
    if dump_spans is not None:
        _dump_spans = int(dump_spans)
    if dump_limit is not None:
        _dump_limit = int(dump_limit)
    if dump_window_s is not None:
        _dump_window_s = float(dump_window_s)


def _dump_write_allowed(now):
    """Sliding-window admission for dump FILE writes (the report itself
    always assembles). True = write, with the slot recorded."""
    if _dump_limit <= 0:
        return True
    with _dump_lock:
        while _dump_times and now - _dump_times[0] > _dump_window_s:
            _dump_times.popleft()
        if len(_dump_times) >= _dump_limit:
            return False
        _dump_times.append(now)
        return True


def record_event(kind, **fields):
    """Append a structured event to the ring. Values should already be
    JSON-friendly (strings/numbers); anything else is repr'd at dump."""
    _stats.inc('flight_events')
    ev = {'kind': kind, 'ts_ns': time.time_ns()}
    ev.update(fields)
    # archlint: ok[lock-discipline] lock-free ring by design: deque.append is one atomic op under the GIL and the ring is bounded by maxlen
    _events.append(ev)
    return ev


def recent_events(n=None):
    """The newest `n` events (all, oldest first, when n is None)."""
    evs = list(_events)
    return evs if n is None else evs[-n:]


def clear_events():
    # archlint: ok[lock-discipline] lock-free ring by design: deque.clear is one atomic op under the GIL (test-scoped reset, not a hot path)
    _events.clear()


def dump_flight_record(trigger, detail=None, path=None):
    """Assemble (and possibly write) the forensic report around `trigger`.
    Returns the report dict; it is also retained for
    ``last_flight_record()``. ``path`` overrides the configured dump
    directory for this one dump — and bypasses the rate limit (an
    explicit path is an operator asking, not a storm amplifying). Disk
    writes to the CONFIGURED directory are rate-limited (see
    ``configure``): a suppressed dump still assembles in memory, gains
    ``'suppressed': True``, and bumps the 'dumps_suppressed' health
    counter."""
    global _last
    from . import hist
    _stats.inc('flight_dumps')
    now = time.time()
    report = {
        'trigger': trigger,
        'seq': _stats['flight_dumps'],
        'ts': now,
        'detail': detail,
        'events': list(_events),
        'recent_spans': _spans.iter_spans()[-_dump_spans:],
        'health': health_counts(),
        'histograms': {name: h.summary()
                       for name, h in hist._registry.items()},
    }
    _last = report
    out_path = path
    if out_path is None and _dump_dir is not None:
        if _dump_write_allowed(now):
            os.makedirs(_dump_dir, exist_ok=True)
            out_path = os.path.join(
                _dump_dir, f'flight-{trigger}-{report["seq"]}.json')
        else:
            _stats.inc('dumps_suppressed')
            report['suppressed'] = True
    if out_path is not None:
        with open(out_path, 'w') as f:
            json.dump(report, f, indent=1, default=repr)
        report['path'] = out_path
    return report


def last_flight_record():
    """The most recent forensic report (None before the first dump)."""
    return _last


def flight_stats():
    return dict(_stats)
