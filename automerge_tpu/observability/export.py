"""Prometheus exposition: every counter, histogram, and SLO gauge on a
machine-scrapable surface.

``render_prometheus()`` renders text-format 0.0.4 exposition from an
ATOMIC snapshot — every value is copied into plain data first, and a
histogram's ``_count`` is derived from the very bucket vector the
``_bucket`` lines are printed from, so a scrape racing a service tick
can never show cumulative buckets that disagree with their own count
(the classic torn-read artifact of rendering live state field by
field). What lands on the page:

- the health-counter roll-up (``automerge_tpu_health_total``) and the
  device-dispatch roll-up (``automerge_tpu_dispatch_total``),
- every registered histogram as cumulative buckets + sum + count
  (log2 bucket upper bounds as ``le`` labels, trailing empty buckets
  collapsed into ``+Inf``),
- the span ring's truncation state (``automerge_tpu_spans_dropped``),
- and, when an ``SloRegistry`` is passed: per-(tenant, kind) request
  outcome counters, per-pair committed-latency histograms, burn-rate /
  alert gauges per SLO and window, and worst cursor-lag gauges.

``MetricsExporter`` is the stdlib-only serving thread: an HTTP server
on ``127.0.0.1:<port>`` answering ``GET /metrics`` (port 0 binds an
ephemeral port — the test mode), plus ``write_snapshot()`` for
scrape-less environments: the same exposition rendered to a temp file
and atomically renamed into place, so a sidecar tailing the file never
reads a half-written page. ``maybe_start_exporter()`` is the
env-driven entry: ``AUTOMERGE_TPU_METRICS_PORT`` unset means fully
disabled — no server, no thread, nothing started.
"""

import os
import threading

from . import hist as _hist
from . import spans as _spans
from .metrics import dispatch_counts, health_counts

__all__ = ['render_prometheus', 'snapshot_all', 'MetricsExporter',
           'maybe_start_exporter', 'METRICS_PORT_ENV',
           'METRICS_SNAPSHOT_ENV', 'SHARD_ENV']

METRICS_PORT_ENV = 'AUTOMERGE_TPU_METRICS_PORT'
METRICS_SNAPSHOT_ENV = 'AUTOMERGE_TPU_METRICS_SNAPSHOT'
SHARD_ENV = 'AUTOMERGE_TPU_SHARD'
_PREFIX = 'automerge_tpu'


def _sanitize(name):
    """A Prometheus-legal metric-name fragment."""
    out = []
    for ch in str(name):
        out.append(ch if (ch.isascii() and (ch.isalnum() or ch == '_'))
                   else '_')
    frag = ''.join(out)
    return frag if not frag[:1].isdigit() else '_' + frag


def _label(value):
    """A Prometheus-escaped label VALUE (quotes/backslashes/newlines)."""
    return str(value).replace('\\', '\\\\').replace('"', '\\"') \
        .replace('\n', '\\n')


def _fmt(value):
    if isinstance(value, float):
        return repr(value)
    return str(value)


def _hist_snapshot(h):
    """Torn-read-proof plain snapshot of one histogram: the bucket
    vector is copied ONCE and count derived from that same copy, so
    the rendered cumulative buckets always sum to the rendered count
    even while another thread is recording. The SUM is derived the same
    way — from the per-bucket sums vector copied inside a counts-stable
    bracket (copy counts, copy sums, re-copy counts; retry on movement),
    and Histogram.record updates sums BEFORE counts, so every record
    the page counts has its value in the page's sum (the page may
    additionally carry the value of a record still in flight — sum is
    an upper bound by at most the in-flight writers' values, never an
    undercount of what _count claims). That is what makes
    rate(..._sum)/rate(..._count) PromQL honest under concurrent
    recording."""
    for _ in range(8):
        counts = list(h.counts)
        sums = list(h.sums)
        if list(h.counts) == counts:
            break
    return {'counts': counts, 'count': sum(counts),
            'sum': float(sum(sums)), 'scale': h.scale}


def snapshot_all(slo=None, fleets=(), router=None, control=None):
    """Every exposed value as plain data — the atomic snapshot both the
    text renderer and the snapshot-file mode serialize from. ``router``
    (an in-process ShardRouter) adds per-shard tick-overrun telemetry:
    each shard's slipped-tick counter and last pump seconds.
    ``control`` (a control/ Controller) adds the controller's own
    gauges — its ``gauges()`` copies under the controller lock, the
    same torn-read-proof contract the SLO reads carry."""
    snap = {
        'health': health_counts(),
        'dispatch': dispatch_counts(fleets),
        'spans_dropped': _spans.spans_dropped(),
        'histograms': {name: _hist_snapshot(h)
                       for name, h in list(_hist._registry.items())},
    }
    if router is not None:
        snap['shard_slips'] = {sid: s.ticks_slipped
                               for sid, s in router.shards.items()}
        snap['shard_pump_s'] = {sid: s.last_pump_s
                                for sid, s in router.shards.items()}
    if control is not None:
        snap['control'] = control.gauges()
    if slo is not None:
        snap['slo_tallies'] = slo.tallies()
        snap['slo_gauges'] = slo.gauges()
        snap['slo_lag'] = slo.lag_gauges()
        snap['slo_hists'] = {key: _hist_snapshot(h)
                             for key, h in slo.histograms().items()}
    # the perf observatory's three legs (perf.py), each empty until its
    # switch is on — no series churn for processes that never enable it
    from . import perf as _perf
    snap['perf_seams'] = _perf.baseline_gauges()
    snap['kernels'] = _perf.kernel_snapshot()
    snap['mem'] = _perf.watermark_snapshot() if _perf._mem_last else None
    return snap


def _labelset(*parts):
    """'{a,b}' from the non-empty label fragments, '' when none."""
    joined = ','.join(p for p in parts if p)
    return f'{{{joined}}}' if joined else ''


def _render_hist_lines(lines, metric, snap, labels=''):
    counts = snap['counts']
    scale = snap['scale']
    cum = 0
    last = max((b for b, c in enumerate(counts) if c), default=-1)
    sep = ',' if labels else ''
    for b in range(last + 1):
        cum += counts[b]
        le = (1 << b) / scale
        lines.append(f'{metric}_bucket{{{labels}{sep}le="{_fmt(le)}"}} '
                     f'{cum}')
    lines.append(f'{metric}_bucket{{{labels}{sep}le="+Inf"}} '
                 f'{snap["count"]}')
    lines.append(f'{metric}_sum{{{labels}}} {_fmt(snap["sum"])}'
                 if labels else f'{metric}_sum {_fmt(snap["sum"])}')
    lines.append(f'{metric}_count{{{labels}}} {snap["count"]}'
                 if labels else f'{metric}_count {snap["count"]}')


def render_prometheus(slo=None, fleets=(), shard=None, router=None,
                      control=None):
    """The full text-format 0.0.4 exposition page (one trailing
    newline), rendered from ``snapshot_all``. ``shard`` stamps a
    ``shard="<id>"`` label on EVERY sample line — the process-level
    identity a multi-shard deployment scrapes by (one exporter per
    shard process; the in-process ``ShardRouter`` testbed renders one
    page per shard the same way), so per-shard dashboards and the
    failover runbooks can select a single failure domain."""
    snap = snapshot_all(slo=slo, fleets=fleets, router=router,
                        control=control)
    sl = f'shard="{_label(shard)}"' if shard is not None else ''
    lines = []

    lines.append(f'# TYPE {_PREFIX}_health_total counter')
    for name, value in sorted(snap['health'].items()):
        ls = _labelset(sl, 'counter="%s"' % _label(name))
        lines.append(f'{_PREFIX}_health_total{ls} {value}')
    lines.append(f'# TYPE {_PREFIX}_dispatch_total counter')
    for name, value in sorted(snap['dispatch'].items()):
        ls = _labelset(sl, 'source="%s"' % _label(name))
        lines.append(f'{_PREFIX}_dispatch_total{ls} {value}')
    lines.append(f'# TYPE {_PREFIX}_spans_dropped gauge')
    lines.append(f'{_PREFIX}_spans_dropped{_labelset(sl)} '
                 f'{snap["spans_dropped"]}')
    if 'shard_slips' in snap:
        # per-shard tick-overrun telemetry (ISSUE-12 satellite): the
        # loadgen's aggregate ticks_slipped, attributed per failure
        # domain — which shard's tick work does not fit the cadence.
        # The `shard` label here is the FAILURE DOMAIN the counter
        # describes (the in-process router testbed exposes all of its
        # shards from one page); a process-level `shard=` identity
        # label composes alongside it as `proc_shard`.
        psl = f'proc_{sl}' if sl else ''
        lines.append(f'# TYPE {_PREFIX}_shard_ticks_slipped_total '
                     f'counter')
        for sid, n in sorted(snap['shard_slips'].items()):
            ls = _labelset(psl, f'shard="{_label(sid)}"')
            lines.append(f'{_PREFIX}_shard_ticks_slipped_total{ls} {n}')
        lines.append(f'# TYPE {_PREFIX}_shard_pump_seconds gauge')
        for sid, v in sorted(snap['shard_pump_s'].items()):
            ls = _labelset(psl, f'shard="{_label(sid)}"')
            lines.append(f'{_PREFIX}_shard_pump_seconds{ls} {_fmt(v)}')

    if snap.get('control'):
        # the control plane's own reasoning as series (control/): how
        # often each (policy, action) decided — split by mode, so a
        # shadow deployment graphs would-have-acted next to an active
        # one — plus direction reversals (the anti-oscillation number),
        # currently-active policy state, and decision latency. The
        # process `shard=` identity composes alongside like every other
        # domain label.
        ctl = snap['control']
        lines.append(f'# TYPE {_PREFIX}_control_decisions_total counter')
        for (policy, action, mode), n in sorted(
                ctl['decisions'].items()):
            ls = _labelset(sl, (f'policy="{_label(policy)}",'
                                f'action="{_label(action)}",'
                                f'mode="{_label(mode)}"'))
            lines.append(f'{_PREFIX}_control_decisions_total{ls} {n}')
        lines.append(f'# TYPE {_PREFIX}_control_reversals_total counter')
        for policy, n in sorted(ctl['reversals'].items()):
            ls = _labelset(sl, f'policy="{_label(policy)}"')
            lines.append(f'{_PREFIX}_control_reversals_total{ls} {n}')
        lines.append(f'# TYPE {_PREFIX}_control_policy_active gauge')
        for (policy, target), value in sorted(ctl['active'].items()):
            ls = _labelset(sl, (f'policy="{_label(policy)}",'
                                f'target="{_label(target)}"'))
            lines.append(f'{_PREFIX}_control_policy_active{ls} '
                         f'{_fmt(value)}')
        lines.append(f'# TYPE {_PREFIX}_control_windows_total counter')
        lines.append(f'{_PREFIX}_control_windows_total{_labelset(sl)} '
                     f'{ctl["windows"]}')
        lines.append(f'# TYPE {_PREFIX}_control_last_decision_tick '
                     f'gauge')
        lines.append(f'{_PREFIX}_control_last_decision_tick'
                     f'{_labelset(sl)} '
                     f'{ctl["last_decision_tick"] or 0}')
        lines.append(f'# TYPE {_PREFIX}_control_decide_seconds gauge')
        for which, key in (('last', 'decide_s_last'),
                           ('max', 'decide_s_max')):
            ls = _labelset(sl, f'window="{which}"')
            lines.append(f'{_PREFIX}_control_decide_seconds{ls} '
                         f'{_fmt(ctl[key])}')

    if snap.get('perf_seams'):
        # seam perf baselines (perf.py): trailing baseline vs newest
        # window, the drift ratio the alert machinery judges, and the
        # alert state — one series set per seam that closed a window
        lines.append(f'# TYPE {_PREFIX}_perf_baseline_seconds gauge')
        lines.append(f'# TYPE {_PREFIX}_perf_window_seconds gauge')
        lines.append(f'# TYPE {_PREFIX}_perf_drift_ratio gauge')
        lines.append(f'# TYPE {_PREFIX}_perf_alert_active gauge')
        rows = {'perf_baseline_seconds': 'baseline_s',
                'perf_window_seconds': 'window_s',
                'perf_drift_ratio': 'drift',
                'perf_alert_active': 'alert'}
        for seam, gauge in sorted(snap['perf_seams'].items()):
            ls = _labelset(sl, f'seam="{_label(seam)}"')
            for metric, key in rows.items():
                lines.append(f'{_PREFIX}_{metric}{ls} '
                             f'{_fmt(gauge[key])}')
    if snap.get('kernels'):
        # device-kernel cost ledger: dispatches + blocking wall seconds
        # per kernel kind (flops/bytes live in obs_report --floor — the
        # AOT cost analysis has no place on a scrape hot path)
        lines.append(f'# TYPE {_PREFIX}_kernel_dispatches_total counter')
        lines.append(f'# TYPE {_PREFIX}_kernel_seconds_total counter')
        for kind, row in sorted(snap['kernels'].items()):
            ls = _labelset(sl, f'kernel="{_label(kind)}"')
            lines.append(f'{_PREFIX}_kernel_dispatches_total{ls} '
                         f'{row["dispatches"]}')
            lines.append(f'{_PREFIX}_kernel_seconds_total{ls} '
                         f'{_fmt(row["seconds"])}')
    if snap.get('mem'):
        # memory watermarks: current resident bytes + process-lifetime
        # high per tier (rss rides as its own tier)
        lines.append(f'# TYPE {_PREFIX}_mem_bytes gauge')
        lines.append(f'# TYPE {_PREFIX}_mem_high_bytes gauge')
        for tier, value in sorted(snap['mem']['current'].items()):
            ls = _labelset(sl, f'tier="{_label(tier)}"')
            lines.append(f'{_PREFIX}_mem_bytes{ls} {value}')
        for tier, value in sorted(snap['mem']['high'].items()):
            ls = _labelset(sl, f'tier="{_label(tier)}"')
            lines.append(f'{_PREFIX}_mem_high_bytes{ls} {value}')

    for name, hsnap in sorted(snap['histograms'].items()):
        metric = f'{_PREFIX}_{_sanitize(name)}'
        lines.append(f'# TYPE {metric} histogram')
        _render_hist_lines(lines, metric, hsnap, labels=sl)

    if 'slo_tallies' in snap:
        lines.append(f'# TYPE {_PREFIX}_slo_requests_total counter')
        for (tenant, kind), tally in sorted(snap['slo_tallies'].items()):
            for cls, value in sorted(tally.items()):
                ls = _labelset(sl, (f'tenant="{_label(tenant)}",'
                                    f'kind="{_label(kind)}",'
                                    f'outcome="{_label(cls)}"'))
                lines.append(f'{_PREFIX}_slo_requests_total{ls} {value}')
        lines.append(f'# TYPE {_PREFIX}_slo_burn_rate gauge')
        lines.append(f'# TYPE {_PREFIX}_slo_alert_active gauge')
        burn, alert = [], []
        for (tenant, kind, sli), gauge in sorted(
                snap['slo_gauges'].items()):
            labels = (f'tenant="{_label(tenant)}",kind="{_label(kind)}",'
                      f'sli="{_label(sli)}"')
            for window in ('fast', 'slow'):
                ls = _labelset(sl, f'{labels},window="{window}"')
                if f'{window}_burn' in gauge:
                    burn.append(f'{_PREFIX}_slo_burn_rate{ls} '
                                f'{_fmt(gauge[f"{window}_burn"])}')
                if f'alert_{window}' in gauge:
                    alert.append(f'{_PREFIX}_slo_alert_active{ls} '
                                 f'{gauge[f"alert_{window}"]}')
        lines.extend(burn)
        lines.extend(alert)
        if snap['slo_lag']:
            lines.append(f'# TYPE {_PREFIX}_slo_cursor_lag_ticks_max '
                         f'gauge')
            for (tenant, kind), lag in sorted(snap['slo_lag'].items()):
                ls = _labelset(sl, (f'tenant="{_label(tenant)}",'
                                    f'kind="{_label(kind)}"'))
                lines.append(f'{_PREFIX}_slo_cursor_lag_ticks_max{ls}'
                             f' {lag}')
        if snap['slo_hists']:
            metric = f'{_PREFIX}_slo_request_latency_seconds'
            lines.append(f'# TYPE {metric} histogram')
            for (tenant, kind), hsnap in sorted(snap['slo_hists'].items()):
                labels = (f'tenant="{_label(tenant)}",'
                          f'kind="{_label(kind)}"')
                _render_hist_lines(lines, metric, hsnap,
                                   labels=','.join(
                                       p for p in (sl, labels) if p))

    return '\n'.join(lines) + '\n'


class MetricsExporter:
    """The serving thread (see the module docstring). ``start()`` binds
    and serves; ``stop()`` shuts the server down and joins the thread.
    With ``port=None`` no server is created — the instance is then a
    snapshot-file writer only."""

    def __init__(self, port=0, host='127.0.0.1', slo=None, fleets=(),
                 snapshot_path=None, shard=None, router=None,
                 control=None):
        self._port_arg = port
        self.host = host
        self.slo = slo
        self.fleets = tuple(fleets)
        self.snapshot_path = snapshot_path
        self.shard = shard
        self.router = router
        self.control = control
        self.port = None
        self._server = None
        self._thread = None

    def render(self):
        return render_prometheus(slo=self.slo, fleets=self.fleets,
                                 shard=self.shard, router=self.router,
                                 control=self.control)

    # -- HTTP mode ------------------------------------------------------

    def start(self):
        """Bind (port 0 = ephemeral; ``self.port`` is then the real
        one) and serve /metrics from a daemon thread. No-op when
        ``port=None`` (snapshot-only mode) or already started."""
        if self._port_arg is None or self._server is not None:
            return self
        import http.server

        exporter = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split('?', 1)[0] not in ('/metrics', '/'):
                    self.send_error(404)
                    return
                body = exporter.render().encode()
                self.send_response(200)
                self.send_header('Content-Type',
                                 'text/plain; version=0.0.4; '
                                 'charset=utf-8')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):    # scrapes are not stderr news
                pass

        self._server = http.server.ThreadingHTTPServer(
            (self.host, int(self._port_arg)), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name='metrics-exporter',
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Shut the server down, close the socket, join the thread."""
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None
        self.port = None

    @property
    def running(self):
        return self._thread is not None and self._thread.is_alive()

    # -- snapshot-file mode ---------------------------------------------

    def write_snapshot(self, path=None):
        """Render the exposition to ``path`` (default: the configured
        ``snapshot_path``) atomically: temp file + rename, so a reader
        never sees a torn page. Returns the path written."""
        path = path if path is not None else self.snapshot_path
        if path is None:
            raise ValueError('no snapshot path configured')
        body = self.render()
        tmp = f'{path}.tmp.{os.getpid()}'
        with open(tmp, 'w') as f:
            f.write(body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path


def maybe_start_exporter(slo=None, fleets=(), shard=None, router=None,
                         control=None):
    """The env-driven entry point: ``AUTOMERGE_TPU_METRICS_PORT`` set
    starts (and returns) a serving ``MetricsExporter`` on that port
    (0 = ephemeral); ``AUTOMERGE_TPU_METRICS_SNAPSHOT`` set (with no
    port) returns a snapshot-only exporter bound to that file path;
    NEITHER set returns None with zero threads started — telemetry
    export is strictly opt-in. ``AUTOMERGE_TPU_SHARD`` (or the `shard`
    arg, which wins) stamps the shard identity label on every sample —
    how a shard process names its failure domain to the scraper."""
    port = os.environ.get(METRICS_PORT_ENV)
    snapshot = os.environ.get(METRICS_SNAPSHOT_ENV)
    if shard is None:
        shard = os.environ.get(SHARD_ENV) or None
    if port is not None and port != '':
        exporter = MetricsExporter(port=int(port), slo=slo, fleets=fleets,
                                   snapshot_path=snapshot or None,
                                   shard=shard, router=router,
                                   control=control)
        return exporter.start()
    if snapshot:
        return MetricsExporter(port=None, slo=slo, fleets=fleets,
                               snapshot_path=snapshot, shard=shard,
                               router=router, control=control)
    return None
