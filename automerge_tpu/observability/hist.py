"""Fixed log2-bucket latency/size histograms.

64 power-of-two buckets over a scaled integer domain: bucket 0 holds
scaled values < 1, bucket b holds [2^(b-1), 2^b). A latency histogram
uses ``scale=1e9`` (nanosecond resolution across ~9 seconds of dynamic
range per bucket doubling); a byte histogram uses ``scale=1``. Fixed
buckets mean ``record`` is one multiply + one int.bit_length + two adds —
cheap enough for per-batch seams — and two snapshots subtract bucketwise,
so ``delta`` gives exact per-workload distributions the way the metrics
counters do.

Percentiles report the bucket UPPER bound (conservative: the true pN is
<= the reported value), which makes test pins exact instead of
interpolation-dependent.

The module keeps a global registry (``histogram(name)`` get-or-creates)
behind the same off-by-default master switch the spans use:
``record_value`` / ``record_many`` are no-ops until ``enable()``.
"""

import math

__all__ = ['Histogram', 'histogram', 'record_value', 'histogram_snapshot',
           'histogram_delta', 'reset', 'enable', 'disable', 'on',
           'NBUCKETS']

NBUCKETS = 64

_on = False
_registry = {}


def on():
    return _on


def enable():
    global _on
    _on = True


def disable():
    global _on
    _on = False


def reset():
    """Drop every registered histogram (name registry included)."""
    _registry.clear()


def _percentile_from_buckets(counts, count, q, scale):
    """Upper bound of the bucket holding the q-quantile observation."""
    if count <= 0:
        return None
    target = max(int(math.ceil(q * count)), 1)
    acc = 0
    for b, c in enumerate(counts):
        acc += c
        if acc >= target:
            return (1 << b) / scale
    return (1 << (NBUCKETS - 1)) / scale


def _summarize(counts, count, total, scale):
    return {
        'count': count,
        'sum': total,
        'mean': (total / count) if count else None,
        'p50': _percentile_from_buckets(counts, count, 0.50, scale),
        'p95': _percentile_from_buckets(counts, count, 0.95, scale),
        'p99': _percentile_from_buckets(counts, count, 0.99, scale),
    }


class Histogram:
    """Fixed log2-bucket histogram of non-negative values."""

    __slots__ = ('name', 'scale', 'unit', 'counts', 'sums', 'count',
                 'total', 'vmin', 'vmax')

    def __init__(self, name, scale=1, unit=''):
        self.name = name
        self.scale = scale
        self.unit = unit
        self.counts = [0] * NBUCKETS
        # per-bucket value sums, updated BEFORE the bucket count: the
        # exposition derives its `_sum` from a copy of this vector taken
        # inside a counts-stable bracket (export._hist_snapshot), so a
        # record counted on the page always has its value in the page's
        # sum — the `_sum` twin of the round-14 torn-read contract
        self.sums = [0.0] * NBUCKETS
        self.count = 0
        self.total = 0.0
        self.vmin = None
        self.vmax = None

    def bucket_of(self, value):
        """Bucket index for a raw (unscaled) value."""
        s = int(value * self.scale)
        if s <= 0:
            return 0
        b = s.bit_length()
        return b if b < NBUCKETS else NBUCKETS - 1

    def bucket_bounds(self, b):
        """(lo, hi) raw-value bounds of bucket b: values v with
        lo <= v*scale < hi land in b (bucket 0 is [0, 1/scale))."""
        lo = (1 << (b - 1)) / self.scale if b > 0 else 0.0
        hi = (1 << b) / self.scale
        return lo, hi

    def record(self, value):
        """Returns the bucket index the value landed in, so a caller
        that also classifies by bucket (the SLO latency SLI) pays
        bucket_of once."""
        b = self.bucket_of(value)
        self.sums[b] += value
        self.counts[b] += 1
        self.count += 1
        self.total += value
        if self.vmin is None or value < self.vmin:
            self.vmin = value
        if self.vmax is None or value > self.vmax:
            self.vmax = value
        return b

    def record_many(self, values):
        """Vectorized record over an array-like of raw values — one
        numpy pass (frexp exponent == bit_length for positive ints), for
        the per-doc seams where a Python loop would be the overhead."""
        import numpy as np
        v = np.asarray(values, dtype=np.float64)
        if v.size == 0:
            return
        s = np.maximum((v * self.scale).astype(np.int64), 0)
        _m, exp = np.frexp(s.astype(np.float64))
        b = np.where(s > 0, exp, 0)
        np.clip(b, 0, NBUCKETS - 1, out=b)
        binned = np.bincount(b, minlength=NBUCKETS)
        summed = np.bincount(b, weights=v, minlength=NBUCKETS)
        for i in np.flatnonzero(binned):
            self.sums[int(i)] += float(summed[i])
            self.counts[int(i)] += int(binned[i])
        self.count += int(v.size)
        self.total += float(v.sum())
        lo, hi = float(v.min()), float(v.max())
        if self.vmin is None or lo < self.vmin:
            self.vmin = lo
        if self.vmax is None or hi > self.vmax:
            self.vmax = hi

    def percentile(self, q):
        return _percentile_from_buckets(self.counts, self.count, q,
                                        self.scale)

    def summary(self):
        out = _summarize(self.counts, self.count, self.total, self.scale)
        out['min'] = self.vmin
        out['max'] = self.vmax
        out['unit'] = self.unit
        return out

    def snapshot(self):
        """Monotonic state for later delta(): bucket counts + count/sum
        plus the summary fields."""
        out = self.summary()
        out['buckets'] = tuple(self.counts)
        out['scale'] = self.scale
        return out

    def delta(self, prev):
        """Distribution accumulated since `prev` (an earlier snapshot()):
        bucketwise subtraction with percentiles recomputed over the
        difference. min/max are not delta-able and are omitted."""
        buckets = [c - p for c, p in zip(self.counts, prev['buckets'])]
        count = self.count - prev['count']
        total = self.total - prev['sum']
        out = _summarize(buckets, count, total, self.scale)
        out['buckets'] = tuple(buckets)
        out['unit'] = self.unit
        return out

    def __repr__(self):
        s = self.summary()
        return (f'Histogram({self.name!r}, n={s["count"]}, '
                f'p50={s["p50"]}, p99={s["p99"]})')


def histogram(name, scale=1, unit=''):
    """Get-or-create the named histogram in the global registry."""
    h = _registry.get(name)
    if h is None:
        h = _registry[name] = Histogram(name, scale=scale, unit=unit)
    return h


def record_value(name, value, scale=1, unit=''):
    """Record into the named histogram iff histograms are enabled."""
    if _on:
        histogram(name, scale=scale, unit=unit).record(value)


def histogram_snapshot():
    """{name: snapshot()} for every registered histogram."""
    return {name: h.snapshot() for name, h in _registry.items()}


def histogram_delta(prev):
    """{name: delta vs prev[name]} for histograms present in both."""
    out = {}
    for name, h in _registry.items():
        if name in prev:
            out[name] = h.delta(prev[name])
        else:
            out[name] = h.snapshot()
    return out
