"""Monotonic counters and the dispatch/health roll-up registries.

- `Metrics`: cheap monotonic counters every DocFleet maintains
  (`fleet.metrics`): device dispatches, ops applied on device, changes
  ingested, bytes ingested, host fallbacks, actor renumber remaps,
  capacity growths. `snapshot()` returns a plain dict; `delta(prev)`
  diffs two snapshots — subtract around a workload to get per-phase
  counts.
- `trace(path)`: context manager around `jax.profiler.trace` — writes a
  TensorBoard-loadable XLA trace of everything dispatched inside the
  block (merge the host-span Chrome trace from spans.py next to it in
  Perfetto; see BASELINE.md "Observability contract").
- `timed(metrics, key)`: context manager accumulating wall-clock seconds
  into a counter, for host-side phases (decode, gate, patch build).
- `register_dispatch_source(name, fn)` / `dispatch_counts(fleets)`: one
  roll-up of every device-dispatch counter in the system. DocFleet counts
  its dispatches in `fleet.metrics.dispatches`, but some batched paths run
  over HOST backends with no fleet in sight (the sync driver's Bloom
  build/probe lives in `fleet/bloom.py` module state); those modules
  register a monotonic counter here, so bench.py and the dispatch-count
  regression tests can diff total device dispatches around a workload
  without knowing which modules dispatched.
- `register_health_source(name, fn)` / `health_counts()`: the same
  roll-up pattern for fault-containment counters — quarantined docs,
  rejected changes/filters, sync retries, injected wire faults, fuzz
  corpus size, and the durability layer's checkpoint/compaction/
  journal-fsync/replay/truncation/rot counters (fleet/durability.py).

The roll-up key space is shared with the synthetic keys `dispatch_counts`
itself emits ('total', and 'fleet<N>' per passed fleet), so those names
are RESERVED: registering a source under one would silently corrupt the
roll-up (the module counter overwritten by — or summed into — the
synthetic key). Both register functions reject them with ValueError.
"""

import contextlib
import re
import threading
import time

__all__ = ['Counters', 'Metrics', 'timed', 'trace',
           'register_dispatch_source', 'dispatch_counts',
           'register_health_source', 'health_counts',
           'counts_delta', 'health_delta', 'dispatch_delta']


# One process-global lock for every Counters family: stat increments are
# rare events (health counters, not per-op work), so contention on a
# shared lock is cheaper than a lock object per module — and a single
# lock means two families incremented from one code path can never
# deadlock against each other.
_COUNTERS_LOCK = threading.Lock()


class Counters(dict):
    """A module-stats dict whose increments are ATOMIC under threads.

    ``d[key] += n`` on a plain dict is a read-modify-write that the GIL
    can split between threads — which is exactly how the round-15
    thread-per-shard pump pool undercounted health counters (two pumps
    read the same value, both wrote value+1). Every module `_stats`
    family is now one of these, and every increment goes through
    ``inc``, which holds the shared lock across the whole
    read-add-write. Plain reads and whole-value assignments
    (``d[key] = 0`` resets, gauge sets) stay ordinary dict operations —
    each is a single GIL-atomic bytecode effect.
    """

    __slots__ = ()

    def inc(self, key, n=1):
        """Atomically add ``n`` (may be negative) to ``key`` (missing
        keys start at 0). Returns the new value."""
        with _COUNTERS_LOCK:
            value = self.get(key, 0) + n
            self[key] = value
        return value


class Metrics:
    """Monotonic counters; plain attributes so incrementing is one add."""

    _FIELDS = (
        'dispatches',            # device merge dispatches issued
        'device_ops',            # real op rows applied on device (padding excluded)
        'changes_ingested',      # binary changes accepted by apply paths
        'bytes_ingested',        # wire bytes parsed
        'turbo_calls',           # batched turbo applies
        'exact_calls',           # mirror-exact applies
        'fallbacks',             # turbo calls routed to the exact path
        'promotions',            # documents promoted to the host engine
        'remaps',                # actor renumber dispatches
        'grows',                 # capacity regrowths (doc/key axes)
        'mirror_rebuilds',       # lazy mirror replays after turbo
        'graph_builds',          # deferred hash-graph materializations
        'docs_bulk_loaded',      # documents installed by the native loader
        'doc_materializations',  # bulk-loaded docs whose history was read
        'turbo_commit_fallback_docs',  # per-doc commit-loop iterations
                                 # (staged/slow docs only; the columnar
                                 # fast path contributes ZERO — pinned
                                 # by the commit-phase regression guard)
    )

    def __init__(self):
        for name in self._FIELDS:
            setattr(self, name, 0)
        self.seconds = {}        # phase name -> accumulated wall seconds

    def snapshot(self):
        out = {name: getattr(self, name) for name in self._FIELDS}
        out['seconds'] = dict(self.seconds)
        return out

    def delta(self, prev):
        """Counters accumulated since `prev` (an earlier snapshot())."""
        now = self.snapshot()
        out = {k: now[k] - prev.get(k, 0) for k in self._FIELDS}
        out['seconds'] = {k: v - prev.get('seconds', {}).get(k, 0.0)
                          for k, v in now['seconds'].items()}
        return out

    def __repr__(self):
        parts = [f'{k}={getattr(self, k)}' for k in self._FIELDS
                 if getattr(self, k)]
        return f'Metrics({", ".join(parts)})'


@contextlib.contextmanager
def timed(metrics, key):
    """Accumulate the block's wall-clock seconds into metrics.seconds[key]."""
    start = time.perf_counter()
    try:
        yield
    finally:
        metrics.seconds[key] = metrics.seconds.get(key, 0.0) + \
            (time.perf_counter() - start)


# ---- device-dispatch roll-up ----------------------------------------------

_dispatch_sources = {}

# 'total' and 'fleet<N>' are synthesized by dispatch_counts itself; a
# module registering under either would corrupt the roll-up (round-7
# satellite: the collision was silent before this guard).
_RESERVED = re.compile(r'total|fleet\d+')


def _check_source_name(name):
    if not isinstance(name, str) or _RESERVED.fullmatch(name):
        raise ValueError(
            f'{name!r} is reserved: dispatch_counts() synthesizes '
            f"'total' and 'fleet<N>' keys, so sources may not register "
            f'under those names')


def register_dispatch_source(name, fn):
    """Register a zero-arg callable returning a module's monotonic device
    dispatch count (e.g. fleet.bloom registers its batched build/probe
    counter at import). Re-registering a name replaces the source.
    Raises ValueError for the reserved roll-up keys ('total',
    'fleet<N>')."""
    _check_source_name(name)
    with _COUNTERS_LOCK:
        _dispatch_sources[name] = fn


def dispatch_counts(fleets=()):
    """Snapshot every registered module dispatch counter plus the given
    fleets' `metrics.dispatches`, with a 'total' sum. Take one snapshot
    before and one after a workload and subtract per key (the counters are
    monotonic) to get dispatches attributable to that workload."""
    out = {name: int(fn()) for name, fn in _dispatch_sources.items()}
    for i, fleet in enumerate(fleets):
        out[f'fleet{i}'] = int(fleet.metrics.dispatches)
    out['total'] = sum(out.values())
    return out


# ---- fault-containment health roll-up -------------------------------------

_health_sources = {}


def register_health_source(name, fn):
    """Register a zero-arg callable returning a module's monotonic
    fault-containment counter (quarantined docs, rejected changes, sync
    retries, injected wire faults, ...). Re-registering a name replaces
    the source — same contract (and same reserved-name rejection) as
    register_dispatch_source."""
    _check_source_name(name)
    with _COUNTERS_LOCK:
        _health_sources[name] = fn


def health_counts():
    """Snapshot every registered health counter. Counters are monotonic;
    subtract two snapshots around a workload to attribute events to it."""
    return {name: int(fn()) for name, fn in _health_sources.items()}


# ---- snapshot/delta over counter roll-ups ---------------------------------
#
# The counter twin of Histogram.snapshot()/delta(): the roll-ups return
# plain monotonic dicts, and every consumer used to subtract them by hand
# (bench.py's faults section, obs_report dump comparisons, now the SLO
# windows every tick). One shared subtraction keeps the semantics in one
# place: keys are unioned, a key missing from either side reads 0.

def counts_delta(now, prev):
    """Per-key difference of two counter snapshots (``now - prev``).
    Keys are unioned; a key absent from one side counts as 0 there, so
    a counter that appeared (or a source registered) between the two
    snapshots still contributes its full movement."""
    out = {}
    for k, v in now.items():
        out[k] = v - prev.get(k, 0)
    for k, v in prev.items():
        if k not in now:
            out[k] = -v
    return out


def health_delta(prev):
    """Health counters accumulated since ``prev`` (an earlier
    health_counts() snapshot)."""
    return counts_delta(health_counts(), prev)


def dispatch_delta(prev, fleets=()):
    """Device dispatches accumulated since ``prev`` (an earlier
    dispatch_counts() snapshot over the same fleets)."""
    return counts_delta(dispatch_counts(fleets), prev)


@contextlib.contextmanager
def trace(log_dir):
    """JAX profiler trace of every dispatch inside the block; view the
    written trace with TensorBoard's profile plugin or Perfetto."""
    import jax
    with jax.profiler.trace(str(log_dir)):
        yield
