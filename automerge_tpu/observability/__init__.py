"""Metrics, tracing, and forensics for the fleet engine.

The reference has no tracing/profiling/metrics at all (SURVEY.md §5 — its
only observability is patchCallback/Observable/getHistory, which this
framework also provides). A batched device engine needs more: you cannot
see an XLA dispatch from a patchCallback, and when one document in a
10k-doc fused batch is quarantined you need to know which one, in what
phase, and what happened around it. Four layers, one package:

- **Counters & roll-ups** (metrics.py): per-fleet monotonic `Metrics`,
  `timed` phase seconds, `register_dispatch_source`/`dispatch_counts`
  and `register_health_source`/`health_counts` system-wide roll-ups,
  and the `trace` wrapper around `jax.profiler.trace`.
- **Host-phase spans** (spans.py): `span(name, **attrs)` — near-zero
  overhead while disabled, a bounded ring while enabled — instrumented
  at every hot seam (native parse, SHA, turbo gate/stage/commit, device
  dispatch, mirror rebuild, actor remap, journal append/commit/fsync,
  checkpoint, compaction, recovery replay, Bloom build/probe, sync
  encode/decode). `export_chrome_trace` writes Perfetto-loadable JSON
  that lines up beside a `trace()` device capture.
- **Latency histograms** (hist.py): fixed log2-bucket `Histogram`s with
  p50/p95/p99 summaries and bucketwise `snapshot()`/`delta()` — batch
  apply latency, fsync latency, sync round-trip, per-doc change bytes,
  recovery per-doc replay time.
- **Flight recorder** (recorder.py): an always-on bounded ring of
  structured health events (doc ids, durable ids, typed error names,
  change-byte digests) that dumps a JSON forensic report automatically
  on quarantine, recovery truncation/rot, and SyncOverflow — each dump
  also carrying the span ring's tail, so a traced run's report includes
  the phase timeline around the fault without span churn ever evicting
  the fault events themselves.

And the tenant telemetry plane on top (ISSUE-10):

- **SLO accounting** (slo.py): per-(tenant, kind) SLIs — latency,
  availability split by rejection class, subscription freshness — as
  rolling deltas over the histograms/counters above, with multi-window
  burn-rate alerting (hysteretic, edge-triggered, flight-recorded).
- **Exposition** (export.py): Prometheus text format over every
  counter, histogram, and SLO gauge, served by the stdlib-only
  `MetricsExporter` (`AUTOMERGE_TPU_METRICS_PORT`; unset = fully off)
  or written atomically to a snapshot file.
- **Trace stitching** (tracecontext.py): `TraceContext` minted per
  service request, span `links` on the fused batches, and an opt-in
  wire envelope so two peers' sync span trees share one trace id —
  merged by `tools/obs_report.py --stitch`.

`enable()`/`disable()` flip spans + histograms together (the switch the
bench's <=2% overhead budget is measured across); the flight recorder's
event ring and the SLO accounting stay on either way (the latter has
its own switch: `DocService(slo=False)`). `tools/obs_report.py` renders
a phase-attribution report from an exported trace or a forensic dump.
"""

from . import hist as _hist
from . import recorder as _recorder
from . import spans as _spans
from .export import (MetricsExporter, maybe_start_exporter,
                     render_prometheus)
from .hist import (Histogram, histogram, histogram_delta,
                   histogram_snapshot, record_value)
from .metrics import (Counters, Metrics, counts_delta, dispatch_counts,
                      dispatch_delta, health_counts, health_delta,
                      register_dispatch_source, register_health_source,
                      timed, trace)
from .perf import (PerfBaselines, baselines, disable_observatory,
                   dump_ledger, enable_observatory, instrument_kernel,
                   kernel_report, kernel_snapshot, perf_stats,
                   register_mem_source, sample_watermarks,
                   watermark_snapshot)
from .recorder import (configure as configure_flight_recorder, clear_events,
                       dump_flight_record, flight_stats, last_flight_record,
                       recent_events, record_event)
from .slo import SloPolicy, SloRegistry, outcome_class, slo_stats
from .spans import (clear as clear_spans, export_chrome_trace, iter_spans,
                    record_span, span, span_count, span_seq, spanned,
                    spans_dropped)
from .tracecontext import TraceContext

__all__ = [
    'Metrics', 'timed', 'trace',
    'register_dispatch_source', 'dispatch_counts',
    'register_health_source', 'health_counts',
    'counts_delta', 'health_delta', 'dispatch_delta',
    'span', 'span_seq', 'spanned', 'iter_spans', 'clear_spans',
    'span_count', 'export_chrome_trace', 'record_span', 'spans_dropped',
    'Histogram', 'histogram', 'record_value', 'histogram_snapshot',
    'histogram_delta',
    'record_event', 'recent_events', 'clear_events', 'dump_flight_record',
    'last_flight_record', 'flight_stats', 'configure_flight_recorder',
    'SloPolicy', 'SloRegistry', 'outcome_class', 'slo_stats',
    'MetricsExporter', 'maybe_start_exporter', 'render_prometheus',
    'TraceContext', 'Counters',
    'PerfBaselines', 'baselines', 'enable_observatory',
    'disable_observatory', 'instrument_kernel', 'kernel_snapshot',
    'kernel_report', 'dump_ledger', 'register_mem_source',
    'sample_watermarks', 'watermark_snapshot', 'perf_stats',
    'enable', 'disable', 'enabled',
]


def enable(span_capacity=4096):
    """Turn span recording AND histogram recording on (the observe
    switch; off by default — the hot seams' instrumentation cost while
    off is one flag check per seam)."""
    _spans.enable(capacity=span_capacity)
    _hist.enable()


def disable():
    """Turn spans + histograms off (rings/registries are retained for
    inspection until the next enable()/reset)."""
    _spans.disable()
    _hist.disable()


def enabled():
    return _spans.on() or _hist.on()
