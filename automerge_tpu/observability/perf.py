"""Continuous performance observatory: seam baselines, the device
kernel cost ledger, and memory watermarks.

The SLO plane (slo.py) answers "is each TENANT inside its objectives";
this module answers the engineering twin — *is each SEAM as fast as it
was yesterday, what does each device kernel actually cost, and where
is the memory?* Three legs, all riding machinery the repo already
trusts:

- **Seam perf baselines** (``PerfBaselines``): every named seam reads
  its signal from an existing log2 histogram (``apply_batch_s``,
  ``sync_round_s``, ``fsync_s``, ``materialize_at_s``,
  ``subscription_diff_s``, ``service_tick_s``, ``shard_pump_s``) as
  consecutive (count, sum) deltas — the same incremental-delta
  discipline the SLO windows use, never a bucket rescan. Deltas
  accumulate into event WINDOWS (``window_events`` per window, so
  per-event ±40% box noise averages down before any judgment), each
  closed window's mean joins a preallocated ring, and the trailing
  baseline is an EWMA over those means that FREEZES while the current
  window drifts past the fire threshold — a regression must not teach
  the baseline its own slowdown. Drift (window mean / baseline) drives
  the round-14 hysteretic edge-triggered alert machinery (slo._Alert):
  a seam that sustains drift >= 1 + ``drift_pct`` for ``up_ticks``
  windows fires ONE edge, lands in the flight-recorder event ring, and
  assembles a forensic dump carrying the seam's recent spans (matched
  by the seam's span prefixes) plus its window-mean history. Gauges
  (baseline/window seconds, drift ratio, alert state) export on the
  Prometheus page.
- **Kernel cost ledger** (``instrument_kernel``): every jitted kernel
  entry point (fleet/apply.py, registers.py, sequence.py, bloom.py)
  is wrapped at its definition site. Off (default), the wrap costs one
  flag check per dispatch. On (``enable_ledger()``), each call counts
  per-kind dispatches and host-blocking wall seconds (= execution on
  the synchronous CPU backend this repo records on; = ENQUEUE time on
  async device backends — see ``instrument_kernel``) and records the
  call's abstract signature (shapes/dtypes; static scalars verbatim)
  ONCE per distinct compilation. XLA ``compiled.cost_analysis()`` (flops, bytes
  accessed) is resolved LAZILY per signature at report time — via
  ``jitted.lower(...).compile()`` on ShapeDtypeStructs, which hits the
  compile cache and never runs on the hot path. ``kernel_report()`` /
  ``dump_ledger()`` feed ``tools/obs_report.py --floor``: the
  residual-floor table (native parse vs scatter dispatch vs host
  phases) as live data instead of a hand-measured ROADMAP note.
- **Memory watermarks** (``sample_watermarks``): process RSS (VmRSS,
  with the kernel's own VmHWM high watermark) plus per-tier byte
  gauges from registered sources — fleet-resident device/mirror state
  (fleet/backend.py), the ``MainStore`` causal lanes (RESIDENT) and its
  mmap'd segment arena (``mainstore_disk_bytes`` — MAPPED, page-cache-
  served, deliberately outside the RSS budget; fleet/storage.py), the
  journal's ``pending_fsync_bytes`` loss window, and the span /
  flight-recorder rings — each with a process-lifetime high watermark.
  ``page_fault_counts()`` rides the same sampler: minor/major fault
  counters splitting "served from page cache" from "went to disk" for
  the storage tier's cold reads. This is the signal the cost-based
  tiering plane (fleet/tiering.py) consumes.

Everything is off by default. ``enable_observatory()`` /
``disable_observatory()`` flip all three legs together (the switch the
bench's paired <=2% budget is measured across, BENCH_r14_perf.json);
each leg also has its own switch. ``maybe_tick()`` is the cheap hook
the service tick calls: a no-op unless the default baselines registry
is enabled.
"""

import json
import os
import threading
import time

from . import hist as _hist
from . import recorder as _flight
from . import spans as _spans
from .metrics import Counters, register_health_source
from .slo import _Alert

__all__ = ['PerfBaselines', 'SeamSpec', 'DEFAULT_SEAMS', 'baselines',
           'enable_baselines', 'disable_baselines', 'maybe_tick',
           'instrument_kernel', 'enable_ledger', 'disable_ledger',
           'ledger_on', 'kernel_snapshot', 'kernel_report', 'kernel_kinds',
           'reset_ledger', 'dump_ledger',
           'register_mem_source', 'sample_watermarks',
           'watermark_snapshot', 'reset_watermarks', 'rss_bytes',
           'page_fault_counts',
           'enable_observatory', 'disable_observatory', 'perf_stats']

_stats = Counters({
    'perf_alerts_fired': 0,      # seam drift alert activations (monotonic)
    'perf_alerts_cleared': 0,    # seam drift alert deactivations
    'perf_alerts_active': 0,     # currently-firing seam alerts (gauge)
    'perf_ticks': 0,             # baseline evaluation ticks (monotonic)
    'kernel_dispatches': 0,      # ledger-counted kernel calls (monotonic)
})
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])


def perf_stats():
    return dict(_stats)


# ---- seam perf baselines ---------------------------------------------------

class SeamSpec:
    """One watched seam: which histogram carries its latency signal and
    which span-name prefixes a forensic dump should attach (the phase
    timeline around the regression)."""

    __slots__ = ('name', 'hist', 'span_prefixes')

    def __init__(self, name, hist, span_prefixes=()):
        self.name = name
        self.hist = hist
        self.span_prefixes = tuple(span_prefixes)


# The seams the repo has banked perf wins on (ROADMAP), each already
# instrumented with a log2 histogram at its hot path.
DEFAULT_SEAMS = (
    SeamSpec('apply_batch', 'apply_batch_s',
             ('turbo_', 'native_parse', 'parse_chunk')),
    SeamSpec('sync_round', 'sync_round_s', ('sync_', 'bloom_')),
    SeamSpec('fsync', 'fsync_s', ('journal_',)),
    SeamSpec('materialize_at', 'materialize_at_s', ('materialize',)),
    SeamSpec('subscription_diff', 'subscription_diff_s',
             ('subscription', 'diff')),
    SeamSpec('service_tick', 'service_tick_s', ('service_',)),
    SeamSpec('shard_pump', 'shard_pump_s', ('shard_tick',)),
)


class _SeamState:
    """Rolling state for one seam: the open window's accumulation, the
    preallocated ring of closed window means, the frozen-while-drifting
    EWMA baseline, and the hysteretic alert."""

    __slots__ = ('spec', 'prev_count', 'prev_total', 'win_events',
                 'win_total', 'ring', 'ring_n', 'ring_idx', 'windows',
                 'ewma', 'last_window', 'drift', 'alert')

    def __init__(self, spec, history):
        self.spec = spec
        self.prev_count = 0
        self.prev_total = 0.0
        self.win_events = 0        # events accumulated in the open window
        self.win_total = 0.0
        self.ring = [0.0] * history   # closed window means, preallocated
        self.ring_n = 0               # ring slots filled (<= history)
        self.ring_idx = 0             # next write position
        self.windows = 0              # lifetime closed windows
        self.ewma = None              # trailing baseline (seconds)
        self.last_window = None       # newest closed window mean
        self.drift = 1.0
        self.alert = _Alert()

    def recent_means(self):
        """Closed window means, oldest first."""
        n, cap = self.ring_n, len(self.ring)
        if n < cap:
            return list(self.ring[:n])
        return list(self.ring[self.ring_idx:]) + \
            list(self.ring[:self.ring_idx])


class PerfBaselines:
    """See the module docstring. Single-writer by contract (the tick
    caller); gauge readers take plain-dict snapshots."""

    def __init__(self, seams=DEFAULT_SEAMS, window_events=32, history=16,
                 ewma_alpha=0.3, drift_pct=0.20, up_ticks=2, down_ticks=6,
                 min_windows=3, forensic_spans=48):
        # tick() holds this lock: the default registry is driven from
        # every DocService.pump, and a ShardRouter pump POOL runs those
        # concurrently — two interleaved ticks would double-drain the
        # histogram deltas (both read the same prev_count) and race the
        # window rings. One uncontended acquire per tick, nothing on
        # any per-request path.
        self._tick_lock = threading.Lock()
        self.seams = {s.name: _SeamState(s, int(history)) for s in seams}
        self.window_events = int(window_events)
        self.ewma_alpha = float(ewma_alpha)
        self.drift_pct = float(drift_pct)
        self.up_ticks = int(up_ticks)
        self.down_ticks = int(down_ticks)
        # windows before a baseline is trusted enough to judge drift: a
        # cold seam must not alert off its very first (compile-warmup
        # shaped) window
        self.min_windows = int(min_windows)
        self.forensic_spans = int(forensic_spans)
        self.ticks = 0

    @property
    def fire_threshold(self):
        return 1.0 + self.drift_pct

    def record(self, seam, seconds):
        """Record a latency sample directly (the replay/test path, and
        seams without a registered histogram). Production seams feed
        through their histograms instead."""
        state = self.seams[seam]
        state.win_events += 1
        state.win_total += float(seconds)

    def tick(self):
        """One evaluation round: drain each seam's histogram delta into
        its open window, close windows that reached ``window_events``,
        fold closed means into the baseline, judge drift, drive alerts.
        Cost is O(seams) dict reads — independent of event volume.
        Thread-safe: concurrent tickers (the shard pump pool's services
        all drive the default registry) serialize on the tick lock."""
        with self._tick_lock:
            self._tick_locked()

    def _tick_locked(self):
        self.ticks += 1
        _stats.inc('perf_ticks')
        registry = _hist._registry
        for state in self.seams.values():
            h = registry.get(state.spec.hist)
            if h is not None:
                count, total = h.count, h.total
                d_count = count - state.prev_count
                if d_count > 0:
                    state.win_events += d_count
                    state.win_total += total - state.prev_total
                if d_count >= 0:
                    state.prev_count, state.prev_total = count, total
                else:
                    # the histogram registry was reset under us: re-pin
                    state.prev_count, state.prev_total = count, total
            while state.win_events >= self.window_events:
                self._close_window(state)

    def _close_window(self, state):
        """Close one window of exactly ``window_events`` events (an
        over-full open window carries its excess into the next — window
        means stay comparable across ticks of any cadence)."""
        n = self.window_events
        mean = state.win_total / state.win_events
        take_total = mean * n
        state.win_events -= n
        state.win_total = max(0.0, state.win_total - take_total)
        state.ring[state.ring_idx] = mean
        state.ring_idx = (state.ring_idx + 1) % len(state.ring)
        state.ring_n = min(state.ring_n + 1, len(state.ring))
        state.windows += 1
        state.last_window = mean
        baseline = state.ewma
        if baseline is None:
            state.ewma = mean
            state.drift = 1.0
            return
        drifting = state.windows > self.min_windows and \
            mean >= baseline * self.fire_threshold
        state.drift = (mean / baseline) if baseline > 0 else 1.0
        if not drifting:
            # fold the clean window into the trailing baseline; a
            # drifting window is QUARANTINED from it — the baseline must
            # not absorb the regression it exists to expose (else the
            # alert self-clears as the EWMA chases the slowdown)
            state.ewma = baseline + self.ewma_alpha * (mean - baseline)
        if state.windows <= self.min_windows:
            state.drift = 1.0
            return
        # the alert machinery judges EXCESS drift (drift - 1), not the
        # raw ratio: _Alert clears at signal <= threshold/2, which for a
        # ratio centered at 1.0 would demand the seam run ~40% FASTER
        # than its own baseline to clear — with the excess, fire holds
        # at drift >= 1 + drift_pct and clear at drift <= 1 + drift_pct/2
        edge = state.alert.observe(state.drift - 1.0, self.drift_pct,
                                   self.up_ticks, self.down_ticks)
        if edge is not None:
            self._transition(state, edge)

    def _transition(self, state, edge):
        name = state.spec.name
        if edge == 'fire':
            _stats.inc('perf_alerts_fired')
            _stats.inc('perf_alerts_active')
        else:
            _stats.inc('perf_alerts_cleared')
            _stats.inc('perf_alerts_active', -1)
        _flight.record_event(
            'perf_drift', seam=name, edge=edge,
            drift=round(state.drift, 3),
            window_s=state.last_window, baseline_s=state.ewma,
            tick=self.ticks)
        if edge == 'fire':
            prefixes = state.spec.span_prefixes
            spans = [s for s in _spans.iter_spans()
                     if s['name'].startswith(prefixes)] if prefixes else []
            _flight.dump_flight_record('perf', detail={
                'seam': name,
                'drift': round(state.drift, 3),
                'window_s': state.last_window,
                'baseline_s': state.ewma,
                'window_means_s': state.recent_means(),
                'offending_spans': spans[-self.forensic_spans:],
            })

    # -- read surfaces ---------------------------------------------------

    def gauges(self):
        """{seam: {'baseline_s', 'window_s', 'drift', 'alert',
        'windows'}} — plain data for the Prometheus page. Seams that
        closed no window yet are omitted (no series churn for idle
        seams)."""
        out = {}
        for name, state in self.seams.items():
            if state.windows == 0:
                continue
            out[name] = {'baseline_s': state.ewma,
                         'window_s': state.last_window,
                         'drift': round(state.drift, 4),
                         'alert': int(state.alert.active),
                         'windows': state.windows}
        return out

    def active_alerts(self):
        return [name for name, s in self.seams.items() if s.alert.active]


_default_baselines = None


def baselines():
    """The default registry (created enabled=False state on first use)."""
    global _default_baselines
    if _default_baselines is None:
        _default_baselines = PerfBaselines()
    return _default_baselines


_baselines_on = False


def enable_baselines(**kwargs):
    """Install (and reset) the default baselines registry; service ticks
    then drive it through ``maybe_tick``."""
    global _default_baselines, _baselines_on
    _default_baselines = PerfBaselines(**kwargs)
    _baselines_on = True
    return _default_baselines


def disable_baselines():
    global _baselines_on
    _baselines_on = False


def maybe_tick():
    """The per-tick hook (DocService.pump): one flag check when off."""
    if _baselines_on:
        baselines().tick()


def baseline_gauges():
    """Gauges of the default registry when enabled, else {} (what
    export.snapshot_all reads)."""
    if not _baselines_on or _default_baselines is None:
        return {}
    return _default_baselines.gauges()


# ---- device-kernel cost ledger ---------------------------------------------

_ledger_lock = threading.Lock()
_ledger_enabled = False
_kernels = {}                  # kind -> _KernelEntry


class _KernelEntry:
    __slots__ = ('kind', 'fn', 'dispatches', 'seconds', 'sigs')

    def __init__(self, kind, fn):
        self.kind = kind
        self.fn = fn
        self.dispatches = 0
        self.seconds = 0.0
        # sig key -> {'count', 'seconds', 'spec': (treedef, spec_leaves)}
        self.sigs = {}


def _sig_key(leaves):
    """Hashable signature of flattened call leaves: arrays by (shape,
    dtype), everything else (static ints, bools) by repr. The steady
    state computes ONLY this — the lowerable spec is built on a
    signature MISS (once per compilation), never per dispatch."""
    key = []
    for leaf in leaves:
        shape = getattr(leaf, 'shape', None)
        dtype = getattr(leaf, 'dtype', None)
        if shape is not None and dtype is not None:
            key.append(('a', tuple(shape), str(dtype)))
        else:
            key.append(('s', repr(leaf)))
    return tuple(key)


def _sig_spec(leaves):
    """The lazily-lowerable spec: arrays become ShapeDtypeStructs (so
    ``fn.lower`` can reproduce the compilation without values), static
    scalars ride verbatim."""
    import jax
    spec = []
    for leaf in leaves:
        shape = getattr(leaf, 'shape', None)
        dtype = getattr(leaf, 'dtype', None)
        if shape is not None and dtype is not None:
            spec.append(jax.ShapeDtypeStruct(tuple(shape), dtype))
        else:
            spec.append(leaf)
    return spec


def instrument_kernel(kind, jitted):
    """Wrap a jitted kernel entry point for the cost ledger. Off: one
    flag check of overhead per dispatch. The wrapper is transparent to
    donation and tracing (it only forwards), and exposes the jitted
    callable as ``__wrapped__``.

    Timing caveat: ``seconds`` is the HOST-BLOCKING wall time of the
    dispatch call. On the synchronous CPU backend (where this repo's
    numbers are recorded) that is the kernel's execution; on an async
    device backend it is ENQUEUE time — the wrapper deliberately does
    NOT ``block_until_ready`` (that would serialize the dispatch
    pipeline the seam exists to overlap), so device-time attribution
    there belongs to ``observability.trace`` profiler captures, and
    the derived GB/s columns read as host-side rates."""
    entry = _KernelEntry(kind, jitted)
    with _ledger_lock:
        _kernels[kind] = entry

    def wrapper(*args, **kwargs):
        if not _ledger_enabled:
            return jitted(*args, **kwargs)
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        dt = time.perf_counter() - t0
        import jax
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        key = _sig_key(leaves)
        with _ledger_lock:
            entry.dispatches += 1
            entry.seconds += dt
            sig = entry.sigs.get(key)
            if sig is None:
                # signature MISS (one per compilation): only now build
                # the lowerable ShapeDtypeStruct spec
                sig = entry.sigs[key] = {
                    'count': 0, 'seconds': 0.0,
                    'spec': (treedef, _sig_spec(leaves))}
            sig['count'] += 1
            sig['seconds'] += dt
        _stats.inc('kernel_dispatches')
        return out

    wrapper.__name__ = getattr(jitted, '__name__', kind)
    wrapper.__wrapped__ = jitted
    wrapper.kernel_kind = kind
    return wrapper


def enable_ledger():
    global _ledger_enabled
    _ledger_enabled = True


def disable_ledger():
    global _ledger_enabled
    _ledger_enabled = False


def ledger_on():
    return _ledger_enabled


def kernel_kinds():
    with _ledger_lock:
        return sorted(_kernels)


def reset_ledger():
    """Zero every entry's counters (instrumented kinds stay wired)."""
    with _ledger_lock:
        for entry in _kernels.values():
            entry.dispatches = 0
            entry.seconds = 0.0
            entry.sigs = {}


def kernel_snapshot():
    """{kind: {'dispatches', 'seconds', 'signatures'}} — the cheap
    monotonic view (Prometheus gauges; no compilation, no cost math)."""
    with _ledger_lock:
        return {kind: {'dispatches': e.dispatches,
                       'seconds': e.seconds,
                       'signatures': len(e.sigs)}
                for kind, e in _kernels.items() if e.dispatches}


def _cost_analysis_for(entry, spec):
    """Resolve XLA cost_analysis for one recorded signature via the AOT
    path on ShapeDtypeStructs — hits the compile cache, never executes.
    Returns a plain {str: float} dict or {'error': ...}."""
    treedef, leaves = spec
    import jax
    args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
    fn = entry.fn
    try:
        lowered = fn.lower(*args, **kwargs)
        cost = lowered.compile().cost_analysis()
    except Exception as exc:                      # noqa: BLE001
        return {'error': f'{type(exc).__name__}: {exc}'}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return {}
    out = {}
    for k, v in cost.items():
        if isinstance(v, (int, float)):
            out[str(k)] = float(v)
    return out


# cost_analysis cache: (kind, sig key) -> cost dict. Per COMPILATION,
# like the issue says — a kernel recompiled at a new capacity step is a
# new signature, a redispatch at a seen signature is a cache hit.
_cost_cache = {}

_COST_KEYS = ('flops', 'bytes accessed', 'transcendentals',
              'utilization operand 0', 'optimal_seconds')


def kernel_report(include_costs=True):
    """The full ledger: per kind, dispatch count, blocking wall seconds,
    and per-signature cost analysis (flops / bytes accessed, resolved
    lazily and cached). The shape ``tools/obs_report.py --floor``
    renders."""
    with _ledger_lock:
        entries = [(kind, e, {k: dict(count=s['count'],
                                      seconds=s['seconds'],
                                      spec=s['spec'])
                              for k, s in e.sigs.items()})
                   for kind, e in _kernels.items() if e.dispatches]
    report = {}
    for kind, entry, sigs in entries:
        kind_row = {'dispatches': entry.dispatches,
                    'seconds': round(entry.seconds, 6),
                    'signatures': []}
        flops_total = bytes_total = 0.0
        have_cost = False
        for key, sig in sigs.items():
            row = {'dispatches': sig['count'],
                   'seconds': round(sig['seconds'], 6)}
            if include_costs:
                with _ledger_lock:
                    cost = _cost_cache.get((kind, key))
                if cost is None:
                    cost = _cost_analysis_for(entry, sig['spec'])
                    with _ledger_lock:
                        _cost_cache[(kind, key)] = cost
                row['cost'] = {k: v for k, v in cost.items()
                               if k in _COST_KEYS or k == 'error'}
                if 'flops' in cost:
                    have_cost = True
                    flops_total += cost['flops'] * sig['count']
                    bytes_total += cost.get('bytes accessed', 0.0) * \
                        sig['count']
            kind_row['signatures'].append(row)
        if have_cost:
            kind_row['flops_total'] = flops_total
            kind_row['bytes_accessed_total'] = bytes_total
            if entry.seconds > 0:
                kind_row['gflops_per_s'] = flops_total / entry.seconds / 1e9
                kind_row['gbytes_per_s'] = bytes_total / entry.seconds / 1e9
        report[kind] = kind_row
    return report


def dump_ledger(path, include_costs=True, extra=None):
    """Write the ledger report as JSON (the ``obs_report --floor``
    input), atomically (temp + rename)."""
    body = {'kind': 'kernel_ledger', 'ts': time.time(),
            'kernels': kernel_report(include_costs=include_costs)}
    if extra:
        body.update(extra)
    tmp = f'{path}.tmp.{os.getpid()}'
    with open(tmp, 'w') as f:
        json.dump(body, f, indent=1, default=repr)
    os.replace(tmp, path)
    return path


# ---- memory watermarks -----------------------------------------------------

_mem_sources = {}
_mem_high = {}
_mem_last = {}
_mem_lock = threading.Lock()


def register_mem_source(name, fn):
    """Register a zero-arg callable returning a tier's CURRENT resident
    bytes (same registry discipline as register_dispatch_source; unlike
    the counter roll-ups these are gauges, so re-reads may go down)."""
    with _mem_lock:
        _mem_sources[name] = fn


def rss_bytes():
    """(rss, hwm) bytes of this process. Linux: VmRSS/VmHWM from
    /proc/self/status (the kernel's own high watermark); elsewhere:
    ru_maxrss doubles for both."""
    try:
        with open('/proc/self/status') as f:
            rss = hwm = 0
            for line in f:
                if line.startswith('VmRSS:'):
                    rss = int(line.split()[1]) * 1024
                elif line.startswith('VmHWM:'):
                    hwm = int(line.split()[1]) * 1024
            if rss:
                return rss, (hwm or rss)
    except OSError:
        pass
    import resource
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    return peak, peak


def page_fault_counts():
    """(minor, major) page faults for this process since start. Major
    faults are the storage tier's cold-read signal: an mmap'd parked
    chunk served off the page cache costs zero; one read from disk
    costs a major fault. Linux: /proc/self/stat fields 10/12;
    elsewhere: getrusage ru_minflt/ru_majflt."""
    try:
        with open('/proc/self/stat') as f:
            # field 2 (comm) may contain spaces — split after the
            # closing paren
            rest = f.read().rsplit(')', 1)[1].split()
        # rest[0] is field 3 (state); minflt/majflt are fields 10/12
        return int(rest[7]), int(rest[9])
    except (OSError, IndexError, ValueError):
        pass
    import resource
    ru = resource.getrusage(resource.RUSAGE_SELF)
    return int(ru.ru_minflt), int(ru.ru_majflt)


def sample_watermarks():
    """Read every tier source + RSS, fold the process-lifetime highs,
    return current values. Cost: one /proc read + one call per source —
    a per-tick sampler, not a per-request one. Page-fault counters ride
    along under 'pagefaults_minor'/'pagefaults_major' (monotonic
    counters, not byte gauges — the storage tier's cold-read split)."""
    rss, hwm = rss_bytes()
    current = {'rss': rss}
    highs = {'rss': max(hwm, rss)}
    minor, major = page_fault_counts()
    current['pagefaults_minor'] = highs['pagefaults_minor'] = minor
    current['pagefaults_major'] = highs['pagefaults_major'] = major
    for name, fn in list(_mem_sources.items()):
        try:
            value = int(fn())
        # archlint: ok[typed-errors] containment: a dying mem source must not take the sampler down; the source is skipped, not trusted
        except Exception:                         # noqa: BLE001
            continue
        current[name] = value
        highs[name] = value
    # sources were read unlocked (they may call back into modules that
    # take their own locks); only the shared fold holds _mem_lock
    with _mem_lock:
        for name, value in highs.items():
            _mem_high[name] = max(_mem_high.get(name, 0), value)
        _mem_last.clear()
        _mem_last.update(current)
    return current


def watermark_snapshot(sample=True):
    """{'current': {tier: bytes}, 'high': {tier: bytes}} — optionally
    sampling first (the exporter path samples so a scrape is never
    staler than its own page)."""
    current = sample_watermarks() if sample else dict(_mem_last)
    return {'current': current, 'high': dict(_mem_high)}


def reset_watermarks():
    with _mem_lock:
        _mem_high.clear()
        _mem_last.clear()


# the observatory's own rings are tiers too (bounded by design, but the
# bound should be VISIBLE): rough per-slot estimates, documented as such
def _span_ring_bytes():
    from . import spans as _spans
    return _spans._cap * 120        # (name, 2 ints, tid, attrs) estimate


def _flight_ring_bytes():
    return len(_flight._events) * 200


register_mem_source('span_ring_est', _span_ring_bytes)
register_mem_source('flight_ring_est', _flight_ring_bytes)


# ---- the one switch --------------------------------------------------------

def enable_observatory(**baseline_kwargs):
    """All three legs on (plus spans/histograms via observability.enable
    stays the caller's choice — the observatory needs only histograms).
    Returns the baselines registry."""
    _hist.enable()
    enable_ledger()
    reg = enable_baselines(**baseline_kwargs)
    sample_watermarks()
    return reg


def disable_observatory():
    disable_ledger()
    disable_baselines()
