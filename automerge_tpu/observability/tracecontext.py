"""Cross-peer trace stitching: one trace id from submit to sync reply.

A request that fans out through a fused batch and a sync exchange
leaves span fragments in several rings — the submitting service's tick
spans, the fused dispatch that carried N requests at once, and the
REMOTE peer's generate/receive spans — with nothing tying them
together. This module is the thread:

- ``TraceContext`` is (trace_id, span_id): 16 hex chars each, minted
  from a per-process random prefix + a counter (two peers can never
  collide; minting is one string format, cheap enough for every
  ``DocService.submit``).
- ``use(ctx)`` / ``current()``: a thread-local ambient context.
  Instrumented seams (sync generate/receive) attach
  ``trace=<trace_id>`` to their span attrs when a context is ambient —
  the attr rides the ordinary span ring into the Chrome-trace export,
  where ``tools/obs_report.py --stitch`` groups spans from MULTIPLE
  peers' exports by shared trace id.
- ``wrap(payload, ctx)`` / ``unwrap(data)``: the wire envelope — one
  magic byte (0x54, 'T'; sync messages start 0x42, cursors 0x51, so
  the namespaces cannot collide) + 8-byte trace id + 8-byte span id,
  prepended to an otherwise-unchanged payload. Enveloping is OPT-IN
  per message (a peer that never wraps produces byte-identical wire
  traffic to a build without this module); ``unwrap`` passes
  non-enveloped bytes through untouched, so a receiver can always
  probe. The service wraps a sync reply iff the request arrived
  wrapped — a tracing client opts its own requests in, and plain
  clients never see an envelope.

Batch attribution: the fused service batches record their member
requests' trace ids as a ``links`` span attr (one dispatch span →
N request traces), the span-link idiom of the OpenTelemetry data
model without the dependency.
"""

import contextlib
import itertools
import os
import threading

__all__ = ['TraceContext', 'TRACE_MAGIC', 'mint', 'current', 'use',
           'wrap', 'unwrap', 'trace_attr']

TRACE_MAGIC = 0x54           # 'T': a trace-envelope frame
_ENVELOPE_LEN = 1 + 8 + 8    # magic + trace id + span id

# per-process uniqueness: 4 random bytes + a counter; two peers minting
# concurrently diverge in the prefix, one peer's mints in the counter
_prefix = os.urandom(4).hex()
_counter = itertools.count(1)
_local = threading.local()


class TraceContext:
    """One request's identity across peers: ``trace_id`` names the whole
    request tree, ``span_id`` the minting site (the parent of whatever
    the receiving side records)."""

    __slots__ = ('trace_id', 'span_id')

    def __init__(self, trace_id, span_id):
        self.trace_id = trace_id
        self.span_id = span_id

    def child(self):
        """Same trace, fresh span id — what a peer continuing the trace
        stamps on its own side of the exchange."""
        return TraceContext(self.trace_id,
                            f'{_prefix}{next(_counter):08x}')

    def __eq__(self, other):
        return isinstance(other, TraceContext) and \
            self.trace_id == other.trace_id and \
            self.span_id == other.span_id

    def __repr__(self):
        return f'TraceContext({self.trace_id}, span={self.span_id})'


def mint():
    """A fresh context (new trace id). One string format + counter —
    the root span id IS the trace id (the minting site is the tree's
    root), so the format is not paid twice."""
    sid = f'{_prefix}{next(_counter):08x}'
    return TraceContext(sid, sid)


def current():
    """The ambient context set by ``use`` (None outside any block)."""
    return getattr(_local, 'ctx', None)


@contextlib.contextmanager
def use(ctx):
    """Make ``ctx`` ambient for the block: instrumented seams inside it
    attach the trace id to their spans, and a None ctx is allowed (the
    block then just restores whatever was ambient before)."""
    prev = getattr(_local, 'ctx', None)
    _local.ctx = ctx
    try:
        yield ctx
    finally:
        _local.ctx = prev


def trace_attr(ctx=None):
    """{'trace': id} for the given (or ambient) context, {} when there
    is none — the kwargs splat for span attrs at instrumented seams."""
    if ctx is None:
        ctx = getattr(_local, 'ctx', None)
    return {} if ctx is None else {'trace': ctx.trace_id}


def wrap(payload, ctx):
    """Prepend the trace envelope to a wire payload. A None ctx returns
    the payload untouched (callers can wrap unconditionally). The ids
    must be 16 hex chars (what mint/child/unwrap produce) — a
    hand-built context with short ids would emit an envelope whose
    fixed-offset unwrap on the peer silently eats payload bytes, so
    the length is enforced at this encode boundary."""
    if ctx is None:
        return payload
    trace_id = bytes.fromhex(ctx.trace_id)
    span_id = bytes.fromhex(ctx.span_id)
    if len(trace_id) != 8 or len(span_id) != 8:
        raise ValueError('trace/span ids must be 16 hex chars, got '
                         f'{ctx.trace_id!r}/{ctx.span_id!r}')
    return bytes([TRACE_MAGIC]) + trace_id + span_id + bytes(payload)


def unwrap(data):
    """(ctx, payload): strip the envelope when present, else
    (None, data) untouched. Never raises on short/foreign bytes — the
    envelope namespace is disjoint from every other frame magic, so a
    leading 0x54 with enough bytes IS an envelope."""
    if data is None or len(data) < _ENVELOPE_LEN or data[0] != TRACE_MAGIC:
        return None, data
    body = bytes(data[1:_ENVELOPE_LEN])
    return (TraceContext(body[:8].hex(), body[8:].hex()),
            bytes(data[_ENVELOPE_LEN:]))
