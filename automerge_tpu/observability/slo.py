"""Per-tenant SLO telemetry: SLIs, error budgets, burn-rate alerting.

The flight recorder answers post-hoc forensic questions; this module
answers the standing one — *is each tenant inside its service
objectives right now, and if not, how fast is its error budget
burning?* Three SLI families, all maintained as cheap rolling deltas
over state the request path already touches (the continuous-view
framing of "Formal Foundations of Continuous Graph Processing",
PAPERS.md: incremental maintenance over the event stream, never a full
rescan):

- **Latency**: per (tenant, kind), the fraction of committed requests
  completing under ``SloPolicy.threshold_s``, classified bucketwise on
  the per-pair ``service_request_s`` log2 histogram: good iff the
  bucket's UPPER bound is within the threshold (the boundary bucket is
  precomputed per pair, so the per-request cost is one integer
  compare). The per-tick (good, bad) movement the windows consume is
  the INCREMENTAL form of ``counts_delta``/``Histogram.delta`` between
  consecutive tick snapshots — accumulated at record time instead of
  recomputed by subtraction, same numbers, none of the rescan
  (tests/test_slo.py pins the equivalence).
- **Availability**: committed vs typed-rejection fractions, split by
  rejection class. The classes burn DIFFERENT budgets —
  ``TenantThrottled`` (the tenant ran itself dry; a generous budget),
  ``Overloaded`` (the service shed; a tight budget), and
  ``DeadlineExceeded`` (admitted but too late; the tightest) — so a
  tenant flooding itself into throttles cannot mask the service
  starting to shed other work. The class comes from the typed error's
  ``budget`` attribute (errors.py), never from string matching.
- **Freshness**: subscription cursor lag in service ticks — how long a
  subscriber's cursor trailed the document heads before a push caught
  it up (fed by ``DocService._run_subscriptions`` and
  ``SubscriptionHub.bind_slo``).

Objectives are ``SloPolicy(target, ...)`` declarations resolved most
specific first: (tenant, kind) > kind > registry default, cached per
pair. Evaluation is multi-window burn-rate alerting: burn =
bad_fraction / (1 - target) over a FAST window (default 5 ticks, high
threshold — pages on sharp regressions) and a SLOW window (default 60
ticks, low threshold — catches slow leaks), each window's alert
edge-triggered and hysteretic like the brownout ladder (sustained
above-threshold ticks to fire, sustained below-clear ticks to clear,
so a flapping signal cannot thrash). Every transition bumps the
``slo_alerts_fired``/``slo_alerts_cleared`` health counters, lands in
the flight-recorder event ring, and an alert FIRING assembles a full
forensic dump carrying the offending tenant's recent request outcomes.

``SloRegistry.record`` is the per-request hot path (a few dict adds +
one histogram record); ``tick()`` runs once per service tick over the
DIRTY pairs only, plus the pairs with a currently-firing alert (their
clear hysteresis needs per-tick decay) — an idle pair costs NOTHING
per tick, its windows catching up with zeros on the next push. The
steady-state cost is therefore proportional to the tenants actually
talking this tick, not the tenant universe, which is what holds the
measured budget to <=2% on the 10k-session clean service leg (bench.py
``slo`` section, paired alternating-order reps — BASELINE.md "SLO
contract").
"""

import array
import collections

from . import hist as _hist
from . import recorder as _flight
from .metrics import Counters, register_health_source

__all__ = ['SloPolicy', 'SloRegistry', 'outcome_class', 'slo_stats',
           'DEFAULT_POLICIES', 'AVAILABILITY_CLASSES']

# rejection classes that burn an availability budget (each its own SLO;
# 'wire'/'error'/'retries' outcomes are tallied but burn no budget by
# default — they are the CLIENT's bytes or a typed retry exhaustion)
AVAILABILITY_CLASSES = ('throttled', 'overloaded', 'deadline')

_stats = Counters({
    'slo_alerts_fired': 0,       # alert activations (monotonic)
    'slo_alerts_cleared': 0,     # alert deactivations (monotonic)
    'slo_alerts_active': 0,      # currently-firing alerts (gauge)
    'slo_ticks': 0,              # registry evaluation ticks (monotonic)
})
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])


def slo_stats():
    return dict(_stats)


def outcome_class(error):
    """Budget class of one request resolution: 'committed' for success,
    the typed error's ``budget`` attribute ('throttled' / 'overloaded' /
    'deadline') for the shedding classes, 'retries' for exhausted retry
    schedules, 'wire' for corruption the client sent, 'error' for
    everything else typed."""
    if error is None:
        return 'committed'
    budget = getattr(error, 'budget', None)
    if budget is not None:
        return budget
    from ..errors import RetriesExhausted, WireCorruption
    if isinstance(error, RetriesExhausted):
        return 'retries'
    if isinstance(error, WireCorruption):
        return 'wire'
    return 'error'


class SloPolicy:
    """One objective: ``target`` is the good fraction (0.99 = 1% error
    budget). ``threshold_s`` scopes latency SLOs (a committed request is
    good iff its histogram bucket's upper bound is <= threshold_s —
    conservative, like the percentile convention in hist.py);
    ``max_lag_ticks`` scopes freshness SLOs. Window geometry and burn
    thresholds: the FAST window (default 5 ticks) alerts at
    ``fast_burn`` (sharp regressions), the SLOW window (default 60) at
    ``slow_burn`` (slow leaks). Hysteresis mirrors the brownout ladder:
    burn must hold >= the threshold for ``up_ticks`` evaluations to
    fire and <= threshold/2 for ``down_ticks`` to clear; windows with
    fewer than ``min_events`` observations evaluate as burn 0 (no
    alerting on noise floors)."""

    __slots__ = ('target', 'threshold_s', 'max_lag_ticks', 'fast_window',
                 'slow_window', 'fast_burn', 'slow_burn', 'up_ticks',
                 'down_ticks', 'min_events')

    def __init__(self, target, threshold_s=None, max_lag_ticks=None,
                 fast_window=5, slow_window=60, fast_burn=8.0,
                 slow_burn=2.0, up_ticks=2, down_ticks=10, min_events=8):
        if not 0.0 < target < 1.0:
            raise ValueError(f'target must be in (0, 1), got {target!r}')
        self.target = float(target)
        self.threshold_s = threshold_s
        self.max_lag_ticks = max_lag_ticks
        self.fast_window = int(fast_window)
        self.slow_window = int(slow_window)
        if self.fast_window <= 0 or self.slow_window < self.fast_window:
            raise ValueError('need 0 < fast_window <= slow_window')
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.up_ticks = int(up_ticks)
        self.down_ticks = int(down_ticks)
        self.min_events = int(min_events)

    @property
    def budget(self):
        return 1.0 - self.target

    def __repr__(self):
        return (f'SloPolicy(target={self.target}, '
                f'threshold_s={self.threshold_s}, '
                f'max_lag_ticks={self.max_lag_ticks})')


# Registry defaults: deliberately loose enough that a healthy service
# never pages, documented in BASELINE.md "SLO contract". Callers with a
# real contract override per kind or per (tenant, kind).
DEFAULT_POLICIES = {
    'latency': SloPolicy(0.99, threshold_s=0.25),
    'avail_throttled': SloPolicy(0.95),
    'avail_overloaded': SloPolicy(0.99),
    'avail_deadline': SloPolicy(0.995),
    'freshness': SloPolicy(0.95, max_lag_ticks=8),
}


class _Window:
    """One (good, bad) event stream evaluated over two nested rolling
    tick windows, held in a PREALLOCATED ring of ``slow_n`` per-tick
    slots (slot = tick % slow_n) with running sums for both spans — a
    push mutates ints in place and allocates NOTHING. That matters
    beyond the raw op count: the first cut kept (tick, good, bad)
    tuples in eviction deques, and the ~10^5 short-lived tuples per
    service leg tripled the measured overhead via gen-0 GC pressure
    (the collector's cost lands OUTSIDE the accounting wrappers, which
    is exactly how it hid from the in-leg attribution).

    A gap of idle ticks is caught up on the next push by zeroing only
    the skipped slots (bounded by ``slow_n``; a gap past the slow span
    resets the whole ring in O(slow_n), independent of gap length), so
    idle pairs still cost nothing per tick. Sums are identical to the
    dense per-tick interpretation: a window covers the half-open tick
    span (now - n, now]."""

    __slots__ = ('fast_n', 'slow_n', 'ring_good', 'ring_bad',
                 'fast_good', 'fast_bad', 'slow_good', 'slow_bad',
                 'last_tick', 'zero_published')

    def __init__(self, fast_n, slow_n):
        self.fast_n = fast_n
        self.slow_n = slow_n
        # array('q'), not list: raw C longs carry no per-slot PyObject
        # pointers, so a registry's hundreds of rings add NOTHING to
        # the GC's gen-1/2 scan working set (with list rings the sweep
        # cost showed up as paired-leg overhead the in-leg attribution
        # could not see)
        self.ring_good = array.array('q', bytes(8 * slow_n))
        self.ring_bad = array.array('q', bytes(8 * slow_n))
        self.fast_good = self.fast_bad = 0
        self.slow_good = self.slow_bad = 0
        self.last_tick = None
        self.zero_published = False    # healthy gauge already rendered 0

    def _advance(self, tick):
        """Roll the ring forward to ``tick``: every tick slot walked in
        order, evicting the slot's previous occupant (tick - slow_n)
        from the slow sums and the tick leaving the fast span from the
        fast sums, then zeroing the slot for its new tick."""
        last = self.last_tick
        slow_n = self.slow_n
        if last is None or tick - last >= slow_n:
            ring = self.ring_good
            for i in range(slow_n):
                ring[i] = 0
            ring = self.ring_bad
            for i in range(slow_n):
                ring[i] = 0
            self.fast_good = self.fast_bad = 0
            self.slow_good = self.slow_bad = 0
        else:
            ring_good = self.ring_good
            ring_bad = self.ring_bad
            fast_n = self.fast_n
            for t in range(last + 1, tick + 1):
                # fast eviction first: with fast_n == slow_n the two
                # horizons share a slot, and the slow step zeroes it
                f = (t - fast_n) % slow_n
                g = ring_good[f]
                b = ring_bad[f]
                if g or b:
                    self.fast_good -= g
                    self.fast_bad -= b
                s = t % slow_n
                g = ring_good[s]
                b = ring_bad[s]
                if g or b:
                    self.slow_good -= g
                    self.slow_bad -= b
                    ring_good[s] = 0
                    ring_bad[s] = 0
        self.last_tick = tick

    def push(self, tick, good, bad):
        self._advance(tick)
        if good or bad:
            s = tick % self.slow_n
            self.ring_good[s] = good
            self.ring_bad[s] = bad
            self.slow_good += good
            self.slow_bad += bad
            self.fast_good += good
            self.fast_bad += bad
            if bad:
                self.zero_published = False

    @property
    def empty(self):
        return self.slow_good == 0 and self.slow_bad == 0

    def burn(self, policy):
        """(fast_burn, slow_burn) rates vs the policy's error budget.
        Windows under ``min_events`` observations read 0 (noise floor)."""
        out = []
        for good, bad in ((self.fast_good, self.fast_bad),
                          (self.slow_good, self.slow_bad)):
            total = good + bad
            if total < policy.min_events:
                out.append(0.0)
            else:
                out.append((bad / total) / policy.budget)
        return out[0], out[1]


class _AvailWindow:
    """The three availability SLIs share their good stream (committed
    requests) and, in the healthy steady state, differ in nothing at
    all — so one merged window carries (committed, throttled,
    overloaded, deadline) per entry with running sums per class,
    turning three deque pushes + three evictions per dirty pair per
    tick into one. Requires the classes' policies to share window
    geometry (the defaults do; heterogeneous geometries fall back to
    per-SLI ``_Window``s). Per-class burn semantics are identical to
    three independent windows: an SLI's denominator is committed + its
    OWN bad class."""

    __slots__ = ('fast_n', 'slow_n', 'ring', 'fast', 'slow',
                 'last_tick', 'zero_published')

    def __init__(self, fast_n, slow_n):
        self.fast_n = fast_n
        self.slow_n = slow_n
        # flat preallocated ring: 4 lanes per tick slot (same
        # allocation-free, GC-invisible discipline as _Window)
        self.ring = array.array('q', bytes(8 * slow_n * 4))
        self.fast = [0, 0, 0, 0]     # committed, throttled, over, deadline
        self.slow = [0, 0, 0, 0]
        self.last_tick = None
        self.zero_published = False

    def _advance(self, tick):
        last = self.last_tick
        slow_n = self.slow_n
        ring = self.ring
        if last is None or tick - last >= slow_n:
            for i in range(slow_n * 4):
                ring[i] = 0
            self.fast = [0, 0, 0, 0]
            self.slow = [0, 0, 0, 0]
        else:
            fast_n = self.fast_n
            fast = self.fast
            slow = self.slow
            for t in range(last + 1, tick + 1):
                f = ((t - fast_n) % slow_n) * 4
                if ring[f] or ring[f + 1] or ring[f + 2] or ring[f + 3]:
                    fast[0] -= ring[f]
                    fast[1] -= ring[f + 1]
                    fast[2] -= ring[f + 2]
                    fast[3] -= ring[f + 3]
                s = (t % slow_n) * 4
                if ring[s] or ring[s + 1] or ring[s + 2] or ring[s + 3]:
                    slow[0] -= ring[s]
                    slow[1] -= ring[s + 1]
                    slow[2] -= ring[s + 2]
                    slow[3] -= ring[s + 3]
                    ring[s] = ring[s + 1] = ring[s + 2] = ring[s + 3] = 0
        self.last_tick = tick

    def push(self, tick, committed, thr, ovl, dl):
        self._advance(tick)
        if committed or thr or ovl or dl:
            s = (tick % self.slow_n) * 4
            ring = self.ring
            ring[s] = committed
            ring[s + 1] = thr
            ring[s + 2] = ovl
            ring[s + 3] = dl
            slow = self.slow
            slow[0] += committed
            fast = self.fast
            fast[0] += committed
            if thr or ovl or dl:
                slow[1] += thr
                slow[2] += ovl
                slow[3] += dl
                fast[1] += thr
                fast[2] += ovl
                fast[3] += dl
                self.zero_published = False

    @property
    def bad_total(self):
        slow = self.slow
        return slow[1] + slow[2] + slow[3]

    @property
    def empty(self):
        return not any(self.slow)

    def burn(self, idx, policy):
        """(fast, slow) burn of availability class ``idx`` (0=throttled,
        1=overloaded, 2=deadline) vs its policy."""
        out = []
        for sums in (self.fast, self.slow):
            total = sums[0] + sums[idx + 1]
            if total < policy.min_events:
                out.append(0.0)
            else:
                out.append((sums[idx + 1] / total) / policy.budget)
        return out[0], out[1]


class _Alert:
    """Hysteretic edge-triggered alert state for one window of one SLO
    (the brownout ladder's transition discipline, applied to burn)."""

    __slots__ = ('active', 'above', 'below')

    def __init__(self):
        self.active = False
        self.above = 0
        self.below = 0

    def observe(self, burn, threshold, up_ticks, down_ticks):
        """Returns 'fire' / 'clear' on an edge, None otherwise."""
        if burn >= threshold:
            self.above += 1
            self.below = 0
        elif burn <= threshold / 2.0:
            self.below += 1
            self.above = 0
        else:
            self.above = 0
            self.below = 0
        if not self.active and self.above >= up_ticks:
            self.active = True
            self.above = 0
            return 'fire'
        if self.active and self.below >= down_ticks:
            self.active = False
            self.below = 0
            return 'clear'
        return None


# pending-delta slots (see _PairState.pending): one tick's (good, bad)
# movement per SLI, accumulated AT RECORD TIME so the tick roll never
# rescans counters or buckets. The committed count doubles as the good
# side of every availability SLO.
_P_COMMITTED, _P_THROTTLED, _P_OVERLOADED, _P_DEADLINE = 0, 1, 2, 3
_P_LAT_GOOD, _P_LAT_BAD, _P_FRESH_GOOD, _P_FRESH_BAD = 4, 5, 6, 7


class _PairState:
    """Everything the registry tracks for one (tenant, kind) pair."""

    __slots__ = ('tallies', 'hist', 'lag_max', 'windows', 'alerts',
                 'pending', 'policy_gen', 'lat_policy', 'lat_good_bucket',
                 'avail_policies', 'fresh_policy', 'avail_window')

    def __init__(self):
        self.tallies = {}            # outcome class -> monotonic count
        self.hist = None             # committed-request latency histogram
        self.lag_max = 0             # worst cursor lag ever seen (gauge)
        self.windows = {}            # sli -> _Window (latency/freshness,
        #                              and the avail fallback path)
        self.alerts = {}             # (sli, 'fast'|'slow') -> _Alert
        self.pending = [0] * 8       # this tick's per-SLI (good, bad)
        self.policy_gen = -1         # resolved-policy cache generation
        self.lat_policy = None
        self.lat_good_bucket = -1    # largest log2 bucket within threshold
        self.avail_policies = (None, None, None)
        self.fresh_policy = None
        self.avail_window = None     # merged _AvailWindow when geometry
        #                              is homogeneous across the classes


class SloRegistry:
    """See the module docstring. Single-writer by contract (the service
    tick thread); readers (the metrics exporter) take snapshot copies
    with a bounded retry, so a concurrent scrape never sees a torn
    dict."""

    def __init__(self, policies=None, tick_windows=True, forensics=24):
        base = dict(DEFAULT_POLICIES)
        if policies:
            base.update(policies)
        # (sli, tenant, kind) -> SloPolicy; None wildcards, resolved
        # most-specific-first and cached per concrete (tenant, kind, sli)
        self._policies = {(sli, None, None): p for sli, p in base.items()
                          if p is not None}
        self._policy_cache = {}
        self._policy_gen = 0         # bumped by set_policy: pairs re-pin
        self._pairs = {}             # (tenant, kind) -> _PairState
        self._dirty = set()          # pairs touched since the last tick
        self._alerting = set()       # pairs with an alert currently firing
        self._gauges = {}            # (tenant, kind, sli) -> gauge dict
        self._forensics = {}         # tenant -> deque of recent outcomes
        self._forensic_cap = int(forensics)
        self._tick_windows = bool(tick_windows)
        self.ticks = 0
        # (tick, tenant, kind, sli, window, 'fire'|'clear', burn) —
        # BOUNDED like every other telemetry ring here (a flapping
        # tenant must not grow process memory forever); lifetime totals
        # live in the slo_alerts_fired/cleared health counters, so a
        # wrapped log discloses its loss as fired+cleared-len(log)
        self.alert_log = collections.deque(maxlen=4096)

    # -- objectives -----------------------------------------------------

    def set_policy(self, sli, policy, tenant=None, kind=None):
        """Declare (or, with policy=None, remove) the objective for
        ``sli`` ('latency', 'avail_throttled', 'avail_overloaded',
        'avail_deadline', 'freshness'), scoped to a tenant and/or kind
        (None = wildcard)."""
        key = (sli, tenant, kind)
        if policy is None:
            self._policies.pop(key, None)
        else:
            self._policies[key] = policy
        self._policy_cache.clear()
        self._policy_gen += 1        # existing pairs re-pin lazily

    def policy_for(self, sli, tenant, kind):
        """Most-specific policy for (sli, tenant, kind); None when the
        SLI has no objective at any scope."""
        ckey = (sli, tenant, kind)
        try:
            return self._policy_cache[ckey]
        except KeyError:
            pass
        for key in ((sli, tenant, kind), (sli, None, kind),
                    (sli, tenant, None), (sli, None, None)):
            policy = self._policies.get(key)
            if policy is not None:
                break
        self._policy_cache[ckey] = policy
        return policy

    # -- the per-request hot path ---------------------------------------

    def _pair(self, tenant, kind):
        key = (tenant, kind)
        pair = self._pairs.get(key)
        if pair is None:
            pair = self._pairs[key] = _PairState()
        if pair.policy_gen != self._policy_gen:
            self._resolve_pair_policies(pair, tenant, kind)
        return pair

    def _resolve_pair_policies(self, pair, tenant, kind):
        """Pin the pair's resolved policies (re-done when set_policy
        bumps the generation): the hot path then classifies against
        plain attributes instead of walking the scope ladder. An SLI
        whose objective was REMOVED drops its windows and alerts here
        (an active alert counts as cleared — it must not dangle in the
        gauges or pin the pair in the per-tick alerting set)."""
        pair.policy_gen = self._policy_gen
        pair.lat_policy = self.policy_for('latency', tenant, kind)
        pair.lat_good_bucket = -1
        if pair.lat_policy is not None and \
                pair.lat_policy.threshold_s is not None:
            # good iff the log2 bucket's UPPER bound 2^b/scale is within
            # the threshold: b <= floor(log2(threshold * scale)) — the
            # bucketwise histogram-delta classification, precomputed to
            # one integer compare per committed request
            scaled = int(pair.lat_policy.threshold_s * 1e9)
            pair.lat_good_bucket = scaled.bit_length() - 1 \
                if scaled >= 1 else -1
        pair.avail_policies = tuple(
            self.policy_for(f'avail_{cls}', tenant, kind)
            for cls in AVAILABILITY_CLASSES)
        pair.fresh_policy = self.policy_for('freshness', tenant, kind)
        geometries = {(p.fast_window, p.slow_window)
                      for p in pair.avail_policies if p is not None}
        if len(geometries) == 1:
            geometry = geometries.pop()
            if pair.avail_window is None or \
                    (pair.avail_window.fast_n,
                     pair.avail_window.slow_n) != geometry:
                pair.avail_window = _AvailWindow(*geometry)
            # merged mode owns the avail accounting: per-SLI fallback
            # windows (from an earlier heterogeneous config) retire
            for cls in AVAILABILITY_CLASSES:
                pair.windows.pop(f'avail_{cls}', None)
        else:
            pair.avail_window = None
        live = {f'avail_{cls}' for cls, p in
                zip(AVAILABILITY_CLASSES, pair.avail_policies)
                if p is not None}
        if pair.lat_policy is not None:
            live.add('latency')
        if pair.fresh_policy is not None:
            live.add('freshness')
        for sli in [s for s in pair.windows if s not in live]:
            del pair.windows[sli]
        # gauges swept for EVERY de-declared SLI, not just windowed
        # ones: merged-avail mode keeps the avail SLIs out of
        # pair.windows, so their burn/alert gauges would otherwise
        # export stale series forever after set_policy(..., None)
        for sli in (['latency', 'freshness'] +
                    [f'avail_{c}' for c in AVAILABILITY_CLASSES]):
            if sli not in live:
                self._gauges.pop((tenant, kind, sli), None)
        for key in [k for k in pair.alerts if k[0] not in live]:
            alert = pair.alerts.pop(key)
            if alert.active:
                _stats.inc('slo_alerts_cleared')
                _stats.inc('slo_alerts_active', -1)
                self.alert_log.append((self.ticks, tenant, kind, key[0],
                                       key[1], 'clear', 0.0))
        if not any(a.active for a in pair.alerts.values()):
            self._alerting.discard((tenant, kind))

    def record(self, tenant, kind, latency_s, error=None, trace=None):
        """One request resolution (or typed admission rejection). The
        latency lands in the pair's histogram only for COMMITTED
        requests — a fast typed rejection must not flatter the latency
        SLI. ``trace`` is the request's trace id (tracecontext.py),
        kept in the forensic ring so an alert's dump stitches into the
        Perfetto view. This is the per-request hot path: the committed
        branch is laid out straight-line (no classifier call, one key
        tuple) because the clean leg takes it 100% of the time."""
        key = (tenant, kind)
        pair = self._pairs.get(key)
        if pair is None:
            pair = self._pairs[key] = _PairState()
        if pair.policy_gen != self._policy_gen:
            self._resolve_pair_policies(pair, tenant, kind)
        pending = pair.pending
        if error is None:
            cls = 'committed'
            hist = pair.hist
            if hist is None:
                hist = pair.hist = _hist.Histogram(
                    f'service_request_s:{tenant}:{kind}', scale=1e9,
                    unit='s')
            bucket = hist.record(latency_s)
            pending[_P_COMMITTED] += 1
            if pair.lat_good_bucket >= 0:
                if bucket <= pair.lat_good_bucket:
                    pending[_P_LAT_GOOD] += 1
                else:
                    pending[_P_LAT_BAD] += 1
        else:
            cls = outcome_class(error)
            if cls == 'throttled':
                pending[_P_THROTTLED] += 1
            elif cls == 'overloaded':
                pending[_P_OVERLOADED] += 1
            elif cls == 'deadline':
                pending[_P_DEADLINE] += 1
        pair.tallies[cls] = pair.tallies.get(cls, 0) + 1
        self._dirty.add(key)
        forensics = self._forensics.get(tenant)
        if forensics is None:
            forensics = self._forensics[tenant] = collections.deque(
                maxlen=self._forensic_cap)
        # latency kept as integer microseconds: cheaper than rounding a
        # float on every request, converted back at dump time
        forensics.append((self.ticks, kind, cls, int(latency_s * 1e6),
                          trace))

    def record_freshness(self, tenant, lag_ticks, kind='subscribe'):
        """One subscription push's cursor lag (ticks the cursor trailed
        the heads before this push). Good iff within the freshness
        policy's ``max_lag_ticks``; without a policy only the lag gauge
        moves."""
        pair = self._pair(tenant, kind)
        if lag_ticks > pair.lag_max:
            pair.lag_max = lag_ticks
        policy = pair.fresh_policy
        if policy is None or policy.max_lag_ticks is None:
            return
        if lag_ticks <= policy.max_lag_ticks:
            pair.pending[_P_FRESH_GOOD] += 1
        else:
            pair.pending[_P_FRESH_BAD] += 1
        self._dirty.add((tenant, kind))

    # -- the tick -------------------------------------------------------

    def tick(self, now=None):
        """One evaluation round over the DIRTY pairs (touched since the
        last tick) plus the pairs with a currently-firing alert (their
        clear hysteresis needs per-tick decay). Idle pairs cost NOTHING
        here — their windows catch up with zeros when they next push
        (see _Window.push) — so the steady-state tick is O(talkers),
        independent of the tenant universe and of request volume."""
        self.ticks += 1
        _stats.inc('slo_ticks')
        if not self._tick_windows:
            self._dirty.clear()
            return
        transitions = []
        todo = self._dirty
        if self._alerting:
            todo = todo | self._alerting
        for key in todo:
            pair = self._pairs[key]
            if pair.policy_gen != self._policy_gen:
                # a policy change mid-flight: re-pin (and shed windows/
                # alerts for de-declared SLIs) even if the pair is only
                # here because its alert is decaying
                self._resolve_pair_policies(pair, key[0], key[1])
            self._roll(key, pair, transitions)
        self._dirty = set()
        for tenant, kind, sli, window, edge, burn in transitions:
            self._transition(tenant, kind, sli, window, edge, burn)

    def _roll(self, key, pair, transitions):
        """Push one pair's pending per-SLI (good, bad) deltas — the
        incremental form of ``counts_delta`` between consecutive tally
        snapshots, accumulated at record time — into its windows, then
        evaluate burn and drive the alert edges."""
        tenant, kind = key
        pending = pair.pending
        tick_no = self.ticks
        committed = pending[_P_COMMITTED]
        windows = pair.windows
        avail_window = pair.avail_window
        if avail_window is not None:
            # merged path (homogeneous geometry — the default config):
            # ONE push covers all three classes, and the healthy fast
            # path skips all three evaluations in one compare
            avail_window.push(tick_no, committed, pending[_P_THROTTLED],
                              pending[_P_OVERLOADED],
                              pending[_P_DEADLINE])
            if not (avail_window.bad_total == 0 and
                    avail_window.zero_published and not pair.alerts):
                for i, cls in enumerate(AVAILABILITY_CLASSES):
                    policy = pair.avail_policies[i]
                    if policy is None:
                        continue
                    fast, slow = avail_window.burn(i, policy)
                    self._drive_alert(tenant, kind, 'avail_' + cls,
                                      policy, fast, slow, pair,
                                      transitions)
                if avail_window.bad_total == 0:
                    avail_window.zero_published = True
        else:
            for i, cls in enumerate(AVAILABILITY_CLASSES):
                policy = pair.avail_policies[i]
                if policy is None:
                    continue
                sli = 'avail_' + cls
                bad = pending[i + 1]
                window = windows.get(sli)
                if window is None:
                    if not (committed or bad):
                        continue
                    window = windows[sli] = _Window(policy.fast_window,
                                                    policy.slow_window)
                window.push(tick_no, committed, bad)
                self._evaluate_one(tenant, kind, sli, policy, window,
                                   pair, transitions)
        policy = pair.lat_policy
        if policy is not None and pair.lat_good_bucket >= 0:
            good, bad = pending[_P_LAT_GOOD], pending[_P_LAT_BAD]
            window = windows.get('latency')
            if window is None and (good or bad):
                window = windows['latency'] = _Window(policy.fast_window,
                                                      policy.slow_window)
            if window is not None:
                window.push(tick_no, good, bad)
                self._evaluate_one(tenant, kind, 'latency', policy,
                                   window, pair, transitions)
        policy = pair.fresh_policy
        if policy is not None:
            good, bad = pending[_P_FRESH_GOOD], pending[_P_FRESH_BAD]
            window = windows.get('freshness')
            if window is None and (good or bad):
                window = windows['freshness'] = _Window(
                    policy.fast_window, policy.slow_window)
            if window is not None:
                window.push(tick_no, good, bad)
                self._evaluate_one(tenant, kind, 'freshness', policy,
                                   window, pair, transitions)
        for i in range(8):
            pending[i] = 0

    def _evaluate_one(self, tenant, kind, sli, policy, window, pair,
                      transitions):
        if window.slow_bad == 0 and window.zero_published and \
                (sli, 'fast') not in pair.alerts and \
                (sli, 'slow') not in pair.alerts:
            # the healthy steady state (the clean leg's every pair): no
            # bad events anywhere in the slow span, gauges already read
            # 0, no alert brewing or decaying — nothing can transition,
            # so the evaluation is three compares and out
            return
        fast, slow = window.burn(policy)
        self._drive_alert(tenant, kind, sli, policy, fast, slow, pair,
                          transitions)
        if window.slow_bad == 0:
            window.zero_published = True

    def _drive_alert(self, tenant, kind, sli, policy, fast, slow, pair,
                     transitions):
        """Publish one SLI's burns to its gauge and run both windows'
        hysteretic alert machinery."""
        gauge = self._gauges.get((tenant, kind, sli))
        if gauge is None:
            gauge = self._gauges[(tenant, kind, sli)] = {}
        gauge['fast_burn'] = fast
        gauge['slow_burn'] = slow
        for wname, burn, threshold in (('fast', fast, policy.fast_burn),
                                       ('slow', slow, policy.slow_burn)):
            alert = pair.alerts.get((sli, wname))
            if alert is None:
                if burn < threshold:
                    gauge['alert_' + wname] = 0
                    continue        # nothing brewing: stay allocation-free
                alert = pair.alerts[(sli, wname)] = _Alert()
            edge = alert.observe(burn, threshold, policy.up_ticks,
                                 policy.down_ticks)
            gauge['alert_' + wname] = int(alert.active)
            if edge is not None:
                transitions.append((tenant, kind, sli, wname, edge, burn))
            elif not alert.active and not alert.above:
                # no fire streak brewing (an inactive alert's `below`
                # counter drives nothing): drop the object so the
                # healthy fast path above re-engages
                del pair.alerts[(sli, wname)]

    def _transition(self, tenant, kind, sli, window, edge, burn):
        pair = self._pairs[(tenant, kind)]
        if edge == 'fire':
            _stats.inc('slo_alerts_fired')
            _stats.inc('slo_alerts_active')
            # a firing pair joins the per-tick evaluation set: its clear
            # hysteresis must decay even if the tenant goes silent
            self._alerting.add((tenant, kind))
        else:
            _stats.inc('slo_alerts_cleared')
            _stats.inc('slo_alerts_active', -1)
            if not any(a.active for a in pair.alerts.values()):
                self._alerting.discard((tenant, kind))
        self.alert_log.append((self.ticks, tenant, kind, sli, window,
                               edge, round(burn, 3)))
        _flight.record_event('slo_alert', tenant=tenant,
                             request_kind=kind, sli=sli, window=window,
                             edge=edge, burn=round(burn, 3),
                             tick=self.ticks)
        if edge == 'fire':
            # the forensic dump an on-call reads first: which tenant,
            # which objective, and what its last requests looked like
            _flight.dump_flight_record('slo', detail={
                'alert': {'tenant': tenant, 'kind': kind, 'sli': sli,
                          'window': window, 'burn': round(burn, 3),
                          'tick': self.ticks},
                'recent_requests': [
                    {'tick': t, 'kind': k, 'outcome': c,
                     'latency_ms': us / 1e3,
                     **({'trace': tr} if tr is not None else {})}
                    for t, k, c, us, tr in
                    self._forensics.get(tenant, ())],
            })

    # -- read surfaces ---------------------------------------------------

    @staticmethod
    def _copy(d, deep=False):
        """Snapshot a dict that a concurrent writer may be growing: a
        plain dict() copy with a bounded retry on the (rare) resize
        race. The VALUES are ints/tuples or dicts copied one level —
        enough for torn-free exposition."""
        for _ in range(8):
            try:
                if deep:
                    return {k: dict(v) for k, v in d.items()}
                return dict(d)
            except RuntimeError:
                continue
        return {}

    def tallies(self):
        """{(tenant, kind): {outcome class: count}} — the monotonic
        request-outcome tallies (the loadgen audit's server side). The
        inner dicts take the same retry-guarded copy as the outer map:
        a tick thread inserting a pair's FIRST outcome of a new class
        resizes that inner dict too."""
        return {key: self._copy(pair.tallies)
                for key, pair in self._copy(self._pairs).items()}

    def gauges(self):
        """{(tenant, kind, sli): {'fast_burn', 'slow_burn',
        'alert_fast', 'alert_slow'}} as of the last tick()."""
        return self._copy(self._gauges, deep=True)

    def lag_gauges(self):
        """{(tenant, kind): worst cursor lag seen} for pairs that
        recorded freshness."""
        return {key: pair.lag_max
                for key, pair in self._copy(self._pairs).items()
                if pair.lag_max}

    def histograms(self):
        """{(tenant, kind): Histogram} of committed-request latency —
        what the Prometheus exposition renders as per-tenant series."""
        return {key: pair.hist
                for key, pair in self._copy(self._pairs).items()
                if pair.hist is not None}

    def active_alerts(self):
        """[(tenant, kind, sli, window)] currently firing."""
        out = []
        for (tenant, kind), pair in self._copy(self._pairs).items():
            for (sli, wname), alert in self._copy(pair.alerts).items():
                if alert.active:
                    out.append((tenant, kind, sli, wname))
        return out
