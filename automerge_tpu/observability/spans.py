"""Host-phase spans: a near-zero-overhead-when-off begin/end recorder.

``span(name, **attrs)`` is the one call sites use. When tracing is OFF
(the default) it returns a shared no-op context manager — the entire cost
of an instrumented seam is one module-flag check and two empty method
calls, which is why the hot paths (turbo apply, journal commit, Bloom
build) can stay instrumented permanently instead of behind copy-pasted
``if`` guards. When ON (``enable()``), every span close records
``(name, t0_ns, t1_ns, thread, attrs, error)`` into a bounded ring — old
spans fall off the end, so a long-running fleet never grows memory.

``span_seq()`` is the shape the multi-phase seams use (turbo apply,
recovery): ``mark(name)`` closes the previous phase and opens the next at
the SAME timestamp, so consecutive phases tile an interval with no
unattributed gap — that contiguity is what lets bench.py's observability
section prove the emitted trace accounts for >= 90% of a seam batch's
wall-clock.

Spans stay in THIS ring only; the flight recorder reads the ring's tail
at dump time (recorder.dump_flight_record) rather than mirroring every
close into its own event ring — a traced run would otherwise flood the
small fault-event ring with span closes and evict exactly the
quarantine/rot events a forensic dump exists to preserve.

``export_chrome_trace(path)`` writes the ring as Chrome trace-event JSON
("X" complete events, microsecond timestamps), the format Perfetto and
chrome://tracing load directly — drop it next to a ``jax.profiler.trace``
capture and the host phases line up beside the device timeline.
"""

import json
import threading
import time

from .metrics import register_health_source

__all__ = ['enable', 'disable', 'on', 'span', 'span_seq', 'spanned',
           'clear', 'iter_spans', 'export_chrome_trace', 'Span',
           'record_span', 'spans_dropped']

_on = False                 # the master switch; module-global for one-load checks
_ring = []                  # preallocated record slots (None until written)
_cap = 0
_idx = 0                    # next write position
_total = 0                  # lifetime spans recorded (wraparound-aware)
_dropped_lifetime = 0       # spans evicted by wraparound, never reset
_lock = threading.Lock()    # guards ring writes only; reads copy under it

# a wrapped ring silently truncating a trace is the no-silent-caps rule's
# textbook violation: the health counter makes the loss countable, and
# export_chrome_trace emits a synthetic marker event so the Perfetto view
# itself discloses that older spans fell off
register_health_source('spans_dropped', lambda: _dropped_lifetime)


def on():
    """True when span recording is enabled (the fast-path guard)."""
    return _on


def enable(capacity=4096):
    """Turn span recording on with a bounded ring of `capacity` spans."""
    global _on, _ring, _cap, _idx, _total
    with _lock:
        _ring = [None] * int(capacity)
        _cap = int(capacity)
        _idx = 0
        _total = 0
        _on = True


def disable():
    """Turn span recording off. The ring is kept until enable() resets it
    so a forensic dump can still read the tail of a disabled trace."""
    global _on
    _on = False


def clear():
    """Drop every recorded span (keeps the enabled state and capacity)."""
    global _idx, _total
    with _lock:
        for i in range(_cap):
            _ring[i] = None
        _idx = 0
        _total = 0


def _record(name, t0, t1, attrs, error, tid=None):
    global _idx, _total, _dropped_lifetime
    rec = (name, t0, t1,
           threading.get_ident() if tid is None else tid, attrs, error)
    with _lock:
        if not _cap:
            return
        if _ring[_idx] is not None:
            _dropped_lifetime += 1
        _ring[_idx] = rec
        _idx = (_idx + 1) % _cap
        _total += 1


def record_span(name, t0_ns, t1_ns, tid=None, **attrs):
    """Inject an externally-timed span into the ring. For phases measured
    outside Python — the native codec's pool workers time their parse
    slices against CLOCK_MONOTONIC, the same epoch ``perf_counter_ns``
    reads on Linux, so injected slices line up with host-phase spans in
    one Perfetto timeline. ``tid`` (default: calling thread) lets each
    worker render as its own track."""
    if not _on:
        return
    _record(name, t0_ns, t1_ns, attrs or None, None, tid=tid)


class Span:
    """A live span: records on close (including exceptional close, with
    the exception type attached as the ``error`` field — every begin has
    an end even when the guarded block raises)."""

    __slots__ = ('_name', '_t0', '_attrs')

    def __init__(self, name, attrs):
        self._name = name
        self._attrs = attrs or None
        self._t0 = 0

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def set(self, **attrs):
        """Attach attributes discovered mid-span."""
        if self._attrs is None:
            self._attrs = {}
        self._attrs.update(attrs)
        return self

    def __exit__(self, exc_type, exc, tb):
        _record(self._name, self._t0, time.perf_counter_ns(), self._attrs,
                exc_type.__name__ if exc_type is not None else None)
        return False


class _NullSpan:
    """Shared do-nothing span returned while recording is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **attrs):
        return self


class SpanSeq:
    """Sequential phase spans: each mark() closes the running phase and
    opens the next at the same instant, so the phases tile the interval."""

    __slots__ = ('_name', '_t0', '_attrs')

    def __init__(self):
        self._name = None
        self._t0 = 0
        self._attrs = None

    def mark(self, name, **attrs):
        t = time.perf_counter_ns()
        if self._name is not None:
            _record(self._name, self._t0, t, self._attrs, None)
        self._name = name
        self._t0 = t
        self._attrs = attrs or None

    def done(self, error=None, **attrs):
        if self._name is None:
            return
        if attrs:
            if self._attrs is None:
                self._attrs = {}
            self._attrs.update(attrs)
        _record(self._name, self._t0, time.perf_counter_ns(), self._attrs,
                error)
        self._name = None
        self._attrs = None


class _NullSeq:
    __slots__ = ()

    def mark(self, name, **attrs):
        pass

    def done(self, error=None, **attrs):
        pass


_NULL = _NullSpan()
_NULL_SEQ = _NullSeq()


def span(name, **attrs):
    """Open a span. Off: returns the shared no-op context manager. On:
    returns a recording Span — use as ``with span('native_parse', n=5):``."""
    if not _on:
        return _NULL
    return Span(name, attrs)


def span_seq():
    """A sequential-phase recorder (see SpanSeq); no-op when off."""
    if not _on:
        return _NULL_SEQ
    return SpanSeq()


def spanned(name):
    """Decorator recording the whole call as one span. For per-batch
    seams only: the off cost is one flag check + two no-op calls per
    invocation, fine per batch, too much per op."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(name):
                return fn(*args, **kwargs)
        return wrapper
    return deco


def iter_spans():
    """Recorded spans, oldest first, as dicts. Copies the ring under the
    lock, so it is safe against concurrent recording."""
    with _lock:
        if _total >= _cap:
            raw = _ring[_idx:] + _ring[:_idx]
        else:
            raw = _ring[:_idx]
    out = []
    for rec in raw:
        if rec is None:
            continue
        name, t0, t1, tid, attrs, error = rec
        d = {'name': name, 't0_ns': t0, 't1_ns': t1,
             'dur_ns': t1 - t0, 'tid': tid}
        if attrs:
            d['attrs'] = dict(attrs)
        if error:
            d['error'] = error
        out.append(d)
    return out


def span_count():
    """Lifetime spans recorded since enable()/clear() (past wraparound)."""
    return _total


def spans_dropped():
    """Spans evicted from the CURRENT ring by wraparound — the count of
    older spans an export of this ring is missing (0 = the ring holds
    the full trace). The 'spans_dropped' health counter is the lifetime
    total across enable()/clear() cycles."""
    return max(0, _total - _cap) if _cap else 0


def export_chrome_trace(path=None, pid=1):
    """The recorded spans as Chrome trace-event 'X' (complete) events —
    the JSON Perfetto / chrome://tracing load. Timestamps are the raw
    perf_counter microseconds; host spans from one process share a clock,
    so phases nest correctly. Returns the event list; writes
    ``{"traceEvents": [...]}`` to `path` when given."""
    events = []
    for rec in iter_spans():
        ev = {'ph': 'X', 'name': rec['name'], 'pid': pid,
              'tid': rec['tid'] % 1_000_000,
              'ts': rec['t0_ns'] / 1000.0,
              'dur': rec['dur_ns'] / 1000.0}
        args = dict(rec.get('attrs') or {})
        if rec.get('error'):
            args['error'] = rec['error']
        if args:
            ev['args'] = args
        events.append(ev)
    dropped = spans_dropped()
    if dropped and events:
        # truncation disclosure (no-silent-caps): a wrapped ring means
        # this trace is a TAIL, not the run — say so inside the trace
        # itself, as an instant event at the surviving window's start
        events.insert(0, {
            'ph': 'I', 'name': 'spans_dropped', 'pid': pid, 'tid': 0,
            's': 'g', 'ts': events[0]['ts'],
            'args': {'dropped': dropped,
                     'note': 'span ring wrapped; this trace is the '
                             f'newest window only ({dropped} older '
                             'spans lost)'}})
    if path is not None:
        with open(path, 'w') as f:
            json.dump({'traceEvents': events,
                       'displayTimeUnit': 'ms'}, f, default=repr)
    return events
