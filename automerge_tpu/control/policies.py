"""Hysteretic control policies: signal windows in, declarative actions out.

Each policy is a pure decision function over one SignalBus sample plus
its own hysteresis state — the same ``_Alert`` edge machinery the SLO
burn alerts and the brownout ladder run on (threshold to arm, half the
threshold to disarm, N consecutive windows either way), so a noisy
signal hovering at a boundary cannot flap an actuator. Policies never
touch the system: they return plain action dicts and the controller's
actuator layer routes them through existing seams (admission bucket
rates, ``ClockDemote`` pin lane, ``ShardRouter`` migration machinery).
That split is what makes shadow mode exact — the decision path is
byte-for-byte the active path, minus the final apply.

Every action carries a ``direction`` ('up'/'down', or 'src->dst' for a
move) so the controller can count REVERSALS — the anti-oscillation
number the chaos leg pins (<= 2 per policy per episode).
"""

from ..observability.slo import _Alert

__all__ = ['AdmissionRatePolicy', 'PinResidentPolicy',
           'ShardBalancePolicy']


class AdmissionRatePolicy:
    """Adapt per-tenant token-bucket rates from observed pushback.

    Raise lane: a tenant whose throttled fraction (typed
    ``TenantThrottled`` rejections over its admission attempts) holds
    above ``throttle_frac`` for ``up_windows`` consecutive windows —
    while the service has headroom (queue pressure under ``queue_low``,
    no overload rejections) — gets its bucket rate raised by
    ``raise_factor``, capped at ``max_mult`` x the service base rate.

    Cut lane: sustained overload (global ``Overloaded`` rejections or
    queue pressure over ``queue_high``) walks every boosted tenant back
    toward the base rate by ``cut_factor`` per window. Boosts never go
    below base — the base rate is the operator's floor, and a policy
    that can starve a quiet tenant is a worse outage than the one it
    heals.
    """

    name = 'admission_rate'

    def __init__(self, *, throttle_frac=0.15, raise_factor=1.5,
                 cut_factor=0.5, max_mult=4.0, queue_low=0.3,
                 queue_high=0.7, up_windows=2, down_windows=2,
                 max_actions=4):
        self.throttle_frac = float(throttle_frac)
        self.raise_factor = float(raise_factor)
        self.cut_factor = float(cut_factor)
        self.max_mult = float(max_mult)
        self.queue_low = float(queue_low)
        self.queue_high = float(queue_high)
        self.up_windows = int(up_windows)
        self.down_windows = int(down_windows)
        self.max_actions = int(max_actions)
        self._raise = {}             # tenant -> _Alert
        self._overload = _Alert()
        self.mult = {}               # tenant -> applied rate multiplier

    def decide(self, sig):
        adm = sig['admission']
        out = []
        overloaded = adm['overloaded_d'] > 0 or \
            adm['queue_pressure'] >= self.queue_high
        self._overload.observe(1.0 if overloaded else 0.0, 1.0,
                               self.up_windows, self.down_windows)
        if self._overload.active:
            # walk every boost back toward base while overload persists
            for tenant in sorted(self.mult):
                mult = self.mult[tenant]
                info = sig['tenants'].get(tenant)
                if info is None or mult <= 1.0:
                    continue
                new = max(1.0, mult * self.cut_factor)
                self.mult[tenant] = new
                if new <= 1.0:
                    del self.mult[tenant]
                out.append({
                    'policy': self.name, 'action': 'set_rate',
                    'direction': 'down', 'tenant': tenant,
                    'target': f'tenant:{tenant}',
                    'rate': info['base_rate'] * new, 'mult': new,
                    'detail': {'queue_pressure': adm['queue_pressure'],
                               'overloaded_d': adm['overloaded_d']}})
            return out
        candidates = []
        for tenant, info in sig['tenants'].items():
            seen = info['admitted_d'] + info['throttled_d']
            frac = info['throttled_d'] / seen if seen else 0.0
            alert = self._raise.get(tenant)
            if alert is None:
                if frac < self.throttle_frac:
                    continue
                alert = self._raise[tenant] = _Alert()
            alert.observe(frac, self.throttle_frac, self.up_windows,
                          self.down_windows)
            if not alert.active and not alert.above:
                del self._raise[tenant]
                continue
            if alert.active and adm['queue_pressure'] < self.queue_low:
                mult = self.mult.get(tenant, 1.0)
                if mult < self.max_mult:
                    candidates.append((frac, tenant, info, mult))
        for frac, tenant, info, mult in sorted(candidates,
                                               reverse=True)[
                                                   :self.max_actions]:
            new = min(self.max_mult, mult * self.raise_factor)
            self.mult[tenant] = new
            out.append({
                'policy': self.name, 'action': 'set_rate',
                'direction': 'up', 'tenant': tenant,
                'target': f'tenant:{tenant}',
                'rate': info['base_rate'] * new, 'mult': new,
                'detail': {'throttled_frac': round(frac, 4),
                           'queue_pressure': adm['queue_pressure']}})
        return out

    def active(self):
        return {f'tenant:{t}': round(m, 3)
                for t, m in self.mult.items() if m > 1.0}


class PinResidentPolicy:
    """Pin an SLO-freshness-lagging tenant's docs resident.

    A tenant burning its freshness budget (fast burn >= ``burn``, or
    its freshness alert already firing) for ``up_windows`` windows gets
    its docs PINNED in the demote clock — the tiering plane stops
    parking exactly the docs whose staleness is burning budget. The pin
    lifts on the hysteretic clear (burn <= half threshold for
    ``down_windows`` windows).

    Watermark lane: sustained clock pressure above ``wm_high`` tightens
    the demote budget (``pressure_factor`` -> ``factor_low``) so the
    UNPINNED population demotes harder — the memory the pins hold
    resident has to come from somewhere; the factor relaxes to 1.0 on
    clear.
    """

    name = 'pin_resident'

    def __init__(self, *, burn=1.0, up_windows=2, down_windows=2,
                 wm_high=1.2, factor_low=0.75):
        self.burn = float(burn)
        self.up_windows = int(up_windows)
        self.down_windows = int(down_windows)
        self.wm_high = float(wm_high)
        self.factor_low = float(factor_low)
        self._alerts = {}            # tenant -> _Alert
        self._wm = _Alert()
        self.pinned = set()

    def decide(self, sig):
        out = []
        for tenant, info in sig['tenants'].items():
            burn = max(info['fresh_burn'],
                       self.burn if info['fresh_alert'] else 0.0)
            alert = self._alerts.get(tenant)
            if alert is None:
                if burn < self.burn and tenant not in self.pinned:
                    continue
                alert = self._alerts[tenant] = _Alert()
            edge = alert.observe(burn, self.burn, self.up_windows,
                                 self.down_windows)
            if edge == 'fire' and tenant not in self.pinned:
                self.pinned.add(tenant)
                out.append({
                    'policy': self.name, 'action': 'pin',
                    'direction': 'up', 'tenant': tenant,
                    'target': f'tenant:{tenant}',
                    'detail': {'fresh_burn': round(burn, 4),
                               'lag': info['lag']}})
            elif edge == 'clear' and tenant in self.pinned:
                self.pinned.discard(tenant)
                del self._alerts[tenant]
                out.append({
                    'policy': self.name, 'action': 'unpin',
                    'direction': 'down', 'tenant': tenant,
                    'target': f'tenant:{tenant}',
                    'detail': {'fresh_burn': round(burn, 4)}})
            elif not alert.active and not alert.above and \
                    tenant not in self.pinned:
                del self._alerts[tenant]
        pressure = sig['watermark']['pressure']
        if pressure is not None:
            edge = self._wm.observe(pressure, self.wm_high,
                                    self.up_windows, self.down_windows)
            if edge == 'fire':
                out.append({
                    'policy': self.name, 'action': 'pressure_factor',
                    'direction': 'down', 'target': 'demote_clock',
                    'value': self.factor_low,
                    'detail': {'pressure': round(pressure, 4)}})
            elif edge == 'clear':
                out.append({
                    'policy': self.name, 'action': 'pressure_factor',
                    'direction': 'up', 'target': 'demote_clock',
                    'value': 1.0,
                    'detail': {'pressure': round(pressure, 4)}})
        return out

    def active(self):
        out = {f'tenant:{t}': 1 for t in self.pinned}
        if self._wm.active:
            out['demote_clock'] = 1
        return out


class ShardBalancePolicy:
    """Placement healing + hot-shard relief through the migration seam.

    Heal lane: tenants whose live ring-primary differs from their home
    (the post-failover/revive displacement) sustained for
    ``up_windows`` windows are re-homed BACK to their ring primary, up
    to ``heal_per_window`` per window — the controller-driven
    replacement for loadgen's hardcoded rebalance-after-revive call.

    Relief lane: a live shard whose pump-seconds EWMA holds at
    ``hot_ratio`` x the live-shard mean moves ONE tenant per window to
    the coolest live shard. Tenants the relief lane moved are owned by
    the controller — the heal lane stops counting them as misplaced, so
    the two lanes cannot tug one tenant in a loop.
    """

    name = 'shard_balance'

    def __init__(self, *, hot_ratio=2.0, up_windows=3, down_windows=2,
                 heal_up_windows=2, heal_per_window=4,
                 min_pump_s=0.0005):
        self.hot_ratio = float(hot_ratio)
        self.up_windows = int(up_windows)
        self.down_windows = int(down_windows)
        self.heal_up_windows = int(heal_up_windows)
        self.heal_per_window = int(heal_per_window)
        self.min_pump_s = float(min_pump_s)
        self._heal = _Alert()
        self._hot = {}               # shard id -> _Alert
        self.owned = set()           # tenants the relief lane placed

    def decide(self, sig):
        out = []
        shards = sig.get('shards')
        if not shards:
            return out
        misplaced = [t for t in sig.get('misplaced', ())
                     if t not in self.owned]
        self._heal.observe(1.0 if misplaced else 0.0, 1.0,
                           self.heal_up_windows, 1)
        if self._heal.active and misplaced:
            for tenant in misplaced[:self.heal_per_window]:
                out.append({
                    'policy': self.name, 'action': 'rehome',
                    'direction': 'heal', 'tenant': tenant,
                    'dst': None,     # resolved to the ring primary
                    'target': f'tenant:{tenant}',
                    'detail': {'misplaced': len(misplaced)}})
        live = {sid: s for sid, s in shards.items() if s['alive']}
        mean = sig.get('pump_mean_s', 0.0)
        if len(live) < 2 or mean < self.min_pump_s:
            return out
        moved = False
        for sid in sorted(live, key=lambda s: -live[s]['pump_ewma_s']):
            ratio = live[sid]['pump_ewma_s'] / mean if mean else 0.0
            alert = self._hot.get(sid)
            if alert is None:
                if ratio < self.hot_ratio:
                    continue
                alert = self._hot[sid] = _Alert()
            alert.observe(ratio, self.hot_ratio, self.up_windows,
                          self.down_windows)
            if not alert.active:
                if not alert.above:
                    del self._hot[sid]
                continue
            if moved or live[sid]['tenants'] <= 1:
                continue
            tenants = sig.get('shard_tenants', {}).get(sid, ())
            coolest = min(live, key=lambda s: live[s]['pump_ewma_s'])
            if not tenants or coolest == sid:
                continue
            tenant = tenants[0]
            self.owned.add(tenant)
            moved = True
            out.append({
                'policy': self.name, 'action': 'rehome',
                'direction': f'{sid}->{coolest}', 'tenant': tenant,
                'dst': coolest, 'target': f'tenant:{tenant}',
                'detail': {'pump_ratio': round(ratio, 3),
                           'pump_mean_s': round(mean, 6)}})
        return out

    def active(self):
        out = {f'shard:{sid}': 1 for sid, a in self._hot.items()
               if a.active}
        if self._heal.active:
            out['heal'] = 1
        return out
