"""Self-driving control plane: the observatory closes the loop.

The rest of the stack EMITS — watermarks, burn rates, drift baselines,
pump seconds, reject fractions, tiering verdicts. This package CONSUMES
them: a tick-driven feedback controller riding the existing pumps,
whose every decision (including shadow-mode would-have-acted entries)
is itself a first-class observability record — flight-recorded with the
signal snapshot that justified it, exported as ``automerge_tpu_control_*``
Prometheus series, and rendered by ``obs_report --control`` as a
why-did-it-act timeline. See BASELINE.md "Control plane contract".
"""

from .controller import Controller, control_stats
from .policies import (AdmissionRatePolicy, PinResidentPolicy,
                       ShardBalancePolicy)
from .signals import SignalBus

__all__ = ['Controller', 'SignalBus', 'AdmissionRatePolicy',
           'PinResidentPolicy', 'ShardBalancePolicy', 'control_stats']
