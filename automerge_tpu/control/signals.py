"""SignalBus: one incremental sample of the whole observatory.

The controller (control/controller.py) decides on WINDOWS, not ticks,
and every window it needs the same cross-cutting read the dashboards
get — but as plain deltas, not rendered pages. The bus keeps the
previous window's monotonic counters and hands back per-window
movement, mirroring the ``_adm_counts`` idiom the service pump already
uses for its brownout inputs: snapshot the counters, diff against the
stash, never rescan history.

What a sample carries (every field plain data, JSON-friendly):

- ``admission``: admitted/throttled/overloaded deltas since the last
  sample, the rejected fraction, and the queued-backlog pressure —
  summed across every live service (one service standalone, one per
  live shard under a router).
- ``tenants``: per-tenant admitted/throttled deltas and the current
  token-bucket rate beside the service's base rate, plus the tenant's
  SLO reads (max availability-throttled and freshness fast-burn across
  request kinds, freshness alert state, worst cursor lag).
- ``shards`` (router mode): per-shard liveness, last pump seconds and
  its EWMA, slipped-tick delta, and homed-tenant count; ``pump_mean_s``
  is the mean EWMA across live shards.
- ``misplaced`` (router mode): tenants whose live ring-primary differs
  from their current home and who are not already migrating — the
  post-revive healing signal.
- ``perf``: max drift ratio and active-alert count from the seam
  baselines (empty observatory reads as 0/0).
- ``watermark``: the demote clock's budget pressure when a ``ClockDemote``
  is attached (None otherwise).
- ``tiering``: fire/defer verdict counts from the cost-model ledger.

The bus holds no locks: it runs on the pump thread (single writer) and
reads the same retry-guarded snapshot surfaces the Prometheus exporter
uses (``SloRegistry.gauges`` et al are torn-read-proof by contract).
"""

__all__ = ['SignalBus']


class SignalBus:
    """See the module docstring. Attach exactly one of ``service`` /
    ``router`` (a router implies its shards' services)."""

    def __init__(self, service=None, router=None, tiering=None,
                 demote=None, pump_alpha=0.3):
        self.service = service
        self.router = router
        self.tiering = tiering if tiering is not None else \
            getattr(service, 'tiering', None)
        self.demote = demote if demote is not None else \
            getattr(self.tiering, 'demote', None)
        self.pump_alpha = float(pump_alpha)
        self._prev_tenant = {}       # tenant -> (admitted, throttled)
        self._prev_adm = (0, 0, 0)   # summed (admitted, ovl, thr)
        self._prev_slips = {}        # shard id -> ticks_slipped
        self._pump_ewma = {}         # shard id -> EWMA pump seconds

    def services(self):
        """[(shard_id_or_None, DocService)] for every live service."""
        if self.router is not None:
            return [(sid, shard.service)
                    for sid, shard in self.router.shards.items()
                    if shard.alive]
        if self.service is not None:
            return [(None, self.service)]
        return []

    # -- the sample ------------------------------------------------------

    def sample(self, tick):
        services = self.services()
        sig = {'tick': tick}
        sig['admission'] = self._sample_admission(services)
        sig['tenants'] = self._sample_tenants(services)
        sig['perf'] = self._sample_perf()
        sig['watermark'] = {
            'pressure': None if self.demote is None
            else float(self.demote.pressure())}
        sig['tiering'] = self._sample_tiering()
        if self.router is not None:
            self._sample_router(sig)
        return sig

    def _sample_admission(self, services):
        admitted = overloaded = throttled = 0
        queued = capacity = 0
        for _sid, svc in services:
            adm = svc.admission
            stats = adm.stats
            admitted += stats['admitted']
            overloaded += stats['rejected_overloaded']
            throttled += stats['rejected_throttled']
            queued += adm.queued
            capacity += adm.max_queued
        counts = (admitted, overloaded, throttled)
        prev = self._prev_adm
        self._prev_adm = counts
        # deltas clamp at 0: a dead shard takes its monotonic counters
        # out of the sum, which must read as "no events", not negative
        admitted_d = max(0, counts[0] - prev[0])
        overloaded_d = max(0, counts[1] - prev[1])
        throttled_d = max(0, counts[2] - prev[2])
        rejected_d = overloaded_d + throttled_d
        seen = admitted_d + rejected_d
        return {'admitted_d': admitted_d, 'overloaded_d': overloaded_d,
                'throttled_d': throttled_d,
                'reject_frac': rejected_d / seen if seen else 0.0,
                'queue_pressure': min(1.0, queued / capacity)
                if capacity else 0.0}

    def _sample_tenants(self, services):
        # monotonic per-tenant counters summed across services (a
        # rehomed tenant's book may briefly exist on two admission
        # controllers; the sum stays monotonic while both live)
        counts = {}
        rates = {}
        base_rate = None
        for _sid, svc in services:
            adm = svc.admission
            if base_rate is None:
                base_rate = adm.rate
            for name, t in list(adm.tenants.items()):
                a, th = counts.get(name, (0, 0))
                counts[name] = (a + t.admitted, th + t.throttled)
                rates[name] = t.bucket.rate
        gauges, lags = self._slo_reads(services)
        out = {}
        for name, (admitted, throttled) in counts.items():
            pa, pt = self._prev_tenant.get(name, (0, 0))
            g = gauges.get(name, {})
            out[name] = {
                'admitted_d': max(0, admitted - pa),
                'throttled_d': max(0, throttled - pt),
                'rate': rates.get(name, base_rate or 0.0),
                'base_rate': base_rate or 0.0,
                'throttled_burn': g.get('throttled_burn', 0.0),
                'fresh_burn': g.get('fresh_burn', 0.0),
                'fresh_alert': g.get('fresh_alert', 0),
                'lag': lags.get(name, 0),
            }
            self._prev_tenant[name] = (admitted, throttled)
        return out

    @staticmethod
    def _slo_reads(services):
        """Per-tenant max burn reads folded across kinds and services."""
        gauges = {}
        lags = {}
        for _sid, svc in services:
            slo = getattr(svc, 'slo', None)
            if not slo:
                continue
            for (tenant, _kind, sli), gauge in slo.gauges().items():
                g = gauges.setdefault(tenant, {})
                fast = gauge.get('fast_burn', 0.0)
                if sli == 'avail_throttled':
                    g['throttled_burn'] = max(
                        g.get('throttled_burn', 0.0), fast)
                elif sli == 'freshness':
                    g['fresh_burn'] = max(g.get('fresh_burn', 0.0), fast)
                    g['fresh_alert'] = max(
                        g.get('fresh_alert', 0),
                        gauge.get('alert_fast', 0),
                        gauge.get('alert_slow', 0))
            for (tenant, _kind), lag in slo.lag_gauges().items():
                lags[tenant] = max(lags.get(tenant, 0), lag)
        return gauges, lags

    def _sample_perf(self):
        from ..observability.perf import baseline_gauges
        max_drift = 0.0
        alerts = 0
        for gauge in baseline_gauges().values():
            max_drift = max(max_drift, float(gauge.get('drift') or 0.0))
            alerts += int(bool(gauge.get('alert')))
        return {'max_drift': max_drift, 'alerts': alerts}

    def _sample_tiering(self):
        model = getattr(self.tiering, 'model', None)
        if model is None:
            return {'fire': 0, 'defer': 0}
        verdicts = list(model._verdicts.values())
        return {'fire': verdicts.count('fire'),
                'defer': verdicts.count('defer')}

    def _sample_router(self, sig):
        router = self.router
        alpha = self.pump_alpha
        homed = {}
        misplaced = []
        shard_tenants = {}
        for rec in router._tenants.values():
            if rec.home is None:
                continue
            homed[rec.home] = homed.get(rec.home, 0) + 1
            shard_tenants.setdefault(rec.home, []).append(rec.name)
            if rec.migrating is None:
                want = router.ring.primary(rec.name, alive=router.alive)
                if want is not None and want != rec.home:
                    misplaced.append(rec.name)
        shards = {}
        ewma_sum = 0.0
        live = 0
        for sid, shard in router.shards.items():
            prev = self._pump_ewma.get(sid, shard.last_pump_s)
            ewma = prev + alpha * (shard.last_pump_s - prev)
            self._pump_ewma[sid] = ewma
            slipped_prev = self._prev_slips.get(sid, 0)
            self._prev_slips[sid] = shard.ticks_slipped
            shards[sid] = {
                'alive': shard.alive and sid in router.alive,
                'last_pump_s': shard.last_pump_s,
                'pump_ewma_s': ewma,
                'slipped_d': max(0, shard.ticks_slipped - slipped_prev),
                'tenants': homed.get(sid, 0),
            }
            if shards[sid]['alive']:
                ewma_sum += ewma
                live += 1
        sig['shards'] = shards
        sig['shard_tenants'] = shard_tenants
        sig['pump_mean_s'] = ewma_sum / live if live else 0.0
        sig['misplaced'] = sorted(misplaced)
        sig['migrating'] = len(router.migrating())
