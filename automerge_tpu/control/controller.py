"""The feedback controller: sample -> decide -> actuate -> ledger.

``Controller.tick()`` rides the owning pump (``DocService.pump`` or
``ShardRouter.pump`` calls it once per tick when attached); off-window
ticks cost one integer increment and a modulo. Every ``window`` ticks
it takes one SignalBus sample, runs each policy over it, and commits
the resulting decisions:

- **actuate** (mode='active'): route the action through an existing
  seam — ``AdmissionController.set_tenant_rate``, the ``ClockDemote``
  pin lane / ``pressure_factor``, ``ShardRouter.rehome_tenant`` (the
  same migration machinery ``rebalance`` uses). Mode='shadow' runs the
  IDENTICAL decision path and records "would have acted" without
  touching anything — the parity the bench section pins.
- **ledger**: every decision (applied or shadow) lands in the bounded
  in-memory decision ledger AND the flight recorder, stamped with the
  input signal snapshot that justified it and the trace ids of affected
  in-flight requests, so ``obs_report --control`` can answer
  why-did-it-act from a dump alone.
- **reversals**: an up following a down (or a move undoing the previous
  move) on the same (policy, target) counts a reversal — the
  anti-oscillation number the chaos leg bounds.

Snapshot contract: ``gauges()`` returns plain copies taken under the
controller lock; the pump thread mutates the same state under that
lock, so a concurrent Prometheus scrape can never see a torn map
(pinned by the hammer test in tests/test_export.py).
"""

import collections
import json
import threading
import time

from ..observability import recorder as _flight
from ..observability.metrics import Counters, register_health_source
from .policies import (AdmissionRatePolicy, PinResidentPolicy,
                       ShardBalancePolicy)
from .signals import SignalBus

__all__ = ['Controller']

_stats = Counters({
    'control_windows': 0,        # decision windows evaluated
    'control_decisions': 0,      # decisions committed (both modes)
    'control_actuations': 0,     # decisions actually applied
    'control_shadow_decisions': 0,   # would-have-acted entries
    'control_reversals': 0,      # direction flips per (policy, target)
    'control_apply_failures': 0,     # actuations the seam refused
})
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])


def control_stats():
    return dict(_stats)


class Controller:
    """See the module docstring. Construct first, then hand it to the
    pump owner (``DocService(control=...)`` / ``ShardRouter(control=
    ...)``), which binds itself via ``attach``."""

    def __init__(self, *, mode='active', window=10, policies=None,
                 service=None, router=None, tiering=None, demote=None,
                 ledger_cap=512, trace_cap=8):
        if mode not in ('active', 'shadow'):
            raise ValueError(f"mode must be 'active' or 'shadow', "
                             f'got {mode!r}')
        self.mode = mode
        self.window = max(1, int(window))
        self.policies = list(policies) if policies is not None else [
            AdmissionRatePolicy(), PinResidentPolicy(),
            ShardBalancePolicy()]
        self.service = service
        self.router = router
        self.tiering = tiering
        self.demote = demote
        self.trace_cap = int(trace_cap)
        self.ledger = collections.deque(maxlen=int(ledger_cap))
        self._lock = threading.Lock()
        self._ticks = 0
        self._windows = 0
        self._decisions = {}         # (policy, action, mode) -> count
        self._reversals = {}         # policy -> count
        self._last_dir = {}          # (policy, target) -> direction
        self._last_decision_tick = None
        self._decide_s_last = 0.0
        self._decide_s_max = 0.0
        self._active = {}            # (policy, target) -> value
        self.bus = None
        self._rebind()

    # -- wiring ----------------------------------------------------------

    def attach(self, service=None, router=None, tiering=None,
               demote=None):
        """Bind the controller to its pump owner (idempotent; the owner
        calls this from its constructor)."""
        if service is not None:
            self.service = service
        if router is not None:
            self.router = router
        if tiering is not None:
            self.tiering = tiering
        if demote is not None:
            self.demote = demote
        self._rebind()
        return self

    def _rebind(self):
        tiering = self.tiering if self.tiering is not None else \
            getattr(self.service, 'tiering', None)
        self.bus = SignalBus(service=self.service, router=self.router,
                             tiering=tiering, demote=self.demote)

    def _demote_clock(self):
        if self.demote is not None:
            return self.demote
        return getattr(self.bus, 'demote', None)

    # -- the tick --------------------------------------------------------

    def tick(self, now=None):
        """One pump tick. Returns the window's decision list when a
        decision window closed, else None."""
        self._ticks += 1
        if self._ticks % self.window:
            return None
        start = time.perf_counter()
        sig = self.bus.sample(self._ticks)
        decisions = []
        for policy in self.policies:
            decisions.extend(policy.decide(sig))
        entries = [self._commit(d, sig) for d in decisions]
        if self.mode == 'active':
            self.reassert_pins()
        elapsed = time.perf_counter() - start
        active = {}
        for policy in self.policies:
            for target, value in policy.active().items():
                active[(policy.name, target)] = value
        with self._lock:
            self._windows += 1
            self._decide_s_last = elapsed
            self._decide_s_max = max(self._decide_s_max, elapsed)
            self._active = active
            if entries:
                self._last_decision_tick = self._ticks
        _stats.inc('control_windows')
        return entries

    def _commit(self, d, sig):
        applied = False
        if self.mode == 'active':
            applied = self._apply(d)
        target = d.get('target', '')
        direction = d.get('direction', '')
        prev = self._last_dir.get((d['policy'], target))
        reversal = _is_reversal(prev, direction)
        self._last_dir[(d['policy'], target)] = direction
        traces = self._traces_for(d.get('tenant'))
        entry = {k: v for k, v in d.items()}
        entry.update(tick=self._ticks, mode=self.mode, applied=applied,
                     reversal=reversal, traces=traces,
                     signals=self._signal_slice(sig, d))
        with self._lock:
            key = (d['policy'], d['action'], self.mode)
            self._decisions[key] = self._decisions.get(key, 0) + 1
            if reversal:
                self._reversals[d['policy']] = \
                    self._reversals.get(d['policy'], 0) + 1
            self.ledger.append(entry)
        _stats.inc('control_decisions')
        if self.mode == 'shadow':
            _stats.inc('control_shadow_decisions')
        elif applied:
            _stats.inc('control_actuations')
        else:
            _stats.inc('control_apply_failures')
        if reversal:
            _stats.inc('control_reversals')
        _flight.record_event('control_decision', policy=d['policy'],
                             action=d['action'], target=target,
                             direction=direction, mode=self.mode,
                             applied=applied, reversal=reversal,
                             tick=self._ticks,
                             signals=entry['signals'], traces=traces,
                             detail=d.get('detail'))
        return entry

    @staticmethod
    def _signal_slice(sig, d):
        """The input snapshot that justified this decision: the global
        planes plus the affected tenant/shard rows — small enough for
        the flight ring, complete enough for a forensic why."""
        out = {'tick': sig['tick'], 'admission': dict(sig['admission']),
               'watermark': dict(sig['watermark']),
               'perf': dict(sig['perf']), 'tiering': dict(sig['tiering'])}
        tenant = d.get('tenant')
        if tenant is not None and tenant in sig['tenants']:
            out['tenant'] = dict(sig['tenants'][tenant])
        if 'shards' in sig:
            out['pump_mean_s'] = sig.get('pump_mean_s', 0.0)
            out['misplaced'] = list(sig.get('misplaced', ()))
        return out

    def _traces_for(self, tenant):
        """Trace ids of in-flight requests the decision touches (the
        affected tenant's queued work; every pending request when the
        decision is tenant-less). Best-effort and bounded."""
        out = []
        if self.router is not None:
            for req in self.router._pending:
                if len(out) >= self.trace_cap:
                    return out
                if tenant is not None and req.tenant != tenant:
                    continue
                sub = req.sub
                trace = getattr(sub, 'trace', None) if sub is not None \
                    else None
                if trace is not None:
                    out.append(trace.trace_id)
        for _sid, svc in self.bus.services():
            if len(out) >= self.trace_cap:
                return out
            for t in list(svc.admission.tenants.values()):
                if tenant is not None and t.name != tenant:
                    continue
                for req in t.queue[:self.trace_cap]:
                    trace = getattr(req.ticket, 'trace', None)
                    if trace is not None:
                        out.append(trace.trace_id)
                    if len(out) >= self.trace_cap:
                        return out
        return out

    # -- actuators (existing seams only) ---------------------------------

    def _apply(self, d):
        action = d['action']
        if action == 'set_rate':
            applied = False
            for _sid, svc in self.bus.services():
                if d['tenant'] in svc.admission.tenants:
                    svc.admission.set_tenant_rate(d['tenant'],
                                                  rate=d['rate'])
                    applied = True
            return applied
        if action in ('pin', 'unpin'):
            demote = self._demote_clock()
            if demote is None:
                return False
            handles = self._tenant_handles(d['tenant'])
            if action == 'pin':
                demote.pin(handles)
                return bool(handles)
            demote.unpin(handles)
            return True
        if action == 'pressure_factor':
            demote = self._demote_clock()
            if demote is None:
                return False
            demote.pressure_factor = float(d['value'])
            return True
        if action == 'rehome':
            if self.router is None:
                return False
            dst = d.get('dst')
            if dst is None:
                dst = self.router.ring.primary(
                    d['tenant'], alive=self.router.alive)
                d['dst'] = dst
            if dst is None:
                return False
            return self.router.rehome_tenant(d['tenant'], dst)
        return False

    def _tenant_handles(self, tenant):
        out = []
        for _sid, svc in self.bus.services():
            out.extend(s.handle for s in list(svc.sessions.values())
                       if s.tenant == tenant and not s.closed)
        return out

    def reassert_pins(self):
        """Re-pin the CURRENT handles of every pinned tenant. The apply
        seam freezes old handle dicts, so a pinned doc's live handle
        churns; the demote clock prunes frozen pins and this re-asserts
        the fresh ones. The pump owner may call it on any cadence; the
        controller also runs it once per decision window."""
        demote = self._demote_clock()
        if demote is None:
            return
        for policy in self.policies:
            for tenant in getattr(policy, 'pinned', ()):
                demote.pin(self._tenant_handles(tenant))

    # -- read surfaces ---------------------------------------------------

    def gauges(self):
        """Plain-data snapshot for export (torn-read-proof: the same
        lock brackets every writer)."""
        with self._lock:
            return {
                'mode': self.mode,
                'window': self.window,
                'ticks': self._ticks,
                'windows': self._windows,
                'decisions': dict(self._decisions),
                'reversals': dict(self._reversals),
                'active': dict(self._active),
                'last_decision_tick': self._last_decision_tick,
                'decide_s_last': self._decide_s_last,
                'decide_s_max': self._decide_s_max,
            }

    def decision_log(self, n=None):
        """The newest `n` ledger entries (all when n is None), oldest
        first, as plain copies."""
        with self._lock:
            entries = list(self.ledger)
        entries = entries if n is None else entries[-n:]
        return [dict(e) for e in entries]

    def dump_decisions(self, path=None):
        """The decision ledger as one JSON-ready report (the
        ``obs_report --control`` input). Written to ``path`` when
        given; always returned."""
        gauges = self.gauges()
        # the in-memory gauges are tuple-keyed for the exporter; JSON
        # wants strings
        gauges['decisions'] = {'/'.join(k): v for k, v
                               in gauges['decisions'].items()}
        gauges['active'] = {f'{p}/{t}': v for (p, t), v
                            in gauges['active'].items()}
        report = {'kind': 'control_ledger', 'mode': self.mode,
                  'window': self.window, 'gauges': gauges,
                  'decisions': self.decision_log()}
        if path is not None:
            with open(path, 'w') as f:
                json.dump(report, f, indent=1, default=repr)
            report['path'] = path
        return report


def _is_reversal(prev, cur):
    """An up after a down (or vice versa), or a move undoing the
    previous move, on the same (policy, target)."""
    if prev is None or prev == cur:
        return False
    if {prev, cur} == {'up', 'down'}:
        return True
    if '->' in prev and '->' in cur:
        ps, _, pd = prev.partition('->')
        cs, _, cd = cur.partition('->')
        return ps == cd and pd == cs
    return False
