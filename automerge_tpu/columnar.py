"""Columnar change/document container format.

Wire-compatible with the reference format (backend/columnar.js): change
chunks (magic bytes 85 6f 4a 83, 4-byte SHA-256 checksum prefix, LEB128
length), column-oriented op storage, SHA-256 change hashing, DEFLATE
compression of large chunks/columns.

Ops cross this layer as plain dicts: {action, obj, key|elemId, insert,
value?, datatype?, pred|succ, child?}, with opIds as 'counter@actor'
strings, matching the reference's JSON op representation.
"""

import hashlib
import struct
import zlib

from .common import parse_op_id, lamport_key
from .errors import MalformedChange, MalformedDocument, as_wire_error
from .encoding import (
    Encoder, Decoder, RLEEncoder, RLEDecoder, DeltaEncoder, DeltaDecoder,
    BooleanEncoder, BooleanDecoder, hex_string_to_bytes, bytes_to_hex_string,
    MAX_SAFE_INTEGER, MIN_SAFE_INTEGER,
)

MAGIC_BYTES = bytes([0x85, 0x6f, 0x4a, 0x83])

CHUNK_TYPE_DOCUMENT = 0
CHUNK_TYPE_CHANGE = 1
CHUNK_TYPE_DEFLATE = 2  # a change chunk, DEFLATE-compressed

DEFLATE_MIN_SIZE = 256

# Least-significant 3 bits of a columnId are its datatype (ref columnar.js:35-38)
COLUMN_TYPE = {
    'GROUP_CARD': 0, 'ACTOR_ID': 1, 'INT_RLE': 2, 'INT_DELTA': 3, 'BOOLEAN': 4,
    'STRING_RLE': 5, 'VALUE_LEN': 6, 'VALUE_RAW': 7,
}
COLUMN_TYPE_DEFLATE = 8  # 4th bit: column is DEFLATE-compressed

# Bottom 4 bits of a VALUE_LEN value are the type tag; upper bits are the
# byte length in the VALUE_RAW column (ref columnar.js:46-49)
VALUE_TYPE = {
    'NULL': 0, 'FALSE': 1, 'TRUE': 2, 'LEB128_UINT': 3, 'LEB128_INT': 4,
    'IEEE754': 5, 'UTF8': 6, 'BYTES': 7, 'COUNTER': 8, 'TIMESTAMP': 9,
    'MIN_UNKNOWN': 10, 'MAX_UNKNOWN': 15,
}

# make* actions at even indexes by design (ref columnar.js:51-52)
ACTIONS = ['makeMap', 'set', 'makeList', 'del', 'makeText', 'inc', 'makeTable', 'link']

OBJECT_TYPE = {'makeMap': 'map', 'makeList': 'list', 'makeText': 'text', 'makeTable': 'table'}

COMMON_COLUMNS = [
    ('objActor',  0 << 4 | COLUMN_TYPE['ACTOR_ID']),
    ('objCtr',    0 << 4 | COLUMN_TYPE['INT_RLE']),
    ('keyActor',  1 << 4 | COLUMN_TYPE['ACTOR_ID']),
    ('keyCtr',    1 << 4 | COLUMN_TYPE['INT_DELTA']),
    ('keyStr',    1 << 4 | COLUMN_TYPE['STRING_RLE']),
    ('idActor',   2 << 4 | COLUMN_TYPE['ACTOR_ID']),
    ('idCtr',     2 << 4 | COLUMN_TYPE['INT_DELTA']),
    ('insert',    3 << 4 | COLUMN_TYPE['BOOLEAN']),
    ('action',    4 << 4 | COLUMN_TYPE['INT_RLE']),
    ('valLen',    5 << 4 | COLUMN_TYPE['VALUE_LEN']),
    ('valRaw',    5 << 4 | COLUMN_TYPE['VALUE_RAW']),
    ('chldActor', 6 << 4 | COLUMN_TYPE['ACTOR_ID']),
    ('chldCtr',   6 << 4 | COLUMN_TYPE['INT_DELTA']),
]

CHANGE_COLUMNS = COMMON_COLUMNS + [
    ('predNum',   7 << 4 | COLUMN_TYPE['GROUP_CARD']),
    ('predActor', 7 << 4 | COLUMN_TYPE['ACTOR_ID']),
    ('predCtr',   7 << 4 | COLUMN_TYPE['INT_DELTA']),
]

DOC_OPS_COLUMNS = COMMON_COLUMNS + [
    ('succNum',   8 << 4 | COLUMN_TYPE['GROUP_CARD']),
    ('succActor', 8 << 4 | COLUMN_TYPE['ACTOR_ID']),
    ('succCtr',   8 << 4 | COLUMN_TYPE['INT_DELTA']),
]

# Column ids valid only inside the document container (the succ group):
# change containers treating them as "unknown" would collide on save
_DOC_RESERVED_COLUMN_IDS = \
    {cid for _, cid in DOC_OPS_COLUMNS} - {cid for _, cid in CHANGE_COLUMNS}

DOCUMENT_COLUMNS = [
    ('actor',     0 << 4 | COLUMN_TYPE['ACTOR_ID']),
    ('seq',       0 << 4 | COLUMN_TYPE['INT_DELTA']),
    ('maxOp',     1 << 4 | COLUMN_TYPE['INT_DELTA']),
    ('time',      2 << 4 | COLUMN_TYPE['INT_DELTA']),
    ('message',   3 << 4 | COLUMN_TYPE['STRING_RLE']),
    ('depsNum',   4 << 4 | COLUMN_TYPE['GROUP_CARD']),
    ('depsIndex', 4 << 4 | COLUMN_TYPE['INT_DELTA']),
    ('extraLen',  5 << 4 | COLUMN_TYPE['VALUE_LEN']),
    ('extraRaw',  5 << 4 | COLUMN_TYPE['VALUE_RAW']),
]


def _deflate_raw(data):
    c = zlib.compressobj(6, zlib.DEFLATED, -15)
    return c.compress(bytes(data)) + c.flush()


def _inflate_raw(data):
    return zlib.decompress(bytes(data), -15)


class ParsedOpId:
    """An opId resolved against an actor table: (counter, actorNum, actorId)."""
    __slots__ = ('counter', 'actor_num', 'actor_id')

    def __init__(self, counter, actor_num, actor_id):
        self.counter = counter
        self.actor_num = actor_num
        self.actor_id = actor_id

    def sort_key(self):
        # Lamport order: by counter, then by actorId string (ref columnar.js:114-120)
        return (self.counter, self.actor_id)


def _parse(op_id_str, actor_ids):
    counter, actor_id = parse_op_id(op_id_str)
    try:
        actor_num = actor_ids.index(actor_id)
    except ValueError:
        raise ValueError('missing actorId')
    return ParsedOpId(counter, actor_num, actor_id)


def _valid_multi_insert_value(value, datatype):
    if datatype is None:
        return isinstance(value, (str, bool)) or value is None
    return isinstance(value, (int, float)) and not isinstance(value, bool)


def expand_multi_ops(ops, start_op, actor):
    """Expand multi-insert `values` and `multiOp` deletions into individual
    ops (ref columnar.js:446-475)."""
    op_num = start_op
    expanded = []
    for op in ops:
        if op.get('action') == 'set' and op.get('values') is not None and op.get('insert'):
            if op.get('pred'):
                raise ValueError('multi-insert pred must be empty')
            last_elem_id = op['elemId']
            datatype = op.get('datatype')
            for value in op['values']:
                if not _valid_multi_insert_value(value, datatype):
                    raise ValueError(
                        f'Decode failed: bad value/datatype association ({value},{datatype})')
                new_op = {'action': 'set', 'obj': op['obj'], 'elemId': last_elem_id,
                          'value': value, 'pred': [], 'insert': True}
                if datatype is not None:
                    new_op['datatype'] = datatype
                expanded.append(new_op)
                last_elem_id = f'{op_num}@{actor}'
                op_num += 1
        elif op.get('action') == 'del' and op.get('multiOp', 1) > 1:
            if len(op.get('pred', [])) != 1:
                raise ValueError('multiOp deletion must have exactly one pred')
            ctr, eactor = parse_op_id(op['elemId'])
            pctr, pactor = parse_op_id(op['pred'][0])
            for i in range(op['multiOp']):
                expanded.append({'action': 'del', 'obj': op['obj'],
                                 'elemId': f'{ctr + i}@{eactor}',
                                 'pred': [f'{pctr + i}@{pactor}']})
                op_num += 1
        else:
            expanded.append(dict(op))
            op_num += 1
    return expanded


def _collect_unknown_actors(cid, value, actors):
    """Actor-id strings inside unknown columns must be in the actor table."""
    if cid % 8 == COLUMN_TYPE['ACTOR_ID'] and isinstance(value, str):
        actors.add(value)
    elif isinstance(value, list):
        for item in value:
            for inner_cid, inner_value in item.items():
                _collect_unknown_actors(inner_cid, inner_value, actors)


def parse_all_op_ids(changes, single):
    """Replace string opIds in `changes` with ParsedOpId objects and return
    (parsed_changes, actor_ids) (ref columnar.js:133-170)."""
    actors = set()
    new_changes = []
    for change in changes:
        change = dict(change)
        actors.add(change['actor'])
        change['ops'] = expand_multi_ops(change['ops'], change['startOp'], change['actor'])
        for op in change['ops']:
            if op['obj'] != '_root':
                actors.add(parse_op_id(op['obj'])[1])
            if op.get('elemId') and op['elemId'] != '_head':
                actors.add(parse_op_id(op['elemId'])[1])
            if op.get('child'):
                actors.add(parse_op_id(op['child'])[1])
            for pred in op.get('pred', []):
                actors.add(parse_op_id(pred)[1])
            for cid, value in op.get('unknownCols', {}).items():
                _collect_unknown_actors(cid, value, actors)
        new_changes.append(change)

    actor_ids = sorted(actors)
    if single:
        first = changes[0]['actor']
        actor_ids = [first] + [a for a in actor_ids if a != first]
    for change in new_changes:
        actor_num = actor_ids.index(change['actor'])
        change['actorNum'] = actor_num
        for i, op in enumerate(change['ops']):
            op['id'] = ParsedOpId(change['startOp'] + i, actor_num, change['actor'])
            if op['obj'] != '_root':
                op['obj'] = _parse(op['obj'], actor_ids)
            if op.get('elemId') and op['elemId'] != '_head':
                op['elemId'] = _parse(op['elemId'], actor_ids)
            if op.get('child'):
                op['child'] = _parse(op['child'], actor_ids)
            op['pred'] = [_parse(p, actor_ids) for p in op.get('pred', [])]
            if 'succ' in op:
                op['succ'] = [_parse(s, actor_ids) for s in op['succ']]
    return new_changes, actor_ids


def _encode_object_id(op, columns):
    if op['obj'] == '_root':
        columns['objActor'].append_value(None)
        columns['objCtr'].append_value(None)
    else:
        columns['objActor'].append_value(op['obj'].actor_num)
        columns['objCtr'].append_value(op['obj'].counter)


def _encode_operation_key(op, columns):
    if op.get('key'):
        columns['keyActor'].append_value(None)
        columns['keyCtr'].append_value(None)
        columns['keyStr'].append_value(op['key'])
    elif op.get('elemId') == '_head' and op.get('insert'):
        columns['keyActor'].append_value(None)
        columns['keyCtr'].append_value(0)
        columns['keyStr'].append_value(None)
    elif op.get('elemId') is not None and op['elemId'].actor_num >= 0 and \
            op['elemId'].counter > 0:
        columns['keyActor'].append_value(op['elemId'].actor_num)
        columns['keyCtr'].append_value(op['elemId'].counter)
        columns['keyStr'].append_value(None)
    else:
        raise ValueError(f'Unexpected operation key: {op}')


def _encode_operation_action(op, columns):
    action = op['action']
    if isinstance(action, str):
        try:
            columns['action'].append_value(ACTIONS.index(action))
        except ValueError:
            raise ValueError(f'Unexpected operation action: {action}')
    elif isinstance(action, int):
        columns['action'].append_value(action)
    else:
        raise ValueError(f'Unexpected operation action: {action}')


def encode_value_to_columns(op, val_len, val_raw):
    """Encode op's value into the valLen/valRaw column pair (ref columnar.js:259-292)."""
    value = op.get('value')
    datatype = op.get('datatype')
    action = op['action']
    if (action not in ('set', 'inc') and not isinstance(action, int)) or value is None:
        val_len.append_value(VALUE_TYPE['NULL'])
    elif value is False:
        val_len.append_value(VALUE_TYPE['FALSE'])
    elif value is True:
        val_len.append_value(VALUE_TYPE['TRUE'])
    elif isinstance(value, str):
        num_bytes = val_raw.append_raw_string(value)
        val_len.append_value(num_bytes << 4 | VALUE_TYPE['UTF8'])
    elif isinstance(datatype, int) and not isinstance(datatype, bool) and \
            VALUE_TYPE['MIN_UNKNOWN'] <= datatype <= VALUE_TYPE['MAX_UNKNOWN'] and \
            isinstance(value, (bytes, bytearray)):
        num_bytes = val_raw.append_raw_bytes(value)
        val_len.append_value(num_bytes << 4 | datatype)
    elif isinstance(value, (bytes, bytearray)):
        num_bytes = val_raw.append_raw_bytes(value)
        val_len.append_value(num_bytes << 4 | VALUE_TYPE['BYTES'])
    elif isinstance(value, (int, float)):
        type_tag, num_bytes = _encode_number(value, datatype, val_raw)
        val_len.append_value(num_bytes << 4 | type_tag)
    elif datatype:
        raise ValueError(f'Unknown datatype {datatype} for value {value}')
    else:
        raise ValueError(f'Unsupported value in operation: {value}')


def _encode_number(value, datatype, val_raw):
    """Pick the VALUE_TYPE tag for a numeric value (ref columnar.js:228-253)."""
    if datatype == 'counter':
        return VALUE_TYPE['COUNTER'], val_raw.append_int53(int(value))
    if datatype == 'timestamp':
        return VALUE_TYPE['TIMESTAMP'], val_raw.append_int53(int(value))
    if datatype == 'uint':
        return VALUE_TYPE['LEB128_UINT'], val_raw.append_uint53(int(value))
    if datatype == 'int':
        return VALUE_TYPE['LEB128_INT'], val_raw.append_int53(int(value))
    if datatype == 'float64' or isinstance(value, float):
        return VALUE_TYPE['IEEE754'], val_raw.append_raw_bytes(struct.pack('<d', value))
    if MIN_SAFE_INTEGER <= value <= MAX_SAFE_INTEGER:
        return VALUE_TYPE['LEB128_INT'], val_raw.append_int53(value)
    return VALUE_TYPE['IEEE754'], val_raw.append_raw_bytes(struct.pack('<d', float(value)))


def decode_value(size_tag, data):
    """Decode a (valLen tag, valRaw bytes) pair into {value, datatype?}
    (ref columnar.js:300-329)."""
    if size_tag == VALUE_TYPE['NULL']:
        return {'value': None}
    if size_tag == VALUE_TYPE['FALSE']:
        return {'value': False}
    if size_tag == VALUE_TYPE['TRUE']:
        return {'value': True}
    tag = size_tag % 16
    if tag == VALUE_TYPE['UTF8']:
        return {'value': bytes(data).decode('utf-8')}
    if tag == VALUE_TYPE['LEB128_UINT']:
        return {'value': Decoder(data).read_uint53(), 'datatype': 'uint'}
    if tag == VALUE_TYPE['LEB128_INT']:
        return {'value': Decoder(data).read_int53(), 'datatype': 'int'}
    if tag == VALUE_TYPE['IEEE754']:
        if len(data) == 8:
            return {'value': struct.unpack('<d', bytes(data))[0], 'datatype': 'float64'}
        raise ValueError(f'Invalid length for floating point number: {len(data)}')
    if tag == VALUE_TYPE['COUNTER']:
        return {'value': Decoder(data).read_int53(), 'datatype': 'counter'}
    if tag == VALUE_TYPE['TIMESTAMP']:
        return {'value': Decoder(data).read_int53(), 'datatype': 'timestamp'}
    return {'value': bytes(data), 'datatype': tag}


def _unknown_column_plan(ops):
    """Collect unknown column ids across ops: returns (groups, standalone)
    where `groups` maps a GROUP_CARD column id to the set of inner column ids
    observed in its items."""
    groups = {}
    standalone = set()
    for op in ops:
        for cid, value in op.get('unknownCols', {}).items():
            if cid % 8 == COLUMN_TYPE['GROUP_CARD']:
                inner = groups.setdefault(cid, set())
                if isinstance(value, list):
                    for item in value:
                        inner.update(item.keys())
            else:
                standalone.add(cid)
    return groups, standalone


def _append_unknown_scalar(encoders, cid, value, actor_lookup):
    """Append one op's value for an unknown column, re-normalizing actor
    strings to table indexes and value dicts to valLen/valRaw pairs."""
    enc = encoders[cid]
    t = cid & 7
    if t == COLUMN_TYPE['VALUE_LEN']:
        entry = value if isinstance(value, dict) else {'value': value}
        encode_value_to_columns({'action': 'set', 'value': entry.get('value'),
                                 'datatype': entry.get('datatype')},
                                enc, encoders[cid + 1])
    elif t == COLUMN_TYPE['ACTOR_ID'] and value is not None and \
            actor_lookup is not None and isinstance(value, str):
        enc.append_value(actor_lookup[value])
    else:
        enc.append_value(value)


def _encode_unknown_columns(ops, actor_lookup):
    """Build encoders for unknown forward-compat columns so they survive
    re-encoding (the reference carries them in its raw block store instead,
    new_backend_test.js:1857). Returns a list of (column_id, name, encoder)."""
    groups, standalone = _unknown_column_plan(ops)
    if not groups and not standalone:
        return []
    all_ids = set(standalone) | set(groups)
    for inner in groups.values():
        all_ids |= inner
    encoders = {}
    for cid in sorted(all_ids):
        encoders[cid] = encoder_by_column_id(cid)
        if cid % 8 == COLUMN_TYPE['VALUE_LEN'] and cid + 1 not in encoders:
            encoders[cid + 1] = Encoder()
    standalone_order = sorted(standalone)
    group_order = [(gid, sorted(inner)) for gid, inner in sorted(groups.items())]
    for op in ops:
        ucols = op.get('unknownCols', {})
        for cid in standalone_order:
            _append_unknown_scalar(encoders, cid, ucols.get(cid), actor_lookup)
        for gid, inner_order in group_order:
            items = ucols.get(gid)
            if items is None:
                encoders[gid].append_value(None)
                continue
            encoders[gid].append_value(len(items))
            for item in items:
                for cid in inner_order:
                    _append_unknown_scalar(encoders, cid, item.get(cid), actor_lookup)
    return [(cid, f'col_{cid}', enc) for cid, enc in encoders.items()]


def encode_ops(ops, for_document, actor_lookup=None):
    """Encode parsed ops into columns; returns a sorted list of
    (column_id, column_name, encoder) (ref columnar.js:370-436).
    `actor_lookup` maps actor id strings to table indexes for re-encoding
    unknown actor-type columns."""
    columns = {
        'objActor': RLEEncoder('uint'), 'objCtr': RLEEncoder('uint'),
        'keyActor': RLEEncoder('uint'), 'keyCtr': DeltaEncoder(),
        'keyStr': RLEEncoder('utf8'), 'insert': BooleanEncoder(),
        'action': RLEEncoder('uint'), 'valLen': RLEEncoder('uint'),
        'valRaw': Encoder(), 'chldActor': RLEEncoder('uint'),
        'chldCtr': DeltaEncoder(),
    }
    if for_document:
        columns.update({'idActor': RLEEncoder('uint'), 'idCtr': DeltaEncoder(),
                        'succNum': RLEEncoder('uint'), 'succActor': RLEEncoder('uint'),
                        'succCtr': DeltaEncoder()})
    else:
        columns.update({'predNum': RLEEncoder('uint'), 'predCtr': DeltaEncoder(),
                        'predActor': RLEEncoder('uint')})

    for op in ops:
        _encode_object_id(op, columns)
        _encode_operation_key(op, columns)
        columns['insert'].append_value(bool(op.get('insert')))
        _encode_operation_action(op, columns)
        encode_value_to_columns(op, columns['valLen'], columns['valRaw'])

        child = op.get('child')
        if child is not None and child.counter:
            columns['chldActor'].append_value(child.actor_num)
            columns['chldCtr'].append_value(child.counter)
        else:
            columns['chldActor'].append_value(None)
            columns['chldCtr'].append_value(None)

        if for_document:
            columns['idActor'].append_value(op['id'].actor_num)
            columns['idCtr'].append_value(op['id'].counter)
            succ = sorted(op['succ'], key=ParsedOpId.sort_key)
            columns['succNum'].append_value(len(succ))
            for s in succ:
                columns['succActor'].append_value(s.actor_num)
                columns['succCtr'].append_value(s.counter)
        else:
            pred = sorted(op['pred'], key=ParsedOpId.sort_key)
            columns['predNum'].append_value(len(pred))
            for p in pred:
                columns['predActor'].append_value(p.actor_num)
                columns['predCtr'].append_value(p.counter)

    spec = DOC_OPS_COLUMNS if for_document else CHANGE_COLUMNS
    column_list = [(column_id, name, columns[name])
                   for name, column_id in spec if name in columns]
    column_list.extend(_encode_unknown_columns(ops, actor_lookup))
    return sorted(column_list, key=lambda c: c[0])


def encoder_by_column_id(column_id):
    t = column_id & 7
    if t == COLUMN_TYPE['INT_DELTA']:
        return DeltaEncoder()
    if t == COLUMN_TYPE['BOOLEAN']:
        return BooleanEncoder()
    if t == COLUMN_TYPE['STRING_RLE']:
        return RLEEncoder('utf8')
    if t == COLUMN_TYPE['VALUE_RAW']:
        return Encoder()
    return RLEEncoder('uint')


def decoder_by_column_id(column_id, buffer):
    t = column_id & 7
    if t == COLUMN_TYPE['INT_DELTA']:
        return DeltaDecoder(buffer)
    if t == COLUMN_TYPE['BOOLEAN']:
        return BooleanDecoder(buffer)
    if t == COLUMN_TYPE['STRING_RLE']:
        return RLEDecoder('utf8', buffer)
    if t == COLUMN_TYPE['VALUE_RAW']:
        return Decoder(buffer)
    return RLEDecoder('uint', buffer)


def make_decoders(columns, column_spec):
    """Merge encoded columns with the expected spec, supplying empty decoders
    for missing columns and passing through unknown ones (ref columnar.js:553-575).

    `columns` is a list of dicts {columnId, buffer}; returns a list of dicts
    {columnId, columnName?, decoder}.
    """
    decoders = []
    ci = 0
    si = 0
    while ci < len(columns) or si < len(column_spec):
        if ci == len(columns) or (si < len(column_spec) and
                                  column_spec[si][1] < columns[ci]['columnId']):
            name, column_id = column_spec[si]
            decoders.append({'columnId': column_id, 'columnName': name,
                             'decoder': decoder_by_column_id(column_id, b'')})
            si += 1
        elif si == len(column_spec) or columns[ci]['columnId'] < column_spec[si][1]:
            column_id = columns[ci]['columnId']
            decoders.append({'columnId': column_id,
                             'decoder': decoder_by_column_id(column_id, columns[ci]['buffer'])})
            ci += 1
        else:
            name, column_id = column_spec[si]
            decoders.append({'columnId': column_id, 'columnName': name,
                             'decoder': decoder_by_column_id(column_id, columns[ci]['buffer'])})
            ci += 1
            si += 1
    return decoders


def _decode_value_columns(columns, col_index, actor_ids, result):
    """Read one value from columns[col_index] into `result`; returns the number
    of columns consumed (2 for a VALUE_LEN/VALUE_RAW pair) (ref columnar.js:339-361)."""
    col = columns[col_index]
    column_id = col['columnId']
    name = col.get('columnName', f'col_{column_id}')
    if column_id % 8 == COLUMN_TYPE['VALUE_LEN'] and col_index + 1 < len(columns) and \
            columns[col_index + 1]['columnId'] == column_id + 1:
        size_tag = col['decoder'].read_value()
        raw = columns[col_index + 1]['decoder'].read_raw_bytes((size_tag or 0) >> 4)
        decoded = decode_value(size_tag or 0, raw)
        result[name] = decoded['value']
        if 'datatype' in decoded:
            result[name + '_datatype'] = decoded['datatype']
        return 2
    if column_id % 8 == COLUMN_TYPE['ACTOR_ID']:
        actor_num = col['decoder'].read_value()
        if actor_num is None:
            result[name] = None
        else:
            if actor_num >= len(actor_ids):
                raise ValueError(f'No actor index {actor_num}')
            result[name] = actor_ids[actor_num]
        return 1
    result[name] = col['decoder'].read_value()
    return 1


def decode_columns(columns, actor_ids, column_spec):
    """Decode columns into a list of row dicts (ref columnar.js:577-607)."""
    columns = make_decoders(columns, column_spec)
    # Duplicate column ids make the row scan ambiguous (a duplicate group
    # member is never drained, spinning the scan forever): reject up front.
    ids = [c['columnId'] for c in columns]
    if len(set(ids)) != len(ids):
        raise ValueError('duplicate column id in columns')
    rows = []
    while any(not c['decoder'].done for c in columns):
        row = {}
        col = 0
        while col < len(columns):
            column_id = columns[col]['columnId']
            group_id = column_id >> 4
            group_cols = 1
            while col + group_cols < len(columns) and \
                    columns[col + group_cols]['columnId'] >> 4 == group_id:
                group_cols += 1
            if column_id % 8 == COLUMN_TYPE['GROUP_CARD']:
                count = columns[col]['decoder'].read_value()
                # Distinguish null from 0 for unknown group columns so a
                # re-encode reproduces the original bytes; known group columns
                # keep the reference's null->[] behavior (columnar.js:590-598)
                if count is None and 'columnName' not in columns[col]:
                    row[f'col_{column_id}'] = None
                    col += group_cols
                    continue
                values = []
                for _ in range(count or 0):
                    value = {}
                    off = 1
                    while off < group_cols:
                        off += _decode_value_columns(columns, col + off,
                                                     actor_ids, value)
                    values.append(value)
                row[columns[col].get('columnName', f'col_{column_id}')] = values
                col += group_cols
            else:
                col += _decode_value_columns(columns, col, actor_ids, row)
        rows.append(row)
    return rows


def decode_ops(rows, for_document):
    """Convert decoded column rows into op dicts (ref columnar.js:483-510).

    Beyond the reference: unknown columns (decoded under `col_<id>` keys) and
    the values of unknown actions are preserved on the op under 'unknownCols'
    / 'value', so that a document save/load round-trip reproduces the original
    change bytes (and hence hashes) even for forward-compatibility data the
    engine doesn't understand."""
    ops = []
    for row in rows:
        obj = '_root' if row['objCtr'] is None else f"{row['objCtr']}@{row['objActor']}"
        if row['keyStr'] is not None:
            elem_id = None
        elif row['keyCtr'] == 0:
            elem_id = '_head'
        else:
            elem_id = f"{row['keyCtr']}@{row['keyActor']}"
        action_num = row['action']
        action = ACTIONS[action_num] if isinstance(action_num, int) and \
            0 <= action_num < len(ACTIONS) else action_num
        op = {'obj': obj, 'action': action}
        if elem_id is not None:
            op['elemId'] = elem_id
        else:
            op['key'] = row['keyStr']
        op['insert'] = bool(row['insert'])
        if action in ('set', 'inc') or isinstance(action, int):
            op['value'] = row['valLen']
            if row.get('valLen_datatype') is not None:
                op['datatype'] = row['valLen_datatype']
        unknown = _collect_unknown_columns(row)
        if unknown:
            if not for_document:
                # Change-container columns in the document succ group would
                # collide with the succ columns the document container adds
                # on save, making the saved document undecodable
                bad = sorted(set(unknown) & _DOC_RESERVED_COLUMN_IDS)
                if bad:
                    raise ValueError(
                        f'change column id {bad[0]} is reserved for the '
                        f'document container')
            op['unknownCols'] = unknown
        if (row.get('chldCtr') is None) != (row.get('chldActor') is None):
            raise ValueError(
                f"Mismatched child columns: {row.get('chldCtr')} and {row.get('chldActor')}")
        if row.get('chldCtr') is not None:
            op['child'] = f"{row['chldCtr']}@{row['chldActor']}"
        if for_document:
            op['id'] = f"{row['idCtr']}@{row['idActor']}"
            op['succ'] = [f"{s['succCtr']}@{s['succActor']}" for s in row['succNum']]
            _check_sorted_op_ids([(s['succCtr'], s['succActor']) for s in row['succNum']])
        else:
            op['pred'] = [f"{p['predCtr']}@{p['predActor']}" for p in row['predNum']]
            _check_sorted_op_ids([(p['predCtr'], p['predActor']) for p in row['predNum']])
        ops.append(op)
    return ops


def _collect_unknown_columns(row):
    """Gather `col_<id>` entries from a decoded row into {column_id: value}.
    Unknown VALUE_LEN columns become {'value':..., 'datatype':...} dicts;
    unknown group columns keep their list-of-dicts shape with the inner dicts
    normalized recursively."""
    unknown = {}
    for k in row:
        if not k.startswith('col_') or k.endswith('_datatype'):
            continue
        column_id = int(k[4:])
        value = row[k]
        if column_id % 8 == COLUMN_TYPE['VALUE_LEN']:
            entry = {'value': value}
            if row.get(k + '_datatype') is not None:
                entry['datatype'] = row[k + '_datatype']
            unknown[column_id] = entry
        elif isinstance(value, list) and column_id % 8 == COLUMN_TYPE['GROUP_CARD']:
            unknown[column_id] = [_collect_unknown_columns(item) for item in value]
        else:
            unknown[column_id] = value
    return unknown


def _check_sorted_op_ids(keys):
    for i in range(1, len(keys)):
        if keys[i - 1] >= keys[i]:
            raise ValueError('operation IDs are not in ascending order')


def materialize_columns(columns):
    """Finish each column's encoder once, yielding (column_id, name, bytes)."""
    return [(cid, name, enc.buffer) for cid, name, enc in columns]


def encode_column_info(encoder, columns):
    """`columns` is a materialized list of (column_id, name, bytes)."""
    non_empty = [(cid, name, buf) for cid, name, buf in columns if len(buf) > 0]
    encoder.append_uint53(len(non_empty))
    for cid, _name, buf in non_empty:
        encoder.append_uint53(cid)
        encoder.append_uint53(len(buf))


def decode_column_info(decoder):
    column_id_mask = ~COLUMN_TYPE_DEFLATE
    last = -1
    columns = []
    for _ in range(decoder.read_uint53()):
        column_id = decoder.read_uint53()
        buffer_len = decoder.read_uint53()
        if (column_id & column_id_mask) <= (last & column_id_mask):
            raise ValueError('Columns must be in ascending order')
        last = column_id
        columns.append({'columnId': column_id, 'bufferLen': buffer_len})
    return columns


def decode_change_header(decoder):
    num_deps = decoder.read_uint53()
    deps = [bytes_to_hex_string(decoder.read_raw_bytes(32)) for _ in range(num_deps)]
    change = {
        'actor': decoder.read_hex_string(),
        'seq': decoder.read_uint53(),
        'startOp': decoder.read_uint53(),
        'time': decoder.read_int53(),
        'message': decoder.read_prefixed_string(),
        'deps': deps,
    }
    actor_ids = [change['actor']]
    for _ in range(decoder.read_uint53()):
        actor_ids.append(decoder.read_hex_string())
    change['actorIds'] = actor_ids
    return change


def encode_container(chunk_type, contents):
    """Wrap `contents` bytes in a chunk container: magic + 4-byte checksum +
    type byte + LEB128 length + contents. Returns (hash_hex, bytes)
    (ref columnar.js:659-686)."""
    header = Encoder()
    header.append_byte(chunk_type)
    header.append_uint53(len(contents))
    hashed = header.buffer + contents
    digest = hashlib.sha256(hashed).digest()
    return bytes_to_hex_string(digest), MAGIC_BYTES + digest[:4] + hashed


def decode_container_header(decoder, compute_hash):
    if decoder.read_raw_bytes(4) != MAGIC_BYTES:
        raise ValueError('Data does not begin with magic bytes 85 6f 4a 83')
    expected_checksum = decoder.read_raw_bytes(4)
    hash_start = decoder.offset
    chunk_type = decoder.read_byte()
    chunk_length = decoder.read_uint53()
    header = {'chunkType': chunk_type, 'chunkLength': chunk_length,
              'chunkData': decoder.read_raw_bytes(chunk_length)}
    if compute_hash:
        digest = hashlib.sha256(decoder.buf[hash_start:decoder.offset]).digest()
        if digest[:4] != expected_checksum:
            raise ValueError('checksum does not match data')
        header['hash'] = bytes_to_hex_string(digest)
    return header


def encode_change(change_obj):
    """Encode a change (JSON-ish dict) to its binary form (ref columnar.js:710-739)."""
    changes, actor_ids = parse_all_op_ids([change_obj], True)
    change = changes[0]

    body = Encoder()
    deps = change.get('deps', [])
    body.append_uint53(len(deps))
    for dep in sorted(deps):
        body.append_raw_bytes(hex_string_to_bytes(dep))
    body.append_hex_string(change['actor'])
    body.append_uint53(change['seq'])
    body.append_uint53(change['startOp'])
    body.append_int53(change.get('time', 0))
    body.append_prefixed_string(change.get('message') or '')
    body.append_uint53(len(actor_ids) - 1)
    for actor in actor_ids[1:]:
        body.append_hex_string(actor)
    columns = materialize_columns(encode_ops(
        change['ops'], False, {a: i for i, a in enumerate(actor_ids)}))
    encode_column_info(body, columns)
    for _cid, _name, buf in columns:
        body.append_raw_bytes(buf)
    if change.get('extraBytes'):
        body.append_raw_bytes(change['extraBytes'])

    hex_hash, data = encode_container(CHUNK_TYPE_CHANGE, body.buffer)
    if change_obj.get('hash') and change_obj['hash'] != hex_hash:
        raise ValueError(
            f"Change hash does not match encoding: {change_obj['hash']} != {hex_hash}")
    return deflate_change(data) if len(data) >= DEFLATE_MIN_SIZE else data


def decode_change_columns(buffer):
    """Decode a binary change's header and raw columns (ref columnar.js:741-765)."""
    buffer = bytes(buffer)
    if buffer[8] == CHUNK_TYPE_DEFLATE:
        buffer = inflate_change(buffer)
    decoder = Decoder(buffer)
    header = decode_container_header(decoder, True)
    chunk = Decoder(header['chunkData'])
    if not decoder.done:
        raise ValueError('Encoded change has trailing data')
    if header['chunkType'] != CHUNK_TYPE_CHANGE:
        raise ValueError(f"Unexpected chunk type: {header['chunkType']}")

    change = decode_change_header(chunk)
    columns = decode_column_info(chunk)
    for col in columns:
        if col['columnId'] & COLUMN_TYPE_DEFLATE:
            raise ValueError('change must not contain deflated columns')
        col['buffer'] = chunk.read_raw_bytes(col['bufferLen'])
    if not chunk.done:
        change['extraBytes'] = chunk.read_raw_bytes(len(chunk.buf) - chunk.offset)
    change['columns'] = columns
    change['hash'] = header['hash']
    return change


def decode_change(buffer):
    """Decode a binary change into its dict representation (ref
    columnar.js:770-776). Undecodable bytes — whatever the parser trips
    over — raise `MalformedChange` (a ValueError), never a bare decoder
    exception: callers quarantine on the type, and the wire fuzzer pins
    the contract."""
    try:
        change = decode_change_columns(buffer)
        change['ops'] = decode_ops(
            decode_columns(change['columns'], change['actorIds'],
                           CHANGE_COLUMNS), False)
    except Exception as exc:
        raise as_wire_error(exc, MalformedChange, 'decode_change')
    del change['actorIds']
    del change['columns']
    return change


def decode_change_meta(buffer, compute_hash=False):
    """Decode only the header fields of a change (ref columnar.js:783-793).
    Raises `MalformedChange` on undecodable bytes (see decode_change)."""
    try:
        buffer = bytes(buffer)
        if buffer[8] == CHUNK_TYPE_DEFLATE:
            buffer = inflate_change(buffer)
        header = decode_container_header(Decoder(buffer), compute_hash)
        if header['chunkType'] != CHUNK_TYPE_CHANGE:
            raise ValueError('Buffer chunk type is not a change')
        meta = decode_change_header(Decoder(header['chunkData']))
    except Exception as exc:
        raise as_wire_error(exc, MalformedChange, 'decode_change_meta')
    meta['change'] = buffer
    if compute_hash:
        meta['hash'] = header['hash']
    return meta


def deflate_change(buffer):
    header = decode_container_header(Decoder(buffer), False)
    if header['chunkType'] != CHUNK_TYPE_CHANGE:
        raise ValueError(f"Unexpected chunk type: {header['chunkType']}")
    compressed = _deflate_raw(header['chunkData'])
    out = Encoder()
    out.append_raw_bytes(buffer[:8])  # magic + checksum of the uncompressed form
    out.append_byte(CHUNK_TYPE_DEFLATE)
    out.append_uint53(len(compressed))
    out.append_raw_bytes(compressed)
    return out.buffer


def inflate_change(buffer):
    header = decode_container_header(Decoder(buffer), False)
    if header['chunkType'] != CHUNK_TYPE_DEFLATE:
        raise ValueError(f"Unexpected chunk type: {header['chunkType']}")
    decompressed = _inflate_raw(header['chunkData'])
    out = Encoder()
    out.append_raw_bytes(buffer[:8])
    out.append_byte(CHUNK_TYPE_CHANGE)
    out.append_uint53(len(decompressed))
    out.append_raw_bytes(decompressed)
    return out.buffer


def split_containers(buffer):
    """Split concatenated chunks into individual byte arrays (ref
    columnar.js:829-837). Raises `MalformedChange` when the container
    framing itself is corrupt."""
    try:
        decoder = Decoder(buffer)
        chunks = []
        start = 0
        while not decoder.done:
            decode_container_header(decoder, False)
            chunks.append(decoder.buf[start:decoder.offset])
            start = decoder.offset
    except Exception as exc:
        raise as_wire_error(exc, MalformedChange, 'split_containers')
    return chunks


def decode_changes(binary_changes):
    """Decode a list of byte buffers (changes and/or documents) into change dicts
    (ref columnar.js:843-857)."""
    decoded = []
    for binary in binary_changes:
        for chunk in split_containers(binary):
            if chunk[8] == CHUNK_TYPE_DOCUMENT:
                decoded.extend(decode_document(chunk))
            elif chunk[8] in (CHUNK_TYPE_CHANGE, CHUNK_TYPE_DEFLATE):
                decoded.append(decode_change(chunk))
    return decoded


def group_change_ops(changes, ops):
    """Redistribute a document's consolidated ops back into the changes they
    came from, resynthesizing del ops from succ entries (ref columnar.js:876-943)."""
    changes_by_actor = {}
    for change in changes:
        change['ops'] = []
        actor_changes = changes_by_actor.setdefault(change['actor'], [])
        if change['seq'] != len(actor_changes) + 1:
            raise ValueError(f"Expected seq = {len(actor_changes) + 1}, got {change['seq']}")
        if change['seq'] > 1 and actor_changes[change['seq'] - 2]['maxOp'] > change['maxOp']:
            raise ValueError('maxOp must increase monotonically per actor')
        actor_changes.append(change)

    ops_by_id = {}
    for op in ops:
        if op['action'] == 'del':
            raise ValueError('document should not contain del operations')
        op['pred'] = ops_by_id[op['id']]['pred'] if op['id'] in ops_by_id else []
        ops_by_id[op['id']] = op
        for succ in op['succ']:
            if succ not in ops_by_id:
                if op.get('elemId'):
                    elem_id = op['id'] if op.get('insert') else op['elemId']
                    ops_by_id[succ] = {'id': succ, 'action': 'del', 'obj': op['obj'],
                                       'elemId': elem_id, 'pred': []}
                else:
                    ops_by_id[succ] = {'id': succ, 'action': 'del', 'obj': op['obj'],
                                       'key': op['key'], 'pred': []}
            ops_by_id[succ]['pred'].append(op['id'])
        del op['succ']
    for op in ops_by_id.values():
        if op['action'] == 'del':
            ops.append(op)

    for op in ops:
        counter, actor_id = parse_op_id(op['id'])
        actor_changes = changes_by_actor[actor_id]
        left, right = 0, len(actor_changes)
        while left < right:
            mid = (left + right) // 2
            if actor_changes[mid]['maxOp'] < counter:
                left = mid + 1
            else:
                right = mid
        if left >= len(actor_changes):
            raise ValueError(f"Operation ID {op['id']} outside of allowed range")
        actor_changes[left]['ops'].append(op)

    for change in changes:
        change['ops'].sort(key=lambda op: lamport_key(op['id']))
        change['startOp'] = change['maxOp'] - len(change['ops']) + 1
        del change['maxOp']
        for i, op in enumerate(change['ops']):
            expected = f"{change['startOp'] + i}@{change['actor']}"
            if op['id'] != expected:
                raise ValueError(f"Expected opId {expected}, got {op['id']}")
            del op['id']


def decode_document_changes(changes, expected_heads):
    """Resolve dep indexes to hashes and recompute each change's hash by
    re-encoding (ref columnar.js:945-981)."""
    heads = {}
    for i, change in enumerate(changes):
        change['deps'] = []
        for dep in change['depsNum']:
            index = dep['depsIndex']
            if index >= i or 'hash' not in changes[index]:
                raise ValueError(f'No hash for index {index} while processing index {i}')
            dep_hash = changes[index]['hash']
            change['deps'].append(dep_hash)
            heads.pop(dep_hash, None)
        change['deps'].sort()
        del change['depsNum']

        if change.get('extraLen_datatype') != VALUE_TYPE['BYTES']:
            raise ValueError(f"Bad datatype for extra bytes: {VALUE_TYPE['BYTES']}")
        change['extraBytes'] = change.pop('extraLen')
        change.pop('extraLen_datatype', None)

        changes[i] = decode_change(encode_change(change))
        heads[changes[i]['hash']] = True

    if sorted(heads.keys()) != sorted(expected_heads):
        raise ValueError(
            f"Mismatched heads hashes: expected {', '.join(expected_heads)}, "
            f"got {', '.join(sorted(heads.keys()))}")


def encode_document_header(doc):
    """Encode document metadata + column buffers into a document chunk
    (ref columnar.js:983-1004). `doc` keys: changesColumns, opsColumns,
    actorIds, heads, headsIndexes, extraBytes. Columns are
    (column_id, name, encoder) tuples."""
    changes_columns = [_deflate_column(c) for c in materialize_columns(doc['changesColumns'])]
    ops_columns = [_deflate_column(c) for c in materialize_columns(doc['opsColumns'])]
    body = Encoder()
    body.append_uint53(len(doc['actorIds']))
    for actor in doc['actorIds']:
        body.append_hex_string(actor)
    body.append_uint53(len(doc['heads']))
    for head in sorted(doc['heads']):
        body.append_raw_bytes(hex_string_to_bytes(head))
    encode_column_info(body, changes_columns)
    encode_column_info(body, ops_columns)
    for _cid, _name, buf in changes_columns:
        body.append_raw_bytes(buf)
    for _cid, _name, buf in ops_columns:
        body.append_raw_bytes(buf)
    for index in doc.get('headsIndexes', []):
        body.append_uint53(index)
    if doc.get('extraBytes'):
        body.append_raw_bytes(doc['extraBytes'])
    _hash, data = encode_container(CHUNK_TYPE_DOCUMENT, body.buffer)
    return data


def _deflate_column(column):
    cid, name, buf = column
    if len(buf) >= DEFLATE_MIN_SIZE:
        return (cid | COLUMN_TYPE_DEFLATE, name, _deflate_raw(buf))
    return column


def _inflate_column(column):
    if column['columnId'] & COLUMN_TYPE_DEFLATE:
        column['buffer'] = _inflate_raw(column['buffer'])
        column['columnId'] ^= COLUMN_TYPE_DEFLATE
    return column


def decode_document_header(buffer):
    """Parse a document chunk into raw columns + metadata (ref columnar.js:1006-1038)."""
    doc_decoder = Decoder(buffer)
    header = decode_container_header(doc_decoder, True)
    decoder = Decoder(header['chunkData'])
    if not doc_decoder.done:
        raise ValueError('Encoded document has trailing data')
    if header['chunkType'] != CHUNK_TYPE_DOCUMENT:
        raise ValueError(f"Unexpected chunk type: {header['chunkType']}")

    actor_ids = [decoder.read_hex_string() for _ in range(decoder.read_uint53())]
    num_heads = decoder.read_uint53()
    heads = [bytes_to_hex_string(decoder.read_raw_bytes(32)) for _ in range(num_heads)]

    changes_columns = decode_column_info(decoder)
    ops_columns = decode_column_info(decoder)
    for col in changes_columns:
        col['buffer'] = decoder.read_raw_bytes(col['bufferLen'])
        _inflate_column(col)
    for col in ops_columns:
        col['buffer'] = decoder.read_raw_bytes(col['bufferLen'])
        _inflate_column(col)
    heads_indexes = []
    if not decoder.done:
        heads_indexes = [decoder.read_uint53() for _ in range(num_heads)]
    extra_bytes = decoder.read_raw_bytes(len(decoder.buf) - decoder.offset)
    return {'changesColumns': changes_columns, 'opsColumns': ops_columns,
            'actorIds': actor_ids, 'heads': heads, 'headsIndexes': heads_indexes,
            'extraBytes': extra_bytes}


def decode_document(buffer):
    """Decode a document chunk back into the original list of changes
    (ref columnar.js:1040-1047). Raises `MalformedDocument` on
    undecodable bytes or when the recomputed heads miss the header's."""
    try:
        header = decode_document_header(buffer)
        changes = decode_columns(header['changesColumns'],
                                 header['actorIds'], DOCUMENT_COLUMNS)
        ops = decode_ops(
            decode_columns(header['opsColumns'], header['actorIds'],
                           DOC_OPS_COLUMNS), True)
        group_change_ops(changes, ops)
        decode_document_changes(changes, header['heads'])
    except Exception as exc:
        raise as_wire_error(exc, MalformedDocument, 'decode_document')
    return changes


def _native_column_decode(buf, delta):
    """One change-meta column via the native decoders; None = no codec
    (caller falls back to the Python decoders). Decode failures re-raise
    typed as MalformedDocument — the view's containment contract."""
    from . import native
    if not native.available():
        return None
    try:
        if delta:
            values, valid = native.decode_delta_column(buf)
        else:
            values, valid = native.decode_rle_column(buf, signed=False)
    except Exception as exc:
        raise as_wire_error(exc, MalformedDocument, 'DocChunkView column')
    return values.tolist(), valid.tolist()


class DocChunkView:
    """Compute-on-compressed reads over a document chunk (the LSM-OPD
    idea applied to the parked main store): heads, actor table, change
    count, per-actor clock, and maxOp are answered straight from the
    chunk's HEADER and change-metadata columns — the op columns (the
    bulk of the chunk, and the expensive part of `decode_document`) are
    never inflated, decoded, or re-encoded.

    Used by the delta+main storage engine (fleet/storage.py) to serve
    causal-state reads and sync-membership probes for parked documents
    without materializing them, and by `park_docs` as the header-derived
    change count. Raises `MalformedDocument` on undecodable bytes."""

    __slots__ = ('heads', 'actor_ids', '_cols', '_n_changes', '_clock',
                 '_max_op')

    # change-metadata column ids ((spec << 4) | type)
    _ACTOR, _SEQ, _MAXOP = 0x01, 0x03, 0x13

    def __init__(self, chunk, check=True):
        try:
            # memoryview chunks (the storage engine's mmap'd segment
            # arena) parse ZERO-COPY: the Decoder slices the view in
            # place, the op columns are never touched, and the few
            # header columns this view keeps are copied out below —
            # building a DocChunkView never materializes the chunk
            if not isinstance(chunk, (bytes, memoryview)):
                chunk = bytes(chunk)
            self._parse(chunk, check)
        except Exception as exc:
            raise as_wire_error(exc, MalformedDocument, 'DocChunkView')
        self._n_changes = None
        self._clock = None
        self._max_op = None

    def _parse(self, chunk, check):
        decoder = Decoder(chunk)
        header = decode_container_header(decoder, check)
        if header['chunkType'] != CHUNK_TYPE_DOCUMENT:
            raise ValueError(f"Unexpected chunk type: {header['chunkType']}")
        body = Decoder(header['chunkData'])
        self.actor_ids = [body.read_hex_string()
                          for _ in range(body.read_uint53())]
        num_heads = body.read_uint53()
        self.heads = [bytes_to_hex_string(body.read_raw_bytes(32))
                      for _ in range(num_heads)]
        changes_info = decode_column_info(body)
        ops_info = decode_column_info(body)
        # slice ONLY the change-metadata columns this view serves;
        # everything after (all op columns) stays untouched bytes
        cols = {}
        for col in changes_info:
            buf = body.read_raw_bytes(col['bufferLen'])
            cid = col['columnId']
            if (cid & ~COLUMN_TYPE_DEFLATE) in (self._ACTOR, self._SEQ,
                                                self._MAXOP):
                if cid & COLUMN_TYPE_DEFLATE:
                    buf = _inflate_raw(buf)
                    cid &= ~COLUMN_TYPE_DEFLATE
                cols[cid] = bytes(buf)
        self._cols = cols

    def _decode(self, cid, delta):
        """(values, valid) for one change-meta column; native decoders
        when available, the Python codecs otherwise."""
        buf = self._cols.get(cid, b'')
        out = _native_column_decode(buf, delta)
        if out is not None:
            return out
        dec = DeltaDecoder(buf) if delta else RLEDecoder('uint', buf)
        values, valid = [], []
        while not dec.done:
            v = dec.read_value()
            values.append(0 if v is None else v)
            valid.append(v is not None)
        return values, valid

    @property
    def n_changes(self):
        """Number of changes in the chunk, from the seq column's row
        count alone (no per-change decode)."""
        if self._n_changes is None:
            values, _valid = self._decode(self._SEQ, delta=True)
            self._n_changes = len(values)
        return self._n_changes

    @property
    def clock(self):
        """{actor_id: max seq} straight from the actor/seq columns."""
        if self._clock is None:
            actors, a_ok = self._decode(self._ACTOR, delta=False)
            seqs, s_ok = self._decode(self._SEQ, delta=True)
            if len(actors) != len(seqs):
                raise MalformedDocument(
                    'DocChunkView: actor/seq column length mismatch')
            clock = {}
            for a, av, s, sv in zip(actors, a_ok, seqs, s_ok):
                if not av or not sv:
                    raise MalformedDocument(
                        'DocChunkView: null actor/seq row')
                a = int(a)
                if a >= len(self.actor_ids) or a < 0:
                    raise MalformedDocument(f'DocChunkView: no actor {a}')
                hexa = self.actor_ids[a]
                s = int(s)
                if clock.get(hexa, 0) < s:
                    clock[hexa] = s
            self._clock = clock
        return dict(self._clock)

    @property
    def max_op(self):
        if self._max_op is None:
            values, valid = self._decode(self._MAXOP, delta=True)
            self._max_op = max((int(v) for v, ok in zip(values, valid)
                                if ok), default=0)
        return self._max_op

    def contains_head(self, hash_hex):
        """Sync-membership probe: is `hash_hex` one of this document's
        heads? (Exact interior-history membership needs materialized
        hashes; the heads answer is what the sync driver's have-check
        consumes for parked docs.)"""
        return hash_hex in self.heads

    def covers_heads(self, their_heads):
        """True when every hash in `their_heads` is one of this chunk's
        heads — the parked-doc form of the reference's
        all-deps-already-known fast path: a peer whose heads are a
        subset of ours (and vice versa for equality) needs no revive to
        answer 'in sync'."""
        heads = set(self.heads)
        return all(h in heads for h in their_heads)
