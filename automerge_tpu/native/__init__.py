"""ctypes bindings for the native codec kernels (codec.cpp).

Compiled on demand with g++ (cached next to the source); all entry points
have pure-Python fallbacks so the library works without a toolchain, but the
native path is the production one (SURVEY.md section 2.9 native accounting):
SHA-256 (single + batched across documents), raw DEFLATE, and the
LEB128/RLE/delta/boolean column decoders emitting int64 arrays + null masks.

Multi-core contract (BASELINE.md "Multi-core contract"): the batched
change parse and batched SHA run over a persistent native thread pool
sized by ``AUTOMERGE_TPU_NATIVE_THREADS`` (default: the machine's cores,
capped at 16; ``set_native_threads`` overrides at runtime). Parallel
output is byte-identical to ``AUTOMERGE_TPU_NATIVE_THREADS=1`` — same
column bytes, hashes, interned-table order, and typed-error verdicts —
pinned by tests/test_native_parallel.py. The GIL is released across the
whole batch (CDLL entry points release it implicitly; the zero-copy list
entry releases it inside C++ after gathering buffer pointers), which is
what lets fleet.backend's pipelined turbo path overlap the parse of
sub-batch k+1 with the device dispatch of sub-batch k.

A compiled binary carries an ABI stamp (``am_abi_version``); a stale .so
that cannot be rebuilt fails loudly at import instead of silently running
an old single-threaded codec (see tools/build_native.sh).
"""

import ctypes
import os
import subprocess
import sys
import threading

import numpy as np

from ..errors import MalformedChange
from ..observability import hist as _hist
from ..observability.metrics import register_health_source
from ..observability.spans import on as _spans_on
from ..observability.spans import record_span as _record_span
from ..observability.spans import span as _span

# Bumped in lockstep with codec.cpp's am_abi_version whenever the C
# surface changes shape. A mismatch means the cached .so predates this
# wrapper (or vice versa) and MUST NOT be used.
_ABI_VERSION = 3


class NativeAbiMismatch(RuntimeError):
    """A compiled codec binary is stale and could not be rebuilt."""

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), 'codec.cpp')
# AUTOMERGE_TPU_NATIVE_SO points the wrapper at an alternate prebuilt
# binary — the sanitizer plane loads the ASan/UBSan build this way
# (tools/build_native.sh --sanitize). The override is loaded VERBATIM:
# never rebuilt, and any failure (missing file, ABI skew) is loud —
# silently falling back to the normal .so would make a sanitizer replay
# quietly test the wrong library.
_SO_OVERRIDE = os.environ.get('AUTOMERGE_TPU_NATIVE_SO') or None
_LIB_PATH = _SO_OVERRIDE or os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    f'_codec_{sys.implementation.cache_tag}.so')

_lib = None
_load_error = None


_pylib = None


def _load_pydll():
    """PyDLL handle (GIL held during calls) for the zero-copy list
    entry; None when the .so was built without CPython headers."""
    global _pylib
    if _pylib is not None:
        return _pylib if _pylib is not False else None
    if _load() is None:
        _pylib = False
        return None
    try:
        lib = ctypes.PyDLL(_LIB_PATH)
        lib.am_ingest_changes_list.argtypes = [ctypes.py_object,
                                               ctypes.c_int, ctypes.c_int]
        lib.am_ingest_changes_list.restype = ctypes.c_int64
        _pylib = lib
        return lib
    except (OSError, AttributeError):
        _pylib = False
        return None


def _build():
    # -pthread: the codec spawns a persistent worker pool (NativePool);
    # keep in sync with tools/build_native.sh
    cmd = ['g++', '-O3', '-shared', '-fPIC', '-std=c++17', '-pthread',
           _SRC, '-lz', '-o', _LIB_PATH]
    # CPython headers enable the zero-copy list ingest entry
    # (am_ingest_changes_list); codec.cpp compiles without them too
    try:
        import sysconfig
        inc = sysconfig.get_paths().get('include')
        if inc and os.path.exists(os.path.join(inc, 'Python.h')):
            cmd.insert(1, f'-I{inc}')
    except (ImportError, KeyError, OSError):
        pass    # no headers: build without the zero-copy list entry
    subprocess.run(cmd, check=True, capture_output=True)


def _abi_of(lib):
    """The binary's ABI stamp, or -1 when the symbol predates stamping."""
    try:
        fn = lib.am_abi_version
    except AttributeError:
        return -1
    fn.argtypes = []
    fn.restype = ctypes.c_int64
    return int(fn())


def _load():
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return _lib
    try:
        if _SO_OVERRIDE:
            try:
                lib = ctypes.CDLL(_LIB_PATH)
            except OSError as exc:
                raise NativeAbiMismatch(
                    f'AUTOMERGE_TPU_NATIVE_SO={_LIB_PATH} could not be '
                    f'loaded ({exc}) — the override is never rebuilt or '
                    f'fallen back from; fix the path or unset it'
                ) from exc
            if _abi_of(lib) != _ABI_VERSION:
                raise NativeAbiMismatch(
                    f'AUTOMERGE_TPU_NATIVE_SO={_LIB_PATH} reports ABI '
                    f'{_abi_of(lib)}, wrapper expects {_ABI_VERSION} — '
                    f'rebuild it (tools/build_native.sh --sanitize=... '
                    f'for sanitized binaries)')
            return _finish_load(lib)
        if not os.path.exists(_LIB_PATH) or \
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC):
            _build()
        lib = ctypes.CDLL(_LIB_PATH)
        if _abi_of(lib) != _ABI_VERSION:
            # Stale binary (mtime lied — e.g. a prebuilt .so shipped with
            # a fresher timestamp than the source). Rebuild; if that is
            # impossible, fail LOUDLY rather than run the old codec
            # single-threaded with a mismatched C surface.
            try:
                # unlink first: the stale mapping is still dlopen'd, and
                # glibc dedups by (dev, inode) — rebuilding in place and
                # re-dlopening the same inode would return the OLD library
                os.remove(_LIB_PATH)
                _build()
            except Exception as exc:
                raise NativeAbiMismatch(
                    f'native codec binary {_LIB_PATH} has ABI '
                    f'{_abi_of(lib)}, wrapper expects {_ABI_VERSION}, and '
                    f'rebuilding failed ({exc}); rebuild it with '
                    f'tools/build_native.sh or delete the stale .so'
                ) from exc
            lib = ctypes.CDLL(_LIB_PATH)
            if _abi_of(lib) != _ABI_VERSION:
                raise NativeAbiMismatch(
                    f'native codec binary {_LIB_PATH} still reports ABI '
                    f'{_abi_of(lib)} after a rebuild (wrapper expects '
                    f'{_ABI_VERSION}) — source/wrapper version skew')
        return _finish_load(lib)
    except NativeAbiMismatch:
        raise                     # stale binaries fail loudly, not silently
    except Exception as exc:  # toolchain missing or compile failure
        _load_error = exc
        _lib = None
    return _lib


def _finish_load(lib):
    """Declare the C surface and adopt `lib` as THE loaded codec."""
    global _lib, _threads
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.am_sha256.argtypes = [u8p, ctypes.c_uint64, u8p]
    lib.am_sha256_batch.argtypes = [u8p, u64p, u64p, ctypes.c_uint64, u8p]
    lib.am_deflate_raw.argtypes = [u8p, ctypes.c_uint64, u8p, ctypes.c_uint64]
    lib.am_deflate_raw.restype = ctypes.c_int64
    lib.am_inflate_raw.argtypes = [u8p, ctypes.c_uint64, u8p, ctypes.c_uint64]
    lib.am_inflate_raw.restype = ctypes.c_int64
    lib.am_decode_rle.argtypes = [u8p, ctypes.c_uint64, ctypes.c_int,
                                  i64p, u8p, ctypes.c_int64]
    lib.am_decode_rle.restype = ctypes.c_int64
    lib.am_decode_delta.argtypes = [u8p, ctypes.c_uint64, i64p, u8p,
                                    ctypes.c_int64]
    lib.am_decode_delta.restype = ctypes.c_int64
    lib.am_decode_boolean.argtypes = [u8p, ctypes.c_uint64, i64p, u8p,
                                      ctypes.c_int64]
    lib.am_decode_boolean.restype = ctypes.c_int64
    lib.am_count_rle.argtypes = [u8p, ctypes.c_uint64, ctypes.c_int]
    lib.am_count_rle.restype = ctypes.c_int64
    lib.am_pool_configure.argtypes = [ctypes.c_int]
    lib.am_pool_configure.restype = ctypes.c_int64
    lib.am_pool_threads.argtypes = []
    lib.am_pool_threads.restype = ctypes.c_int64
    lib.am_pool_stats.argtypes = [i64p, i64p, i64p]
    lib.am_pool_stats.restype = ctypes.c_int64
    lib.am_ingest_parse_stats.argtypes = [i64p, i64p, i64p, i64p,
                                          ctypes.c_int64]
    lib.am_ingest_parse_stats.restype = ctypes.c_int64
    _threads = int(lib.am_pool_configure(_default_threads()))
    _lib = lib
    return _lib


_threads = 1


def _default_threads():
    """Pool width: AUTOMERGE_TPU_NATIVE_THREADS, else cores capped at 16
    (the codec's slices are memory-bandwidth-bound past that)."""
    env = os.environ.get('AUTOMERGE_TPU_NATIVE_THREADS')
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, min(os.cpu_count() or 1, 16))


def native_threads():
    """The configured parse-pool width (1 when the codec is unavailable)."""
    return _threads if _load() is not None else 1


def set_native_threads(n):
    """Resize the native parse pool; returns the previous width. The
    determinism contract makes this a pure performance knob — outputs are
    byte-identical at every width."""
    global _threads
    lib = _load()
    if lib is None:
        return 1
    prev = _threads
    with _ingest_lock:
        _threads = int(lib.am_pool_configure(int(n)))
    return prev


def pool_stats():
    """{'threads', 'tasks', 'busy_s'} — lifetime pool occupancy counters."""
    lib = _load()
    if lib is None:
        return {'threads': 1, 'tasks': 0, 'busy_s': 0.0}
    t = ctypes.c_int64(0)
    n = ctypes.c_int64(0)
    b = ctypes.c_int64(0)
    lib.am_pool_stats(ctypes.byref(t), ctypes.byref(n), ctypes.byref(b))
    return {'threads': int(t.value), 'tasks': int(n.value),
            'busy_s': float(b.value) / 1e9}


register_health_source('native_pool_tasks',
                       lambda: pool_stats()['tasks'] if _lib else 0)


def _note_parse_stats(lib):
    """After an ingest: inject per-slice `parse_chunk` spans (worker-tagged
    tids — each pool lane renders as its own Perfetto track) and record the
    parse_chunk_s / parse_pool_occupancy histograms. Only runs when the
    observability switches are on; called under _ingest_lock so the C-side
    stats belong to OUR parse."""
    spans_on = _spans_on()
    hist_on = _hist.on()
    if not (spans_on or hist_on):
        return
    wall_t0 = ctypes.c_int64(0)
    wall_t1 = ctypes.c_int64(0)
    threads = ctypes.c_int64(1)
    rows = np.zeros(5 * 256, dtype=np.int64)
    n = int(lib.am_ingest_parse_stats(
        ctypes.byref(wall_t0), ctypes.byref(wall_t1), ctypes.byref(threads),
        rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), 256))
    if n <= 0:
        return
    rows = rows[:5 * n].reshape(n, 5)
    busy_ns = 0
    for t0, t1, first, count, worker in rows.tolist():
        busy_ns += t1 - t0
        if spans_on:
            _record_span('parse_chunk', t0, t1, tid=1_000_000 + worker,
                         first_chunk=first, chunks=count, worker=worker)
        if hist_on:
            _hist.record_value('parse_chunk_s', (t1 - t0) / 1e9,
                               scale=1e9, unit='s')
    if hist_on:
        wall = max(int(wall_t1.value) - int(wall_t0.value), 1)
        occ = 100.0 * busy_ns / (wall * max(int(threads.value), 1))
        _hist.record_value('parse_pool_occupancy', occ, scale=1,
                           unit='%')


# The native ingest context is single-flight (two-phase parse+fetch over
# one global C context); concurrent callers — e.g. the pipelined turbo
# prefetch thread racing the first sub-batch's foreground parse —
# serialize here instead of corrupting each other's fetches.
_ingest_lock = threading.RLock()


def available():
    return _load() is not None


def _u8(buf):
    """Byte buffer -> (uint8 array, pointer) WITHOUT an owned-bytes
    copy: bytes, bytearray, and memoryview (incl. views into mmap'd
    storage segments) go straight through the buffer protocol, so the
    native codec reads compressed chunks off the page cache in place."""
    if not isinstance(buf, (bytes, bytearray, memoryview)):
        buf = bytes(buf)
    arr = np.frombuffer(buf, dtype=np.uint8)
    if arr.size == 0:
        arr = np.zeros(1, dtype=np.uint8)
    return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def sha256(data):
    """SHA-256 digest (native; falls back to hashlib)."""
    lib = _load()
    if lib is None:
        import hashlib
        return hashlib.sha256(bytes(data)).digest()
    arr, ptr = _u8(data)
    out = np.zeros(32, dtype=np.uint8)
    lib.am_sha256(ptr, arr.size if len(data) else 0,
                  out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out.tobytes()


def sha256_batch(buffers):
    """Hash many buffers (e.g. one change per document across a fleet)."""
    with _span('sha256_batch', buffers=len(buffers)):
        return _sha256_batch(buffers)


def _sha256_batch(buffers):
    lib = _load()
    if lib is None:
        import hashlib
        return [hashlib.sha256(bytes(b)).digest() for b in buffers]
    blob = b''.join(bytes(b) for b in buffers)
    offsets = np.zeros(len(buffers), dtype=np.uint64)
    lens = np.array([len(b) for b in buffers], dtype=np.uint64)
    np.cumsum(lens[:-1], out=offsets[1:]) if len(buffers) > 1 else None
    arr, ptr = _u8(blob)
    out = np.zeros(32 * len(buffers), dtype=np.uint8)
    lib.am_sha256_batch(
        ptr, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(buffers),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    raw = out.tobytes()
    return [raw[32 * i:32 * i + 32] for i in range(len(buffers))]


def deflate_raw(data):
    lib = _load()
    if lib is None:
        import zlib
        c = zlib.compressobj(6, zlib.DEFLATED, -15)
        return c.compress(bytes(data)) + c.flush()
    data = bytes(data)
    cap = len(data) + (len(data) >> 3) + 64
    out = np.zeros(cap, dtype=np.uint8)
    arr, ptr = _u8(data)
    size = lib.am_deflate_raw(ptr, len(data),
                              out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                              cap)
    if size < 0:
        raise ValueError('deflate failed')
    return out[:size].tobytes()


def inflate_raw(data, max_size=1 << 28):
    lib = _load()
    if lib is None:
        import zlib
        return zlib.decompress(bytes(data), -15)
    data = bytes(data)
    cap = min(max(len(data) * 8, 1 << 16), max_size)
    arr, ptr = _u8(data)
    while True:
        out = np.zeros(cap, dtype=np.uint8)
        size = lib.am_inflate_raw(
            ptr, len(data), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            cap)
        if size >= 0:
            return out[:size].tobytes()
        if cap >= max_size:
            # hostile wire bytes reach this decoder (deflated columns in
            # change/document chunks), so the failure is typed
            raise MalformedChange('inflate failed: corrupt or oversized '
                                  'deflate stream')
        cap = min(cap * 4, max_size)


def _decode_column(fn_name, buf, signed=False):
    lib = _load()
    if lib is None:
        return None  # caller falls back to the Python codecs
    data = bytes(buf)
    arr, ptr = _u8(data)
    if fn_name == 'rle':
        count = lib.am_count_rle(ptr, len(data), int(signed))
    elif fn_name == 'delta':
        count = lib.am_count_rle(ptr, len(data), 1)
    else:
        count = len(data) * 8  # upper bound for boolean runs is large; count below
    if fn_name == 'boolean':
        # booleans: decode with a growing buffer. -2 = capacity too
        # small (retry bigger), -1 = malformed — the distinction keeps a
        # hostile run count from driving the retry loop into multi-GB
        # allocations before the typed failure; the ceiling matches the
        # C side's kMaxColumnValues.
        cap = max(64, len(data) * 8)
        while True:
            out = np.zeros(cap, dtype=np.int64)
            mask = np.zeros(cap, dtype=np.uint8)
            n = lib.am_decode_boolean(
                ptr, len(data), out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap)
            if n >= 0:
                return out[:n], mask[:n].astype(bool)
            if n != -2:
                raise MalformedChange('malformed boolean column')
            cap *= 4
            if cap > 1 << 26:
                raise MalformedChange('boolean column too large')
    if count < 0:
        raise MalformedChange('malformed column')
    out = np.zeros(max(count, 1), dtype=np.int64)
    mask = np.zeros(max(count, 1), dtype=np.uint8)
    fn = lib.am_decode_rle if fn_name == 'rle' else lib.am_decode_delta
    args = [ptr, len(data)]
    if fn_name == 'rle':
        args.append(int(signed))
    args += [out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
             mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
             max(count, 1)]
    n = fn(*args)
    if n < 0:
        raise MalformedChange('malformed column')
    return out[:n], mask[:n].astype(bool)


def decode_rle_column(buf, signed=False):
    """Decode an entire RLE column to (values int64[], valid bool[])."""
    return _decode_column('rle', buf, signed)


def decode_delta_column(buf):
    """Decode a delta column to absolute values (values int64[], valid bool[])."""
    return _decode_column('delta', buf)


def decode_boolean_column(buf):
    return _decode_column('boolean', buf)


def ingest_changes(buffers, doc_ids, with_meta=False, with_seq=False,
                   blob=None, lens=None):
    """Batched native change ingest: parse N binary changes into flat op-row
    arrays (doc, key_id, packed_opid, value, flags) with C++-side dictionary
    encoding of keys and actors.

    Returns (rows dict, key_strings list, actor_hex list), or None if any
    change falls outside the fleet-kernel subset (caller falls back to the
    general host engine). With with_meta=True, a fourth element carries
    per-change header metadata (the whole hash-graph feed: SHA-256 hash with
    checksum verification, deps, actor/seq/startOp/time/message, op counts)
    so no Python-side header decode is needed. With with_seq=True, the
    parser also accepts sequence ops (insert/set/del/inc on sequence
    objects), make ops at map keys (root or nested), and keyed set/del/inc
    on nested map/table objects; the rows dict gains obj/ref/vtype columns
    (packed containing objectId — 0 = root, packed referent elemId, wire
    value-type tag); flags extend to 3=seq insert, 4=seq set, 5=seq del,
    6=seq inc, 7=makeText, 8=makeList, 9=makeMap, 10=makeTable.

    doc_ids=None means the identity mapping (buffer i -> doc i, the
    turbo shape) and enables the zero-copy list entry: C walks the
    Python list's bytes objects in place — no blob join, no length
    array, no type scan (those Python-side passes cost more than the
    parse itself at fleet scale).

    The parse itself is chunk-parallel over the native thread pool with
    the GIL released (see the module docstring's multi-core contract);
    concurrent callers serialize on the module ingest lock."""
    with _span('native_parse', buffers=len(buffers), with_meta=with_meta,
               threads=_threads):
        with _ingest_lock:
            out = _ingest_changes(buffers, doc_ids, with_meta, with_seq,
                                  blob, lens)
            lib = _lib
            if lib is not None:
                _note_parse_stats(lib)
            return out


def _ingest_changes(buffers, doc_ids, with_meta, with_seq, blob, lens):
    lib = _load()
    if lib is None:
        return None
    i64 = ctypes.c_int64
    n_rows = None
    if doc_ids is None:
        if blob is None:
            plib = _load_pydll()
            if plib is not None and type(buffers) is list:
                # no Python-side type scan: the C entry PyBytes-checks
                # each item and returns -2 to select the blob path
                n_rows = plib.am_ingest_changes_list(
                    buffers, 1 if with_meta else 0, 1 if with_seq else 0)
                if n_rows == -2:
                    n_rows = None    # non-bytes item: blob path below
                elif n_rows < 0:
                    return None
        if n_rows is None:
            doc_ids = list(range(len(buffers)))
    if n_rows is None:
        n_bufs = len(buffers)
        if blob is None:
            bufs = buffers if all(type(b) is bytes for b in buffers) else \
                [bytes(b) for b in buffers]
            blob = b''.join(bufs)
            lens = np.fromiter(map(len, bufs), dtype=np.uint64, count=n_bufs)
        offsets = np.zeros(n_bufs, dtype=np.uint64)
        if n_bufs > 1:
            np.cumsum(lens[:-1], out=offsets[1:])
        docs = np.asarray(doc_ids, dtype=np.int32)
        arr, ptr = _u8(blob)
        lib.am_ingest_changes.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
        lib.am_ingest_changes.restype = i64
        n_rows = lib.am_ingest_changes(
            ptr, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            docs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(buffers), 1 if with_meta else 0, 1 if with_seq else 0)
        if n_rows < 0:
            return None
    metas = None
    preds = None
    seq_cols = None
    if with_meta:
        metas = _fetch_ingest_meta(lib, len(buffers))
        if metas is None:
            return None
        preds = _fetch_ingest_preds(lib, int(n_rows))
        if preds is None:
            return None
    if with_seq:
        i32p_ = ctypes.POINTER(ctypes.c_int32)
        u8p_ = ctypes.POINTER(ctypes.c_uint8)
        obj = np.zeros(max(int(n_rows), 1), dtype=np.int32)
        ref = np.zeros(max(int(n_rows), 1), dtype=np.int32)
        vtype = np.zeros(max(int(n_rows), 1), dtype=np.uint8)
        lib.am_ingest_seq_fetch.argtypes = [i32p_, i32p_, u8p_]
        lib.am_ingest_seq_fetch.restype = i64
        got = lib.am_ingest_seq_fetch(
            obj.ctypes.data_as(i32p_), ref.ctypes.data_as(i32p_),
            vtype.ctypes.data_as(u8p_))
        if got < 0:
            return None
        seq_cols = (obj[:int(n_rows)], ref[:int(n_rows)],
                    vtype[:int(n_rows)])
        # boxed-value passthrough: per-row wire byte lengths + raw arena
        lib.am_ingest_val_size.argtypes = []
        lib.am_ingest_val_size.restype = i64
        arena_size = int(lib.am_ingest_val_size())
        if arena_size < 0:
            return None
        vlen = np.zeros(max(int(n_rows), 1), dtype=np.int32)
        arena = np.zeros(max(arena_size, 1), dtype=np.uint8)
        lib.am_ingest_val_fetch.argtypes = [i32p_, u8p_, ctypes.c_uint64]
        lib.am_ingest_val_fetch.restype = i64
        if lib.am_ingest_val_fetch(vlen.ctypes.data_as(i32p_),
                                   arena.ctypes.data_as(u8p_),
                                   arena.size) != arena_size:
            return None
        seq_cols = seq_cols + (vlen[:int(n_rows)],
                               arena[:arena_size].tobytes())
    n = max(int(n_rows), 1)
    doc = np.zeros(n, dtype=np.int32)
    key = np.zeros(n, dtype=np.int32)
    packed = np.zeros(n, dtype=np.int32)
    val = np.zeros(n, dtype=np.int32)
    flags = np.zeros(n, dtype=np.uint8)
    kb_used = i64(0)
    ab_used = i64(0)
    lib.am_ingest_blob_sizes.argtypes = [ctypes.POINTER(i64),
                                         ctypes.POINTER(i64)]
    lib.am_ingest_blob_sizes.restype = i64
    if lib.am_ingest_blob_sizes(ctypes.byref(kb_used),
                                ctypes.byref(ab_used)) < 0:
        return None
    key_blob = np.empty(max(int(kb_used.value), 1), dtype=np.uint8)
    actor_blob = np.empty(max(int(ab_used.value), 1), dtype=np.uint8)
    n_keys = i64(0)
    n_actors = i64(0)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.am_ingest_fetch.argtypes = [i32p, i32p, i32p, i32p, u8p, u8p,
                                    ctypes.c_uint64, ctypes.POINTER(i64),
                                    u8p, ctypes.c_uint64, ctypes.POINTER(i64)]
    lib.am_ingest_fetch.restype = i64
    ret = lib.am_ingest_fetch(
        doc.ctypes.data_as(i32p), key.ctypes.data_as(i32p),
        packed.ctypes.data_as(i32p), val.ctypes.data_as(i32p),
        flags.ctypes.data_as(u8p), key_blob.ctypes.data_as(u8p),
        key_blob.size, ctypes.byref(n_keys),
        actor_blob.ctypes.data_as(u8p), actor_blob.size,
        ctypes.byref(n_actors))
    if ret < 0:
        raise ValueError('ingest fetch failed')

    def read_blob(blob_arr, count):
        from ..encoding import Decoder
        decoder = Decoder(blob_arr.tobytes())
        return [decoder.read_prefixed_string() for _ in range(count)]

    keys = read_blob(key_blob, int(n_keys.value))
    actors = read_blob(actor_blob, int(n_actors.value))
    rows = {'doc': doc[:int(n_rows)], 'key': key[:int(n_rows)],
            'packed': packed[:int(n_rows)], 'value': val[:int(n_rows)],
            'flags': flags[:int(n_rows)]}
    if seq_cols is not None:
        (rows['obj'], rows['ref'], rows['vtype'], rows['vlen'],
         rows['vblob']) = seq_cols
    if with_meta:
        rows['pred_off'], rows['pred'] = preds
        return rows, keys, actors, metas
    return rows, keys, actors


def _fetch_ingest_preds(lib, n_rows):
    """Copy out per-op pred lists (packed opIds with native actor numbers).
    Must run before am_ingest_fetch."""
    i64 = ctypes.c_int64
    i64p = ctypes.POINTER(i64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.am_ingest_pred_count.argtypes = []
    lib.am_ingest_pred_count.restype = i64
    n_preds = int(lib.am_ingest_pred_count())
    if n_preds < 0:
        return None
    pred_off = np.zeros(max(n_rows, 1) + 1, dtype=np.int64)
    pred_blob = np.zeros(max(n_preds, 1), dtype=np.int32)
    lib.am_ingest_pred_fetch.argtypes = [i64p, i32p, ctypes.c_uint64]
    lib.am_ingest_pred_fetch.restype = i64
    got = lib.am_ingest_pred_fetch(
        pred_off.ctypes.data_as(i64p), pred_blob.ctypes.data_as(i32p),
        pred_blob.size)
    if got < 0:
        return None
    return pred_off[:n_rows + 1], pred_blob[:int(got)]


def _fetch_ingest_meta(lib, n_changes):
    """Copy out the per-change metadata captured by am_ingest_changes.
    Must run before am_ingest_fetch (which frees the native context)."""
    i64 = ctypes.c_int64
    i64p = ctypes.POINTER(i64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    n = max(n_changes, 1)
    actor = np.zeros(n, dtype=np.int32)
    seq = np.zeros(n, dtype=np.int64)
    start_op = np.zeros(n, dtype=np.int64)
    time = np.zeros(n, dtype=np.int64)
    nops = np.zeros(n, dtype=np.int64)
    hash32 = np.zeros(32 * n, dtype=np.uint8)
    deps_off = np.zeros(n + 1, dtype=np.int64)
    msg_off = np.zeros(n + 1, dtype=np.int64)
    buf_len = np.zeros(n, dtype=np.int64)
    deps_bytes = i64(0)
    msg_bytes = i64(0)
    lib.am_ingest_meta_sizes.argtypes = [i64p, i64p]
    lib.am_ingest_meta_sizes.restype = i64
    if lib.am_ingest_meta_sizes(ctypes.byref(deps_bytes),
                                ctypes.byref(msg_bytes)) < 0:
        return None
    deps_blob = np.zeros(max(int(deps_bytes.value), 1), dtype=np.uint8)
    msg_blob = np.zeros(max(int(msg_bytes.value), 1), dtype=np.uint8)
    lib.am_ingest_meta_fetch.argtypes = [
        i32p, i64p, i64p, i64p, i64p, u8p, i64p, u8p, ctypes.c_uint64,
        i64p, u8p, ctypes.c_uint64, i64p]
    lib.am_ingest_meta_fetch.restype = i64
    got = lib.am_ingest_meta_fetch(
        actor.ctypes.data_as(i32p), seq.ctypes.data_as(i64p),
        start_op.ctypes.data_as(i64p), time.ctypes.data_as(i64p),
        nops.ctypes.data_as(i64p), hash32.ctypes.data_as(u8p),
        deps_off.ctypes.data_as(i64p), deps_blob.ctypes.data_as(u8p),
        deps_blob.size, msg_off.ctypes.data_as(i64p),
        msg_blob.ctypes.data_as(u8p), msg_blob.size,
        buf_len.ctypes.data_as(i64p))
    if got != n_changes:
        return None
    # Raw arrays/blobs only — hex strings and per-change dicts are built
    # lazily by the caller (most changes never need them on the fast path)
    return {
        'actor': actor[:n_changes], 'seq': seq[:n_changes],
        'startOp': start_op[:n_changes], 'time': time[:n_changes],
        'nops': nops[:n_changes], 'hash32': hash32.reshape(n, 32)[:n_changes],
        'deps_off': deps_off[:n_changes + 1],
        'deps_blob': deps_blob[:32 * int(deps_off[n_changes])].tobytes(),
        'msg_off': msg_off[:n_changes + 1],
        'msg_blob': msg_blob[:int(msg_off[n_changes])].tobytes(),
        'buf_len': buf_len[:n_changes],
    }


def turbo_gate(doc_off, actor, seq, hash32, deps_off, deps_blob,
               head32, head_n):
    """Batched linear-chain causal gate (codec.cpp am_turbo_gate): the
    whole batch's deps-present / heads-match / seq-contiguity checks in
    one native call over the extractor's hash lanes, GIL released.

    Inputs are the am_ingest_changes meta arrays plus the fleet's
    columnar per-doc head state (head32 rows gathered for this batch's
    docs; head_n outside {0, 1} routes that doc's first-change deps
    check back to the host). Returns None when the codec is
    unavailable, else ``(doc_ok, doc_hostcheck, g_doc, g_actor,
    g_first, g_last)`` — per-doc verdict bools plus the per-(doc,
    actor) seq-run group records whose ``g_first`` the caller checks
    against its clock columns (and whose ``g_last`` it scatters back
    as the clock advance)."""
    lib = _load()
    if lib is None:
        return None
    i64 = ctypes.c_int64
    i64p = ctypes.POINTER(i64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    if not hasattr(lib, '_turbo_gate_ready'):
        lib.am_turbo_gate.argtypes = [
            i64p, i32p, i64p, u8p, i64p, u8p, u8p, i32p,
            i64, i64, i64,
            u8p, u8p, i32p, i32p, i64p, i64p]
        lib.am_turbo_gate.restype = i64
        lib._turbo_gate_ready = True
    n_docs = len(doc_off) - 1
    n_changes = len(actor)
    doc_off = np.ascontiguousarray(doc_off, dtype=np.int64)
    actor = np.ascontiguousarray(actor, dtype=np.int32)
    seq = np.ascontiguousarray(seq, dtype=np.int64)
    hash32 = np.ascontiguousarray(hash32, dtype=np.uint8)
    deps_off = np.ascontiguousarray(deps_off, dtype=np.int64)
    deps_arr = np.frombuffer(deps_blob, dtype=np.uint8) \
        if isinstance(deps_blob, (bytes, bytearray)) else \
        np.ascontiguousarray(deps_blob, dtype=np.uint8)
    if deps_arr.size == 0:
        deps_arr = np.zeros(1, dtype=np.uint8)
    head32 = np.ascontiguousarray(head32, dtype=np.uint8)
    head_n = np.ascontiguousarray(head_n, dtype=np.int32)
    # the actor column's ids are dense interned indexes; the scratch
    # tables size to the max id + 1
    n_actors = int(actor.max()) + 1 if n_changes else 1
    doc_ok = np.zeros(max(n_docs, 1), dtype=np.uint8)
    hostcheck = np.zeros(max(n_docs, 1), dtype=np.uint8)
    cap = max(n_changes, 1)
    g_doc = np.zeros(cap, dtype=np.int32)
    g_actor = np.zeros(cap, dtype=np.int32)
    g_first = np.zeros(cap, dtype=np.int64)
    g_last = np.zeros(cap, dtype=np.int64)
    n_groups = lib.am_turbo_gate(
        doc_off.ctypes.data_as(i64p), actor.ctypes.data_as(i32p),
        seq.ctypes.data_as(i64p), hash32.ctypes.data_as(u8p),
        deps_off.ctypes.data_as(i64p), deps_arr.ctypes.data_as(u8p),
        head32.ctypes.data_as(u8p), head_n.ctypes.data_as(i32p),
        n_docs, n_changes, n_actors,
        doc_ok.ctypes.data_as(u8p), hostcheck.ctypes.data_as(u8p),
        g_doc.ctypes.data_as(i32p), g_actor.ctypes.data_as(i32p),
        g_first.ctypes.data_as(i64p), g_last.ctypes.data_as(i64p))
    if n_groups < 0:
        return None
    k = int(n_groups)
    return (doc_ok[:n_docs].astype(bool), hostcheck[:n_docs].astype(bool),
            g_doc[:k], g_actor[:k], g_first[:k], g_last[:k])


def parse_documents(buffers):
    """Batched native document-container parse (ref columnar.js:1006-1047):
    one call parses N saved documents straight to flat columns — per-doc
    actor tables / heads / maxOp, per-change (actor, seq, maxOp) metadata,
    and document-order op rows with succ lists — with no per-change
    re-encode or hashing (the deferred-hash-graph load of ref
    new.js:1709-1749).

    Returns None when the native codec is unavailable, else a dict:
      ok          [N] uint8   1 = parsed; 0 = doc needs the Python path
      n_changes / n_ops / max_op   [N] int64 per doc
      heads_off   [N+1] int64 into heads
      heads       [H, 32] uint8 head hashes
      actor_off   [N+1] int64 into doc_actors
      doc_actors  [.] int32   per-doc actor tables (global actor numbers)
      c_doc/c_actor [C] int32, c_seq/c_max_op [C] int64 per change
      op columns  [M]: doc(i32), obj_ctr(i64), obj_actor(i32, -1=root),
                  key_ctr(i64), key_actor(i32, -1=none), key_str(i32,
                  -1=none), insert(u8), action(u8), vtype(u8), id_ctr(i64),
                  id_actor(i32), val_int(i64; int-family value or single
                  text codepoint, -1 = multi-char), val_off(i64)/val_len(i32)
                  into val_blob, succ_off [M+1] int64 into succ_ctr(i64)/
                  succ_actor(i32)
      val_blob    raw value bytes; actors / keys: global string tables
    Actions are wire numbers (0 makeMap, 1 set, 2 makeList, 4 makeText,
    5 inc, 6 makeTable); del rows never appear in documents
    (columnar.js:892)."""
    with _span('native_doc_parse', buffers=len(buffers)):
        return _parse_documents(buffers)


def _parse_documents(buffers):
    lib = _load()
    if lib is None:
        return None
    # same unowned-buffer discipline as _extract_changes: memoryviews
    # (mmap'd parked chunks on the revive path) join without a
    # per-buffer copy, and a single doc parses fully in place
    bufs = buffers if all(type(b) is bytes for b in buffers) else \
        [b if type(b) is bytes or isinstance(b, memoryview) else bytes(b)
         for b in buffers]
    n_docs = len(bufs)
    blob = bufs[0] if n_docs == 1 else b''.join(bufs)
    lens = np.fromiter(map(len, bufs), dtype=np.uint64, count=n_docs)
    offsets = np.zeros(max(n_docs, 1), dtype=np.uint64)
    if n_docs > 1:
        np.cumsum(lens[:-1], out=offsets[1:])
    arr, ptr = _u8(blob)
    u8p_ = ctypes.POINTER(ctypes.c_uint8)
    u64p_ = ctypes.POINTER(ctypes.c_uint64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.am_parse_documents.argtypes = [u8p_, u64p_, u64p_, ctypes.c_uint64]
    lib.am_parse_documents.restype = ctypes.c_int64
    if n_docs == 0:
        lens_arr = np.zeros(1, dtype=np.uint64)
    else:
        lens_arr = lens
    n_ops = int(lib.am_parse_documents(
        ptr, offsets.ctypes.data_as(u64p_),
        lens_arr.ctypes.data_as(u64p_), n_docs))
    if n_ops < 0:
        return None
    sizes = [ctypes.c_int64() for _ in range(9)]
    lib.am_docparse_sizes.argtypes = [i64p] * 9
    lib.am_docparse_sizes.restype = ctypes.c_int64
    if lib.am_docparse_sizes(*(ctypes.byref(s) for s in sizes)) != 0:
        return None
    (n_changes, n_succ, n_heads, val_bytes, actor_blob_bytes, n_actors,
     key_blob_bytes, n_keys, n_doc_actors) = (int(s.value) for s in sizes)

    def a(n, dtype):
        return np.zeros(max(n, 1), dtype=dtype)

    d_ok = a(n_docs, np.uint8)
    d_n_changes, d_n_ops, d_max_op = (a(n_docs, np.int64) for _ in range(3))
    d_heads_off = a(n_docs + 1, np.int64)
    d_actor_off = a(n_docs + 1, np.int64)
    d_actor_ids = a(n_doc_actors, np.int32)
    heads = a(n_heads * 32, np.uint8)
    c_doc, c_actor = a(n_changes, np.int32), a(n_changes, np.int32)
    c_seq, c_max_op = a(n_changes, np.int64), a(n_changes, np.int64)
    o_doc = a(n_ops, np.int32)
    o_obj_ctr = a(n_ops, np.int64)
    o_obj_actor = a(n_ops, np.int32)
    o_key_ctr = a(n_ops, np.int64)
    o_key_actor = a(n_ops, np.int32)
    o_key_str = a(n_ops, np.int32)
    o_insert, o_action, o_vtype = (a(n_ops, np.uint8) for _ in range(3))
    o_id_ctr = a(n_ops, np.int64)
    o_id_actor = a(n_ops, np.int32)
    o_val_int, o_val_off = a(n_ops, np.int64), a(n_ops, np.int64)
    o_val_len = a(n_ops, np.int32)
    val_blob = a(val_bytes, np.uint8)
    o_succ_off = a(n_ops + 1, np.int64)
    s_ctr, s_actor = a(n_succ, np.int64), a(n_succ, np.int32)
    key_blob = a(key_blob_bytes, np.uint8)
    actor_blob = a(actor_blob_bytes, np.uint8)

    lib.am_docparse_fetch.argtypes = [
        u8p_, i64p, i64p, i64p, i64p, i64p, i32p, u8p_,
        i32p, i32p, i64p, i64p,
        i32p, i64p, i32p, i64p, i32p, i32p, u8p_, u8p_, u8p_,
        i64p, i32p, i64p, i64p, i32p, u8p_, i64p, i64p, i32p,
        u8p_, ctypes.c_uint64, u8p_, ctypes.c_uint64]
    lib.am_docparse_fetch.restype = ctypes.c_int64
    got = lib.am_docparse_fetch(
        d_ok.ctypes.data_as(u8p_), d_n_changes.ctypes.data_as(i64p),
        d_n_ops.ctypes.data_as(i64p), d_max_op.ctypes.data_as(i64p),
        d_heads_off.ctypes.data_as(i64p), d_actor_off.ctypes.data_as(i64p),
        d_actor_ids.ctypes.data_as(i32p), heads.ctypes.data_as(u8p_),
        c_doc.ctypes.data_as(i32p), c_actor.ctypes.data_as(i32p),
        c_seq.ctypes.data_as(i64p), c_max_op.ctypes.data_as(i64p),
        o_doc.ctypes.data_as(i32p), o_obj_ctr.ctypes.data_as(i64p),
        o_obj_actor.ctypes.data_as(i32p), o_key_ctr.ctypes.data_as(i64p),
        o_key_actor.ctypes.data_as(i32p), o_key_str.ctypes.data_as(i32p),
        o_insert.ctypes.data_as(u8p_), o_action.ctypes.data_as(u8p_),
        o_vtype.ctypes.data_as(u8p_), o_id_ctr.ctypes.data_as(i64p),
        o_id_actor.ctypes.data_as(i32p), o_val_int.ctypes.data_as(i64p),
        o_val_off.ctypes.data_as(i64p), o_val_len.ctypes.data_as(i32p),
        val_blob.ctypes.data_as(u8p_), o_succ_off.ctypes.data_as(i64p),
        s_ctr.ctypes.data_as(i64p), s_actor.ctypes.data_as(i32p),
        key_blob.ctypes.data_as(u8p_), key_blob.size,
        actor_blob.ctypes.data_as(u8p_), actor_blob.size)
    if got != n_ops:
        return None

    def read_blob(blob_arr, count):
        from ..encoding import Decoder
        decoder = Decoder(blob_arr.tobytes())
        return [decoder.read_prefixed_string() for _ in range(count)]

    return {
        'ok': d_ok[:n_docs], 'n_changes': d_n_changes[:n_docs],
        'n_ops': d_n_ops[:n_docs], 'max_op': d_max_op[:n_docs],
        'heads_off': d_heads_off[:n_docs + 1],
        'heads': heads[:n_heads * 32].reshape(max(n_heads, 1) if n_heads
                                              else 0, 32),
        'actor_off': d_actor_off[:n_docs + 1],
        'doc_actors': d_actor_ids[:n_doc_actors],
        'c_doc': c_doc[:n_changes], 'c_actor': c_actor[:n_changes],
        'c_seq': c_seq[:n_changes], 'c_max_op': c_max_op[:n_changes],
        'doc': o_doc[:n_ops], 'obj_ctr': o_obj_ctr[:n_ops],
        'obj_actor': o_obj_actor[:n_ops], 'key_ctr': o_key_ctr[:n_ops],
        'key_actor': o_key_actor[:n_ops], 'key_str': o_key_str[:n_ops],
        'insert': o_insert[:n_ops], 'action': o_action[:n_ops],
        'vtype': o_vtype[:n_ops], 'id_ctr': o_id_ctr[:n_ops],
        'id_actor': o_id_actor[:n_ops], 'val_int': o_val_int[:n_ops],
        'val_off': o_val_off[:n_ops], 'val_len': o_val_len[:n_ops],
        'val_blob': val_blob[:val_bytes].tobytes(),
        'succ_off': o_succ_off[:n_ops + 1], 'succ_ctr': s_ctr[:n_succ],
        'succ_actor': s_actor[:n_succ],
        'actors': read_blob(actor_blob, n_actors),
        'keys': read_blob(key_blob, n_keys),
    }


def build_document(change_buffers, heads):
    """Native mirror-free save (ref columnar.js:983-1004 + the canonical
    ordering of op_set.OpSet.save): parse the doc's change log, replay into
    a succ-annotated op store, and serialize the canonical document chunk —
    all in C++. `heads` are hex hash strings. Returns the container bytes,
    or None when the log needs the Python path (link/child ops, unknown
    columns, or no native codec)."""
    lib = _load()
    if lib is None or not change_buffers:
        return None
    bufs = [bytes(b) for b in change_buffers]
    blob = b''.join(bufs)
    lens = np.fromiter(map(len, bufs), dtype=np.uint64, count=len(bufs))
    offsets = np.zeros(len(bufs), dtype=np.uint64)
    if len(bufs) > 1:
        np.cumsum(lens[:-1], out=offsets[1:])
    heads_blob = b''.join(bytes.fromhex(h) for h in heads)
    arr, ptr = _u8(blob)
    harr, hptr = _u8(heads_blob)
    u8p_ = ctypes.POINTER(ctypes.c_uint8)
    u64p_ = ctypes.POINTER(ctypes.c_uint64)
    lib.am_build_document.argtypes = [u8p_, u64p_, u64p_, ctypes.c_uint64,
                                      u8p_, ctypes.c_uint64]
    lib.am_build_document.restype = ctypes.c_int64
    lib.am_build_fetch.argtypes = [u8p_, ctypes.c_uint64]
    lib.am_build_fetch.restype = ctypes.c_int64
    size = int(lib.am_build_document(
        ptr, offsets.ctypes.data_as(u64p_), lens.ctypes.data_as(u64p_),
        len(bufs), hptr, len(heads)))
    if size < 0:
        return None
    out = np.zeros(max(size, 1), dtype=np.uint8)
    got = int(lib.am_build_fetch(out.ctypes.data_as(u8p_), out.size))
    if got != size:
        return None
    return out[:size].tobytes()


def extract_changes(buffers):
    """Native change-list extraction (the delta+main materialize kernel,
    inverse of build_document): each document chunk splits into its
    canonical per-change chunks + SHA-256 hashes + per-change maxOp,
    byte-identical to Python's ``decode_document`` + ``encode_change``
    round trip, with the header heads verified against the re-encoded
    hash frontier. Docs are independent, so the batch fans over the
    native thread pool with byte-identical output at every width.

    Returns None when the native codec is unavailable, else a list with
    one entry per input doc: ``(chunks, hashes, max_ops)`` — lists of
    change-chunk bytes, hex hash strings, and ints — or None for docs
    the extractor routed to the Python path (unknown columns, link ops,
    non-canonical payloads, or any integrity failure: the Python
    fallback reproduces the exact typed verdict)."""
    with _span('native_doc_extract', buffers=len(buffers)):
        return _extract_changes(buffers)


def _extract_changes(buffers):
    lib = _load()
    if lib is None:
        return None
    # buffer-protocol inputs pass through unowned (memoryviews into the
    # storage engine's mmap'd segments included): a single doc reads in
    # place with ZERO copies; a multi-doc batch pays exactly one join
    bufs = [b if type(b) is bytes or isinstance(b, memoryview)
            else bytes(b) for b in buffers]
    n_docs = len(bufs)
    if n_docs == 0:
        return []
    blob = bufs[0] if n_docs == 1 else b''.join(bufs)
    lens = np.fromiter(map(len, bufs), dtype=np.uint64, count=n_docs)
    offsets = np.zeros(n_docs, dtype=np.uint64)
    if n_docs > 1:
        np.cumsum(lens[:-1], out=offsets[1:])
    arr, ptr = _u8(blob)
    u8p_ = ctypes.POINTER(ctypes.c_uint8)
    u64p_ = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.am_extract_changes.argtypes = [u8p_, u64p_, u64p_, ctypes.c_uint64]
    lib.am_extract_changes.restype = ctypes.c_int64
    lib.am_extract_sizes.argtypes = [i64p, i64p]
    lib.am_extract_sizes.restype = ctypes.c_int64
    lib.am_extract_fetch.argtypes = [u8p_, i64p, i64p, u8p_, u8p_, i64p]
    lib.am_extract_fetch.restype = ctypes.c_int64
    total = int(lib.am_extract_changes(
        ptr, offsets.ctypes.data_as(u64p_), lens.ctypes.data_as(u64p_),
        n_docs))
    if total < 0:
        return None
    tc, tb = ctypes.c_int64(), ctypes.c_int64()
    if lib.am_extract_sizes(ctypes.byref(tc), ctypes.byref(tb)) != 0:
        return None
    n_changes, blob_bytes = int(tc.value), int(tb.value)
    ok = np.zeros(max(n_docs, 1), dtype=np.uint8)
    d_off = np.zeros(n_docs + 1, dtype=np.int64)
    c_off = np.zeros(n_changes + 1, dtype=np.int64)
    out_blob = np.zeros(max(blob_bytes, 1), dtype=np.uint8)
    hashes = np.zeros(max(32 * n_changes, 1), dtype=np.uint8)
    max_ops = np.zeros(max(n_changes, 1), dtype=np.int64)
    got = int(lib.am_extract_fetch(
        ok.ctypes.data_as(u8p_), d_off.ctypes.data_as(i64p),
        c_off.ctypes.data_as(i64p), out_blob.ctypes.data_as(u8p_),
        hashes.ctypes.data_as(u8p_), max_ops.ctypes.data_as(i64p)))
    if got != n_changes:
        return None
    blob_b = out_blob[:blob_bytes].tobytes()
    hash_hex = hashes[:32 * n_changes].tobytes().hex()
    out = []
    for d in range(n_docs):
        if not ok[d]:
            out.append(None)
            continue
        lo, hi = int(d_off[d]), int(d_off[d + 1])
        chunks = [blob_b[int(c_off[i]):int(c_off[i + 1])]
                  for i in range(lo, hi)]
        doc_hashes = [hash_hex[64 * i:64 * (i + 1)] for i in range(lo, hi)]
        doc_max_ops = [int(m) for m in max_ops[lo:hi]]
        out.append((chunks, doc_hashes, doc_max_ops))
    return out
