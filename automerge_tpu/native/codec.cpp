// Native codec kernels for automerge_tpu.
//
// The components the JS reference delegates to npm packages (SHA-256 via
// fast-sha256, DEFLATE via pako) plus its hand-rolled LEB128/RLE/delta/
// boolean column codecs (ref backend/encoding.js) are implemented here as
// first-class C++ host kernels (SURVEY.md section 2.9). Column decoders emit
// int64 value arrays + validity masks directly, so binary changes decode
// straight into the padded tensors the fleet engine consumes.
//
// Exposed as a plain C ABI consumed from Python via ctypes.

// Python.h must precede every standard header (it sets libc feature-test
// macros); it is optional — without CPython headers everything except the
// zero-copy list ingest entry still builds (platform-independent: not
// tied to the x86 SIMD guard below).
#if defined(__has_include)
#if __has_include(<Python.h>)
#define AM_HAVE_PYTHON 1
#include <Python.h>
#endif
#endif

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <zlib.h>
#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#include <cpuid.h>
#define AM_HAVE_X86 1
#endif

// memcpy with a null pointer is UB even when n == 0 (glibc declares both
// arguments nonnull, and UBSan's nonnull check fires), and an empty
// std::vector's data() is exactly such a null — which every *_fetch
// entry hits when a hostile batch parses to zero rows. All bulk copies
// funnel through this guard.
static inline void copy_bytes(void *dst, const void *src, size_t n) {
  if (n && dst && src) memcpy(dst, src, n);
}

extern "C" {

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4), compact single-shot implementation
// ---------------------------------------------------------------------------

static const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

static void sha256_block(uint32_t state[8], const uint8_t *p) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++) {
    w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
           (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
  }
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K256[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

#ifdef AM_HAVE_X86
// SHA-NI block loop (Intel SHA extensions; FIPS 180-4 schedule expressed
// through sha256msg1/msg2 + sha256rnds2). Function-level target attribute so
// the rest of the TU stays baseline; dispatched behind a cpuid check.
__attribute__((target("sha,sse4.1,ssse3")))
static void sha256_blocks_shani(uint32_t state[8], const uint8_t *data,
                                uint64_t nblocks) {
#define AM_K4(i)                                                            \
  _mm_set_epi32(int(K256[(i) + 3]), int(K256[(i) + 2]), int(K256[(i) + 1]), \
                int(K256[(i)]))
  const __m128i MASK =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
  __m128i TMP = _mm_loadu_si128((const __m128i *)&state[0]);
  __m128i STATE1 = _mm_loadu_si128((const __m128i *)&state[4]);
  TMP = _mm_shuffle_epi32(TMP, 0xB1);
  STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);
  __m128i STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);
  STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0);

  while (nblocks--) {
    const __m128i ABEF_SAVE = STATE0;
    const __m128i CDGH_SAVE = STATE1;
    __m128i MSG, MSG0, MSG1, MSG2, MSG3;

    /* rounds 0-3 */
    MSG0 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(data + 0)), MASK);
    MSG = _mm_add_epi32(MSG0, AM_K4(0));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

    /* rounds 4-7 */
    MSG1 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(data + 16)), MASK);
    MSG = _mm_add_epi32(MSG1, AM_K4(4));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    MSG0 = _mm_sha256msg1_epu32(MSG0, MSG1);

    /* rounds 8-11 */
    MSG2 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(data + 32)), MASK);
    MSG = _mm_add_epi32(MSG2, AM_K4(8));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    MSG1 = _mm_sha256msg1_epu32(MSG1, MSG2);

    /* rounds 12-15 */
    MSG3 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i *)(data + 48)), MASK);
    MSG = _mm_add_epi32(MSG3, AM_K4(12));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    TMP = _mm_alignr_epi8(MSG3, MSG2, 4);
    MSG0 = _mm_add_epi32(MSG0, TMP);
    MSG0 = _mm_sha256msg2_epu32(MSG0, MSG3);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);
    MSG2 = _mm_sha256msg1_epu32(MSG2, MSG3);

#define AM_ROUND4(W0, W1, W2, W3, i, do_msg1)                   \
    MSG = _mm_add_epi32(W0, AM_K4(i));                          \
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);        \
    TMP = _mm_alignr_epi8(W0, W3, 4);                           \
    W1 = _mm_add_epi32(W1, TMP);                                \
    W1 = _mm_sha256msg2_epu32(W1, W0);                          \
    MSG = _mm_shuffle_epi32(MSG, 0x0E);                         \
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);        \
    if (do_msg1) W3 = _mm_sha256msg1_epu32(W3, W0);

    AM_ROUND4(MSG0, MSG1, MSG2, MSG3, 16, 1)   /* rounds 16-19 */
    AM_ROUND4(MSG1, MSG2, MSG3, MSG0, 20, 1)   /* rounds 20-23 */
    AM_ROUND4(MSG2, MSG3, MSG0, MSG1, 24, 1)   /* rounds 24-27 */
    AM_ROUND4(MSG3, MSG0, MSG1, MSG2, 28, 1)   /* rounds 28-31 */
    AM_ROUND4(MSG0, MSG1, MSG2, MSG3, 32, 1)   /* rounds 32-35 */
    AM_ROUND4(MSG1, MSG2, MSG3, MSG0, 36, 1)   /* rounds 36-39 */
    AM_ROUND4(MSG2, MSG3, MSG0, MSG1, 40, 1)   /* rounds 40-43 */
    AM_ROUND4(MSG3, MSG0, MSG1, MSG2, 44, 1)   /* rounds 44-47 */
    AM_ROUND4(MSG0, MSG1, MSG2, MSG3, 48, 1)   /* rounds 48-51 */
    AM_ROUND4(MSG1, MSG2, MSG3, MSG0, 52, 0)   /* rounds 52-55 */
    AM_ROUND4(MSG2, MSG3, MSG0, MSG1, 56, 0)   /* rounds 56-59 */
#undef AM_ROUND4

    /* rounds 60-63 */
    MSG = _mm_add_epi32(MSG3, AM_K4(60));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, MSG);
    MSG = _mm_shuffle_epi32(MSG, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, MSG);

    STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
    STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);
    data += 64;
  }

  TMP = _mm_shuffle_epi32(STATE0, 0x1B);
  STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);
  STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0);
  STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);
  _mm_storeu_si128((__m128i *)&state[0], STATE0);
  _mm_storeu_si128((__m128i *)&state[4], STATE1);
#undef AM_K4
}

// Raw cpuid instead of __builtin_cpu_supports("sha"): not every GCC in the
// field accepts "sha" as a builtin feature name (g++ 10 rejects it at
// compile time, taking the whole codec — and the turbo seam — down with it).
// SHA extensions: CPUID.(EAX=7,ECX=0):EBX bit 29; SSE4.1: CPUID.1:ECX bit
// 19; SSSE3: CPUID.1:ECX bit 9.
static bool have_shani() {
  static const bool v = [] {
    unsigned eax = 0, ebx = 0, ecx = 0, edx = 0;
    if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx)) return false;
    if (!(ebx & (1u << 29))) return false;
    if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx)) return false;
    return (ecx & (1u << 19)) != 0 && (ecx & (1u << 9)) != 0;
  }();
  return v;
}
#endif  // AM_HAVE_X86

static void sha256_blocks(uint32_t state[8], const uint8_t *data,
                          uint64_t nblocks) {
#ifdef AM_HAVE_X86
  if (have_shani()) {
    sha256_blocks_shani(state, data, nblocks);
    return;
  }
#endif
  for (uint64_t i = 0; i < nblocks; i++) sha256_block(state, data + 64 * i);
}

// Streaming context so multi-part inputs (chunk header + body) hash without
// concatenating into a scratch buffer.
struct Sha256Stream {
  uint32_t st[8];
  uint8_t buf[64];
  uint64_t total = 0;
  uint32_t buffered = 0;
};

static void sha256_stream_init(Sha256Stream &s) {
  static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                   0xa54ff53a, 0x510e527f, 0x9b05688c,
                                   0x1f83d9ab, 0x5be0cd19};
  copy_bytes(s.st, init, sizeof(init));
  s.total = 0;
  s.buffered = 0;
}

static void sha256_stream_update(Sha256Stream &s, const uint8_t *p,
                                 uint64_t n) {
  s.total += n;
  if (s.buffered) {
    uint64_t take = 64 - s.buffered < n ? 64 - s.buffered : n;
    copy_bytes(s.buf + s.buffered, p, take);
    s.buffered += uint32_t(take);
    p += take;
    n -= take;
    if (s.buffered == 64) {
      sha256_blocks(s.st, s.buf, 1);
      s.buffered = 0;
    }
  }
  uint64_t full = n / 64;
  if (full) {
    sha256_blocks(s.st, p, full);
    p += 64 * full;
    n -= 64 * full;
  }
  if (n) {
    copy_bytes(s.buf, p, n);
    s.buffered = uint32_t(n);
  }
}

static void sha256_stream_final(Sha256Stream &s, uint8_t *out) {
  uint8_t tail[128];
  uint32_t rem = s.buffered;
  copy_bytes(tail, s.buf, rem);
  tail[rem] = 0x80;
  uint64_t tail_len = (rem + 9 <= 64) ? 64 : 128;
  memset(tail + rem + 1, 0, tail_len - rem - 9);
  uint64_t bits = s.total * 8;
  for (int i = 0; i < 8; i++)
    tail[tail_len - 1 - i] = uint8_t(bits >> (8 * i));
  sha256_blocks(s.st, tail, tail_len / 64);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = uint8_t(s.st[i] >> 24);
    out[4 * i + 1] = uint8_t(s.st[i] >> 16);
    out[4 * i + 2] = uint8_t(s.st[i] >> 8);
    out[4 * i + 3] = uint8_t(s.st[i]);
  }
}

// out must have room for 32 bytes
void am_sha256(const uint8_t *data, uint64_t len, uint8_t *out) {
  Sha256Stream s;
  sha256_stream_init(s);
  sha256_stream_update(s, data, len);
  sha256_stream_final(s, out);
}

// Defined next to the thread pool (below): fans the batch over the pool
// when it is worth it. Returns false when the caller should hash serially.
static bool sha256_batch_parallel(const uint8_t *data, const uint64_t *offsets,
                                  const uint64_t *lens, uint64_t n,
                                  uint8_t *out);

// Batched hashing: n buffers, each lens[i] bytes at data + offsets[i];
// out receives n * 32 bytes. The per-doc hash chains of a fleet are
// independent, so this parallelizes across documents (SURVEY.md section 7
// hard part 5: batch across docs, not within a doc) — long contiguous
// runs per worker keep the SHA-NI block loop hot instead of interleaving
// per-chunk state swaps.
void am_sha256_batch(const uint8_t *data, const uint64_t *offsets,
                     const uint64_t *lens, uint64_t n, uint8_t *out) {
  if (sha256_batch_parallel(data, offsets, lens, n, out)) return;
  for (uint64_t i = 0; i < n; i++) {
    am_sha256(data + offsets[i], lens[i], out + 32 * i);
  }
}

// ---------------------------------------------------------------------------
// Raw DEFLATE via zlib (the reference uses pako: columnar.js:1)
// ---------------------------------------------------------------------------

// Returns compressed size, or -1 on error. out_cap must be generous.
int64_t am_deflate_raw(const uint8_t *data, uint64_t len, uint8_t *out,
                       uint64_t out_cap) {
  z_stream zs;
  memset(&zs, 0, sizeof(zs));
  if (deflateInit2(&zs, 6, Z_DEFLATED, -15, 8, Z_DEFAULT_STRATEGY) != Z_OK)
    return -1;
  zs.next_in = const_cast<uint8_t *>(data);
  zs.avail_in = uInt(len);
  zs.next_out = out;
  zs.avail_out = uInt(out_cap);
  int ret = deflate(&zs, Z_FINISH);
  deflateEnd(&zs);
  if (ret != Z_STREAM_END) return -1;
  return int64_t(out_cap - zs.avail_out);
}

int64_t am_inflate_raw(const uint8_t *data, uint64_t len, uint8_t *out,
                       uint64_t out_cap) {
  z_stream zs;
  memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK) return -1;
  zs.next_in = const_cast<uint8_t *>(data);
  zs.avail_in = uInt(len);
  zs.next_out = out;
  zs.avail_out = uInt(out_cap);
  int ret = inflate(&zs, Z_FINISH);
  inflateEnd(&zs);
  if (ret != Z_STREAM_END) return -1;
  return int64_t(out_cap - zs.avail_out);
}

// ---------------------------------------------------------------------------
// LEB128 (ref encoding.js:97-230)
// ---------------------------------------------------------------------------

// Reads one unsigned LEB128; advances *pos; returns value or sets *err.
static inline uint64_t read_uleb(const uint8_t *buf, uint64_t len,
                                 uint64_t *pos, int *err) {
  uint64_t result = 0;
  int shift = 0;
  while (*pos < len) {
    uint8_t byte = buf[(*pos)++];
    if (shift >= 64) { *err = 1; return 0; }
    result |= uint64_t(byte & 0x7f) << shift;
    shift += 7;
    if ((byte & 0x80) == 0) return result;
  }
  *err = 1;
  return 0;
}

static inline int64_t read_sleb(const uint8_t *buf, uint64_t len,
                                uint64_t *pos, int *err) {
  // assembled unsigned: a signed left shift that reaches bit 63 is UB
  // (a 10-byte hostile varint put `42 << 63` here under UBSan), while
  // unsigned shifts just discard the overflow like the JS reference
  uint64_t result = 0;
  int shift = 0;
  while (*pos < len) {
    uint8_t byte = buf[(*pos)++];
    if (shift >= 64) { *err = 1; return 0; }
    result |= uint64_t(byte & 0x7f) << shift;
    shift += 7;
    if ((byte & 0x80) == 0) {
      if ((byte & 0x40) && shift < 64) result |= ~uint64_t(0) << shift;
      return int64_t(result);
    }
  }
  *err = 1;
  return 0;
}

// ---------------------------------------------------------------------------
// Column decoders (ref encoding.js RLEDecoder/DeltaDecoder/BooleanDecoder)
//
// Each decodes an entire column buffer into out[0..cap) int64 values with a
// validity mask (0 = null), returning the number of values decoded or -1 on
// malformed input / overflow. This is the "decode straight into padded
// arrays" path: the output arrays are reused as device-transfer staging.
// ---------------------------------------------------------------------------

int64_t am_decode_rle(const uint8_t *buf, uint64_t len, int is_signed,
                      int64_t *out, uint8_t *mask, int64_t cap) {
  uint64_t pos = 0;
  int64_t n = 0;
  int err = 0;
  int64_t last_value = 0;
  int have_last = 0, last_was_literal = 0, last_was_nulls = 0;
  while (pos < len) {
    int64_t count = read_sleb(buf, len, &pos, &err);
    if (err) return -1;
    if (count > 1) {
      int64_t value = is_signed ? read_sleb(buf, len, &pos, &err)
                                : int64_t(read_uleb(buf, len, &pos, &err));
      if (err) return -1;
      if (have_last && !last_was_nulls && last_value == value) return -1;
      // overflow-proof form of n + count > cap: cap - n never underflows
      // (n <= cap invariant), and a hostile count near INT64_MAX would
      // wrap a naive signed addition past the check
      if (count > cap - n) return -1;
      for (int64_t i = 0; i < count; i++) { out[n] = value; mask[n] = 1; n++; }
      last_value = value; have_last = 1; last_was_literal = 0; last_was_nulls = 0;
    } else if (count == 1) {
      return -1;  // repetition count of 1 is not allowed
    } else if (count < 0) {
      if (last_was_literal) return -1;  // successive literals not allowed
      if (count == INT64_MIN) return -1;  // -count would overflow (UB)
      int64_t m = -count;
      if (m > cap - n) return -1;
      for (int64_t i = 0; i < m; i++) {
        int64_t value = is_signed ? read_sleb(buf, len, &pos, &err)
                                  : int64_t(read_uleb(buf, len, &pos, &err));
        if (err) return -1;
        if (have_last && !last_was_nulls && value == last_value) return -1;
        out[n] = value; mask[n] = 1; n++;
        last_value = value; have_last = 1;
      }
      last_was_literal = 1; last_was_nulls = 0;
    } else {  // count == 0: null run
      if (last_was_nulls) return -1;
      uint64_t m = read_uleb(buf, len, &pos, &err);
      if (err || m == 0) return -1;
      if (m > uint64_t(cap - n)) return -1;  // uint64 space: no overflow
      for (uint64_t i = 0; i < m; i++) { out[n] = 0; mask[n] = 0; n++; }
      last_was_nulls = 1; last_was_literal = 0;
    }
  }
  return n;
}

int64_t am_decode_delta(const uint8_t *buf, uint64_t len, int64_t *out,
                        uint8_t *mask, int64_t cap) {
  // Delta = RLE('int') of successive differences; accumulate absolutes
  int64_t n = am_decode_rle(buf, len, 1, out, mask, cap);
  if (n < 0) return -1;
  int64_t absolute = 0;
  for (int64_t i = 0; i < n; i++) {
    if (mask[i]) {
      absolute += out[i];
      out[i] = absolute;
    }
  }
  return n;
}

// Returns the decoded count, -1 for malformed bytes, or -2 when the
// output capacity is too small (callers retry with a bigger buffer; a
// malformed column must NOT look like that, or hostile run counts send
// the retry loop into multi-GB allocations). The capacity check
// compares in uint64 space: a hostile LEB run count near 2^64 would
// overflow int64 and sail past a signed `n + count > cap` check — the
// classic heap-smash the wire fuzzer caught.
int64_t am_decode_boolean(const uint8_t *buf, uint64_t len, int64_t *out,
                          uint8_t *mask, int64_t cap) {
  uint64_t pos = 0;
  int64_t n = 0;
  int err = 0;
  int value = 0, first = 1;
  while (pos < len) {
    uint64_t count = read_uleb(buf, len, &pos, &err);
    if (err) return -1;
    if (count == 0 && !first) return -1;  // zero-length runs not allowed
    if (count > uint64_t(cap - n)) return -2;
    for (uint64_t i = 0; i < count; i++) { out[n] = value; mask[n] = 1; n++; }
    value = !value;
    first = 0;
  }
  return n;
}

// Counts values in an RLE/delta column without materializing them.
// Totals are capped at kMaxColumnValues: RLE expansion is unbounded by
// construction, so a few hostile bytes could otherwise declare 2^60
// values and turn the caller's allocation into a multi-GB DoS (or wrap
// the signed accumulator into a bogus non-negative count).
static const int64_t kMaxColumnValues = int64_t(1) << 26;

int64_t am_count_rle(const uint8_t *buf, uint64_t len, int is_signed) {
  uint64_t pos = 0;
  int64_t n = 0;
  int err = 0;
  while (pos < len) {
    int64_t count = read_sleb(buf, len, &pos, &err);
    if (err) return -1;
    if (count > 1) {
      if (is_signed) read_sleb(buf, len, &pos, &err);
      else read_uleb(buf, len, &pos, &err);
      if (err) return -1;
      if (count > kMaxColumnValues - n) return -1;
      n += count;
    } else if (count == 1) {
      return -1;
    } else if (count < 0) {
      if (count == INT64_MIN) return -1;  // -count would overflow (UB)
      for (int64_t i = 0; i < -count; i++) {
        if (is_signed) read_sleb(buf, len, &pos, &err);
        else read_uleb(buf, len, &pos, &err);
        if (err) return -1;
      }
      if (-count > kMaxColumnValues - n) return -1;
      n += -count;
    } else {
      uint64_t m = read_uleb(buf, len, &pos, &err);
      if (err) return -1;
      if (m > uint64_t(kMaxColumnValues - n)) return -1;
      n += int64_t(m);
    }
  }
  return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Batched change ingest: parse whole binary changes into fleet op rows.
//
// One call parses N change chunks (possibly DEFLATE-compressed), decodes
// their header + columns, dictionary-encodes map keys and actor ids, and
// emits flat op-row arrays ready to scatter into OpBatch tensors. This is
// the host runtime leg of the wire->device pipeline; doing it in C++ removes
// the per-change Python orchestration cost.
//
// Supports the fleet-kernel subset: root-map set/inc/del ops with integer
// values (LEB128 uint/int/counter/timestamp). Returns -1 if any change needs
// the general host engine.
// ---------------------------------------------------------------------------

#include <pthread.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <ctime>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// CLOCK_MONOTONIC nanoseconds — the SAME epoch CPython's
// time.perf_counter_ns() reads on Linux, so slice timings exported to the
// Python span ring line up with host-phase spans in one Perfetto timeline.
static int64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return int64_t(ts.tv_sec) * 1000000000ll + ts.tv_nsec;
}

// ---------------------------------------------------------------------------
// Persistent native thread pool (the multi-core parse engine).
//
// One pool per process, lazily spawned, sized by am_pool_configure (the
// Python wrapper feeds AUTOMERGE_TPU_NATIVE_THREADS). The caller thread
// participates as worker 0, so `threads` == concurrent lanes, not helper
// count. run() is a blocking fork-join over an atomic task counter; jobs
// are serialized by run_m_ (the codec's ingest contexts are single-flight
// anyway). All sync objects live behind pointers so the pthread_atfork
// child handler can abandon them wholesale: in a forked child the worker
// threads do not exist and any mutex held at fork time is locked forever —
// leaking a few kilobytes beats deadlocking the child's first parse.
// ---------------------------------------------------------------------------

constexpr int kMaxThreads = 64;

class NativePool {
 public:
  static NativePool &inst() {
    static NativePool *p = new NativePool();  // leaked: no exit-order races
    return *p;
  }

  int configure(int n) {
    if (n < 1) n = 1;
    if (n > kMaxThreads) n = kMaxThreads;
    std::lock_guard<std::mutex> rg(*run_m_);  // never mid-job
    std::unique_lock<std::mutex> lk(*m_);
    target_ = n;
    if (int(workers_->size()) > target_ - 1) {
      // shrink: stop everyone; they respawn lazily up to target-1
      stop_ = true;
      cv_->notify_all();
      lk.unlock();
      for (auto &t : *workers_) t.join();
      workers_->clear();
      lk.lock();
      stop_ = false;
    }
    return target_;
  }

  int threads() {
    std::lock_guard<std::mutex> lk(*m_);
    return target_;
  }

  // Run fn(task, worker) for every task in [0, n_tasks); caller included
  // as worker 0. Blocks until all tasks completed AND helpers are idle
  // (no straggler may observe the next job's half-written state).
  void run(int n_tasks, const std::function<void(int, int)> &fn) {
    if (n_tasks <= 0) return;
    std::lock_guard<std::mutex> rg(*run_m_);
    {
      std::unique_lock<std::mutex> lk(*m_);
      while (int(workers_->size()) < target_ - 1) {
        int widx = int(workers_->size()) + 1;
        workers_->emplace_back([this, widx] { worker_main(widx); });
      }
      cv_done_->wait(lk, [&] { return active_ == 0; });  // flush stragglers
      job_ = &fn;
      n_tasks_ = n_tasks;
      next_task_.store(0, std::memory_order_relaxed);
      completed_.store(0, std::memory_order_relaxed);
      gen_++;
      cv_->notify_all();
    }
    work(0);
    std::unique_lock<std::mutex> lk(*m_);
    cv_done_->wait(lk, [&] {
      return completed_.load(std::memory_order_acquire) >= n_tasks_ &&
             active_ == 0;
    });
    job_ = nullptr;
  }

  int64_t tasks() const { return tasks_total_.load(); }
  int64_t busy_ns() const { return busy_ns_total_.load(); }

  void reset_after_fork() {
    m_ = new std::mutex();
    cv_ = new std::condition_variable();
    cv_done_ = new std::condition_variable();
    run_m_ = new std::mutex();
    workers_ = new std::vector<std::thread>();  // old handles abandoned
    active_ = 0;
    stop_ = false;
  }

 private:
  NativePool() {
    reset_after_fork();  // initial allocation of the sync objects
    pthread_atfork(nullptr, nullptr, [] { inst().reset_after_fork(); });
  }

  void worker_main(int widx) {
    std::unique_lock<std::mutex> lk(*m_);
    // seen = 0, NOT gen_: a worker spawned by run() first acquires the
    // mutex after the spawning job's gen bump — reading gen_ here would
    // make it sleep through that job (entering a finished job's state is
    // safe: the exhausted task counter bounces it straight back to wait)
    int64_t seen = 0;
    for (;;) {
      cv_->wait(lk, [&] { return stop_ || gen_ != seen; });
      if (stop_) return;
      seen = gen_;
      active_++;
      lk.unlock();
      work(widx);
      lk.lock();
      if (--active_ == 0) cv_done_->notify_all();
    }
  }

  void work(int widx) {
    for (;;) {
      int t = next_task_.fetch_add(1, std::memory_order_relaxed);
      if (t >= n_tasks_) break;
      int64_t t0 = now_ns();
      (*job_)(t, widx);
      busy_ns_total_.fetch_add(now_ns() - t0, std::memory_order_relaxed);
      tasks_total_.fetch_add(1, std::memory_order_relaxed);
      completed_.fetch_add(1, std::memory_order_release);
    }
  }

  std::mutex *m_ = nullptr;
  std::mutex *run_m_ = nullptr;
  std::condition_variable *cv_ = nullptr;
  std::condition_variable *cv_done_ = nullptr;
  std::vector<std::thread> *workers_ = nullptr;
  const std::function<void(int, int)> *job_ = nullptr;
  int target_ = 1;
  int n_tasks_ = 0;
  int active_ = 0;
  bool stop_ = false;
  int64_t gen_ = 0;
  std::atomic<int> next_task_{0};
  std::atomic<int> completed_{0};
  std::atomic<int64_t> tasks_total_{0};
  std::atomic<int64_t> busy_ns_total_{0};
};

// Slices per job: a few per lane balances byte-size skew across chunks
// without per-chunk dispatch overhead.
static uint64_t slice_count(uint64_t n, int threads) {
  uint64_t target = uint64_t(threads) * 4;
  return n < target ? n : target;
}

}  // namespace

// Disjoint per-worker output ranges make the parallel batch trivially
// byte-identical to the serial loop. Below 64 buffers the pool wake-up
// costs more than the hashing. (Braced extern "C" so the language
// linkage matches the forward declaration in the first extern "C" block.)
extern "C" {
static bool sha256_batch_parallel(const uint8_t *data,
                                  const uint64_t *offsets,
                                  const uint64_t *lens, uint64_t n,
                                  uint8_t *out) {
  int threads = NativePool::inst().threads();
  if (threads <= 1 || n < 64) return false;
  uint64_t n_slices = slice_count(n, threads);
  NativePool::inst().run(int(n_slices), [&](int t, int) {
    uint64_t lo = n * uint64_t(t) / n_slices;
    uint64_t hi = n * uint64_t(t + 1) / n_slices;
    for (uint64_t i = lo; i < hi; i++)
      am_sha256(data + offsets[i], lens[i], out + 32 * i);
  });
  return true;
}
}  // extern "C"

namespace {

// Per-slice parse timings of the LAST ingest call (exported to the span
// ring / parse_chunk_s histogram by the Python wrapper).
struct ParseStats {
  int64_t wall_t0 = 0, wall_t1 = 0;
  int64_t threads = 1;
  struct Slice { int64_t t0, t1, first, count, worker; };
  std::vector<Slice> slices;
};
static ParseStats g_parse_stats;

struct Cursor {
  const uint8_t *buf;
  uint64_t len;
  uint64_t pos = 0;
  bool fail = false;

  uint64_t uleb() {
    int err = 0;
    uint64_t v = read_uleb(buf, len, &pos, &err);
    if (err) fail = true;
    return v;
  }
  int64_t sleb() {
    int err = 0;
    int64_t v = read_sleb(buf, len, &pos, &err);
    if (err) fail = true;
    return v;
  }
  void skip(uint64_t n) {
    if (pos + n > len) { fail = true; return; }
    pos += n;
  }
  const uint8_t *bytes(uint64_t n) {
    if (pos + n > len) { fail = true; return nullptr; }
    const uint8_t *p = buf + pos;
    pos += n;
    return p;
  }
};

struct Interner {
  std::unordered_map<std::string, int32_t> index;
  std::vector<std::string> items;

  int32_t intern(const std::string &s) {
    auto it = index.find(s);
    if (it != index.end()) return it->second;
    int32_t id = int32_t(items.size());
    index.emplace(s, id);
    items.push_back(s);
    return id;
  }
};

// Per-change parse scratch, reused across the batch so the hot loop does no
// heap allocation after the first few changes (clear() keeps capacity).
struct ParseScratch {
  std::vector<int32_t> actor_table;
  std::vector<uint32_t> col_ids;
  std::vector<uint64_t> col_lens;
  std::vector<const uint8_t *> col_bufs;
  std::vector<int32_t> key_ids;
  std::vector<int64_t> actions, val_lens, obj_ctr, insert_i64;
  std::vector<uint8_t> actions_ok, val_lens_ok, obj_ctr_ok, insert_ok;
  std::vector<int64_t> pred_num, pred_actor, pred_ctr;
  std::vector<uint8_t> pred_num_ok, pred_actor_ok, pred_ctr_ok;
  std::vector<int64_t> obj_actor, key_actor, key_ctr;
  std::vector<uint8_t> obj_actor_ok, key_actor_ok, key_ctr_ok;
  std::vector<int64_t> bool_v;
  std::vector<uint8_t> bool_m;

  void reset() {
    actor_table.clear();
    col_ids.clear();
    col_lens.clear();
    col_bufs.clear();
    key_ids.clear();
    actions.clear();
    val_lens.clear();
    obj_ctr.clear();
    insert_i64.clear();
    actions_ok.clear();
    val_lens_ok.clear();
    obj_ctr_ok.clear();
    insert_ok.clear();
    pred_num.clear();
    pred_actor.clear();
    pred_ctr.clear();
    pred_num_ok.clear();
    pred_actor_ok.clear();
    pred_ctr_ok.clear();
    obj_actor.clear();
    key_actor.clear();
    key_ctr.clear();
    obj_actor_ok.clear();
    key_actor_ok.clear();
    key_ctr_ok.clear();
  }
};

struct IngestCtx {
  Interner keys, actors;
  // Raw actor bytes -> interned id, skipping the hex conversion + string
  // intern on the (hot) repeated-actor case. The first 32 distinct actors
  // also land in a linear memcmp cache (no per-lookup allocation).
  std::unordered_map<std::string, int32_t> actor_raw_cache;
  std::vector<std::string> actor_lin_keys;
  std::vector<int32_t> actor_lin_ids;
  ParseScratch scratch;
  std::vector<int32_t> out_doc, out_key, out_packed, out_val;
  std::vector<uint8_t> out_flags;  // 1 = set/del, 2 = inc
  std::string error;
  // Per-change metadata (filled only when am_ingest_changes gets
  // with_meta=1): header fields + full SHA-256 chunk hash, so the causal
  // gate / hash graph never needs a Python-side header decode.
  std::vector<int32_t> m_actor;
  std::vector<int64_t> m_seq, m_start_op, m_time, m_nops;
  std::vector<uint8_t> m_hash;      // 32 bytes per change
  std::vector<int64_t> m_deps_off;  // per change, index into m_deps/32
  std::vector<uint8_t> m_deps;      // 32 bytes per dep, concatenated
  std::vector<int64_t> m_msg_off;   // per change, byte offset into m_msg
  std::vector<uint8_t> m_msg;       // UTF-8 message bytes, concatenated
  std::vector<int64_t> m_buf_len;   // per change, wire buffer byte length
  // Per-op pred lists (with_meta only): out_pred_off[i] indexes the first
  // pred of op row i in out_pred; packed as (ctr << kActorBits) | actor
  // with GLOBAL actor numbers (the per-change actor table is interned)
  std::vector<int64_t> out_pred_off;
  std::vector<int32_t> out_pred;
  // Sequence-op columns (with_seq only): packed objectId (0 = root map),
  // packed referent elemId (0 = head/none), wire value-type tag low nibble
  std::vector<int32_t> out_obj, out_ref;
  std::vector<uint8_t> out_vtype;
  // Boxed-value passthrough (with_seq only): rows whose payload an int32
  // lane can't carry (strings/floats/bytes, multi-char text) get their raw
  // wire value bytes appended here; out_vlen is 0 for inline-value rows
  std::vector<int32_t> out_vlen;
  std::vector<uint8_t> val_arena;
};

// Intern an actor given its raw (binary) bytes, caching by raw bytes so the
// hex conversion + string intern runs once per distinct actor per batch.
// The hit path scans a small linear cache with memcmp — batches hold a
// handful of distinct actors, and the hash-map path's std::string key
// construction per change was a measurable slice of the meta parse.
static int32_t intern_actor_raw(IngestCtx &ctx, const uint8_t *raw,
                                uint64_t len) {
  size_t n_lin = ctx.actor_lin_keys.size();
  for (size_t i = 0; i < n_lin; i++) {
    const std::string &k = ctx.actor_lin_keys[i];
    if (k.size() == len && memcmp(k.data(), raw, len) == 0)
      return ctx.actor_lin_ids[i];
  }
  std::string key((const char *)raw, len);
  auto it = ctx.actor_raw_cache.find(key);
  if (it != ctx.actor_raw_cache.end()) return it->second;
  static const char *hex = "0123456789abcdef";
  std::string actor_hex;
  actor_hex.reserve(len * 2);
  for (uint64_t i = 0; i < len; i++) {
    actor_hex.push_back(hex[raw[i] >> 4]);
    actor_hex.push_back(hex[raw[i] & 15]);
  }
  int32_t id = ctx.actors.intern(actor_hex);
  if (ctx.actor_lin_keys.size() < 32) {
    ctx.actor_lin_keys.push_back(key);
    ctx.actor_lin_ids.push_back(id);
  }
  ctx.actor_raw_cache.emplace(std::move(key), id);
  return id;
}

// SHA-256 of a change chunk as the reference hashes it (columnar.js:688-708):
// over [chunk type 1][uleb body length][uncompressed body].
static void change_chunk_hash(const uint8_t *body, uint64_t body_len,
                              uint8_t out[32]) {
  uint8_t header[11];
  uint64_t n = 0;
  header[n++] = 1;
  uint64_t v = body_len;
  do {
    uint8_t b = v & 0x7f;
    v >>= 7;
    if (v) b |= 0x80;
    header[n++] = b;
  } while (v);
  Sha256Stream s;
  sha256_stream_init(s);
  sha256_stream_update(s, header, n);
  sha256_stream_update(s, body, body_len);
  sha256_stream_final(s, out);
}

constexpr int kColObjActor = 0x01, kColObjCtr = 0x02;
constexpr int kColKeyActor = 0x11, kColKeyCtr = 0x13, kColKeyStr = 0x15;
constexpr int kColInsert = 0x34, kColAction = 0x42;
constexpr int kColValLen = 0x56, kColValRaw = 0x57;
constexpr int kColPredNum = 0x70, kColPredActor = 0x71, kColPredCtr = 0x73;
constexpr int kActionSet = 1, kActionDel = 3, kActionInc = 5;
constexpr int kActionMakeMap = 0, kActionMakeList = 2;
constexpr int kActionMakeText = 4, kActionMakeTable = 6;
constexpr int kActorBits = 8;

// Decode a UTF-8 buffer holding EXACTLY one code point; returns it or -1.
// Text-element payloads are single characters in the hot editing path —
// multi-char / non-string values fall back to the host value table.
static int64_t utf8_single_cp(const uint8_t *p, uint64_t n) {
  if (n == 0 || p == nullptr) return -1;
  uint32_t cp;
  uint64_t need;
  uint8_t b = p[0];
  if (b < 0x80) { cp = b; need = 1; }
  else if ((b >> 5) == 6) { cp = b & 0x1f; need = 2; }
  else if ((b >> 4) == 14) { cp = b & 0x0f; need = 3; }
  else if ((b >> 3) == 30) { cp = b & 0x07; need = 4; }
  else return -1;
  if (n != need) return -1;
  for (uint64_t i = 1; i < need; i++) {
    if ((p[i] >> 6) != 2) return -1;
    cp = (cp << 6) | (p[i] & 0x3f);
  }
  // Match Python's strict UTF-8 decode (encoding.py read_prefixed_string):
  // reject overlong encodings, surrogates, and out-of-range code points —
  // otherwise turbo would commit values whose later chr()/encode crashes.
  static const uint32_t min_cp[5] = {0, 0, 0x80, 0x800, 0x10000};
  if (cp < min_cp[need]) return -1;              // overlong
  if (cp >= 0xd800 && cp <= 0xdfff) return -1;   // surrogate
  if (cp > 0x10ffff) return -1;
  return int64_t(cp);
}

// Decode an RLE utf8 column into interned key ids (-1 = null)
bool decode_keystr(const uint8_t *buf, uint64_t len, Interner &keys,
                   std::vector<int32_t> &out) {
  Cursor c{buf, len};
  while (c.pos < c.len && !c.fail) {
    int64_t count = c.sleb();
    if (c.fail) return false;
    if (count > 1) {
      uint64_t slen = c.uleb();
      const uint8_t *p = c.bytes(slen);
      if (c.fail) return false;
      int32_t id = keys.intern(std::string((const char *)p, slen));
      for (int64_t i = 0; i < count; i++) out.push_back(id);
    } else if (count == 1) {
      return false;
    } else if (count < 0) {
      for (int64_t i = 0; i < -count; i++) {
        uint64_t slen = c.uleb();
        const uint8_t *p = c.bytes(slen);
        if (c.fail) return false;
        out.push_back(keys.intern(std::string((const char *)p, slen)));
      }
    } else {
      uint64_t nulls = c.uleb();
      if (c.fail) return false;
      for (uint64_t i = 0; i < nulls; i++) out.push_back(-1);
    }
  }
  return !c.fail;
}

bool decode_i64_col(const uint8_t *buf, uint64_t len, bool is_signed,
                    bool is_delta, std::vector<int64_t> &vals,
                    std::vector<uint8_t> &mask) {
  int64_t count = am_count_rle(buf, len, is_signed || is_delta);
  if (count < 0) return false;
  vals.resize(size_t(count));
  mask.resize(size_t(count));
  if (count == 0) return true;
  int64_t n = is_delta
      ? am_decode_delta(buf, len, vals.data(), mask.data(), count)
      : am_decode_rle(buf, len, is_signed ? 1 : 0, vals.data(), mask.data(),
                      count);
  return n == count;
}

}  // namespace

extern "C" {

// Implemented without the goto mess: parse body given the chunk *contents*
// (after the 8-byte magic+checksum, 1-byte type, LEB length header).
static bool parse_change_body(IngestCtx &ctx, const uint8_t *body,
                              uint64_t body_len, int32_t doc,
                              int with_meta, int with_seq,
                              const uint8_t *checksum) {
  size_t rows_before = ctx.out_doc.size();
  if (with_meta) {
    uint8_t digest[32];
    change_chunk_hash(body, body_len, digest);
    if (memcmp(digest, checksum, 4) != 0) return false;  // corrupt chunk
    ctx.m_hash.insert(ctx.m_hash.end(), digest, digest + 32);
  }
  Cursor c{body, body_len};
  uint64_t num_deps = c.uleb();
  if (with_meta) {
    ctx.m_deps_off.push_back(int64_t(ctx.m_deps.size() / 32));
    const uint8_t *deps = c.bytes(32 * num_deps);
    if (c.fail) return false;
    ctx.m_deps.insert(ctx.m_deps.end(), deps, deps + 32 * num_deps);
  } else {
    c.skip(32 * num_deps);
  }
  // actor hex string (length-prefixed bytes)
  uint64_t actor_len = c.uleb();
  const uint8_t *actor_bytes = c.bytes(actor_len);
  if (c.fail) return false;
  int32_t actor_id = intern_actor_raw(ctx, actor_bytes, actor_len);
  if (actor_id >= (1 << kActorBits)) return false;
  uint64_t seq = c.uleb();
  uint64_t start_op = c.uleb();   // startOp
  int64_t time = c.sleb();
  uint64_t msg_len = c.uleb();    // message
  if (with_meta) {
    ctx.m_actor.push_back(actor_id);
    ctx.m_seq.push_back(int64_t(seq));
    ctx.m_start_op.push_back(int64_t(start_op));
    ctx.m_time.push_back(time);
    ctx.m_msg_off.push_back(int64_t(ctx.m_msg.size()));
    const uint8_t *msg = c.bytes(msg_len);
    if (c.fail) return false;
    ctx.m_msg.insert(ctx.m_msg.end(), msg, msg + msg_len);
  } else {
    c.skip(msg_len);
  }
  ParseScratch &sc = ctx.scratch;
  sc.reset();
  std::vector<int32_t> &actor_table = sc.actor_table;
  actor_table.push_back(actor_id);
  uint64_t num_other_actors = c.uleb();
  for (uint64_t i = 0; i < num_other_actors; i++) {
    uint64_t alen = c.uleb();
    const uint8_t *abytes = c.bytes(alen);
    if (c.fail) return false;
    if (with_meta) {
      int32_t oid = intern_actor_raw(ctx, abytes, alen);
      if (oid >= (1 << kActorBits)) return false;
      actor_table.push_back(oid);
    }
  }
  if (c.fail) return false;

  uint64_t num_cols = c.uleb();
  std::vector<uint64_t> &col_lens = sc.col_lens;
  std::vector<uint32_t> &col_ids = sc.col_ids;
  for (uint64_t i = 0; i < num_cols; i++) {
    uint32_t cid = uint32_t(c.uleb());
    uint64_t blen = c.uleb();
    col_ids.push_back(cid);
    col_lens.push_back(blen);
  }
  if (c.fail) return false;
  std::vector<const uint8_t *> &col_bufs = sc.col_bufs;
  for (uint64_t i = 0; i < num_cols; i++) {
    col_bufs.push_back(c.bytes(col_lens[i]));
  }
  if (c.fail) return false;

  std::vector<int32_t> &key_ids = sc.key_ids;
  std::vector<int64_t> &actions = sc.actions, &val_lens = sc.val_lens,
                       &obj_ctr = sc.obj_ctr;
  std::vector<uint8_t> &actions_ok = sc.actions_ok,
                       &val_lens_ok = sc.val_lens_ok,
                       &obj_ctr_ok = sc.obj_ctr_ok, &insert_ok = sc.insert_ok;
  std::vector<int64_t> &insert_i64 = sc.insert_i64;
  std::vector<int64_t> &pred_num = sc.pred_num, &pred_actor = sc.pred_actor,
                       &pred_ctr = sc.pred_ctr;
  std::vector<uint8_t> &pred_num_ok = sc.pred_num_ok,
                       &pred_actor_ok = sc.pred_actor_ok,
                       &pred_ctr_ok = sc.pred_ctr_ok;
  std::vector<int64_t> &obj_actor = sc.obj_actor, &key_actor = sc.key_actor,
                       &key_ctr = sc.key_ctr;
  std::vector<uint8_t> &obj_actor_ok = sc.obj_actor_ok,
                       &key_actor_ok = sc.key_actor_ok,
                       &key_ctr_ok = sc.key_ctr_ok;
  const uint8_t *val_raw = nullptr;
  uint64_t val_raw_len = 0;

  for (uint64_t i = 0; i < num_cols; i++) {
    uint32_t cid = col_ids[i];
    const uint8_t *b = col_bufs[i];
    uint64_t blen = col_lens[i];
    if (cid == kColKeyStr) {
      if (!decode_keystr(b, blen, ctx.keys, key_ids)) return false;
    } else if (cid == kColAction) {
      if (!decode_i64_col(b, blen, false, false, actions, actions_ok))
        return false;
    } else if (cid == kColValLen) {
      if (!decode_i64_col(b, blen, false, false, val_lens, val_lens_ok))
        return false;
    } else if (cid == kColValRaw) {
      val_raw = b;
      val_raw_len = blen;
    } else if (cid == kColObjCtr) {
      if (!decode_i64_col(b, blen, false, false, obj_ctr, obj_ctr_ok))
        return false;
    } else if (with_seq && cid == kColObjActor) {
      if (!decode_i64_col(b, blen, false, false, obj_actor, obj_actor_ok))
        return false;
    } else if (with_seq && cid == kColKeyActor) {
      if (!decode_i64_col(b, blen, false, false, key_actor, key_actor_ok))
        return false;
    } else if (with_seq && cid == kColKeyCtr) {
      if (!decode_i64_col(b, blen, true, true, key_ctr, key_ctr_ok))
        return false;
    } else if (with_meta && cid == kColPredNum) {
      if (!decode_i64_col(b, blen, false, false, pred_num, pred_num_ok))
        return false;
    } else if (with_meta && cid == kColPredActor) {
      if (!decode_i64_col(b, blen, false, false, pred_actor, pred_actor_ok))
        return false;
    } else if (with_meta && cid == kColPredCtr) {
      if (!decode_i64_col(b, blen, true, true, pred_ctr, pred_ctr_ok))
        return false;
    } else if (cid == kColInsert) {
      if (!decode_i64_col(b, blen, false, false, insert_i64, insert_ok)) {
        // boolean column needs the boolean decoder
        insert_i64.clear();
        insert_ok.clear();
      }
      // decode as boolean
      {
        int64_t cap = int64_t(sc.bool_v.size()) < 16
                          ? 16 : int64_t(sc.bool_v.size());
        std::vector<int64_t> &v = sc.bool_v;
        std::vector<uint8_t> &m = sc.bool_m;
        // -2 = capacity too small (retry bigger, bounded by the column
        // ceiling); -1 = malformed, fail immediately — a hostile run
        // count must not drive the resize loop toward bad_alloc
        int64_t n = -2;
        while (n == -2 && cap <= kMaxColumnValues) {
          v.resize(size_t(cap));
          m.resize(size_t(cap));
          n = am_decode_boolean(b, blen, v.data(), m.data(), cap);
          if (n == -2) cap *= 4;
        }
        if (n < 0) return false;
        insert_i64.assign(v.begin(), v.begin() + n);
      }
    }
    // other columns (keyActor/keyCtr, pred group, chld) are irrelevant for
    // root-map set/inc/del ingest; their presence with non-null content for
    // list ops is caught via key_ids null check below
  }

  uint64_t n_ops = actions.size();
  uint64_t raw_pos = 0;
  uint64_t pred_pos = 0;
  for (uint64_t i = 0; i < n_ops; i++) {
    int64_t action = actions[i];
    if (with_meta) {
      ctx.out_pred_off.push_back(int64_t(ctx.out_pred.size()));
      uint64_t np = 0;
      if (i < pred_num.size()) {
        if (!pred_num_ok[i]) return false;  // null group cardinality
        np = uint64_t(pred_num[i]);
      }
      for (uint64_t d = 0; d < np; d++, pred_pos++) {
        if (pred_pos >= pred_actor.size() || pred_pos >= pred_ctr.size())
          return false;
        if (!pred_actor_ok[pred_pos] || !pred_ctr_ok[pred_pos])
          return false;  // null entries inside a pred group are malformed
        uint64_t ta = uint64_t(pred_actor[pred_pos]);
        if (ta >= actor_table.size()) return false;
        int64_t pctr = pred_ctr[pred_pos];
        if (pctr <= 0 || pctr >= (int64_t(1) << (31 - kActorBits)))
          return false;
        ctx.out_pred.push_back(
            int32_t((pctr << kActorBits) | actor_table[ta]));
      }
    }
    bool is_root = !(i < obj_ctr.size() && obj_ctr_ok.size() > i &&
                     obj_ctr_ok[i]);
    bool insert = (i < insert_i64.size()) && insert_i64[i];
    int32_t key = (i < key_ids.size()) ? key_ids[i] : -1;
    int64_t tag = (i < val_lens.size() && val_lens_ok[i]) ? val_lens[i] : 0;
    uint64_t vsize = uint64_t(tag) >> 4;
    int vtype = int(tag & 0x0f);
    if (raw_pos + vsize > val_raw_len) return false;
    const uint8_t *vbytes = val_raw ? val_raw + raw_pos : nullptr;
    raw_pos += vsize;
    int64_t ctr = int64_t(start_op + i);
    if (ctr >= (int64_t(1) << (31 - kActorBits))) return false;
    int32_t self_packed = int32_t((ctr << kActorBits) | actor_id);

    // Containing object for non-root ops, packed (ctr << bits) | actor
    int32_t obj_packed = 0;
    if (!is_root) {
      if (i >= obj_actor.size() || !obj_actor_ok[i]) return false;
      uint64_t ta = uint64_t(obj_actor[i]);
      if (ta >= actor_table.size()) return false;
      int64_t objc = (i < obj_ctr.size()) ? obj_ctr[i] : 0;
      if (objc <= 0 || objc >= (int64_t(1) << (31 - kActorBits)))
        return false;
      obj_packed = int32_t((objc << kActorBits) | actor_table[ta]);
    }

    if (!is_root && with_seq && key < 0) {
      // ---- sequence element op (flags 3-6; makes 11-14) ----
      bool is_make = action == kActionMakeMap || action == kActionMakeList ||
          action == kActionMakeText || action == kActionMakeTable;
      if (!is_make && action != kActionSet && action != kActionDel &&
          action != kActionInc)
        return false;                 // link inside a sequence: host engine
      int32_t obj = obj_packed;
      // referent elemId: keyCtr 0 = '_head' (insert only); else packed
      if (i >= key_ctr.size() || !key_ctr_ok[i]) return false;
      int64_t kc = key_ctr[i];
      if (kc < 0 || kc >= (int64_t(1) << (31 - kActorBits))) return false;
      int32_t ref = 0;
      if (kc == 0) {
        if (!insert) return false;    // update needs a real target
      } else {
        if (i >= key_actor.size() || !key_actor_ok[i]) return false;
        uint64_t ka = uint64_t(key_actor[i]);
        if (ka >= actor_table.size()) return false;
        ref = int32_t((kc << kActorBits) | actor_table[ka]);
      }
      if (is_make) {
        // Object nested inside a sequence (rows-in-lists): flag-coded
        // 11 makeText, 12 makeList, 13 makeMap, 14 makeTable; the value
        // lane carries the insert bit (makes have no payload)
        if (vsize != 0) return false;
        uint8_t mk = action == kActionMakeText ? 11
            : action == kActionMakeList ? 12
            : action == kActionMakeMap ? 13 : 14;
        ctx.out_doc.push_back(doc);
        ctx.out_key.push_back(-1);
        ctx.out_packed.push_back(self_packed);
        ctx.out_val.push_back(insert ? 1 : 0);
        ctx.out_flags.push_back(mk);
        ctx.out_obj.push_back(obj);
        ctx.out_ref.push_back(ref);
        ctx.out_vtype.push_back(0);
        ctx.out_vlen.push_back(0);
        continue;
      }
      int64_t value = 0;
      uint8_t flags;
      if (action == kActionDel) {
        if (insert || vsize != 0) return false;
        flags = 5;
      } else if (action == kActionInc) {
        if (insert) return false;
        uint64_t p = 0;
        int err = 0;
        if (vtype == 3) value = int64_t(read_uleb(vbytes, vsize, &p, &err));
        else if (vtype == 4 || vtype == 8 || vtype == 9)
          value = read_sleb(vbytes, vsize, &p, &err);
        else return false;
        if (err || value <= -(int64_t(1) << 31) ||
            value >= (int64_t(1) << 31))
          return false;
        flags = 6;
      } else {
        uint64_t p = 0;
        int err = 0;
        bool boxed = false;
        if (vtype == 3) {
          value = int64_t(read_uleb(vbytes, vsize, &p, &err));
        } else if (vtype == 4 || vtype == 8 || vtype == 9) {
          value = read_sleb(vbytes, vsize, &p, &err);
        } else if (vtype == 6) {      // UTF-8: single code point inline,
          value = utf8_single_cp(vbytes, vsize);
          if (value < 0) {            // multi-char spans box via the arena
            value = 0;
            boxed = true;
          }
        } else if (vtype <= 9) {      // null/bool/float/bytes: arena
          value = 0;
          boxed = true;
        } else {
          return false;               // unknown value types: host engine
        }
        if (err) return false;
        if (!boxed && vtype != 6 &&
            (value < 0 || value >= (int64_t(1) << 31))) {
          value = 0;                  // out-of-int32-lane ints box too
          boxed = true;
        }
        flags = insert ? 3 : 4;
        if (boxed) {
          if (vsize == 0 && vtype >= 5) return false;  // malformed
          ctx.out_vlen.push_back(int32_t(vsize));
          ctx.val_arena.insert(ctx.val_arena.end(), vbytes, vbytes + vsize);
        } else {
          ctx.out_vlen.push_back(0);
        }
        ctx.out_doc.push_back(doc);
        ctx.out_key.push_back(-1);
        ctx.out_packed.push_back(self_packed);
        ctx.out_val.push_back(int32_t(value));
        ctx.out_flags.push_back(flags);
        ctx.out_obj.push_back(obj);
        ctx.out_ref.push_back(ref);
        ctx.out_vtype.push_back(uint8_t(vtype));
        continue;
      }
      ctx.out_doc.push_back(doc);
      ctx.out_key.push_back(-1);
      ctx.out_packed.push_back(self_packed);
      ctx.out_val.push_back(int32_t(value));
      ctx.out_flags.push_back(flags);
      ctx.out_obj.push_back(obj);
      ctx.out_ref.push_back(ref);
      ctx.out_vtype.push_back(uint8_t(vtype));
      ctx.out_vlen.push_back(0);
      continue;
    }

    // ---- keyed map/table op (root, or a nested object under with_seq;
    // without with_seq the flat register path accepts root only) ----
    if (!is_root && !with_seq) return false;
    if (insert) return false;
    if (key < 0) return false;
    if (with_seq && (action == kActionMakeText || action == kActionMakeList ||
                     action == kActionMakeMap || action == kActionMakeTable)) {
      // makes become flag-coded rows: 7 makeText, 8 makeList, 9 makeMap,
      // 10 makeTable; out_obj carries the (possibly nested) parent
      if (vsize != 0) return false;
      uint8_t mk = action == kActionMakeText ? 7
          : action == kActionMakeList ? 8
          : action == kActionMakeMap ? 9 : 10;
      ctx.out_doc.push_back(doc);
      ctx.out_key.push_back(key);
      ctx.out_packed.push_back(self_packed);
      ctx.out_val.push_back(0);
      ctx.out_flags.push_back(mk);
      ctx.out_obj.push_back(obj_packed);
      ctx.out_ref.push_back(0);
      ctx.out_vtype.push_back(0);
      ctx.out_vlen.push_back(0);
      continue;
    }

    int64_t value = 0;
    bool boxed = false;
    if (action == kActionSet || action == kActionInc) {
      uint64_t p = 0;
      int err = 0;
      if (vtype == 3) {  // LEB128 uint
        value = int64_t(read_uleb(vbytes, vsize, &p, &err));
      } else if (vtype == 4 || vtype == 8 || vtype == 9) {  // int/counter/ts
        value = read_sleb(vbytes, vsize, &p, &err);
      } else if (with_seq && action == kActionSet && vtype <= 9) {
        // null/bool/str/float/bytes set values ride the arena and box
        // host-side (the flat register path without with_seq keeps its
        // int-only contract)
        boxed = true;
      } else {
        return false;  // inc of a non-int / unknown value type: host path
      }
      if (err) return false;
      // inc deltas are raw int32 addends (negatives allowed); set values
      // must be non-negative inline ints (others box via the arena)
      if (action == kActionInc) {
        if (value <= -(int64_t(1) << 31) || value >= (int64_t(1) << 31))
          return false;
      } else if (!boxed && (value < 0 || value >= (int64_t(1) << 31))) {
        if (!with_seq) return false;
        boxed = true;               // out-of-lane ints box too
      }
      if (boxed) {
        if (vsize == 0 && vtype >= 5) return false;  // empty str/bytes/f64
        value = 0;
      }
    } else if (action != kActionDel) {
      return false;  // link needs the general engine
    }

    ctx.out_doc.push_back(doc);
    ctx.out_key.push_back(key);
    ctx.out_packed.push_back(self_packed);
    // A winning delete must be distinguishable from set-to-zero: deletions
    // carry the TOMBSTONE value (-1), matching tensor_doc.TOMBSTONE
    ctx.out_val.push_back(action == kActionDel ? -1 : int32_t(value));
    ctx.out_flags.push_back(action == kActionInc ? 2 : 1);
    if (with_seq) {
      ctx.out_obj.push_back(obj_packed);   // 0 = root; else nested parent
      ctx.out_ref.push_back(0);
      ctx.out_vtype.push_back(uint8_t(vtype));
      if (boxed) {
        ctx.out_vlen.push_back(int32_t(vsize));
        ctx.val_arena.insert(ctx.val_arena.end(), vbytes, vbytes + vsize);
      } else {
        ctx.out_vlen.push_back(0);
      }
    }
  }
  if (with_meta) ctx.m_nops.push_back(int64_t(ctx.out_doc.size() - rows_before));
  return true;
}

// One-shot batched ingest. Returns number of op rows, or -1 on any change
// that needs the general host engine. Outputs are retrieved with
// am_ingest_fetch (two-phase because row count is not known in advance).
static IngestCtx *g_ingest = nullptr;

// One-op-per-change is the common bulk shape: pre-size the output
// vectors to the batch so the hot loop never pays geometric-growth
// memcpys over multi-MB buffers.
static void ingest_reserve(IngestCtx &ctx, uint64_t n_changes,
                           int with_meta, int with_seq) {
  ctx.out_doc.reserve(n_changes);
  ctx.out_key.reserve(n_changes);
  ctx.out_packed.reserve(n_changes);
  ctx.out_val.reserve(n_changes);
  ctx.out_flags.reserve(n_changes);
  if (with_meta) {
    ctx.m_actor.reserve(n_changes);
    ctx.m_seq.reserve(n_changes);
    ctx.m_start_op.reserve(n_changes);
    ctx.m_time.reserve(n_changes);
    ctx.m_nops.reserve(n_changes);
    ctx.m_hash.reserve(32 * n_changes);
    ctx.m_deps.reserve(32 * n_changes);
    ctx.m_deps_off.reserve(n_changes);
    ctx.m_msg_off.reserve(n_changes);
    ctx.out_pred_off.reserve(n_changes);
    ctx.out_pred.reserve(n_changes);
  }
  if (with_seq) {
    ctx.out_obj.reserve(n_changes);
    ctx.out_ref.reserve(n_changes);
    ctx.out_vtype.reserve(n_changes);
    ctx.out_vlen.reserve(n_changes);
  }
}

// One change chunk into the global ingest context; returns false on any
// malformed/unsupported input (caller tears the context down).
static bool ingest_one_chunk(IngestCtx &ctx, const uint8_t *chunk,
                             uint64_t chunk_len, int32_t doc_id,
                             int with_meta, int with_seq) {
  if (chunk_len < 12) return false;
  // The checksum covers type+length+body but NOT the magic bytes, so
  // they must be checked explicitly: without this, a buffer whose magic
  // is corrupt parses "clean", its ops land on the device, and the raw
  // garbage bytes enter the change log where save()'s host decode later
  // explodes — silent acceptance instead of a typed quarantine (found
  // by the ISSUE-7 chaos client, pinned by
  // tests/test_service.py::test_corrupt_magic_is_quarantined_not_stored).
  if (memcmp(chunk, "\x85\x6f\x4a\x83", 4) != 0) return false;
  const uint8_t *body;
  uint64_t body_len;
  std::vector<uint8_t> inflated;
  Cursor hc{chunk, chunk_len};
  hc.skip(8);  // magic (verified above) + checksum (verified per body)
  uint8_t chunk_type = *hc.bytes(1);
  uint64_t blen = hc.uleb();
  const uint8_t *bptr = hc.bytes(blen);
  if (hc.fail) return false;
  if (chunk_type == 2) {  // deflated change
    size_t cap = blen * 16 + 1024;
    int64_t n = -1;
    while (n < 0 && cap < (size_t(1) << 28)) {
      inflated.resize(cap);
      n = am_inflate_raw(bptr, blen, inflated.data(), cap);
      if (n < 0) cap *= 4;
    }
    if (n < 0) return false;
    body = inflated.data();
    body_len = uint64_t(n);
  } else if (chunk_type == 1) {
    body = bptr;
    body_len = blen;
  } else {
    return false;
  }
  // The chunk header + declared body must span the whole buffer: buffers
  // holding concatenated chunks (split_containers territory) take the
  // exact path, where every chunk is applied
  if (hc.pos != chunk_len) return false;
  return parse_change_body(ctx, body, body_len, doc_id, with_meta,
                           with_seq, chunk + 4);
}

// Merge per-slice parse contexts into the global one, remapping every
// slice-local interned id into the global tables. Interning each slice's
// items IN SLICE ORDER reproduces exactly the first-occurrence order a
// serial chunk-order parse would assign, so the merged arrays are
// byte-identical to a single-threaded parse — same key/actor numbering,
// same packed opIds, same hashes — no matter how many workers ran or
// where the slice boundaries fell. Returns false when the merged actor
// table overflows the kActorBits packing (the serial parse fails the
// batch for the same population; both paths return -1).
static bool merge_ingest_slices(IngestCtx &g, std::vector<IngestCtx> &slices,
                                int with_meta, int with_seq) {
  size_t rows = 0, preds = 0, deps = 0, msgs = 0, arena = 0;
  for (auto &s : slices) {
    rows += s.out_doc.size();
    preds += s.out_pred.size();
    deps += s.m_deps.size();
    msgs += s.m_msg.size();
    arena += s.val_arena.size();
  }
  ingest_reserve(g, rows, with_meta, with_seq);
  g.out_pred.reserve(preds);
  g.m_deps.reserve(deps);
  g.m_msg.reserve(msgs);
  g.val_arena.reserve(arena);
  std::vector<int32_t> kmap, amap;
  for (auto &s : slices) {
    kmap.resize(s.keys.items.size());
    for (size_t i = 0; i < kmap.size(); i++)
      kmap[i] = g.keys.intern(s.keys.items[i]);
    amap.resize(s.actors.items.size());
    for (size_t i = 0; i < amap.size(); i++) {
      amap[i] = g.actors.intern(s.actors.items[i]);
      if (amap[i] >= (1 << kActorBits)) return false;
    }
    constexpr uint32_t kAMask = (1u << kActorBits) - 1;
    auto remap = [&](int32_t v) -> int32_t {
      return int32_t((uint32_t(v) & ~kAMask) |
                     uint32_t(amap[uint32_t(v) & kAMask]));
    };
    g.out_doc.insert(g.out_doc.end(), s.out_doc.begin(), s.out_doc.end());
    for (int32_t k : s.out_key) g.out_key.push_back(k < 0 ? k : kmap[k]);
    for (int32_t p : s.out_packed) g.out_packed.push_back(remap(p));
    g.out_val.insert(g.out_val.end(), s.out_val.begin(), s.out_val.end());
    g.out_flags.insert(g.out_flags.end(), s.out_flags.begin(),
                       s.out_flags.end());
    if (with_meta) {
      for (int32_t a : s.m_actor) g.m_actor.push_back(amap[a]);
      g.m_seq.insert(g.m_seq.end(), s.m_seq.begin(), s.m_seq.end());
      g.m_start_op.insert(g.m_start_op.end(), s.m_start_op.begin(),
                          s.m_start_op.end());
      g.m_time.insert(g.m_time.end(), s.m_time.begin(), s.m_time.end());
      g.m_nops.insert(g.m_nops.end(), s.m_nops.begin(), s.m_nops.end());
      g.m_hash.insert(g.m_hash.end(), s.m_hash.begin(), s.m_hash.end());
      int64_t dep_base = int64_t(g.m_deps.size() / 32);
      for (int64_t off : s.m_deps_off) g.m_deps_off.push_back(off + dep_base);
      g.m_deps.insert(g.m_deps.end(), s.m_deps.begin(), s.m_deps.end());
      int64_t msg_base = int64_t(g.m_msg.size());
      for (int64_t off : s.m_msg_off) g.m_msg_off.push_back(off + msg_base);
      g.m_msg.insert(g.m_msg.end(), s.m_msg.begin(), s.m_msg.end());
      int64_t pred_base = int64_t(g.out_pred.size());
      for (int64_t off : s.out_pred_off)
        g.out_pred_off.push_back(off + pred_base);
      for (int32_t p : s.out_pred) g.out_pred.push_back(remap(p));
    }
    if (with_seq) {
      // 0 is the root/none sentinel in obj/ref — never an actor number
      // (packed object/referent counters are >= 1 by parse validation)
      for (int32_t v : s.out_obj) g.out_obj.push_back(v == 0 ? 0 : remap(v));
      for (int32_t v : s.out_ref) g.out_ref.push_back(v == 0 ? 0 : remap(v));
      g.out_vtype.insert(g.out_vtype.end(), s.out_vtype.begin(),
                         s.out_vtype.end());
      g.out_vlen.insert(g.out_vlen.end(), s.out_vlen.begin(),
                        s.out_vlen.end());
      g.val_arena.insert(g.val_arena.end(), s.val_arena.begin(),
                         s.val_arena.end());
    }
  }
  return true;
}

// Chunk-parallel parse: contiguous chunk slices (balanced by byte size)
// parsed concurrently into per-slice contexts, then merged in slice order.
static bool ingest_parallel(IngestCtx &g, const uint8_t *const *ptrs,
                            const uint64_t *lens, const int32_t *doc_ids,
                            uint64_t n, int with_meta, int with_seq,
                            int threads) {
  uint64_t n_slices = slice_count(n, threads);
  std::vector<uint64_t> pre(n + 1, 0);
  for (uint64_t i = 0; i < n; i++) pre[i + 1] = pre[i] + lens[i];
  std::vector<uint64_t> bounds(n_slices + 1, 0);
  bounds[n_slices] = n;
  for (uint64_t s = 1; s < n_slices; s++) {
    uint64_t want = pre[n] / n_slices * s;
    uint64_t idx = uint64_t(
        std::lower_bound(pre.begin(), pre.end(), want) - pre.begin());
    uint64_t lo = bounds[s - 1] + 1, hi = n - (n_slices - s);
    bounds[s] = idx < lo ? lo : (idx > hi ? hi : idx);
  }
  std::vector<IngestCtx> slices(n_slices);
  std::vector<uint8_t> slice_ok(n_slices, 1);
  std::vector<ParseStats::Slice> stats(n_slices);
  std::atomic<bool> failed{false};
  NativePool::inst().run(int(n_slices), [&](int t, int w) {
    int64_t t0 = now_ns();
    IngestCtx &ctx = slices[size_t(t)];
    uint64_t lo = bounds[size_t(t)], hi = bounds[size_t(t) + 1];
    ingest_reserve(ctx, hi - lo, with_meta, with_seq);
    for (uint64_t i = lo; i < hi; i++) {
      if (failed.load(std::memory_order_relaxed)) {
        slice_ok[size_t(t)] = 0;   // sibling failed: the batch is dead
        break;
      }
      if (!ingest_one_chunk(ctx, ptrs[i], lens[i],
                            doc_ids ? doc_ids[i] : int32_t(i),
                            with_meta, with_seq)) {
        slice_ok[size_t(t)] = 0;
        failed.store(true, std::memory_order_relaxed);
        break;
      }
    }
    stats[size_t(t)] = {t0, now_ns(), int64_t(lo), int64_t(hi - lo), w};
  });
  g_parse_stats.slices = stats;
  for (uint8_t okf : slice_ok)
    if (!okf) return false;
  return merge_ingest_slices(g, slices, with_meta, with_seq);
}

// Shared entry: serial below 2 chunks or a 1-lane pool, chunk-parallel
// otherwise. Either way the resulting context (and therefore every fetch)
// is byte-identical; failure is all-or-nothing (-1) on both paths.
static int64_t ingest_dispatch(const uint8_t *const *ptrs,
                               const uint64_t *lens, const int32_t *doc_ids,
                               uint64_t n_changes, int with_meta,
                               int with_seq) {
  delete g_ingest;
  g_ingest = new IngestCtx();
  g_parse_stats.slices.clear();
  g_parse_stats.wall_t0 = now_ns();
  int threads = NativePool::inst().threads();
  bool ok;
  if (threads <= 1 || n_changes < 2) {
    g_parse_stats.threads = 1;
    ingest_reserve(*g_ingest, n_changes, with_meta, with_seq);
    ok = true;
    int64_t t0 = now_ns();
    for (uint64_t i = 0; i < n_changes; i++) {
      if (!ingest_one_chunk(*g_ingest, ptrs[i], lens[i],
                            doc_ids ? doc_ids[i] : int32_t(i),
                            with_meta, with_seq)) {
        ok = false;
        break;
      }
    }
    if (n_changes)
      g_parse_stats.slices.push_back(
          {t0, now_ns(), 0, int64_t(n_changes), 0});
  } else {
    g_parse_stats.threads = threads;
    ok = ingest_parallel(*g_ingest, ptrs, lens, doc_ids, n_changes,
                         with_meta, with_seq, threads);
  }
  g_parse_stats.wall_t1 = now_ns();
  if (!ok) {
    delete g_ingest;
    g_ingest = nullptr;
    return -1;
  }
  if (with_meta) {
    // Per-change wire byte lengths: a buffer is exactly one change here
    // (multi-chunk buffers are refused by ingest_one_chunk), so the
    // caller's bytes accounting never needs a Python-side len() pass.
    g_ingest->m_buf_len.reserve(n_changes);
    for (uint64_t i = 0; i < n_changes; i++)
      g_ingest->m_buf_len.push_back(int64_t(lens[i]));
  }
  return int64_t(g_ingest->out_doc.size());
}

int64_t am_ingest_changes(const uint8_t *blob, const uint64_t *offsets,
                          const uint64_t *lens, const int32_t *doc_ids,
                          uint64_t n_changes, int with_meta, int with_seq) {
  std::vector<const uint8_t *> ptrs(n_changes);
  for (uint64_t i = 0; i < n_changes; i++) ptrs[i] = blob + offsets[i];
  return ingest_dispatch(ptrs.data(), lens, doc_ids, n_changes, with_meta,
                         with_seq);
}

#ifdef AM_HAVE_PYTHON
// Zero-copy list ingest: walk a Python list of bytes objects directly
// (no join into a contiguous blob, no per-buffer length array — those
// Python-side passes cost more than the parse itself at fleet scale).
// Each buffer's doc id is its list index (the turbo path's shape).
// MUST be called through ctypes.PyDLL: the pointer/length gather needs
// the GIL, after which the whole batch parse runs with the GIL RELEASED
// (Py_BEGIN_ALLOW_THREADS) so pool workers — and the caller's other
// Python threads — get real cores. The borrowed buffer pointers stay
// valid because the caller holds the list (and its bytes) alive across
// the call. Returns -2 for a non-list / non-bytes item (caller falls
// back to the blob entry), -1 for malformed chunks, row count otherwise.
int64_t am_ingest_changes_list(PyObject *buffers, int with_meta,
                               int with_seq) {
  if (!PyList_Check(buffers)) return -2;
  Py_ssize_t n = PyList_GET_SIZE(buffers);
  std::vector<const uint8_t *> ptrs;
  std::vector<uint64_t> lens;
  ptrs.reserve(size_t(n));
  lens.reserve(size_t(n));
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *it = PyList_GET_ITEM(buffers, i);
    if (!PyBytes_Check(it)) return -2;
    ptrs.push_back(reinterpret_cast<const uint8_t *>(PyBytes_AS_STRING(it)));
    lens.push_back(uint64_t(PyBytes_GET_SIZE(it)));
  }
  int64_t rc;
  Py_BEGIN_ALLOW_THREADS
  rc = ingest_dispatch(ptrs.data(), lens.data(), nullptr, uint64_t(n),
                       with_meta, with_seq);
  Py_END_ALLOW_THREADS
  return rc;
}
#endif  // AM_HAVE_PYTHON

// ---- pool / parse instrumentation exports ---------------------------------

// Monotone ABI stamp, bumped on any C-surface change. The Python wrapper
// refuses to run against a binary whose stamp mismatches (a stale .so
// would otherwise silently run the old single-threaded codec).
int64_t am_abi_version() { return 3; }

int64_t am_pool_configure(int n) { return NativePool::inst().configure(n); }

int64_t am_pool_threads() { return NativePool::inst().threads(); }

int64_t am_pool_stats(int64_t *threads, int64_t *tasks, int64_t *busy_ns) {
  *threads = NativePool::inst().threads();
  *tasks = NativePool::inst().tasks();
  *busy_ns = NativePool::inst().busy_ns();
  return 0;
}

// Per-slice timings of the LAST am_ingest_changes[_list] call, in
// CLOCK_MONOTONIC ns (same epoch as time.perf_counter_ns on Linux).
// rows receives up to cap records of 5 int64s: t0, t1, first_chunk,
// n_chunks, worker. Returns rows written.
int64_t am_ingest_parse_stats(int64_t *wall_t0, int64_t *wall_t1,
                              int64_t *threads, int64_t *rows, int64_t cap) {
  *wall_t0 = g_parse_stats.wall_t0;
  *wall_t1 = g_parse_stats.wall_t1;
  *threads = g_parse_stats.threads;
  int64_t n = int64_t(g_parse_stats.slices.size());
  if (n > cap) n = cap;
  for (int64_t i = 0; i < n; i++) {
    const ParseStats::Slice &s = g_parse_stats.slices[size_t(i)];
    rows[5 * i] = s.t0;
    rows[5 * i + 1] = s.t1;
    rows[5 * i + 2] = s.first;
    rows[5 * i + 3] = s.count;
    rows[5 * i + 4] = s.worker;
  }
  return n;
}

// Copy results out after am_ingest_changes. key_blob receives the interned
// keys as length-prefixed (uleb) strings; returns bytes written or -1 if cap
// too small.
int64_t am_ingest_fetch(int32_t *doc, int32_t *key, int32_t *packed,
                        int32_t *val, uint8_t *flags, uint8_t *key_blob,
                        uint64_t key_blob_cap, int64_t *n_keys,
                        uint8_t *actor_blob, uint64_t actor_blob_cap,
                        int64_t *n_actors) {
  if (!g_ingest) return -1;
  IngestCtx &ctx = *g_ingest;
  size_t n = ctx.out_doc.size();
  copy_bytes(doc, ctx.out_doc.data(), n * 4);
  copy_bytes(key, ctx.out_key.data(), n * 4);
  copy_bytes(packed, ctx.out_packed.data(), n * 4);
  copy_bytes(val, ctx.out_val.data(), n * 4);
  copy_bytes(flags, ctx.out_flags.data(), n);

  auto write_blob = [](const std::vector<std::string> &items, uint8_t *out,
                       uint64_t cap) -> int64_t {
    uint64_t pos = 0;
    for (const auto &s : items) {
      uint64_t len = s.size();
      // uleb encode length
      uint64_t v = len;
      do {
        if (pos >= cap) return -1;
        uint8_t byte = v & 0x7f;
        v >>= 7;
        out[pos++] = byte | (v ? 0x80 : 0);
      } while (v);
      if (pos + len > cap) return -1;
      copy_bytes(out + pos, s.data(), len);
      pos += len;
    }
    return int64_t(pos);
  };
  int64_t kb = write_blob(ctx.keys.items, key_blob, key_blob_cap);
  int64_t ab = write_blob(ctx.actors.items, actor_blob, actor_blob_cap);
  if (kb < 0 || ab < 0) return -1;
  *n_keys = int64_t(ctx.keys.items.size());
  *n_actors = int64_t(ctx.actors.items.size());
  delete g_ingest;
  g_ingest = nullptr;
  return kb;
}

// Bytes used in the actor blob by the last am_ingest_fetch-compatible
// context; callable BEFORE am_ingest_fetch to size slices (returns the
// exact serialized sizes of both blobs as (key_bytes, actor_bytes)).
int64_t am_ingest_blob_sizes(int64_t *key_bytes, int64_t *actor_bytes) {
  if (!g_ingest) return -1;
  IngestCtx &ctx = *g_ingest;
  auto blob_size = [](const std::vector<std::string> &items) -> int64_t {
    uint64_t pos = 0;
    for (const auto &s : items) {
      uint64_t v = s.size();
      do { pos++; v >>= 7; } while (v);
      pos += s.size();
    }
    return int64_t(pos);
  };
  *key_bytes = blob_size(ctx.keys.items);
  *actor_bytes = blob_size(ctx.actors.items);
  return 0;
}

// Exact byte sizes of the pending meta deps/msg blobs so the Python side
// allocates (and copies) only what is used. Must run before am_ingest_fetch.
int64_t am_ingest_meta_sizes(int64_t *deps_bytes, int64_t *msg_bytes) {
  if (!g_ingest) return -1;
  *deps_bytes = int64_t(g_ingest->m_deps.size());
  *msg_bytes = int64_t(g_ingest->m_msg.size());
  return 0;
}

// Copy per-change metadata captured by am_ingest_changes(with_meta=1).
// Must be called BEFORE am_ingest_fetch (which frees the context).
// deps_off/msg_off receive n_changes+1 entries (prefix offsets); deps_blob
// holds 32 bytes per dep. Returns the number of changes, or -1 when the
// context is missing, metadata was not requested, or a blob doesn't fit.
int64_t am_ingest_meta_fetch(int32_t *actor, int64_t *seq, int64_t *start_op,
                             int64_t *time, int64_t *nops, uint8_t *hash32,
                             int64_t *deps_off, uint8_t *deps_blob,
                             uint64_t deps_cap, int64_t *msg_off,
                             uint8_t *msg_blob, uint64_t msg_cap,
                             int64_t *buf_len) {
  if (!g_ingest) return -1;
  IngestCtx &ctx = *g_ingest;
  size_t n = ctx.m_seq.size();
  if (ctx.m_actor.size() != n || ctx.m_nops.size() != n ||
      ctx.m_hash.size() != 32 * n || ctx.m_buf_len.size() != n)
    return -1;
  if (ctx.m_deps.size() > deps_cap || ctx.m_msg.size() > msg_cap) return -1;
  copy_bytes(actor, ctx.m_actor.data(), n * 4);
  copy_bytes(seq, ctx.m_seq.data(), n * 8);
  copy_bytes(start_op, ctx.m_start_op.data(), n * 8);
  copy_bytes(time, ctx.m_time.data(), n * 8);
  copy_bytes(nops, ctx.m_nops.data(), n * 8);
  copy_bytes(hash32, ctx.m_hash.data(), 32 * n);
  copy_bytes(deps_off, ctx.m_deps_off.data(), n * 8);
  deps_off[n] = int64_t(ctx.m_deps.size() / 32);
  copy_bytes(deps_blob, ctx.m_deps.data(), ctx.m_deps.size());
  copy_bytes(msg_off, ctx.m_msg_off.data(), n * 8);
  msg_off[n] = int64_t(ctx.m_msg.size());
  copy_bytes(msg_blob, ctx.m_msg.data(), ctx.m_msg.size());
  copy_bytes(buf_len, ctx.m_buf_len.data(), n * 8);
  return int64_t(n);
}

// ---- batched turbo gate ---------------------------------------------------
//
// The linear-chain causal gate over a whole parsed batch in ONE call,
// replacing the Python side's per-doc hex/dict probes and the numpy
// chain-validation pass (argsort + per-row 32-byte compares). Operates
// directly on the extractor's hash lanes (hash32 / deps_blob are the
// am_ingest_meta_fetch outputs) plus the fleet's columnar per-doc head
// state. Called through ctypes CDLL, so the GIL is released for the
// whole scan.
//
// Per change i of doc d (changes are doc-contiguous, doc_off gives the
// per-doc ranges):
//   - non-first changes must dep on EXACTLY the previous change's hash
//     (deps_count == 1 + 32-byte memcmp against hash32[i-1]);
//   - the doc's first change must dep on the doc's current head
//     frontier: head_n[d] == 0 -> deps_count == 0; head_n[d] == 1 ->
//     deps_count == 1 + memcmp against head32[d]. Docs whose frontier
//     is not columnar-representable (head_n outside {0, 1}) are flagged
//     in doc_hostcheck and the caller re-checks JUST their first-change
//     deps on the host (the rare multi-head case);
//   - per-(doc, actor) seq runs must be contiguous. The first seq of
//     each run is emitted as a group record (g_doc/g_actor/g_first/
//     g_last, capacity n_changes) so the caller can verify the bases
//     against its clock columns vectorized — and scatter g_last back as
//     the clock advance without re-deriving groups.
//
// Any violation clears doc_ok[d] (doc granularity is all the turbo path
// needs: one bad change sends the whole doc to the general gate).
// Returns the group count, or -1 on out-of-range actor ids.
int64_t am_turbo_gate(const int64_t *doc_off, const int32_t *actor,
                      const int64_t *seq, const uint8_t *hash32,
                      const int64_t *deps_off, const uint8_t *deps_blob,
                      const uint8_t *head32, const int32_t *head_n,
                      int64_t n_docs, int64_t n_changes, int64_t n_actors,
                      uint8_t *doc_ok, uint8_t *doc_hostcheck,
                      int32_t *g_doc, int32_t *g_actor, int64_t *g_first,
                      int64_t *g_last) {
  if (n_docs < 0 || n_changes < 0 || n_actors < 0) return -1;
  // per-actor scratch, epoch-tagged per doc: O(1) reset per document
  std::vector<int32_t> a_epoch(size_t(n_actors), -1);
  std::vector<int64_t> a_last(size_t(n_actors), 0);
  std::vector<int64_t> a_group(size_t(n_actors), 0);
  int64_t n_groups = 0;
  for (int64_t d = 0; d < n_docs; d++) {
    int64_t lo = doc_off[d], hi = doc_off[d + 1];
    uint8_t ok = 1;
    doc_hostcheck[d] = 0;
    if (lo > hi || lo < 0 || hi > n_changes) return -1;
    for (int64_t i = lo; i < hi && ok; i++) {
      int64_t dc = deps_off[i + 1] - deps_off[i];
      if (i == lo) {
        int32_t hn = head_n[d];
        if (hn == 0) {
          if (dc != 0) ok = 0;
        } else if (hn == 1) {
          if (dc != 1 ||
              memcmp(deps_blob + deps_off[i] * 32, head32 + d * 32, 32) != 0)
            ok = 0;
        } else {
          doc_hostcheck[d] = 1;  // caller compares against the attr heads
        }
      } else {
        if (dc != 1 ||
            memcmp(deps_blob + deps_off[i] * 32, hash32 + (i - 1) * 32,
                   32) != 0)
          ok = 0;
      }
      int32_t a = actor[i];
      if (a < 0 || a >= n_actors) return -1;
      if (a_epoch[size_t(a)] != int32_t(d)) {
        a_epoch[size_t(a)] = int32_t(d);
        a_group[size_t(a)] = n_groups;
        g_doc[n_groups] = int32_t(d);
        g_actor[n_groups] = a;
        g_first[n_groups] = seq[i];
        g_last[n_groups] = seq[i];
        n_groups++;
      } else {
        if (seq[i] != a_last[size_t(a)] + 1) ok = 0;
        g_last[a_group[size_t(a)]] = seq[i];
      }
      a_last[size_t(a)] = seq[i];
    }
    doc_ok[d] = ok;
  }
  return n_groups;
}

// Copy sequence-op columns captured by am_ingest_changes(with_seq=1).
// Must be called BEFORE am_ingest_fetch (which frees the context).
// Returns row count, or -1 when the context is missing / seq columns were
// not requested (arrays empty while rows exist).
int64_t am_ingest_seq_fetch(int32_t *obj, int32_t *ref, uint8_t *vtype) {
  if (!g_ingest) return -1;
  IngestCtx &ctx = *g_ingest;
  size_t n = ctx.out_obj.size();
  if (n != ctx.out_doc.size() || ctx.out_ref.size() != n ||
      ctx.out_vtype.size() != n)
    return -1;
  copy_bytes(obj, ctx.out_obj.data(), n * 4);
  copy_bytes(ref, ctx.out_ref.data(), n * 4);
  copy_bytes(vtype, ctx.out_vtype.data(), n);
  return int64_t(n);
}

// Number of pred entries captured by the last am_ingest_changes call
// (with_meta=1), so the caller can size the fetch buffer exactly.
// Boxed-value arena size for the pending ingest (with_seq only).
int64_t am_ingest_val_size() {
  return g_ingest ? int64_t(g_ingest->val_arena.size()) : -1;
}

// Copy per-row boxed-value lengths + the raw value arena. Rows with
// vlen == 0 carry inline values (or none); boxed rows' wire bytes
// concatenate in row order. Must run before am_ingest_fetch.
int64_t am_ingest_val_fetch(int32_t *vlen, uint8_t *arena, uint64_t cap) {
  if (!g_ingest) return -1;
  IngestCtx &ctx = *g_ingest;
  if (ctx.out_vlen.size() != ctx.out_doc.size()) return -1;
  if (ctx.val_arena.size() > cap) return -1;
  copy_bytes(vlen, ctx.out_vlen.data(), ctx.out_vlen.size() * 4);
  if (!ctx.val_arena.empty())
    copy_bytes(arena, ctx.val_arena.data(), ctx.val_arena.size());
  return int64_t(ctx.val_arena.size());
}

int64_t am_ingest_pred_count() {
  if (!g_ingest) return -1;
  return int64_t(g_ingest->out_pred.size());
}

// Copy per-op pred lists captured by am_ingest_changes(with_meta=1).
// pred_off receives n_rows+1 prefix offsets. Must be called BEFORE
// am_ingest_fetch (which frees the context). Returns total preds or -1.
int64_t am_ingest_pred_fetch(int64_t *pred_off, int32_t *pred_blob,
                             uint64_t pred_cap) {
  if (!g_ingest) return -1;
  IngestCtx &ctx = *g_ingest;
  size_t n = ctx.out_pred_off.size();
  if (n != ctx.out_doc.size()) return -1;
  if (ctx.out_pred.size() > pred_cap) return -1;
  copy_bytes(pred_off, ctx.out_pred_off.data(), n * 8);
  pred_off[n] = int64_t(ctx.out_pred.size());
  copy_bytes(pred_blob, ctx.out_pred.data(), ctx.out_pred.size() * 4);
  return int64_t(ctx.out_pred.size());
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Batched document-container parse (ref columnar.js:1006-1047): one call
// parses a whole fleet's saved documents straight to flat op/change columns —
// actor tables, heads, change metadata, and document-order op rows with succ
// lists — with NO per-change re-encode or hashing (the deferred-hash-graph
// load of ref new.js:1709-1749). Docs using features outside the flat subset
// (child/link columns, unknown columns, unknown value types, extra bytes)
// get a per-doc ok=0 flag and zero rows; the Python caller routes those
// through the general decode path.
// ---------------------------------------------------------------------------

namespace {

// Known document ops-column ids ((spec << 4) | type; deflate bit 3 cleared)
constexpr int kColIdActor = 0x21, kColIdCtr = 0x23;
constexpr int kColChldActor = 0x61, kColChldCtr = 0x63;
constexpr int kColSuccNum = 0x80, kColSuccActor = 0x81, kColSuccCtr = 0x83;
// Document change-metadata column ids
constexpr int kDocActor = 0x01, kDocSeq = 0x03, kDocMaxOp = 0x13;
constexpr int kDocTime = 0x23, kDocMessage = 0x35;
constexpr int kDocDepsNum = 0x40, kDocDepsIndex = 0x43;
constexpr int kDocExtraLen = 0x56, kDocExtraRaw = 0x57;
constexpr int kDeflateBit = 8;

struct DocParseCtx {
  Interner keys, actors;        // global across the batch
  std::string error;
  // per-doc
  std::vector<uint8_t> d_ok;    // 1 = parsed; 0 = caller falls back
  std::vector<int64_t> d_n_changes, d_n_ops, d_max_op, d_heads_off;
  std::vector<int64_t> d_actor_off;   // into d_actor_ids
  std::vector<int32_t> d_actor_ids;   // per-doc actor table (global ids)
  std::vector<uint8_t> heads;         // 32 bytes per head, concatenated
  // per-change (flat, doc-major)
  std::vector<int32_t> c_doc, c_actor;
  std::vector<int64_t> c_seq, c_max_op;
  // per-op (flat, doc-major, document order)
  std::vector<int32_t> o_doc;
  std::vector<int64_t> o_obj_ctr;     // 0 = root object
  std::vector<int32_t> o_obj_actor;   // global id; -1 = root
  std::vector<int64_t> o_key_ctr;     // elemId counter; 0 = _head/none
  std::vector<int32_t> o_key_actor;   // global id; -1 = none
  std::vector<int32_t> o_key_str;     // interned key; -1 = none (seq op)
  std::vector<uint8_t> o_insert, o_action, o_vtype;
  std::vector<int64_t> o_id_ctr;
  std::vector<int32_t> o_id_actor;    // global id
  std::vector<int64_t> o_val_int;     // int-family value / single codepoint
  std::vector<int64_t> o_val_off;     // into val_blob
  std::vector<int32_t> o_val_len;
  std::vector<uint8_t> val_blob;      // raw value bytes (strings/doubles/...)
  std::vector<int64_t> o_succ_off;    // per op, start index into s_*
  std::vector<int64_t> s_ctr;
  std::vector<int32_t> s_actor;       // global ids
};

static DocParseCtx *g_docparse = nullptr;

// Inflate a raw-DEFLATE column of unknown decompressed size.
static bool inflate_vec(const uint8_t *data, uint64_t len,
                        std::vector<uint8_t> &out) {
  out.clear();
  out.resize(len * 4 + 64);
  z_stream zs;
  memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK) return false;
  zs.next_in = const_cast<uint8_t *>(data);
  zs.avail_in = uInt(len);
  size_t written = 0;
  int ret = Z_OK;
  while (ret != Z_STREAM_END) {
    if (written == out.size()) out.resize(out.size() * 2);
    zs.next_out = out.data() + written;
    zs.avail_out = uInt(out.size() - written);
    ret = inflate(&zs, Z_NO_FLUSH);
    if (ret != Z_OK && ret != Z_STREAM_END) { inflateEnd(&zs); return false; }
    written = out.size() - zs.avail_out;
    if (ret == Z_OK && zs.avail_in == 0 && zs.avail_out != 0) break;
  }
  inflateEnd(&zs);
  out.resize(written);
  return true;
}

struct DocColumn {
  uint32_t id = 0;
  const uint8_t *buf = nullptr;
  uint64_t len = 0;
  std::vector<uint8_t> inflated;  // backing storage when deflated
};

// Parse one document chunk into ctx; returns false (after truncating any
// partial rows) when the doc needs the general Python path.
static bool parse_document_body(DocParseCtx &ctx, const uint8_t *chunk,
                                uint64_t chunk_len, int32_t doc) {
  Cursor c{chunk, chunk_len};
  const uint8_t *magic = c.bytes(4);
  if (c.fail || memcmp(magic, "\x85\x6f\x4a\x83", 4) != 0) return false;
  const uint8_t *checksum = c.bytes(4);
  uint64_t hash_start = c.pos;
  if (c.fail || c.pos >= chunk_len) return false;
  uint8_t chunk_type = chunk[c.pos];
  c.skip(1);
  uint64_t body_len = c.uleb();
  if (c.fail || chunk_type != 0) return false;
  const uint8_t *body = c.bytes(body_len);
  if (c.fail || c.pos != chunk_len) return false;  // trailing data
  uint8_t digest[32];
  {
    Sha256Stream s;
    sha256_stream_init(s);
    sha256_stream_update(s, chunk + hash_start, c.pos - hash_start);
    sha256_stream_final(s, digest);
  }
  if (memcmp(digest, checksum, 4) != 0) return false;

  Cursor b{body, body_len};
  // Actor table
  uint64_t n_actors = b.uleb();
  std::vector<int32_t> local_actors;
  for (uint64_t i = 0; i < n_actors && !b.fail; i++) {
    uint64_t alen = b.uleb();
    const uint8_t *raw = b.bytes(alen);
    if (b.fail) return false;
    static const char *hex = "0123456789abcdef";
    std::string actor_hex;
    actor_hex.reserve(alen * 2);
    for (uint64_t j = 0; j < alen; j++) {
      actor_hex.push_back(hex[raw[j] >> 4]);
      actor_hex.push_back(hex[raw[j] & 15]);
    }
    local_actors.push_back(ctx.actors.intern(actor_hex));
  }
  if (b.fail) return false;
  // Heads
  uint64_t n_heads = b.uleb();
  if (b.fail) return false;
  size_t heads_start = ctx.heads.size();
  for (uint64_t i = 0; i < n_heads; i++) {
    const uint8_t *h = b.bytes(32);
    if (b.fail) { ctx.heads.resize(heads_start); return false; }
    ctx.heads.insert(ctx.heads.end(), h, h + 32);
  }
  auto bail = [&]() { ctx.heads.resize(heads_start); return false; };

  // Column info tables (ids ascending; only non-empty columns present)
  auto read_col_info = [&](std::vector<DocColumn> &cols) -> bool {
    uint64_t n = b.uleb();
    if (b.fail) return false;
    for (uint64_t i = 0; i < n; i++) {
      DocColumn col;
      col.id = uint32_t(b.uleb());
      col.len = b.uleb();
      if (b.fail) return false;
      cols.push_back(col);
    }
    return true;
  };
  std::vector<DocColumn> ccols, ocols;
  if (!read_col_info(ccols) || !read_col_info(ocols)) return bail();
  for (auto *cols : {&ccols, &ocols}) {
    for (auto &col : *cols) {
      col.buf = b.bytes(col.len);
      if (b.fail) return bail();
      if (col.id & kDeflateBit) {
        if (!inflate_vec(col.buf, col.len, col.inflated)) return bail();
        col.id &= ~uint32_t(kDeflateBit);
        col.buf = col.inflated.data();
        col.len = col.inflated.size();
      }
    }
  }
  // headsIndexes (n_heads ulebs, optional) then extraBytes; any non-empty
  // extraBytes must be preserved -> general path
  if (b.pos < b.len) {
    for (uint64_t i = 0; i < n_heads; i++) b.uleb();
    if (b.fail || b.pos != b.len) return bail();
  }

  auto find = [](std::vector<DocColumn> &cols, uint32_t id) -> DocColumn * {
    for (auto &col : cols) if (col.id == id) return &col;
    return nullptr;
  };

  // ---- change metadata: actor / seq / maxOp (rest lazily via Python) ----
  for (auto &col : ccols) {
    switch (col.id) {
      case kDocActor: case kDocSeq: case kDocMaxOp: case kDocTime:
      case kDocMessage: case kDocDepsNum: case kDocDepsIndex:
      case kDocExtraLen: case kDocExtraRaw:
        break;
      default:
        return bail();      // unknown change-meta column
    }
  }
  std::vector<int64_t> cm_actor, cm_seq, cm_maxop;
  std::vector<uint8_t> m1, m2, m3;
  DocColumn *col_a = find(ccols, kDocActor);
  DocColumn *col_s = find(ccols, kDocSeq);
  DocColumn *col_m = find(ccols, kDocMaxOp);
  if (col_a && !decode_i64_col(col_a->buf, col_a->len, false, false,
                               cm_actor, m1))
    return bail();
  if (col_s && !decode_i64_col(col_s->buf, col_s->len, false, true,
                               cm_seq, m2))
    return bail();
  if (col_m && !decode_i64_col(col_m->buf, col_m->len, false, true,
                               cm_maxop, m3))
    return bail();
  size_t n_changes = cm_actor.size();
  if (cm_seq.size() != n_changes || cm_maxop.size() != n_changes)
    return bail();
  for (size_t i = 0; i < n_changes; i++) {
    if (!m1[i] || !m2[i] || !m3[i]) return bail();
    if (cm_actor[i] < 0 || uint64_t(cm_actor[i]) >= local_actors.size())
      return bail();
  }

  // ---- ops columns ----
  for (auto &col : ocols) {
    switch (col.id) {
      case kColObjActor: case kColObjCtr: case kColKeyActor: case kColKeyCtr:
      case kColKeyStr: case kColIdActor: case kColIdCtr: case kColInsert:
      case kColAction: case kColValLen: case kColValRaw:
      case kColSuccNum: case kColSuccActor: case kColSuccCtr:
        break;
      case kColChldActor: case kColChldCtr:
        if (col.len > 0) return bail();  // child/link ops: general path
        break;
      default:
        return bail();      // unknown ops column: must be preserved
    }
  }
  auto dec = [&](uint32_t id, bool is_signed, bool is_delta,
                 std::vector<int64_t> &vals, std::vector<uint8_t> &mask) {
    DocColumn *col = find(ocols, id);
    if (!col) { vals.clear(); mask.clear(); return true; }
    return decode_i64_col(col->buf, col->len, is_signed, is_delta, vals,
                          mask);
  };
  std::vector<int64_t> obj_actor, obj_ctr, key_actor, key_ctr, id_actor,
      id_ctr, insert_v, action_v, val_len, succ_num, succ_actor, succ_ctr;
  std::vector<uint8_t> obj_actor_m, obj_ctr_m, key_actor_m, key_ctr_m,
      id_actor_m, id_ctr_m, insert_m, action_m, val_len_m, succ_num_m,
      succ_actor_m, succ_ctr_m;
  if (!dec(kColObjActor, false, false, obj_actor, obj_actor_m)) return bail();
  if (!dec(kColObjCtr, false, false, obj_ctr, obj_ctr_m)) return bail();
  if (!dec(kColKeyActor, false, false, key_actor, key_actor_m)) return bail();
  if (!dec(kColKeyCtr, false, true, key_ctr, key_ctr_m)) return bail();
  if (!dec(kColIdActor, false, false, id_actor, id_actor_m)) return bail();
  if (!dec(kColIdCtr, false, true, id_ctr, id_ctr_m)) return bail();
  if (!dec(kColAction, false, false, action_v, action_m)) return bail();
  if (!dec(kColValLen, false, false, val_len, val_len_m)) return bail();
  if (!dec(kColSuccNum, false, false, succ_num, succ_num_m)) return bail();
  if (!dec(kColSuccActor, false, false, succ_actor, succ_actor_m))
    return bail();
  if (!dec(kColSuccCtr, false, true, succ_ctr, succ_ctr_m)) return bail();
  size_t n_ops = id_ctr.size();
  if (id_actor.size() != n_ops || action_v.size() != n_ops) return bail();
  {
    DocColumn *col = find(ocols, kColInsert);
    insert_v.resize(n_ops);
    insert_m.resize(n_ops);
    if (col) {
      int64_t n = am_decode_boolean(col->buf, col->len, insert_v.data(),
                                    insert_m.data(), int64_t(n_ops));
      if (n != int64_t(n_ops)) return bail();
    } else if (n_ops) {
      return bail();
    }
  }
  // keyStr: interned string ids, -1 for null rows
  std::vector<int32_t> key_str;
  {
    DocColumn *col = find(ocols, kColKeyStr);
    if (col) {
      if (!decode_keystr(col->buf, col->len, ctx.keys, key_str))
        return bail();
      if (key_str.size() != n_ops) return bail();
    } else {
      key_str.assign(n_ops, -1);
    }
  }
  // Columns that can be all-null (absent): size them as null rows
  auto pad_null = [&](std::vector<int64_t> &vals, std::vector<uint8_t> &mask) {
    if (vals.empty()) { vals.assign(n_ops, 0); mask.assign(n_ops, 0); }
    return vals.size() == n_ops;
  };
  if (!pad_null(obj_actor, obj_actor_m) || !pad_null(obj_ctr, obj_ctr_m) ||
      !pad_null(key_actor, key_actor_m) || !pad_null(key_ctr, key_ctr_m) ||
      !pad_null(val_len, val_len_m) || !pad_null(succ_num, succ_num_m))
    return bail();
  // succ group: total entries must match the sum of succNum
  uint64_t succ_total = 0;
  for (size_t i = 0; i < n_ops; i++)
    succ_total += succ_num_m[i] ? uint64_t(succ_num[i]) : 0;
  if (succ_actor.size() != succ_total || succ_ctr.size() != succ_total)
    return bail();
  DocColumn *vraw = find(ocols, kColValRaw);
  const uint8_t *raw_buf = vraw ? vraw->buf : nullptr;
  uint64_t raw_len = vraw ? vraw->len : 0;

  // ---- emit rows (rollback on any failure) ----
  size_t ops_start = ctx.o_doc.size();
  size_t succ_start = ctx.s_ctr.size();
  size_t val_start = ctx.val_blob.size();
  auto bail_rows = [&]() {
    ctx.o_doc.resize(ops_start);
    ctx.o_obj_ctr.resize(ops_start);
    ctx.o_obj_actor.resize(ops_start);
    ctx.o_key_ctr.resize(ops_start);
    ctx.o_key_actor.resize(ops_start);
    ctx.o_key_str.resize(ops_start);
    ctx.o_insert.resize(ops_start);
    ctx.o_action.resize(ops_start);
    ctx.o_vtype.resize(ops_start);
    ctx.o_id_ctr.resize(ops_start);
    ctx.o_id_actor.resize(ops_start);
    ctx.o_val_int.resize(ops_start);
    ctx.o_val_off.resize(ops_start);
    ctx.o_val_len.resize(ops_start);
    ctx.o_succ_off.resize(ops_start);
    ctx.s_ctr.resize(succ_start);
    ctx.s_actor.resize(succ_start);
    ctx.val_blob.resize(val_start);
    return bail();
  };
  uint64_t raw_pos = 0;
  uint64_t succ_pos = 0;
  for (size_t i = 0; i < n_ops; i++) {
    if (!id_actor_m[i] || !id_ctr_m[i] || !action_m[i]) return bail_rows();
    int64_t action = action_v[i];
    if (action < 0 || action > 6 || action == 3) return bail_rows();
    // (action 3 = del: documents never store del rows, columnar.js:892;
    //  action 7 = link and anything higher: general path)
    if (uint64_t(id_actor[i]) >= local_actors.size()) return bail_rows();
    if (obj_actor_m[i] != obj_ctr_m[i]) return bail_rows();
    if (obj_actor_m[i] && uint64_t(obj_actor[i]) >= local_actors.size())
      return bail_rows();
    if (key_actor_m[i] && uint64_t(key_actor[i]) >= local_actors.size())
      return bail_rows();
    // elemId columns must be consistent: a non-zero keyCtr needs its actor
    // (keyCtr==0 with null actor is the legal _head encoding), and an
    // actor without a counter is malformed — aliasing either to actor 0
    // would target the wrong element
    if (key_ctr_m[i] && !key_actor_m[i] && key_ctr[i] != 0)
      return bail_rows();
    if (key_actor_m[i] && !key_ctr_m[i]) return bail_rows();
    // value
    uint8_t vtype = 0;
    int64_t vint = 0, voff = 0;
    int32_t vlen = 0;
    if (val_len_m[i]) {
      uint64_t tag = uint64_t(val_len[i]);
      vtype = uint8_t(tag & 0xf);
      vlen = int32_t(tag >> 4);
      if (vtype >= 10) return bail_rows();      // unknown value types
      if (raw_pos + uint64_t(vlen) > raw_len) return bail_rows();
      voff = int64_t(ctx.val_blob.size());
      ctx.val_blob.insert(ctx.val_blob.end(), raw_buf + raw_pos,
                          raw_buf + raw_pos + vlen);
      if (vtype == 3 || vtype == 4 || vtype == 8 || vtype == 9) {
        uint64_t p = 0;
        int err = 0;
        vint = (vtype == 3)
            ? int64_t(read_uleb(raw_buf + raw_pos, vlen, &p, &err))
            : read_sleb(raw_buf + raw_pos, vlen, &p, &err);
        if (err || p != uint64_t(vlen)) return bail_rows();
      } else if (vtype == 6) {
        vint = utf8_single_cp(raw_buf + raw_pos, vlen);  // -1 = multi-char
      }
      raw_pos += uint64_t(vlen);
    }
    ctx.o_doc.push_back(doc);
    ctx.o_obj_ctr.push_back(obj_ctr_m[i] ? obj_ctr[i] : 0);
    ctx.o_obj_actor.push_back(
        obj_actor_m[i] ? local_actors[size_t(obj_actor[i])] : -1);
    ctx.o_key_ctr.push_back(key_ctr_m[i] ? key_ctr[i] : 0);
    ctx.o_key_actor.push_back(
        key_actor_m[i] ? local_actors[size_t(key_actor[i])] : -1);
    ctx.o_key_str.push_back(key_str[i]);
    ctx.o_insert.push_back(uint8_t(insert_m[i] ? insert_v[i] : 0));
    ctx.o_action.push_back(uint8_t(action));
    ctx.o_vtype.push_back(vtype);
    ctx.o_id_ctr.push_back(id_ctr[i]);
    ctx.o_id_actor.push_back(local_actors[size_t(id_actor[i])]);
    ctx.o_val_int.push_back(vint);
    ctx.o_val_off.push_back(voff);
    ctx.o_val_len.push_back(vlen);
    ctx.o_succ_off.push_back(int64_t(succ_start + succ_pos));
    uint64_t num = succ_num_m[i] ? uint64_t(succ_num[i]) : 0;
    for (uint64_t k = 0; k < num; k++, succ_pos++) {
      if (!succ_actor_m[succ_pos] || !succ_ctr_m[succ_pos])
        return bail_rows();
      if (uint64_t(succ_actor[succ_pos]) >= local_actors.size())
        return bail_rows();
      ctx.s_ctr.push_back(succ_ctr[succ_pos]);
      ctx.s_actor.push_back(local_actors[size_t(succ_actor[succ_pos])]);
    }
  }
  if (raw_pos != raw_len || succ_pos != succ_total) return bail_rows();

  // ---- commit per-doc/per-change metadata ----
  int64_t max_op = 0;
  for (size_t i = 0; i < n_changes; i++) {
    ctx.c_doc.push_back(doc);
    ctx.c_actor.push_back(local_actors[size_t(cm_actor[i])]);
    ctx.c_seq.push_back(cm_seq[i]);
    ctx.c_max_op.push_back(cm_maxop[i]);
    if (cm_maxop[i] > max_op) max_op = cm_maxop[i];
  }
  ctx.d_n_changes.push_back(int64_t(n_changes));
  ctx.d_n_ops.push_back(int64_t(n_ops));
  ctx.d_max_op.push_back(max_op);
  ctx.d_heads_off.push_back(int64_t(heads_start / 32));
  ctx.d_actor_off.push_back(int64_t(ctx.d_actor_ids.size()));
  ctx.d_actor_ids.insert(ctx.d_actor_ids.end(), local_actors.begin(),
                         local_actors.end());
  return true;
}

}  // namespace

extern "C" {

// Parse a batch of document chunks. Returns total op rows across parsed
// docs, or -1 on allocation-level failure. Per-doc failures set ok=0 and
// contribute no rows (the caller falls back per doc).
int64_t am_parse_documents(const uint8_t *blob, const uint64_t *offsets,
                           const uint64_t *lens, uint64_t n_docs) {
  delete g_docparse;
  g_docparse = new DocParseCtx();
  DocParseCtx &ctx = *g_docparse;
  for (uint64_t d = 0; d < n_docs; d++) {
    size_t nc = ctx.c_doc.size();
    bool ok = parse_document_body(ctx, blob + offsets[d], lens[d],
                                  int32_t(d));
    if (!ok) {
      // parse_document_body rolls back rows/heads; change meta may remain
      ctx.c_doc.resize(nc);
      ctx.c_actor.resize(nc);
      ctx.c_seq.resize(nc);
      ctx.c_max_op.resize(nc);
      ctx.d_ok.push_back(0);
      ctx.d_n_changes.push_back(0);
      ctx.d_n_ops.push_back(0);
      ctx.d_max_op.push_back(0);
      ctx.d_heads_off.push_back(int64_t(ctx.heads.size() / 32));
      ctx.d_actor_off.push_back(int64_t(ctx.d_actor_ids.size()));
    } else {
      ctx.d_ok.push_back(1);
    }
  }
  return int64_t(ctx.o_doc.size());
}

// Sizes needed to allocate fetch buffers. Returns 0, or -1 with no context.
int64_t am_docparse_sizes(int64_t *n_changes, int64_t *n_succ,
                          int64_t *n_heads, int64_t *val_bytes,
                          int64_t *actor_blob_bytes, int64_t *n_actors,
                          int64_t *key_blob_bytes, int64_t *n_keys,
                          int64_t *n_doc_actors) {
  if (!g_docparse) return -1;
  DocParseCtx &ctx = *g_docparse;
  auto blob_size = [](const std::vector<std::string> &items) -> int64_t {
    uint64_t pos = 0;
    for (const auto &s : items) {
      uint64_t v = s.size();
      do { pos++; v >>= 7; } while (v);
      pos += s.size();
    }
    return int64_t(pos);
  };
  *n_changes = int64_t(ctx.c_doc.size());
  *n_succ = int64_t(ctx.s_ctr.size());
  *n_heads = int64_t(ctx.heads.size() / 32);
  *val_bytes = int64_t(ctx.val_blob.size());
  *actor_blob_bytes = blob_size(ctx.actors.items);
  *n_actors = int64_t(ctx.actors.items.size());
  *key_blob_bytes = blob_size(ctx.keys.items);
  *n_keys = int64_t(ctx.keys.items.size());
  *n_doc_actors = int64_t(ctx.d_actor_ids.size());
  return 0;
}

// Copy out every parsed array. Array sizes follow am_parse_documents'
// return (n_ops) and am_docparse_sizes. Frees the context on success.
int64_t am_docparse_fetch(
    uint8_t *d_ok, int64_t *d_n_changes, int64_t *d_n_ops, int64_t *d_max_op,
    int64_t *d_heads_off, int64_t *d_actor_off, int32_t *d_actor_ids,
    uint8_t *heads,
    int32_t *c_doc, int32_t *c_actor, int64_t *c_seq, int64_t *c_max_op,
    int32_t *o_doc, int64_t *o_obj_ctr, int32_t *o_obj_actor,
    int64_t *o_key_ctr, int32_t *o_key_actor, int32_t *o_key_str,
    uint8_t *o_insert, uint8_t *o_action, uint8_t *o_vtype,
    int64_t *o_id_ctr, int32_t *o_id_actor,
    int64_t *o_val_int, int64_t *o_val_off, int32_t *o_val_len,
    uint8_t *val_blob, int64_t *o_succ_off, int64_t *s_ctr, int32_t *s_actor,
    uint8_t *key_blob, uint64_t key_blob_cap,
    uint8_t *actor_blob, uint64_t actor_blob_cap) {
  if (!g_docparse) return -1;
  DocParseCtx &ctx = *g_docparse;
  size_t nd = ctx.d_ok.size(), nc = ctx.c_doc.size(), no = ctx.o_doc.size();
  copy_bytes(d_ok, ctx.d_ok.data(), nd);
  copy_bytes(d_n_changes, ctx.d_n_changes.data(), nd * 8);
  copy_bytes(d_n_ops, ctx.d_n_ops.data(), nd * 8);
  copy_bytes(d_max_op, ctx.d_max_op.data(), nd * 8);
  copy_bytes(d_heads_off, ctx.d_heads_off.data(), nd * 8);
  d_heads_off[nd] = int64_t(ctx.heads.size() / 32);
  copy_bytes(d_actor_off, ctx.d_actor_off.data(), nd * 8);
  d_actor_off[nd] = int64_t(ctx.d_actor_ids.size());
  copy_bytes(d_actor_ids, ctx.d_actor_ids.data(), ctx.d_actor_ids.size() * 4);
  copy_bytes(heads, ctx.heads.data(), ctx.heads.size());
  copy_bytes(c_doc, ctx.c_doc.data(), nc * 4);
  copy_bytes(c_actor, ctx.c_actor.data(), nc * 4);
  copy_bytes(c_seq, ctx.c_seq.data(), nc * 8);
  copy_bytes(c_max_op, ctx.c_max_op.data(), nc * 8);
  copy_bytes(o_doc, ctx.o_doc.data(), no * 4);
  copy_bytes(o_obj_ctr, ctx.o_obj_ctr.data(), no * 8);
  copy_bytes(o_obj_actor, ctx.o_obj_actor.data(), no * 4);
  copy_bytes(o_key_ctr, ctx.o_key_ctr.data(), no * 8);
  copy_bytes(o_key_actor, ctx.o_key_actor.data(), no * 4);
  copy_bytes(o_key_str, ctx.o_key_str.data(), no * 4);
  copy_bytes(o_insert, ctx.o_insert.data(), no);
  copy_bytes(o_action, ctx.o_action.data(), no);
  copy_bytes(o_vtype, ctx.o_vtype.data(), no);
  copy_bytes(o_id_ctr, ctx.o_id_ctr.data(), no * 8);
  copy_bytes(o_id_actor, ctx.o_id_actor.data(), no * 4);
  copy_bytes(o_val_int, ctx.o_val_int.data(), no * 8);
  copy_bytes(o_val_off, ctx.o_val_off.data(), no * 8);
  copy_bytes(o_val_len, ctx.o_val_len.data(), no * 4);
  copy_bytes(val_blob, ctx.val_blob.data(), ctx.val_blob.size());
  copy_bytes(o_succ_off, ctx.o_succ_off.data(), no * 8);
  o_succ_off[no] = int64_t(ctx.s_ctr.size());
  copy_bytes(s_ctr, ctx.s_ctr.data(), ctx.s_ctr.size() * 8);
  copy_bytes(s_actor, ctx.s_actor.data(), ctx.s_actor.size() * 4);

  auto write_blob = [](const std::vector<std::string> &items, uint8_t *out,
                       uint64_t cap) -> int64_t {
    uint64_t pos = 0;
    for (const auto &s : items) {
      uint64_t len = s.size();
      uint64_t v = len;
      do {
        if (pos >= cap) return -1;
        uint8_t byte = v & 0x7f;
        v >>= 7;
        out[pos++] = byte | (v ? 0x80 : 0);
      } while (v);
      if (pos + len > cap) return -1;
      copy_bytes(out + pos, s.data(), len);
      pos += len;
    }
    return int64_t(pos);
  };
  if (write_blob(ctx.keys.items, key_blob, key_blob_cap) < 0) return -1;
  if (write_blob(ctx.actors.items, actor_blob, actor_blob_cap) < 0) return -1;
  delete g_docparse;
  g_docparse = nullptr;
  return int64_t(no);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native document builder: change log -> canonical document container
// (the mirror-free save of round-2 VERDICT item 8). Parses the engine's
// binary changes (full op coverage), replays them into a succ-annotated op
// store (the visibility model of ref new.js:1204-1217, RGA insertion of
// new.js:145-163), and serializes the document chunk (ref
// columnar.js:983-1004) with the same canonical change order and byte-exact
// column encodings as the host engine's save() — no host mirror, no Python
// per-op work. Bails (caller falls back to the Python path) on link/child
// ops, unknown columns, or malformed histories.
// ---------------------------------------------------------------------------

#include <algorithm>
#include <list>
#include <map>
#include <queue>

namespace {

// ---- byte-exact column encoders (mirroring automerge_tpu/encoding.py) ----

struct ByteBuf {
  std::vector<uint8_t> b;
  void u8(uint8_t v) { b.push_back(v); }
  void uleb(uint64_t v) {
    do {
      uint8_t byte = v & 0x7f;
      v >>= 7;
      b.push_back(byte | (v ? 0x80 : 0));
    } while (v);
  }
  void sleb(int64_t v) {
    bool more = true;
    while (more) {
      uint8_t byte = v & 0x7f;
      v >>= 7;
      if ((v == 0 && !(byte & 0x40)) || (v == -1 && (byte & 0x40)))
        more = false;
      b.push_back(byte | (more ? 0x80 : 0));
    }
  }
  void raw(const uint8_t *p, size_t n) { b.insert(b.end(), p, p + n); }
  void prefixed(const std::string &s) {
    uleb(s.size());
    raw((const uint8_t *)s.data(), s.size());
  }
};

// RLE encoder over int64 values (uint/int wire flavors) or strings, with
// nulls; exact state machine of encoding.py RLEEncoder.
struct RleEnc {
  enum Type { UINT, INT, UTF8 } type;
  enum State { EMPTY, LONE, REP, LIT, NULLS } state = EMPTY;
  ByteBuf out;
  int64_t last_i = 0;
  std::string last_s;
  bool last_null = false;
  uint64_t count = 0;
  std::vector<std::pair<int64_t, std::string>> literal;

  explicit RleEnc(Type t) : type(t) {}

  void raw_value(int64_t vi, const std::string &vs) {
    if (type == UINT) out.uleb(uint64_t(vi));
    else if (type == INT) out.sleb(vi);
    else out.prefixed(vs);
  }
  bool eq_last(bool is_null, int64_t vi, const std::string &vs) const {
    if (last_null || is_null) return last_null == is_null;
    return type == UTF8 ? last_s == vs : last_i == vi;
  }
  void set_last(bool is_null, int64_t vi, const std::string &vs) {
    last_null = is_null;
    last_i = vi;
    last_s = vs;
  }
  void flush() {
    if (state == LONE) {
      out.sleb(-1);
      raw_value(last_i, last_s);
    } else if (state == REP) {
      out.sleb(int64_t(count));
      raw_value(last_i, last_s);
    } else if (state == LIT) {
      out.sleb(-int64_t(literal.size()));
      for (auto &v : literal) raw_value(v.first, v.second);
      literal.clear();
    } else if (state == NULLS) {
      out.sleb(0);
      out.uleb(count);
    }
    state = EMPTY;
  }
  void append(bool is_null, int64_t vi, const std::string &vs,
              uint64_t reps = 1) {
    if (reps == 0) return;
    if (state == EMPTY) {
      state = is_null ? NULLS : (reps == 1 ? LONE : REP);
      set_last(is_null, vi, vs);
      count = reps;
    } else if (state == LONE) {
      if (is_null) {
        flush(); state = NULLS; count = reps;
      } else if (eq_last(false, vi, vs)) {
        state = REP; count = 1 + reps;
      } else if (reps > 1) {
        flush(); state = REP; count = reps; set_last(false, vi, vs);
      } else {
        state = LIT;
        literal.clear();
        literal.emplace_back(last_i, last_s);
        set_last(false, vi, vs);
      }
    } else if (state == REP) {
      if (is_null) {
        flush(); state = NULLS; count = reps;
      } else if (eq_last(false, vi, vs)) {
        count += reps;
      } else if (reps > 1) {
        flush(); state = REP; count = reps; set_last(false, vi, vs);
      } else {
        flush(); state = LONE; set_last(false, vi, vs);
      }
    } else if (state == LIT) {
      if (is_null) {
        literal.emplace_back(last_i, last_s);
        flush(); state = NULLS; count = reps;
      } else if (eq_last(false, vi, vs)) {
        flush(); state = REP; count = 1 + reps;
      } else if (reps > 1) {
        literal.emplace_back(last_i, last_s);
        flush(); state = REP; count = reps; set_last(false, vi, vs);
      } else {
        literal.emplace_back(last_i, last_s);
        set_last(false, vi, vs);
      }
    } else {  // NULLS
      if (is_null) {
        count += reps;
      } else if (reps > 1) {
        flush(); state = REP; count = reps; set_last(false, vi, vs);
      } else {
        flush(); state = LONE; set_last(false, vi, vs);
      }
    }
  }
  void value(int64_t v) { append(false, v, std::string()); }
  void str(const std::string &s) { append(false, 0, s); }
  void null_() { append(true, 0, std::string()); }
  void finish() {
    if (state == LIT) literal.emplace_back(last_i, last_s);
    // an all-null sequence encodes to nothing (encoding.py finish)
    if (state != NULLS || !out.b.empty()) flush();
  }
};

// Delta encoder: RLE('int') over successive differences (encoding.py).
struct DeltaEnc {
  RleEnc rle{RleEnc::INT};
  int64_t absolute = 0;
  void value(int64_t v) {
    rle.append(false, v - absolute, std::string());
    absolute = v;
  }
  void null_() { rle.null_(); }
  void finish() { rle.finish(); }
};

// Boolean encoder: alternating false/true run lengths starting with false.
struct BoolEnc {
  ByteBuf out;
  bool last = false;
  uint64_t count = 0;
  void value(bool v) {
    if (last == v) {
      count++;
    } else {
      out.uleb(count);
      last = v;
      count = 1;
    }
  }
  void finish() {
    if (count > 0) {
      out.uleb(count);
      count = 0;
    }
  }
};

// ---- parsed change / op store --------------------------------------------

struct BOp {
  int64_t ctr;                 // own opId counter
  int32_t actor;               // own actor (doc-table number, hex-sorted)
  uint8_t action;              // wire action 0..6
  uint8_t insert;
  int8_t key_kind;             // 0 = map key, 1 = _head, 2 = elemId
  std::string key;             // map key (utf8)
  int64_t ek_ctr = 0;          // elemId ref (insert: original referent;
  int32_t ek_actor = -1;       //  update: target element)
  int64_t obj_ctr = 0;         // containing object (0/-1 = root)
  int32_t obj_actor = -1;
  uint32_t vtag = 0;           // valLen tag (len<<4 | type)
  uint64_t voff = 0;           // into BuildCtx::vals
  std::vector<std::pair<int64_t, int32_t>> pred;
};

struct BChange {
  std::string actor_hex;
  int32_t actor = 0;
  uint64_t seq = 0, start_op = 0;
  int64_t time = 0;
  std::string message;
  std::vector<std::string> deps;     // dep hashes (hex)
  std::string hash;                  // own hash (hex)
  std::string extra;                 // change-level extra bytes
  std::vector<BOp> ops;
};

struct BRow {
  int64_t ctr;
  int32_t actor;
  uint8_t action;
  uint8_t insert;
  int8_t key_kind;
  int64_t ek_ctr;
  int32_t ek_actor;
  uint32_t vtag;
  uint64_t voff;
  std::vector<std::pair<int64_t, int32_t>> succ;   // kept lamport-sorted
};

struct BElem {
  int64_t ctr;
  int32_t actor;
  std::vector<BRow> rows;
};

struct BObj {
  uint8_t type = 0;              // wire make action; root = 0 (map)
  bool is_seq = false;
  // map keys sorted by UTF-16 code units (op_set._utf16_key)
  std::map<std::u16string, std::vector<BRow>> keys;
  std::map<std::u16string, std::string> key_utf8;
  std::list<BElem> elems;
  std::unordered_map<int64_t, std::list<BElem>::iterator> elem_index;
};

struct BuildCtx {
  std::vector<BChange> changes;
  std::vector<std::string> actors;             // hex-sorted doc actor table
  std::unordered_map<std::string, int32_t> actor_index;
  std::map<std::pair<int64_t, int32_t>, BObj> objects;  // (ctr, actor)
  BObj root;
  std::vector<uint8_t> vals;                   // raw value bytes arena
  std::vector<uint8_t> result;
  std::string error;
};

static bool utf8_to_u16(const std::string &s, std::u16string &out) {
  size_t i = 0;
  out.clear();
  while (i < s.size()) {
    uint8_t b = s[i];
    uint32_t cp;
    size_t need;
    if (b < 0x80) { cp = b; need = 1; }
    else if ((b >> 5) == 6) { cp = b & 0x1f; need = 2; }
    else if ((b >> 4) == 14) { cp = b & 0x0f; need = 3; }
    else if ((b >> 3) == 30) { cp = b & 0x07; need = 4; }
    else return false;
    if (i + need > s.size()) return false;
    for (size_t k = 1; k < need; k++) {
      if ((uint8_t(s[i + k]) >> 6) != 2) return false;
      cp = (cp << 6) | (uint8_t(s[i + k]) & 0x3f);
    }
    i += need;
    if (cp >= 0x10000) {
      cp -= 0x10000;
      out.push_back(char16_t(0xd800 + (cp >> 10)));
      out.push_back(char16_t(0xdc00 + (cp & 0x3ff)));
    } else {
      out.push_back(char16_t(cp));
    }
  }
  return true;
}

static const char *kHex = "0123456789abcdef";

static std::string to_hex(const uint8_t *p, size_t n) {
  std::string s;
  s.reserve(n * 2);
  for (size_t i = 0; i < n; i++) {
    s.push_back(kHex[p[i] >> 4]);
    s.push_back(kHex[p[i] & 15]);
  }
  return s;
}

// Parse one change chunk (full op coverage; link/child/unknown bail).
// Pass 1 (actors_only): just collect the author hex id.
static bool build_parse_change(BuildCtx &ctx, const uint8_t *chunk,
                               uint64_t chunk_len, bool actors_only,
                               std::vector<uint8_t> &inflate_scratch) {
  // container: magic, checksum, type, length
  if (chunk_len < 11) return false;
  if (memcmp(chunk, "\x85\x6f\x4a\x83", 4) != 0) return false;
  uint8_t chunk_type = chunk[8];
  if (chunk_type == 2) {  // deflated change: inflate body, rebuild chunk
    Cursor c{chunk, chunk_len};
    c.skip(9);
    uint64_t blen = c.uleb();
    const uint8_t *body = c.bytes(blen);
    if (c.fail || c.pos != chunk_len) return false;
    std::vector<uint8_t> raw;
    if (!inflate_vec(body, blen, raw)) return false;
    // Reconstruct the uncompressed chunk (magic + original checksum +
    // type 1 + LEB length + inflated body): the change hash is defined
    // over exactly these bytes (columnar.js:688-708). The recursive call
    // sees chunk type 1 and never touches the scratch it is reading from.
    std::vector<uint8_t> rebuilt(chunk, chunk + 8);
    rebuilt.push_back(1);
    uint64_t v = raw.size();
    do {
      uint8_t byte = v & 0x7f;
      v >>= 7;
      rebuilt.push_back(byte | (v ? 0x80 : 0));
    } while (v);
    rebuilt.insert(rebuilt.end(), raw.begin(), raw.end());
    return build_parse_change(ctx, rebuilt.data(), rebuilt.size(),
                              actors_only, inflate_scratch);
  }
  if (chunk_type != 1) return false;
  Cursor c{chunk, chunk_len};
  c.skip(8);
  uint64_t hash_start = c.pos;
  c.skip(1);
  uint64_t body_len = c.uleb();
  const uint8_t *body = c.bytes(body_len);
  if (c.fail || c.pos != chunk_len) return false;

  BChange ch;
  {
    uint8_t digest[32];
    Sha256Stream s;
    sha256_stream_init(s);
    sha256_stream_update(s, chunk + hash_start, c.pos - hash_start);
    sha256_stream_final(s, digest);
    ch.hash = to_hex(digest, 32);
  }

  Cursor b{body, body_len};
  uint64_t n_deps = b.uleb();
  for (uint64_t i = 0; i < n_deps; i++) {
    const uint8_t *h = b.bytes(32);
    if (b.fail) return false;
    ch.deps.push_back(to_hex(h, 32));
  }
  uint64_t alen = b.uleb();
  const uint8_t *araw = b.bytes(alen);
  if (b.fail) return false;
  ch.actor_hex = to_hex(araw, alen);
  ch.seq = b.uleb();
  ch.start_op = b.uleb();
  ch.time = b.sleb();
  uint64_t mlen = b.uleb();
  const uint8_t *mraw = b.bytes(mlen);
  if (b.fail) return false;
  ch.message.assign((const char *)mraw, mlen);
  // other actors referenced by this change's op columns
  std::vector<std::string> chg_actors{ch.actor_hex};
  uint64_t n_more = b.uleb();
  for (uint64_t i = 0; i < n_more; i++) {
    uint64_t l = b.uleb();
    const uint8_t *p = b.bytes(l);
    if (b.fail) return false;
    chg_actors.push_back(to_hex(p, l));
  }
  if (actors_only) {
    ctx.changes.push_back(std::move(ch));
    return true;
  }

  // column info + buffers
  std::vector<DocColumn> cols;
  uint64_t n_cols = b.uleb();
  if (b.fail) return false;
  for (uint64_t i = 0; i < n_cols; i++) {
    DocColumn col;
    col.id = uint32_t(b.uleb());
    col.len = b.uleb();
    if (b.fail) return false;
    cols.push_back(col);
  }
  for (auto &col : cols) {
    col.buf = b.bytes(col.len);
    if (b.fail) return false;
    if (col.id & kDeflateBit) {
      if (!inflate_vec(col.buf, col.len, col.inflated)) return false;
      col.id &= ~uint32_t(kDeflateBit);
      col.buf = col.inflated.data();
      col.len = col.inflated.size();
    }
  }
  if (b.pos != b.len) {
    // change-level extraBytes: preserved through the changes columns
    ch.extra.assign((const char *)(body + b.pos), body_len - b.pos);
  }
  for (auto &col : cols) {
    switch (col.id) {
      case kColObjActor: case kColObjCtr: case kColKeyActor: case kColKeyCtr:
      case kColKeyStr: case kColInsert: case kColAction: case kColValLen:
      case kColValRaw: case kColPredNum: case kColPredActor: case kColPredCtr:
        break;
      case kColChldActor: case kColChldCtr:
        if (col.len > 0) return false;   // link/child ops: Python path
        break;
      default:
        return false;                    // unknown columns: Python path
    }
  }
  auto find = [&](uint32_t id) -> DocColumn * {
    for (auto &col : cols) if (col.id == id) return &col;
    return nullptr;
  };
  auto dec = [&](uint32_t id, bool sgn, bool delta, std::vector<int64_t> &v,
                 std::vector<uint8_t> &m) {
    DocColumn *col = find(id);
    if (!col) { v.clear(); m.clear(); return true; }
    return decode_i64_col(col->buf, col->len, sgn, delta, v, m);
  };
  std::vector<int64_t> obj_a, obj_c, key_a, key_c, act_v, vlen_v, pn, pa, pc;
  std::vector<uint8_t> obj_am, obj_cm, key_am, key_cm, act_m, vlen_m, pnm,
      pam, pcm;
  if (!dec(kColObjActor, false, false, obj_a, obj_am)) return false;
  if (!dec(kColObjCtr, false, false, obj_c, obj_cm)) return false;
  if (!dec(kColKeyActor, false, false, key_a, key_am)) return false;
  if (!dec(kColKeyCtr, false, true, key_c, key_cm)) return false;
  if (!dec(kColAction, false, false, act_v, act_m)) return false;
  if (!dec(kColValLen, false, false, vlen_v, vlen_m)) return false;
  if (!dec(kColPredNum, false, false, pn, pnm)) return false;
  if (!dec(kColPredActor, false, false, pa, pam)) return false;
  if (!dec(kColPredCtr, false, true, pc, pcm)) return false;
  size_t n_ops = act_v.size();
  std::vector<int64_t> ins_v(n_ops);
  std::vector<uint8_t> ins_m(n_ops);
  {
    DocColumn *col = find(kColInsert);
    if (col) {
      if (am_decode_boolean(col->buf, col->len, ins_v.data(), ins_m.data(),
                            int64_t(n_ops)) != int64_t(n_ops))
        return false;
    } else if (n_ops) {
      return false;
    }
  }
  // keyStr: decode to per-op strings (-1 = null)
  std::vector<int32_t> kstr(n_ops, -1);
  Interner local_keys;
  {
    DocColumn *col = find(kColKeyStr);
    if (col) {
      std::vector<int32_t> tmp;
      if (!decode_keystr(col->buf, col->len, local_keys, tmp)) return false;
      if (tmp.size() != n_ops) return false;
      kstr = tmp;
    }
  }
  auto pad = [&](std::vector<int64_t> &v, std::vector<uint8_t> &m) {
    if (v.empty()) { v.assign(n_ops, 0); m.assign(n_ops, 0); }
    return v.size() == n_ops;
  };
  if (!pad(obj_a, obj_am) || !pad(obj_c, obj_cm) || !pad(key_a, key_am) ||
      !pad(key_c, key_cm) || !pad(vlen_v, vlen_m) || !pad(pn, pnm))
    return false;
  uint64_t pred_total = 0;
  for (size_t i = 0; i < n_ops; i++)
    pred_total += pnm[i] ? uint64_t(pn[i]) : 0;
  if (pa.size() != pred_total || pc.size() != pred_total) return false;
  DocColumn *vraw = find(kColValRaw);
  const uint8_t *raw_buf = vraw ? vraw->buf : nullptr;
  uint64_t raw_len = vraw ? vraw->len : 0;

  auto remap = [&](int64_t local) -> int32_t {
    if (local < 0 || uint64_t(local) >= chg_actors.size()) return -1;
    auto it = ctx.actor_index.find(chg_actors[size_t(local)]);
    return it == ctx.actor_index.end() ? -1 : it->second;
  };
  uint64_t raw_pos = 0, pred_pos = 0;
  for (size_t i = 0; i < n_ops; i++) {
    if (!act_m[i]) return false;
    // actions 0..6 only (7 = link and above need the Python path)
    if (act_v[i] < 0 || act_v[i] > 6) return false;
    BOp op;
    op.ctr = int64_t(ch.start_op + i);
    op.actor = remap(0);           // own ops are always by the change actor
    op.action = uint8_t(act_v[i]);
    op.insert = uint8_t(ins_m[i] ? ins_v[i] : 0);
    if (op.actor < 0) return false;
    // object
    if (obj_am[i] != obj_cm[i]) return false;
    if (obj_am[i]) {
      op.obj_ctr = obj_c[i];
      op.obj_actor = remap(obj_a[i]);
      if (op.obj_actor < 0) return false;
    }
    // key
    if (kstr[i] >= 0) {
      if (key_am[i] || (key_cm[i])) return false;
      op.key_kind = 0;
      op.key = local_keys.items[size_t(kstr[i])];
    } else if (key_cm[i] && key_c[i] == 0 && !key_am[i]) {
      op.key_kind = 1;   // _head
    } else if (key_cm[i] && key_am[i]) {
      op.key_kind = 2;
      op.ek_ctr = key_c[i];
      op.ek_actor = remap(key_a[i]);
      if (op.ek_actor < 0) return false;
    } else {
      return false;
    }
    // value
    if (vlen_m[i]) {
      uint64_t tag = uint64_t(vlen_v[i]);
      uint32_t ln = uint32_t(tag >> 4);
      if (raw_pos + ln > raw_len) return false;
      op.vtag = uint32_t(tag);
      op.voff = ctx.vals.size();
      ctx.vals.insert(ctx.vals.end(), raw_buf + raw_pos,
                      raw_buf + raw_pos + ln);
      raw_pos += ln;
    } else {
      op.vtag = 0;       // VALUE_TYPE NULL, zero length
      op.voff = ctx.vals.size();
    }
    // preds
    uint64_t np = pnm[i] ? uint64_t(pn[i]) : 0;
    for (uint64_t k = 0; k < np; k++, pred_pos++) {
      if (!pam[pred_pos] || !pcm[pred_pos]) return false;
      int32_t pactor = remap(pa[pred_pos]);
      if (pactor < 0) return false;
      op.pred.emplace_back(pc[pred_pos], pactor);
    }
    ch.ops.push_back(std::move(op));
  }
  if (raw_pos != raw_len || pred_pos != pred_total) return false;
  ctx.changes.push_back(std::move(ch));
  return true;
}

}  // namespace

namespace {

static inline int64_t elem_key(int64_t ctr, int32_t actor) {
  return (ctr << 8) | int64_t(actor & 0xff);
}

static inline bool lamport_lt(int64_t c1, int32_t a1, int64_t c2,
                              int32_t a2) {
  // actor numbers are hex-sorted doc-table indexes, so (ctr, num) ordering
  // equals the reference's (counter, actorId-string) lamportCompare
  return c1 != c2 ? c1 < c2 : a1 < a2;
}

static BObj *build_resolve_obj(BuildCtx &ctx, int64_t ctr, int32_t actor) {
  if (actor < 0) return &ctx.root;
  auto it = ctx.objects.find({ctr, actor});
  return it == ctx.objects.end() ? nullptr : &it->second;
}

static BRow build_row_from(const BOp &op) {
  BRow r;
  r.ctr = op.ctr;
  r.actor = op.actor;
  r.action = op.action;
  r.insert = op.insert;
  r.key_kind = op.key_kind;
  r.ek_ctr = op.ek_ctr;
  r.ek_actor = op.ek_actor;
  r.vtag = op.vtag;
  r.voff = op.voff;
  return r;
}

// Apply one op to the store (host op_set._apply_op minus patches):
// succ marking on preds, lamport-sorted row insertion, RGA element splice
// with the concurrent-insert skip (ref new.js:145-163, :1204-1217).
static bool build_apply_op(BuildCtx &ctx, const BOp &op, std::string &key16buf) {
  if (op.action == 0 || op.action == 2 || op.action == 4 || op.action == 6) {
    BObj obj;
    obj.type = op.action;
    obj.is_seq = (op.action == 2 || op.action == 4);
    auto ins = ctx.objects.emplace(std::make_pair(op.ctr, op.actor),
                                   std::move(obj));
    if (!ins.second) return false;        // duplicate objectId
  }
  BObj *parent = build_resolve_obj(ctx, op.obj_ctr, op.obj_actor);
  if (!parent) return false;

  if (op.insert) {
    if (!parent->is_seq || op.key_kind == 0) return false;
    std::list<BElem>::iterator pos;
    if (op.key_kind == 1) {
      pos = parent->elems.begin();
    } else {
      auto it = parent->elem_index.find(elem_key(op.ek_ctr, op.ek_actor));
      if (it == parent->elem_index.end()) return false;
      pos = std::next(it->second);
    }
    // concurrent-insert skip: pass elems whose id is greater than ours
    while (pos != parent->elems.end() &&
           lamport_lt(op.ctr, op.actor, pos->ctr, pos->actor))
      ++pos;
    BElem elem;
    elem.ctr = op.ctr;
    elem.actor = op.actor;
    if (!op.pred.empty()) return false;    // inserts carry no preds
    elem.rows.push_back(build_row_from(op));
    auto at = parent->elems.insert(pos, std::move(elem));
    if (!parent->elem_index.emplace(elem_key(op.ctr, op.actor), at).second)
      return false;                        // duplicate elemId
    return true;
  }

  // update (set / del / inc / make-at-key)
  std::vector<BRow> *rows;
  if (parent->is_seq) {
    if (op.key_kind != 2) return false;
    auto it = parent->elem_index.find(elem_key(op.ek_ctr, op.ek_actor));
    if (it == parent->elem_index.end()) return false;  // missing referent
    rows = &it->second->rows;
  } else {
    if (op.key_kind != 0) return false;
    std::u16string k16;
    if (!utf8_to_u16(op.key, k16)) return false;
    auto it = parent->keys.find(k16);
    if (it == parent->keys.end()) {
      it = parent->keys.emplace(k16, std::vector<BRow>()).first;
      parent->key_utf8.emplace(k16, op.key);
    }
    rows = &it->second;
  }
  // mark succ on preds (kept lamport-sorted), detect duplicates
  size_t seen = 0;
  for (auto &row : *rows) {
    if (row.ctr == op.ctr && row.actor == op.actor) return false;  // dup id
    for (auto &p : op.pred) {
      if (row.ctr == p.first && row.actor == p.second) {
        auto s = std::make_pair(op.ctr, int64_t(op.actor));
        auto at = std::lower_bound(
            row.succ.begin(), row.succ.end(),
            std::make_pair(op.ctr, op.actor),
            [](const std::pair<int64_t, int32_t> &x,
               const std::pair<int64_t, int32_t> &y) {
              return lamport_lt(x.first, x.second, y.first, y.second);
            });
        row.succ.insert(at, {op.ctr, op.actor});
        (void)s;
        seen++;
      }
    }
  }
  if (seen != op.pred.size()) return false;   // pred with no matching op
  if (op.action != 3) {                       // dels are succ-only
    auto at = std::lower_bound(
        rows->begin(), rows->end(), op,
        [](const BRow &r, const BOp &o) {
          return lamport_lt(r.ctr, r.actor, o.ctr, o.actor);
        });
    rows->insert(at, build_row_from(op));
  }
  return true;
}

// Canonical change order: Kahn topological traversal, ties broken on hash,
// with implicit per-actor seq edges (mirrors op_set._canonical_change_order).
static bool build_canonical_order(BuildCtx &ctx, std::vector<size_t> &order) {
  size_t n = ctx.changes.size();
  std::unordered_map<std::string, size_t> by_hash;
  for (size_t i = 0; i < n; i++) by_hash[ctx.changes[i].hash] = i;
  std::vector<std::vector<size_t>> children(n);
  std::vector<size_t> indeg(n, 0);
  for (size_t i = 0; i < n; i++) {
    for (auto &dep : ctx.changes[i].deps) {
      auto it = by_hash.find(dep);
      if (it == by_hash.end()) return false;
      children[it->second].push_back(i);
      indeg[i]++;
    }
  }
  std::unordered_map<std::string, std::vector<size_t>> by_actor;
  for (size_t i = 0; i < n; i++)
    by_actor[ctx.changes[i].actor_hex].push_back(i);
  for (auto &kv : by_actor) {
    auto idxs = kv.second;
    std::sort(idxs.begin(), idxs.end(), [&](size_t a, size_t b) {
      return ctx.changes[a].seq < ctx.changes[b].seq;
    });
    for (size_t k = 0; k + 1 < idxs.size(); k++) {
      children[idxs[k]].push_back(idxs[k + 1]);
      indeg[idxs[k + 1]]++;
    }
  }
  using HI = std::pair<std::string, size_t>;
  std::priority_queue<HI, std::vector<HI>, std::greater<HI>> heap;
  for (size_t i = 0; i < n; i++)
    if (indeg[i] == 0) heap.push({ctx.changes[i].hash, i});
  order.clear();
  while (!heap.empty()) {
    size_t i = heap.top().second;
    heap.pop();
    order.push_back(i);
    for (size_t c : children[i])
      if (--indeg[c] == 0) heap.push({ctx.changes[c].hash, c});
  }
  return order.size() == n;
}

static void emit_doc_row(const BRow &r, int64_t obj_ctr, int32_t obj_actor,
                         const std::string *map_key, BuildCtx &ctx,
                         RleEnc &obj_a, RleEnc &obj_c, RleEnc &key_a,
                         DeltaEnc &key_c, RleEnc &key_s, BoolEnc &ins,
                         RleEnc &act, RleEnc &vlen, ByteBuf &vraw,
                         RleEnc &chld_a, DeltaEnc &chld_c, RleEnc &id_a,
                         DeltaEnc &id_c, RleEnc &succ_n, RleEnc &succ_a,
                         DeltaEnc &succ_c) {
  if (obj_actor < 0) {
    obj_a.null_();
    obj_c.null_();
  } else {
    obj_a.value(obj_actor);
    obj_c.value(obj_ctr);
  }
  if (map_key) {
    key_a.null_();
    key_c.null_();
    key_s.str(*map_key);
  } else if (r.insert && r.key_kind == 1) {
    key_a.null_();
    key_c.value(0);
    key_s.null_();
  } else {
    key_a.value(r.key_kind == 2 ? r.ek_actor : r.actor);
    key_c.value(r.key_kind == 2 ? r.ek_ctr : r.ctr);
    key_s.null_();
  }
  ins.value(bool(r.insert));
  act.value(r.action);
  uint32_t ln = r.vtag >> 4;
  vlen.value(int64_t(r.vtag));
  if (ln) vraw.raw(ctx.vals.data() + r.voff, ln);
  chld_a.null_();
  chld_c.null_();
  id_a.value(r.actor);
  id_c.value(r.ctr);
  succ_n.value(int64_t(r.succ.size()));
  for (auto &s : r.succ) {
    succ_a.value(s.second);
    succ_c.value(s.first);
  }
}

static void deflate_maybe(uint32_t cid, std::vector<uint8_t> &buf,
                          std::vector<std::pair<uint32_t,
                                                std::vector<uint8_t>>> &cols) {
  if (buf.empty()) return;
  if (buf.size() >= 256) {
    z_stream zs;
    memset(&zs, 0, sizeof(zs));
    if (deflateInit2(&zs, 6, Z_DEFLATED, -15, 8, Z_DEFAULT_STRATEGY) == Z_OK) {
      std::vector<uint8_t> out(deflateBound(&zs, buf.size()));
      zs.next_in = buf.data();
      zs.avail_in = uInt(buf.size());
      zs.next_out = out.data();
      zs.avail_out = uInt(out.size());
      if (deflate(&zs, Z_FINISH) == Z_STREAM_END) {
        out.resize(out.size() - zs.avail_out);
        deflateEnd(&zs);
        cols.emplace_back(cid | 8u, std::move(out));
        return;
      }
      deflateEnd(&zs);
    }
  }
  cols.emplace_back(cid, std::move(buf));
}

static bool build_serialize(BuildCtx &ctx,
                            const std::vector<std::string> &heads) {
  std::vector<size_t> order;
  if (!build_canonical_order(ctx, order)) return false;
  std::unordered_map<std::string, size_t> canon;
  for (size_t pos = 0; pos < order.size(); pos++)
    canon[ctx.changes[order[pos]].hash] = pos;

  // ---- ops columns in document order ----
  RleEnc obj_a(RleEnc::UINT), obj_c(RleEnc::UINT), key_a(RleEnc::UINT),
      key_s(RleEnc::UTF8), act(RleEnc::UINT), vlen(RleEnc::UINT),
      chld_a(RleEnc::UINT), id_a(RleEnc::UINT), succ_n(RleEnc::UINT),
      succ_a(RleEnc::UINT);
  DeltaEnc key_c, chld_c, id_c, succ_c;
  BoolEnc ins;
  ByteBuf vraw;

  auto emit_obj = [&](BObj &obj, int64_t octr, int32_t oactor) {
    if (obj.is_seq) {
      for (auto &elem : obj.elems)
        for (auto &r : elem.rows)
          emit_doc_row(r, octr, oactor, nullptr, ctx, obj_a, obj_c, key_a,
                       key_c, key_s, ins, act, vlen, vraw, chld_a, chld_c,
                       id_a, id_c, succ_n, succ_a, succ_c);
    } else {
      for (auto &kv : obj.keys) {
        const std::string &key = obj.key_utf8[kv.first];
        for (auto &r : kv.second)
          emit_doc_row(r, octr, oactor, &key, ctx, obj_a, obj_c, key_a,
                       key_c, key_s, ins, act, vlen, vraw, chld_a, chld_c,
                       id_a, id_c, succ_n, succ_a, succ_c);
      }
    }
  };
  emit_obj(ctx.root, 0, -1);
  for (auto &kv : ctx.objects)
    emit_obj(kv.second, kv.first.first, kv.first.second);

  // ---- changes metadata columns in canonical order ----
  RleEnc m_actor(RleEnc::UINT), m_msg(RleEnc::UTF8), m_depsn(RleEnc::UINT),
      m_extral(RleEnc::UINT);
  DeltaEnc m_seq, m_maxop, m_time, m_depsi;
  ByteBuf m_extrar;
  for (size_t pos = 0; pos < order.size(); pos++) {
    BChange &ch = ctx.changes[order[pos]];
    auto it = ctx.actor_index.find(ch.actor_hex);
    if (it == ctx.actor_index.end()) return false;
    m_actor.value(it->second);
    m_seq.value(int64_t(ch.seq));
    m_maxop.value(int64_t(ch.start_op + ch.ops.size() - 1));
    m_time.value(ch.time);
    m_msg.str(ch.message);
    std::vector<std::string> deps = ch.deps;
    std::sort(deps.begin(), deps.end());
    m_depsn.value(int64_t(deps.size()));
    for (auto &dep : deps) {
      auto d = canon.find(dep);
      if (d == canon.end()) return false;
      m_depsi.value(int64_t(d->second));
    }
    if (!ch.extra.empty()) {
      m_extrar.raw((const uint8_t *)ch.extra.data(), ch.extra.size());
      m_extral.value(int64_t((ch.extra.size() << 4) | 7));  // BYTES
    } else {
      m_extral.value(7);                                    // BYTES, len 0
    }
  }

  // ---- assemble container ----
  for (RleEnc *e : {&obj_a, &obj_c, &key_a, &key_s, &act, &vlen, &chld_a,
                    &id_a, &succ_n, &succ_a, &m_actor, &m_msg, &m_depsn,
                    &m_extral})
    e->finish();
  for (DeltaEnc *e : {&key_c, &chld_c, &id_c, &succ_c, &m_seq, &m_maxop,
                      &m_time, &m_depsi})
    e->finish();
  ins.finish();

  using Col = std::pair<uint32_t, std::vector<uint8_t>>;
  std::vector<Col> ccols, ocols;
  deflate_maybe(0x01, m_actor.out.b, ccols);
  deflate_maybe(0x03, m_seq.rle.out.b, ccols);
  deflate_maybe(0x13, m_maxop.rle.out.b, ccols);
  deflate_maybe(0x23, m_time.rle.out.b, ccols);
  deflate_maybe(0x35, m_msg.out.b, ccols);
  deflate_maybe(0x40, m_depsn.out.b, ccols);
  deflate_maybe(0x43, m_depsi.rle.out.b, ccols);
  deflate_maybe(0x56, m_extral.out.b, ccols);
  deflate_maybe(0x57, m_extrar.b, ccols);
  deflate_maybe(kColObjActor, obj_a.out.b, ocols);
  deflate_maybe(kColObjCtr, obj_c.out.b, ocols);
  deflate_maybe(kColKeyActor, key_a.out.b, ocols);
  deflate_maybe(kColKeyCtr, key_c.rle.out.b, ocols);
  deflate_maybe(kColKeyStr, key_s.out.b, ocols);
  deflate_maybe(kColInsert, ins.out.b, ocols);
  deflate_maybe(kColAction, act.out.b, ocols);
  deflate_maybe(kColValLen, vlen.out.b, ocols);
  deflate_maybe(kColValRaw, vraw.b, ocols);
  deflate_maybe(kColChldActor, chld_a.out.b, ocols);
  deflate_maybe(kColChldCtr, chld_c.rle.out.b, ocols);
  deflate_maybe(kColIdActor, id_a.out.b, ocols);
  deflate_maybe(kColIdCtr, id_c.rle.out.b, ocols);
  deflate_maybe(kColSuccNum, succ_n.out.b, ocols);
  deflate_maybe(kColSuccActor, succ_a.out.b, ocols);
  deflate_maybe(kColSuccCtr, succ_c.rle.out.b, ocols);
  auto by_id = [](const Col &a, const Col &b) {
    return (a.first & ~8u) < (b.first & ~8u);
  };
  std::sort(ccols.begin(), ccols.end(), by_id);
  std::sort(ocols.begin(), ocols.end(), by_id);

  ByteBuf body;
  body.uleb(ctx.actors.size());
  for (auto &a : ctx.actors) {
    body.uleb(a.size() / 2);
    for (size_t i = 0; i + 1 < a.size(); i += 2) {
      auto nib = [](char ch) -> uint8_t {
        return ch <= '9' ? ch - '0' : ch - 'a' + 10;
      };
      body.u8(uint8_t(nib(a[i]) << 4 | nib(a[i + 1])));
    }
  }
  std::vector<std::string> sheads = heads;
  std::sort(sheads.begin(), sheads.end());
  body.uleb(sheads.size());
  for (auto &h : sheads) {
    for (size_t i = 0; i + 1 < h.size(); i += 2) {
      auto nib = [](char ch) -> uint8_t {
        return ch <= '9' ? ch - '0' : ch - 'a' + 10;
      };
      body.u8(uint8_t(nib(h[i]) << 4 | nib(h[i + 1])));
    }
  }
  auto col_info = [&](std::vector<Col> &cols) {
    body.uleb(cols.size());
    for (auto &c : cols) {
      body.uleb(c.first);
      body.uleb(c.second.size());
    }
  };
  col_info(ccols);
  col_info(ocols);
  for (auto &c : ccols) body.raw(c.second.data(), c.second.size());
  for (auto &c : ocols) body.raw(c.second.data(), c.second.size());
  for (auto &h : sheads) {
    auto d = canon.find(h);
    if (d == canon.end()) return false;
    body.uleb(d->second);
  }

  ByteBuf chunk;
  chunk.u8(0);
  chunk.uleb(body.b.size());
  chunk.raw(body.b.data(), body.b.size());
  uint8_t digest[32];
  {
    Sha256Stream s;
    sha256_stream_init(s);
    sha256_stream_update(s, chunk.b.data(), chunk.b.size());
    sha256_stream_final(s, digest);
  }
  ctx.result.clear();
  const uint8_t magic[4] = {0x85, 0x6f, 0x4a, 0x83};
  ctx.result.insert(ctx.result.end(), magic, magic + 4);
  ctx.result.insert(ctx.result.end(), digest, digest + 4);
  ctx.result.insert(ctx.result.end(), chunk.b.begin(), chunk.b.end());
  return true;
}

static BuildCtx *g_build = nullptr;

}  // namespace

extern "C" {

// Build a canonical document container from a doc's change log (application
// order) + current heads (32 bytes each). Returns the result byte size, or
// -1 when the log needs the Python path (link/child/unknown columns,
// malformed history). Fetch with am_build_fetch.
int64_t am_build_document(const uint8_t *blob, const uint64_t *offsets,
                          const uint64_t *lens, uint64_t n_changes,
                          const uint8_t *heads, uint64_t n_heads) {
  delete g_build;
  g_build = new BuildCtx();
  BuildCtx &ctx = *g_build;
  std::vector<uint8_t> scratch;
  // pass 1: authors -> hex-sorted doc actor table
  for (uint64_t i = 0; i < n_changes; i++) {
    if (!build_parse_change(ctx, blob + offsets[i], lens[i], true, scratch))
      return -1;
  }
  std::vector<std::string> authors;
  for (auto &ch : ctx.changes) authors.push_back(ch.actor_hex);
  std::sort(authors.begin(), authors.end());
  authors.erase(std::unique(authors.begin(), authors.end()), authors.end());
  // elem_key packs actor indexes into 8 bits: larger actor populations
  // must take the Python path rather than alias elemIds
  if (authors.size() > 256) return -1;
  ctx.actors = authors;
  for (size_t i = 0; i < ctx.actors.size(); i++)
    ctx.actor_index[ctx.actors[i]] = int32_t(i);
  ctx.changes.clear();
  // pass 2: full parse with doc-table actor numbers
  for (uint64_t i = 0; i < n_changes; i++) {
    if (!build_parse_change(ctx, blob + offsets[i], lens[i], false, scratch))
      return -1;
  }
  // replay into the op store
  std::string k16;
  for (auto &ch : ctx.changes)
    for (auto &op : ch.ops)
      if (!build_apply_op(ctx, op, k16)) return -1;
  std::vector<std::string> head_hex;
  for (uint64_t i = 0; i < n_heads; i++)
    head_hex.push_back(to_hex(heads + 32 * i, 32));
  if (!build_serialize(ctx, head_hex)) return -1;
  return int64_t(ctx.result.size());
}

int64_t am_build_fetch(uint8_t *out, uint64_t cap) {
  if (!g_build) return -1;
  if (g_build->result.size() > cap) return -1;
  copy_bytes(out, g_build->result.data(), g_build->result.size());
  int64_t n = int64_t(g_build->result.size());
  delete g_build;
  g_build = nullptr;
  return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native change-list extraction: document chunk -> canonical per-change
// chunks + SHA-256 hashes (the inverse of am_build_document; ref
// columnar.js:1040-1047 decodeDocument). This is the delta+main engine's
// materialize kernel: a parked document revives its change log without the
// Python decode_document + encode_change round trip (~700us/doc ->
// ~100-150us/doc), and recovery / bulk load feed change buffers straight
// from parked chunks.
//
// Parity contract: when extraction SUCCEEDS its output is byte-identical
// to Python's decode_document + encode_change — both normalize the same
// way (value tags for non-set/inc actions collapse to NULL, zero-counter
// children collapse to null, preds/deps sort canonically) and both verify
// that the re-encoded hash frontier reproduces the header's heads. Every
// change is an ancestor of some head, so ANY byte divergence cascades into
// the heads check; extraction bails (caller falls back to Python, which
// reproduces the exact typed verdict) on anything it cannot prove it
// normalizes identically: unknown columns, unknown value types with
// ambiguous round-trips, non-minimal LEB payloads, invalid UTF-8, link
// ops, del rows in the ops table, null change-meta fields Python raises
// on. Per-doc extraction is independent, so the pool fan-out is
// byte-identical at every width by construction.
// ---------------------------------------------------------------------------

namespace {

// Strict UTF-8 validation matching CPython's decoder (encoding.py
// read_prefixed_string): rejects overlong forms, surrogates, > U+10FFFF.
// Python re-encodes decoded strings verbatim only for valid input; invalid
// input raises typed — so the extractor bails to keep verdicts identical.
static bool validate_utf8(const uint8_t *p, uint64_t n) {
  uint64_t i = 0;
  while (i < n) {
    uint8_t b = p[i];
    uint32_t cp;
    uint64_t need;
    if (b < 0x80) { cp = b; need = 1; }
    else if ((b >> 5) == 6) { cp = b & 0x1f; need = 2; }
    else if ((b >> 4) == 14) { cp = b & 0x0f; need = 3; }
    else if ((b >> 3) == 30) { cp = b & 0x07; need = 4; }
    else return false;
    if (i + need > n) return false;
    for (uint64_t k = 1; k < need; k++) {
      if ((p[i + k] >> 6) != 2) return false;
      cp = (cp << 6) | (p[i + k] & 0x3f);
    }
    static const uint32_t min_cp[5] = {0, 0, 0x80, 0x800, 0x10000};
    if (cp < min_cp[need]) return false;
    if (cp >= 0xd800 && cp <= 0xdfff) return false;
    if (cp > 0x10ffff) return false;
    i += need;
  }
  return true;
}

// RLE utf8 column -> per-row interned string ids (-1 = null), strict utf8,
// count-bombs capped. (decode_keystr is the no-null-validation variant the
// doc parser uses; messages and extraction keys need the strict one.)
static bool decode_strcol_strict(const uint8_t *buf, uint64_t len,
                                 Interner &pool, std::vector<int32_t> &out) {
  Cursor c{buf, len};
  while (c.pos < c.len && !c.fail) {
    int64_t count = c.sleb();
    if (c.fail) return false;
    if (count > 1) {
      if (count > kMaxColumnValues - int64_t(out.size())) return false;
      uint64_t slen = c.uleb();
      const uint8_t *p = c.bytes(slen);
      if (c.fail || !validate_utf8(p, slen)) return false;
      int32_t id = pool.intern(std::string((const char *)p, slen));
      for (int64_t i = 0; i < count; i++) out.push_back(id);
    } else if (count == 1) {
      return false;              // non-canonical lone run
    } else if (count < 0) {
      if (-count > kMaxColumnValues - int64_t(out.size())) return false;
      for (int64_t i = 0; i < -count; i++) {
        uint64_t slen = c.uleb();
        const uint8_t *p = c.bytes(slen);
        if (c.fail || !validate_utf8(p, slen)) return false;
        out.push_back(pool.intern(std::string((const char *)p, slen)));
      }
    } else {
      uint64_t nulls = c.uleb();
      if (c.fail || nulls > uint64_t(kMaxColumnValues - int64_t(out.size())))
        return false;
      for (uint64_t i = 0; i < nulls; i++) out.push_back(-1);
    }
  }
  return !c.fail;
}

struct XOp {
  int64_t ctr = 0;
  int32_t actor = -1;             // local doc-actor index
  int64_t obj_ctr = 0;
  int32_t obj_actor = -1;         // -1 = root
  int8_t key_kind = 0;            // 0 = map key, 1 = _head, 2 = elemId
  int32_t key_str = -1;           // interned map key
  int64_t ek_ctr = 0;
  int32_t ek_actor = -1;
  uint8_t insert = 0;
  uint8_t action = 0;
  uint32_t vtag = 0;              // normalized valLen tag (len<<4 | type)
  uint64_t voff = 0;              // into the per-doc value arena
  int64_t chld_ctr = 0;
  int32_t chld_actor = -1;        // -1 = none
  std::vector<std::pair<int64_t, int32_t>> pred;   // (ctr, local actor)
};

struct XChange {
  int32_t actor = -1;             // local doc-actor index
  int64_t seq = 0, max_op = 0, time = 0;
  int32_t msg = -1;               // interned message id (-1 = null)
  std::vector<int64_t> deps_idx;  // indexes into the doc's change list
  const uint8_t *extra = nullptr;
  uint64_t extra_len = 0;
  std::vector<int32_t> ops;       // indexes into the op pool, sorted by ctr
  uint8_t hash[32];
};

struct DocExtract {
  uint8_t ok = 0;
  std::vector<uint8_t> blob;      // concatenated canonical change chunks
  std::vector<int64_t> lens;      // per-change chunk byte length
  std::vector<uint8_t> hashes;    // 32 bytes per change
  std::vector<int64_t> max_ops;   // per-change maxOp
};

// Encode one reconstructed change as its canonical chunk (encode_change,
// ref columnar.js:710-739), appending to doc.blob. Returns false on shapes
// Python's encoder would reject.
constexpr int64_t kMaxSafeInt = (int64_t(1) << 53) - 1;

static bool encode_extracted_change(
    XChange &ch, const std::vector<XOp> &pool,
    const std::vector<std::string> &actors, const Interner &keys,
    const Interner &msgs, const std::vector<uint8_t> &vals,
    const std::vector<XChange> &changes, DocExtract &doc) {
  // per-change actor table: change actor first, others hex-sorted
  std::vector<int32_t> tbl_of(actors.size(), -1);
  std::vector<int32_t> referenced;
  auto touch = [&](int32_t a) {
    if (a >= 0 && tbl_of[size_t(a)] < 0) {
      tbl_of[size_t(a)] = 0;        // mark; numbered below
      referenced.push_back(a);
    }
  };
  touch(ch.actor);
  for (int32_t oi : ch.ops) {
    const XOp &op = pool[size_t(oi)];
    touch(op.obj_actor);
    if (op.key_kind == 2) touch(op.ek_actor);
    if (op.chld_actor >= 0 && op.chld_ctr != 0) touch(op.chld_actor);
    for (auto &p : op.pred) touch(p.second);
  }
  std::vector<int32_t> others;
  for (int32_t a : referenced)
    if (a != ch.actor) others.push_back(a);
  std::sort(others.begin(), others.end(), [&](int32_t x, int32_t y) {
    return actors[size_t(x)] < actors[size_t(y)];
  });
  tbl_of[size_t(ch.actor)] = 0;
  for (size_t i = 0; i < others.size(); i++)
    tbl_of[size_t(others[i])] = int32_t(i + 1);

  // ---- op columns (CHANGE_COLUMNS; ids ascending) ----
  RleEnc obj_a(RleEnc::UINT), obj_c(RleEnc::UINT), key_a(RleEnc::UINT),
      key_s(RleEnc::UTF8), act(RleEnc::UINT), vlen(RleEnc::UINT),
      chld_a(RleEnc::UINT), pred_n(RleEnc::UINT), pred_a(RleEnc::UINT);
  DeltaEnc key_c, chld_c, pred_c;
  BoolEnc ins;
  ByteBuf vraw;
  for (int32_t oi : ch.ops) {
    const XOp &op = pool[size_t(oi)];
    if (op.obj_actor < 0) {
      obj_a.null_();
      obj_c.null_();
    } else {
      obj_a.value(tbl_of[size_t(op.obj_actor)]);
      obj_c.value(op.obj_ctr);
    }
    if (op.key_kind == 0) {
      // empty map keys fail Python's falsy key check — stay identical
      if (op.key_str < 0 || keys.items[size_t(op.key_str)].empty())
        return false;
      key_a.null_();
      key_c.null_();
      key_s.str(keys.items[size_t(op.key_str)]);
    } else if (op.key_kind == 1) {
      if (!op.insert) return false;   // _head on a non-insert: Python raises
      key_a.null_();
      key_c.value(0);
      key_s.null_();
    } else {
      if (op.ek_actor < 0 || op.ek_ctr <= 0) return false;
      key_a.value(tbl_of[size_t(op.ek_actor)]);
      key_c.value(op.ek_ctr);
      key_s.null_();
    }
    ins.value(bool(op.insert));
    act.value(op.action);
    // value: set/inc keep their (normalized) tag + raw bytes; all other
    // actions encode NULL (encode_value_to_columns' action gate)
    if ((op.action == 1 || op.action == 5) && op.vtag != 0) {
      uint32_t ln = op.vtag >> 4;
      uint8_t vt = uint8_t(op.vtag & 0xf);
      if (vt == 1 || vt == 2) {
        vlen.value(int64_t(vt));      // FALSE/TRUE carry no payload
      } else {
        vlen.value(int64_t(op.vtag));
        if (ln) vraw.raw(vals.data() + op.voff, ln);
      }
    } else {
      vlen.value(0);                  // NULL
    }
    if (op.chld_actor >= 0 && op.chld_ctr != 0) {
      chld_a.value(tbl_of[size_t(op.chld_actor)]);
      chld_c.value(op.chld_ctr);
    } else {
      chld_a.null_();
      chld_c.null_();
    }
    // preds sorted by (ctr, actor hex) — ParsedOpId.sort_key
    std::vector<std::pair<int64_t, int32_t>> pred = op.pred;
    std::sort(pred.begin(), pred.end(),
              [&](const std::pair<int64_t, int32_t> &x,
                  const std::pair<int64_t, int32_t> &y) {
                if (x.first != y.first) return x.first < y.first;
                return actors[size_t(x.second)] < actors[size_t(y.second)];
              });
    for (size_t i = 1; i < pred.size(); i++)
      if (pred[i - 1].first == pred[i].first &&
          pred[i - 1].second == pred[i].second)
        return false;                 // duplicate pred: decode would raise
    pred_n.value(int64_t(pred.size()));
    for (auto &p : pred) {
      pred_a.value(tbl_of[size_t(p.second)]);
      pred_c.value(p.first);
    }
  }
  for (RleEnc *e : {&obj_a, &obj_c, &key_a, &key_s, &act, &vlen, &chld_a,
                    &pred_n, &pred_a})
    e->finish();
  for (DeltaEnc *e : {&key_c, &chld_c, &pred_c}) e->finish();
  ins.finish();

  // ---- body (encode_change layout) ----
  ByteBuf body;
  {
    // deps: resolved hashes, sorted bytewise (== hex sort)
    std::vector<const uint8_t *> deps;
    for (int64_t di : ch.deps_idx) deps.push_back(changes[size_t(di)].hash);
    std::sort(deps.begin(), deps.end(),
              [](const uint8_t *a, const uint8_t *b) {
                return memcmp(a, b, 32) < 0;
              });
    body.uleb(deps.size());
    for (const uint8_t *d : deps) body.raw(d, 32);
  }
  const std::string &ahex = actors[size_t(ch.actor)];
  auto hex_bytes = [&](const std::string &h) {
    body.uleb(h.size() / 2);
    for (size_t i = 0; i + 1 < h.size(); i += 2) {
      auto nib = [](char c) -> uint8_t {
        return c <= '9' ? uint8_t(c - '0') : uint8_t(c - 'a' + 10);
      };
      body.u8(uint8_t(nib(h[i]) << 4 | nib(h[i + 1])));
    }
  };
  hex_bytes(ahex);
  // Python's append_uint53/append_int53 bound every header field
  if (ch.seq <= 0 || ch.seq > kMaxSafeInt) return false;
  body.uleb(uint64_t(ch.seq));
  int64_t start_op = ch.max_op - int64_t(ch.ops.size()) + 1;
  if (start_op < 0 || start_op > kMaxSafeInt) return false;
  body.uleb(uint64_t(start_op));
  if (ch.time < -kMaxSafeInt || ch.time > kMaxSafeInt) return false;
  body.sleb(ch.time);
  if (ch.msg < 0) {
    body.uleb(0);
  } else {
    const std::string &m = msgs.items[size_t(ch.msg)];
    body.uleb(m.size());
    body.raw((const uint8_t *)m.data(), m.size());
  }
  body.uleb(others.size());
  for (int32_t a : others) hex_bytes(actors[size_t(a)]);
  using Col = std::pair<uint32_t, std::vector<uint8_t> *>;
  std::vector<Col> cols = {
      {kColObjActor, &obj_a.out.b}, {kColObjCtr, &obj_c.out.b},
      {kColKeyActor, &key_a.out.b}, {kColKeyCtr, &key_c.rle.out.b},
      {kColKeyStr, &key_s.out.b},   {kColInsert, &ins.out.b},
      {kColAction, &act.out.b},     {kColValLen, &vlen.out.b},
      {kColValRaw, &vraw.b},        {kColChldActor, &chld_a.out.b},
      {kColChldCtr, &chld_c.rle.out.b}, {kColPredNum, &pred_n.out.b},
      {kColPredActor, &pred_a.out.b},   {kColPredCtr, &pred_c.rle.out.b}};
  std::sort(cols.begin(), cols.end(),
            [](const Col &a, const Col &b) { return a.first < b.first; });
  uint64_t n_cols = 0;
  for (auto &c : cols)
    if (!c.second->empty()) n_cols++;
  body.uleb(n_cols);
  for (auto &c : cols) {
    if (c.second->empty()) continue;
    body.uleb(c.first);
    body.uleb(c.second->size());
  }
  for (auto &c : cols)
    if (!c.second->empty()) body.raw(c.second->data(), c.second->size());
  if (ch.extra_len) body.raw(ch.extra, ch.extra_len);

  // ---- container + hash (+ canonical DEFLATE past 256 bytes) ----
  ByteBuf framed;
  framed.u8(1);
  framed.uleb(body.b.size());
  framed.raw(body.b.data(), body.b.size());
  uint8_t digest[32];
  {
    Sha256Stream s;
    sha256_stream_init(s);
    sha256_stream_update(s, framed.b.data(), framed.b.size());
    sha256_stream_final(s, digest);
  }
  const uint8_t magic[4] = {0x85, 0x6f, 0x4a, 0x83};
  size_t chunk_start = doc.blob.size();
  if (8 + framed.b.size() >= 256) {
    // deflate_change: magic + checksum of the UNCOMPRESSED form, type 2,
    // LEB compressed length, raw-DEFLATE body (level 6, matching Python)
    z_stream zs;
    memset(&zs, 0, sizeof(zs));
    if (deflateInit2(&zs, 6, Z_DEFLATED, -15, 8, Z_DEFAULT_STRATEGY) != Z_OK)
      return false;
    std::vector<uint8_t> comp(deflateBound(&zs, uInt(body.b.size())));
    zs.next_in = body.b.data();
    zs.avail_in = uInt(body.b.size());
    zs.next_out = comp.data();
    zs.avail_out = uInt(comp.size());
    if (deflate(&zs, Z_FINISH) != Z_STREAM_END) {
      deflateEnd(&zs);
      return false;
    }
    comp.resize(comp.size() - zs.avail_out);
    deflateEnd(&zs);
    doc.blob.insert(doc.blob.end(), magic, magic + 4);
    doc.blob.insert(doc.blob.end(), digest, digest + 4);
    ByteBuf dh;
    dh.u8(2);
    dh.uleb(comp.size());
    doc.blob.insert(doc.blob.end(), dh.b.begin(), dh.b.end());
    doc.blob.insert(doc.blob.end(), comp.begin(), comp.end());
  } else {
    doc.blob.insert(doc.blob.end(), magic, magic + 4);
    doc.blob.insert(doc.blob.end(), digest, digest + 4);
    doc.blob.insert(doc.blob.end(), framed.b.begin(), framed.b.end());
  }
  doc.lens.push_back(int64_t(doc.blob.size() - chunk_start));
  doc.hashes.insert(doc.hashes.end(), digest, digest + 32);
  doc.max_ops.push_back(ch.max_op);
  copy_bytes(ch.hash, digest, 32);
  return true;
}

// Extract one document chunk into per-change canonical chunks; returns
// false (doc.ok stays 0, partial output discarded by the caller using a
// fresh DocExtract) when the doc needs the Python path.
static bool extract_document_body(const uint8_t *chunk, uint64_t chunk_len,
                                  DocExtract &doc) {
  Cursor c{chunk, chunk_len};
  const uint8_t *magic = c.bytes(4);
  if (c.fail || memcmp(magic, "\x85\x6f\x4a\x83", 4) != 0) return false;
  const uint8_t *checksum = c.bytes(4);
  uint64_t hash_start = c.pos;
  if (c.fail || c.pos >= chunk_len) return false;
  uint8_t chunk_type = chunk[c.pos];
  c.skip(1);
  uint64_t body_len = c.uleb();
  if (c.fail || chunk_type != 0) return false;
  const uint8_t *body = c.bytes(body_len);
  if (c.fail || c.pos != chunk_len) return false;
  {
    uint8_t digest[32];
    Sha256Stream s;
    sha256_stream_init(s);
    sha256_stream_update(s, chunk + hash_start, c.pos - hash_start);
    sha256_stream_final(s, digest);
    if (memcmp(digest, checksum, 4) != 0) return false;
  }

  Cursor b{body, body_len};
  uint64_t n_actors = b.uleb();
  if (b.fail || n_actors > (1u << 20)) return false;
  std::vector<std::string> actors;
  for (uint64_t i = 0; i < n_actors; i++) {
    uint64_t alen = b.uleb();
    const uint8_t *raw = b.bytes(alen);
    if (b.fail) return false;
    actors.push_back(to_hex(raw, alen));
  }
  uint64_t n_heads = b.uleb();
  if (b.fail || n_heads > (1u << 20)) return false;
  std::vector<const uint8_t *> heads;
  for (uint64_t i = 0; i < n_heads; i++) {
    const uint8_t *h = b.bytes(32);
    if (b.fail) return false;
    heads.push_back(h);
  }
  auto read_col_info = [&](std::vector<DocColumn> &cols) -> bool {
    uint64_t n = b.uleb();
    if (b.fail || n > 4096) return false;
    uint32_t last_id = 0;
    bool first = true;
    for (uint64_t i = 0; i < n; i++) {
      DocColumn col;
      col.id = uint32_t(b.uleb());
      col.len = b.uleb();
      if (b.fail) return false;
      uint32_t bare = col.id & ~uint32_t(kDeflateBit);
      if (!first && bare <= (last_id & ~uint32_t(kDeflateBit))) return false;
      last_id = col.id;
      first = false;
      cols.push_back(col);
    }
    return true;
  };
  std::vector<DocColumn> ccols, ocols;
  if (!read_col_info(ccols) || !read_col_info(ocols)) return false;
  for (auto *cols : {&ccols, &ocols}) {
    for (auto &col : *cols) {
      col.buf = b.bytes(col.len);
      if (b.fail) return false;
      if (col.id & kDeflateBit) {
        if (!inflate_vec(col.buf, col.len, col.inflated)) return false;
        col.id &= ~uint32_t(kDeflateBit);
        col.buf = col.inflated.data();
        col.len = col.inflated.size();
      }
    }
  }
  // optional headsIndexes + doc-level extraBytes (both ignored by the
  // Python decode path too)
  if (b.pos < b.len) {
    for (uint64_t i = 0; i < n_heads; i++) b.uleb();
    if (b.fail) return false;
  }

  auto find = [](std::vector<DocColumn> &cols, uint32_t id) -> DocColumn * {
    for (auto &col : cols) if (col.id == id) return &col;
    return nullptr;
  };

  // ---- change metadata columns ----
  for (auto &col : ccols) {
    switch (col.id) {
      case kDocActor: case kDocSeq: case kDocMaxOp: case kDocTime:
      case kDocMessage: case kDocDepsNum: case kDocDepsIndex:
      case kDocExtraLen: case kDocExtraRaw:
        break;
      default:
        return false;           // unknown change-meta column: Python path
    }
  }
  auto dec = [&](std::vector<DocColumn> &cols, uint32_t id, bool sgn,
                 bool delta, std::vector<int64_t> &v,
                 std::vector<uint8_t> &m) {
    DocColumn *col = find(cols, id);
    if (!col) { v.clear(); m.clear(); return true; }
    return decode_i64_col(col->buf, col->len, sgn, delta, v, m);
  };
  std::vector<int64_t> cm_actor, cm_seq, cm_maxop, cm_time, cm_depsn,
      cm_depsi, cm_extral;
  std::vector<uint8_t> cm_actor_m, cm_seq_m, cm_maxop_m, cm_time_m,
      cm_depsn_m, cm_depsi_m, cm_extral_m;
  if (!dec(ccols, kDocActor, false, false, cm_actor, cm_actor_m) ||
      !dec(ccols, kDocSeq, false, true, cm_seq, cm_seq_m) ||
      !dec(ccols, kDocMaxOp, false, true, cm_maxop, cm_maxop_m) ||
      !dec(ccols, kDocTime, false, true, cm_time, cm_time_m) ||
      !dec(ccols, kDocDepsNum, false, false, cm_depsn, cm_depsn_m) ||
      !dec(ccols, kDocDepsIndex, false, true, cm_depsi, cm_depsi_m) ||
      !dec(ccols, kDocExtraLen, false, false, cm_extral, cm_extral_m))
    return false;
  size_t n_changes = cm_actor.size();
  if (cm_seq.size() != n_changes || cm_maxop.size() != n_changes)
    return false;
  Interner msgs;
  std::vector<int32_t> cm_msg;
  {
    DocColumn *col = find(ccols, kDocMessage);
    if (col) {
      if (!decode_strcol_strict(col->buf, col->len, msgs, cm_msg))
        return false;
      if (cm_msg.size() != n_changes) return false;
    } else {
      cm_msg.assign(n_changes, -1);
    }
  }
  auto padn = [&](std::vector<int64_t> &v, std::vector<uint8_t> &m,
                  size_t n) {
    if (v.empty()) { v.assign(n, 0); m.assign(n, 0); }
    return v.size() == n;
  };
  if (!padn(cm_time, cm_time_m, n_changes) ||
      !padn(cm_depsn, cm_depsn_m, n_changes) ||
      !padn(cm_extral, cm_extral_m, n_changes))
    return false;
  uint64_t deps_total = 0;
  for (size_t i = 0; i < n_changes; i++)
    deps_total += cm_depsn_m[i] ? uint64_t(cm_depsn[i]) : 0;
  if (cm_depsi.size() != deps_total) return false;
  DocColumn *xraw = find(ccols, kDocExtraRaw);
  const uint8_t *extra_buf = xraw ? xraw->buf : nullptr;
  uint64_t extra_len_total = xraw ? xraw->len : 0;

  std::vector<XChange> changes(n_changes);
  {
    uint64_t dpos = 0, xpos = 0;
    for (size_t i = 0; i < n_changes; i++) {
      XChange &ch = changes[i];
      // null actor/seq/maxOp/time -> Python raises in re-encode: bail
      if (!cm_actor_m[i] || !cm_seq_m[i] || !cm_maxop_m[i] || !cm_time_m[i])
        return false;
      if (cm_actor[i] < 0 || uint64_t(cm_actor[i]) >= actors.size())
        return false;
      ch.actor = int32_t(cm_actor[i]);
      ch.seq = cm_seq[i];
      ch.max_op = cm_maxop[i];
      ch.time = cm_time[i];
      ch.msg = cm_msg[i];
      uint64_t nd = cm_depsn_m[i] ? uint64_t(cm_depsn[i]) : 0;
      for (uint64_t k = 0; k < nd; k++, dpos++) {
        if (!cm_depsi_m[dpos]) return false;
        int64_t di = cm_depsi[dpos];
        if (di < 0 || uint64_t(di) >= i) return false;  // forward dep: bail
        ch.deps_idx.push_back(di);
      }
      // extraLen must be a BYTES tag (decode_document_changes' check)
      if (!cm_extral_m[i]) return false;
      uint64_t tag = uint64_t(cm_extral[i]);
      if ((tag & 0xf) != 7) return false;
      uint64_t xlen = tag >> 4;
      if (xpos + xlen > extra_len_total) return false;
      ch.extra = extra_buf + xpos;
      ch.extra_len = xlen;
      xpos += xlen;
    }
    if (dpos != deps_total || xpos != extra_len_total) return false;
  }

  // ---- ops columns ----
  for (auto &col : ocols) {
    switch (col.id) {
      case kColObjActor: case kColObjCtr: case kColKeyActor: case kColKeyCtr:
      case kColKeyStr: case kColIdActor: case kColIdCtr: case kColInsert:
      case kColAction: case kColValLen: case kColValRaw:
      case kColChldActor: case kColChldCtr:
      case kColSuccNum: case kColSuccActor: case kColSuccCtr:
        break;
      default:
        return false;           // unknown ops column: Python path
    }
  }
  std::vector<int64_t> obj_a, obj_c, key_a, key_c, id_a, id_c, act_v, vlen_v,
      chld_a, chld_c, succ_n, succ_a, succ_c;
  std::vector<uint8_t> obj_am, obj_cm, key_am, key_cm, id_am, id_cm, act_m,
      vlen_m, chld_am, chld_cm, succ_nm, succ_am, succ_cm;
  if (!dec(ocols, kColObjActor, false, false, obj_a, obj_am) ||
      !dec(ocols, kColObjCtr, false, false, obj_c, obj_cm) ||
      !dec(ocols, kColKeyActor, false, false, key_a, key_am) ||
      !dec(ocols, kColKeyCtr, false, true, key_c, key_cm) ||
      !dec(ocols, kColIdActor, false, false, id_a, id_am) ||
      !dec(ocols, kColIdCtr, false, true, id_c, id_cm) ||
      !dec(ocols, kColAction, false, false, act_v, act_m) ||
      !dec(ocols, kColValLen, false, false, vlen_v, vlen_m) ||
      !dec(ocols, kColChldActor, false, false, chld_a, chld_am) ||
      !dec(ocols, kColChldCtr, false, true, chld_c, chld_cm) ||
      !dec(ocols, kColSuccNum, false, false, succ_n, succ_nm) ||
      !dec(ocols, kColSuccActor, false, false, succ_a, succ_am) ||
      !dec(ocols, kColSuccCtr, false, true, succ_c, succ_cm))
    return false;
  size_t n_ops = id_c.size();
  if (id_a.size() != n_ops || act_v.size() != n_ops) return false;
  std::vector<int64_t> ins_v(n_ops);
  std::vector<uint8_t> ins_m(n_ops);
  {
    DocColumn *col = find(ocols, kColInsert);
    if (col) {
      if (am_decode_boolean(col->buf, col->len, ins_v.data(), ins_m.data(),
                            int64_t(n_ops)) != int64_t(n_ops))
        return false;
    } else if (n_ops) {
      return false;
    }
  }
  Interner keys;
  std::vector<int32_t> key_str;
  {
    DocColumn *col = find(ocols, kColKeyStr);
    if (col) {
      if (!decode_strcol_strict(col->buf, col->len, keys, key_str))
        return false;
      if (key_str.size() != n_ops) return false;
    } else {
      key_str.assign(n_ops, -1);
    }
  }
  if (!padn(obj_a, obj_am, n_ops) || !padn(obj_c, obj_cm, n_ops) ||
      !padn(key_a, key_am, n_ops) || !padn(key_c, key_cm, n_ops) ||
      !padn(vlen_v, vlen_m, n_ops) || !padn(chld_a, chld_am, n_ops) ||
      !padn(chld_c, chld_cm, n_ops) || !padn(succ_n, succ_nm, n_ops))
    return false;
  uint64_t succ_total = 0;
  for (size_t i = 0; i < n_ops; i++)
    succ_total += succ_nm[i] ? uint64_t(succ_n[i]) : 0;
  if (succ_a.size() != succ_total || succ_c.size() != succ_total)
    return false;
  DocColumn *vraw_col = find(ocols, kColValRaw);
  const uint8_t *raw_buf = vraw_col ? vraw_col->buf : nullptr;
  uint64_t raw_len = vraw_col ? vraw_col->len : 0;

  // ---- reconstruct ops; redistribute into changes (group_change_ops) ----
  // changes_by_actor: Python enforces seq == count+1 in column order and
  // maxOp monotonic per actor
  std::unordered_map<int32_t, std::vector<int32_t>> by_actor;
  for (size_t i = 0; i < n_changes; i++) {
    auto &list = by_actor[changes[i].actor];
    if (changes[i].seq != int64_t(list.size()) + 1) return false;
    if (!list.empty() &&
        changes[size_t(list.back())].max_op > changes[i].max_op)
      return false;
    list.push_back(int32_t(i));
  }

  std::vector<uint8_t> vals;          // raw value bytes arena
  std::vector<XOp> pool;
  pool.reserve(n_ops);
  // (ctr << 20 | actor) -> pool index; actors bounded above by 2^20
  std::unordered_map<int64_t, int32_t> by_id;
  auto idkey = [](int64_t ctr, int32_t actor) -> int64_t {
    return (ctr << 20) | int64_t(uint32_t(actor));
  };
  if (actors.size() > (1u << 20)) return false;
  uint64_t raw_pos = 0, succ_pos = 0;
  for (size_t i = 0; i < n_ops; i++) {
    if (!id_am[i] || !id_cm[i] || !act_m[i]) return false;
    int64_t action = act_v[i];
    // del rows never appear in documents; link (7) and unknown numeric
    // actions take the Python path
    if (action < 0 || action > 6 || action == 3) return false;
    if (uint64_t(id_a[i]) >= actors.size()) return false;
    if (id_c[i] <= 0 || id_c[i] >= (int64_t(1) << 40)) return false;
    XOp op;
    op.ctr = id_c[i];
    op.actor = int32_t(id_a[i]);
    op.action = uint8_t(action);
    op.insert = uint8_t(ins_m[i] ? ins_v[i] : 0);
    if (obj_am[i] != obj_cm[i]) return false;
    if (obj_am[i]) {
      if (uint64_t(obj_a[i]) >= actors.size()) return false;
      op.obj_actor = int32_t(obj_a[i]);
      op.obj_ctr = obj_c[i];
    }
    if (key_str[i] >= 0) {
      if (key_am[i] || key_cm[i]) return false;
      op.key_kind = 0;
      op.key_str = key_str[i];
    } else if (key_cm[i] && key_c[i] == 0 && !key_am[i]) {
      op.key_kind = 1;
    } else if (key_cm[i] && key_am[i]) {
      if (uint64_t(key_a[i]) >= actors.size()) return false;
      op.key_kind = 2;
      op.ek_ctr = key_c[i];
      op.ek_actor = int32_t(key_a[i]);
    } else {
      return false;
    }
    if (chld_am[i] != chld_cm[i]) return false;
    if (chld_am[i]) {
      if (uint64_t(chld_a[i]) >= actors.size()) return false;
      op.chld_actor = int32_t(chld_a[i]);
      op.chld_ctr = chld_c[i];
    }
    // value: normalize exactly as Python's decode+re-encode round trip
    if (vlen_m[i]) {
      uint64_t tag = uint64_t(vlen_v[i]);
      uint8_t vt = uint8_t(tag & 0xf);
      uint32_t ln = uint32_t(tag >> 4);
      if (raw_pos + ln > raw_len) return false;
      const uint8_t *vp = raw_buf + raw_pos;
      if (ln == 0 && (vt == 0 || vt == 1 || vt == 2)) {
        op.vtag = vt;                 // NULL / FALSE / TRUE, no payload
      } else if (vt == 0 || vt == 1 || vt == 2) {
        // a NULL/FALSE/TRUE tag with payload bytes decodes to a raw-bytes
        // value in Python (decode_value's fallthrough) and re-encodes as
        // BYTES — normalize the same way
        op.vtag = (ln << 4) | 7u;
        op.voff = vals.size();
        vals.insert(vals.end(), vp, vp + ln);
      } else if (vt == 3 || vt == 4 || vt == 8 || vt == 9) {
        // minimal-LEB + int53-range check: Python's read/append round
        // trip must reproduce the bytes or raise
        uint64_t p = 0;
        int err = 0;
        int64_t v;
        if (vt == 3) {
          uint64_t uv = read_uleb(vp, ln, &p, &err);
          if (uv > uint64_t(kMaxSafeInt)) return false;
          v = int64_t(uv);
        } else {
          v = read_sleb(vp, ln, &p, &err);
          if (v < -kMaxSafeInt || v > kMaxSafeInt) return false;
        }
        if (err || p != ln) return false;
        // reject non-minimal encodings (Python would shrink them)
        if (ln > 1) {
          uint8_t last = vp[ln - 1];
          if (vt == 3 && last == 0) return false;
          if (vt != 3) {
            uint8_t prev_top = vp[ln - 2] & 0x40;
            if ((last == 0x00 && !prev_top) || (last == 0x7f && prev_top))
              return false;
          }
        }
        (void)v;
        op.vtag = uint32_t(tag);
        op.voff = vals.size();
        vals.insert(vals.end(), vp, vp + ln);
      } else if (vt == 5) {
        if (ln != 8) return false;    // Python: invalid float length
        op.vtag = uint32_t(tag);
        op.voff = vals.size();
        vals.insert(vals.end(), vp, vp + ln);
      } else if (vt == 6) {
        if (!validate_utf8(vp, ln)) return false;
        op.vtag = uint32_t(tag);
        op.voff = vals.size();
        vals.insert(vals.end(), vp, vp + ln);
      } else {
        // BYTES (7) and unknown tags 10-15 round-trip verbatim
        op.vtag = uint32_t(tag);
        op.voff = vals.size();
        vals.insert(vals.end(), vp, vp + ln);
      }
      raw_pos += ln;
    }
    int32_t pool_idx;
    auto it = by_id.find(idkey(op.ctr, op.actor));
    if (it != by_id.end()) {
      XOp &ph = pool[size_t(it->second)];
      // only a synthesized del placeholder (action 3; real del rows bail
      // above) may be superseded — a second real op with the same id is
      // a duplicate the Python path would also reject downstream
      if (ph.action != 3) return false;
      // placeholder created by an earlier succ ref: adopt its preds
      op.pred = std::move(ph.pred);
      ph = op;
      pool_idx = it->second;
    } else {
      pool.push_back(std::move(op));
      pool_idx = int32_t(pool.size() - 1);
      by_id.emplace(idkey(pool[size_t(pool_idx)].ctr,
                          pool[size_t(pool_idx)].actor),
                    pool_idx);
    }
    // succ entries: strictly ascending by (ctr, actor hex)
    uint64_t ns = succ_nm[i] ? uint64_t(succ_n[i]) : 0;
    int64_t prev_ctr = -1;
    int32_t prev_actor = -1;
    for (uint64_t k = 0; k < ns; k++, succ_pos++) {
      if (!succ_am[succ_pos] || !succ_cm[succ_pos]) return false;
      if (uint64_t(succ_a[succ_pos]) >= actors.size()) return false;
      int64_t sc = succ_c[succ_pos];
      int32_t sa = int32_t(succ_a[succ_pos]);
      if (prev_ctr >= 0) {
        if (sc < prev_ctr ||
            (sc == prev_ctr &&
             actors[size_t(sa)] <= actors[size_t(prev_actor)]))
          return false;               // Python: ids not ascending
      }
      prev_ctr = sc;
      prev_actor = sa;
      if (sc <= 0 || sc >= (int64_t(1) << 40)) return false;
      auto sit = by_id.find(idkey(sc, sa));
      int32_t succ_idx;
      if (sit == by_id.end()) {
        // synthesize a del op (group_change_ops, columnar.js:876-943)
        const XOp &self = pool[size_t(pool_idx)];
        XOp del;
        del.ctr = sc;
        del.actor = sa;
        del.action = 3;
        del.obj_ctr = self.obj_ctr;
        del.obj_actor = self.obj_actor;
        if (self.key_kind == 0) {
          del.key_kind = 0;
          del.key_str = self.key_str;
        } else {
          del.key_kind = 2;
          if (self.insert) {
            del.ek_ctr = self.ctr;
            del.ek_actor = self.actor;
          } else if (self.key_kind == 2) {
            del.ek_ctr = self.ek_ctr;
            del.ek_actor = self.ek_actor;
          } else {
            return false;   // _head referent on a non-insert op
          }
        }
        pool.push_back(std::move(del));
        succ_idx = int32_t(pool.size() - 1);
        by_id.emplace(idkey(sc, sa), succ_idx);
      } else {
        succ_idx = sit->second;
      }
      pool[size_t(succ_idx)].pred.emplace_back(
          pool[size_t(pool_idx)].ctr, pool[size_t(pool_idx)].actor);
    }
  }
  if (raw_pos != raw_len || succ_pos != succ_total) return false;

  // assign every op (incl. synthesized dels) to its change by binary
  // search over the actor's maxOp sequence
  for (size_t pi = 0; pi < pool.size(); pi++) {
    const XOp &op = pool[pi];
    auto ait = by_actor.find(op.actor);
    if (ait == by_actor.end()) return false;
    std::vector<int32_t> &list = ait->second;
    size_t lo = 0, hi = list.size();
    while (lo < hi) {
      size_t mid = (lo + hi) / 2;
      if (changes[size_t(list[mid])].max_op < op.ctr) lo = mid + 1;
      else hi = mid;
    }
    if (lo >= list.size()) return false;   // opId outside allowed range
    changes[size_t(list[lo])].ops.push_back(int32_t(pi));
  }
  for (XChange &ch : changes) {
    std::sort(ch.ops.begin(), ch.ops.end(), [&](int32_t x, int32_t y) {
      return pool[size_t(x)].ctr < pool[size_t(y)].ctr;
    });
    int64_t start_op = ch.max_op - int64_t(ch.ops.size()) + 1;
    for (size_t k = 0; k < ch.ops.size(); k++)
      if (pool[size_t(ch.ops[k])].ctr != start_op + int64_t(k))
        return false;                 // non-contiguous opIds in a change
  }

  // ---- encode canonically, in document order; verify heads ----
  std::vector<uint8_t> is_head(n_changes, 1);
  for (size_t i = 0; i < n_changes; i++) {
    for (int64_t di : changes[i].deps_idx) is_head[size_t(di)] = 0;
    if (!encode_extracted_change(changes[i], pool, actors, keys, msgs, vals,
                                 changes, doc))
      return false;
  }
  std::vector<std::string> got_heads, want_heads;
  for (size_t i = 0; i < n_changes; i++)
    if (is_head[i])
      got_heads.emplace_back((const char *)changes[i].hash, 32);
  for (const uint8_t *h : heads)
    want_heads.emplace_back((const char *)h, 32);
  std::sort(got_heads.begin(), got_heads.end());
  std::sort(want_heads.begin(), want_heads.end());
  if (got_heads != want_heads) return false;
  doc.ok = 1;
  return true;
}

static std::vector<DocExtract> *g_extract = nullptr;

}  // namespace

extern "C" {

// Extract a batch of document chunks into canonical per-change chunks +
// hashes. Returns the total change count across extracted docs, or -1 on
// allocation-level failure. Per-doc failures set ok=0 (caller falls back
// per doc). Docs are independent, so the batch fans over the native pool
// with byte-identical output at every width.
int64_t am_extract_changes(const uint8_t *blob, const uint64_t *offsets,
                           const uint64_t *lens, uint64_t n_docs) {
  delete g_extract;
  g_extract = new std::vector<DocExtract>(n_docs);
  std::vector<DocExtract> &docs = *g_extract;
  int threads = NativePool::inst().threads();
  auto one = [&](int t, int) {
    DocExtract &d = docs[size_t(t)];
    if (!extract_document_body(blob + offsets[t], lens[t], d)) {
      DocExtract fresh;
      d = std::move(fresh);           // discard partial output
    }
  };
  if (threads > 1 && n_docs >= 2) {
    NativePool::inst().run(int(n_docs), one);
  } else {
    for (uint64_t i = 0; i < n_docs; i++) one(int(i), 0);
  }
  int64_t total = 0;
  for (auto &d : docs) total += int64_t(d.lens.size());
  return total;
}

// Sizes for fetch-buffer allocation. Returns 0, or -1 with no context.
int64_t am_extract_sizes(int64_t *total_changes, int64_t *blob_bytes) {
  if (!g_extract) return -1;
  int64_t tc = 0, tb = 0;
  for (auto &d : *g_extract) {
    tc += int64_t(d.lens.size());
    tb += int64_t(d.blob.size());
  }
  *total_changes = tc;
  *blob_bytes = tb;
  return 0;
}

// Copy out: ok [n_docs], d_off [n_docs+1] (per-doc first change index),
// c_off [C+1] (per-change byte offsets into blob), blob, hashes [32*C],
// max_ops [C]. Returns C and frees the context.
int64_t am_extract_fetch(uint8_t *ok, int64_t *d_off, int64_t *c_off,
                         uint8_t *blob, uint8_t *hashes, int64_t *max_ops) {
  if (!g_extract) return -1;
  std::vector<DocExtract> &docs = *g_extract;
  int64_t ci = 0, bpos = 0;
  for (size_t d = 0; d < docs.size(); d++) {
    ok[d] = docs[d].ok;
    d_off[d] = ci;
    for (size_t k = 0; k < docs[d].lens.size(); k++) {
      c_off[ci] = bpos;
      max_ops[ci] = docs[d].max_ops[k];
      bpos += docs[d].lens[k];
      ci++;
    }
    copy_bytes(blob + (bpos - int64_t(docs[d].blob.size())),
           docs[d].blob.data(), docs[d].blob.size());
    copy_bytes(hashes + 32 * (ci - int64_t(docs[d].lens.size())),
           docs[d].hashes.data(), docs[d].hashes.size());
  }
  d_off[docs.size()] = ci;
  c_off[ci] = bpos;
  delete g_extract;
  g_extract = nullptr;
  return ci;
}

}  // extern "C"
