"""Shard-per-core scale-out: consistent-hash routing, replica sync,
kill-driven failover (the ROADMAP's horizontal-scale frontier).

``HashRing`` (ring.py) owns placement; ``Shard`` / ``ShardRouter``
(cluster.py) own serving, inter-shard replication over the existing
sync wire protocol, lease-based failure detection, replica promotion,
and chunk-transfer rebalance. ``tools/loadgen.py``'s ``run_shard_leg``
is the kill-and-recover chaos harness; bench.py's ``shards`` section
reports aggregate req/s scaling and failover MTTR.
"""

from .cluster import RouterTicket, Shard, ShardRouter, shard_stats
from .ring import HashRing

__all__ = ['HashRing', 'Shard', 'ShardRouter', 'RouterTicket',
           'shard_stats']
