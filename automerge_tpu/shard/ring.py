"""Consistent-hash ring: stable tenant -> shard placement.

The router's placement problem is the classic one: N shards come and go
(kills, revives, scale-out) and tenant -> shard assignment must move as
LITTLE as possible when membership changes — a modulo hash reshuffles
almost every tenant on every membership event, which would turn one
shard failure into a fleet-wide cold-cache migration storm. The ring
fixes the placement of every shard's virtual nodes on a 64-bit circle
(SHA-256 of ``"{shard}#{vnode}"``) and homes a tenant on the first
vnode clockwise of its own hash, so removing one shard only re-homes
the tenants that shard owned, and re-adding it restores exactly the old
placement (kill -> revive -> rebalance round-trips to the original
topology).

Membership is SPLIT from liveness on purpose: the ring always contains
every configured shard (stable hashing), and lookups take an ``alive``
filter — a dead shard's tenants resolve to the next live shard on the
ring (which is exactly where the router placed their replicas), without
mutating the ring itself.
"""

import bisect
import hashlib

__all__ = ['HashRing']


def _point(key):
    """A stable 64-bit position on the circle."""
    return int.from_bytes(
        hashlib.sha256(key.encode('utf-8')).digest()[:8], 'big')


class HashRing:
    """Consistent-hash ring over shard ids (see the module docstring).

    ``vnodes`` virtual nodes per shard smooth the partition sizes — at
    the default 64, per-shard tenant share is within a few tens of
    percent of uniform for realistic shard counts, and placement stays
    deterministic across processes (pure SHA-256, no process seed)."""

    def __init__(self, shard_ids=(), vnodes=64):
        self.vnodes = int(vnodes)
        self._points = []            # sorted (position, shard_id)
        self._ids = []               # insertion order, for stable iteration
        for shard_id in shard_ids:
            self.add(shard_id)

    def __contains__(self, shard_id):
        return shard_id in self._ids

    def __len__(self):
        return len(self._ids)

    def shard_ids(self):
        return list(self._ids)

    def add(self, shard_id):
        if shard_id in self._ids:
            return
        self._ids.append(shard_id)
        for v in range(self.vnodes):
            self._points.append((_point(f'{shard_id}#{v}'), shard_id))
        self._points.sort()

    def remove(self, shard_id):
        """Drop a shard from the ring entirely (decommission — NOT the
        liveness path; a dead-but-configured shard stays on the ring and
        is skipped via the ``alive`` filter, so its revival restores the
        original placement)."""
        if shard_id not in self._ids:
            return
        self._ids.remove(shard_id)
        self._points = [(p, s) for p, s in self._points if s != shard_id]

    def preference(self, key, n=None, alive=None):
        """The first ``n`` DISTINCT shards clockwise of ``key``'s hash,
        optionally filtered to ``alive`` (a container or predicate).
        This is the tenant's preference list: element 0 is its home,
        element 1 its replica, and a failover simply advances down the
        list."""
        if not self._points:
            return []
        if alive is None:
            ok = lambda s: True                              # noqa: E731
        elif callable(alive):
            ok = alive
        else:
            ok = alive.__contains__
        want = len(self._ids) if n is None else int(n)
        out = []
        start = bisect.bisect_right(self._points, (_point(key), ''))
        for i in range(len(self._points)):
            shard_id = self._points[(start + i) % len(self._points)][1]
            if shard_id in out or not ok(shard_id):
                continue
            out.append(shard_id)
            if len(out) >= want:
                break
        return out

    def primary(self, key, alive=None):
        """The key's home shard (None when no shard qualifies)."""
        got = self.preference(key, n=1, alive=alive)
        return got[0] if got else None

    def replica(self, key, alive=None):
        """The next distinct shard after the key's home — the replica
        placement (None with fewer than two qualifying shards)."""
        got = self.preference(key, n=2, alive=alive)
        return got[1] if len(got) > 1 else None
