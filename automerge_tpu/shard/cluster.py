"""Crash-fault-tolerant shard scale-out: router, replication, failover.

The single ``DocService`` tick loop is the architectural ceiling for
"millions of users" (ROADMAP): one thread, one fleet, one fused dispatch
stream. This module is the horizontal answer — N ``Shard``s (each its
own ``DocFleet`` + ``DocService`` + ``StorageEngine``; thread-per-shard
today, but every cross-shard interaction goes through bytes-on-a-link
or chunk transfer, so a shard could be a process without an API change)
fronted by a ``ShardRouter`` that owns:

- **Placement**: a consistent-hash ring over tenants (shard/ring.py).
  Every tenant has a HOME shard (its serving session) and a REPLICA
  shard (a warm doc kept current by inter-shard replication).
- **Replication**: the EXISTING sync wire protocol
  (fleet/sync_driver.py), batched per shard pair per tick — one fused
  generate and one fused receive per shard per round — over
  ``LossyLink``-wrappable links, so chaos tests drive the REAL
  replication path through drops, dup, corruption, partitions, and
  crashes. Corrupt replication messages quarantine per doc (never
  poison a fleet); stalled pair handshakes (loss-poisoned
  ``sentHashes``) reset like the service's reconnect rule.
- **Acknowledged-write durability**: an 'apply' is acked to the client
  only once its changes are on BOTH the home doc and the replica doc
  (checked by change hash as replication lands). Acked => survives any
  single shard crash. With no replica available (single-shard, or a
  double failure window) the router degrades to single-copy acks —
  visible in ``shard_stats()['shard_degraded_acks']``, never silent.
- **Failure detection + failover**: shards heartbeat by pumping; a
  shard whose lease (``lease_ticks``) expires is declared dead. Its
  tenants re-home onto their replica shard (the warm doc is PROMOTED
  to a serving session via ``DocService.adopt_session``), a new
  replica is placed on the next live ring shard, and in-flight
  requests against the dead shard come back typed
  (``ShardUnavailable``) or ride the router's budgeted jittered
  retries (service/backoff.py) onto the new home. Re-homed sessions
  get a FRESH per-peer sync state — the client's next sync runs the
  ``reset=True`` reconnect rule — and their standing subscription
  cursor is re-registered on the new session; a cursor naming heads
  the replica never received resolves as a TYPED resync event, never a
  silently stale patch.
- **Planned rebalance**: ``rebalance()`` migrates tenants back to
  their ring-preferred home (after a revive, or scale-out) through the
  storage engine's chunk-transfer primitive — ``StorageEngine.park``
  on the donor, ``ingest_chunks`` + ``revive`` on the receiver — with
  brownout-style degraded serving while in flight: reads
  (sync/materialize_at/subscribe) keep flowing from the donor, writes
  get typed pushback with ``retry_after`` (the router parks and
  retries them onto the new home), never hard unavailability.

The router is tick-driven and deterministic: ``pump()`` runs every live
shard's service tick, advances the link clocks, checks leases, runs one
replication round, steps migrations, and settles router-level tickets —
all on an injected clock, so the kill-and-recover chaos harness
(tools/loadgen.py ``run_shard_leg``) replays byte-identically from its
seed. ``tools/loadgen.py`` proves the two contract properties: ZERO
acknowledged-write loss across kills, and post-quiet byte-identical
convergence between every tenant's home and replica docs.
"""

import threading
import time

from ..backend import get_change_by_hash, get_heads
from ..backend.sync import init_sync_state
from ..columnar import decode_change_meta
from ..errors import (AutomergeError, Overloaded, SessionClosed,
                      ShardUnavailable, WireCorruption)
from ..fleet import backend as fleet_backend
from ..fleet.backend import DocFleet
from ..fleet.storage import StorageEngine
from ..fleet.hashindex import release_sync_state
from ..fleet.sync_driver import (generate_sync_messages_docs,
                                 receive_sync_messages_docs)
from ..observability import hist as _hist
from ..observability import recorder as _flight
from ..observability.metrics import Counters, register_health_source
from ..observability.spans import span as _span
from ..service import DocService
from ..service.backoff import Backoff, RetryBudgetPool
from .ring import HashRing

__all__ = ['Shard', 'ShardRouter', 'RouterTicket', 'shard_stats']

# serializes shard_pump_s histogram records across pool pumps (see
# Shard.pump); Counters have their own lock, Histogram.record does not
_pump_hist_lock = threading.Lock()

_stats = Counters({
    'shard_kills': 0,              # Shard.kill() crashes injected
    'shard_revives': 0,            # Shard.revive() restarts
    'shard_failovers': 0,          # lease expiries acted on
    'shard_rehomed_sessions': 0,   # tenants promoted onto their replica
    'shard_rebalances': 0,         # planned migrations started
    'shard_migrations': 0,         # chunk-transfer migrations completed
    'shard_unavailable': 0,        # typed ShardUnavailable routing events
    'shard_retries': 0,            # router-level backoff retries parked
    'shard_repl_rounds': 0,        # replication rounds run
    'shard_repl_resets': 0,        # stalled pair handshakes reset
    'shard_repl_quarantined': 0,   # corrupt replication messages contained
    'shard_degraded_acks': 0,      # applies acked with no replica copy
    'shard_ticks_slipped': 0,      # shard pumps that overran tick_budget_s
    'shard_scrub_mismatches': 0,   # anti-entropy frontier divergences found
})
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])


def shard_stats():
    return dict(_stats)


class Shard:
    """One failure domain: its own fleet, service, and storage engine.
    ``pump`` is the heartbeat — a crashed shard (``kill()``) simply
    stops pumping, and the router notices only through the missed
    lease, exactly like a dead process. ``revive()`` restarts the
    shard EMPTY (crash-fault semantics: its memory died with it; state
    re-enters via replication catch-up or a planned migration)."""

    def __init__(self, shard_id, *, exact_device=False,
                 clock=time.monotonic, service_kwargs=None):
        self.id = shard_id
        self._exact = exact_device
        self._clock = clock
        self._service_kwargs = dict(service_kwargs or {})
        self.alive = True
        self.last_beat = 0
        # tick-overrun telemetry: pumps whose wall time exceeded the
        # router's tick_budget_s (None = free-running, never counted),
        # plus the last pump's duration for dashboards. A box whose
        # per-shard tick work does not fit the serving cadence shows it
        # HERE, per failure domain, instead of only in the loadgen's
        # aggregate pacing loop.
        self.ticks_slipped = 0
        self.last_pump_s = 0.0
        self._build()

    def _build(self):
        self.fleet = DocFleet(exact_device=self._exact)
        kwargs = dict(slo=False)
        kwargs.update(self._service_kwargs)
        self.service = DocService(fleet=self.fleet, clock=self._clock,
                                  **kwargs)
        self.storage = StorageEngine(fleet=self.fleet)

    def pump(self, tick, now=None, budget_s=None):
        """One service tick + heartbeat. A dead shard does nothing —
        whatever its queues held is unreachable until revive. With a
        `budget_s` cadence, a pump that overruns it counts a slipped
        tick (per shard here, globally in `shard_ticks_slipped`)."""
        if not self.alive:
            return None
        start = time.perf_counter()
        with _span('shard_tick', shard=self.id):
            stats = self.service.pump(now=now)
        self.last_pump_s = time.perf_counter() - start
        # the perf observatory's shard seam: pump seconds as a log2
        # histogram, the signal PerfBaselines('shard_pump') judges.
        # Recorded under a lock: pumps run CONCURRENTLY on the pool,
        # and Histogram.record is a read-modify-write (one acquire per
        # pump TICK, not per request — nothing the 2% budget sees)
        with _pump_hist_lock:
            _hist.record_value('shard_pump_s', self.last_pump_s,
                               scale=1e9, unit='s')
        if budget_s is not None and self.last_pump_s > budget_s:
            self.ticks_slipped += 1
            _stats.inc('shard_ticks_slipped')
        self.last_beat = tick
        return stats

    def kill(self):
        """Crash the shard: it stops pumping (and so heart-beating).
        Nothing is cleaned up — a crash doesn't flush queues."""
        if not self.alive:
            return
        self.alive = False
        _stats.inc('shard_kills')
        _flight.record_event('shard_kill', shard=self.id)

    def revive(self):
        """Restart the crashed shard with EMPTY state (its memory died
        with the process). The router must re-admit it before it serves
        (``ShardRouter.revive_shard`` does both)."""
        if self.alive:
            return
        self._build()
        self.alive = True
        _stats.inc('shard_revives')
        _flight.record_event('shard_revive', shard=self.id)


class RouterTicket:
    """A router-level request handle: resolves 'ok' or 'error' (typed —
    shedding, unavailability, and failover gaps are never untyped).
    For 'apply' requests, 'ok' means the REPLICATION CONTRACT is met:
    the changes are on the home doc AND the replica doc (or the router
    is running replica-less, counted in ``shard_degraded_acks``)."""

    __slots__ = ('kind', 'tenant', 'status', 'result', 'error',
                 'submitted_tick', 'finished_tick', 'attempts', 'shard')

    def __init__(self, kind, tenant, tick):
        self.kind = kind
        self.tenant = tenant
        self.status = 'pending'
        self.result = None
        self.error = None
        self.submitted_tick = tick
        self.finished_tick = None
        self.attempts = 0
        self.shard = None

    @property
    def done(self):
        return self.status != 'pending'

    def _finish(self, tick, result=None, error=None, shard=None):
        if self.done:
            return
        self.finished_tick = tick
        self.shard = shard
        if error is not None:
            self.status = 'error'
            self.error = error
        else:
            self.status = 'ok'
            self.result = result

    def __repr__(self):
        return (f'RouterTicket({self.kind}, tenant={self.tenant!r}, '
                f'status={self.status!r})')


class _RReq:
    __slots__ = ('kind', 'tenant', 'payload', 'payload_fn', 'timeout',
                 'priority', 'ticket', 'attempts', 'not_before', 'state',
                 'sub', 'hashes', 'home_at_submit', 'result_cache')

    def __init__(self, kind, tenant, payload, payload_fn, timeout,
                 priority, ticket):
        self.kind = kind
        self.tenant = tenant
        self.payload = payload
        self.payload_fn = payload_fn
        self.timeout = timeout
        self.priority = priority
        self.ticket = ticket
        self.attempts = 0
        self.not_before = 0.0
        self.state = 'new'        # parked | submitted | await_replica
        self.sub = None
        self.hashes = None
        self.home_at_submit = None
        self.result_cache = None


class _Tenant:
    """The router's record of one tenant: where it lives, its warm
    replica, the replication handshake state for the pair, and the
    standing-subscription cursor the router re-registers on re-home."""

    __slots__ = ('name', 'home', 'replica_on', 'session',
                 'replica_handle', 'state_home', 'state_rep',
                 'inbox_home', 'inbox_rep', 'cursor', 'needs_reset',
                 'read_only', 'stall', 'last_pair_heads', 'quiet',
                 'migrating', 'placed')

    def __init__(self, name):
        self.name = name
        self.home = None
        self.replica_on = None
        self.session = None
        self.replica_handle = None
        self.state_home = init_sync_state()
        self.state_rep = init_sync_state()
        self.inbox_home = []
        self.inbox_rep = []
        self.cursor = []            # last subscription heads served
        self.needs_reset = False    # next client sync runs reset=True
        self.read_only = False      # in-migration: writes pushed back
        self.stall = 0
        self.last_pair_heads = None
        self.quiet = True
        self.migrating = None       # {'phase': ..., 'to': shard_id}
        self.placed = False         # ever had a home session (a
                                    # never-placed tenant can be placed
                                    # fresh on revive without data loss;
                                    # a double-failure one cannot)

    def _reset_pair(self):
        # the old handshake's sentHashes may ride fleet peer-spaces:
        # hand them back now, not at GC (space ids are never reused, so
        # the fresh pair cannot inherit the stale sent set either way)
        release_sync_state(self.state_home)
        release_sync_state(self.state_rep)
        self.state_home = init_sync_state()
        self.state_rep = init_sync_state()
        self.inbox_home = []
        self.inbox_rep = []
        self.stall = 0
        self.last_pair_heads = None
        self.quiet = False


class ShardRouter:
    """See the module docstring. ``submit`` never raises for transient
    conditions — routing gaps (dead shard, migration read-only window,
    admission pushback) park the request under the budgeted jittered
    backoff and the ticket resolves typed if the budget runs dry."""

    def __init__(self, n_shards=None, shard_ids=None, *,
                 exact_device=False, clock=None, lease_ticks=3,
                 vnodes=64, link_factory=None, backoff=None,
                 retry_rate=50.0, retry_burst=100.0,
                 repl_stall_rounds=8, service_kwargs=None,
                 pump_threads=None, repl_every=1, tick_budget_s=None,
                 scrub_every=25, control=None):
        if shard_ids is None:
            shard_ids = [f'shard{i}' for i in range(n_shards or 1)]
        self.clock = clock if clock is not None else time.monotonic
        self.shards = {sid: Shard(sid, exact_device=exact_device,
                                  clock=self.clock,
                                  service_kwargs=service_kwargs)
                       for sid in shard_ids}
        self.ring = HashRing(shard_ids, vnodes=vnodes)
        self.alive = set(shard_ids)    # the ROUTER's lease-driven view
        self.lease_ticks = int(lease_ticks)
        self.link_factory = link_factory
        self._links = {}               # (src, dst) -> LossyLink or None
        self.backoff = backoff if backoff is not None else \
            Backoff(base=0.05, factor=1.5, cap=1.0, retries=12, seed=7)
        self._retry_budgets = RetryBudgetPool(retry_rate, retry_burst)
        self.repl_stall_rounds = int(repl_stall_rounds)
        # group-commit cadence: a replication round every `repl_every`
        # ticks. >1 amortizes the fused sync-protocol cost over more
        # committed changes per round (higher aggregate throughput, ack
        # latency up by <= repl_every ticks). The ACK CONTRACT is
        # cadence-independent: an apply resolves only once its hashes
        # are on both copies, however long replication takes.
        self.repl_every = max(1, int(repl_every))
        # serving cadence for tick-overrun telemetry: when set, every
        # shard pump that overruns it counts a per-shard slipped tick
        # (Shard.ticks_slipped; Prometheus exposition with shard labels
        # via observability.export.render_prometheus(router=...))
        self.tick_budget_s = tick_budget_s
        # anti-entropy head-frontier scrub cadence (ticks; 0/None = off):
        # a cheap per-replica-pair heads compare that catches SILENT
        # home/replica divergence — a pair that believes itself
        # converged-quiet while the frontiers disagree — earlier than
        # the next write would. Found pairs emit a typed
        # shard_frontier_mismatch event and reset their handshake.
        self.scrub_every = int(scrub_every or 0)
        self.scrub_mismatches = []     # [{'tick', 'tenant', ...}]
        self.ticks = 0
        self._tenants = {}
        self._pending = []
        self.failovers = []            # [{'tick', 'shard', 'moved'}]
        # thread-per-shard pump: shard ticks are independent (each shard
        # owns its fleet/service; every cross-shard phase — links,
        # leases, replication, migration, settlement — runs serially
        # after the barrier), so pumping them concurrently changes no
        # DOC/TICKET state outcome, only wall time. None/1 = serial.
        # Module-global telemetry COUNTERS are EXACT under the pool:
        # every `_stats` family is an observability.Counters whose
        # increments hold a shared lock across the read-add-write (the
        # round-15 undercount caveat, retired — pinned by the
        # pump_threads>1 hammer in tests/test_perf_obs.py). The
        # shard_pump_s histogram takes a lock at its record site;
        # other histograms recorded from inside concurrent pumps
        # (service_tick_s, apply_batch_s — off unless observability is
        # enabled) remain best-effort per-sample, which the perf
        # baselines' window means tolerate.
        self._pool = None
        if pump_threads is not None and int(pump_threads) > 1:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=int(pump_threads),
                thread_name_prefix='shard-pump')
        # `control`: a control/ Controller ticked once per cluster pump
        # (after harvest, when the tick's placement/pending state is
        # settled). Its shard-balance policy drives rehome_tenant —
        # the same migration machinery rebalance() uses.
        self.control = control
        if control is not None:
            control.attach(router=self)

    # -- wiring ---------------------------------------------------------

    def close(self):
        """Release the pump thread pool (no-op when serial)."""
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def _link(self, src, dst):
        key = (src, dst)
        if key not in self._links:
            self._links[key] = None if self.link_factory is None \
                else self.link_factory(src, dst)
        return self._links[key]

    def _transmit(self, src, dst, message):
        link = self._link(src, dst)
        if link is None:
            return [message] if message is not None else []
        return link.transmit(message)

    def tenant_record(self, name):
        """The router's internal record (tests and the chaos harness
        read home/replica placement and doc handles through this)."""
        return self._tenants[name]

    def tenants_on(self, shard_id):
        return [r.name for r in self._tenants.values()
                if r.home == shard_id]

    # -- membership -----------------------------------------------------

    def kill_shard(self, shard_id):
        """Crash a shard (chaos entry). The router does NOT learn of it
        here — detection happens through the missed lease, like a real
        dead process."""
        self.shards[shard_id].kill()

    def revive_shard(self, shard_id):
        """Restart a crashed shard empty and re-admit it to the serving
        set (fresh lease). Existing tenants stay on their failover home
        until ``rebalance()`` migrates them back."""
        shard = self.shards[shard_id]
        if not shard.alive and shard_id in self.alive:
            # revived before the lease noticed the death: the crash
            # still destroyed its memory, so the failover must run NOW
            # or tenants would keep sessions into the dead incarnation
            # while the router routes at a rebuilt-empty service
            self._failover(shard_id)
        shard.revive()
        shard.last_beat = self.ticks
        self.alive.add(shard_id)
        # returned capacity heals replica-less tenants NOW: a failover
        # that found no spare shard left them on degraded single-copy
        # acks, and nothing else re-places a replica for a tenant whose
        # HOME never moves — without this, every later apply would keep
        # acking single-copy forever despite a live spare shard
        for rec in self._tenants.values():
            if rec.home is None and not rec.placed:
                # opened during the outage: place it fresh now (it
                # never held data, so nothing can be lost)
                self._place(rec)
            elif rec.home in self.alive and rec.session is not None and \
                    (rec.replica_on not in self.alive or
                     rec.replica_handle is None):
                self._ensure_replica(rec)

    # -- tenants --------------------------------------------------------

    def open_tenant(self, name):
        """Place a tenant: home session on its ring-primary shard, warm
        replica doc on the next live ring shard. Idempotent. During a
        FULL outage the tenant is recorded unplaced (home None) rather
        than raising — its requests park/resolve typed through the
        normal unavailable path, and the next ``revive_shard`` places
        it (fresh and empty, so no data can be lost by the wait)."""
        rec = self._tenants.get(name)
        if rec is not None:
            return rec
        rec = _Tenant(name)
        self._tenants[name] = rec
        self._place(rec)
        return rec

    def _place(self, rec):
        home = self.ring.primary(rec.name, alive=self.alive)
        if home is None:
            return False
        rec.home = home
        rec.session = self.shards[home].service.open_session(rec.name)
        rec.placed = True
        self._ensure_replica(rec)
        return True

    def _ensure_replica(self, rec):
        """(Re)place the tenant's warm replica on the first live ring
        shard after its home; fresh pair handshake. No-op when the
        placement is already correct. With fewer than two live shards
        the tenant runs replica-less (degraded single-copy acks)."""
        want = None
        for sid in self.ring.preference(rec.name, alive=self.alive):
            if sid != rec.home:
                want = sid
                break
        if want == rec.replica_on and rec.replica_handle is not None:
            return
        old_on, old_handle = rec.replica_on, rec.replica_handle
        if old_handle is not None and old_on in self.alive and \
                self.shards[old_on].alive:
            fleet_backend.free_docs([old_handle])
        rec.replica_on = want
        rec.replica_handle = None
        if want is not None:
            rec.replica_handle = fleet_backend.init_docs(
                1, self.shards[want].fleet)[0]
        rec._reset_pair()

    # -- submission -----------------------------------------------------

    def submit(self, tenant, kind, payload=None, *, payload_fn=None,
               timeout=None, priority=None):
        """Route one request to the tenant's home shard. Returns a
        ``RouterTicket``; resolution (including every failure) is typed.
        `payload_fn` is the client transport draw — the router draws it
        ONCE PER ATTEMPT (the same bytes reach home and, via
        replication, the replica), and wire-corruption verdicts retry
        through the router's backoff with a fresh draw."""
        rec = self._tenants.get(tenant)
        if rec is None:
            rec = self.open_tenant(tenant)
        ticket = RouterTicket(kind, tenant, self.ticks)
        req = _RReq(kind, tenant, payload, payload_fn, timeout, priority,
                    ticket)
        self._dispatch(req, self.clock())
        if not ticket.done:
            self._pending.append(req)
        return ticket

    def _unavailable(self, message, *, shard, tenant):
        """Mint a typed ``ShardUnavailable`` and count it — EVERY mint
        site goes through here so ``shard_unavailable`` matches the
        tickets that actually saw the error."""
        _stats.inc('shard_unavailable')
        return ShardUnavailable(message, shard=shard, tenant=tenant,
                                retry_after=None)

    def _fail_or_retry(self, req, error, now, transient=True):
        """Park the request under backoff + per-tenant budget, or
        resolve it with the (typed) error."""
        if transient and not self.backoff.exhausted(req.attempts) and \
                self._retry_budgets.get(req.tenant).spend(now):
            delay = self.backoff.delay(req.attempts)
            req.attempts += 1
            req.ticket.attempts = req.attempts
            req.not_before = now + delay
            req.state = 'parked'
            req.sub = None
            _stats.inc('shard_retries')
            return
        req.ticket._finish(self.ticks, error=error,
                           shard=self._tenants[req.tenant].home)

    def _dispatch(self, req, now):
        rec = self._tenants[req.tenant]
        if rec.home is None or rec.home not in self.alive:
            self._fail_or_retry(req, self._unavailable(
                f'tenant {req.tenant!r} home shard unavailable',
                shard=rec.home, tenant=req.tenant), now)
            return
        if rec.read_only and req.kind == 'apply':
            # brownout-style degraded serving while the tenant migrates:
            # reads keep flowing, writes get typed pushback and ride the
            # router's backoff onto the new home
            self._fail_or_retry(req, Overloaded(
                f'tenant {req.tenant!r} migrating: reads only',
                retry_after=0.05, shed=False, stage='migration'), now)
            return
        if req.payload_fn is not None:
            try:
                payload = req.payload_fn()
            except Exception as exc:
                req.ticket._finish(self.ticks, error=Overloaded(
                    f'transport draw failed: {exc!r}', retry_after=None,
                    shed=False, stage=None, budget='throttled'),
                    shard=rec.home)
                return
            if payload is None:
                req.ticket._finish(self.ticks, error=Overloaded(
                    'transport delivered nothing', retry_after=0.01,
                    shed=False, stage=None, budget='throttled'),
                    shard=rec.home)
                return
        else:
            payload = req.payload
        hashes = None
        if req.kind == 'apply':
            # the ack contract needs the change hashes BEFORE anything
            # is enqueued: bytes that don't even decode can never meet
            # it, so they resolve typed here (and, on a payload_fn
            # transport, retry with a fresh draw) instead of raising
            # out of submit()/pump() with a doomed request queued
            try:
                hashes = [decode_change_meta(bytes(b), True)['hash']
                          for b in payload]
            except AutomergeError as exc:
                self._fail_or_retry(req, exc, now,
                                    transient=req.payload_fn is not None)
                return
        reset = req.kind == 'sync' and rec.needs_reset
        try:
            sub = self.shards[rec.home].service.submit(
                rec.session, req.kind, payload, timeout=req.timeout,
                priority=req.priority, reset=reset)
        except AutomergeError as exc:
            self._fail_or_retry(req, exc, now)
            return
        if reset:
            rec.needs_reset = False
        req.sub = sub
        req.state = 'submitted'
        req.home_at_submit = rec.home
        if req.kind == 'apply':
            req.hashes = hashes
            req.result_cache = len(payload)

    # -- the tick -------------------------------------------------------

    def pump(self, now=None):
        """One cluster tick (see the module docstring for the phases).
        Deterministic given the injected clock and link seeds."""
        self.ticks += 1
        now = self.clock() if now is None else now
        with _span('shard_router_tick', tick=self.ticks,
                   shards=len(self.alive)):
            budget = self.tick_budget_s
            if self._pool is not None:
                futures = [self._pool.submit(self.shards[sid].pump,
                                             self.ticks, now, budget)
                           for sid in self.ring.shard_ids()]
                for f in futures:
                    f.result()
            else:
                for sid in self.ring.shard_ids():
                    self.shards[sid].pump(self.ticks, now,
                                          budget_s=budget)
            for link in self._links.values():
                if link is not None:
                    link.tick()
            for sid in list(self.alive):
                if self.ticks - self.shards[sid].last_beat > \
                        self.lease_ticks:
                    self._failover(sid)
            if self.ticks % self.repl_every == 0:
                self._replicate()
            if self.scrub_every and self.ticks % self.scrub_every == 0:
                self.scrub_frontiers()
            self._advance_migrations()
            self._harvest(now)
            if self.control is not None:
                self.control.tick(now)

    # -- failover -------------------------------------------------------

    def _failover(self, dead):
        """The lease expired: re-home the dead shard's tenants onto
        their replicas, re-place replicas that lived there, cancel
        migrations touching it."""
        self.alive.discard(dead)
        _stats.inc('shard_failovers')
        _flight.record_event('shard_failover', shard=dead,
                             tick=self.ticks)
        moved = []
        for rec in self._tenants.values():
            if rec.migrating is not None and \
                    (rec.home == dead or rec.migrating['to'] == dead):
                rec.migrating = None
                rec.read_only = False
            if rec.home == dead:
                new_home = rec.replica_on \
                    if rec.replica_on in self.alive else None
                if new_home is None:
                    # both copies gone (double failure): unavailable,
                    # typed, until an operator re-homes it
                    rec.home = None
                    rec.session = None
                    rec.replica_on = None
                    rec.replica_handle = None
                    continue
                shard = self.shards[new_home]
                rec.session = shard.service.adopt_session(
                    rec.name, rec.replica_handle)
                # the standing subscription survives the re-home: the
                # promoted session continues from the cursor the router
                # tracked; heads the replica never received resolve as
                # a TYPED resync event, never a silently stale patch
                rec.session.sub_cursor = list(rec.cursor)
                rec.home = new_home
                rec.replica_on = None
                rec.replica_handle = None
                rec.needs_reset = True
                _stats.inc('shard_rehomed_sessions')
                self._ensure_replica(rec)
                moved.append(rec.name)
            elif rec.replica_on == dead:
                rec.replica_on = None
                rec.replica_handle = None
                self._ensure_replica(rec)
        self.failovers.append({'tick': self.ticks, 'shard': dead,
                               'moved': moved})

    # -- replication ----------------------------------------------------

    def _repl_active(self):
        # PHYSICAL liveness gates the data plane: a killed shard's
        # memory cannot accept or produce bytes even while the router's
        # lease-driven view (self.alive) hasn't noticed the death yet —
        # during that window the pair is simply dark (an apply's
        # replica wait keeps waiting; failover re-places the replica
        # and the wait settles through the NEW copy). The router-view
        # checks stay: they cover failed-over placement holes.
        return [rec for rec in self._tenants.values()
                if rec.home in self.alive and rec.session is not None
                and rec.replica_on in self.alive
                and rec.replica_handle is not None
                and self.shards[rec.home].alive
                and self.shards[rec.replica_on].alive]

    def _replicate(self):
        """One replication round: per live shard, ONE fused generate and
        ONE fused receive for each side of its tenant pairs, messages
        crossing the (possibly lossy) inter-shard links.

        Converged-QUIET pairs whose heads have not moved since their
        last round are skipped entirely — two ``get_heads`` reads
        instead of riding the fused generate — so the steady-state cost
        of a round is O(dirty pairs), not O(tenants). A skipped pair
        wakes the moment either side's heads move: the home moves on a
        committed apply, and replica heads can only move through a
        round it participated in, so no wake-up can be missed."""
        everyone = self._repl_active()
        active = []
        for rec in everyone:
            pair = (tuple(get_heads(rec.session.handle)),
                    tuple(get_heads(rec.replica_handle)))
            if rec.quiet and pair == rec.last_pair_heads and \
                    not rec.inbox_home and not rec.inbox_rep:
                continue
            active.append(rec)
        if not active:
            return
        _stats.inc('shard_repl_rounds')
        sent = {}
        with _span('shard_replication', pairs=len(active)):
            # generate, home side, grouped per home shard
            for side in ('home', 'rep'):
                groups = {}
                for rec in active:
                    key = rec.home if side == 'home' else rec.replica_on
                    groups.setdefault(key, []).append(rec)
                for recs in groups.values():
                    if side == 'home':
                        handles = [r.session.handle for r in recs]
                        states = [r.state_home for r in recs]
                    else:
                        handles = [r.replica_handle for r in recs]
                        states = [r.state_rep for r in recs]
                    new_states, msgs = generate_sync_messages_docs(
                        handles, states)
                    for r, st, m in zip(recs, new_states, msgs):
                        if side == 'home':
                            r.state_home = st
                            if m is not None:
                                r.inbox_rep.extend(self._transmit(
                                    r.home, r.replica_on, m))
                        else:
                            r.state_rep = st
                            if m is not None:
                                r.inbox_home.extend(self._transmit(
                                    r.replica_on, r.home, m))
                        if m is not None:
                            sent[id(r)] = True
            # receive, both sides, one inbox message per pair per round
            for side in ('home', 'rep'):
                groups = {}
                for rec in active:
                    inbox = rec.inbox_home if side == 'home' \
                        else rec.inbox_rep
                    if inbox:
                        key = rec.home if side == 'home' \
                            else rec.replica_on
                        groups.setdefault(key, []).append(rec)
                for recs in groups.values():
                    if side == 'home':
                        handles = [r.session.handle for r in recs]
                        states = [r.state_home for r in recs]
                        msgs = [r.inbox_home.pop(0) for r in recs]
                    else:
                        handles = [r.replica_handle for r in recs]
                        states = [r.state_rep for r in recs]
                        msgs = [r.inbox_rep.pop(0) for r in recs]
                    out_handles, out_states, _patches, errors = \
                        receive_sync_messages_docs(
                            handles, states, msgs, mirror=False,
                            on_error='quarantine')
                    for r, handle, st, err in zip(recs, out_handles,
                                                  out_states, errors):
                        if side == 'home':
                            r.session.handle = handle
                            r.state_home = st
                        else:
                            r.replica_handle = handle
                            r.state_rep = st
                        if err is not None:
                            # corrupt wire bytes: contained to this doc,
                            # equivalent to a drop — the handshake
                            # re-sends through its own machinery
                            _stats.inc('shard_repl_quarantined')
                        sent[id(r)] = True
        # stall detection: TRAFFIC without head movement is the
        # loss-poisoned handshake (split heads = poisoned sentHashes;
        # equal heads = one side soliciting a peer whose "you're in
        # sync" reply was dropped — it stays silent forever while the
        # solicitor never learns). Both livelocks keep messages flowing
        # with frozen heads, and a genuinely converged-quiet pair
        # exchanges NO messages, so resetting on stalled traffic can
        # never disturb a quiet pair (the sync_until_quiet rule;
        # idempotent delivery makes the reset always safe).
        for rec in active:
            pair = (tuple(get_heads(rec.session.handle)),
                    tuple(get_heads(rec.replica_handle)))
            split = sorted(pair[0]) != sorted(pair[1])
            rec.quiet = not split and not sent.get(id(rec)) and \
                not rec.inbox_home and not rec.inbox_rep
            if pair == rec.last_pair_heads and sent.get(id(rec)):
                rec.stall += 1
            else:
                rec.stall = 0
            rec.last_pair_heads = pair
            if rec.stall >= self.repl_stall_rounds:
                rec._reset_pair()
                _stats.inc('shard_repl_resets')

    def scrub_frontiers(self):
        """Anti-entropy head-frontier scrub (ROADMAP shard leftover):
        per replica pair, compare the home and replica head frontiers.
        A pair that is merely LAGGING (replication in flight, inboxes
        non-empty, quiet=False) is left alone — the rounds converge it.
        A pair that believes itself converged-QUIET while the frontiers
        DISAGREE is silent divergence (state damaged out-of-band — e.g.
        a quarantined replication message whose re-send never landed, or
        replica memory rot): the replication skip rule would never wake
        it until the tenant's next write. Each such pair emits a typed
        ``shard_frontier_mismatch`` flight event, counts in
        ``shard_scrub_mismatches``, and has its handshake reset with
        quiet cleared — the next replication round re-converges it from
        a fresh sync state. Cost: two get_heads reads per pair (no
        message traffic, no doc decode). Returns mismatches found."""
        found = 0
        for rec in self._repl_active():
            if not rec.quiet or rec.last_pair_heads is None:
                continue             # converging normally: rounds own it
            home = sorted(get_heads(rec.session.handle))
            rep = sorted(get_heads(rec.replica_handle))
            if home == rep:
                continue
            if home != sorted(rec.last_pair_heads[0]):
                # the HOME frontier moved since the round that declared
                # quiet: a normal write raced the scrub — the next
                # replication round owns that; flagging it would turn
                # every write into a false divergence event
                continue
            found += 1
            _stats.inc('shard_scrub_mismatches')
            record = {'tick': self.ticks, 'tenant': rec.name,
                      'home': rec.home, 'replica': rec.replica_on,
                      'home_heads': len(home), 'replica_heads': len(rep)}
            self.scrub_mismatches.append(record)
            _flight.record_event('shard_frontier_mismatch', **record)
            rec.quiet = False
            rec._reset_pair()
        return found

    def replication_quiet(self):
        """True when every replicated pair converged and went quiet in
        the last round (the post-quiet audit precondition)."""
        return all(rec.quiet for rec in self._repl_active())

    # -- planned rebalance ---------------------------------------------

    def rebalance(self):
        """Start migrating every tenant whose live ring-primary differs
        from its current home (post-revive healing, scale-out). Returns
        how many migrations were started; they advance across the next
        few ``pump`` ticks (read-only window -> chunk transfer ->
        cutover)."""
        started = 0
        for rec in self._tenants.values():
            if rec.migrating is not None or rec.home is None:
                continue
            want = self.ring.primary(rec.name, alive=self.alive)
            if want is not None and want != rec.home:
                rec.migrating = {'phase': 'readonly', 'to': want}
                _stats.inc('shard_rebalances')
                started += 1
        return started

    def rehome_tenant(self, name, dst):
        """Start migrating ONE tenant to an explicit destination shard —
        the control plane's targeted actuator (hot-shard relief, ring
        healing), riding the exact migration machinery ``rebalance``
        uses (read-only window -> chunk transfer -> cutover across the
        next pumps). Returns True when a migration started; False when
        the move is impossible right now (unknown tenant, already
        migrating, unplaced, dead or identical destination)."""
        rec = self._tenants.get(name)
        if rec is None or rec.migrating is not None or rec.home is None:
            return False
        if dst == rec.home or dst not in self.alive or \
                dst not in self.shards:
            return False
        rec.migrating = {'phase': 'readonly', 'to': dst}
        _stats.inc('shard_rebalances')
        return True

    def migrating(self):
        return [rec.name for rec in self._tenants.values()
                if rec.migrating is not None]

    def _advance_migrations(self):
        for rec in self._tenants.values():
            mig = rec.migrating
            if mig is None:
                continue
            if mig['to'] not in self.alive or rec.home not in self.alive:
                rec.migrating = None
                rec.read_only = False
                continue
            if mig['phase'] == 'readonly':
                rec.read_only = True
                busy = any(r.tenant == rec.name and r.kind == 'apply'
                           and r.state == 'submitted' and not r.sub.done
                           for r in self._pending)
                if not busy:
                    # transfer NEXT tick: the read-only window is a real
                    # window, not a same-tick flicker
                    mig['phase'] = 'transfer'
                continue
            # transfer: park on the donor -> chunk -> ingest + revive on
            # the receiver -> cutover
            donor = self.shards[rec.home]
            receiver = self.shards[mig['to']]
            with _span('shard_migrate', tenant=rec.name,
                       src=rec.home, dst=mig['to']):
                ids = donor.storage.park([rec.session.handle])
                if ids[0] is None:
                    continue            # queued changes — retry next tick
                chunk = donor.storage.discard([ids[0]])[0]
                rid = receiver.storage.ingest_chunks([chunk])[0]
                handle = receiver.storage.revive([rid])[0]
                donor.service.release_session(rec.session)
                rec.session = receiver.service.adopt_session(rec.name,
                                                             handle)
            rec.session.sub_cursor = list(rec.cursor)
            rec.home = mig['to']
            rec.needs_reset = True
            rec.read_only = False
            rec.migrating = None
            rec._reset_pair()
            self._ensure_replica(rec)
            _stats.inc('shard_migrations')
            _flight.record_event('shard_migration', tenant=rec.name,
                                 dst=rec.home, tick=self.ticks)

    # -- settlement -----------------------------------------------------

    def _hashes_on(self, handle, hashes):
        return all(get_change_by_hash(handle, h) is not None
                   for h in hashes)

    def _resolve_ok(self, req, rec):
        result = req.sub.result if req.sub is not None and \
            req.sub.status == 'ok' else req.result_cache
        if req.kind == 'apply':
            result = req.result_cache
        elif req.kind == 'subscribe' and isinstance(result, dict):
            rec.cursor = list(result.get('heads', rec.cursor))
        req.ticket._finish(self.ticks, result=result, shard=rec.home)

    def _harvest(self, now):
        still = []
        for req in self._pending:
            if req.ticket.done:
                continue
            rec = self._tenants[req.tenant]
            if req.state == 'parked':
                if req.not_before <= now:
                    self._dispatch(req, now)
            elif req.state == 'submitted':
                if req.sub.done:
                    self._settle_sub(req, rec, now)
                elif rec.home != req.home_at_submit or \
                        req.home_at_submit not in self.alive:
                    # orphaned in a dead/abandoned shard's queues
                    self._settle_orphan(req, rec, now)
            elif req.state == 'await_replica':
                self._settle_replica_wait(req, rec, now)
            if not req.ticket.done:
                still.append(req)
        self._pending = still

    def _settle_sub(self, req, rec, now):
        sub = req.sub
        if sub.status == 'ok':
            if req.kind == 'apply' and rec.replica_handle is not None:
                req.state = 'await_replica'
                self._settle_replica_wait(req, rec, now)
                return
            if req.kind == 'apply':
                _stats.inc('shard_degraded_acks')
            self._resolve_ok(req, rec)
            return
        err = sub.error
        if rec.home != req.home_at_submit and \
                isinstance(err, SessionClosed):
            # the session moved (failover/migration) while this request
            # sat queued: not the client's fault — retry on the new home
            self._fail_or_retry(req, self._unavailable(
                f'tenant {req.tenant!r} re-homed mid-flight',
                shard=req.home_at_submit, tenant=req.tenant), now)
            return
        if req.payload_fn is not None and isinstance(err, WireCorruption):
            # transient transport fault: re-draw and retry, budgeted
            self._fail_or_retry(req, err, now)
            return
        req.ticket._finish(self.ticks, error=err, shard=rec.home)

    def _settle_orphan(self, req, rec, now):
        if req.kind == 'apply' and req.hashes and rec.home in self.alive \
                and rec.session is not None and \
                self._hashes_on(rec.session.handle, req.hashes):
            # the write survived onto the promoted replica before the
            # crash: the ack contract is already met (or about to be,
            # via the new replica) — settle through the replica wait
            req.state = 'await_replica'
            self._settle_replica_wait(req, rec, now)
            return
        self._fail_or_retry(req, self._unavailable(
            f'shard {req.home_at_submit!r} lost mid-flight',
            shard=req.home_at_submit, tenant=req.tenant), now)

    def _settle_replica_wait(self, req, rec, now):
        if rec.home is None or rec.home not in self.alive or \
                rec.session is None:
            self._fail_or_retry(req, self._unavailable(
                f'tenant {req.tenant!r} home shard unavailable',
                shard=rec.home, tenant=req.tenant), now)
            return
        if not self._hashes_on(rec.session.handle, req.hashes):
            # the only copy died before replicating: NOT acked — the
            # retry replays the same changes (idempotent by hash)
            self._fail_or_retry(req, self._unavailable(
                'committed copy lost before replication',
                shard=req.home_at_submit, tenant=req.tenant), now)
            return
        if rec.replica_handle is None:
            _stats.inc('shard_degraded_acks')
            self._resolve_ok(req, rec)
            return
        if self.shards[rec.replica_on].alive and \
                self._hashes_on(rec.replica_handle, req.hashes):
            self._resolve_ok(req, rec)
        # else: keep waiting — replication lands it (a physically dead
        # replica's memory doesn't count even if the hashes reached it
        # before the crash killed them; failover re-places the replica
        # and this wait settles through the new copy)

    # -- drain helpers --------------------------------------------------

    def idle(self):
        return not self._pending and all(
            self.shards[sid].service.idle() for sid in self.alive)

    def run_until_quiet(self, max_ticks=10_000, advance=None):
        """Pump until no router/shard work is pending AND replication is
        quiet. `advance` steps an injected fake clock per tick."""
        now = self.clock()
        for _ in range(max_ticks):
            if self.idle() and self.replication_quiet() and \
                    not self.migrating():
                return True
            self.pump(now=now)
            if advance is not None:
                now += advance
        return self.idle() and self.replication_quiet()
