"""Causal-history selection for the query engine.

Both query families (time-travel reads and incremental patch
subscriptions) reduce to the same primitive: given a document's change
log and a heads frontier, partition the log into the frontier's ANCESTOR
CLOSURE (everything causally at-or-before the frontier) and its
complement (everything past it). This module answers that question over
every document form the system has, without ever inflating op columns
for the selection step:

- **Live fleet docs** use the HashGraph the engine already maintains
  (``dependencies_by_hash`` / ``change_index_by_hash``). For bulk-loaded
  or parked-then-revived docs those dicts materialize through the native
  extractor's change-meta lanes (``_doc_resolve``: per-change hash +
  header-only decode — op columns untouched).
- **Parked MainStore docs** never leave the store: the chunk splits into
  canonical per-change buffers + hashes via ``native.extract_changes``
  (Python ``decode_document`` fallback), and deps come from header-only
  ``decode_change_meta`` reads of those buffers, resolved lazily — a
  selection touching K ancestors decodes K headers, not the whole log.

Selections come back as change BUFFERS in log order. Log order is
causally valid by construction (a change's deps always precede it, both
in application order and in the document container's canonical order),
so a selection replays through the ordinary batched apply path with no
re-sorting. Frontier hashes outside the history raise typed
``UnknownHeads`` — the caller (query/timetravel.py, subscriptions.py)
decides between rejection and resync.
"""

from .. import native
from ..columnar import decode_change_meta, decode_document, encode_change
from ..errors import MalformedDocument, UnknownHeads, as_wire_error

__all__ = ['ChunkHistory', 'history_of', 'select_ancestors',
           'select_descendants', 'frontier_of']


class ChunkHistory:
    """Change-log view over a parked document chunk: canonical per-change
    buffers + hashes from the extractor, deps decoded header-only and
    lazily per change. Shaped like the slice of HashGraph the selection
    walk needs (``change_index_by_hash`` / ``changes`` / ``heads``)."""

    __slots__ = ('changes', 'hashes', 'change_index_by_hash', '_deps',
                 'heads')

    def __init__(self, chunk, heads=None):
        # memoryview chunks (parked docs in the mmap'd segment arena)
        # extract in place — the time-travel read path never copies the
        # compressed bytes off the page cache
        if not isinstance(chunk, (bytes, memoryview)):
            chunk = bytes(chunk)
        extracted = native.extract_changes([chunk]) \
            if native.available() else None
        if extracted is not None and extracted[0] is not None:
            buffers, hashes, _max_ops = extracted[0]
            self._deps = [None] * len(buffers)
        else:
            try:
                decoded = decode_document(chunk)
            except MalformedDocument:
                raise
            except Exception as exc:
                raise as_wire_error(exc, MalformedDocument, 'ChunkHistory')
            buffers = [encode_change(ch) for ch in decoded]
            hashes = [ch['hash'] for ch in decoded]
            self._deps = [list(ch['deps']) for ch in decoded]
        self.changes = buffers
        self.hashes = hashes
        self.change_index_by_hash = {h: i for i, h in enumerate(hashes)}
        if heads is not None:
            self.heads = sorted(heads)
        else:
            deps = set()
            for i in range(len(buffers)):
                deps.update(self.deps_of(i))
            self.heads = sorted(h for h in hashes if h not in deps)

    def deps_of(self, i):
        deps = self._deps[i]
        if deps is None:
            deps = self._deps[i] = \
                list(decode_change_meta(self.changes[i])['deps'])
        return deps


def history_of(source, heads=None):
    """Normalize a query source into a selection-capable history view.

    Accepts a backend handle dict (``{'state': ...}``), a bare engine
    state, raw document-chunk ``bytes``, or a ``(store, id)`` pair where
    ``store`` is a ``StorageEngine`` or ``MainStore`` — the parked form;
    the chunk is read compute-on-compressed, the doc is NOT revived."""
    if isinstance(source, (bytes, bytearray)):
        return ChunkHistory(source, heads=heads)
    if isinstance(source, tuple) and len(source) == 2:
        store, doc_id = source
        return ChunkHistory(store.chunk(doc_id), heads=store.heads(doc_id))
    state = source.get('state') if isinstance(source, dict) else source
    if state is None or not hasattr(state, 'change_index_by_hash'):
        raise ValueError(f'not a query source: {source!r}')
    return state


def _deps_fn(history):
    """hash -> deps-list lookup over either history form. Live engines'
    graph dicts materialize lazily (FleetDoc properties ensure it; bare
    HashGraph subclasses expose _ensure_graph)."""
    if isinstance(history, ChunkHistory):
        index = history.change_index_by_hash
        return lambda h: history.deps_of(index[h])
    ensure = getattr(history, '_ensure_graph', None)
    if ensure is not None:
        ensure()
    deps_by_hash = history.dependencies_by_hash
    return deps_by_hash.__getitem__


def _walk(deps, roots):
    """Hash closure of `roots` under the deps relation (inclusive)."""
    seen = set()
    stack = list(roots)
    while stack:
        h = stack.pop()
        if h in seen:
            continue
        seen.add(h)
        stack.extend(deps(h))
    return seen


def _check_known(history, heads, what):
    index = history.change_index_by_hash
    missing = sorted(h for h in heads if h not in index)
    if missing:
        raise UnknownHeads(
            f'{what}: {len(missing)} hash(es) outside the document '
            f'history: {", ".join(m[:16] for m in missing[:4])}'
            f'{"..." if len(missing) > 4 else ""}', missing=missing)


def select_ancestors(history, heads, what='select_ancestors'):
    """Change buffers of the ancestor closure of `heads`, in log order
    (causally valid for replay). `heads` == [] selects nothing (the
    empty document frontier)."""
    if not heads:
        return []
    _check_known(history, heads, what)
    seen = _walk(_deps_fn(history), heads)
    index = history.change_index_by_hash
    rows = sorted(index[h] for h in seen)
    changes = history.changes
    return [changes[i] for i in rows]


def select_descendants(history, have_heads, what='select_descendants'):
    """Change buffers PAST the `have_heads` frontier (the log minus the
    frontier's ancestor closure), in log order — the incremental patch a
    subscriber at that cursor is owed. `have_heads` == [] returns the
    whole log (the full-resync payload)."""
    changes = history.changes      # materialize first: the index needs it
    if not have_heads:
        return list(changes)
    _check_known(history, have_heads, what)
    seen = _walk(_deps_fn(history), have_heads)
    index = history.change_index_by_hash
    keep = sorted(i for h, i in index.items() if h not in seen)
    return [changes[i] for i in keep]


def frontier_of(history, heads, what='frontier_of'):
    """Normalize a requested frontier to its MAXIMAL elements: the subset
    of `heads` not in the strict ancestor closure of the others (a
    frontier listing both a change and its ancestor is legal input; the
    ancestor is redundant). This is what the replayed document's heads
    will equal."""
    heads = list(dict.fromkeys(heads))
    _check_known(history, heads, what)
    deps = _deps_fn(history)
    strict = _walk(deps, [d for h in heads for d in deps(h)])
    return sorted(h for h in heads if h not in strict)
