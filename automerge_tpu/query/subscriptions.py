"""Incremental patch subscriptions: push only the diff since a cursor.

A subscriber follows a document without running a full sync peer: it
holds a CURSOR (the heads frontier of the last state it folded) and, per
tick, receives the changes PAST that frontier — exactly the incremental
recomputation of a view over a growing op graph that "Formal Foundations
of Continuous Graph Processing" frames (PAPERS.md). Folding the pushed
buffers onto the subscriber's shadow copy reproduces the server document
at the pushed heads byte-identically (the chaos-universe audit pins it).

``SubscriptionHub`` is the fan-out engine:

- Documents register under caller-chosen keys; sources can be live fleet
  handles OR parked ``(store, id)`` rows — a doc parking or reviving
  mid-subscription just rebinds its source (``update_source``), cursors
  survive because history (and its hashes) survives.
- Per tick, subscribers group into (doc, cursor-frontier) EQUIVALENCE
  CLASSES: one diff is computed per class and shared by every member, so
  10k subscribers at k distinct cursors over one doc cost k selection
  walks — and ZERO device dispatches (the diff is pure hash-graph work;
  the dispatch-count tests pin it).
- Cursor hygiene is typed, never wrong: a cursor naming hashes outside
  the doc's history (bogus, or stale past a server that never had them)
  triggers a full RESYNC event (changes from the empty frontier) tagged
  with the typed ``UnknownHeads`` — plus a forensic flight-recorder dump
  — while replayed-but-valid cursors simply get the (idempotent) diff
  from their older frontier again.

``encode_cursor``/``decode_cursor`` are the wire form of a cursor (what
a client presents over the service boundary); hostile bytes fail with
typed ``InvalidCursor`` (``WireCorruption``) — tools/fuzz_wire.py holds
the decode boundary to the zero-untyped-escapes contract.
"""

import time

from ..encoding import Decoder, Encoder
from ..errors import InvalidCursor, UnknownHeads, as_wire_error
from ..observability import hist as _hist
from ..observability import recorder as _flight
from ..observability.spans import span as _span
from .history import history_of, select_descendants

__all__ = ['SubscriptionHub', 'Subscription', 'encode_cursor',
           'decode_cursor', 'diff_since']

CURSOR_MAGIC = 0x51          # 'Q': a query-engine cursor frame
_MAX_CURSOR_HEADS = 4096     # count-bomb ceiling (a real frontier is tiny)


def encode_cursor(heads):
    """Wire form of a cursor: magic byte + uint53 count + 32-byte hashes
    (sorted, deduped). The inverse of ``decode_cursor``."""
    heads = sorted(dict.fromkeys(str(h) for h in heads))
    out = Encoder()
    out.append_byte(CURSOR_MAGIC)
    out.append_uint53(len(heads))
    for h in heads:
        raw = bytes.fromhex(h)
        if len(raw) != 32:
            raise ValueError(f'cursor head is not a 32-byte hash: {h!r}')
        out.append_raw_bytes(raw)
    return out.buffer


def decode_cursor(data):
    """Decode cursor bytes to a sorted list of hex head hashes. Hostile
    bytes (bad magic, count bombs, truncation, trailing garbage) raise
    typed ``InvalidCursor`` — never a bare decoder exception."""
    try:
        decoder = Decoder(bytes(data))
        if decoder.read_byte() != CURSOR_MAGIC:
            raise ValueError('cursor does not begin with magic byte 0x51')
        count = decoder.read_uint53()
        if count > _MAX_CURSOR_HEADS:
            raise ValueError(f'cursor head count {count} exceeds '
                             f'{_MAX_CURSOR_HEADS}')
        heads = [decoder.read_raw_bytes(32).hex() for _ in range(count)]
        if not decoder.done:
            raise ValueError('cursor has trailing data')
        if heads != sorted(dict.fromkeys(heads)):
            raise ValueError('cursor heads are not sorted and unique')
        # canonical-form discipline, enforced as decode∘encode identity:
        # a frame that decodes but would not re-encode to the same bytes
        # (e.g. a non-minimal LEB count) must be rejected, or equivalent
        # cursors would split subscriber equivalence classes
        if bytes(encode_cursor(heads)) != bytes(data):
            raise ValueError('cursor frame is not in canonical form')
    except Exception as exc:
        raise as_wire_error(exc, InvalidCursor, 'decode_cursor')
    return heads


def diff_since(source, cursor, what='diff_since'):
    """(changes, heads): the change buffers past the `cursor` frontier
    and the source's current heads — the patch that takes a shadow copy
    from the cursor state to the current state. Typed ``UnknownHeads``
    when the cursor names history the source does not have.

    The quiet case (cursor already at the heads) is answered from the
    causal state alone: a parked doc's chunk is never extracted, a live
    doc's graph never materialized — at-frontier subscribers are the
    steady state, so their tick cost is a heads comparison."""
    cursor = sorted(str(h) for h in cursor)
    if isinstance(source, tuple):
        heads = sorted(source[0].heads(source[1]))
    elif not isinstance(source, (bytes, bytearray)):
        state = source.get('state') if isinstance(source, dict) else source
        heads = sorted(state.heads)
    else:
        heads = None
    if heads is not None and cursor == heads:
        return [], heads
    history = history_of(source)
    if heads is None:
        heads = sorted(history.heads)
        if cursor == heads:
            return [], heads
    start = time.perf_counter()
    changes = select_descendants(history, cursor, what=what)
    _hist.record_value('subscription_diff_s',
                       time.perf_counter() - start, scale=1e9, unit='s')
    return [bytes(c) for c in changes], heads


class Subscription:
    """One subscriber's hub-side state. ``cursor`` auto-advances to the
    pushed heads on every patch/resync event (delivery is assumed; a
    client that lost a push re-subscribes — or presents its own cursor
    via ``resubscribe`` — and gets the idempotent diff again).
    ``fresh_tick`` is the hub tick at which the cursor last matched the
    document heads (the freshness SLI's anchor: a push's cursor lag is
    the ticks elapsed since then)."""

    __slots__ = ('id', 'key', 'cursor', 'priority', 'closed',
                 'fresh_tick')

    def __init__(self, sid, key, cursor, priority):
        self.id = sid
        self.key = key
        self.cursor = list(cursor)
        self.priority = priority
        self.closed = False
        self.fresh_tick = None

    def __repr__(self):
        return (f'Subscription({self.id}, key={self.key!r}, '
                f'cursor={len(self.cursor)} heads)')


class SubscriptionHub:
    """See the module docstring. Single-threaded by contract, like the
    service core it plugs into."""

    def __init__(self):
        self._sources = {}           # key -> query source
        self._subs = {}              # sub id -> Subscription
        self._next_sid = 0
        self._slo = None             # (SloRegistry, tenant_of) when bound
        self.stats = {
            'ticks': 0, 'pushes': 0, 'resyncs': 0, 'quiet': 0,
            'diffs_computed': 0, 'diffs_reused': 0, 'lag_max': 0,
        }

    def bind_slo(self, registry, tenant_of=str):
        """Feed the freshness SLI: every served push reports its cursor
        lag (ticks since the subscriber was last at the heads) to
        ``registry.record_freshness`` under ``tenant_of(key)`` — the
        hub already walks each subscriber per tick, so the accounting
        rides the walk instead of adding a rescan. ``registry=None``
        unbinds."""
        self._slo = None if registry is None else (registry, tenant_of)

    # -- documents -----------------------------------------------------

    def register(self, key, source):
        """Bind `key` to a query source (live handle, parked (store, id)
        pair, or raw chunk bytes). Re-registering rebinds."""
        self._sources[key] = source

    update_source = register

    def unregister(self, key):
        """Drop the doc; its subscribers resolve closed on next tick."""
        self._sources.pop(key, None)

    def keys(self):
        return list(self._sources)

    # -- subscribers ---------------------------------------------------

    def subscribe(self, key, cursor=None, priority=0):
        """Attach a subscriber to `key` at `cursor` (None/[] = from the
        empty document: the first tick pushes the full state)."""
        if key not in self._sources:
            raise KeyError(f'no document registered under {key!r}')
        sid = self._next_sid
        self._next_sid += 1
        sub = Subscription(sid, key, cursor or [], priority)
        self._subs[sid] = sub
        return sub

    def resubscribe(self, sub, cursor):
        """Reset a subscriber's cursor (the client-driven recovery path:
        present the frontier of the state you actually hold)."""
        sub.cursor = list(cursor)

    def unsubscribe(self, sub):
        sub.closed = True
        self._subs.pop(sub.id, None)

    def __len__(self):
        return len(self._subs)

    # -- the tick ------------------------------------------------------

    def tick(self):
        """One fan-out round. Returns {sub_id: event} for every
        subscriber owed something this tick; quiet subscribers (cursor
        already at the doc's heads) are omitted. Events:

        - ``{'kind': 'patch', 'changes': [...], 'heads': [...]}`` —
          fold the buffers onto the shadow copy; it now equals the
          server doc at ``heads``.
        - ``{'kind': 'resync', 'changes': [...], 'heads': [...],
          'error': 'UnknownHeads'}`` — the cursor was invalid; the
          changes rebuild the doc from scratch (fold onto an EMPTY
          shadow).
        - ``{'kind': 'closed'}`` — the doc was unregistered.

        One diff per (doc, cursor-frontier) equivalence class; class
        members past the first are served from the memo (the
        ``diffs_reused`` counter / reuse ratio in bench)."""
        from . import _stats

        self.stats['ticks'] += 1
        events = {}
        memo = {}                  # (key, cursor tuple) -> event | None
        invalid = []
        with _span('subscription_tick', subscribers=len(self._subs)):
            for sub in list(self._subs.values()):
                source = self._sources.get(sub.key)
                if source is None:
                    events[sub.id] = {'kind': 'closed'}
                    self._subs.pop(sub.id, None)
                    continue
                ckey = (sub.key, tuple(sorted(sub.cursor)))
                if ckey in memo:
                    # membership, not get(): a QUIET class memoizes None,
                    # and its members must share that answer instead of
                    # recomputing (one diff — or one heads compare — per
                    # class, even at 10k at-frontier subscribers)
                    event = memo[ckey]
                    if event is not None:
                        self.stats['diffs_reused'] += 1
                        _stats.inc('subscription_diff_reuse')
                else:
                    event = self._class_diff(source, sub, invalid)
                    memo[ckey] = event
                    if event is not None:
                        self.stats['diffs_computed'] += 1
                tick_no = self.stats['ticks']
                if event is None:
                    self.stats['quiet'] += 1
                    sub.fresh_tick = tick_no   # at the heads right now
                    continue
                events[sub.id] = event
                sub.cursor = list(event['heads'])
                self.stats['pushes'] += 1
                _stats.inc('subscription_pushes')
                # freshness: this push catches the cursor up — its lag
                # is the ticks since the subscriber was last at-frontier
                lag = 0 if sub.fresh_tick is None \
                    else tick_no - sub.fresh_tick
                sub.fresh_tick = tick_no
                if lag > self.stats['lag_max']:
                    self.stats['lag_max'] = lag
                if self._slo is not None:
                    registry, tenant_of = self._slo
                    registry.record_freshness(tenant_of(sub.key), lag)
        if invalid:
            _flight.dump_flight_record('query', detail={
                'invalid_cursors': invalid})
        return events

    def _class_diff(self, source, sub, invalid):
        """The diff event for one (doc, cursor) class; None = quiet."""
        from . import _stats
        try:
            changes, heads = diff_since(source, sub.cursor,
                                        what='subscription_tick')
        except UnknownHeads as exc:
            # bogus/stale cursor: typed, resync from scratch — never a
            # wrong patch
            self.stats['resyncs'] += 1
            _stats.inc('subscription_resyncs')
            _stats.inc('unknown_heads')
            invalid.append({'subscriber': sub.id, 'key': repr(sub.key),
                            'error': type(exc).__name__,
                            'message': str(exc)[:200]})
            changes, heads = diff_since(source, [],
                                        what='subscription_resync')
            return {'kind': 'resync', 'changes': changes, 'heads': heads,
                    'error': type(exc).__name__}
        if not changes and sorted(sub.cursor) == heads:
            return None
        return {'kind': 'patch', 'changes': changes, 'heads': heads}
