"""Incremental patch subscriptions: push only the diff since a cursor.

A subscriber follows a document without running a full sync peer: it
holds a CURSOR (the heads frontier of the last state it folded) and, per
tick, receives the changes PAST that frontier — exactly the incremental
recomputation of a view over a growing op graph that "Formal Foundations
of Continuous Graph Processing" frames (PAPERS.md). Folding the pushed
buffers onto the subscriber's shadow copy reproduces the server document
at the pushed heads byte-identically (the chaos-universe audit pins it).

``SubscriptionHub`` is the fan-out engine:

- Documents register under caller-chosen keys; sources can be live fleet
  handles OR parked ``(store, id)`` rows — a doc parking or reviving
  mid-subscription just rebinds its source (``update_source``), cursors
  survive because history (and its hashes) survives.
- Per tick, subscribers group into (doc, cursor-frontier) EQUIVALENCE
  CLASSES: one diff is computed per class and shared by every member, so
  10k subscribers at k distinct cursors over one doc cost k selection
  walks — and ZERO device dispatches (the diff is pure hash-graph work;
  the dispatch-count tests pin it).
- Cursor hygiene is typed, never wrong: a cursor naming hashes outside
  the doc's history (bogus, or stale past a server that never had them)
  triggers a full RESYNC event (changes from the empty frontier) tagged
  with the typed ``UnknownHeads`` — plus a forensic flight-recorder dump
  — while replayed-but-valid cursors simply get the (idempotent) diff
  from their older frontier again.

``encode_cursor``/``decode_cursor`` are the wire form of a cursor (what
a client presents over the service boundary); hostile bytes fail with
typed ``InvalidCursor`` (``WireCorruption``) — tools/fuzz_wire.py holds
the decode boundary to the zero-untyped-escapes contract.
"""

import time

import numpy as np

from ..encoding import Decoder, Encoder
from ..errors import InvalidCursor, UnknownHeads, as_wire_error
from ..observability import hist as _hist
from ..observability import recorder as _flight
from ..observability.metrics import Counters
from ..observability.spans import span as _span
from .history import history_of, select_descendants

__all__ = ['SubscriptionHub', 'Subscription', 'encode_cursor',
           'decode_cursor', 'diff_since']

CURSOR_MAGIC = 0x51          # 'Q': a query-engine cursor frame
_MAX_CURSOR_HEADS = 4096     # count-bomb ceiling (a real frontier is tiny)


def encode_cursor(heads):
    """Wire form of a cursor: magic byte + uint53 count + 32-byte hashes
    (sorted, deduped). The inverse of ``decode_cursor``."""
    heads = sorted(dict.fromkeys(str(h) for h in heads))
    out = Encoder()
    out.append_byte(CURSOR_MAGIC)
    out.append_uint53(len(heads))
    for h in heads:
        raw = bytes.fromhex(h)
        if len(raw) != 32:
            raise ValueError(f'cursor head is not a 32-byte hash: {h!r}')
        out.append_raw_bytes(raw)
    return out.buffer


def decode_cursor(data):
    """Decode cursor bytes to a sorted list of hex head hashes. Hostile
    bytes (bad magic, count bombs, truncation, trailing garbage) raise
    typed ``InvalidCursor`` — never a bare decoder exception."""
    try:
        decoder = Decoder(bytes(data))
        if decoder.read_byte() != CURSOR_MAGIC:
            raise ValueError('cursor does not begin with magic byte 0x51')
        count = decoder.read_uint53()
        if count > _MAX_CURSOR_HEADS:
            raise ValueError(f'cursor head count {count} exceeds '
                             f'{_MAX_CURSOR_HEADS}')
        heads = [decoder.read_raw_bytes(32).hex() for _ in range(count)]
        if not decoder.done:
            raise ValueError('cursor has trailing data')
        if heads != sorted(dict.fromkeys(heads)):
            raise ValueError('cursor heads are not sorted and unique')
        # canonical-form discipline, enforced as decode∘encode identity:
        # a frame that decodes but would not re-encode to the same bytes
        # (e.g. a non-minimal LEB count) must be rejected, or equivalent
        # cursors would split subscriber equivalence classes
        if bytes(encode_cursor(heads)) != bytes(data):
            raise ValueError('cursor frame is not in canonical form')
    except Exception as exc:
        raise as_wire_error(exc, InvalidCursor, 'decode_cursor')
    return heads


def diff_since(source, cursor, what='diff_since'):
    """(changes, heads): the change buffers past the `cursor` frontier
    and the source's current heads — the patch that takes a shadow copy
    from the cursor state to the current state. Typed ``UnknownHeads``
    when the cursor names history the source does not have.

    The quiet case (cursor already at the heads) is answered from the
    causal state alone: a parked doc's chunk is never extracted, a live
    doc's graph never materialized — at-frontier subscribers are the
    steady state, so their tick cost is a heads comparison."""
    cursor = sorted(str(h) for h in cursor)
    if isinstance(source, tuple):
        heads = sorted(source[0].heads(source[1]))
    elif not isinstance(source, (bytes, bytearray)):
        state = source.get('state') if isinstance(source, dict) else source
        heads = sorted(state.heads)
    else:
        heads = None
    if heads is not None and cursor == heads:
        return [], heads
    history = history_of(source)
    if heads is None:
        heads = sorted(history.heads)
        if cursor == heads:
            return [], heads
    start = time.perf_counter()
    changes = select_descendants(history, cursor, what=what)
    _hist.record_value('subscription_diff_s',
                       time.perf_counter() - start, scale=1e9, unit='s')
    return [bytes(c) for c in changes], heads


class Subscription:
    """One subscriber's hub-side state. ``cursor`` auto-advances to the
    pushed heads on every patch/resync event (delivery is assumed; a
    client that lost a push re-subscribes — or presents its own cursor
    via ``resubscribe`` — and gets the idempotent diff again).
    ``fresh_tick`` is the hub tick at which the cursor last matched the
    document heads (the freshness SLI's anchor: a push's cursor lag is
    the ticks elapsed since then)."""

    __slots__ = ('id', 'key', 'cursor', 'priority', 'closed',
                 'fresh_tick', 'born_tick')

    def __init__(self, sid, key, cursor, priority, born_tick=0):
        self.id = sid
        self.key = key
        self.cursor = list(cursor)
        self.priority = priority
        self.closed = False
        self.fresh_tick = None
        # hub tick count at subscribe time: an all-quiet fast tick's
        # hub-wide freshness floor applies to this subscriber only for
        # ticks it actually existed in (floor > born_tick)
        self.born_tick = born_tick

    def __repr__(self):
        return (f'Subscription({self.id}, key={self.key!r}, '
                f'cursor={len(self.cursor)} heads)')


class SubscriptionHub:
    """See the module docstring. Single-threaded by contract, like the
    service core it plugs into."""

    def __init__(self, batch_quiet=True):
        self._sources = {}           # key -> query source
        self._subs = {}              # sub id -> Subscription
        self._next_sid = 0
        self._slo = None             # (SloRegistry, tenant_of) when bound
        # stats ride the atomic Counters family like every other module
        # stat: the threaded shard pump can tick hubs concurrently with
        # readers, and a bare-dict `+=` is a splittable read-modify-write
        # (the round-15 undercount bug class)
        self.stats = Counters({
            'ticks': 0, 'pushes': 0, 'resyncs': 0, 'quiet': 0,
            'diffs_computed': 0, 'diffs_reused': 0, 'lag_max': 0,
        })
        # (key, cursor tuple) -> member count, maintained incrementally
        # at every cursor-mutation point so the tick can enumerate
        # equivalence CLASSES (k of them) without walking subscribers
        # (10k of them) — the all-quiet fast path's input
        self._classes = {}
        self._cursor_rows = {}       # ckey -> (head32 row | None, n)
        self._class_epoch = 0        # bumped when the class SET changes
        self._source_epoch = 0       # bumped when a source (re)binds
        self._scan_cache = None      # assembled compare arrays (by epoch)
        self.batch_quiet = batch_quiet
        # the hub-wide freshness floor: the latest tick every subscriber
        # was proven at-frontier by the batched compare (per-sub
        # fresh_tick updates are exactly what the fast path skips)
        self._quiet_floor = None

    def bind_slo(self, registry, tenant_of=str):
        """Feed the freshness SLI: every served push reports its cursor
        lag (ticks since the subscriber was last at the heads) to
        ``registry.record_freshness`` under ``tenant_of(key)`` — the
        hub already walks each subscriber per tick, so the accounting
        rides the walk instead of adding a rescan. ``registry=None``
        unbinds."""
        self._slo = None if registry is None else (registry, tenant_of)

    # -- documents -----------------------------------------------------

    def register(self, key, source):
        """Bind `key` to a query source (live handle, parked (store, id)
        pair, or raw chunk bytes). Re-registering rebinds."""
        self._sources[key] = source
        self._source_epoch += 1

    update_source = register

    def unregister(self, key):
        """Drop the doc; its subscribers resolve closed on next tick."""
        self._sources.pop(key, None)
        self._source_epoch += 1

    def keys(self):
        return list(self._sources)

    # -- subscribers ---------------------------------------------------

    def subscribe(self, key, cursor=None, priority=0):
        """Attach a subscriber to `key` at `cursor` (None/[] = from the
        empty document: the first tick pushes the full state)."""
        if key not in self._sources:
            raise KeyError(f'no document registered under {key!r}')
        sid = self._next_sid
        self._next_sid += 1
        sub = Subscription(sid, key, cursor or [], priority,
                           born_tick=self.stats['ticks'])
        self._subs[sid] = sub
        self._class_add(sub)
        return sub

    def resubscribe(self, sub, cursor):
        """Reset a subscriber's cursor (the client-driven recovery path:
        present the frontier of the state you actually hold)."""
        if self._subs.get(sub.id) is sub:
            self._class_move(sub, list(cursor))
        else:
            # detached subscriber: its classes were already released —
            # touch only the cursor, never the live class map
            sub.cursor = list(cursor)

    def unsubscribe(self, sub):
        sub.closed = True
        if self._subs.pop(sub.id, None) is not None:
            self._class_drop(sub)

    def __len__(self):
        return len(self._subs)

    # -- cursor equivalence classes ------------------------------------

    @staticmethod
    def _ckey(sub):
        return (sub.key, tuple(sorted(sub.cursor)))

    def _class_add(self, sub):
        ckey = self._ckey(sub)
        count = self._classes.get(ckey, 0)
        self._classes[ckey] = count + 1
        if count == 0:
            self._class_epoch += 1

    def _class_drop(self, sub):
        ckey = self._ckey(sub)
        n = self._classes.get(ckey, 0) - 1
        if n > 0:
            self._classes[ckey] = n
        else:
            self._classes.pop(ckey, None)
            self._cursor_rows.pop(ckey, None)
            self._class_epoch += 1

    def _class_move(self, sub, new_cursor):
        self._class_drop(sub)
        sub.cursor = new_cursor
        self._class_add(sub)

    def _cursor_row(self, ckey):
        """(head32 row | None, head count) for a class cursor; row None
        marks a host-residue cursor (multi-head, or not a hex hash)."""
        ent = self._cursor_rows.get(ckey)
        if ent is None:
            heads = ckey[1]
            if len(heads) == 0:
                ent = (np.zeros(32, dtype=np.uint8), 0)
            elif len(heads) == 1 and len(heads[0]) == 64:
                try:
                    row = np.frombuffer(bytes.fromhex(heads[0]),
                                        dtype=np.uint8)
                except ValueError:
                    row = None
                ent = (row, 1)
            else:
                ent = (None, len(heads))
            self._cursor_rows[ckey] = ent
        return ent

    # -- the tick ------------------------------------------------------

    def tick(self):
        """One fan-out round. Returns {sub_id: event} for every
        subscriber owed something this tick; quiet subscribers (cursor
        already at the doc's heads) are omitted. Events:

        - ``{'kind': 'patch', 'changes': [...], 'heads': [...]}`` —
          fold the buffers onto the shadow copy; it now equals the
          server doc at ``heads``.
        - ``{'kind': 'resync', 'changes': [...], 'heads': [...],
          'error': 'UnknownHeads'}`` — the cursor was invalid; the
          changes rebuild the doc from scratch (fold onto an EMPTY
          shadow).
        - ``{'kind': 'closed'}`` — the doc was unregistered.

        One diff per (doc, cursor-frontier) equivalence class; class
        members past the first are served from the memo (the
        ``diffs_reused`` counter / reuse ratio in bench). An ALL-QUIET
        tick (every class cursor at its doc's frontier) is proven by ONE
        batched frontier-compare dispatch over the classes — cursor
        head32 rows against the fleet's columnar ``_DocCols`` heads —
        and returns without walking subscribers at all; any non-quiet
        residue falls back to this per-class diff path byte-identically
        (proven-quiet classes just pre-seed the memo)."""
        from . import _stats

        tick_no = self.stats.inc('ticks')
        quiet_classes = None
        with _span('subscription_tick', subscribers=len(self._subs)):
            if self.batch_quiet and self._subs:
                quiet_classes, all_quiet = self._try_batch_quiet()
                if all_quiet:
                    # every subscriber is at its frontier: one counter
                    # bump and a hub-wide freshness floor instead of 10k
                    # attribute writes (push-time lag accounting folds
                    # the floor back in)
                    self.stats.inc('quiet', len(self._subs))
                    self._quiet_floor = tick_no
                    return {}
            events = {}
            memo = {}              # (key, cursor tuple) -> event | None
            if quiet_classes:
                # classes the batched compare already proved quiet: the
                # diff path would return None for them by definition
                # (cursor == heads), so seed the memo and skip the
                # recompute — the residue keeps the existing path
                for ckey in quiet_classes:
                    memo[ckey] = None
            invalid = []
            for sub in list(self._subs.values()):
                source = self._sources.get(sub.key)
                if source is None:
                    events[sub.id] = {'kind': 'closed'}
                    if self._subs.pop(sub.id, None) is not None:
                        self._class_drop(sub)
                    continue
                ckey = (sub.key, tuple(sorted(sub.cursor)))
                if ckey in memo:
                    # membership, not get(): a QUIET class memoizes None,
                    # and its members must share that answer instead of
                    # recomputing (one diff — or one heads compare — per
                    # class, even at 10k at-frontier subscribers)
                    event = memo[ckey]
                    if event is not None:
                        self.stats.inc('diffs_reused')
                        _stats.inc('subscription_diff_reuse')
                else:
                    event = self._class_diff(source, sub, invalid)
                    memo[ckey] = event
                    if event is not None:
                        self.stats.inc('diffs_computed')
                if event is None:
                    self.stats.inc('quiet')
                    sub.fresh_tick = tick_no   # at the heads right now
                    continue
                events[sub.id] = event
                self._class_move(sub, list(event['heads']))
                self.stats.inc('pushes')
                _stats.inc('subscription_pushes')
                # freshness: this push catches the cursor up — its lag
                # is the ticks since the subscriber was last at-frontier
                # (per-sub fresh_tick, or the hub-wide all-quiet floor
                # for ticks the subscriber existed in)
                base = sub.fresh_tick
                floor = self._quiet_floor
                if floor is not None and floor > sub.born_tick and \
                        (base is None or floor > base):
                    base = floor
                lag = 0 if base is None else tick_no - base
                sub.fresh_tick = tick_no
                if lag > self.stats['lag_max']:
                    self.stats['lag_max'] = lag
                if self._slo is not None:
                    registry, tenant_of = self._slo
                    registry.record_freshness(tenant_of(sub.key), lag)
        if invalid:
            _flight.dump_flight_record('query', detail={
                'invalid_cursors': invalid})
        return events

    # -- the batched quiet proof ---------------------------------------

    @staticmethod
    def _doc_frontier(source):
        """The doc's frontier in its cheapest form: ('cols', doc_cols,
        slot, head_n) for a single-or-empty-head fleet doc (compares on
        device), ('host', sorted hex list) when a host compare is
        cheap, None when there is no cheap frontier (raw chunk bytes,
        freed engines) — the tick then takes the slow path."""
        if isinstance(source, tuple):
            return ('host', sorted(source[0].heads(source[1])))
        if isinstance(source, (bytes, bytearray)):
            return None
        state = source.get('state') if isinstance(source, dict) else source
        impl = getattr(state, '_impl', state)
        slot = getattr(impl, 'slot', None)
        fleet = getattr(impl, 'fleet', None)
        if fleet is not None and isinstance(slot, int):
            cols = fleet.doc_cols
            n = int(cols.head_n[slot])
            if n >= 0:
                return ('cols', cols, slot, n, fleet)
            return ('host', sorted(impl.heads))   # multi-head: rare
        heads = getattr(state, 'heads', None)
        if heads is None:
            return None
        return ('host', sorted(heads))

    def _scan_plan(self):
        """The compare plan for the CURRENT class set, cached until the
        set changes (cursor moves / churn bump ``_class_epoch``; the
        all-quiet steady state never rebuilds): the device-comparable
        classes' cursor rows as assembled arrays, their keys deduplicated
        with a class->key index vector, and the host-residue classes
        (multi-head / non-hex cursors) listed separately."""
        epochs = (self._class_epoch, self._source_epoch)
        cache = self._scan_cache
        if cache is not None and cache['epochs'] == epochs:
            return cache
        dev_ckeys, dev_rows, dev_n, key_idx = [], [], [], []
        host_ckeys = []
        keys, key_of = [], {}
        for ckey in self._classes:
            k = key_of.get(ckey[0])
            if k is None:
                k = key_of[ckey[0]] = len(keys)
                keys.append(ckey[0])
            cur_row, cur_n = self._cursor_row(ckey)
            if cur_row is None:
                host_ckeys.append((ckey, k))
            else:
                dev_ckeys.append(ckey)
                dev_rows.append(cur_row)
                dev_n.append(cur_n)
                key_idx.append(k)
        # resolve every key's SOURCE once per (class, source) epoch pair:
        # fleet docs collapse to (shared _DocCols, slot) for one gather
        # per tick; anything else stays 'dynamic' (re-resolved per tick);
        # a missing source or one with no cheap frontier disables the
        # whole scan (closed events / the slow path are owed)
        n_keys = len(keys)
        col_slots = np.full(n_keys, -1, dtype=np.int64)
        dynamic = []                 # key indexes resolved per tick
        shared_cols = None
        shared_fleet = None
        usable = True
        for k, key in enumerate(keys):
            source = self._sources.get(key)
            if source is None:
                usable = False
                break
            frontier = self._doc_frontier(source)
            if frontier is None:
                usable = False
                break
            if frontier[0] == 'cols' and \
                    (shared_cols is None or shared_cols is frontier[1]):
                shared_cols = frontier[1]
                shared_fleet = frontier[4]
                col_slots[k] = frontier[2]
            else:
                dynamic.append(k)
        cache = {
            'epochs': epochs,
            'keys': keys,
            'dev_ckeys': dev_ckeys,
            'cur32': np.stack(dev_rows) if dev_rows else
                np.zeros((0, 32), dtype=np.uint8),
            'cur_n': np.asarray(dev_n, dtype=np.int32),
            'key_idx': np.asarray(key_idx, dtype=np.int64),
            'host_ckeys': host_ckeys,
            'usable': usable,
            'shared_cols': shared_cols,
            'shared_fleet': shared_fleet,
            'free_epoch': shared_fleet.free_epoch
                if shared_fleet is not None else 0,
            'col_slots': col_slots,
            'dynamic': dynamic,
        }
        self._scan_cache = cache
        return cache

    def _try_batch_quiet(self):
        """Prove per-class quietness in ONE frontier-compare dispatch:
        per-KEY doc frontiers gathered from the ``_DocCols`` columns,
        fanned out to classes through the cached plan's index vector.
        Returns (proven_quiet_ckeys, all_quiet); (None, False) when the
        scan cannot run — a class's doc is unregistered (closed events
        are owed) or has no cheap frontier."""
        from ..fleet.hashindex import frontier_compare

        if not self._classes:
            # belt-and-braces: an empty class map with live subscribers
            # would otherwise prove a vacuous all-quiet
            return None, False
        plan = self._scan_plan()
        if plan['shared_fleet'] is not None and \
                plan['shared_fleet'].free_epoch != plan['free_epoch']:
            # slots were freed since the plan was built: a recycled slot
            # must never serve a stale frontier row — re-resolve
            self._scan_cache = None
            plan = self._scan_plan()
        if not plan['usable']:
            return None, False
        keys = plan['keys']
        n_keys = len(keys)
        key_rows = np.zeros((n_keys, 32), dtype=np.uint8)
        key_n = np.zeros(n_keys, dtype=np.int32)
        key_lists = [None] * n_keys    # hex lists, for host compares
        shared_cols = plan['shared_cols']
        col_slots = plan['col_slots']
        gather = col_slots >= 0
        if gather.any():
            # the steady-state path: every fleet doc's frontier in two
            # vectorized gathers off the shared _DocCols columns
            slots = col_slots[gather]
            key_rows[gather] = shared_cols.head32[slots]
            key_n[gather] = shared_cols.head_n[slots]
        for k in plan['dynamic']:
            source = self._sources.get(keys[k])
            if source is None:
                return None, False
            frontier = self._doc_frontier(source)
            if frontier is None:
                return None, False
            if frontier[0] == 'cols':
                cols, slot, doc_n = frontier[1], frontier[2], frontier[3]
                key_rows[k] = cols.head32[slot]
                key_n[k] = doc_n
            else:
                heads = frontier[1]
                key_lists[k] = heads
                key_n[k] = len(heads)
                if len(heads) == 1 and len(heads[0]) == 64:
                    try:
                        key_rows[k] = np.frombuffer(
                            bytes.fromhex(heads[0]), dtype=np.uint8)
                    except ValueError:
                        key_n[k] = -9      # non-hex head: never quiet
        quiet = set()
        if len(plan['dev_ckeys']):
            idx = plan['key_idx']
            flags = frontier_compare(plan['cur32'], plan['cur_n'],
                                     key_rows[idx], key_n[idx])
            for ckey, flag in zip(plan['dev_ckeys'], flags):
                if flag:
                    quiet.add(ckey)
        for ckey, k in plan['host_ckeys']:
            # residue cursors (multi-head / non-hex): exact list compare
            # against the doc frontier; columnar docs hold 0/1 heads so
            # only a 'host'-form doc can ever match them
            heads = key_lists[k]
            if heads is None:
                doc_n = int(key_n[k])
                heads = [] if doc_n == 0 else \
                    [key_rows[k].tobytes().hex()] if doc_n == 1 else None
            if heads is not None and list(ckey[1]) == heads:
                quiet.add(ckey)
        return quiet, len(quiet) == len(self._classes)

    def _class_diff(self, source, sub, invalid):
        """The diff event for one (doc, cursor) class; None = quiet."""
        from . import _stats
        try:
            changes, heads = diff_since(source, sub.cursor,
                                        what='subscription_tick')
        except UnknownHeads as exc:
            # bogus/stale cursor: typed, resync from scratch — never a
            # wrong patch
            self.stats['resyncs'] += 1
            _stats.inc('subscription_resyncs')
            _stats.inc('unknown_heads')
            invalid.append({'subscriber': sub.id, 'key': repr(sub.key),
                            'error': type(exc).__name__,
                            'message': str(exc)[:200]})
            changes, heads = diff_since(source, [],
                                        what='subscription_resync')
            return {'kind': 'resync', 'changes': changes, 'heads': heads,
                    'error': type(exc).__name__}
        if not changes and sorted(sub.cursor) == heads:
            return None
        return {'kind': 'patch', 'changes': changes, 'heads': heads}
