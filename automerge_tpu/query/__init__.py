"""The query engine: time-travel reads and incremental patch subscriptions.

The change journal + hash graph make every historical version of every
document addressable, and the delta+main storage engine answers causal
questions straight off compressed chunks — this package SERVES that
history (the ROADMAP's scenario-diversity step):

- **Time-travel reads** (timetravel.py): ``materialize_at(source,
  heads)`` reconstructs a document at any historical heads frontier —
  ancestor-closure selection over the hash graph / extractor change-meta
  lanes (no op columns inflated to decide WHAT to replay), then one
  batched replay through the ordinary fused apply seam.
  ``materialize_at_docs`` runs N audit reads as ONE fused dispatch.
  Works against live fleet docs AND parked ``MainStore`` rows without
  reviving them.
- **Patch subscriptions** (subscriptions.py): ``SubscriptionHub`` tracks
  per-subscriber cursor heads and pushes, per tick, only the changes
  past each cursor — one diff per (doc, cursor-frontier) equivalence
  class, zero device dispatches per tick. Cursors cross the wire via
  ``encode_cursor``/``decode_cursor`` (hostile bytes fail typed
  ``InvalidCursor``); cursors naming unknown history resync typed
  (``UnknownHeads``) — never a wrong patch.
- **History selection** (history.py): the shared ancestor-closure /
  frontier machinery over live hash graphs and parked chunks.

Both families ride ``service.DocService`` as the 'materialize_at' and
'subscribe' request kinds (admission, deadlines, brownout; subscription
pushes are the first work shed under pressure). Observability:
``materialize_at_s`` / ``subscription_diff_s`` histograms, spans
(``materialize_at``, ``subscription_tick``), the health counters below,
and forensic flight-recorder dumps on invalid cursors / unknown heads.
BASELINE.md "Query contract" states the full semantics.
"""

from ..observability.metrics import Counters, register_health_source

_stats = Counters({
    'timetravel_reads': 0,         # materialized historical reads
    'subscription_pushes': 0,      # patch/resync events pushed
    'subscription_resyncs': 0,     # invalid-cursor full resyncs
    'subscription_diff_reuse': 0,  # diffs served from an equivalence class
    'unknown_heads': 0,            # typed UnknownHeads rejections
    'invalid_cursors': 0,          # typed InvalidCursor rejections
})
for _key in _stats:
    register_health_source(_key, lambda k=_key: _stats[k])


def query_stats():
    return dict(_stats)


from .history import (ChunkHistory, frontier_of, history_of,  # noqa: E402
                      select_ancestors, select_descendants)
from .subscriptions import (Subscription, SubscriptionHub,  # noqa: E402
                            decode_cursor, diff_since, encode_cursor)
from .timetravel import materialize_at, materialize_at_docs  # noqa: E402

__all__ = [
    'materialize_at', 'materialize_at_docs',
    'SubscriptionHub', 'Subscription',
    'encode_cursor', 'decode_cursor', 'diff_since',
    'ChunkHistory', 'history_of', 'select_ancestors',
    'select_descendants', 'frontier_of',
    'query_stats',
]
