"""Time-travel reads: materialize a document at any historical frontier.

The change journal and hash graph make every historical version of every
document addressable; this module serves them. ``materialize_at(source,
heads)`` reconstructs the document state at the heads frontier by
selecting the frontier's ancestor closure from the causal history
(query/history.py — hash-graph / extractor change-meta lanes only, no op
columns inflated for the selection) and replaying the selected buffers
through the existing batched apply path onto a FRESH fleet slot. The
batched ``materialize_at_docs`` variant runs N audit reads as one fused
dispatch: one ``init_docs`` allocation + one quarantining
``apply_changes_docs`` for the whole batch, regardless of N.

Sources can be live fleet docs, promoted host docs, parked
``MainStore``/``StorageEngine`` rows (read compute-on-compressed — the
parked doc is NOT revived into the fleet), or raw saved chunks. The
result is an ordinary backend handle: read it, save it, diff it, free it
(the caller owns the ephemeral slot).

Frontiers outside the history raise typed ``UnknownHeads`` (with a
forensic flight-recorder dump in quarantine mode); replay divergence —
the reconstructed doc's heads not matching the normalized frontier — is
an internal invariant violation and raises hard.
"""

import time

from ..errors import DocError, UnknownHeads, WireCorruption
from ..observability import hist as _hist
from ..observability import recorder as _flight
from ..observability.spans import span as _span
from .history import frontier_of, history_of, select_ancestors

__all__ = ['materialize_at', 'materialize_at_docs']


def materialize_at_docs(sources, heads_list, fleet=None, deadline=None,
                        on_error='raise'):
    """Reconstruct N historical reads in one fused dispatch.

    ``sources[i]`` is any query source (see ``history.history_of``);
    ``heads_list[i]`` its requested frontier (hex hash list; ``[]`` is
    the empty document). Returns handles in input order.

    ``on_error='raise'`` (default) aborts the batch on the first bad
    frontier (typed ``UnknownHeads`` carrying ``doc_index``).
    ``on_error='quarantine'`` returns ``(handles, errors)``: a bad
    frontier, an unreadable (rotted) source chunk, or a history the
    apply gate rejects costs ONLY its own slot (``errors[i]`` is a
    ``DocError``, ``handles[i]`` is None) while the other reads commit
    in the same fused dispatch. ``deadline`` is checked before the selection walk
    and again by the apply seam before the fused dispatch — a read is
    served whole or not at all (reads mutate nothing, so the bound is
    purely latency)."""
    from ..fleet import backend as fleet_backend
    from . import _stats

    n = len(sources)
    if len(heads_list) != n:
        raise ValueError('sources and heads_list must align')
    quarantine = on_error == 'quarantine'
    if not quarantine and on_error != 'raise':
        raise ValueError(f"on_error must be 'raise' or 'quarantine', "
                         f'got {on_error!r}')
    if fleet is None:
        for source in sources:
            state = source.get('state') if isinstance(source, dict) else None
            if state is not None and getattr(state, 'is_fleet', False):
                fleet = state.fleet
                break
        if fleet is None:
            fleet = fleet_backend.default_fleet()

    start = time.perf_counter()
    errors = [None] * n
    per_doc = [None] * n
    expect = [None] * n
    with _span('materialize_at', docs=n):
        if deadline is not None:
            deadline.check(what='materialize_at_docs')
        for i, (source, heads) in enumerate(zip(sources, heads_list)):
            heads = [str(h) for h in heads]
            try:
                history = history_of(source)
                expect[i] = frontier_of(history, heads,
                                        what='materialize_at')
                per_doc[i] = select_ancestors(history, expect[i],
                                              what='materialize_at')
            except (UnknownHeads, WireCorruption) as exc:
                # UnknownHeads: the frontier names missing history;
                # WireCorruption (MalformedDocument): a rotted parked
                # chunk failed extraction. Both are THIS doc's problem.
                if getattr(exc, 'doc_index', None) is None:
                    exc.doc_index = i
                if isinstance(exc, UnknownHeads):
                    _stats.inc('unknown_heads')
                if not quarantine:
                    raise
                errors[i] = DocError(i, 'select', exc)
                per_doc[i] = []
                expect[i] = []
        if any(e is not None for e in errors):
            _flight.dump_flight_record('query', detail={'errors': [
                e.describe() for e in errors if e is not None]})
        handles = fleet_backend.init_docs(n, fleet)
        if any(per_doc):
            try:
                if quarantine:
                    # a history whose selected buffers fail the apply
                    # gate (e.g. a rotted chunk's extracted change) must
                    # cost only ITS slot, like a bad frontier does
                    handles, _patches, apply_errors = \
                        fleet_backend.apply_changes_docs(
                            handles, per_doc, mirror=False,
                            on_error='quarantine', deadline=deadline)
                    for i, err in enumerate(apply_errors):
                        if err is not None and errors[i] is None:
                            errors[i] = err
                else:
                    handles, _patches = fleet_backend.apply_changes_docs(
                        handles, per_doc, mirror=False, deadline=deadline)
            except Exception:
                # nothing committed (all-or-nothing seam): release the
                # freshly allocated slots before propagating
                fleet_backend.free_docs(handles)
                raise
        to_free = []
        diverged = None
        for i, handle in enumerate(handles):
            if errors[i] is not None:
                to_free.append(handle)
                handles[i] = None
                continue
            got = sorted(fleet_backend.get_heads(handle))
            if got != expect[i] and diverged is None:
                diverged = (i, got)
        if diverged is not None:
            # internal invariant violation: free the WHOLE batch before
            # raising (nothing here is safe to hand out)
            fleet_backend.free_docs([h for h in handles if h is not None])
            i, got = diverged
            raise AssertionError(
                f'materialize_at doc {i}: replay reached frontier '
                f'{got} instead of {expect[i]}')
        if to_free:
            fleet_backend.free_docs(to_free)
    elapsed = time.perf_counter() - start
    _stats.inc('timetravel_reads', n)
    _hist.record_value('materialize_at_s', elapsed, scale=1e9, unit='s')
    if quarantine:
        return handles, errors
    return handles


def materialize_at(source, heads, fleet=None, deadline=None):
    """One historical read: the document at frontier `heads`, as a fresh
    backend handle (see ``materialize_at_docs`` for the batched form —
    N reads there cost the same dispatches as one here)."""
    return materialize_at_docs([source], [heads], fleet=fleet,
                               deadline=deadline)[0]
