"""Typed failure taxonomy for the batched seam and the sync wire.

The reference backend reports every failure as a bare ``ValueError`` (or
lets decoder ``IndexError``/``KeyError`` escape), which is survivable when
one document fails one call — but the fleet engine applies N documents per
fused dispatch and a whole shard's sync round per collective, so callers
need to know three things a bare exception cannot tell them: WHICH
document's input was bad, WHAT CLASS of input it was (malformed bytes vs a
well-formed but causally-invalid change vs an oversized payload), and
whether the failure is CONTAINED (the other N-1 documents committed) or
batch-fatal. This module is that contract:

- Wire-corruption errors (``MalformedChange``, ``MalformedDocument``,
  ``MalformedSyncMessage``) mean the bytes themselves cannot be decoded —
  checksum mismatch, truncation, garbage columns. Decoder entry points
  convert whatever the parser tripped over (IndexError, struct noise,
  UnicodeDecodeError, zlib errors) into these, so "only typed errors
  escape a decoder" is an invariant the wire fuzzer
  (tools/fuzz_wire.py) can enforce.
- Validity errors (``InvalidChange``, ``DanglingPred``,
  ``DuplicateOpId``) mean the bytes decoded fine but the change violates
  the causal/structural rules the apply gate checks.
- ``SyncOverflow`` means a sync payload exceeded the multihost wire's
  hard ceiling (exchange.py) — raised identically on every controller so
  no peer blocks inside a collective.
- Durability-layer corruption (``MalformedJournal``, ``TornTail``,
  ``MalformedSnapshot``) means bytes ON DISK — change-journal frames,
  fleet snapshots, the checkpoint manifest — failed their CRC framing
  (fleet/durability.py). They are ``WireCorruption`` too: disk is just a
  wire with a longer flight time, and recovery gives rotted disk bytes
  the same one-doc blast radius the sync wire gets.
- Load-shedding rejections (``Overloaded``, ``TenantThrottled``,
  ``DeadlineExceeded``, ``RetriesExhausted``, ``SyncStalled``,
  ``ShardUnavailable``) mean the
  INPUT was fine but the system declined the work: global or per-tenant
  admission control refused it, its deadline passed before the fused
  dispatch, or its retry/reconnect budget ran dry (service/ and
  fleet/faults.py). They join the taxonomy so shedding is never an
  untyped escape — a client can always distinguish "your bytes are bad"
  from "come back later" (``retry_after``) from "too late". A shed
  request is all-or-nothing: these errors are only ever raised BEFORE
  the request's batch commits, never after a partial apply.
- Query-engine rejections (``InvalidCursor``, ``UnknownHeads``) scope
  the time-travel/subscription surface (automerge_tpu/query/):
  ``InvalidCursor`` is wire corruption at the subscription-cursor
  decode boundary (hostile cursor bytes fail typed, like every other
  decoder); ``UnknownHeads`` means the cursor/frontier DECODED fine but
  names hashes outside the document's causal history — a stale, bogus,
  or cross-document cursor. A subscriber presenting one is resynced or
  rejected typed; it is never sent a wrong patch.

Every class subclasses ``ValueError`` (the reference's error type), so
existing ``except ValueError`` / ``pytest.raises(ValueError)`` call sites
keep working; new code catches ``AutomergeError`` (or a subclass) and
reads ``doc_index`` to scope the blast radius. ``DocError`` is the
structured per-document rejection record the quarantining batch APIs
(``apply_changes_docs(..., on_error='quarantine')``,
``receive_sync_messages_docs(..., on_error='quarantine')``) return for
rejected slots while the healthy documents commit in the same fused
dispatch.
"""

__all__ = [
    'AutomergeError', 'WireCorruption', 'MalformedChange',
    'MalformedDocument', 'MalformedSyncMessage', 'MalformedJournal',
    'TornTail', 'MalformedSnapshot', 'InvalidChange',
    'DanglingPred', 'DuplicateOpId', 'SyncOverflow', 'DocError',
    'Overloaded', 'TenantThrottled', 'DeadlineExceeded',
    'RetriesExhausted', 'SyncStalled', 'SessionClosed',
    'ShardUnavailable',
    'InvalidCursor', 'UnknownHeads',
    'as_wire_error',
]


class AutomergeError(Exception):
    """Base of every typed failure. `doc_index` scopes the error to one
    slot of a batched call (None = not doc-scoped / unknown).

    `budget` is the SLO error-budget class the failure burns (None =
    burns no availability budget): the shedding classes each carry
    their own so the telemetry plane (observability/slo.py) can hold
    TenantThrottled, Overloaded, and DeadlineExceeded against DIFFERENT
    objectives — a tenant flooding itself dry must not spend the budget
    that pages when the service starts shedding everyone."""

    budget = None

    def __init__(self, *args, doc_index=None, **attrs):
        super().__init__(*args)
        self.doc_index = doc_index
        for name, value in attrs.items():
            setattr(self, name, value)


class WireCorruption(AutomergeError, ValueError):
    """Bytes off the wire (or disk) that cannot be decoded at all."""


class MalformedChange(WireCorruption):
    """A binary change chunk that fails to decode: bad magic/checksum,
    truncated columns, out-of-range LEBs, invalid UTF-8."""


class MalformedDocument(WireCorruption):
    """A saved document chunk that fails to decode or whose recomputed
    heads do not reproduce the header."""


class MalformedSyncMessage(WireCorruption):
    """A sync-protocol message that fails to decode (wrong type byte,
    truncated hash runs, bad filter framing)."""


class MalformedJournal(WireCorruption):
    """A change-journal frame that fails its CRC framing: rotted header
    or payload bytes, garbage between frames (fleet/durability.py)."""


class TornTail(MalformedJournal):
    """A journal whose final frame runs past end-of-file or whose tail
    is garbage with no later valid frame — the signature of a crash
    mid-write. Recovery truncates at the first bad CRC frame."""


class MalformedSnapshot(WireCorruption):
    """A fleet snapshot or checkpoint manifest that fails to decode:
    bad magic, missing END terminator, rotted per-doc frames."""


class InvalidChange(AutomergeError, ValueError):
    """A change that decoded fine but violates the apply gate's rules
    (sequence reuse/skip, unresolvable structure)."""


class DanglingPred(InvalidChange):
    """A change whose pred names no existing operation — the reference
    rejects invalid op references during the merge (new.js:1219-1220)."""


class DuplicateOpId(InvalidChange):
    """Two operations in one document claim the same opId."""


class SyncOverflow(AutomergeError, ValueError):
    """A sync payload exceeded the multihost wire's hard ceiling. Carries
    `global_max` (largest payload anywhere this round), `max_msg` (the
    per-sub-round wire width), `max_chunks` (how many sub-rounds the wire
    will chunk across), and `pairs` (locally-observed offending
    (src, dst) shard pairs — each controller sees only its own)."""


class Overloaded(AutomergeError, ValueError):
    """The service's global admission ceiling (queued + in-flight work)
    is full, or a brownout stage shed this request class. Carries
    `retry_after` (seconds the client should wait, None = unknown) and,
    for brownout sheds, `shed=True` + `stage`."""

    budget = 'overloaded'


class TenantThrottled(Overloaded):
    """THIS tenant exhausted its token bucket or bounded queue — other
    tenants are unaffected (per-tenant isolation is the point). Carries
    `tenant` and `retry_after`."""

    budget = 'throttled'


class SessionClosed(Overloaded):
    """The request's session was closed before it could be served (the
    client disconnected, or kept a dead handle after a failover or
    migration moved its tenant). Burns the 'throttled' budget — the
    CLIENT's fault, not the service shedding. A dedicated type so the
    shard router can recognize 'this session moved out from under a
    queued request' structurally and retry on the new home, instead of
    matching message text."""

    budget = 'throttled'


class ShardUnavailable(Overloaded):
    """The tenant's home shard is dead or unreachable (crashed, lease
    expired, or not yet failed over) — the request never reached a
    serving shard. Carries `shard` (the unavailable shard id, when
    known), `tenant`, and `retry_after`: the router's failover machinery
    re-homes the tenant within the lease window, so a budgeted jittered
    retry normally lands on the replica. Burns the 'overloaded'
    availability budget — a dead shard is the SERVICE's fault, never
    the tenant's."""


class DeadlineExceeded(AutomergeError, ValueError):
    """The request's deadline passed before its batch's fused dispatch.
    All-or-nothing: raised only while the request is still entirely
    unapplied — a deadline NEVER fires after a partial commit. Carries
    `deadline` (the absolute clock value) and `late_by` (seconds)."""

    budget = 'deadline'


class RetriesExhausted(AutomergeError, ValueError):
    """A transient fault persisted past the bounded jittered-backoff
    schedule or the per-tenant retry budget — retrying further would
    amplify the outage. Carries `attempts` and (when tenant-scoped)
    `tenant`; `__cause__` is the last underlying typed failure."""


class SyncStalled(RetriesExhausted):
    """The two-peer sync handshake kept traffic flowing but made no head
    progress through the whole reconnect-with-backoff schedule
    (fleet/faults.py sync_until_quiet) — a protocol bug or a dead wire,
    not bad luck. Carries `rounds` and `resets`."""


class InvalidCursor(WireCorruption):
    """Subscription-cursor bytes that cannot be decoded: bad magic,
    truncated hash runs, count bombs, trailing garbage
    (automerge_tpu/query/subscriptions.py decode_cursor)."""


class UnknownHeads(AutomergeError, ValueError):
    """A time-travel frontier or subscription cursor that decoded fine
    but names change hashes outside the document's history (stale after
    a history the server never had, bogus, or aimed at the wrong doc).
    Carries `missing` (the unknown hex hashes). The query engine answers
    with a typed rejection or a full resync — never a wrong patch."""


class DocError:
    """Structured per-document rejection record from a quarantining batch
    call: `index` (slot in the batch), `stage` ('decode' | 'apply' |
    'sync'), `error` (the typed exception). Healthy docs in the same call
    carry None in the errors vector."""

    __slots__ = ('index', 'stage', 'error')

    def __init__(self, index, stage, error):
        self.index = index
        self.stage = stage
        self.error = error

    def __repr__(self):
        return (f'DocError(index={self.index}, stage={self.stage!r}, '
                f'error={type(self.error).__name__}: {self.error})')

    def describe(self, durable_id=None):
        """JSON-friendly record for forensic flight-recorder dumps: slot
        index, stage, typed error name, truncated message, and (when the
        caller knows it) the document's durable journal id."""
        return {'doc': self.index, 'stage': self.stage,
                'error': type(self.error).__name__,
                'message': str(self.error)[:200],
                'durable_id': durable_id}


def as_wire_error(exc, err_cls, what, doc_index=None):
    """Normalize an arbitrary decoder exception into the typed class:
    already-typed errors pass through (gaining a doc_index if they lack
    one), everything else wraps with the original as __cause__."""
    if isinstance(exc, AutomergeError):
        if doc_index is not None and exc.doc_index is None:
            exc.doc_index = doc_index
        return exc
    err = err_cls(f'{what}: {type(exc).__name__}: {exc}',
                  doc_index=doc_index)
    err.__cause__ = exc
    return err
