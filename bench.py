#!/usr/bin/env python
"""Fleet benchmark: batched change application over a document fleet.

The HEADLINE metric is the end-to-end Backend-seam rate: binary changes ->
header decode + SHA-256 hash graph + causal gate (host) -> native C++ column
parse -> one device merge dispatch, via fleet.backend.apply_changes_docs
(mirror=False). That is the full setDefaultBackend-pluggable pipeline a user
of the reference would hit — nothing skipped. Kernel-only numbers (device
merge on pre-built batches) are reported separately and labeled as such.

All key rates are medians over BENCH_REPS (default 5) timed runs after a
compile warmup.

Note: the reference JS backend cannot run in this image (no Node.js, no JS
engine wheels, no network — attempts recorded in BASELINE.md), so the
recorded baseline is our host reference engine (CPython OpSet); V8 would be
several times faster, so treat vs_baseline as vs-CPython.

Section modes:
- BENCH_SECTION=<name> runs ONE section standalone (fresh process, fenced)
  and prints {"section": name, ...} — the reproducibility answer to bench
  lines that moved 178x with section ordering (round-5 VERDICT weak #7).
  BENCH_SECTION=list prints the section names.
- BENCH_SANITY=1 runs a scaled-down full pass, then re-runs key sections
  standalone in subprocesses and fails (exit 1) if any full-run rate
  disagrees with its standalone rate by more than 2x.

Dispatch accounting: the seam section reports device dispatches for an
N-doc init and per apply round (DocFleet.metrics.dispatches), and the sync
driver section reports Bloom build+probe dispatches per 10k-peer generate
round (fleet.bloom.dispatch_count()) — both must be O(1), size-independent.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

REPS = int(os.environ.get('BENCH_REPS', 5))

BENCH_PLATFORM = None


def _guard_dead_accelerator():
    """The TPU is reached through a local tunnel; when the tunnel daemon is
    down or half-dead, the platform plugin HANGS on first device query (it
    retries forever) and the whole bench run would time out recording
    nothing. A socket probe is not reliable (a flapping tunnel can accept
    and even answer while the device behind it is gone), so probe by
    actually initializing the device in a SUBPROCESS under a hard timeout
    and fall back to CPU — clearly labeled in the output — when it cannot.
    An honest slower record beats silence."""
    global BENCH_PLATFORM
    import subprocess
    import jax
    if os.environ.get('JAX_PLATFORMS') == 'cpu':
        BENCH_PLATFORM = 'cpu-forced'
        jax.config.update('jax_platforms', 'cpu')
        return
    probe_s = int(os.environ.get('BENCH_DEVICE_PROBE_TIMEOUT', 60))
    if probe_s == 0:
        return    # probe disabled
    # The probe tries the real device, so a healthy accelerator (tunneled
    # or directly attached) always passes; only a device that genuinely
    # cannot initialize+compute within the timeout demotes the run.
    try:
        proc = subprocess.run(
            [sys.executable, '-c',
             'import jax, jax.numpy as jnp;'
             'print(int(jnp.arange(4).sum()), jax.devices()[0].platform)'],
            timeout=probe_s, capture_output=True)
        ok = proc.returncode == 0 and proc.stdout.startswith(b'6')
    except subprocess.TimeoutExpired:
        ok = False
    if ok:
        BENCH_PLATFORM = None      # device initializes and computes
        return
    print(f'# WARNING: accelerator failed to initialize within {probe_s}s '
          f'-> benchmarking on CPU fallback (BENCH_DEVICE_PROBE_TIMEOUT=0 '
          f'disables this probe)', file=sys.stderr)
    BENCH_PLATFORM = 'cpu-fallback'
    jax.config.update('jax_platforms', 'cpu')


def median_rate(run, total, reps=None):
    """Median ops-per-second over `reps` timed runs of run()."""
    rates = []
    for _ in range(reps or REPS):
        start = time.perf_counter()
        run()
        rates.append(total / (time.perf_counter() - start))
    return float(np.median(rates))


def build_workload(n_docs, n_keys, n_actors, rounds, ops_per_round, seed=0):
    """Concurrent map-set workload as per-round op columns [N, P]."""
    from automerge_tpu.fleet import OpBatch
    from automerge_tpu.fleet.tensor_doc import ACTOR_BITS
    rng = np.random.default_rng(seed)
    batches = []
    ctr = 1
    for _ in range(rounds):
        shape = (n_docs, ops_per_round)
        key_id = rng.integers(0, n_keys, shape, dtype=np.int32)
        actor = rng.integers(0, n_actors, shape, dtype=np.int32)
        ctrs = ctr + np.broadcast_to(
            np.arange(ops_per_round, dtype=np.int32), shape)
        packed = (ctrs.astype(np.int32) << ACTOR_BITS) | actor
        value = rng.integers(1, 1 << 20, shape, dtype=np.int32)
        ones = np.ones(shape, dtype=bool)
        batches.append(OpBatch(key_id, packed, value, ones,
                               np.zeros(shape, dtype=bool), ones))
        ctr += ops_per_round
    return batches


def bench_fleet(n_docs, n_keys, rounds, ops_per_round, use_pallas=False,
                pallas_variant='dense'):
    import functools
    import jax
    from automerge_tpu.fleet import FleetState, apply_op_batch
    if use_pallas:
        from automerge_tpu.fleet.pallas_merge import pallas_apply_op_batch
        apply_op_batch = functools.partial(pallas_apply_op_batch,
                                           variant=pallas_variant)

    batches = build_workload(n_docs, n_keys, 2, rounds, ops_per_round)
    state = FleetState.empty(n_docs, n_keys)
    device_batches = [jax.device_put(b) for b in batches]
    state = jax.tree_util.tree_map(jax.device_put, state)

    # Warmup / compile
    warm, _ = apply_op_batch(state, device_batches[0])
    jax.block_until_ready(warm.winners)

    def run():
        s = state
        for b in device_batches:
            s, _stats = apply_op_batch(s, b)
        jax.block_until_ready(s.winners)

    total_ops = n_docs * ops_per_round * rounds
    return median_rate(run, total_ops), None


def bench_pallas_merge(n_docs, n_keys, rounds, ops_per_round):
    """Fused Pallas merge kernel (interpret=False: real Mosaic compile) on
    the same workload as bench_fleet, with a correctness cross-check
    against the jnp path. Tries the dense one-hot formulation first, then
    the VMEM-conservative lane-loop variant if Mosaic rejects it. Runs
    whenever a TPU is the default backend (or BENCH_PALLAS=1 forces it
    elsewhere); returns (rate, variant) or (None, None) when unavailable
    (reported, never fatal to the bench)."""
    import jax
    if not os.environ.get('BENCH_PALLAS') and \
            jax.default_backend() != 'tpu':
        return None, None
    for variant in ('dense', 'loop'):
        try:
            from automerge_tpu.fleet import FleetState, apply_op_batch
            from automerge_tpu.fleet.pallas_merge import pallas_apply_op_batch
            # differential check on a small batch before timing
            check = build_workload(64, n_keys, 3, 1, 32)[0]
            st0 = FleetState.empty(64, n_keys)
            want, _ = apply_op_batch(st0, check)
            got, _ = pallas_apply_op_batch(st0, check, interpret=False,
                                           variant=variant)
            for name in ('winners', 'values', 'counters'):
                w = np.asarray(getattr(want, name))[:, :n_keys]
                g = np.asarray(getattr(got, name))[:, :n_keys]
                if not np.array_equal(w, g):
                    raise AssertionError(f'pallas/jnp mismatch in {name}')
            rate, _ = bench_fleet(n_docs, n_keys, rounds, ops_per_round,
                                  use_pallas=True, pallas_variant=variant)
            return rate, variant
        except AssertionError:
            raise          # a MISCOMPILED kernel must fail loudly, not
                           # masquerade as a benign compile failure
        except Exception as exc:   # Mosaic lowering/compile issues: report
            print(f'# pallas merge kernel ({variant}) unavailable: '
                  f'{type(exc).__name__}: {str(exc)[:200]}', file=sys.stderr)
    return None, None


def capture_trace(n_docs, n_keys, ops_per_round, pallas_variant=None):
    """Write a jax.profiler trace of steady-state merge + sequence + (when
    compiled) Pallas dispatches to BENCH_TRACE_DIR (default traces/bench).
    Runs on a real TPU backend, or anywhere with BENCH_TRACE=1; the trace is
    the evidence base for BASELINE.md's bandwidth accounting. Returns the
    trace dir or None (failure is reported, never fatal)."""
    import jax
    if not os.environ.get('BENCH_TRACE') and jax.default_backend() != 'tpu':
        return None
    try:
        from automerge_tpu import observability
        from automerge_tpu.fleet import FleetState, apply_op_batch
        from automerge_tpu.fleet.sequence import (
            SeqState, apply_seq_batch, SeqOpBatch, INSERT, SEQ_PRED_LANES)
        from automerge_tpu.fleet.tensor_doc import ACTOR_BITS
        batches = [jax.device_put(b) for b in
                   build_workload(n_docs, n_keys, 2, 3, ops_per_round)]
        state = jax.tree_util.tree_map(jax.device_put,
                                       FleetState.empty(n_docs, n_keys))
        warm, _ = apply_op_batch(state, batches[0])    # compile outside
        jax.block_until_ready(warm.winners)
        # small sequence batch: chained inserts per doc
        sd, sl = 256, 64
        kind = np.full((sd, sl), INSERT, dtype=np.int32)
        ctrs = 2 + np.arange(sl, dtype=np.int32)
        packed = np.broadcast_to(ctrs << ACTOR_BITS, (sd, sl)).astype(np.int32)
        ref = np.zeros((sd, sl), dtype=np.int32)
        ref[:, 1:] = packed[:, :-1]
        seq_batch = jax.device_put(SeqOpBatch(
            kind, ref, packed, np.full((sd, sl), 97, dtype=np.int32),
            np.zeros((sd, sl, SEQ_PRED_LANES), dtype=np.int32)))
        seq_state = jax.tree_util.tree_map(jax.device_put,
                                           SeqState.empty(sd, sl + 1))
        warm_seq, _ = apply_seq_batch(seq_state, seq_batch)
        jax.block_until_ready(warm_seq.nxt)
        if pallas_variant:
            from automerge_tpu.fleet.pallas_merge import pallas_apply_op_batch
            warm_p, _ = pallas_apply_op_batch(state, batches[0],
                                              variant=pallas_variant)
            jax.block_until_ready(warm_p.winners)
        trace_dir = os.environ.get('BENCH_TRACE_DIR', 'traces/bench')
        with observability.trace(trace_dir):
            s = state
            for b in batches:
                s, _ = apply_op_batch(s, b)
            jax.block_until_ready(s.winners)
            out, _ = apply_seq_batch(seq_state, seq_batch)
            jax.block_until_ready(out.nxt)
            if pallas_variant:
                s2, _ = pallas_apply_op_batch(state, batches[0],
                                              variant=pallas_variant)
                jax.block_until_ready(s2.winners)
        return trace_dir
    except Exception as exc:
        print(f'# profiler trace capture failed: '
              f'{type(exc).__name__}: {str(exc)[:200]}', file=sys.stderr)
        return None


def bench_host(n_docs, n_keys, rounds, ops_per_round, seed=0):
    """Same workload shape through the host OpSet engine (single-op changes,
    matching the backend_test.js concurrent-key-set shape)."""
    from automerge_tpu import backend as Backend
    from automerge_tpu.columnar import encode_change
    rng = np.random.default_rng(seed)
    actors = ['aa' * 4, 'bb' * 4]

    # Pre-encode all changes (decode cost is part of applyChanges either way;
    # encode cost is the remote peer's problem)
    docs = []
    for d in range(n_docs):
        changes = []
        seqs = {0: 0, 1: 0}
        ctr = 1
        for _ in range(rounds):
            for i in range(ops_per_round):
                a = int(rng.integers(0, 2))
                seqs[a] += 1
                changes.append(encode_change({
                    'actor': actors[a], 'seq': seqs[a], 'startOp': ctr,
                    'time': 0, 'message': '', 'deps': [],
                    'ops': [{'action': 'set', 'obj': '_root',
                             'key': f'k{int(rng.integers(0, n_keys))}',
                             'value': int(rng.integers(1, 1 << 20)),
                             'datatype': 'int', 'pred': []}],
                }))
                ctr += 1
        docs.append(changes)

    def run():
        for changes in docs:
            backend = Backend.init()
            state = backend['state']
            # seq contiguity: interleave per actor in recorded order
            state.apply_changes(changes)

    total_ops = n_docs * rounds * ops_per_round
    return median_rate(run, total_ops, reps=3), None


def bench_pipeline(n_docs, n_keys, changes_per_doc, seed=0):
    """Full wire-to-device pipeline: binary changes -> native C++ column
    decode -> dictionary encoding -> device merge."""
    import jax
    from automerge_tpu.columnar import encode_change
    from automerge_tpu.fleet import FleetState, apply_op_batch
    from automerge_tpu.fleet.ingest import (
        changes_to_op_batch, KeyInterner, ActorInterner)
    rng = np.random.default_rng(seed)
    actors = ['aa' * 4, 'bb' * 4]
    per_doc = []
    for d in range(n_docs):
        changes = []
        seqs = [0, 0]
        for c in range(changes_per_doc):
            a = int(rng.integers(0, 2))
            seqs[a] += 1
            changes.append(encode_change({
                'actor': actors[a], 'seq': seqs[a], 'startOp': c + 1,
                'time': 0, 'message': '', 'deps': [],
                'ops': [{'action': 'set', 'obj': '_root',
                         'key': f'k{int(rng.integers(0, n_keys))}',
                         'value': int(rng.integers(1, 1 << 20)),
                         'datatype': 'int', 'pred': []}]}))
        per_doc.append(changes)

    def run():
        ki, ai = KeyInterner(), ActorInterner()
        batch = changes_to_op_batch(per_doc, ki, ai)
        state = FleetState.empty(n_docs, max(len(ki), 1))
        state, _ = apply_op_batch(state, batch)
        jax.block_until_ready(state.winners)

    run()  # warmup: jit compile for these shapes
    return median_rate(run, n_docs * changes_per_doc), None


def bench_backend_pipeline(n_docs, n_keys, changes_per_doc, seed=0,
                           chunks=1, ops_per_change=1, reps=None):
    """Wire-to-device through the Backend seam (fleet.backend turbo path):
    header decode + SHA-256 hash graph + causal gate on host, native C++
    column parse, one device merge dispatch per chunk. This is the full
    setDefaultBackend-pluggable pipeline, unlike bench_pipeline which skips
    the causal/hash-graph bookkeeping.

    chunks > 1 routes the batch through apply_changes_docs_pipelined with
    that many sub-batches: the NATIVE PARSE of sub-batch k+1 runs on a
    background thread (GIL released, chunk-parallel over the codec's
    thread pool) while the host gate/commit and async device dispatch of
    sub-batch k proceed — real CPU overlap, not just dispatch asynchrony
    (the round-6 4-chunk loop split serial work without adding cores and
    REGRESSED the seam ~2x; this path replaced it).

    ops_per_change > 1 packs that many flat-int set ops into each change —
    the op-density control for the mixed-docs gap (a fractional value like
    4.8 is honored by mixing change sizes to that average).

    One change chain is shared by every doc (the bench_backend_text
    pattern): the measured pipeline memoizes nothing by content — every
    buffer is parsed, hashed, and gated per document — so this only makes
    the 10k-doc setup affordable, not the measurement cheaper.

    Returns (changes_per_sec, info) where info records the device dispatch
    counts: {'init_dispatches', 'apply_dispatches', 'rounds',
    'ops_per_change'} — the O(1)-dispatch evidence for the seam."""
    from automerge_tpu.columnar import encode_change, decode_change_meta
    from automerge_tpu.fleet.backend import (
        DocFleet, init_docs, apply_changes_docs, materialize_docs)
    rng = np.random.default_rng(seed)
    actors = ['aa' * 16, 'bb' * 16]
    changes, heads = [], []
    seqs = [0, 0]
    op_counts = []
    acc = 0.0
    for c in range(changes_per_doc):
        # realize a fractional average op density by alternating sizes
        acc += ops_per_change
        k = max(int(round(acc)), 1)
        acc -= k
        op_counts.append(k)
    start_op = 1
    for c in range(changes_per_doc):
        a = c % 2
        seqs[a] += 1
        ops = [{'action': 'set', 'obj': '_root',
                'key': f'k{int(rng.integers(0, n_keys))}',
                'value': int(rng.integers(1, 1 << 20)),
                'datatype': 'int', 'pred': []}
               for _ in range(op_counts[c])]
        buf = encode_change({
            'actor': actors[a], 'seq': seqs[a], 'startOp': start_op,
            'time': 0, 'message': '', 'deps': heads, 'ops': ops})
        start_op += op_counts[c]
        heads = [decode_change_meta(buf, True)['hash']]
        changes.append(buf)
    per_doc = [list(changes) for _ in range(n_docs)]
    # actual sub-batch count: the pipelined driver splits per doc at
    # step = ceil(changes/chunks) and DROPS empty tail sub-batches, so
    # e.g. chunks=8 over 20 changes yields 7 rounds, not 8
    if max(chunks, 1) > 1:
        step = -(-changes_per_doc // max(chunks, 1))
        n_rounds = -(-changes_per_doc // step)
    else:
        n_rounds = 1
    info = {'rounds': n_rounds,
            'ops_per_change': sum(op_counts) / len(op_counts)}

    def run():
        import jax
        from automerge_tpu.fleet.backend import apply_changes_docs_pipelined
        fleet = DocFleet(doc_capacity=n_docs, key_capacity=n_keys + 1)
        d0 = fleet.metrics.dispatches
        handles = init_docs(n_docs, fleet)
        info['init_dispatches'] = fleet.metrics.dispatches - d0
        d1 = fleet.metrics.dispatches
        if n_rounds > 1:
            handles, _ = apply_changes_docs_pipelined(
                handles, per_doc, sub_batches=n_rounds)
        else:
            handles, _ = apply_changes_docs(handles, per_doc, mirror=False)
        jax.block_until_ready(fleet.state.winners)
        info['apply_dispatches'] = fleet.metrics.dispatches - d1
        return handles

    run()  # warmup compile
    return median_rate(run, n_docs * changes_per_doc, reps=reps), info


def bench_sync_bloom(n_docs, hashes_per_doc, seed=0):
    """Config 4 (BASELINE.md): sync Bloom-filter throughput. Device path:
    per-peer filters for the whole fleet built in one scatter dispatch and
    probed in one gather dispatch ([docs, bits] bit tensors); host baseline:
    the per-peer BloomFilter loop the reference runs per sync message
    (ref sync.js:38-125). Returns (device_hashes_per_sec, host_hashes_per_sec)."""
    import hashlib
    import jax
    from automerge_tpu.backend.sync import BloomFilter
    from automerge_tpu.fleet.bloom import (
        build_bloom_filters, probe_bloom_filters, hashes_to_words)
    hashes = [[hashlib.sha256(f'{d}:{i}:{seed}'.encode()).hexdigest()
               for i in range(hashes_per_doc)] for d in range(n_docs)]
    words, valid = hashes_to_words(hashes)
    words = jax.device_put(words)
    valid = jax.device_put(valid)
    bits = build_bloom_filters(words, valid, hashes_per_doc)  # warmup build
    probe_bloom_filters(bits, words, valid).block_until_ready()
    start = time.perf_counter()
    bits = build_bloom_filters(words, valid, hashes_per_doc)
    hit = probe_bloom_filters(bits, words, valid)
    jax.block_until_ready(hit)
    device_rate = (2 * n_docs * hashes_per_doc) / (time.perf_counter() - start)

    host_docs = max(n_docs // 100, 1)
    start = time.perf_counter()
    for d in range(host_docs):
        f = BloomFilter(hashes[d])
        for h in hashes[d]:
            assert f.contains_hash(h)
    host_rate = (2 * host_docs * hashes_per_doc) / (time.perf_counter() - start)
    return device_rate, host_rate


def bench_sync_driver(n_docs, changes_per_doc=8, seed=0):
    """Batched fleet sync driver (fleet/sync_driver.py) vs the host per-doc
    protocol loop: one generate round over n_docs peers, ALL Bloom builds
    in one device dispatch (flat packed layout — size-class count no
    longer matters). Returns (batched_docs_per_sec, host_docs_per_sec,
    dispatches_per_round)."""
    from automerge_tpu import backend as Backend
    from automerge_tpu.backend import init_sync_state
    from automerge_tpu.backend.sync import generate_sync_message
    from automerge_tpu.columnar import encode_change, decode_change_meta
    from automerge_tpu.fleet import bloom as fleet_bloom
    from automerge_tpu.fleet.sync_driver import generate_sync_messages_docs
    rng = np.random.default_rng(seed)

    def build_docs(n):
        docs = []
        for d in range(n):
            backend = Backend.init()
            changes, heads = [], []
            for c in range(changes_per_doc):
                buf = encode_change({
                    'actor': f'{d:04x}' * 4, 'seq': c + 1, 'startOp': c + 1,
                    'time': 0, 'message': '', 'deps': heads,
                    'ops': [{'action': 'set', 'obj': '_root',
                             'key': f'k{int(rng.integers(0, 16))}',
                             'value': int(rng.integers(1, 1 << 20)),
                             'datatype': 'int', 'pred': []}]})
                heads = [decode_change_meta(buf, True)['hash']]
                changes.append(buf)
            backend = Backend.load_changes(backend, changes)
            docs.append(backend)
        return docs

    docs = build_docs(n_docs)
    states = [init_sync_state() for _ in docs]
    generate_sync_messages_docs(docs, states)    # warmup compile
    d0 = fleet_bloom.dispatch_count()
    start = time.perf_counter()
    _, messages = generate_sync_messages_docs(docs, states)
    batched_rate = n_docs / (time.perf_counter() - start)
    dispatches = fleet_bloom.dispatch_count() - d0
    assert all(m is not None for m in messages)

    host_n = max(n_docs // 20, 1)
    start = time.perf_counter()
    for doc, state in zip(docs[:host_n], states[:host_n]):
        generate_sync_message(doc, state)
    host_rate = host_n / (time.perf_counter() - start)
    return batched_rate, host_rate, dispatches


def bench_zipf(n_docs, zipf_a=1.5, max_per_doc=256, round_width=32, seed=0):
    """Config 5 (BASELINE.md stretch): large fleet with Zipf-skewed per-doc
    change rates, mixed set/inc/del ops. Skew is the scatter design's worst
    case: padded [N, P] rounds are sized by the hottest doc, so effective
    throughput = real ops/s (padding excluded) is reported alongside the
    occupancy (real ops / padded lanes)."""
    import jax
    from automerge_tpu.fleet import FleetState, OpBatch, TOMBSTONE, apply_op_batch
    from automerge_tpu.fleet.tensor_doc import ACTOR_BITS
    rng = np.random.default_rng(seed)
    n_keys = 64
    counts = np.minimum(rng.zipf(zipf_a, n_docs), max_per_doc)
    total_ops = int(counts.sum())
    rounds = int(np.ceil(counts.max() / round_width))
    batches = []
    ctr = 1
    for r in range(rounds):
        todo = np.clip(counts - r * round_width, 0, round_width)
        shape = (n_docs, round_width)
        lane = np.arange(round_width)[None, :]
        valid = lane < todo[:, None]
        key_id = rng.integers(0, n_keys, shape, dtype=np.int32)
        actor = rng.integers(0, 4, shape, dtype=np.int32)
        packed = ((ctr + lane).astype(np.int32) << ACTOR_BITS) | actor
        kind = rng.random(shape)
        value = rng.integers(1, 1 << 20, shape, dtype=np.int32)
        value = np.where(kind < 0.1, TOMBSTONE, value)          # 10% deletes
        is_inc = (kind >= 0.8) & valid                          # 20% incs
        is_set = (kind < 0.8) & valid
        batches.append(OpBatch(key_id, packed, value.astype(np.int32),
                               is_set, is_inc, valid))
        ctr += round_width
    state = FleetState.empty(n_docs, n_keys)
    device_batches = [jax.device_put(b) for b in batches]
    state = jax.tree_util.tree_map(jax.device_put, state)
    warm, _ = apply_op_batch(state, device_batches[0])
    jax.block_until_ready(warm.winners)
    start = time.perf_counter()
    s = state
    for b in device_batches:
        s, _ = apply_op_batch(s, b)
    jax.block_until_ready(s.winners)
    elapsed = time.perf_counter() - start
    occupancy = total_ops / (n_docs * round_width * rounds)
    return total_ops / elapsed, occupancy


def bench_registers(n_docs, n_keys=64, n_actor_slots=4, p=128, seed=0):
    """Exact multi-value register engine: ordered scan over the op axis,
    [n_docs]-wide steps (conflict sets / resurrection / counter semantics
    exact on device, unlike the scatter-max LWW engine)."""
    import jax
    from automerge_tpu.fleet.registers import (
        RegisterOpBatch, RegisterState, apply_register_batch)
    rng = np.random.default_rng(seed)
    kind = rng.integers(1, 4, (n_docs, p), dtype=np.int32)
    key = rng.integers(0, n_keys, (n_docs, p), dtype=np.int32)
    actor = rng.integers(0, n_actor_slots - 1, (n_docs, p), dtype=np.int32)
    packed = ((1 + np.arange(p, dtype=np.int32))[None, :] << 8) | actor
    value = rng.integers(0, 1000, (n_docs, p), dtype=np.int32)
    preds = np.zeros((n_docs, p, 2), dtype=np.int32)
    preds[:, 1:, 0] = packed[:, :-1]     # chain preds (kill previous)
    overflow = np.zeros((n_docs, p), dtype=bool)
    batch = RegisterOpBatch(kind, key, packed, value, preds, overflow)
    state = RegisterState.empty(n_docs, n_keys, n_actor_slots)
    state, _ = apply_register_batch(state, batch)
    jax.block_until_ready(state.reg)
    start = time.perf_counter()
    state, stats = apply_register_batch(state, batch)
    jax.block_until_ready(state.reg)
    return (n_docs * p) / (time.perf_counter() - start)


def bench_text(n_docs, trace_len, n_actors=3, seed=0):
    """KERNEL-ONLY config 2 shape: batched text editing traces through the
    raw device sequence engine on pre-built packed columns (no wire decode,
    no hash graph) — the device ceiling, not an end-to-end number; see
    bench_backend_text for the honest seam rate."""
    import jax
    from automerge_tpu.fleet.sequence import (
        DEL, INSERT, SeqOpBatch, SeqState, apply_seq_batch)
    from automerge_tpu.fleet.tensor_doc import ACTOR_BITS
    rng = np.random.default_rng(seed)

    # Randomized trace as packed columns [N, P]: ~80% inserts (after a random
    # earlier insert; head for the first), ~20% deletes of a random earlier
    # insert. The insert/delete column pattern is shared across docs so every
    # ref targets a real elemId; referents and actors vary per doc.
    is_del = rng.random(trace_len) < 0.2
    is_del[0] = False
    kind = np.where(is_del, DEL, INSERT).astype(np.int32)
    kind = np.broadcast_to(kind, (n_docs, trace_len)).copy()
    value = rng.integers(97, 123, (n_docs, trace_len), dtype=np.int32)
    actor = rng.integers(0, n_actors, (n_docs, trace_len), dtype=np.int32)
    ctr = 2 + np.arange(trace_len, dtype=np.int32)
    packed = ((ctr[None, :] << ACTOR_BITS) | actor).astype(np.int32)
    ref = np.zeros((n_docs, trace_len), dtype=np.int32)
    insert_cols = np.flatnonzero(~is_del)
    rows = np.arange(n_docs)
    for i in range(1, trace_len):
        prior = insert_cols[insert_cols < i]
        choice = prior[rng.integers(0, len(prior), n_docs)]
        ref[:, i] = packed[rows, choice]
    # DELs kill exactly their preds (multi-value register semantics): the
    # pred is the insert op being deleted, i.e. the ref elemId itself
    from automerge_tpu.fleet.sequence import SEQ_PRED_LANES
    preds = np.zeros((n_docs, trace_len, SEQ_PRED_LANES), dtype=np.int32)
    preds[:, :, 0] = np.where(kind == DEL, ref, 0)
    batch = SeqOpBatch(kind, ref, packed, value, preds)

    state = SeqState.empty(n_docs, trace_len + 1)
    batch = jax.device_put(batch)
    state = jax.tree_util.tree_map(jax.device_put, state)
    warm, _ = apply_seq_batch(state, batch)
    jax.block_until_ready(warm.nxt)

    def run():
        out, _ = apply_seq_batch(state, batch)
        jax.block_until_ready(out.nxt)

    return median_rate(run, n_docs * trace_len), None


def bench_backend_text(n_docs, trace_len, ops_per_change=32, seed=0):
    """End-to-end text editing through the Backend seam: binary change
    chains (makeText + insert/delete runs) -> turbo wire->device into the
    SeqState fleet. Returns median text ops/s across the fleet."""
    from automerge_tpu.columnar import encode_change, decode_change_meta
    from automerge_tpu.fleet.backend import (
        DocFleet, init_docs, apply_changes_docs)
    rng = np.random.default_rng(seed)
    A = 'aa' * 16
    # One trace shared by every doc: makeText, then chained changes of
    # insert/delete ops (deletes target a random still-visible element)
    ops, elems, alive = [], [], []
    ops.append({'action': 'makeText', 'obj': '_root', 'key': 't',
                'pred': []})
    obj = f'1@{A}'
    op_num = 2
    prev = '_head'
    while len(ops) < trace_len + 1:
        if alive and rng.random() < 0.2:
            i = int(rng.integers(0, len(alive)))
            victim = alive.pop(i)
            ops.append({'action': 'del', 'obj': obj, 'elemId': victim,
                        'pred': [victim]})
        else:
            ref = prev if not alive or rng.random() < 0.5 else \
                alive[int(rng.integers(0, len(alive)))]
            me = f'{op_num}@{A}'
            ops.append({'action': 'set', 'obj': obj, 'elemId': ref,
                        'insert': True,
                        'value': chr(97 + int(rng.integers(0, 26))),
                        'pred': []})
            alive.append(me)
            prev = me
        op_num += 1
    changes, heads = [], []
    seq = 0
    for start in range(0, len(ops), ops_per_change):
        chunk = ops[start:start + ops_per_change]
        seq += 1
        buf = encode_change({'actor': A, 'seq': seq, 'startOp': start + 1,
                             'time': 0, 'message': '', 'deps': heads,
                             'ops': chunk})
        heads = [decode_change_meta(buf, True)['hash']]
        changes.append(buf)
    per_doc = [list(changes) for _ in range(n_docs)]
    n_ops = len(ops) * n_docs

    def run():
        import jax
        fleet = DocFleet(doc_capacity=n_docs, key_capacity=4)
        handles = init_docs(n_docs, fleet)
        handles, _ = apply_changes_docs(handles, per_doc, mirror=False)
        assert fleet.metrics.fallbacks == 0
        jax.block_until_ready([p.nxt for p in fleet.seq_pools.pools.values()])

    run()  # warmup compile

    # Host baseline on the same trace (config 2's "vs" column): the host
    # OpSet engine applying the identical change chain, scaled-down doc
    # count, rate-normalized
    from automerge_tpu import backend as Backend
    host_docs = max(n_docs // 50, 1)

    def run_host():
        for _ in range(host_docs):
            backend = Backend.init()
            Backend.apply_changes(backend, changes)
    host_rate = median_rate(run_host, len(ops) * host_docs, reps=3)
    return median_rate(run, n_ops), host_rate


def bench_bulk_load(n_docs, n_changes=40, seed=0):
    """Fleet bulk load (native document parse -> device state, no replay)
    vs the ordinary per-doc load path (Python document decode + host OpSet
    replay). Returns (bulk docs/s, per-doc docs/s)."""
    import jax
    from automerge_tpu import backend as Backend
    from automerge_tpu.columnar import encode_change, decode_change_meta
    from automerge_tpu.fleet.backend import DocFleet
    from automerge_tpu.fleet import backend as fleet_backend
    from automerge_tpu.fleet.loader import load_docs
    rng = np.random.default_rng(seed)
    A = 'bb' * 16
    # One representative saved document, cloned across the fleet with
    # distinct trailing writes so contents differ per doc
    base = Backend.init()
    heads = []
    for c in range(n_changes):
        ops = [{'action': 'set', 'obj': '_root', 'key': f'k{int(k)}',
                'value': int(rng.integers(0, 1 << 20)),
                'datatype': 'int', 'pred': []}
               for k in rng.integers(0, 64, size=8)]
        buf = encode_change({'actor': A, 'seq': c + 1,
                             'startOp': c * 8 + 1, 'time': 0,
                             'message': '', 'deps': heads, 'ops': ops})
        heads = [decode_change_meta(buf, True)['hash']]
        base, _ = Backend.apply_changes(base, [buf])
    saved = Backend.save(base)
    bufs = [saved] * n_docs

    def run_bulk():
        fleet = DocFleet(doc_capacity=n_docs, key_capacity=128)
        handles = load_docs(bufs, fleet)
        if fleet.metrics.docs_bulk_loaded != n_docs:
            raise RuntimeError('bulk load fell back to the per-doc path')
        if fleet.state is not None:
            jax.block_until_ready(fleet.state.winners)

    host_docs = max(n_docs // 100, 1)

    def run_host():
        fleet = DocFleet(doc_capacity=host_docs, key_capacity=128)
        for buf in bufs[:host_docs]:
            fleet_backend.load(buf, fleet)

    host = median_rate(run_host, host_docs, reps=3)
    from automerge_tpu import native
    if not native.available():
        return None, host      # no native codec: bulk path unavailable
    run_bulk()   # warmup compile
    bulk = median_rate(run_bulk, n_docs, reps=3)
    return bulk, host


def bench_backend_mixed(n_docs, n_changes=16, seed=0):
    """End-to-end seam rate on a REALISTIC document shape: nested config
    maps, rows-in-lists, strings/floats/bools — workloads that used to
    fall off the turbo path entirely (flat-int-only) and now ride the
    native parser's nested rows + value arena + seq-make rows. Returns
    (turbo changes/s, host changes/s)."""
    import jax
    import automerge_tpu as am
    from automerge_tpu import backend as Backend
    from automerge_tpu.fleet.backend import (
        DocFleet, init_docs, apply_changes_docs)
    rng = np.random.default_rng(seed)
    d = am.from_({'cfg': {'name': 'base', 'opts': {'depth': 1}},
                  'tags': {}, 'todo': [{'t': 'first', 'done': False}],
                  'n': 0, 'rate': 1.5, 'on': True}, 'ab' * 16)
    for c in range(n_changes - 1):
        k = f'k{int(rng.integers(0, 12))}'

        def edit(r, c=c, k=k):
            r['cfg']['opts'][k] = f'value-{c}'
            r['tags'][k] = float(c) if c % 3 else c
            r['n'] = c
            if c % 4 == 0:
                r['todo'].append({'t': f'task-{c}', 'done': False})
            else:
                r['todo'][0]['done'] = c % 2 == 1
        d = am.change(d, edit)
    changes = [bytes(b) for b in am.get_all_changes(d)]
    per_doc = [list(changes) for _ in range(n_docs)]
    n_total = n_changes * n_docs
    # ops per change differs from the flat-int headline's 1: report it so
    # the changes/s gap between the two seams can be read per-op
    from automerge_tpu.columnar import decode_change
    ops_per_change = sum(len(decode_change(b)['ops'])
                         for b in changes) / len(changes)

    def run():
        fleet = DocFleet(doc_capacity=n_docs, key_capacity=64)
        handles = init_docs(n_docs, fleet)
        handles, _ = apply_changes_docs(handles, per_doc, mirror=False)
        assert fleet.metrics.fallbacks == 0 and fleet.metrics.turbo_calls
        if fleet.state is not None:
            jax.block_until_ready(fleet.state.winners)

    run()
    rate = median_rate(run, n_total, reps=3)
    host_docs = max(n_docs // 50, 1)

    def run_host():
        for _ in range(host_docs):
            backend = Backend.init()
            Backend.apply_changes(backend, changes)
    host = median_rate(run_host, n_changes * host_docs, reps=3)
    return rate, host, ops_per_change


def bench_native_save(n_changes=200, seed=0):
    """Mirror-free native save (C++ change-log replay + canonical encode)
    vs the host OpSet replay + Python encode, same change log. Returns
    (native saves/s, host saves/s) or (None, host) without the codec."""
    from automerge_tpu import native
    from automerge_tpu import backend as Backend
    from automerge_tpu.backend.op_set import OpSet
    from automerge_tpu.columnar import encode_change, decode_change_meta
    rng = np.random.default_rng(seed)
    A = 'cc' * 16
    changes, heads = [], []
    for c in range(n_changes):
        ops = [{'action': 'set', 'obj': '_root', 'key': f'k{int(k)}',
                'value': int(rng.integers(0, 1 << 20)), 'datatype': 'int',
                'pred': []} for k in rng.integers(0, 64, size=8)]
        buf = encode_change({'actor': A, 'seq': c + 1, 'startOp': c * 8 + 1,
                             'time': 0, 'message': '', 'deps': heads,
                             'ops': ops})
        heads = [decode_change_meta(buf, True)['hash']]
        changes.append(buf)

    def run_host():
        ops = OpSet()
        ops.apply_changes(list(changes))
        ops.binary_doc = None
        ops.save()
    host = median_rate(run_host, 1, reps=3)
    if not native.available():
        return None, host

    def run_native():
        assert native.build_document(changes, heads) is not None
    return median_rate(run_native, 1, reps=3), host


def _fence():
    """Collect cyclic garbage between bench sections. Fleets sit in
    engine<->fleet reference cycles, so a finished section's device pools
    and multi-million-object host heap stay live until a gen-2 collection;
    left to chance, the NEXT section pays for them (gen-2 pauses mid-rep,
    device memory pressure). The round-5 on-chip run measured the mixed
    seam 10x slower inside the full suite than standalone for exactly
    this cross-section bleed."""
    import gc
    gc.collect()


# ---------------------------------------------------------------------------
# Sections: each runs standalone (BENCH_SECTION=<name>) or as part of the
# full pass, writes its results into R, and prints its own stderr lines.
# ---------------------------------------------------------------------------

R = {}
SECTIONS = {}
# section name -> R key whose full-run and standalone values must agree
# within 2x (the BENCH_SANITY contract; VERDICT round-5 weak #7)
SANITY_KEYS = {'seam': 'seam_rate', 'registers': 'reg_rate',
               'mixed': 'mixed_rate', 'seam_dense': 'seam_dense_rate',
               'observability': 'obs_off_rate',
               'service': 'service_clean_rps',
               # recovery rate, not materialize-us: the latter is NaN on
               # hosts without the native codec, which the sanity ratio
               # would turn into an unconditional FAIL
               'storage': 'storage_recovery_docs_per_s',
               # park throughput over the mmap arena: a pure host+disk
               # rate, stable across run order
               'storage_tier': 'tier_park_docs_per_s',
               'query': 'query_materialize_docs_per_s',
               # render throughput, not the overhead percentage: the
               # paired delta is a noise-sensitive difference that can
               # legitimately cross zero run to run
               'slo': 'slo_render_series_per_s',
               # the paced aggregate rate: cadence-bound, so stable
               # across run order by construction
               'shards': 'shards_rps_4',
               # the perf plane's throughput twin of obs_off_rate (the
               # overhead percentage itself is a noise-sensitive paired
               # delta, same reason the slo section pins throughput)
               'perf': 'perf_off_rate',
               # the ISSUE-20 acceptance number itself: a paired delta,
               # so `_pct` keys compare by ABSOLUTE difference (<= 2
               # percentage points) rather than the 2x ratio — a paired
               # overhead near zero legitimately crosses zero run to
               # run, which would blow up a max/min ratio
               'control': 'control_overhead_pct',
               # the gate's deterministic synthetic self-test: 1 in any
               # healthy tree, full-run and standalone alike
               'regress': 'regress_check_ok',
               # the depth-flatness RATIO (two p50s from one process):
               # ~1.0 in a healthy tree and self-normalizing against box
               # load, unlike the raw millisecond legs
               'frontier': 'frontier_depth_ratio',
               # links served per second at the top leg: a throughput
               # rate, stable across run order like the other rates
               'sync_fabric': 'fabric_links_per_s'}


def section(name):
    def deco(fn):
        SECTIONS[name] = fn
        return fn
    return deco


def _env(name, default):
    return int(os.environ.get(name, default))


def _interval_union_us(spans):
    """Total microseconds covered by the union of (ts, ts+dur) intervals."""
    ivs = sorted((s['ts'], s['ts'] + s['dur']) for s in spans)
    total = 0.0
    cur_lo = cur_hi = None
    for lo, hi in ivs:
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        total += cur_hi - cur_lo
    return total


def _measure_pipeline_overlap(n_docs, n_keys, sub_batches):
    """Run ONE pipelined seam batch under the span rig and measure, from
    the exported Perfetto trace, how much parse wall-clock (native_parse /
    per-slice parse_chunk spans, background + pool threads) overlaps the
    gate/commit/stage/dispatch phases of the PREVIOUS sub-batch (main
    thread). Returns (overlap_ms, dispatch_overlap_ms, parse_ms,
    main_thread_parse_stall_ms, trace_path or None) — the acceptance
    evidence that sub-batch k+1's parse tiles under sub-batch k's
    pipeline tail instead of serializing behind it."""
    from automerge_tpu import observability as obs
    from automerge_tpu.columnar import encode_change, decode_change_meta
    from automerge_tpu.fleet.backend import (
        DocFleet, init_docs, apply_changes_docs_pipelined)
    rng = np.random.default_rng(7)
    actors = ['aa' * 16, 'bb' * 16]
    changes, heads = [], []
    seqs = [0, 0]
    for c in range(20):
        a = c % 2
        seqs[a] += 1
        buf = encode_change({
            'actor': actors[a], 'seq': seqs[a], 'startOp': c + 1,
            'time': 0, 'message': '', 'deps': heads,
            'ops': [{'action': 'set', 'obj': '_root',
                     'key': f'k{int(rng.integers(0, n_keys))}',
                     'value': int(rng.integers(1, 1 << 20)),
                     'datatype': 'int', 'pred': []}]})
        heads = [decode_change_meta(buf, True)['hash']]
        changes.append(buf)
    per_doc = [list(changes) for _ in range(n_docs)]
    # warmup universe: compile the dispatch shapes so the traced batch
    # shows steady-state phase widths, not one giant XLA compile
    warm = DocFleet(doc_capacity=n_docs, key_capacity=n_keys + 1)
    apply_changes_docs_pipelined(init_docs(n_docs, warm), per_doc,
                                 sub_batches=sub_batches)
    del warm
    _fence()
    fleet = DocFleet(doc_capacity=n_docs, key_capacity=n_keys + 1)
    handles = init_docs(n_docs, fleet)
    obs.enable()
    obs.clear_spans()
    apply_changes_docs_pipelined(handles, per_doc, sub_batches=sub_batches)
    trace_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              'traces', 'seam_pipeline_trace.json')
    try:
        events = obs.export_chrome_trace(trace_path)
    except OSError:
        events = obs.export_chrome_trace()
        trace_path = None
    obs.disable()
    parse_spans = [e for e in events
                   if e['name'] in ('native_parse', 'parse_chunk')]

    def overlap_with(names):
        civs = sorted((s['ts'], s['ts'] + s['dur']) for s in events
                      if s['name'] in names)
        total = 0.0
        for p in parse_spans:
            lo, hi = p['ts'], p['ts'] + p['dur']
            if p['name'] == 'parse_chunk':
                continue   # slices nest inside native_parse: no double count
            for clo, chi in civs:
                o = min(hi, chi) - max(lo, clo)
                if o > 0:
                    total += o
        return total

    parse_us = _interval_union_us(parse_spans)
    # A prefetched parse can only coincide with the PREVIOUS sub-batch
    # (its own gate/commit start after it completes), so overlap with
    # these phase names IS overlap with sub-batch k's pipeline tail.
    overlap_us = overlap_with(('turbo_gate', 'turbo_commit', 'turbo_stage',
                               'turbo_dispatch'))
    dispatch_us = overlap_with(('turbo_dispatch',))
    # "No serial gap": with the parse prefetched, the main thread's
    # turbo_parse phase collapses to a table lookup for every sub-batch
    # after the first — this is the direct evidence the parse no longer
    # serializes the pipeline (the round-6 4-chunk path's failure mode).
    stalls = sorted(e['dur'] for e in events if e['name'] == 'turbo_parse')
    stall_us = sum(stalls[:-1]) if len(stalls) > 1 else 0.0
    del fleet, handles, per_doc
    _fence()
    return (overlap_us / 1000.0, dispatch_us / 1000.0, parse_us / 1000.0,
            stall_us / 1000.0, trace_path)


@section('seam')
def _sec_seam():
    # HEADLINE: end-to-end Backend seam (wire -> hash graph + causal gate ->
    # native parse -> device merge), median over reps. Measured single-shot
    # AND pipelined (the native multi-core parse of sub-batch k+1
    # overlapping the host commit + device dispatch of sub-batch k via
    # apply_changes_docs_pipelined); the headline is the better of the
    # two — both are the identical public pipeline.
    # 10k docs = the BASELINE.json north-star config ("changes/sec on a
    # 10k-doc concurrent-merge batch")
    n_keys = _env('BENCH_KEYS', 1000)
    seam_docs = _env('BENCH_SEAM_DOCS', 10000)
    seam_chunks = _env('BENCH_SEAM_CHUNKS', 4)
    seam_rate_1, info1 = bench_backend_pipeline(seam_docs, n_keys, 20)
    seam_rate_k, infok = bench_backend_pipeline(seam_docs, n_keys, 20,
                                                chunks=seam_chunks)
    seam_rate = max(seam_rate_1, seam_rate_k)
    # Cross-round continuity: rounds 1-3 measured the seam at 2000 docs
    seam_rate_2k, _ = bench_backend_pipeline(2000, n_keys, 20)
    from automerge_tpu import native as _native
    R.update(seam_rate=seam_rate, seam_rate_1=seam_rate_1,
             seam_rate_k=seam_rate_k, seam_rate_2k=seam_rate_2k,
             seam_docs=seam_docs, seam_native_threads=_native.native_threads(),
             seam_init_dispatches=info1['init_dispatches'],
             seam_dispatches_per_round=info1['apply_dispatches'] /
             info1['rounds'],
             seam_pipeline_dispatches_per_round=infok['apply_dispatches'] /
             infok['rounds'])
    print(f'# HEADLINE backend-seam end-to-end (turbo, incl. hash graph, '
          f'{seam_docs}-doc north-star config, '
          f'{_native.native_threads()} native threads): '
          f'{seam_rate:.0f} changes/s (median of {REPS}; single-dispatch '
          f'{seam_rate_1:.0f}, {seam_chunks}-sub-batch pipelined '
          f'{seam_rate_k:.0f}; rounds 1-3 config at 2000 docs: '
          f'{seam_rate_2k:.0f})', file=sys.stderr)
    print(f'# seam dispatch accounting ({seam_docs} docs): '
          f'{info1["init_dispatches"]} dispatches for init_docs, '
          f'{info1["apply_dispatches"] / info1["rounds"]:.1f} '
          f'dispatches/apply round single-shot, '
          f'{infok["apply_dispatches"] / infok["rounds"]:.1f} per pipelined '
          f'sub-batch (O(1), size-independent)',
          file=sys.stderr)
    # Overlap proof: the span-rig trace must show sub-batch k+1's parse
    # running concurrently with sub-batch k's pipeline tail — no serial
    # gap (ISSUE 6 acceptance). On this box the prefetched parse usually
    # finishes INSIDE the previous gate phase (hidden even before the
    # dispatch); the dispatch-phase share is reported separately.
    overlap_ms, dispatch_ms, parse_ms, stall_ms, trace_path = \
        _measure_pipeline_overlap(seam_docs, n_keys, seam_chunks)
    R.update(pipeline_overlap_ms=overlap_ms,
             pipeline_dispatch_overlap_ms=dispatch_ms,
             pipeline_parse_ms=parse_ms,
             pipeline_parse_stall_ms=stall_ms)
    print(f'# pipelined-parse overlap: {overlap_ms:.1f} ms of sub-batch '
          f'k+1 parse concurrent with sub-batch k\'s gate/commit/dispatch '
          f'({dispatch_ms:.1f} ms of it under the device-dispatch phase; '
          f'parse total {parse_ms:.1f} ms, main-thread parse stall past '
          f'sub-batch 0: {stall_ms:.2f} ms = no serial gap'
          f'{", trace " + trace_path if trace_path else ""})',
          file=sys.stderr)


@section('seam_commit')
def _sec_seam_commit():
    # Host commit-phase breakdown (ISSUE-12 "melt the serial floor"):
    # ONE steady-state seam batch under the span rig, tiled into the
    # turbo phase spans (setup/parse/gate/commit/stage/dispatch — they
    # tile the batch interval with no unattributed gap), reported as ms
    # per phase. The COMMIT phase is the columnar scatter (struct-of-
    # arrays doc state + lazily-folded log segments) and the GATE phase
    # is the native am_turbo_gate call — the two serial-floor terms this
    # round melts; the per-doc fallback counter proves the fast path ran
    # with ZERO per-doc commit-loop iterations, and the dispatch count
    # pins the O(1)-dispatch contract alongside the phase widths.
    from automerge_tpu import observability as obs
    from automerge_tpu.columnar import encode_change, decode_change_meta
    from automerge_tpu.fleet.backend import (
        DocFleet, init_docs, apply_changes_docs)
    import jax
    n_keys = _env('BENCH_KEYS', 1000)
    n_docs = _env('BENCH_SEAM_DOCS', 10000)
    rng = np.random.default_rng(11)
    actors = ['aa' * 16, 'bb' * 16]
    changes, heads = [], []
    seqs = [0, 0]
    for c in range(20):
        a = c % 2
        seqs[a] += 1
        buf = encode_change({
            'actor': actors[a], 'seq': seqs[a], 'startOp': c + 1,
            'time': 0, 'message': '', 'deps': heads,
            'ops': [{'action': 'set', 'obj': '_root',
                     'key': f'k{int(rng.integers(0, n_keys))}',
                     'value': int(rng.integers(1, 1 << 20)),
                     'datatype': 'int', 'pred': []}]})
        heads = [decode_change_meta(buf, True)['hash']]
        changes.append(buf)
    per_doc = [list(changes) for _ in range(n_docs)]
    # warmup universe: steady-state phase widths, not XLA compiles
    warm = DocFleet(doc_capacity=n_docs, key_capacity=n_keys + 1)
    apply_changes_docs(init_docs(n_docs, warm), per_doc, mirror=False)
    jax.block_until_ready(warm.state.winners)
    del warm
    _fence()
    fleet = DocFleet(doc_capacity=n_docs, key_capacity=n_keys + 1)
    handles = init_docs(n_docs, fleet)
    d0 = fleet.metrics.dispatches
    f0 = fleet.metrics.turbo_commit_fallback_docs
    obs.enable()
    obs.clear_spans()
    t0 = time.perf_counter()
    apply_changes_docs(handles, per_doc, mirror=False)
    jax.block_until_ready(fleet.state.winners)
    wall_ms = (time.perf_counter() - t0) * 1000.0
    obs.disable()
    phases = {}
    for s in obs.iter_spans():
        if s['name'].startswith('turbo_'):
            key = s['name'][len('turbo_'):]
            phases[key] = phases.get(key, 0.0) + s['dur_ns'] / 1e6
    dispatches = fleet.metrics.dispatches - d0
    fallback_docs = fleet.metrics.turbo_commit_fallback_docs - f0
    commit_ms = phases.get('commit', 0.0)
    rate = n_docs * 20 / (wall_ms / 1000.0)
    R.update(seam_commit_rate=rate,
             seam_commit_phase_ms={k: round(v, 2)
                                   for k, v in sorted(phases.items())},
             seam_commit_wall_ms=round(wall_ms, 1),
             seam_commit_ms=round(commit_ms, 2),
             seam_commit_dispatches=dispatches,
             seam_commit_fallback_docs=fallback_docs)
    breakdown = ', '.join(f'{k} {v:.1f}' for k, v in
                          sorted(phases.items(),
                                 key=lambda kv: -kv[1]))
    print(f'# seam_commit phase breakdown ({n_docs} docs x 20 changes, '
          f'one traced steady-state batch, {wall_ms:.0f} ms wall): '
          f'{breakdown} ms; commit phase {commit_ms:.1f} ms, '
          f'{dispatches} device dispatch(es), '
          f'{fallback_docs} per-doc commit-loop fallback iterations '
          f'(columnar fast path = 0)', file=sys.stderr)


@section('seam_threads')
def _sec_seam_threads():
    # Thread-scaling sweep: the single-shot seam at a 1/2/4-lane native
    # parse pool (the multi-core contract's measured curve; BASELINE.md
    # "Multi-core contract"). Determinism makes the pool width a pure
    # perf knob, so the SAME workload runs at each width. Widths past the
    # machine's cores are still recorded — the curve's flattening point
    # is the evidence of core saturation (this box reports os.cpu_count
    # in the JSON for that reason).
    from automerge_tpu import native as _native
    n_keys = _env('BENCH_KEYS', 1000)
    seam_docs = _env('BENCH_SEAM_DOCS', 10000)
    sweep = {}
    default = _native.native_threads()
    for t in (1, 2, 4):
        _native.set_native_threads(t)
        rate, _ = bench_backend_pipeline(seam_docs, n_keys, 20)
        sweep[str(t)] = rate
        _fence()
    _native.set_native_threads(default)
    R.update(seam_thread_scaling=sweep, bench_cpus=os.cpu_count())
    base = sweep['1']
    scaled = ', '.join(f'{t}T {r:.0f} ({r / base:.2f}x)'
                       for t, r in sweep.items())
    print(f'# seam thread-scaling sweep ({seam_docs} docs, single-shot, '
          f'{os.cpu_count()} cpus visible): {scaled}', file=sys.stderr)


@section('host')
def _sec_host():
    # Host reference engine on the same workload shape (rate-based).
    # 500 docs x 20 changes (round-4 VERDICT weak #3): the host engine
    # is linear per doc — measured flat between 20 and 500 docs — but a
    # 20-doc extrapolation was not apples-to-apples with the 10k-doc
    # fleet run; 500 docs at the seam's exact per-doc change count keeps
    # the denominator honest.
    host_rate, _ = bench_host(_env('BENCH_HOST_DOCS', 500),
                              _env('BENCH_KEYS', 1000), 1, 20)
    R['host_rate'] = host_rate
    print(f'# host reference engine (CPython, full pipeline): '
          f'{host_rate:.0f} changes/s', file=sys.stderr)


@section('seam_text')
def _sec_seam_text():
    # End-to-end text editing through the seam (config 2, honest number)
    seam_text_rate, host_text_rate = bench_backend_text(
        _env('BENCH_SEAM_TEXT_DOCS', 200), _env('BENCH_SEAM_TEXT_LEN', 512))
    R.update(seam_text_rate=seam_text_rate, host_text_rate=host_text_rate)
    print(f'# backend-seam text editing end-to-end: '
          f'{seam_text_rate:.0f} ops/s (median of {REPS}) vs host '
          f'{host_text_rate:.0f} ops/s '
          f'({seam_text_rate / host_text_rate:.1f}x)', file=sys.stderr)


@section('kernel_merge')
def _sec_kernel_merge():
    # KERNEL-ONLY numbers (device ceilings on pre-built batches — NOT
    # end-to-end; decode/hashing excluded):
    fleet_rate, _ = bench_fleet(_env('BENCH_DOCS', 10000),
                                _env('BENCH_KEYS', 1000),
                                _env('BENCH_ROUNDS', 10),
                                _env('BENCH_OPS', 100))
    R['fleet_rate'] = fleet_rate
    print(f'# kernel-only device merge (pre-built batches): '
          f'{fleet_rate:.0f} ops/s', file=sys.stderr)


@section('pallas')
def _sec_pallas():
    pallas_rate, pallas_variant = bench_pallas_merge(
        _env('BENCH_DOCS', 10000), _env('BENCH_KEYS', 1000),
        _env('BENCH_ROUNDS', 10), _env('BENCH_OPS', 100))
    R.update(pallas_rate=pallas_rate, pallas_variant=pallas_variant)
    if pallas_rate is not None:
        vs = f' ({pallas_rate / R["fleet_rate"]:.2f}x the jnp scatter ' \
             f'path)' if R.get('fleet_rate') else ''
        print(f'# fused pallas merge kernel ({pallas_variant}, '
              f'interpret=False, differentially checked): '
              f'{pallas_rate:.0f} ops/s{vs}', file=sys.stderr)


@section('kernel_pipe')
def _sec_kernel_pipe():
    pipe_rate, _ = bench_pipeline(_env('BENCH_PIPE_DOCS', 500),
                                  _env('BENCH_KEYS', 1000), 20)
    R['pipe_rate'] = pipe_rate
    print(f'# kernel-only pipeline (native decode, no hash graph): '
          f'{pipe_rate:.0f} changes/s', file=sys.stderr)


@section('kernel_text')
def _sec_kernel_text():
    text_rate, _ = bench_text(_env('BENCH_TEXT_DOCS', 2000),
                              _env('BENCH_TEXT_LEN', 512))
    R['text_rate'] = text_rate
    print(f'# kernel-only sequence engine (packed text traces): '
          f'{text_rate:.0f} ops/s', file=sys.stderr)


@section('bloom')
def _sec_bloom():
    # Config 4: sync Bloom filters, device fleet vs per-peer host loop
    bloom_dev, bloom_host = bench_sync_bloom(
        _env('BENCH_BLOOM_DOCS', 10000), _env('BENCH_BLOOM_HASHES', 32))
    R.update(bloom_dev=bloom_dev, bloom_host=bloom_host)
    print(f'# sync bloom build+probe: device {bloom_dev:.0f} hashes/s, '
          f'host {bloom_host:.0f} hashes/s', file=sys.stderr)


@section('sync_driver')
def _sec_sync_driver():
    # Batched sync driver: one generate round over the whole peer fleet
    n = _env('BENCH_SYNCDRV_DOCS', 10000)
    syncdrv_batched, syncdrv_host, syncdrv_disp = bench_sync_driver(n)
    R.update(syncdrv_batched=syncdrv_batched, syncdrv_host=syncdrv_host,
             syncdrv_dispatches_per_round=syncdrv_disp)
    print(f'# batched sync driver, one {n}-peer generate round: '
          f'{syncdrv_batched:.0f} docs/s batched vs {syncdrv_host:.0f} '
          f'docs/s host loop ({syncdrv_batched / syncdrv_host:.1f}x); '
          f'{syncdrv_disp} Bloom device dispatches/round (O(1), '
          f'size-independent)', file=sys.stderr)


@section('faults')
def _sec_faults():
    # Fault-containment cost + health-counter reporting: one quarantine
    # round (N docs, 2 poisoned) vs the clean batch, and one lossy-wire
    # sync; per-round deltas of every registered health counter.
    from automerge_tpu import observability
    from automerge_tpu.columnar import encode_change
    from automerge_tpu.fleet import backend as fleet_backend
    from automerge_tpu.fleet.backend import DocFleet, init_docs
    n = _env('BENCH_FAULT_DOCS', 2000)

    def workload(count):
        # actors cycle under the 256-per-fleet cap; one change per doc
        return [[encode_change({
            'actor': f'{d % 128:04x}' * 4, 'seq': 1, 'startOp': 1,
            'time': 0, 'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                     'value': d, 'datatype': 'int', 'pred': []}]})]
            for d in range(count)]

    warm = DocFleet()                      # JIT warmup for the dispatch shapes
    fleet_backend.apply_changes_docs(init_docs(n, warm), workload(n),
                                     mirror=False)

    fleet = DocFleet()
    handles = init_docs(n, fleet)
    per_doc = workload(n)
    for bad in (1, n // 2):
        buf = bytearray(per_doc[bad][0])
        buf[10] ^= 0xFF
        per_doc[bad] = [bytes(buf)]
    h0 = observability.health_counts()
    start = time.perf_counter()
    _, _, errors = fleet_backend.apply_changes_docs(
        handles, per_doc, mirror=False, on_error='quarantine')
    quarantine_rate = n / (time.perf_counter() - start)
    health_delta = {k: v for k, v in
                    observability.health_delta(h0).items() if v}

    fleet2 = DocFleet()
    handles2 = init_docs(n, fleet2)
    clean_doc = workload(n)
    start = time.perf_counter()
    fleet_backend.apply_changes_docs(handles2, clean_doc, mirror=False)
    clean_rate = n / (time.perf_counter() - start)
    R.update(quarantine_rate=quarantine_rate, clean_rate=clean_rate,
             quarantine_health=health_delta)
    print(f'# fault containment, {n}-doc round with 2 poisoned: '
          f'{quarantine_rate:.0f} docs/s quarantined vs {clean_rate:.0f} '
          f'docs/s clean ({quarantine_rate / clean_rate:.2f}x); '
          f'health counters this round: {health_delta} '
          f'(K rejected docs cost one host re-validate, zero extra '
          f'dispatches)', file=sys.stderr)


@section('durability')
def _sec_durability():
    # Crash-safe durability cost: journaled vs bare apply throughput at
    # the 10k-doc seam (the ISSUE-3 budget is <= 15% overhead), plus
    # recovery wall-clock vs fleet size (snapshot-chain stitch +
    # journal-suffix replay through the quarantining batch apply;
    # includes recovery's closing O(replayed) re-journal — the full
    # return-to-serving cost. The storage section benches this at the
    # crashtest scale with rep medians).
    import shutil
    import tempfile
    from automerge_tpu.columnar import encode_change
    from automerge_tpu.fleet import backend as fleet_backend
    from automerge_tpu.fleet.backend import DocFleet, init_docs
    from automerge_tpu.fleet.durability import DurableFleet
    n = _env('BENCH_DUR_DOCS', 10000)

    def workload(count):
        return [[encode_change({
            'actor': f'{d % 128:04x}' * 4, 'seq': 1, 'startOp': 1,
            'time': 0, 'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                     'value': d, 'datatype': 'int', 'pred': []}]})]
            for d in range(count)]

    warm = DocFleet()                  # JIT warmup for the dispatch shapes
    fleet_backend.apply_changes_docs(init_docs(n, warm), workload(n),
                                     mirror=False)
    del warm
    _fence()

    # PAIRED interleaved reps: single-shot rates on this box swing 4-40%
    # with GC/page-cache state, so the overhead claim uses the median of
    # per-rep (on - off)/off deltas — pairing cancels the drift an
    # unpaired median-of-rates amplifies. The first pair is warmup and
    # discarded, and the rep floor is raised above the global default:
    # per-rep deltas here spread -20..+50% on a busy box, and even a
    # 9-rep median of that distribution wobbles by ~10 points.
    dur_reps = max(3 * REPS, 15)
    root = tempfile.mkdtemp(prefix='bench-dur-')
    try:
        off_rates, on_rates, strict_rates = [], [], []
        deltas, strict_deltas = [], []

        def settle():
            # flush background writeback OUTSIDE the timed regions: a
            # previous rep's dirty journal pages otherwise steal IO/CPU
            # from the next timed section (measured as fake overhead)
            _fence()
            try:
                os.sync()
            except (AttributeError, OSError):
                pass

        for rep in range(dur_reps + 1):
            fleet = DocFleet()
            handles = init_docs(n, fleet)
            per_doc = workload(n)
            settle()
            start = time.perf_counter()
            fleet_backend.apply_changes_docs(handles, per_doc, mirror=False)
            off_s = time.perf_counter() - start
            del fleet, handles, per_doc

            # group-commit config (fsync batching, the deployable default
            # for a batched seam: one fsync per fsync_bytes of journal)
            mgr = DurableFleet(os.path.join(root, f'seam{rep}'),
                               compact_bytes=1 << 40,  # no mid-run compact
                               fsync_bytes=4 << 20)
            handles = mgr.init_docs(n)
            per_doc = workload(n)
            settle()
            start = time.perf_counter()
            fleet_backend.apply_changes_docs(handles, per_doc,
                                             mirror=False)
            on_s = time.perf_counter() - start
            mgr.close()
            del mgr, handles, per_doc
            if rep == 0:
                continue
            off_rates.append(n / off_s)
            on_rates.append(n / on_s)
            deltas.append(on_s - off_s)
        # strict config: fsync on EVERY group commit (zero loss window).
        # Benched in its own loop against the paired baseline medians —
        # interleaving it into the A/B pairs entangles its fsyncs with
        # the other configs' writeback on ordered-mode filesystems.
        off_s_med = float(np.median([n / r for r in off_rates]))
        for rep in range(max(dur_reps // 2, 3) + 1):
            mgr = DurableFleet(os.path.join(root, f'strict{rep}'),
                               compact_bytes=1 << 40)
            handles = mgr.init_docs(n)
            per_doc = workload(n)
            settle()
            start = time.perf_counter()
            fleet_backend.apply_changes_docs(handles, per_doc,
                                             mirror=False)
            strict_s = time.perf_counter() - start
            mgr.close()
            del mgr, handles, per_doc
            if rep == 0:
                continue
            strict_rates.append(n / strict_s)
            strict_deltas.append(strict_s - off_s_med)
        off_rate = float(np.median(off_rates))
        on_rate = float(np.median(on_rates))
        strict_rate = float(np.median(strict_rates))
        # overhead = median ABSOLUTE per-pair delta over the median bare
        # time: a per-rep ratio explodes whenever the off-leg of one pair
        # stalls (this box stalls whole reps by 2-5x), while the paired
        # difference cancels shared drift and the median kills outliers
        off_med_s = n / off_rate
        overhead = float(np.median(deltas)) / off_med_s * 100.0
        strict_overhead = float(np.median(strict_deltas)) / off_med_s * 100.0

        recovery = {}
        for size in sorted({max(n // 10, 100), n}):
            path = os.path.join(root, f'rec{size}')
            m = DurableFleet(path, compact_bytes=1 << 40)
            hs = m.init_docs(size)
            hs, _p = m.apply_changes(hs, workload(size), on_error='raise')
            m.checkpoint()
            hs, _p = m.apply_changes(hs, [
                [encode_change({
                    'actor': f'{d % 128:04x}' * 4, 'seq': 2, 'startOp': 2,
                    'time': 0, 'message': '',
                    'deps': fleet_backend.get_heads(hs[d]),
                    'ops': [{'action': 'set', 'obj': '_root', 'key': 'k2',
                             'value': d, 'datatype': 'int', 'pred': []}]})]
                for d in range(size)], on_error='raise')
            m.close()
            start = time.perf_counter()
            m2, _rec, report = DurableFleet.recover(path)
            recovery[size] = time.perf_counter() - start
            # guard the measurement itself: recovery must have loaded the
            # snapshot AND replayed the journal suffix (a frozen-handle
            # bug here once timed snapshot-load only)
            assert report.snapshot_docs == size and \
                report.replayed_records == size and not \
                report.quarantined, report
            m2.close()
            _fence()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    R.update(dur_on_rate=on_rate, dur_off_rate=off_rate,
             dur_strict_rate=strict_rate,
             dur_overhead_pct=overhead,
             dur_strict_overhead_pct=strict_overhead,
             **{f'dur_recovery_{size}_s': secs
                for size, secs in recovery.items()})
    rec_str = ', '.join(f'{size} docs in {secs:.2f}s '
                        f'({size / secs:.0f} docs/s)'
                        for size, secs in sorted(recovery.items()))
    print(f'# durability: journal-on {on_rate:.0f} docs/s vs journal-off '
          f'{off_rate:.0f} docs/s at the {n}-doc seam '
          f'({overhead:+.1f}% overhead group-commit, budget 15%; '
          f'{strict_overhead:+.1f}% with fsync-every-commit at '
          f'{strict_rate:.0f} docs/s); recovery (snapshot load + '
          f'quarantining replay + re-journal): {rec_str}',
          file=sys.stderr)


@section('storage')
def _sec_storage():
    # Delta+main storage engine: (a) materialize cost — the native
    # change-list extractor (codec.cpp am_extract_changes) vs the Python
    # decode_document + encode_change round trip it replaces, PAIRED
    # interleaved reps over the same chunk set (BENCH_r08 methodology;
    # the acceptance bar is >= 5x vs the recorded ~700us/doc);
    # (b) durability recovery throughput at the crashtest scale —
    # snapshot load + journal-suffix replay + the O(replayed) re-journal
    # finish (acceptance >= 20k docs/s); (c) main-store residency —
    # per-doc host overhead from MainStore.memory_stats (acceptance:
    # measurably below the ~3.3 KB/doc of in-fleet parked residency).
    import shutil
    import tempfile
    from automerge_tpu import native
    from automerge_tpu.columnar import (decode_document, encode_change,
                                        decode_change_meta)
    from automerge_tpu.fleet import backend as fleet_backend
    from automerge_tpu.fleet.backend import DocFleet, init_docs
    from automerge_tpu.fleet.durability import DurableFleet
    from automerge_tpu.fleet.storage import StorageEngine

    n_docs = _env('BENCH_STORAGE_DOCS', 512)
    n_changes = _env('BENCH_STORAGE_CHANGES', 8)

    # one fleet of linear-history docs -> parked chunks
    fleet = DocFleet()
    handles = init_docs(n_docs, fleet)
    heads = [[] for _ in range(n_docs)]
    for c in range(n_changes):
        per_doc = []
        for d in range(n_docs):
            buf = encode_change({
                'actor': f'{d % 128:04x}' * 4, 'seq': c + 1,
                'startOp': 2 * c + 1, 'time': 0, 'message': '',
                'deps': heads[d],
                'ops': [{'action': 'set', 'obj': '_root', 'key': f'k{c}',
                         'value': d * 1000 + c, 'datatype': 'int',
                         'pred': []},
                        {'action': 'set', 'obj': '_root', 'key': 'hot',
                         'value': c, 'datatype': 'int', 'pred': []}]})
            heads[d] = [decode_change_meta(buf, True)['hash']]
            per_doc.append([buf])
        handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                      mirror=False)
    chunks = [bytes(h['state'].save()) for h in handles]
    del fleet, handles
    _fence()

    # ---- (a) materialize: native extract vs Python decode+re-encode ----
    have_native = native.available()
    nat_times, py_times = [], []
    py_sample = max(n_docs // 8, 32)
    for rep in range(max(REPS, 5) + 1):
        if have_native:
            start = time.perf_counter()
            out = native.extract_changes(chunks)
            nat_s = time.perf_counter() - start
            assert out is not None and all(r is not None for r in out), \
                'extractor bailed on bench chunks'
        else:
            nat_s = float('nan')
        start = time.perf_counter()
        for chunk in chunks[:py_sample]:
            [encode_change(ch) for ch in decode_document(chunk)]
        py_s = time.perf_counter() - start
        if rep == 0:
            continue
        nat_times.append(nat_s / n_docs * 1e6)
        py_times.append(py_s / py_sample * 1e6)
    nat_us = float(np.median(nat_times)) if have_native else float('nan')
    py_us = float(np.median(py_times))
    speedup = py_us / nat_us if have_native else float('nan')

    # ---- (b) recovery throughput at the crashtest scale ----
    rec_n = _env('BENCH_STORAGE_RECOVERY_DOCS', 10000)
    root = tempfile.mkdtemp(prefix='bench-storage-')
    try:
        path = os.path.join(root, 'rec')
        m = DurableFleet(path, compact_bytes=1 << 40,
                         fsync_bytes=4 << 20)
        hs = m.init_docs(rec_n)
        per_doc = [[encode_change({
            'actor': f'{d % 128:04x}' * 4, 'seq': 1, 'startOp': 1,
            'time': 0, 'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                     'value': d, 'datatype': 'int', 'pred': []}]})]
            for d in range(rec_n)]
        hs, _p = m.apply_changes(hs, per_doc, on_error='raise')
        m.checkpoint()
        hs, _p = m.apply_changes(hs, [
            [encode_change({
                'actor': f'{d % 128:04x}' * 4, 'seq': 2, 'startOp': 2,
                'time': 0, 'message': '',
                'deps': fleet_backend.get_heads(hs[d]),
                'ops': [{'action': 'set', 'obj': '_root', 'key': 'k2',
                         'value': d, 'datatype': 'int', 'pred': []}]})]
            for d in range(rec_n)], on_error='raise')
        m.close()
        _fence()
        # median over reps, each on a fresh COPY of the directory
        # (recovery rewrites the journal generation; page-cache state is
        # shared so reps measure compute, not cold reads) — single-shot
        # recovery on this box swings ±40% with writeback state
        rec_times = []
        for rep in range(max(REPS, 5) + 1):
            dst = os.path.join(root, f'rec-rep{rep}')
            shutil.copytree(path, dst)
            _fence()
            start = time.perf_counter()
            m2, _rec, report = DurableFleet.recover(dst)
            rec_rep_s = time.perf_counter() - start
            assert report.snapshot_docs == rec_n and \
                report.replayed_records == rec_n and not \
                report.quarantined, report
            m2.close()
            shutil.rmtree(dst, ignore_errors=True)
            if rep == 0:
                continue
            rec_times.append(rec_rep_s)
        rec_s = float(np.median(rec_times))
    finally:
        shutil.rmtree(root, ignore_errors=True)
    rec_rate = rec_n / rec_s
    _fence()

    # ---- (c) main-store residency ----
    eng = StorageEngine(DocFleet())
    eng.ingest_chunks(chunks)
    stats = eng.memory_stats()
    overhead_per_doc = stats['overhead_per_doc']
    chunk_per_doc = stats['chunk_bytes'] / stats['n_docs']
    del eng
    _fence()

    R.update(storage_materialize_native_us=nat_us,
             storage_materialize_python_us=py_us,
             storage_materialize_speedup=speedup,
             storage_recovery_docs_per_s=rec_rate,
             storage_recovery_s=rec_s,
             storage_recovery_docs=rec_n,
             storage_overhead_bytes_per_doc=overhead_per_doc,
             storage_chunk_bytes_per_doc=chunk_per_doc)
    print(f'# storage: materialize {nat_us:.0f}us/doc native vs '
          f'{py_us:.0f}us/doc python ({speedup:.1f}x, {n_changes} '
          f'changes/doc); recovery {rec_n} docs in {rec_s:.2f}s '
          f'({rec_rate:.0f} docs/s); main-store residency '
          f'{overhead_per_doc:.0f} B/doc overhead + '
          f'{chunk_per_doc:.0f} B/doc chunk', file=sys.stderr)


@section('storage_tier')
def _sec_storage_tier():
    # Mmap-backed MainStore + cost-based tiering (ISSUE-15): the chunk
    # arena on disk under the RAM-resident causal index. Measures
    # (a) park (bulk ingest) throughput at BENCH_TIER_DOCS (default 1M;
    # raise to 10M for the full residency headline), with RSS growth and
    # resident-per-doc against the acceptance ceiling; (b) revive and
    # materialize_at throughput off the mapped arena, WARM page cache,
    # against a RAM-resident-arena baseline at the same batch scale
    # (acceptance: >= 0.8x); (c) the COLD leg — posix_fadvise DONTNEED
    # drops the arena's pages, major-fault delta recorded, revive
    # re-measured from actual disk.
    import shutil
    import tempfile
    from automerge_tpu.columnar import DocChunkView, decode_change_meta, \
        encode_change
    from automerge_tpu.fleet import backend as fleet_backend
    from automerge_tpu.fleet.backend import DocFleet, init_docs
    from automerge_tpu.fleet.storage import StorageEngine
    from automerge_tpu.observability.perf import page_fault_counts, \
        rss_bytes
    from automerge_tpu.query import materialize_at_docs

    n_docs = _env('BENCH_TIER_DOCS', 1_000_000)
    distinct = min(_env('BENCH_TIER_DISTINCT', 2048), n_docs)
    ram_n = min(n_docs, _env('BENCH_TIER_RAM_DOCS', 100_000))
    revive_batch = min(_env('BENCH_TIER_REVIVE', 1024), distinct)
    mat_batch = min(_env('BENCH_TIER_MAT', 256), distinct)

    # corpus: `distinct` two-change linear docs, causal rows precomputed
    # once (the arena append + lane install per doc stay honest; only
    # the header decode is memoized across the repeats)
    fleet = DocFleet()
    handles = init_docs(distinct, fleet)
    frontier = [[] for _ in range(distinct)]
    for c in range(2):
        per_doc = []
        for d in range(distinct):
            buf = encode_change({
                'actor': f'{d % 128:04x}' * 4, 'seq': c + 1,
                'startOp': c + 1, 'time': 0, 'message': '',
                'deps': frontier[d],
                'ops': [{'action': 'set', 'obj': '_root', 'key': f'k{c}',
                         'value': d * 1000 + c, 'datatype': 'int',
                         'pred': []}]})
            frontier[d] = [decode_change_meta(buf, True)['hash']]
            per_doc.append([buf])
        handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                      mirror=False)
    chunks = [bytes(h['state'].save()) for h in handles]
    rows = [(v.heads, v.clock, v.max_op, v.n_changes)
            for v in (DocChunkView(c) for c in chunks)]
    fleet_backend.free_docs(handles)
    del handles
    _fence()

    def ingest_all(eng, n):
        start = time.perf_counter()
        i = 0
        while i < n:
            k = min(distinct, n - i)
            eng.ingest_chunks(chunks[:k], rows=rows[:k])
            i += k
        return n / (time.perf_counter() - start)

    def revive_rate(eng, windows, n):
        # clamp every window into the parked id range: a mid-range
        # BENCH_TIER_DOCS must shift the legs, not KeyError the section
        max_w = max(n // revive_batch - 1, 0)
        rates = []
        for w in windows:
            w = min(w, max_w)
            ids = list(range(w * revive_batch,
                             min((w + 1) * revive_batch, n)))
            start = time.perf_counter()
            got = eng.revive(ids)
            rate = len(ids) / (time.perf_counter() - start)
            eng.repark(got, ids)       # restore the store for the next leg
            rates.append(rate)
        return float(np.median(rates))

    def mat_rate(eng, eng_fleet, base, n):
        base = max(0, min(base, n - mat_batch))
        sources = [(eng, base + i) for i in range(mat_batch)]
        heads_list = [eng.heads(base + i) for i in range(mat_batch)]
        rates = []
        for _ in range(3):
            start = time.perf_counter()
            outs = materialize_at_docs(sources, heads_list, fleet=eng_fleet)
            rates.append(mat_batch / (time.perf_counter() - start))
            fleet_backend.free_docs(outs)
        return float(np.median(rates))

    # ---- RAM-resident baseline at the sub-scale ----
    ram_fleet = DocFleet()
    ram = StorageEngine(ram_fleet)
    ram_park = ingest_all(ram, ram_n)
    ram_revive = revive_rate(ram, [1, 3, 5], ram_n)
    ram_mat = mat_rate(ram, ram_fleet, 7 * revive_batch, ram_n)
    del ram, ram_fleet
    _fence()

    # ---- disk-backed engine at full scale ----
    root = tempfile.mkdtemp(prefix='bench-tier-')
    try:
        disk_fleet = DocFleet()
        eng = StorageEngine(disk_fleet, path=os.path.join(root, 'arena'))
        eng.main.reserve(n_docs)
        rss0 = rss_bytes()[0]
        tier_park = ingest_all(eng, n_docs)
        rss1 = rss_bytes()[0]
        stats = eng.memory_stats()
        tier_revive = revive_rate(eng, [1, 3, 5], n_docs)
        tier_mat = mat_rate(eng, disk_fleet, 7 * revive_batch, n_docs)
        # cold leg: drop the arena's clean pages, read from actual disk
        mn0, mj0 = page_fault_counts()
        eng.main._arena.advise_cold()
        tier_revive_cold = revive_rate(eng, [9, 11, 13], n_docs)
        _mn1, mj1 = page_fault_counts()
        eng.close()
        del eng, disk_fleet
    finally:
        shutil.rmtree(root, ignore_errors=True)
    _fence()

    R.update(tier_docs=n_docs,
             tier_park_docs_per_s=tier_park,
             tier_revive_docs_per_s=tier_revive,
             tier_revive_cold_docs_per_s=tier_revive_cold,
             tier_materialize_docs_per_s=tier_mat,
             tier_ram_park_docs_per_s=ram_park,
             tier_ram_revive_docs_per_s=ram_revive,
             tier_ram_materialize_docs_per_s=ram_mat,
             tier_park_ratio=tier_park / ram_park,
             tier_revive_ratio=tier_revive / ram_revive,
             tier_materialize_ratio=tier_mat / ram_mat,
             tier_resident_bytes_per_doc=stats['resident_per_doc'],
             tier_rss_grow_bytes=max(0, rss1 - rss0),
             tier_disk_bytes=stats['disk_bytes'],
             tier_cold_major_faults=mj1 - mj0)
    print(f'# storage_tier: {n_docs} docs on disk — park {tier_park:.0f} '
          f'docs/s ({R["tier_park_ratio"]:.2f}x ram), revive warm '
          f'{tier_revive:.0f} docs/s ({R["tier_revive_ratio"]:.2f}x ram) '
          f'/ cold {tier_revive_cold:.0f} docs/s '
          f'({mj1 - mj0} major faults), materialize '
          f'{tier_mat:.0f} docs/s ({R["tier_materialize_ratio"]:.2f}x '
          f'ram); resident {stats["resident_per_doc"]:.0f} B/doc, RSS '
          f'+{(rss1 - rss0) / (1 << 20):.0f} MiB, arena '
          f'{stats["disk_bytes"] / (1 << 20):.0f} MiB on disk',
          file=sys.stderr)


@section('observability')
def _sec_observability():
    # Tracing cost + attribution quality at the 10k-doc seam. Two
    # numbers: (a) spans+histograms enabled vs disabled, PAIRED reps with
    # the legs ALTERNATING order each pair (a fixed on-after-off order
    # biases the median several points through allocator/GC drift on this
    # box — measured +6.5% fixed-order vs -0.4% alternating for the SAME
    # build), median paired delta over the median off time, budget <= 2%;
    # (b) phase coverage — one traced batch's Chrome trace must account
    # for >= 90% of the measured batch wall-clock across the named host
    # phases (no unattributed gap), which is what makes the trace usable
    # for the ROADMAP's parse/merge-overlap attribution work.
    from automerge_tpu import observability as obs
    from automerge_tpu.columnar import encode_change
    from automerge_tpu.fleet import backend as fleet_backend
    from automerge_tpu.fleet.backend import DocFleet, init_docs
    n = _env('BENCH_OBS_DOCS', 10000)

    def workload(count):
        return [[encode_change({
            'actor': f'{d % 128:04x}' * 4, 'seq': 1, 'startOp': 1,
            'time': 0, 'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                     'value': d, 'datatype': 'int', 'pred': []}]})]
            for d in range(count)]

    warm = DocFleet()
    fleet_backend.apply_changes_docs(init_docs(n, warm), workload(n),
                                     mirror=False)
    del warm
    _fence()

    def one(enabled):
        if enabled:
            obs.enable()
            obs.clear_spans()
        fleet = DocFleet()
        handles = init_docs(n, fleet)
        per_doc = workload(n)
        start = time.perf_counter()
        fleet_backend.apply_changes_docs(handles, per_doc, mirror=False)
        elapsed = time.perf_counter() - start
        if enabled:
            obs.disable()
        del fleet, handles, per_doc
        return elapsed

    obs_reps = max(2 * REPS, 12)
    off_times, on_times = [], []
    deltas = []
    for rep in range(obs_reps + 1):
        if rep % 2:
            on_s = one(True)
            off_s = one(False)
        else:
            off_s = one(False)
            on_s = one(True)
        if rep == 0:
            continue
        off_times.append(off_s)
        on_times.append(on_s)
        deltas.append(on_s - off_s)
    off_med = float(np.median(off_times))
    overhead = float(np.median(deltas)) / off_med * 100.0

    # phase coverage of one traced seam batch
    PHASES = ('turbo_setup', 'turbo_parse', 'turbo_gate', 'turbo_commit',
              'turbo_stage', 'turbo_dispatch', 'journal_append')
    obs.enable()
    fleet = DocFleet()
    handles = init_docs(n, fleet)
    per_doc = workload(n)
    obs.clear_spans()
    start = time.perf_counter()
    fleet_backend.apply_changes_docs(handles, per_doc, mirror=False)
    wall_ns = (time.perf_counter() - start) * 1e9
    trace_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              'traces', 'obs_host_trace.json')
    try:
        events = obs.export_chrome_trace(trace_path)
    except OSError:
        events = obs.export_chrome_trace()
        trace_path = None
    # Union of the phase intervals, NOT the sum of durations: with the
    # multi-core parse, spans from pool workers / the pipelined prefetch
    # thread legitimately run concurrently with the main thread's phases,
    # so summed durations can tile wall-time past 100% — the union keeps
    # "coverage" meaning "fraction of the batch wall accounted for".
    phase_ns = _interval_union_us(
        [e for e in events if e['name'] in PHASES]) * 1000.0
    coverage = phase_ns / wall_ns * 100.0
    hists = obs.histogram_snapshot()
    apply_p50 = (hists.get('apply_batch_s') or {}).get('p50')
    obs.disable()
    del fleet, handles, per_doc
    _fence()

    R.update(obs_off_rate=n / off_med,
             obs_on_rate=n / float(np.median(on_times)),
             obs_overhead_pct=overhead, obs_coverage_pct=coverage)
    print(f'# observability: spans+histograms on {R["obs_on_rate"]:.0f} '
          f'docs/s vs off {R["obs_off_rate"]:.0f} docs/s at the {n}-doc '
          f'seam ({overhead:+.2f}% overhead, paired alternating-order '
          f'medians, budget 2%); traced batch phase coverage '
          f'{coverage:.1f}% of wall (budget >= 90%'
          f'{", trace " + trace_path if trace_path else ""}); '
          f'apply_batch_s p50 {apply_p50}', file=sys.stderr)


@section('perf')
def _sec_perf():
    # Performance-observatory overhead (ISSUE-13 acceptance): the FULL
    # perf plane — seam baselines (histograms + per-rep drift tick),
    # kernel cost ledger, memory-watermark sampling — on vs off at the
    # seam, PAIRED reps with the legs alternating order each pair (the
    # same methodology as the observability/slo sections; fixed order
    # biases this box several points), budget <= 2%. Also dumps the
    # cost ledger for `obs_report --floor` and reports the watermark
    # highs the tiering ROADMAP item will consume.
    from automerge_tpu.columnar import decode_change_meta, encode_change
    from automerge_tpu.fleet import backend as fleet_backend
    from automerge_tpu.fleet.backend import DocFleet, init_docs
    from automerge_tpu.observability import perf as obs_perf
    from automerge_tpu.observability import hist as obs_hist
    n = _env('BENCH_PERF_DOCS', _env('BENCH_SEAM_DOCS', 10000))
    n_keys = _env('BENCH_KEYS', 1000)
    # the seam_commit workload shape (20 chained changes per doc): legs
    # run ~10x longer than the 1-change shape, which is what averages
    # this box's per-leg scheduling noise down far enough for a 2%
    # judgment to mean anything (the 1-change legs swing ±25% pair to
    # pair — the measurement lesson this PR's ledger exists to record)
    rng = np.random.default_rng(23)
    actors = ['aa' * 16, 'bb' * 16]
    changes, heads = [], []
    seqs = [0, 0]
    for c in range(20):
        a = c % 2
        seqs[a] += 1
        buf = encode_change({
            'actor': actors[a], 'seq': seqs[a], 'startOp': c + 1,
            'time': 0, 'message': '', 'deps': heads,
            'ops': [{'action': 'set', 'obj': '_root',
                     'key': f'k{int(rng.integers(0, n_keys))}',
                     'value': int(rng.integers(1, 1 << 20)),
                     'datatype': 'int', 'pred': []}]})
        heads = [decode_change_meta(buf, True)['hash']]
        changes.append(buf)

    def workload(count):
        return [list(changes) for _ in range(count)]

    warm = DocFleet(doc_capacity=n, key_capacity=n_keys + 1)
    fleet_backend.apply_changes_docs(init_docs(n, warm), workload(n),
                                     mirror=False)
    del warm
    _fence()
    reg_holder = [None]

    def one(enabled):
        if enabled:
            reg_holder[0] = obs_perf.enable_observatory()
        fleet = DocFleet(doc_capacity=n, key_capacity=n_keys + 1)
        handles = init_docs(n, fleet)
        per_doc = workload(n)
        start = time.perf_counter()
        fleet_backend.apply_changes_docs(handles, per_doc, mirror=False)
        if enabled:
            reg_holder[0].tick()
            obs_perf.sample_watermarks()
        elapsed = time.perf_counter() - start
        if enabled:
            obs_perf.disable_observatory()
            obs_hist.disable()
        del fleet, handles, per_doc
        _fence()
        return elapsed

    # POOLED paired runs (the round-14 SLO methodology, BENCH_r11: that
    # measurement's per-run medians flip-flopped [-0.26%, +3.83%] on
    # this box while the pooled-pair median held 1.9% — single-run pair
    # medians at these leg widths are exactly the noise artifact the
    # ledger exists to retire): several alternating-order pair passes,
    # every pair's delta pooled, the overhead judged on the POOLED
    # median with the per-run medians reported beside it.
    runs = _env('BENCH_PERF_RUNS', 3)
    pairs_per_run = max(REPS, 7)
    off_times, on_times, deltas = [], [], []
    run_medians = []
    for run in range(runs):
        run_deltas = []
        for rep in range(pairs_per_run + 1):
            if rep % 2:
                on_s = one(True)
                off_s = one(False)
            else:
                off_s = one(False)
                on_s = one(True)
            if rep == 0:
                continue       # each run's first pair is warmup
            off_times.append(off_s)
            on_times.append(on_s)
            run_deltas.append(on_s - off_s)
        deltas.extend(run_deltas)
        run_medians.append(float(np.median(run_deltas)))
        _fence()
    off_med = float(np.median(off_times))
    overhead = float(np.median(deltas)) / off_med * 100.0
    ledger_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               'traces', 'kernel_ledger.json')
    try:
        from automerge_tpu.observability import perf as _p
        _p.dump_ledger(ledger_path,
                       extra={'watermarks': _p.watermark_snapshot(
                           sample=False)})
    except OSError:
        ledger_path = None
    snap = obs_perf.kernel_snapshot()
    wm = obs_perf.watermark_snapshot(sample=False)
    R.update(perf_off_rate=n * 20 / off_med,
             perf_on_rate=n * 20 / float(np.median(on_times)),
             perf_overhead_pct=overhead,
             perf_kernel_dispatches=sum(r['dispatches']
                                        for r in snap.values()),
             perf_rss_high_mb=wm['high'].get('rss', 0) / 1e6,
             perf_pairs_pooled=len(deltas),
             perf_run_medians_pct=[round(m / off_med * 100.0, 2)
                                   for m in run_medians],
             perf_pair_deltas_s=[round(d, 4) for d in deltas])
    print(f'# perf plane: observatory on {R["perf_on_rate"]:.0f} '
          f'changes/s vs off {R["perf_off_rate"]:.0f} changes/s at the '
          f'{n}-doc x 20-change seam '
          f'({overhead:+.2f}% overhead, POOLED median of {len(deltas)} '
          f'alternating-order pairs over {runs} runs, per-run medians '
          f'{R["perf_run_medians_pct"]}%, budget 2%); '
          f'{R["perf_kernel_dispatches"]} '
          f'ledger-counted kernel dispatch(es), RSS high '
          f'{R["perf_rss_high_mb"]:.0f} MB'
          f'{", ledger " + ledger_path if ledger_path else ""}',
          file=sys.stderr)


@section('control')
def _sec_control():
    # Control-plane overhead (ISSUE-20 acceptance): a controller-ON
    # service pump vs the IDENTICAL episode with no controller, budget
    # <= 2%. The ON leg runs the controller in SHADOW mode: the full
    # decision path — SignalBus sample, policy hysteresis, ledger,
    # flight-recorder event per decision — with zero actuation, so the
    # paired delta isolates the controller's measurement cost. (An
    # ACTIVE controller is systematically FASTER than off on this
    # workload — raising the flooded tenants' rates converts typed
    # TenantThrottled exceptions into admitted work — which is feedback
    # the overhead number must not launder.) The episode still floods:
    # every decision window carries real decisions, not idle ticks.
    #
    # Pairing is TICK-LEVEL LOCKSTEP, not episode-level: both services
    # advance through the same tick loop, each tick of each leg timed
    # separately with order alternating per tick. Episode-level pairs
    # cannot resolve a 2% budget on a shared box — frequency ramps and
    # co-tenant load swing whole episodes +-10% in one direction — but
    # in lockstep both legs see the same box conditions tick-by-tick,
    # and the per-tick-index MEDIAN across passes drops preemption
    # spikes while the sum over tick indices keeps the window-tick
    # decision cost in (a plain median-of-ticks would hide it: 9 of 10
    # ticks are off-window by construction).
    #
    # Also reported: per-window decision latency from an ACTIVE run's
    # gauges, and SHADOW-VS-ACTIVE PARITY — the shadow decision
    # sequence must be byte-for-byte the active one (minus the apply),
    # which is what makes a shadow deployment's graphs trustworthy.
    from automerge_tpu.control import Controller
    from automerge_tpu.errors import AutomergeError
    from automerge_tpu.service import DocService
    ticks = _env('BENCH_CONTROL_TICKS', 400)
    tenants = _env('BENCH_CONTROL_TENANTS', 8)
    # 20 submits/tenant/tick saturates the tick (every tenant blows
    # through its burst every tick): the controller's per-window cost
    # is FIXED (reported absolutely as control_decide_us_*), so the
    # overhead PERCENTAGE is only meaningful against a loaded serving
    # tick, not an idle one
    submits = _env('BENCH_CONTROL_SUBMITS', 20)
    # the Controller's default decision cadence — the configuration a
    # deployment gets by not choosing; the loadgen chaos leg and the
    # unit tests deliberately run a tighter window=5 to stress the
    # decision path harder than the default
    window = _env('BENCH_CONTROL_WINDOW', 10)
    # passes floor of 9: each pass rebuilds both services, and allocator
    # placement can bias one leg's whole pass a few points — the
    # per-tick median needs enough passes to outvote a skewed layout
    passes = _env('BENCH_CONTROL_PASSES', max(REPS, 9))

    def build(mode):
        ctrl = Controller(mode=mode, window=window) if mode else None
        svc = DocService(control=ctrl, tenant_rate=2.0,
                         tenant_burst=4.0)
        sessions = [svc.open_session(f'tenant{t}')
                    for t in range(tenants)]
        return ctrl, svc, sessions

    def run_tick(svc, sessions, now):
        for s in sessions:
            for _i in range(submits):
                try:
                    svc.submit(s, 'sync', None)
                except AutomergeError:
                    pass
        svc.pump(now)

    def lockstep(order_flip):
        """One pass: a shadow-controlled service and a bare one driven
        through the same tick loop, each leg's tick timed separately.
        Returns (off_ns, on_ns, shadow_decision_log)."""
        import gc
        ctrl, svc_on, ses_on = build('shadow')
        _c, svc_off, ses_off = build(None)
        off_ns = np.empty(ticks)
        on_ns = np.empty(ticks)
        now = 0.0
        # cyclic GC off while timing: collections trigger on allocation
        # counts, and the ON leg allocates more (signal dicts, ledger
        # entries), so gen-2 pauses land disproportionately inside ON
        # ticks — a bursty whole-heap scan billed to whichever tick
        # tripped it, not a controller cost. _fence() collects the
        # deferred garbage between passes.
        gc.disable()
        try:
            for i in range(ticks):
                first_on = (i + order_flip) % 2
                for leg in (first_on, 1 - first_on):
                    start = time.perf_counter_ns()
                    if leg:
                        run_tick(svc_on, ses_on, now)
                    else:
                        run_tick(svc_off, ses_off, now)
                    elapsed = time.perf_counter_ns() - start
                    (on_ns if leg else off_ns)[i] = elapsed
                now += 0.1
        finally:
            gc.enable()
        log = ctrl.decision_log()
        del ctrl, svc_on, ses_on, svc_off, ses_off
        _fence()
        return off_ns, on_ns, log

    off_mat, on_mat = [], []
    shadow_log = None
    pass_pcts = []
    for p in range(passes + 1):
        off_ns, on_ns, shadow_log = lockstep(p % 2)
        if p == 0:
            continue           # first pass is warmup
        off_mat.append(off_ns)
        on_mat.append(on_ns)
        pass_pcts.append(round(
            float((on_ns.sum() - off_ns.sum()) / off_ns.sum()) * 100.0,
            2))
    off_tick_med = np.median(np.array(off_mat), axis=0)
    on_tick_med = np.median(np.array(on_mat), axis=0)
    off_total = float(off_tick_med.sum()) / 1e9
    on_total = float(on_tick_med.sum()) / 1e9
    overhead = (on_total - off_total) / off_total * 100.0
    # one ACTIVE episode: decision latency gauges + the parity check
    a_ctrl, a_svc, a_sessions = build('active')
    now = 0.0
    for _ in range(ticks):
        run_tick(a_svc, a_sessions, now)
        now += 0.1
    gauges = a_ctrl.gauges()
    log = a_ctrl.decision_log()
    del a_ctrl, a_svc, a_sessions
    _fence()

    def strip(entries):
        return [(e['tick'], e['policy'], e['action'], e['target'],
                 e['direction']) for e in entries]
    parity = int(strip(shadow_log) == strip(log))
    reqs = ticks * tenants * submits
    R.update(control_off_rate=reqs / off_total,
             control_on_rate=reqs / on_total,
             control_overhead_pct=overhead,
             control_decisions=len(log),
             control_windows=gauges['windows'],
             control_decide_us_last=gauges['decide_s_last'] * 1e6,
             control_decide_us_max=gauges['decide_s_max'] * 1e6,
             control_shadow_parity=parity,
             control_passes=len(off_mat),
             control_pass_pcts=pass_pcts)
    print(f'# control plane: on {R["control_on_rate"]:.0f} req/s vs off '
          f'{R["control_off_rate"]:.0f} req/s over {ticks} ticks x '
          f'{tenants} tenants ({overhead:+.2f}% overhead, tick-lockstep '
          f'pairing, per-tick median over {len(off_mat)} passes, '
          f'per-pass {pass_pcts}%, budget 2%); '
          f'{len(log)} decisions / {gauges["windows"]} windows, '
          f'decide p-max {R["control_decide_us_max"]:.0f}us, '
          f'shadow parity {"OK" if parity else "FAIL"}',
          file=sys.stderr)


@section('service')
def _sec_service():
    # Multi-tenant serving core (ISSUE-7): the three standing loadgen
    # legs — clean, chaos client, 2x overload — at 10k concurrent
    # sessions, reporting p99 request latency and sustained rounds/s per
    # leg. Acceptance lives in the report itself: every rejection typed
    # (untyped_escapes == 0), every edit doc byte-identical to the
    # unloaded control, every drained sync session converged, brownout
    # transitions visible under overload.
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from loadgen import run_standard_legs
    sessions = _env('BENCH_SERVICE_SESSIONS', 10000)
    requests = _env('BENCH_SERVICE_REQUESTS', max(20000, sessions * 2))
    tenants = _env('BENCH_SERVICE_TENANTS', 256)
    legs = run_standard_legs(sessions=sessions, tenants=tenants,
                             requests=requests, seed=0)
    for leg in legs:
        name = leg['leg']
        conv = leg['convergence'] or {}
        R[f'service_{name}_p99_ms'] = leg['p99_ms']
        R[f'service_{name}_rps'] = leg['requests_per_s']
        R[f'service_{name}_rounds_per_s'] = leg['rounds_per_s']
        ok = leg['untyped_escapes'] == 0 and \
            conv.get('edit_mismatches', 0) == 0 and \
            conv.get('sync_converged') == conv.get('sync_drained')
        R[f'service_{name}_ok'] = int(ok)
        print(f"# service {name}: {leg['completed_ok']}/{leg['submitted']}"
              f" ok at {sessions} sessions/{tenants} tenants, p99 "
              f"{leg['p99_ms']}ms, {leg['rounds_per_s']} rounds/s, "
              f"{leg['requests_per_s']} req/s, rejections "
              f"{ {k: v for k, v in leg['rejections'].items()} }, "
              f"brownout transitions {leg['brownout_transitions']}, "
              f"convergence {conv}, {'OK' if ok else 'FAIL'}",
              file=sys.stderr)
    R['service_legs_all_ok'] = int(all(
        R[f"service_{leg['leg']}_ok"] for leg in legs))


@section('slo')
def _sec_slo():
    # SLO telemetry plane (ISSUE-10), three numbers:
    # (a) SLO accounting + trace-context overhead on the CLEAN service
    #     leg — the whole per-request accounting path (classify, tally,
    #     histogram record, forensics deque, trace mint) plus the
    #     per-tick window/burn evaluation, measured as paired
    #     alternating-order run_leg reps slo-on vs slo=False (the same
    #     methodology as the observability section: fixed order biases
    #     several points on this box), budget <= 2%. Minting rides the
    #     on-leg (submit mints iff slo-on or spans recording); batch
    #     span-LINK assembly is span-gated and so rides the PR 4 spans
    #     budget, not this one;
    # (b) exposition render time at 10k+ series (the Prometheus page a
    #     scraper pulls mid-tick);
    # (c) alert-detection latency: a synthetic full latency step into a
    #     clean registry, ticks until the fast window fires (acceptance
    #     bound: <= 10).
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from loadgen import run_leg
    from automerge_tpu.errors import TenantThrottled
    from automerge_tpu.observability.export import render_prometheus
    from automerge_tpu.observability.slo import SloPolicy, SloRegistry

    sessions = _env('BENCH_SLO_SESSIONS', 10000)
    requests = _env('BENCH_SLO_REQUESTS', max(20000, sessions * 2))
    tenants = _env('BENCH_SLO_TENANTS', 256)
    pairs = _env('BENCH_SLO_PAIRS', 6)

    def leg(slo_on, seed):
        report = run_leg('clean', sessions=sessions, tenants=tenants,
                         requests=requests, seed=seed, convergence=False,
                         service_kwargs=None if slo_on else
                         {'slo': False})
        _fence()
        return report['elapsed_s']

    deltas, on_times, off_times = [], [], []
    for rep in range(pairs + 1):
        if rep % 2:
            on_s = leg(True, rep)
            off_s = leg(False, rep)
        else:
            off_s = leg(False, rep)
            on_s = leg(True, rep)
        if rep == 0:
            continue               # warmup pair (JIT compiles, pools)
        on_times.append(on_s)
        off_times.append(off_s)
        deltas.append(on_s - off_s)
    off_med = float(np.median(off_times))
    overhead = float(np.median(deltas)) / off_med * 100.0

    # direct accounting cost, free of per-leg box drift: one more REAL
    # on-leg with the registry's record/tick wrapped in wall-clock
    # accumulators — the exact code path at the exact volume, measured
    # from inside. Per-leg drift on this host is ±1s+, the same order
    # as the paired delta itself, so this in-leg number (a slight
    # OVERestimate: the wrapper's own perf_counter pairs are counted)
    # is what separates "the accounting got expensive" from "the box
    # was busy this minute"; the paired medians above bound the
    # end-to-end effect, the in-leg number attributes it.
    from automerge_tpu.observability import slo as _slo_mod
    acc = [0.0]
    orig_record = _slo_mod.SloRegistry.record
    orig_tick = _slo_mod.SloRegistry.tick

    def _timed_record(self, *args, **kwargs):
        t0 = time.perf_counter()
        out = orig_record(self, *args, **kwargs)
        acc[0] += time.perf_counter() - t0
        return out

    def _timed_tick(self, *args, **kwargs):
        t0 = time.perf_counter()
        out = orig_tick(self, *args, **kwargs)
        acc[0] += time.perf_counter() - t0
        return out

    _slo_mod.SloRegistry.record = _timed_record
    _slo_mod.SloRegistry.tick = _timed_tick
    try:
        instr_s = leg(True, pairs + 1)
    finally:
        _slo_mod.SloRegistry.record = orig_record
        _slo_mod.SloRegistry.tick = orig_tick
    direct_s = acc[0]
    direct_pct = direct_s / max(instr_s - direct_s, 1e-9) * 100.0

    # ---- (b) exposition render at scale ----
    # ~50 exposition lines per (tenant, kind) pair at 3 kinds: 80
    # tenants land the page just past the 10k-series acceptance scale
    series_tenants = _env('BENCH_SLO_SERIES_TENANTS', 80)
    reg = SloRegistry()
    for t in range(series_tenants):
        tenant = f'tenant{t}'
        for kind in ('apply', 'sync', 'subscribe'):
            reg.record(tenant, kind, 0.003)
            reg.record(tenant, kind, 0.2)
            reg.record(tenant, kind, 0.0, TenantThrottled(
                'bench', tenant=tenant, retry_after=0.1))
    reg.tick()
    render_times = []
    page = ''
    for _ in range(max(REPS, 3)):
        start = time.perf_counter()
        page = render_prometheus(slo=reg)
        render_times.append(time.perf_counter() - start)
    render_s = float(np.median(render_times))
    n_series = sum(1 for line in page.splitlines()
                   if line and not line.startswith('#'))

    # ---- (c) alert-detection latency under a synthetic step ----
    reg2 = SloRegistry(policies={
        'latency': SloPolicy(0.999, threshold_s=0.05)})
    for _ in range(70):
        for _ in range(20):
            reg2.record('victim', 'apply', 0.002)
        reg2.tick()
    detect = None
    for t in range(1, 21):
        for _ in range(20):
            reg2.record('victim', 'apply', 0.4)
        reg2.tick()
        if any(w == 'fast' for *_rest, w in reg2.active_alerts()):
            detect = t
            break

    R.update(slo_overhead_pct=overhead,
             slo_on_leg_s=float(np.median(on_times)),
             slo_off_leg_s=off_med,
             slo_pair_deltas_s=[round(d, 3) for d in deltas],
             slo_inleg_accounting_s=direct_s,
             slo_inleg_accounting_pct=direct_pct,
             slo_render_ms=render_s * 1e3,
             slo_render_series=n_series,
             slo_render_series_per_s=n_series / render_s,
             slo_alert_detect_ticks=detect)
    print(f'# slo: accounting+trace overhead {overhead:+.2f}% paired on '
          f'the {sessions}-session clean leg ({pairs} alternating-order '
          f'pairs, deltas {[round(d, 2) for d in deltas]}s, on '
          f'{np.median(on_times):.2f}s vs off {off_med:.2f}s); in-leg '
          f'instrumented accounting cost {direct_s:.3f}s = '
          f'{direct_pct:.2f}% of the leg (budget 2%); exposition render '
          f'{render_s * 1e3:.1f}ms at {n_series} series '
          f'({n_series / render_s:.0f} series/s); fast-window alert '
          f'detected a full latency step in {detect} ticks '
          f'(budget <= 10)', file=sys.stderr)


@section('shards')
def _sec_shards():
    # Shard scale-out (ISSUE-11), two numbers:
    # (a) aggregate acked req/s on the CLEAN leg at 1/2/4 shards. The
    #     serving tick is a CADENCE (tick_dt bounds batching latency),
    #     so the legs run wall-paced: per-shard capacity is the modeled
    #     per-core device budget (batch_limit applies per fused tick),
    #     aggregate throughput = capacity x shards IF each tick's work
    #     fits the cadence on this box — overruns are counted and
    #     reported (ticks_slipped), never silently absorbed. Pumps run
    #     thread-per-shard; replication group-commits every 4 ticks
    #     (the ack contract — changes on home AND replica before the
    #     ticket resolves — is cadence-independent).
    # (b) failover MTTR: an UNPACED kill-one-of-4 chaos leg (lossy
    #     replication links), reporting ticks from the kill to the
    #     first acked request served by a re-homed tenant, plus the
    #     zero-acked-loss / byte-identical-convergence audits.
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), 'tools'))
    from loadgen import run_shard_leg
    tenants = _env('BENCH_SHARD_TENANTS', 96)
    requests = _env('BENCH_SHARD_REQUESTS', 1200)
    kill_requests = _env('BENCH_SHARD_KILL_REQUESTS', 400)

    # warm the JIT paths on a throwaway cluster so the 1-shard leg
    # doesn't pay compilation inside its paced window
    run_shard_leg('warmup', n_shards=2, tenants=8, requests=100,
                  arrivals_per_tick=8,
                  service_kwargs={'batch_limit': 8}, seed=0)
    _fence()

    sweep = {}
    slips = {}
    for n in (1, 2, 4):
        leg = run_shard_leg(
            f'clean_{n}', n_shards=n, tenants=tenants,
            requests=requests, arrivals_per_tick=max(8, tenants // 2),
            seed=0, tick_dt=0.03, subscribe_fraction=0.1,
            sync_fraction=0.05, service_kwargs={'batch_limit': 8},
            pump_threads=2, repl_every=4, pace=True)
        sweep[str(n)] = leg['requests_per_s']
        slips[str(n)] = leg['ticks_slipped']
        R[f'shards_rps_{n}'] = leg['requests_per_s']
        R[f'shards_clean_{n}_ok'] = int(leg['ok'])
        _fence()
    monotonic = sweep['1'] < sweep['2'] < sweep['4']
    R['shards_scaling_monotonic'] = int(monotonic)

    kill = run_shard_leg(
        'kill_one_of_four', n_shards=4, tenants=max(8, tenants // 8),
        requests=kill_requests, arrivals_per_tick=8, chaos=True,
        seed=5, kills=((12, 1, 40),), mttr_bound=12)
    mttr = kill['mttr_ticks'][0] if kill['mttr_ticks'] else None
    R['shards_failover_mttr_ticks'] = mttr
    R['shards_kill_leg_ok'] = int(kill['ok'])
    R['shards_kill_acked_lost'] = kill['final_audit']['acked_lost']
    R['shards_kill_replica_mismatches'] = \
        kill['final_audit']['replica_mismatches']
    _fence()

    scaled = ', '.join(
        f'{n}S {r:.0f} req/s ({r / sweep["1"]:.2f}x, '
        f'{slips[n]} slipped)' for n, r in sweep.items())
    print(f'# shards clean paced sweep ({tenants} tenants, '
          f'batch_limit 8/tick/shard, tick 30ms, repl_every 4): '
          f'{scaled}, monotonic {"OK" if monotonic else "FAIL"}',
          file=sys.stderr)
    print(f'# shards kill-one-of-four: MTTR {mttr} ticks (lease '
          f'{kill["lease_ticks"]}), acked lost '
          f'{kill["final_audit"]["acked_lost"]}, replica mismatches '
          f'{kill["final_audit"]["replica_mismatches"]}, '
          f'{"OK" if kill["ok"] else "FAIL"}', file=sys.stderr)


@section('query')
def _sec_query():
    # Query engine (ISSUE-9): (a) batched time-travel reads — N docs
    # materialized at historical frontiers through ONE fused replay
    # dispatch (query.materialize_at_docs), reported as docs/s with the
    # dispatch count pinned; (b) the subscription tick at fleet scale —
    # S subscribers over D docs grouped into (doc, cursor) equivalence
    # classes, reporting tick p99, the per-tick device dispatch count
    # (must be 0: pure hash-graph work), and the one-diff-per-class
    # reuse ratio.
    from automerge_tpu.columnar import decode_change_meta, encode_change
    from automerge_tpu.fleet import backend as fleet_backend
    from automerge_tpu.fleet.backend import DocFleet, init_docs
    from automerge_tpu.query import SubscriptionHub, materialize_at_docs

    n_docs = _env('BENCH_QUERY_DOCS', 1000)
    n_subs = _env('BENCH_QUERY_SUBS', 10000)
    n_changes = 6

    fleet = DocFleet()
    handles = init_docs(n_docs, fleet)
    frontiers = [[] for _ in range(n_docs)]   # current heads per doc
    mid_frontier = [None] * n_docs            # heads at the halfway point
    for c in range(n_changes):
        per_doc = []
        for d in range(n_docs):
            buf = encode_change({
                'actor': f'{d % 128:04x}' * 4, 'seq': c + 1,
                'startOp': c + 1, 'time': 0, 'message': '',
                'deps': frontiers[d],
                'ops': [{'action': 'set', 'obj': '_root', 'key': f'k{c}',
                         'value': d * 100 + c, 'datatype': 'int',
                         'pred': []}]})
            frontiers[d] = [decode_change_meta(buf, True)['hash']]
            if c == n_changes // 2:
                mid_frontier[d] = list(frontiers[d])
            per_doc.append([buf])
        handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                      mirror=False)
    _fence()

    # ---- (a) batched materialize-at ----
    mat_times = []
    dispatches = None
    for rep in range(max(REPS, 3) + 1):
        before = fleet.metrics.dispatches
        start = time.perf_counter()
        outs = materialize_at_docs(handles, mid_frontier, fleet=fleet)
        mat_s = time.perf_counter() - start
        dispatches = fleet.metrics.dispatches - before
        fleet_backend.free_docs(outs)
        if rep == 0:
            continue
        mat_times.append(mat_s)
    mat_s = float(np.median(mat_times))
    mat_rate = n_docs / mat_s

    # ---- (b) the subscription tick at fan-out scale ----
    # subscribers spread over the docs at 3 cursor classes per doc
    # (empty / mid / at-head), so the expected reuse ratio at S >> 3D is
    # ~1 - 3D/S
    hub = SubscriptionHub()
    for d in range(n_docs):
        hub.register(d, handles[d])
    classes = [[], None, 'head']
    for s in range(n_subs):
        d = s % n_docs
        cls = classes[(s // n_docs) % 3]
        cursor = mid_frontier[d] if cls is None else \
            (frontiers[d] if cls == 'head' else [])
        hub.subscribe(d, cursor=cursor)
    tick_times = []
    tick_dispatches = 0
    reuse_ratio = 0.0
    n_ticks = max(REPS, 5)
    for rep in range(n_ticks + 1):
        # advance every doc one change so each tick has real diffs
        per_doc = []
        for d in range(n_docs):
            buf = encode_change({
                'actor': f'{d % 128:04x}' * 4, 'seq': n_changes + rep + 1,
                'startOp': n_changes + rep + 1, 'time': 0, 'message': '',
                'deps': frontiers[d],
                'ops': [{'action': 'set', 'obj': '_root', 'key': 'hot',
                         'value': rep, 'datatype': 'int', 'pred': []}]})
            frontiers[d] = [decode_change_meta(buf, True)['hash']]
            per_doc.append([buf])
        handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                      mirror=False)
        for d in range(n_docs):
            hub.update_source(d, handles[d])
        computed0 = hub.stats['diffs_computed']
        reused0 = hub.stats['diffs_reused']
        before = fleet.metrics.dispatches
        start = time.perf_counter()
        events = hub.tick()
        tick_s = time.perf_counter() - start
        tick_dispatches = fleet.metrics.dispatches - before
        assert len(events) == n_subs
        if rep == 0:
            continue
        computed = hub.stats['diffs_computed'] - computed0
        reused = hub.stats['diffs_reused'] - reused0
        reuse_ratio = reused / max(computed + reused, 1)
        tick_times.append(tick_s)
    tick_p99_ms = float(np.percentile(tick_times, 99)) * 1e3
    tick_p50_ms = float(np.median(tick_times)) * 1e3
    del hub, handles, fleet
    _fence()

    R.update(query_materialize_docs_per_s=mat_rate,
             query_materialize_dispatches=dispatches,
             query_tick_subs=n_subs,
             query_tick_p50_ms=tick_p50_ms,
             query_tick_p99_ms=tick_p99_ms,
             query_tick_dispatches=tick_dispatches,
             query_diff_reuse_ratio=reuse_ratio)
    print(f'# query: batched materialize-at {mat_rate:.0f} docs/s '
          f'({n_docs} docs/batch, {dispatches} dispatches/batch); '
          f'{n_subs}-subscriber tick over {n_docs} docs p50 '
          f'{tick_p50_ms:.1f}ms / p99 {tick_p99_ms:.1f}ms, '
          f'{tick_dispatches} device dispatches/tick, diff reuse '
          f'{reuse_ratio:.3f}', file=sys.stderr)


@section('frontier')
def _sec_frontier():
    # Device-resident frontier index (ISSUE-14): (a) sync-round
    # membership cost vs HISTORY DEPTH at fixed batch — warm rounds ride
    # one batched index dispatch, so the sweep must be FLAT (<=1.2x from
    # 1k to 100k, the acceptance pin), while the fresh-doc contrast leg
    # shows what the index removes: the O(history) hash-graph dict build
    # a converged handshake used to force on a freshly loaded doc;
    # (b) the 10k-subscriber ALL-QUIET tick collapsed to exactly one
    # frontier-compare dispatch, p50 vs the per-class host scan.
    from automerge_tpu.backend import init_sync_state
    from automerge_tpu.columnar import decode_change_meta, encode_change
    from automerge_tpu.fleet import backend as fleet_backend
    from automerge_tpu.fleet import hashindex, sync_driver
    from automerge_tpu.fleet.backend import DocFleet, init_docs
    from automerge_tpu.fleet.loader import load_docs
    from automerge_tpu.query import SubscriptionHub

    depths = [int(x) for x in os.environ.get(
        'BENCH_FRONTIER_DEPTHS', '1000,100000').split(',')]
    behind = _env('BENCH_FRONTIER_BEHIND', 64)
    k_docs = _env('BENCH_FRONTIER_DOCS', 4)

    def chain(n):
        bufs, hashes, deps = [], [], []
        for i in range(n):
            buf = encode_change({
                'actor': 'f1' * 16, 'seq': i + 1, 'startOp': i + 1,
                'time': 0, 'message': '', 'deps': deps,
                'ops': [{'action': 'set', 'obj': '_root',
                         'key': f'k{i % 7}', 'value': i,
                         'datatype': 'int', 'pred': []}]})
            deps = [decode_change_meta(buf, True)['hash']]
            bufs.append(buf)
            hashes.append(deps[0])
        return bufs, hashes

    depth_p50 = {}
    fresh_ms = {}
    # one table GEOMETRY for the whole sweep (provisioned for the
    # deepest leg): the sweep pins cost vs HISTORY DEPTH, and a tiny
    # table's cache-resident probes would otherwise flatter the shallow
    # leg by ~0.3ms of pure L2-vs-RAM gather difference
    table_cap = 2 * k_docs * max(depths)
    for H in depths:
        bufs, hashes = chain(H)
        fleet = DocFleet()
        handles = init_docs(k_docs, fleet)
        step = 20000
        for lo in range(0, H, step):
            handles, _ = fleet_backend.apply_changes_docs(
                handles, [bufs[lo:lo + step]] * k_docs, mirror=False)
        doc_chunk = bytes(handles[0]['state'].save())
        anchor = hashes[H - behind - 1]

        def mk_states(heads):
            out = []
            for _ in range(k_docs):
                s = init_sync_state()
                s['sharedHeads'] = list(heads)
                s['theirHeads'] = list(heads)
                s['theirHave'] = [{'lastSync': list(heads), 'bloom': b''}]
                s['theirNeed'] = []
                out.append(s)
            return out

        # warm: index registration backfill + graph walk caches, then
        # measure steady-state rounds with a peer `behind` changes back.
        # device_min=1 pins the DEVICE table at every depth — the sweep
        # compares depth, not host-vs-device storage modes
        fleet.frontier_index(device_min=1, capacity=table_cap)
        sync_driver.generate_sync_messages_docs(handles,
                                                mk_states([anchor]))
        times = []
        for _ in range(max(REPS, 5)):
            states = mk_states([anchor])
            start = time.perf_counter()
            _s, msgs = sync_driver.generate_sync_messages_docs(handles,
                                                               states)
            times.append(time.perf_counter() - start)
            assert all(m is not None for m in msgs)
        depth_p50[H] = float(np.median(times)) * 1e3
        del handles, fleet, bufs
        _fence()

        # fresh-doc converged round, index on vs off: the one-time cost
        # a revive pays to answer a quiet handshake (extractor hash-lane
        # backfill vs the full Python hash-graph dict build)
        row = {}
        for label, enabled in (('new', True), ('old', False)):
            prev = sync_driver.set_frontier_enabled(enabled)
            try:
                fleet2 = DocFleet()
                if enabled:
                    fleet2.frontier_index(device_min=1,
                                          capacity=table_cap)
                loaded = load_docs([doc_chunk] * k_docs, fleet2)
                heads = list(loaded[0]['heads'])
                start = time.perf_counter()
                sync_driver.generate_sync_messages_docs(
                    loaded, mk_states(heads))
                row[label] = (time.perf_counter() - start) * 1e3
            finally:
                sync_driver.set_frontier_enabled(prev)
            del fleet2, loaded
            _fence()
        fresh_ms[H] = row

    lo_h, hi_h = depths[0], depths[-1]
    depth_ratio = depth_p50[hi_h] / depth_p50[lo_h]

    # ---- (b) the all-quiet tick at fan-out scale ----
    n_docs = _env('BENCH_FRONTIER_TICK_DOCS', 1000)
    n_subs = _env('BENCH_FRONTIER_TICK_SUBS', 10000)
    fleet = DocFleet()
    handles = init_docs(n_docs, fleet)
    per_doc, frontiers = [], []
    for d in range(n_docs):
        buf = encode_change({
            'actor': f'{d % 128:04x}' * 4, 'seq': 1, 'startOp': 1,
            'time': 0, 'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                     'value': d, 'datatype': 'int', 'pred': []}]})
        frontiers.append([decode_change_meta(buf, True)['hash']])
        per_doc.append([buf])
    handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                  mirror=False)
    hub = SubscriptionHub()
    for d in range(n_docs):
        hub.register(d, handles[d])
    for s in range(n_subs):
        hub.subscribe(s % n_docs, cursor=frontiers[s % n_docs])
    hub.tick()                      # warm (plan build, jit)
    tick_p50 = {}
    tick_dispatches = None
    for label, batch in (('batched', True), ('scan', False)):
        hub.batch_quiet = batch
        times = []
        for _ in range(max(REPS, 7)):
            n0 = hashindex.dispatch_count()
            d0 = fleet.metrics.dispatches
            start = time.perf_counter()
            events = hub.tick()
            times.append(time.perf_counter() - start)
            assert events == {}
            if batch:
                tick_dispatches = (hashindex.dispatch_count() - n0,
                                   fleet.metrics.dispatches - d0)
                assert tick_dispatches == (1, 0), tick_dispatches
        tick_p50[label] = float(np.median(times)) * 1e3
    del hub, handles, fleet
    _fence()

    quiet_speedup = tick_p50['scan'] / tick_p50['batched']
    # flat scalar keys (the standalone JSON line and the bench ledger
    # both drop nested values)
    for h in depths:
        R[f'frontier_round_p50_ms_{h}'] = depth_p50[h]
        R[f'frontier_fresh_new_ms_{h}'] = fresh_ms[h]['new']
        R[f'frontier_fresh_old_ms_{h}'] = fresh_ms[h]['old']
    R.update(
        frontier_depth_ratio=depth_ratio,
        frontier_fresh_speedup=fresh_ms[hi_h]['old'] /
            max(fresh_ms[hi_h]['new'], 1e-9),
        frontier_quiet_tick_p50_ms=tick_p50['batched'],
        frontier_quiet_scan_p50_ms=tick_p50['scan'],
        frontier_quiet_speedup=quiet_speedup,
        frontier_quiet_tick_dispatches=1)
    print(f'# frontier: sync-round p50 '
          + ' / '.join(f'{h}ch {depth_p50[h]:.2f}ms' for h in depths)
          + f' (ratio {depth_ratio:.2f}x, budget <=1.2x); fresh-doc '
          f'converged round at {hi_h}ch: index {fresh_ms[hi_h]["new"]:.0f}ms '
          f'vs dicts {fresh_ms[hi_h]["old"]:.0f}ms; {n_subs}-sub all-quiet '
          f'tick p50 {tick_p50["batched"]:.2f}ms (1 dispatch) vs scan '
          f'{tick_p50["scan"]:.2f}ms = {quiet_speedup:.1f}x',
          file=sys.stderr)


@section('sync_fabric')
def _sec_sync_fabric():
    # Fleet-scale sync fabric (ISSUE-16): a shard serving N peer links
    # out of its doc set, every link's sentHashes a peer-space in the
    # shared frontier table. (a) steady-state round p50 across a link
    # sweep with per-round hashindex + Bloom dispatch counts (the O(1)
    # pin: counts must not move with N); (b) fused round vs the classic
    # per-peer generate loop the fabric replaced (subsampled and
    # extrapolated; acceptance >=3x at the 10k leg); (c) the probe-
    # window sweep behind AUTOMERGE_TPU_PROBE_WINDOW.
    from automerge_tpu.backend import init_sync_state
    from automerge_tpu.backend.sync import generate_sync_message
    from automerge_tpu.columnar import decode_change_meta, encode_change
    from automerge_tpu.fleet import backend as fleet_backend
    from automerge_tpu.fleet import bloom as fleet_bloom
    from automerge_tpu.fleet import hashindex, sync_driver
    from automerge_tpu.fleet.backend import DocFleet, init_docs
    from automerge_tpu.fleet.hashindex import PeerSentSet, set_probe_window

    link_sweep = [int(x) for x in os.environ.get(
        'BENCH_FABRIC_LINKS', '1000,10000,100000').split(',')]
    n_docs = _env('BENCH_FABRIC_DOCS', 4)
    depth = _env('BENCH_FABRIC_DEPTH', 8)
    loop_sample = _env('BENCH_FABRIC_LOOP_SAMPLE', 512)
    windows = [int(x) for x in os.environ.get(
        'BENCH_FABRIC_WINDOWS', '8,16,32').split(',')]

    def chain(actor, n):
        bufs, hashes, deps = [], [], []
        for i in range(n):
            buf = encode_change({
                'actor': actor, 'seq': i + 1, 'startOp': i + 1,
                'time': 0, 'message': '', 'deps': deps,
                'ops': [{'action': 'set', 'obj': '_root',
                         'key': f'k{i % 5}', 'value': i,
                         'datatype': 'int', 'pred': []}]})
            deps = [decode_change_meta(buf, True)['hash']]
            bufs.append(buf)
            hashes.append(deps[0])
        return bufs, hashes

    def solicit(states):
        # every peer asks for a full resend (empty bloom): the round
        # must answer membership for every candidate on every link —
        # the fabric's worst-case steady state
        for s in states:
            s['theirHeads'] = []
            s['theirHave'] = [{'lastSync': [], 'bloom': b''}]
            s['theirNeed'] = []

    round_p50, loop_ms, host_loop_ms, disp = {}, {}, {}, {}
    for n_links in link_sweep:
        fleet = DocFleet()
        handles = init_docs(n_docs, fleet)
        doc_rows = [chain(f'{0xe0 + d:02x}' * 16, depth)
                    for d in range(n_docs)]
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [bufs for bufs, _ in doc_rows], mirror=False)
        fleet.frontier_index(device_min=1,
                             capacity=2 * n_links * depth)
        flat_docs = [handles[i % n_docs] for i in range(n_links)]
        states = [init_sync_state() for _ in range(n_links)]
        solicit(states)
        # cold round: every link sends its doc's changes, staging and
        # promoting its sentHashes into a peer-space
        states, msgs = sync_driver.generate_sync_messages_docs(
            flat_docs, states)
        assert all(isinstance(s['sentHashes'], PeerSentSet)
                   for s in states)
        solicit(states)
        # warm round: flushes the staged spaces + compiles steady shapes
        states, _msgs = sync_driver.generate_sync_messages_docs(
            flat_docs, states)
        times = []
        for _ in range(max(REPS, 5)):
            solicit(states)
            h0 = hashindex.dispatch_count()
            b0 = fleet_bloom.dispatch_count()
            start = time.perf_counter()
            states, msgs = sync_driver.generate_sync_messages_docs(
                flat_docs, states)
            times.append(time.perf_counter() - start)
            disp[n_links] = (hashindex.dispatch_count() - h0,
                             fleet_bloom.dispatch_count() - b0)
        assert all(m is not None for m in msgs)
        round_p50[n_links] = float(np.median(times)) * 1e3

        # the per-peer loop this PR replaced (exchange.py/cluster.py
        # before the fabric): one driver call PER PEER PAIR, so every
        # link pays its own Bloom-build + membership-probe dispatches.
        # Subsampled and extrapolated to the full link set (strictly
        # per-link work, so the extrapolation is linear by construction)
        m_links = min(n_links, loop_sample)

        def run_loop():
            sub = states[:m_links]
            solicit(sub)
            start = time.perf_counter()
            for i in range(m_links):
                new, _m = sync_driver.generate_sync_messages_docs(
                    [flat_docs[i]], [states[i]])
                states[i] = new[0]
            return time.perf_counter() - start

        run_loop()                                   # warm n=1 shapes
        loop_reps = [run_loop() for _ in range(max(REPS, 3))]
        loop_ms[n_links] = float(np.median(loop_reps)) * 1e3 \
            * (n_links / m_links)

        # secondary reference: the single-doc HOST protocol with plain-
        # set sentHashes (no device work at all) — the floor the shared
        # per-link host assembly cost imposes on both paths
        host_states = []
        for i in range(m_links):
            s = init_sync_state()
            s['sentHashes'] = set(doc_rows[i % n_docs][1])
            host_states.append(s)

        def run_host_loop():
            solicit(host_states)
            start = time.perf_counter()
            for i in range(m_links):
                host_states[i], _m = generate_sync_message(
                    handles[i % n_docs], host_states[i])
            return time.perf_counter() - start

        run_host_loop()                              # warm
        host_reps = [run_host_loop() for _ in range(max(REPS, 3))]
        host_loop_ms[n_links] = float(np.median(host_reps)) * 1e3 \
            * (n_links / m_links)

        if n_links == link_sweep[len(link_sweep) // 2]:
            # probe-window sweep at the middle leg: the 16-slot default
            # vs narrower/wider windows (static jit arg -> each width
            # compiles once, then steady rounds)
            for width in windows:
                prev = set_probe_window(width)
                try:
                    solicit(states)
                    states, _msgs = sync_driver.\
                        generate_sync_messages_docs(flat_docs, states)
                    wtimes = []
                    for _ in range(max(REPS, 3)):
                        solicit(states)
                        start = time.perf_counter()
                        states, _msgs = sync_driver.\
                            generate_sync_messages_docs(flat_docs, states)
                        wtimes.append(time.perf_counter() - start)
                    R[f'fabric_window_p50_ms_{width}'] = \
                        float(np.median(wtimes)) * 1e3
                finally:
                    set_probe_window(prev)
        del fleet, handles, flat_docs, states, host_states, msgs
        _fence()

    mid = min(link_sweep, key=lambda n: abs(n - 10_000))
    top = link_sweep[-1]
    flat = len({d for d in disp.values()}) == 1
    for n_links in link_sweep:
        R[f'fabric_round_p50_ms_{n_links}'] = round_p50[n_links]
        R[f'fabric_loop_round_ms_{n_links}'] = loop_ms[n_links]
        R[f'fabric_host_loop_round_ms_{n_links}'] = host_loop_ms[n_links]
        R[f'fabric_fused_vs_loop_{n_links}'] = \
            loop_ms[n_links] / round_p50[n_links]
        R[f'fabric_round_hashindex_dispatches_{n_links}'] = \
            disp[n_links][0]
        R[f'fabric_round_bloom_dispatches_{n_links}'] = disp[n_links][1]
    R.update(
        fabric_links_per_s=top / round_p50[top] * 1e3,
        fabric_fused_vs_loop_ratio=loop_ms[mid] / round_p50[mid],
        fabric_dispatches_flat=int(flat))
    print(f'# sync fabric: round p50 '
          + ' / '.join(f'{n}lk {round_p50[n]:.1f}ms' for n in link_sweep)
          + f'; dispatches/round {disp[top]} '
          f'({"FLAT" if flat else "SCALING"} across the sweep); fused vs '
          f'per-peer loop at {mid} links: {loop_ms[mid]:.0f}ms -> '
          f'{round_p50[mid]:.1f}ms = '
          f'{loop_ms[mid] / round_p50[mid]:.1f}x (budget >=3x; host-'
          f'protocol floor {host_loop_ms[mid]:.0f}ms); '
          f'window sweep '
          + ' / '.join(f'w{w} {R.get(f"fabric_window_p50_ms_{w}", 0):.1f}ms'
                       for w in windows),
          file=sys.stderr)


@section('zipf')
def _sec_zipf():
    # Config 5 (stretch): Zipf-skewed change rates over a large fleet
    zipf_rate, zipf_occ = bench_zipf(_env('BENCH_ZIPF_DOCS', 100000))
    R.update(zipf_rate=zipf_rate, zipf_occ=zipf_occ)
    print(f'# zipf 100k-doc fleet: {zipf_rate:.0f} effective ops/s '
          f'(occupancy {zipf_occ:.2f})', file=sys.stderr)


@section('registers')
def _sec_registers():
    # Exact multi-value register engine (ordered scan formulation)
    reg_rate = bench_registers(_env('BENCH_REG_DOCS', 4000))
    R['reg_rate'] = reg_rate
    print(f'# exact register engine: {reg_rate:.0f} ops/s', file=sys.stderr)


@section('bulk_load')
def _sec_bulk_load():
    # Bulk document load: native parse straight to device state vs the
    # per-doc Python decode + host replay path
    bulk_rate, perdoc_rate = bench_bulk_load(_env('BENCH_LOAD_DOCS', 2000))
    R.update(bulk_rate=bulk_rate, perdoc_rate=perdoc_rate)
    if bulk_rate is not None:
        print(f'# bulk document load (native parse -> device state): '
              f'{bulk_rate:.0f} docs/s vs per-doc path '
              f'{perdoc_rate:.0f} docs/s '
              f'({bulk_rate / perdoc_rate:.1f}x)', file=sys.stderr)
    else:
        print(f'# bulk document load: native codec unavailable '
              f'(per-doc path {perdoc_rate:.0f} docs/s)', file=sys.stderr)


@section('native_save')
def _sec_native_save():
    save_native, save_host = bench_native_save(
        _env('BENCH_SAVE_CHANGES', 200))
    R.update(save_native=save_native, save_host=save_host)
    if save_native is not None:
        print(f'# mirror-free native save (200-change log): '
              f'{save_native:.1f} saves/s vs host replay+encode '
              f'{save_host:.1f} saves/s ({save_native / save_host:.1f}x)',
              file=sys.stderr)


@section('mixed')
def _sec_mixed():
    mixed_rate, mixed_host, mixed_opc = bench_backend_mixed(
        _env('BENCH_MIXED_DOCS', 500))
    R.update(mixed_rate=mixed_rate, mixed_host=mixed_host,
             mixed_opc=mixed_opc)
    print(f'# backend-seam e2e, realistic mixed docs (nested trees, '
          f'strings/floats/bools): {mixed_rate:.0f} changes/s vs host '
          f'{mixed_host:.0f} changes/s ({mixed_rate / mixed_host:.1f}x); '
          f'{mixed_opc:.1f} ops/change -> {mixed_rate * mixed_opc:.0f} '
          f'ops/s (headline is 1 op/change)', file=sys.stderr)


@section('seam_dense')
def _sec_seam_dense():
    # Op-density control for the mixed-vs-flat gap (round-5 VERDICT weak
    # #3): the FLAT-int seam at the mixed config's measured op density
    # (~4.8 ops/change). If changes/s here lands near the mixed rate, op
    # density explains the gap and the per-op framing stands; any residual
    # is mixed-content cost (nested objects, value arena, seq rows).
    opc = float(os.environ.get('BENCH_DENSE_OPC',
                               R.get('mixed_opc', 4.8) or 4.8))
    rate, info = bench_backend_pipeline(
        _env('BENCH_MIXED_DOCS', 500), 64, 16, ops_per_change=opc)
    R.update(seam_dense_rate=rate, seam_dense_opc=info['ops_per_change'])
    extra = ''
    if R.get('mixed_rate'):
        extra = f'; mixed config measured {R["mixed_rate"]:.0f} changes/s ' \
                f'-> density explains {rate / R["mixed_rate"]:.2f}x of the ' \
                f'flat-headline gap'
    print(f'# op-density control: flat ints at '
          f'{info["ops_per_change"]:.1f} ops/change: {rate:.0f} changes/s '
          f'({rate * info["ops_per_change"]:.0f} ops/s){extra}',
          file=sys.stderr)


@section('regress')
def _sec_regress():
    # Bench ledger + regression gate (ISSUE-13): measure the seam with
    # RECORDED per-rep samples (the rep spread is what makes the gate's
    # thresholds noise-aware), append one row to BENCH_LEDGER.jsonl,
    # judge HEAD against the ledger's trailing same-box history with
    # tools/perf_gate.judge, and run the gate's synthetic self-test
    # (--check): zero false fires across 5 clean paired runs, a 1.3x
    # slowdown detected. BENCH_LEDGER=0 skips the append (the sanity
    # harness sets it so scaled-down runs don't pollute the trajectory).
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), 'tools'))
    import bench_ledger
    import perf_gate
    docs = _env('BENCH_REGRESS_DOCS', 2000)
    n_keys = _env('BENCH_KEYS', 1000)
    reps = []
    info = None
    for _ in range(max(REPS, 5)):
        rate, info = bench_backend_pipeline(docs, n_keys, 20, reps=1)
        reps.append(rate)
        _fence()
    metric = f'regress_seam_rate_{docs}d'
    head_metrics = {metric: float(np.median(reps))}
    ledger_on = os.environ.get('BENCH_LEDGER', '1') != '0'
    if ledger_on:
        # ride the full run's section numbers along (standalone runs
        # carry only the regress metric). Skipped when the append is
        # off (the sanity harness's SCALED-DOWN runs set BENCH_LEDGER=0:
        # judging a 1000-doc seam_rate against the ledger's full-scale
        # history would manufacture a regression out of the config)
        for key in ('seam_rate', 'seam_commit_rate', 'host_rate',
                    'service_clean_rps', 'slo_render_series_per_s',
                    'storage_recovery_docs_per_s',
                    'tier_park_docs_per_s', 'tier_revive_docs_per_s',
                    'tier_materialize_docs_per_s',
                    'query_materialize_docs_per_s', 'shards_rps_4',
                    'fabric_links_per_s', 'fabric_fused_vs_loop_ratio',
                    'obs_overhead_pct', 'perf_overhead_pct',
                    'control_overhead_pct'):
            if isinstance(R.get(key), (int, float)):
                head_metrics[key] = float(R[key])
    row = bench_ledger.make_row(
        head_metrics, reps={metric: reps},
        notes={'regress_docs': docs, 'platform': BENCH_PLATFORM})
    rows, report = bench_ledger.read_rows()
    verdict = perf_gate.judge(row, rows)
    if ledger_on:
        bench_ledger.append_row(row)
    check_ok = perf_gate.check(out=sys.stderr)
    judged = [f for f in verdict['findings']
              if f['verdict'] != 'insufficient']
    R.update(regress_seam_rate=head_metrics[metric],
             regress_docs=docs,
             regress_gate_ok=int(verdict['ok']),
             regress_check_ok=int(check_ok),
             regress_metrics_judged=len(judged),
             regress_ledger_rows=len(rows) + int(ledger_on),
             regress_ledger_torn_tail=int(report['torn_tail']))
    for f in verdict['regressions']:
        print(f'# REGRESSION {f["metric"]}: head {f["head"]:.5g} vs '
              f'baseline {f["baseline"]:.5g} ({f["delta_pct"]:+.1f}% '
              f'past the ±{f["threshold_pct"]:.1f}% noise gate)',
              file=sys.stderr)
    print(f'# regress: {metric} {head_metrics[metric]:.0f} changes/s '
          f'(reps {[round(r) for r in reps]}), gate '
          f'{"OK" if verdict["ok"] else "REGRESSION"} over '
          f'{len(judged)} judged metric(s) / {len(rows)} ledger rows'
          f'{"" if ledger_on else " (append skipped: BENCH_LEDGER=0)"}; '
          f'perf_gate --check {"OK" if check_ok else "FAIL"}',
          file=sys.stderr)


@section('archlint')
def _sec_archlint():
    # the static-contract gate rides the bench: a perf number appended
    # to the ledger is only trajectory-comparable when the kernel-ledger
    # / counter / determinism contracts held while it was measured. The
    # analysis package is stdlib-only, so this costs ~1s of AST time.
    import time as _time
    from automerge_tpu import analysis
    root = os.path.dirname(os.path.abspath(__file__))
    t0 = _time.perf_counter()
    findings, files, errors = analysis.lint_paths(
        ['automerge_tpu', 'tools', 'bench.py'], analysis.get_rules(),
        root=root)
    baseline = analysis.load_baseline(
        os.path.join(root, 'tools', 'archlint_baseline.json'))
    checked = analysis.check_findings(findings, baseline)
    R['archlint_violations'] = (
        len(checked['violations']) + len(checked['unlisted']) +
        len(checked['stale']) + len(errors))
    R['archlint_suppressed'] = len(checked['suppressed'])
    R['archlint_files'] = len(files)
    R['archlint_s'] = round(_time.perf_counter() - t0, 3)
    print(f'# archlint: {len(files)} files, '
          f'{R["archlint_violations"]} violations, '
          f'{R["archlint_suppressed"]} suppressed '
          f'({R["archlint_s"]}s)', file=sys.stderr)


@section('trace')
def _sec_trace():
    trace_dir = capture_trace(_env('BENCH_DOCS', 10000),
                              _env('BENCH_KEYS', 1000),
                              _env('BENCH_OPS', 100),
                              pallas_variant=R.get('pallas_variant'))
    R['trace_dir'] = trace_dir
    if trace_dir is not None:
        pv = R.get('pallas_variant')
        print(f'# profiler trace (merge + sequence'
              f'{" + pallas " + pv if pv else ""}) '
              f'written to {trace_dir}', file=sys.stderr)


def _final_json():
    from automerge_tpu.observability import health_counts
    result = {
        'metric': 'changes_per_sec_backend_seam_e2e',
        'value': round(R['seam_rate']),
        'unit': 'changes/s',
        'vs_baseline': round(R['seam_rate'] / R['host_rate'], 2),
        'seam_dispatches_per_round': R.get('seam_dispatches_per_round'),
        'init_dispatches': R.get('seam_init_dispatches'),
        'sync_dispatches_per_round': R.get('syncdrv_dispatches_per_round'),
        'archlint_violations': R.get('archlint_violations'),
        'health': health_counts(),
    }
    if BENCH_PLATFORM is not None:
        result['platform'] = BENCH_PLATFORM
    print(json.dumps(result))


def _run_standalone(name):
    """BENCH_SECTION=<name>: one section, fenced, with its own JSON line."""
    if name == 'list':
        print(' '.join(SECTIONS))
        return
    if name not in SECTIONS:
        print(f'unknown BENCH_SECTION {name!r}; one of: '
              f'{" ".join(SECTIONS)}', file=sys.stderr)
        sys.exit(2)
    _guard_dead_accelerator()
    _fence()
    SECTIONS[name]()
    out = {'section': name}
    out.update({k: v for k, v in R.items()
                if isinstance(v, (int, float, str, type(None)))})
    if BENCH_PLATFORM is not None:
        out['platform'] = BENCH_PLATFORM
    print(json.dumps(out))


def _run_sanity():
    """Scaled-down full pass, then key sections standalone in SUBPROCESSES;
    fail if any full-run rate and its standalone rate disagree by > 2x."""
    import subprocess
    small = {'BENCH_SEAM_DOCS': '1000', 'BENCH_DOCS': '1000',
             'BENCH_HOST_DOCS': '50', 'BENCH_SEAM_TEXT_DOCS': '50',
             'BENCH_TEXT_DOCS': '200', 'BENCH_BLOOM_DOCS': '1000',
             'BENCH_SYNCDRV_DOCS': '500', 'BENCH_ZIPF_DOCS': '5000',
             'BENCH_DUR_DOCS': '1000', 'BENCH_OBS_DOCS': '1000',
             'BENCH_REG_DOCS': '500', 'BENCH_LOAD_DOCS': '200',
             'BENCH_SAVE_CHANGES': '50', 'BENCH_MIXED_DOCS': '100',
             'BENCH_SERVICE_SESSIONS': '500',
             'BENCH_SERVICE_REQUESTS': '3000',
             'BENCH_SERVICE_TENANTS': '32',
             'BENCH_SLO_SESSIONS': '500',
             'BENCH_SLO_REQUESTS': '3000',
             'BENCH_SLO_TENANTS': '32',
             'BENCH_SLO_PAIRS': '2',
             'BENCH_SLO_SERIES_TENANTS': '60',
             'BENCH_QUERY_DOCS': '200',
             'BENCH_QUERY_SUBS': '1000',
             'BENCH_TIER_DOCS': '20000',
             'BENCH_TIER_RAM_DOCS': '20000',
             'BENCH_TIER_DISTINCT': '512',
             'BENCH_TIER_REVIVE': '256',
             'BENCH_TIER_MAT': '128',
             # sanity cares about the RATIO's full-vs-standalone
             # agreement, not the absolute depth; 8k keeps the fixture
             # build off the critical path
             'BENCH_FRONTIER_DEPTHS': '1000,8000',
             'BENCH_FRONTIER_TICK_DOCS': '200',
             'BENCH_FRONTIER_TICK_SUBS': '2000',
             # tenants stay at the default: the paced sweep needs the
             # closed-loop writer pool to SATURATE per-shard capacity
             # (tenants >> shards x batch x ack-latency) or the legs go
             # latency-bound and the scaling curve flattens
             'BENCH_SHARD_REQUESTS': '600',
             'BENCH_SHARD_KILL_REQUESTS': '240',
             'BENCH_PERF_DOCS': '1000',
             'BENCH_CONTROL_TICKS': '150',
             'BENCH_REGRESS_DOCS': '500',
             'BENCH_FABRIC_LINKS': '256,1024',
             'BENCH_FABRIC_LOOP_SAMPLE': '64',
             # scaled-down sanity rows must not pollute the trajectory
             'BENCH_LEDGER': '0',
             'BENCH_REPS': '3'}
    for k, v in small.items():
        os.environ.setdefault(k, v)
    _guard_dead_accelerator()
    for name, fn in SECTIONS.items():
        if name == 'trace':
            continue
        fn()
        _fence()
    failures = []
    for name, key in SANITY_KEYS.items():
        full_val = R.get(key)
        if full_val is None or (not full_val and
                                not key.endswith('_pct')):
            continue
        env = dict(os.environ, BENCH_SECTION=name,
                   BENCH_DEVICE_PROBE_TIMEOUT='0')
        if BENCH_PLATFORM is not None:
            # the parent demoted itself to CPU in-process (forced or dead
            # accelerator); the child skips the probe, so it must inherit
            # that decision or it hangs on the dead device / benches a
            # different platform than the full pass it is compared against
            env['JAX_PLATFORMS'] = 'cpu'
        if name == 'seam_dense' and R.get('seam_dense_opc'):
            # the full pass benched at the measured mixed_opc; the
            # standalone run must use the same density or the comparison
            # measures op density, not run-order sensitivity
            env.setdefault('BENCH_DENSE_OPC', str(R['seam_dense_opc']))
        try:
            proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                  env=env, capture_output=True, text=True,
                                  timeout=1800)
        except subprocess.TimeoutExpired:
            failures.append(f'{name}: standalone run timed out (1800s)')
            continue
        try:
            alone = json.loads(proc.stdout.strip().splitlines()[-1])[key]
        except Exception:
            failures.append(f'{name}: standalone run produced no {key} '
                            f'(rc={proc.returncode}, '
                            f'stderr={proc.stderr[-300:]!r})')
            continue
        if key.endswith('_pct'):
            # paired-delta percentages cross zero legitimately: judge
            # by absolute percentage-point difference, not the ratio
            delta = abs(full_val - alone)
            status = 'OK' if delta <= 2.0 else 'FAIL'
            print(f'# sanity {name}.{key}: full {full_val:.2f}% vs '
                  f'standalone {alone:.2f}% ({delta:.2f}pp) {status}',
                  file=sys.stderr)
            if delta > 2.0:
                failures.append(f'{name}.{key}: full {full_val:.2f}% vs '
                                f'standalone {alone:.2f}% = '
                                f'{delta:.2f}pp > 2pp')
            continue
        ratio = max(full_val, alone) / max(min(full_val, alone), 1e-9)
        status = 'OK' if ratio <= 2.0 else 'FAIL'
        print(f'# sanity {name}.{key}: full {full_val:.0f} vs standalone '
              f'{alone:.0f} ({ratio:.2f}x) {status}', file=sys.stderr)
        if ratio > 2.0:
            failures.append(f'{name}.{key}: full {full_val:.0f} vs '
                            f'standalone {alone:.0f} = {ratio:.2f}x > 2x')
    # not a rate ratio: the static-contract gate must read exactly zero
    # (BENCH_SANITY is the harness CI leans on, so a contract violation
    # fails it even when every throughput ratio agrees)
    av = R.get('archlint_violations')
    if av != 0:
        failures.append(f'archlint_violations={av!r} (want 0)')
    print(f'# sanity archlint.archlint_violations: {av!r} '
          f'{"OK" if av == 0 else "FAIL"}', file=sys.stderr)
    if failures:
        print(json.dumps({'sanity': 'FAIL', 'failures': failures}))
        sys.exit(1)
    print(json.dumps({'sanity': 'OK',
                      'sections_checked': list(SANITY_KEYS) +
                      ['archlint']}))


def main():
    standalone = os.environ.get('BENCH_SECTION')
    if standalone:
        _run_standalone(standalone)
        return
    if os.environ.get('BENCH_SANITY'):
        _run_sanity()
        return
    _guard_dead_accelerator()
    for name, fn in SECTIONS.items():
        fn()
        _fence()
    _final_json()


if __name__ == '__main__':
    main()
