"""Micromerge: a miniature straight-line CRDT used as the executable
specification for fuzz testing (ported semantics of the reference's oracle in
test/fuzz_test.js:12-137). Supports maps, lists, and primitives with
last-writer-wins conflict resolution; deps are vector clocks {actor: seq};
no buffering of causally-premature changes (they are a caller error here)."""


def _op_id_lt(id1, id2):
    """True iff id1 < id2 in Lamport order (counter, then actorId)."""
    c1, a1 = id1.split('@', 1)
    c2, a2 = id2.split('@', 1)
    return (int(c1), a1) < (int(c2), a2)


class Micromerge:
    def __init__(self):
        self.by_actor = {}           # actorId -> list of changes
        self.by_obj_id = {'_root': {}}
        self.metadata = {'_root': {}}

    @property
    def root(self):
        return self.by_obj_id['_root']

    def apply_change(self, change):
        last_seq = len(self.by_actor.get(change['actor'], []))
        if change['seq'] != last_seq + 1:
            raise ValueError(
                f"Expected sequence number {last_seq + 1}, got {change['seq']}")
        for actor, dep in (change.get('deps') or {}).items():
            if len(self.by_actor.get(actor, [])) < dep:
                raise ValueError(f'Missing dependency: change {dep} by {actor}')
        self.by_actor.setdefault(change['actor'], []).append(change)
        for index, op in enumerate(change['ops']):
            op = dict(op, opId=f"{change['startOp'] + index}@{change['actor']}")
            self.apply_op(op)

    def apply_op(self, op):
        if op['obj'] not in self.metadata:
            raise ValueError(f"Object does not exist: {op['obj']}")
        if op['action'] == 'makeMap':
            self.by_obj_id[op['opId']] = {}
            self.metadata[op['opId']] = {}
        elif op['action'] == 'makeList':
            self.by_obj_id[op['opId']] = []
            self.metadata[op['opId']] = []
        elif op['action'] not in ('set', 'del'):
            raise ValueError(f"Unsupported operation type: {op['action']}")

        meta = self.metadata[op['obj']]
        if isinstance(meta, list):
            if op.get('insert'):
                self._apply_list_insert(op)
            else:
                self._apply_list_update(op)
        elif meta.get(op['key']) is None or \
                _op_id_lt(meta[op['key']], op['opId']):
            meta[op['key']] = op['opId']
            if op['action'] == 'del':
                self.by_obj_id[op['obj']].pop(op['key'], None)
            elif op['action'].startswith('make'):
                self.by_obj_id[op['obj']][op['key']] = self.by_obj_id[op['opId']]
            else:
                self.by_obj_id[op['obj']][op['key']] = op['value']

    def _apply_list_insert(self, op):
        meta = self.metadata[op['obj']]
        value = self.by_obj_id[op['opId']] \
            if op['action'].startswith('make') else op['value']
        if op['key'] == '_head':
            index, visible = -1, 0
        else:
            index, visible = self._find_list_element(op['obj'], op['key'])
        if index >= 0 and not meta[index]['deleted']:
            visible += 1
        index += 1
        # RGA: skip over concurrent insertions with higher opIds
        while index < len(meta) and _op_id_lt(op['opId'], meta[index]['elemId']):
            if not meta[index]['deleted']:
                visible += 1
            index += 1
        meta.insert(index, {'elemId': op['opId'], 'valueId': op['opId'],
                            'deleted': False})
        self.by_obj_id[op['obj']].insert(visible, value)

    def _apply_list_update(self, op):
        index, visible = self._find_list_element(op['obj'], op['key'])
        meta = self.metadata[op['obj']][index]
        if op['action'] == 'del':
            if not meta['deleted']:
                del self.by_obj_id[op['obj']][visible]
            meta['deleted'] = True
        elif _op_id_lt(meta['valueId'], op['opId']):
            if not meta['deleted']:
                self.by_obj_id[op['obj']][visible] = \
                    self.by_obj_id[op['opId']] \
                    if op['action'].startswith('make') else op['value']
            meta['valueId'] = op['opId']

    def _find_list_element(self, object_id, elem_id):
        index, visible = 0, 0
        meta = self.metadata[object_id]
        while index < len(meta) and meta[index]['elemId'] != elem_id:
            if not meta[index]['deleted']:
                visible += 1
            index += 1
        if index == len(meta):
            raise ValueError(f'List element not found: {elem_id}')
        return index, visible


def expand_ops(change):
    """Expand a frontend change request's compressed ops (multi-insert
    `values` arrays, `multiOp` deletes) into individual Micromerge ops, and
    normalize elemId -> key (ref backend/columnar.js:446-475)."""
    ops = []
    op_num = change['startOp']
    for op in change['ops']:
        key = op.get('elemId', op.get('key'))
        if op['action'] == 'set' and 'values' in op:
            for i, value in enumerate(op['values']):
                ops.append({'action': 'set', 'obj': op['obj'],
                            'key': key if i == 0 else f"{op_num - 1}@{change['actor']}",
                            'insert': True, 'value': value})
                op_num += 1
        elif op['action'] == 'del' and op.get('multiOp'):
            ctr, actor = key.split('@', 1)
            for i in range(op['multiOp']):
                ops.append({'action': 'del', 'obj': op['obj'],
                            'key': f'{int(ctr) + i}@{actor}', 'insert': False})
                op_num += 1
        else:
            ops.append({'action': op['action'], 'obj': op['obj'], 'key': key,
                        'insert': bool(op.get('insert')),
                        'value': op.get('value')})
            op_num += 1
    return dict(change, ops=ops)
