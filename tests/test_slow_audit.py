"""Slow-marker audit (ISSUE-7 satellite): tier-1's 870s timeout is a
budget, and this test is its enforcement. conftest.py accumulates wall
time per test FAMILY (one parametrized function = one family, summed
across its whole matrix) and reorders this module to run LAST, so the
assertions below see the finished session.

The rule: a family not marked `slow` gets DEFAULT_BUDGET_S (~5s — new
tests that need more belong under `-m slow`, or must appear in the
grandfather table below with an explicit measured budget). The
grandfather budgets are the pre-existing heavy families at ~2x their
measured tier-1 cost on the reference box — headroom for box noise,
tight enough that a matrix that doubles fails loudly here instead of
silently eating the suite's timeout.

Scaled-up offline runs (CHAOS_SEEDS/FUZZ_CASES/etc.) legitimately blow
these budgets: the audit disarms itself when the scaling env knobs are
set, and entirely under AUTOMERGE_TPU_SLOW_AUDIT=0.
"""

import os

import conftest

# 5.0s is calibrated on the >=2-core reference box. A 1-core box
# (the round-21 driver) at least doubles every family's wall time —
# XLA compiles lose their thread pool and the 3-4s families straddle
# the default on scheduling noise alone (observed: 3.2s -> 5.4s run to
# run) — so the default scales up there. Grandfather budgets already
# carry contended-worst-case headroom and stay fixed.
DEFAULT_BUDGET_S = 5.0 if (os.cpu_count() or 2) >= 2 else 12.0

# family (tests/<file>.py::<function>) -> tier-1 budget in seconds,
# ~2.5x the family's measured cost on the reference box (2026-08-03
# full-run --durations sweep) so box noise passes but a doubled matrix
# fails. The Mosaic AOT family's SETUP used to burn ~435s on this
# image's pre-existing environment failure; since round 21 a 120s
# deadline-bounded topology probe caps that burn (the family skips on
# broken-libtpu boxes). The 600s budget is the real compile's cost on
# a working-toolchain box, where the probe passes in seconds.
GRANDFATHER_BUDGETS = {
    'tests/test_pallas.py::TestMosaicAOT::test_mosaic_compiles_variant':
        600.0,
    'tests/test_chaos.py::test_chaos_differential': 320.0,
    'tests/test_pallas.py::test_matches_jnp_path': 36.0,
    'tests/test_flight_recorder.py::'
    'test_recovery_rot_produces_forensic_dump': 27.0,
    'tests/test_chaos.py::test_chaos_lossy_wire': 25.0,
    'tests/test_flight_recorder.py::'
    'test_quarantine_dump_names_durable_id': 23.0,
    'tests/test_service_chaos.py::'
    'test_service_chaos_identical_across_device_modes': 15.0,
    'tests/test_sequence.py::TestLongDocSharding::'
    'test_sharded_matches_local': 15.0,
    # measured 3.9s isolated / ~4.8s in-suite on the reference box, but
    # observed at 22.2s under full-suite contention on this box (round
    # 14; family wall time UNCHANGED vs the prior tree, so contention,
    # not a regression) — budgeted off the contended worst case
    'tests/test_chaos.py::test_chaos_checkpoint_crash_recover': 30.0,
    'tests/test_multihost.py::'
    'test_two_process_pairwise_sync_converges': 12.0,
    # TestBenchLedger: 0.2-0.35s isolated, observed 7.9-13.7s under
    # full-suite contention on this 9p box (round 19 — file-I/O latency
    # spikes after the Mosaic-AOT burn; family cost UNCHANGED in
    # isolation, so contention budgets like the round-14 precedent)
    'tests/test_perf_obs.py::TestBenchLedger::'
    'test_append_read_roundtrip': 20.0,
    'tests/test_perf_obs.py::TestBenchLedger::'
    'test_backfill_idempotent_and_covers_every_artifact': 25.0,
    'tests/test_perf_obs.py::TestBenchLedger::'
    'test_trajectory_renders': 30.0,
    # spawns a python child (jax import) that dies inside the vacuum's
    # manifest swap; 1.8s isolated, budgeted for suite contention
    'tests/test_storage_tier.py::TestDiskArena::'
    'test_kill_mid_vacuum_recovers': 12.0,
    'tests/test_fleet_backend.py::TestSequenceSeam::'
    'test_randomized_sequence_counter_differential': 10.0,
    'tests/test_service_chaos.py::'
    'test_service_overload_brownout_smoke': 10.0,
    'tests/test_service_chaos.py::test_service_chaos_smoke': 10.0,
    # 4.3s isolated; observed 25.8s under full-suite I/O contention on
    # this 9p box (round 19 — the suite's file-heavy families draw a
    # latency lottery; family cost unchanged in isolation)
    'tests/test_durability.py::test_crashtest_smoke': 40.0,
    'tests/test_durability.py::'
    'test_recovery_rejournals_instead_of_resnapshotting': 25.0,
    'tests/test_fuzz_wire.py::test_fuzz_wire_smoke': 10.0,
    # measured 4.2-5.3s across two full runs on the 1-core round-21 box
    # (straddling the 5.0s default by box noise alone; family cost
    # unchanged in isolation) — budgeted off the contended worst case
    'tests/test_hashindex.py::TestHashIndexCore::'
    'test_host_and_device_modes_answer_identically': 12.0,
    # ISSUE-19 sanitizer smoke: the replay parent subprocess imports the
    # full stack (jax) to build the fuzz corpus before the jax-free
    # child replays it under the cached ASan .so — 5.0s isolated,
    # budgeted for suite contention like the other child-spawners
    'tests/test_native_sanitize.py::'
    'test_sanitize_smoke_replay_under_cached_so': 20.0,
    # ISSUE-19 tier-1 contract gate: one archlint subprocess over the
    # real tree (stdlib-only AST pass, ~1.1s isolated; subprocess
    # startup draws the same contention lottery as the others)
    'tests/test_archlint.py::'
    'test_real_tree_is_clean_under_checked_in_baseline': 12.0,
    # ISSUE-13 perf-observatory family: the atomic-counter hammer (6
    # threads x 10k locked incs, measured ~2s isolated) and the torn-
    # read `_sum` exposition hammer (writer thread + 50 scrapes,
    # measured ~2.2s) — budgeted at ~4x for full-suite contention on
    # this 2-core box
    'tests/test_perf_obs.py::TestAtomicCounters::'
    'test_inc_exact_under_hammer': 10.0,
    'tests/test_export.py::test_sum_consistent_under_concurrent_'
    'recording': 10.0,
    # measured 0.35s isolated (0.22s at the prior tree — the family's
    # cost is unchanged) but observed at 10.3s under full-suite
    # contention on this box (round-17 run) — the same contention
    # class as test_chaos_checkpoint_crash_recover above; budgeted off
    # the contended worst case
    'tests/test_service.py::test_brownout_widen_fsync_and_restore': 15.0,
}


def _audit_disarmed():
    if os.environ.get('AUTOMERGE_TPU_SLOW_AUDIT', '1') == '0':
        return True
    # offline scale knobs change the dose; budgets only hold for tier-1
    for knob in ('CHAOS_SEEDS', 'CHAOS_STEPS', 'FUZZ_CASES',
                 'CRASHTEST_CASES', 'N_WIRE_SEEDS'):
        if os.environ.get(knob):
            return True
    return False


def test_unmarked_families_fit_their_budgets():
    if _audit_disarmed():
        return
    over = []
    for family, seconds in sorted(conftest.FAMILY_DURATIONS.items()):
        if family in conftest.SLOW_FAMILIES:
            continue
        if family.endswith('test_unmarked_families_fit_their_budgets'):
            continue
        budget = GRANDFATHER_BUDGETS.get(family, DEFAULT_BUDGET_S)
        if seconds > budget:
            over.append(f'{family}: {seconds:.1f}s > {budget:.1f}s')
    assert not over, (
        'unmarked test families exceeded their tier-1 budgets — mark '
        'them `slow`, shrink the tier-1 dose, or (for a deliberate '
        'cost) add a measured budget to GRANDFATHER_BUDGETS:\n  '
        + '\n  '.join(over))
