"""Performance observatory coverage (ISSUE-13).

- DRIFT DETECTOR NOISE IMMUNITY: the seam-baseline detector replayed
  against per-event deltas sampled from BENCH_r07's RECORDED ±40%
  noisy-box history must fire ZERO alerts across 5 clean windows, and
  must detect a synthetic 1.3x slowdown within 2 windows — the
  windowed-mean aggregation (window_events events per judgment) is
  what earns both at once.
- KERNEL COST LEDGER: off = no counting; on = per-kind dispatches /
  blocking seconds / one signature per distinct compilation, with XLA
  cost_analysis resolved lazily and cached.
- MEMORY WATERMARKS: tier sources sampled with sticky process-lifetime
  highs; RSS always present.
- ATOMIC COUNTERS: Counters.inc is exact under a thread hammer
  (the round-15 undercount), and a pump_threads>1 ShardRouter run
  lands EXACT service health counts.
- BENCH LEDGER: atomic append, torn-tail tolerated (and disclosed) on
  read, append-after-torn-tail self-heals, backfill idempotent.
- PERF GATE: noise-aware judge (insufficient without spread data,
  quiet on clean paired rows, fires on 1.3x) and the --check self-test.
"""

import json
import os
import threading

import numpy as np
import pytest

from automerge_tpu.observability import hist as obs_hist
from automerge_tpu.observability import perf as obs_perf
from automerge_tpu.observability import recorder as obs_recorder
from automerge_tpu.observability.metrics import Counters, health_counts
from automerge_tpu.observability.perf import PerfBaselines, SeamSpec

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import sys  # noqa: E402

sys.path.insert(0, os.path.join(_ROOT, 'tools'))

import bench_ledger  # noqa: E402
import perf_gate  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_perf_state():
    obs_perf.disable_observatory()
    obs_hist.disable()
    obs_perf.reset_ledger()
    yield
    obs_perf.disable_observatory()
    obs_hist.disable()
    obs_perf.reset_ledger()


# ---- recorded noise: BENCH_r07's ±40% history ------------------------------

def _recorded_r07_deltas():
    """Relative deltas derived from the numbers BENCH_r07/r06 actually
    recorded (the measurement history that repeatedly blamed the box):
    the r07 headline, its same-day control, the thread sweep, and the
    r06 headline, each vs their common median."""
    with open(os.path.join(_ROOT, 'BENCH_r07.json')) as f:
        r07 = json.load(f)
    with open(os.path.join(_ROOT, 'BENCH_r06.json')) as f:
        r06 = json.load(f)
    values = [float(r07['parsed']['value']),
              float(r07['notes']['same_day_baseline_control_seam']),
              float(r06['parsed']['value'])]
    values += [float(v) for v in
               r07['notes']['thread_scaling_sweep'].values()]
    med = float(np.median(values))
    deltas = [v / med - 1.0 for v in values]
    # the recorded swing really is the ±40% story the ISSUE cites
    assert max(deltas) - min(deltas) > 0.4
    return deltas


class TestDriftDetector:
    def _replay(self, reg, seam, base_s, n_windows, scale=1.0, start=0):
        """Feed n_windows full windows of per-event latencies sampled
        from the recorded delta table, then tick once per window."""
        deltas = _recorded_r07_deltas()
        k = start
        for _ in range(n_windows):
            for _ in range(reg.window_events):
                reg.record(seam, base_s * scale *
                           (1.0 + deltas[k % len(deltas)]))
                k += 1
            reg.tick()
        return k

    def test_zero_false_fires_on_recorded_noise_then_detects_1p3x(self):
        reg = PerfBaselines(seams=(SeamSpec('probe', 'probe_hist_s'),),
                            window_events=32, drift_pct=0.20,
                            up_ticks=2, min_windows=2)
        fired0 = obs_perf.perf_stats()['perf_alerts_fired']
        # 5 clean windows of recorded ±40% per-event noise: quiet
        k = self._replay(reg, 'probe', 0.1, 5)
        assert obs_perf.perf_stats()['perf_alerts_fired'] == fired0
        assert not reg.active_alerts()
        state = reg.seams['probe']
        assert state.windows == 5
        assert 0.9 < state.drift < 1.1        # window means concentrated
        # synthetic 1.3x slowdown: detected within 2 windows
        self._replay(reg, 'probe', 0.1, 2, scale=1.3, start=k)
        assert obs_perf.perf_stats()['perf_alerts_fired'] == fired0 + 1
        assert reg.active_alerts() == ['probe']
        assert state.drift == pytest.approx(1.3, rel=0.1)

    def test_baseline_freezes_under_drift_and_alert_is_edge_triggered(self):
        reg = PerfBaselines(seams=(SeamSpec('probe', 'x'),),
                            window_events=8, drift_pct=0.20,
                            up_ticks=2, min_windows=2)
        self._replay(reg, 'probe', 0.1, 5)
        baseline_before = reg.seams['probe'].ewma
        fired0 = obs_perf.perf_stats()['perf_alerts_fired']
        # a sustained regression must not teach the baseline its own
        # slowdown (else the alert would self-clear)
        self._replay(reg, 'probe', 0.1, 6, scale=1.4)
        assert reg.seams['probe'].ewma == \
            pytest.approx(baseline_before, rel=0.15)
        # edge-triggered: ONE fire despite 6 drifting windows
        assert obs_perf.perf_stats()['perf_alerts_fired'] == fired0 + 1

    def test_alert_clears_after_recovery(self):
        """The clear rule judges EXCESS drift (drift - 1): a recovered
        seam back at its baseline (drift ~1.0) must clear within
        down_ticks windows — not demand the seam run 40% FASTER than
        baseline (the raw-ratio-into-_Alert bug)."""
        reg = PerfBaselines(seams=(SeamSpec('probe', 'x'),),
                            window_events=8, drift_pct=0.20,
                            up_ticks=2, down_ticks=4, min_windows=2)
        self._replay(reg, 'probe', 0.1, 5)
        self._replay(reg, 'probe', 0.1, 4, scale=1.5)
        assert reg.active_alerts() == ['probe']
        cleared0 = obs_perf.perf_stats()['perf_alerts_cleared']
        # full recovery to baseline, same recorded noise
        self._replay(reg, 'probe', 0.1, 8)
        assert reg.active_alerts() == []
        assert obs_perf.perf_stats()['perf_alerts_cleared'] == \
            cleared0 + 1

    def test_fire_lands_in_flight_recorder(self):
        obs_recorder.clear_events()
        reg = PerfBaselines(seams=(SeamSpec('probe', 'x'),),
                            window_events=8, drift_pct=0.20,
                            up_ticks=2, min_windows=2)
        self._replay(reg, 'probe', 0.1, 4)
        self._replay(reg, 'probe', 0.1, 3, scale=1.5)
        kinds = [e['kind'] for e in obs_recorder.recent_events()]
        assert 'perf_drift' in kinds
        dump = obs_recorder.last_flight_record()
        assert dump['trigger'] == 'perf'
        assert dump['detail']['seam'] == 'probe'
        assert dump['detail']['drift'] >= 1.2
        assert len(dump['detail']['window_means_s']) >= 4

    def test_histogram_feed_and_gauges(self):
        obs_hist.enable()
        reg = obs_perf.enable_baselines(window_events=4, min_windows=1)
        try:
            for _ in range(8):
                obs_hist.record_value('apply_batch_s', 0.05, scale=1e9,
                                      unit='s')
            reg.tick()
            gauges = obs_perf.baseline_gauges()
            assert 'apply_batch' in gauges
            g = gauges['apply_batch']
            assert g['window_s'] == pytest.approx(0.05)
            assert g['windows'] == 2
            assert g['alert'] == 0
        finally:
            obs_perf.disable_baselines()

    def test_service_tick_drives_default_registry(self):
        from automerge_tpu.fleet.backend import DocFleet
        from automerge_tpu.service import DocService
        reg = obs_perf.enable_baselines()
        try:
            service = DocService(fleet=DocFleet(), slo=False)
            before = reg.ticks
            service.pump()
            assert reg.ticks == before + 1
        finally:
            obs_perf.disable_baselines()


# ---- kernel cost ledger ----------------------------------------------------

class TestKernelLedger:
    def test_off_by_default_counts_when_enabled(self):
        import jax
        import jax.numpy as jnp
        fn = obs_perf.instrument_kernel(
            'probe_kernel', jax.jit(lambda x: jnp.sum(x * 2)))
        fn(jnp.arange(8))
        assert 'probe_kernel' not in obs_perf.kernel_snapshot()
        obs_perf.enable_ledger()
        fn(jnp.arange(8))
        fn(jnp.arange(8))
        fn(jnp.arange(16))          # a second compilation signature
        snap = obs_perf.kernel_snapshot()['probe_kernel']
        assert snap['dispatches'] == 3
        assert snap['signatures'] == 2
        assert snap['seconds'] > 0

    def test_report_resolves_and_caches_cost_analysis(self):
        import jax
        import jax.numpy as jnp
        fn = obs_perf.instrument_kernel(
            'probe_cost', jax.jit(lambda x: x @ x))
        obs_perf.enable_ledger()
        fn(jnp.ones((16, 16)))
        report = obs_perf.kernel_report()['probe_cost']
        sig = report['signatures'][0]
        assert sig['dispatches'] == 1
        # CPU XLA reports flops for a matmul; tolerate backends that
        # return an error dict, but never a crash
        assert 'cost' in sig
        if 'flops' in sig['cost']:
            assert sig['cost']['flops'] > 0
            assert report['flops_total'] > 0

    def test_dump_ledger_is_floor_readable(self, tmp_path):
        import jax
        import jax.numpy as jnp
        fn = obs_perf.instrument_kernel(
            'probe_dump', jax.jit(lambda x: x + 1))
        obs_perf.enable_ledger()
        fn(jnp.arange(4))
        path = obs_perf.dump_ledger(str(tmp_path / 'ledger.json'))
        with open(path) as f:
            dump = json.load(f)
        assert dump['kind'] == 'kernel_ledger'
        assert 'probe_dump' in dump['kernels']


# ---- memory watermarks -----------------------------------------------------

class TestWatermarks:
    def test_rss_and_sticky_highs(self):
        obs_perf.reset_watermarks()
        value = [1000]
        obs_perf.register_mem_source('probe_tier', lambda: value[0])
        try:
            cur = obs_perf.sample_watermarks()
            assert cur['rss'] > 0
            assert cur['probe_tier'] == 1000
            value[0] = 5000
            obs_perf.sample_watermarks()
            value[0] = 200
            snap = obs_perf.watermark_snapshot()
            assert snap['current']['probe_tier'] == 200
            assert snap['high']['probe_tier'] == 5000   # sticky
            assert snap['high']['rss'] >= snap['current']['rss'] > 0
        finally:
            obs_perf._mem_sources.pop('probe_tier', None)

    def test_fleet_and_store_tiers_registered(self):
        from automerge_tpu.fleet.backend import DocFleet, init_docs
        from automerge_tpu.fleet.storage import MainStore
        fleet = DocFleet()
        init_docs(4, fleet)
        store = MainStore()
        store.add(b'x' * 100, ['ab' * 32], {'ab' * 32: 1}, 3, 1)
        cur = obs_perf.sample_watermarks()
        assert cur['mainstore_bytes'] >= 100
        assert 'fleet_resident_bytes' in cur
        assert store.resident_bytes() >= 100 + 32


# ---- atomic counters under threads -----------------------------------------

class TestAtomicCounters:
    def test_inc_exact_under_hammer(self):
        c = Counters({'hits': 0})
        threads, per_thread = 6, 10000

        def hammer():
            for _ in range(per_thread):
                c.inc('hits')

        ts = [threading.Thread(target=hammer) for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # a plain dict loses updates here (the round-15 undercount);
        # the locked inc must be EXACT
        assert c['hits'] == threads * per_thread

    def test_inc_negative_and_missing_key(self):
        c = Counters()
        assert c.inc('gauge') == 1
        assert c.inc('gauge', -1) == 0
        c['reset_me'] = 7
        c['reset_me'] = 0
        assert c['reset_me'] == 0

    def test_threaded_router_pump_counts_exact(self):
        """The satellite pin: at pump_threads>1, module health counters
        land EXACT (they are Counters now, not bare dict increments)."""
        from automerge_tpu import native
        if not native.available():
            pytest.skip('native codec unavailable')
        from automerge_tpu.columnar import encode_change
        from automerge_tpu.service.backoff import Backoff
        from automerge_tpu.shard import ShardRouter
        clk = [0.0]
        router = ShardRouter(n_shards=4, clock=lambda: clk[0],
                             pump_threads=4, lease_ticks=3,
                             backoff=Backoff(base=0.02, factor=1.5,
                                             cap=0.32, retries=14,
                                             seed=1))
        n_tenants, per_tenant = 12, 3
        try:
            for i in range(n_tenants):
                router.open_tenant(f't{i}')
            before = health_counts()
            tickets = []
            for i in range(n_tenants):
                for seq in range(1, per_tenant + 1):
                    tickets.append(router.submit(
                        f't{i}', 'apply', [encode_change({
                            'actor': f'{i:02x}' * 16, 'seq': seq,
                            'startOp': seq, 'time': 0, 'message': '',
                            'deps': [],
                            'ops': [{'action': 'set', 'obj': '_root',
                                     'key': 'k', 'value': seq,
                                     'datatype': 'int', 'pred': []}]})]))
            for _ in range(400):
                if all(t.done for t in tickets):
                    break
                router.pump(now=clk[0])
                clk[0] += 0.02
            assert all(t.status == 'ok' for t in tickets), \
                [(t.status, t.error) for t in tickets if not t.done
                 or t.status != 'ok'][:4]
            after = health_counts()
            moved = {k: after[k] - before.get(k, 0)
                     for k in after if after[k] != before.get(k, 0)}
            n = n_tenants * per_tenant
            # no retries in a clean router: submit == dispatch == done
            assert moved.get('shard_retries', 0) == 0
            assert moved.get('service_requests') == n, moved
            assert moved.get('service_completed') == n, moved
        finally:
            router.close()


# ---- bench ledger ----------------------------------------------------------

class TestBenchLedger:
    def _row(self, i, **kw):
        return bench_ledger.make_row({'probe_rate': 100.0 + i},
                                     source=f'test:{i}', ts=float(i),
                                     sha='abc', **kw)

    def test_append_read_roundtrip(self, tmp_path):
        path = str(tmp_path / 'ledger.jsonl')
        for i in range(3):
            bench_ledger.append_row(self._row(i), path)
        rows, report = bench_ledger.read_rows(path)
        assert [r['source'] for r in rows] == ['test:0', 'test:1',
                                               'test:2']
        assert report == {'torn_tail': False, 'corrupt': 0}

    def test_torn_tail_tolerated_and_disclosed(self, tmp_path):
        path = str(tmp_path / 'ledger.jsonl')
        bench_ledger.append_row(self._row(0), path)
        bench_ledger.append_row(self._row(1), path)
        with open(path, 'a') as f:      # crash mid-append: partial line
            f.write('{"schema": 1, "ts": 99, "sou')
        rows, report = bench_ledger.read_rows(path)
        assert len(rows) == 2           # complete rows all survive
        assert report['torn_tail'] is True
        assert report['corrupt'] == 0

    def test_append_after_torn_tail_self_heals(self, tmp_path):
        path = str(tmp_path / 'ledger.jsonl')
        bench_ledger.append_row(self._row(0), path)
        with open(path, 'a') as f:
            f.write('{"torn')
        bench_ledger.append_row(self._row(1), path)
        rows, report = bench_ledger.read_rows(path)
        # the new row survives intact; the torn fragment reads as ONE
        # disclosed corrupt line, not a corrupted new row
        assert [r['source'] for r in rows] == ['test:0', 'test:1']
        assert report['corrupt'] == 1
        assert report['torn_tail'] is False

    def test_backfill_idempotent_and_covers_every_artifact(self,
                                                          tmp_path):
        path = str(tmp_path / 'ledger.jsonl')
        added = bench_ledger.backfill(path)
        import glob
        artifacts = glob.glob(os.path.join(_ROOT, 'BENCH_r*.json'))
        assert len(added) == len(artifacts)
        assert bench_ledger.backfill(path) == []    # idempotent
        rows, _ = bench_ledger.read_rows(path)
        assert len(rows) == len(artifacts)
        assert all(r['metrics'] for r in rows)

    def test_repo_ledger_backfilled(self):
        """The acceptance artifact: BENCH_LEDGER.jsonl at the repo root
        holds every historical BENCH_r*.json."""
        rows, report = bench_ledger.read_rows(
            os.path.join(_ROOT, 'BENCH_LEDGER.jsonl'))
        import glob
        artifacts = {f'backfill:{os.path.basename(p)}' for p in
                     glob.glob(os.path.join(_ROOT, 'BENCH_r*.json'))}
        sources = {r['source'] for r in rows}
        assert artifacts <= sources, artifacts - sources
        assert report['corrupt'] == 0

    def test_trajectory_renders(self, tmp_path, capsys):
        path = str(tmp_path / 'ledger.jsonl')
        bench_ledger.backfill(path)
        bench_ledger.render_trajectory(path)
        out = capsys.readouterr().out
        assert 'seam_rate' in out
        assert 'ledger rows' in out


# ---- perf gate -------------------------------------------------------------

class TestPerfGate:
    def test_check_self_test_passes(self, capsys):
        assert perf_gate.check() is True

    def test_insufficient_without_spread(self):
        head = bench_ledger.make_row({'x_rate': 100.0}, source='h',
                                     ts=9.0, sha='a')
        result = perf_gate.judge(head, [])
        assert result['ok'] is True
        assert result['findings'][0]['verdict'] == 'insufficient'

    def test_latency_direction(self):
        box = bench_ledger.box_fingerprint()
        rows = [bench_ledger.make_row(
            {'probe_p99_ms': 10.0}, reps={'probe_p99_ms': [9.8, 10.0,
                                                           10.2]},
            source=f's{i}', ts=float(i), box=box, sha='a')
            for i in range(5)]
        head = bench_ledger.make_row(
            {'probe_p99_ms': 16.0}, reps={'probe_p99_ms': [15.8, 16.0,
                                                           16.2]},
            source='head', ts=9.0, box=box, sha='b')
        result = perf_gate.judge(head, rows)
        assert result['findings'][0]['verdict'] == 'regression'
        # and the inverse (latency DROP) is an improvement, not a fire
        head['metrics']['probe_p99_ms'] = 6.0
        result = perf_gate.judge(head, rows)
        assert result['findings'][0]['verdict'] == 'improvement'
