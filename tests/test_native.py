"""Native C++ codec tests: differential vs the Python codecs, hashlib, and
zlib, plus the end-to-end ingest pipeline (binary change -> native column
decode -> fleet tensors) against the host engine."""

import hashlib
import os
import random
import zlib

import numpy as np
import pytest

from automerge_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native toolchain unavailable')


class TestSha256:
    def test_matches_hashlib(self):
        for n in (0, 1, 55, 56, 63, 64, 65, 127, 128, 1000, 100000):
            data = os.urandom(n)
            assert native.sha256(data) == hashlib.sha256(data).digest()

    def test_batched(self):
        bufs = [os.urandom(i * 7 + 1) for i in range(50)]
        assert native.sha256_batch(bufs) == \
            [hashlib.sha256(b).digest() for b in bufs]


class TestDeflate:
    def test_round_trip_and_zlib_interop(self):
        data = os.urandom(5000) + b'a' * 5000
        compressed = native.deflate_raw(data)
        assert zlib.decompress(compressed, -15) == data
        co = zlib.compressobj(6, zlib.DEFLATED, -15)
        zc = co.compress(data) + co.flush()
        assert native.inflate_raw(zc) == data
        assert native.inflate_raw(compressed) == data

    def test_inflate_garbage_rejected(self):
        with pytest.raises(ValueError):
            native.inflate_raw(b'\xff\xff\xff\xff', max_size=1 << 16)


class TestColumnDecoders:
    def test_rle_int_differential(self):
        from automerge_tpu.encoding import RLEEncoder
        rng = random.Random(7)
        seq = []
        for _ in range(500):
            r = rng.random()
            if r < 0.2:
                seq.append(None)
            elif r < 0.7:
                seq.append(rng.randrange(-50, 50))
            else:
                seq.append(seq[-1] if seq and seq[-1] is not None else 3)
        enc = RLEEncoder('int')
        for v in seq:
            enc.append_value(v)
        vals, valid = native.decode_rle_column(enc.buffer, signed=True)
        assert [(int(v), bool(m)) for v, m in zip(vals, valid)] == \
            [(v if v is not None else 0, v is not None) for v in seq]

    def test_rle_uint_differential(self):
        from automerge_tpu.encoding import RLEEncoder
        rng = random.Random(9)
        seq = [None if rng.random() < 0.15 else rng.randrange(0, 2 ** 40)
               for _ in range(300)]
        enc = RLEEncoder('uint')
        for v in seq:
            enc.append_value(v)
        vals, valid = native.decode_rle_column(enc.buffer, signed=False)
        assert [(int(v) if m else None) for v, m in zip(vals, valid)] == seq

    def test_delta_differential(self):
        from automerge_tpu.encoding import DeltaEncoder
        rng = random.Random(11)
        seq = [None if rng.random() < 0.1 else rng.randrange(0, 10 ** 6)
               for _ in range(400)]
        enc = DeltaEncoder()
        for v in seq:
            enc.append_value(v)
        vals, valid = native.decode_delta_column(enc.buffer)
        assert [(int(v) if m else None) for v, m in zip(vals, valid)] == seq

    def test_boolean_differential(self):
        from automerge_tpu.encoding import BooleanEncoder
        rng = random.Random(13)
        seq = [rng.random() < 0.5 for _ in range(300)]
        enc = BooleanEncoder()
        for v in seq:
            enc.append_value(v)
        vals, valid = native.decode_boolean_column(enc.buffer)
        assert [bool(v) for v in vals] == seq
        assert valid.all()

    def test_malformed_rejected(self):
        for bad in ([1, 1], [2, 1, 2, 1], [0, 0], [0x7f]):
            with pytest.raises(ValueError):
                native.decode_rle_column(bytes(bad), signed=True)


class TestIngestPipeline:
    def test_ingest_matches_host_engine(self):
        import automerge_tpu.backend as Backend
        from automerge_tpu.columnar import encode_change
        from automerge_tpu.common import lamport_key
        from automerge_tpu.fleet import FleetState, apply_op_batch
        from automerge_tpu.fleet.ingest import (
            changes_to_op_batch, KeyInterner, ActorInterner)

        rng = random.Random(123)
        actors = ['aa' * 4, 'bb' * 4, 'cc' * 4]
        n_docs, n_keys = 6, 8
        per_doc = []
        host_backends = []
        for d in range(n_docs):
            changes = []
            seqs = {a: 0 for a in actors}
            ctr = 1
            for _ in range(12):
                a = rng.choice(actors)
                seqs[a] += 1
                n_ops = rng.randrange(1, 4)
                ops = [{'action': 'set', 'obj': '_root',
                        'key': f'k{rng.randrange(n_keys)}',
                        'value': rng.randrange(1, 10 ** 6), 'datatype': 'int',
                        'pred': []} for _ in range(n_ops)]
                changes.append(encode_change(
                    {'actor': a, 'seq': seqs[a], 'startOp': ctr, 'time': 0,
                     'message': '', 'deps': [], 'ops': ops}))
                ctr += n_ops
            per_doc.append(changes)
            backend = Backend.init()
            backend['state'].apply_changes(list(changes))
            host_backends.append(backend)

        key_interner, actor_interner = KeyInterner(), ActorInterner()
        batch = changes_to_op_batch(per_doc, key_interner, actor_interner)
        state = FleetState.empty(n_docs, max(len(key_interner), 1))
        state, stats = apply_op_batch(state, batch)
        values = np.asarray(state.values)

        for d in range(n_docs):
            props = Backend.get_patch(host_backends[d])['diffs']['props']
            for key, conflict in props.items():
                winner = max(conflict.keys(), key=lamport_key)
                assert values[d, key_interner.index[key]] == \
                    conflict[winner]['value']

    def test_ingest_rejects_non_map_ops(self):
        from automerge_tpu.columnar import encode_change
        from automerge_tpu.fleet.ingest import (
            changes_to_op_batch, KeyInterner, ActorInterner)
        change = encode_change({
            'actor': 'aaaa', 'seq': 1, 'startOp': 1, 'time': 0, 'message': '',
            'deps': [], 'ops': [
                {'action': 'makeList', 'obj': '_root', 'key': 'l', 'pred': []}]})
        with pytest.raises(ValueError):
            changes_to_op_batch([[change]], KeyInterner(), ActorInterner())


class TestBuildDocument:
    """Native mirror-free save (am_build_document): byte-identical to the
    host OpSet's canonical save() on the same change log."""

    def _assert_native_matches_host(self, doc):
        import automerge_tpu as A
        from automerge_tpu import backend as Backend
        host_bytes = bytes(A.save(doc))
        changes = [bytes(c) for c in A.get_all_changes(doc)]
        hb = Backend.load(host_bytes)
        built = native.build_document(changes, Backend.get_heads(hb))
        assert built is not None
        assert built == host_bytes

    def test_corpus(self):
        import automerge_tpu as A
        A1, A2 = '01' * 8, '89' * 8
        docs = []
        d = A.from_({'x': 1, 's': 'str', 'c': A.Counter(3), 'f': 1.5,
                     'b': True, 'n': None, 'u': A.Uint(9),
                     'ts': A.Int(1589032171000)}, A1)
        d = A.change(d, lambda r: r['c'].increment(4))
        docs.append(d)
        d = A.from_({'cfg': {'deep': {'er': 'x'}}, 'tbl': A.Table()}, A1)
        d = A.change(d, lambda r: r['tbl'].add({'row': 1}))
        docs.append(d)
        d = A.from_({'t': A.Text('hello'), 'l': [1, 2, 3]}, A1)
        d = A.change(d, lambda r: (r['t'].delete_at(1),
                                   r['t'].insert_at(0, 'ab'),
                                   r['l'].delete_at(2),
                                   r['l'].insert_at(0, 0)))
        docs.append(d)
        # unicode keys incl. astral plane (UTF-16 key ordering)
        d = A.from_({'\U0001F600smile': 1, '�repl': 2, 'plain': 3,
                     'éacute': 4}, A1)
        docs.append(d)
        # multi-actor concurrent conflicts + deletes
        b1 = A.from_({'k': 'one', 'gone': 1}, A1)
        b2 = A.merge(A.init(A2), b1)
        b1 = A.change(b1, lambda r: r.__setitem__('k', 'a'))
        b2 = A.change(b2, lambda r: (r.__setitem__('k', 'b'),
                                     r.__delitem__('gone')))
        docs.append(A.merge(b1, b2))
        # empty change in history
        d = A.from_({'v': 1}, A1)
        d = A.empty_change(d)
        docs.append(d)
        for doc in docs:
            self._assert_native_matches_host(doc)

    def test_long_text_deflated_columns(self):
        """Documents past DEFLATE_MIN_SIZE exercise the native per-column
        deflate (must byte-match Python's zlib level-6 raw stream)."""
        import automerge_tpu as A
        d = A.from_({'t': A.Text('abcdefgh' * 200)}, '01' * 8)
        d = A.change(d, lambda r: r['t'].delete_at(5, 50))
        self._assert_native_matches_host(d)

    def test_fuzz_differential(self):
        import random
        import automerge_tpu as A
        A1, A2, A3 = '01' * 8, '89' * 8, 'fe' * 8
        rng = random.Random(11)
        alphabet = 'abcdefghij'
        for trial in range(5):
            actors = [A1, A2, A3]
            base = A.from_({'t': A.Text('seed'), 'm': {}, 'k': 0}, actors[0])
            reps = [base] + [A.merge(A.init(a), base) for a in actors[1:]]
            for step in range(15):
                i = rng.randrange(len(reps))

                def edit(r, rng=rng):
                    roll = rng.random()
                    t = r['t']
                    if roll < 0.25 and len(t):
                        t.delete_at(rng.randrange(len(t)))
                    elif roll < 0.45:
                        t.insert_at(rng.randrange(len(t) + 1),
                                    rng.choice(alphabet))
                    elif roll < 0.6 and len(t):
                        t.set(rng.randrange(len(t)),
                              rng.choice(alphabet).upper())
                    elif roll < 0.8:
                        r['m'][rng.choice(alphabet)] = rng.randrange(50)
                    else:
                        r['k'] = rng.randrange(1000)
                reps[i] = A.change(reps[i], edit)
                if rng.random() < 0.25:
                    a, b = rng.sample(range(len(reps)), 2)
                    reps[a] = A.merge(reps[a], reps[b])
            final = reps[0]
            for other in reps[1:]:
                final = A.merge(final, other)
            self._assert_native_matches_host(final)

    def test_convergent_replicas_identical_bytes(self):
        """Two replicas that applied the same changes in different orders
        must produce identical native saves (canonical ordering)."""
        import automerge_tpu as A
        from automerge_tpu import backend as Backend
        A1, A2 = '01' * 8, '89' * 8
        b1 = A.from_({'k': 1}, A1)
        b2 = A.merge(A.init(A2), b1)
        b1 = A.change(b1, lambda r: r.__setitem__('a', 1))
        b2 = A.change(b2, lambda r: r.__setitem__('b', 2))
        m1 = A.merge(A.clone(b1), b2)     # a's changes first
        m2 = A.merge(A.clone(b2), b1)     # b's changes first
        c1 = [bytes(c) for c in A.get_all_changes(m1)]
        c2 = [bytes(c) for c in A.get_all_changes(m2)]
        assert c1 != c2                   # different application orders
        h1 = Backend.get_heads(Backend.load(A.save(m1)))
        s1 = native.build_document(c1, h1)
        s2 = native.build_document(c2, h1)
        assert s1 == s2
        assert s1 == bytes(A.save(m1)) == bytes(A.save(m2))
