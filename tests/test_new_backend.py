"""Deep CRDT semantics tests, ported from reference test/new_backend_test.js:
RGA concurrent insertions (same position and head), counters in lists,
conflict shapes, plus permutation-convergence fuzzing in the spirit of
test/fuzz_test.js (the backend itself under op-permutations, with the host
engine as its own oracle via order-independence)."""

import itertools
import random

import pytest

from automerge_tpu.backend.op_set import OpSet
from automerge_tpu.columnar import encode_change, decode_change

A1, A2 = '01234567', '89abcdef'


def hash_of(change):
    return decode_change(encode_change(change))['hash']


class TestConcurrentInsertions:
    def changes(self):
        change1 = {'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeText', 'obj': '_root', 'key': 'text', 'insert': False,
             'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': '_head', 'insert': True,
             'value': 'a', 'pred': []}]}
        change2 = {'actor': A1, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'2@{A1}', 'insert': True,
             'value': 'c', 'pred': []}]}
        change3 = {'actor': A2, 'seq': 1, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'2@{A1}', 'insert': True,
             'value': 'b', 'pred': []}]}
        return change1, change2, change3

    def test_same_position_order1(self):
        """(ref new_backend_test.js:725-780)"""
        change1, change2, change3 = self.changes()
        backend = OpSet()
        patch1 = backend.apply_changes([encode_change(change1)])
        assert patch1['diffs']['props']['text'][f'1@{A1}']['edits'] == [
            {'action': 'insert', 'index': 0, 'elemId': f'2@{A1}',
             'opId': f'2@{A1}', 'value': {'type': 'value', 'value': 'a'}}]
        patch2 = backend.apply_changes([encode_change(change2)])
        assert patch2['diffs']['props']['text'][f'1@{A1}']['edits'] == [
            {'action': 'insert', 'index': 1, 'elemId': f'3@{A1}',
             'opId': f'3@{A1}', 'value': {'type': 'value', 'value': 'c'}}]
        patch3 = backend.apply_changes([encode_change(change3)])
        # actor2's insert (lower actorId) goes after actor1's concurrent one
        assert patch3['diffs']['props']['text'][f'1@{A1}']['edits'] == [
            {'action': 'insert', 'index': 1, 'elemId': f'3@{A2}',
             'opId': f'3@{A2}', 'value': {'type': 'value', 'value': 'b'}}]
        assert patch3['deps'] == sorted([hash_of(change2), hash_of(change3)])

    def test_same_position_order2(self):
        change1, change2, change3 = self.changes()
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        patch3 = backend.apply_changes([encode_change(change3)])
        assert patch3['diffs']['props']['text'][f'1@{A1}']['edits'] == [
            {'action': 'insert', 'index': 1, 'elemId': f'3@{A2}',
             'opId': f'3@{A2}', 'value': {'type': 'value', 'value': 'b'}}]
        patch2 = backend.apply_changes([encode_change(change2)])
        assert patch2['diffs']['props']['text'][f'1@{A1}']['edits'] == [
            {'action': 'insert', 'index': 2, 'elemId': f'3@{A1}',
             'opId': f'3@{A1}', 'value': {'type': 'value', 'value': 'c'}}]

    def test_both_orders_converge(self):
        change1, change2, change3 = self.changes()
        b1, b2 = OpSet(), OpSet()
        for c in (change1, change2, change3):
            b1.apply_changes([encode_change(c)])
        for c in (change1, change3, change2):
            b2.apply_changes([encode_change(c)])
        assert b1.get_patch()['diffs'] == b2.get_patch()['diffs']
        # Document order: a, b, c
        edits = b1.get_patch()['diffs']['props']['text'][f'1@{A1}']['edits']
        assert edits == [{'action': 'multi-insert', 'index': 0,
                          'elemId': f'2@{A1}', 'values': ['a', 'b', 'c']}] or \
            [e['value']['value'] for e in edits] == ['a', 'b', 'c']

    def test_head_insertions(self):
        """(ref new_backend_test.js:814-880)"""
        change1 = {'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeText', 'obj': '_root', 'key': 'text', 'insert': False,
             'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': '_head', 'insert': True,
             'value': 'd', 'pred': []}]}
        change2 = {'actor': A1, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': '_head', 'insert': True,
             'value': 'c', 'pred': []}]}
        change3 = {'actor': A2, 'seq': 1, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': '_head', 'insert': True,
             'value': 'a', 'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'3@{A2}', 'insert': True,
             'value': 'b', 'pred': []}]}

        backend1 = OpSet()
        backend1.apply_changes([encode_change(change1)])
        patch2 = backend1.apply_changes([encode_change(change2)])
        assert patch2['diffs']['props']['text'][f'1@{A1}']['edits'] == [
            {'action': 'insert', 'index': 0, 'elemId': f'3@{A1}',
             'opId': f'3@{A1}', 'value': {'type': 'value', 'value': 'c'}}]
        patch3 = backend1.apply_changes([encode_change(change3)])
        assert patch3['diffs']['props']['text'][f'1@{A1}']['edits'] == [
            {'action': 'multi-insert', 'index': 0, 'elemId': f'3@{A2}',
             'values': ['a', 'b']}]

        backend2 = OpSet()
        backend2.apply_changes([encode_change(change1)])
        patch3b = backend2.apply_changes([encode_change(change3)])
        assert patch3b['diffs']['props']['text'][f'1@{A1}']['edits'] == [
            {'action': 'multi-insert', 'index': 0, 'elemId': f'3@{A2}',
             'values': ['a', 'b']}]
        patch2b = backend2.apply_changes([encode_change(change2)])
        assert patch2b['diffs']['props']['text'][f'1@{A1}']['edits'] == [
            {'action': 'insert', 'index': 2, 'elemId': f'3@{A1}',
             'opId': f'3@{A1}', 'value': {'type': 'value', 'value': 'c'}}]

        # Final order on both: a b c d
        for backend in (backend1, backend2):
            edits = backend.get_patch()['diffs']['props']['text'][f'1@{A1}']['edits']
            flat = []
            for e in edits:
                if e['action'] == 'multi-insert':
                    flat.extend(e['values'])
                else:
                    flat.append(e['value']['value'])
            assert flat == ['a', 'b', 'c', 'd']


class TestCountersInLists:
    def test_counter_in_list_element(self):
        """(ref new_backend_test.js:1196+)"""
        change1 = {'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'counts', 'insert': False,
             'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': '_head', 'insert': True,
             'value': 1, 'datatype': 'counter', 'pred': []}]}
        change2 = {'actor': A1, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'inc', 'obj': f'1@{A1}', 'elemId': f'2@{A1}',
             'value': 2, 'pred': [f'2@{A1}']}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        patch2 = backend.apply_changes([encode_change(change2)])
        assert patch2['diffs']['props']['counts'][f'1@{A1}']['edits'] == [
            {'action': 'update', 'index': 0, 'opId': f'2@{A1}',
             'value': {'type': 'value', 'datatype': 'counter', 'value': 3}}]
        # whole-doc patch shows the accumulated value too
        edits = backend.get_patch()['diffs']['props']['counts'][f'1@{A1}']['edits']
        assert edits == [
            {'action': 'insert', 'index': 0, 'elemId': f'2@{A1}',
             'opId': f'2@{A1}',
             'value': {'type': 'value', 'datatype': 'counter', 'value': 3}}]

    def test_concurrent_increments_in_list(self):
        change1 = {'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'counts', 'insert': False,
             'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': '_head', 'insert': True,
             'value': 10, 'datatype': 'counter', 'pred': []}]}
        change2 = {'actor': A1, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'inc', 'obj': f'1@{A1}', 'elemId': f'2@{A1}', 'value': 2,
             'pred': [f'2@{A1}']}]}
        change3 = {'actor': A2, 'seq': 1, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'inc', 'obj': f'1@{A1}', 'elemId': f'2@{A1}', 'value': 5,
             'pred': [f'2@{A1}']}]}
        for order in ((change2, change3), (change3, change2)):
            backend = OpSet()
            backend.apply_changes([encode_change(change1)])
            for c in order:
                backend.apply_changes([encode_change(c)])
            edits = backend.get_patch()['diffs']['props']['counts'][f'1@{A1}']['edits']
            assert edits[0]['value'] == \
                {'type': 'value', 'datatype': 'counter', 'value': 17}


class TestPermutationConvergence:
    """Fuzz in the spirit of test/fuzz_test.js: causally-concurrent changes
    applied in every permutation must converge to the same document."""

    def _random_concurrent_changes(self, rng, n_actors=3):
        actors = [f'{i + 1:02d}' * 4 for i in range(n_actors)]
        base = {'actor': actors[0], 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'list', 'insert': False,
             'pred': []},
            {'action': 'set', 'obj': f'1@{actors[0]}', 'elemId': '_head',
             'insert': True, 'value': 'x', 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'shared', 'value': 0,
             'datatype': 'int', 'pred': []}]}
        base_hash = hash_of(base)
        concurrent = []
        for i, actor in enumerate(actors):
            ops = []
            start_op = 4
            ctr = start_op
            choice = rng.randrange(4)
            if choice == 0:
                ops.append({'action': 'set', 'obj': '_root', 'key': 'shared',
                            'value': i + 10, 'datatype': 'int',
                            'pred': [f'3@{actors[0]}']})
                ctr += 1
            elif choice == 1:
                ops.append({'action': 'set', 'obj': f'1@{actors[0]}',
                            'elemId': '_head', 'insert': True,
                            'value': f'i{i}', 'pred': []})
                ctr += 1
            elif choice == 2:
                ops.append({'action': 'set', 'obj': f'1@{actors[0]}',
                            'elemId': f'2@{actors[0]}', 'insert': True,
                            'value': f't{i}', 'pred': []})
                ctr += 1
            else:
                ops.append({'action': 'set', 'obj': f'1@{actors[0]}',
                            'elemId': f'2@{actors[0]}',
                            'value': f'u{i}', 'pred': [f'2@{actors[0]}']})
                ctr += 1
            ops.append({'action': 'set', 'obj': '_root', 'key': f'k{i}',
                        'value': i, 'datatype': 'int', 'pred': []})
            seq = 2 if actor == actors[0] else 1
            concurrent.append({'actor': actor, 'seq': seq, 'startOp': start_op,
                               'time': 0, 'deps': [base_hash], 'ops': ops})
        return base, concurrent

    def test_all_permutations_converge(self):
        rng = random.Random(2024)
        for trial in range(6):
            base, concurrent = self._random_concurrent_changes(rng)
            encoded = [encode_change(c) for c in concurrent]
            reference = None
            for perm in itertools.permutations(range(len(encoded))):
                backend = OpSet()
                backend.apply_changes([encode_change(base)])
                for i in perm:
                    backend.apply_changes([encoded[i]])
                diffs = backend.get_patch()['diffs']
                if reference is None:
                    reference = diffs
                else:
                    assert diffs == reference, f'trial {trial} perm {perm} diverged'

    def test_batch_vs_incremental_application(self):
        rng = random.Random(7)
        base, concurrent = self._random_concurrent_changes(rng)
        encoded = [encode_change(c) for c in concurrent]
        b1 = OpSet()
        b1.apply_changes([encode_change(base)] + encoded)
        b2 = OpSet()
        b2.apply_changes([encode_change(base)])
        for e in encoded:
            b2.apply_changes([e])
        assert b1.get_patch()['diffs'] == b2.get_patch()['diffs']

    def test_save_load_convergence(self):
        rng = random.Random(99)
        base, concurrent = self._random_concurrent_changes(rng)
        backend = OpSet()
        backend.apply_changes(
            [encode_change(base)] + [encode_change(c) for c in concurrent])
        loaded = OpSet(backend.save())
        assert loaded.get_patch()['diffs'] == backend.get_patch()['diffs']
        assert loaded.heads == backend.heads
        assert loaded.clock == backend.clock


class TestLongTextStress:
    """Long-text workload (ref new_backend_test.js:2063-2193 scale)."""

    def test_sequential_insertions(self):
        backend = OpSet()
        n = 600
        change1 = {'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [{'action': 'makeText', 'obj': '_root', 'key': 'text',
                            'insert': False, 'pred': []}]}
        backend.apply_changes([encode_change(change1)])
        prev_hash = hash_of(change1)
        elem = '_head'
        for i in range(n):
            change = {'actor': A1, 'seq': i + 2, 'startOp': i + 2, 'time': 0,
                      'deps': [prev_hash], 'ops': [
                {'action': 'set', 'obj': f'1@{A1}', 'elemId': elem,
                 'insert': True, 'value': chr(97 + i % 26), 'pred': []}]}
            backend.apply_changes([encode_change(change)])
            prev_hash = hash_of(change)
            elem = f'{i + 2}@{A1}'
        edits = backend.get_patch()['diffs']['props']['text'][f'1@{A1}']['edits']
        assert edits[0]['action'] == 'multi-insert'
        assert len(edits[0]['values']) == n
        text = ''.join(edits[0]['values'])
        assert text == ''.join(chr(97 + i % 26) for i in range(n))
        # save/load round trip at this size
        loaded = OpSet(backend.save())
        assert loaded.get_patch()['diffs'] == backend.get_patch()['diffs']

    def test_interleaved_insert_delete(self):
        import automerge_tpu as A
        doc = A.from_({'text': A.Text()}, 'aa' * 4)
        rng = random.Random(4)
        expected = []
        for i in range(120):
            if expected and rng.random() < 0.3:
                pos = rng.randrange(len(expected))
                doc = A.change(doc, lambda d, pos=pos: d['text'].delete_at(pos))
                expected.pop(pos)
            else:
                pos = rng.randrange(len(expected) + 1)
                ch = chr(97 + i % 26)
                doc = A.change(doc, lambda d, pos=pos, ch=ch:
                               d['text'].insert_at(pos, ch))
                expected.insert(pos, ch)
            assert str(doc['text']) == ''.join(expected)
        doc2 = A.load(A.save(doc))
        assert str(doc2['text']) == ''.join(expected)


class TestLongTextSaveLoad:
    """Long text editing with persistence (extends the stress suite above,
    ref new_backend_test.js:2063-2193): 1200 inserts + 300 deletes crossing
    several sequence-block splits (_BLOCK_SIZE=256), then save/load
    round-trip and full-log convergence on a second doc."""

    def test_long_text_insert_delete_saveload(self):
        rng = random.Random(42)
        doc = OpSet()
        text_id = f'1@{A1}'
        changes = [encode_change({
            'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
            'ops': [{'action': 'makeText', 'obj': '_root', 'key': 'text',
                     'insert': False, 'pred': []}]})]
        doc.apply_changes([changes[0]])

        # 1200 single-char inserts: 70% append, 30% at a random position
        elem_ids = []      # visible order
        expected = []
        ctr = 2
        seq = 2
        for i in range(1200):
            ch = chr(97 + rng.randrange(26))
            if elem_ids and rng.random() < 0.3:
                pos = rng.randrange(len(elem_ids))
                ref = elem_ids[pos - 1] if pos else '_head'
            else:
                pos = len(elem_ids)
                ref = elem_ids[-1] if elem_ids else '_head'
            buf = encode_change({
                'actor': A1, 'seq': seq, 'startOp': ctr, 'time': 0,
                'deps': doc.heads,
                'ops': [{'action': 'set', 'obj': text_id, 'elemId': ref,
                         'insert': True, 'value': ch, 'pred': []}]})
            doc.apply_changes([buf])
            changes.append(buf)
            elem_ids.insert(pos, f'{ctr}@{A1}')
            expected.insert(pos, ch)
            ctr += 1
            seq += 1

        # 300 deletes at random positions
        for i in range(300):
            pos = rng.randrange(len(elem_ids))
            target = elem_ids.pop(pos)
            expected.pop(pos)
            buf = encode_change({
                'actor': A1, 'seq': seq, 'startOp': ctr, 'time': 0,
                'deps': doc.heads,
                'ops': [{'action': 'del', 'obj': text_id, 'elemId': target,
                         'insert': False, 'pred': [target]}]})
            doc.apply_changes([buf])
            changes.append(buf)
            ctr += 1
            seq += 1

        def text_of(op_set):
            patch = op_set.get_patch()
            text_diff = patch['diffs']['props']['text'][text_id]
            out = []
            for edit in text_diff['edits']:
                if edit['action'] == 'insert':
                    out.insert(edit['index'], edit['value']['value'])
                elif edit['action'] == 'multi-insert':
                    for k, v in enumerate(edit['values']):
                        out.insert(edit['index'] + k, v)
            return ''.join(out)

        assert text_of(doc) == ''.join(expected)
        assert len(expected) == 900

        # Save/load round trip preserves content and heads
        saved = doc.save()
        loaded = OpSet(saved)
        assert loaded.heads == doc.heads
        assert text_of(loaded) == ''.join(expected)

        # A second doc receiving the full change log in one call converges
        other = OpSet()
        other.apply_changes(list(changes))
        assert other.heads == doc.heads
        assert bytes(other.save()) == bytes(doc.save())


def full_patch(clock, deps, max_op, diffs, pending=0):
    return {'maxOp': max_op, 'clock': clock, 'deps': sorted(deps),
            'pendingChanges': pending, 'diffs': diffs}


class TestConflictShapes:
    """The conflict-shape matrix (ref new_backend_test.js:1282-1857):
    conflicts inside list elements, conflicts created by one change,
    conflicts on multi-inserted elements, insert->update conversion,
    conflict growth, delete+overwrite interleavings, and conflicted nested
    objects. Patch assertions are exact (block-internal column checks are
    representation-specific to the reference and are covered by our own
    save/load byte tests instead)."""

    def test_conflicts_inside_list_elements(self):
        """(ref new_backend_test.js:1282)"""
        c1 = {'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'list',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': '_head',
             'insert': True, 'datatype': 'uint', 'value': 1, 'pred': []}]}
        c2 = {'actor': A1, 'seq': 2, 'startOp': 3, 'time': 0,
              'deps': [hash_of(c1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'2@{A1}',
             'insert': False, 'datatype': 'uint', 'value': 2,
             'pred': [f'2@{A1}']}]}
        c3 = {'actor': A2, 'seq': 1, 'startOp': 3, 'time': 0,
              'deps': [hash_of(c1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'2@{A1}',
             'insert': False, 'datatype': 'uint', 'value': 3,
             'pred': [f'2@{A1}']}]}
        b1, b2 = OpSet(), OpSet()
        assert b1.apply_changes([encode_change(c1)]) == full_patch(
            {A1: 1}, [hash_of(c1)], 2,
            {'objectId': '_root', 'type': 'map', 'props': {'list': {f'1@{A1}': {
                'objectId': f'1@{A1}', 'type': 'list', 'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{A1}',
                     'opId': f'2@{A1}',
                     'value': {'type': 'value', 'value': 1,
                               'datatype': 'uint'}}]}}}})
        assert b1.apply_changes([encode_change(c2)]) == full_patch(
            {A1: 2}, [hash_of(c2)], 3,
            {'objectId': '_root', 'type': 'map', 'props': {'list': {f'1@{A1}': {
                'objectId': f'1@{A1}', 'type': 'list', 'edits': [
                    {'action': 'update', 'index': 0, 'opId': f'3@{A1}',
                     'value': {'type': 'value', 'value': 2,
                               'datatype': 'uint'}}]}}}})
        assert b1.apply_changes([encode_change(c3)]) == full_patch(
            {A1: 2, A2: 1}, [hash_of(c2), hash_of(c3)], 3,
            {'objectId': '_root', 'type': 'map', 'props': {'list': {f'1@{A1}': {
                'objectId': f'1@{A1}', 'type': 'list', 'edits': [
                    {'action': 'update', 'index': 0, 'opId': f'3@{A1}',
                     'value': {'type': 'value', 'value': 2,
                               'datatype': 'uint'}},
                    {'action': 'update', 'index': 0, 'opId': f'3@{A2}',
                     'value': {'type': 'value', 'value': 3,
                               'datatype': 'uint'}}]}}}})
        # opposite arrival order converges to the same conflict set
        b2.apply_changes([encode_change(c1)])
        assert b2.apply_changes([encode_change(c3)])['diffs']['props'][
            'list'][f'1@{A1}']['edits'] == [
            {'action': 'update', 'index': 0, 'opId': f'3@{A2}',
             'value': {'type': 'value', 'value': 3, 'datatype': 'uint'}}]
        assert b2.apply_changes([encode_change(c2)])['diffs']['props'][
            'list'][f'1@{A1}']['edits'] == [
            {'action': 'update', 'index': 0, 'opId': f'3@{A1}',
             'value': {'type': 'value', 'value': 2, 'datatype': 'uint'}},
            {'action': 'update', 'index': 0, 'opId': f'3@{A2}',
             'value': {'type': 'value', 'value': 3, 'datatype': 'uint'}}]
        assert b1.save() == b2.save()

    def test_conflicts_introduced_by_single_change(self):
        """(ref new_backend_test.js:1371)"""
        A = 'f0e1d2c3'
        c1 = {'actor': A, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeText', 'obj': '_root', 'key': 'text',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'a', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': True, 'value': 'b', 'pred': []}]}
        c2 = {'actor': A, 'seq': 2, 'startOp': 4, 'time': 0,
              'deps': [hash_of(c1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': False, 'value': 'x', 'pred': [f'2@{A}']},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': False, 'value': 'y', 'pred': [f'2@{A}']}]}
        backend = OpSet()
        assert backend.apply_changes([encode_change(c1)])['diffs']['props'][
            'text'][f'1@{A}']['edits'] == [
            {'action': 'multi-insert', 'index': 0, 'elemId': f'2@{A}',
             'values': ['a', 'b']}]
        assert backend.apply_changes([encode_change(c2)])['diffs']['props'][
            'text'][f'1@{A}']['edits'] == [
            {'action': 'update', 'index': 0, 'opId': f'4@{A}',
             'value': {'type': 'value', 'value': 'x'}},
            {'action': 'update', 'index': 0, 'opId': f'5@{A}',
             'value': {'type': 'value', 'value': 'y'}}]

    def test_conflict_on_multi_inserted_element(self):
        """(ref new_backend_test.js:1437)"""
        A = 'f0e1d2c3'
        c1 = {'actor': A, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeText', 'obj': '_root', 'key': 'text',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'a', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': True, 'value': 'b', 'pred': []}]}
        c2 = {'actor': A, 'seq': 2, 'startOp': 4, 'time': 0,
              'deps': [hash_of(c1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'3@{A}',
             'insert': False, 'value': 'x', 'pred': [f'3@{A}']},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'3@{A}',
             'insert': False, 'value': 'y', 'pred': [f'3@{A}']}]}
        backend = OpSet()
        patch = backend.apply_changes([encode_change(c1), encode_change(c2)])
        assert patch['diffs']['props']['text'][f'1@{A}']['edits'] == [
            {'action': 'multi-insert', 'index': 0, 'elemId': f'2@{A}',
             'values': ['a']},
            {'action': 'insert', 'index': 1, 'elemId': f'3@{A}',
             'opId': f'4@{A}', 'value': {'type': 'value', 'value': 'x'}},
            {'action': 'update', 'index': 1, 'opId': f'5@{A}',
             'value': {'type': 'value', 'value': 'y'}}]

    def test_convert_inserts_to_updates(self):
        """(ref new_backend_test.js:1482)"""
        c1 = {'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeText', 'obj': '_root', 'key': 'text',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': '_head',
             'insert': True, 'value': 'c', 'pred': []}]}
        c2 = {'actor': A1, 'seq': 2, 'startOp': 3, 'time': 0,
              'deps': [hash_of(c1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': '_head',
             'insert': True, 'value': 'a', 'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'3@{A1}',
             'insert': True, 'value': 'b', 'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'2@{A1}',
             'insert': False, 'value': 'C', 'pred': [f'2@{A1}']}]}
        c3 = {'actor': A2, 'seq': 1, 'startOp': 3, 'time': 0,
              'deps': [hash_of(c1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'2@{A1}',
             'insert': False, 'value': 'x', 'pred': [f'2@{A1}']},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'2@{A1}',
             'insert': False, 'value': 'y', 'pred': [f'2@{A1}']}]}
        backend = OpSet()
        patch = backend.apply_changes([encode_change(c1), encode_change(c2)])
        assert patch['diffs']['props']['text'][f'1@{A1}']['edits'] == [
            {'action': 'insert', 'index': 0, 'elemId': f'2@{A1}',
             'opId': f'2@{A1}', 'value': {'type': 'value', 'value': 'c'}},
            {'action': 'multi-insert', 'index': 0, 'elemId': f'3@{A1}',
             'values': ['a', 'b']},
            {'action': 'update', 'index': 2, 'opId': f'5@{A1}',
             'value': {'type': 'value', 'value': 'C'}}]
        patch = backend.apply_changes([encode_change(c3)])
        assert patch['diffs']['props']['text'][f'1@{A1}']['edits'] == [
            {'action': 'update', 'index': 2, 'opId': f'3@{A2}',
             'value': {'type': 'value', 'value': 'x'}},
            {'action': 'update', 'index': 2, 'opId': f'4@{A2}',
             'value': {'type': 'value', 'value': 'y'}},
            {'action': 'update', 'index': 2, 'opId': f'5@{A1}',
             'value': {'type': 'value', 'value': 'C'}}]

    def test_further_conflict_added_to_existing(self):
        """(ref new_backend_test.js:1547)"""
        c1 = {'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeText', 'obj': '_root', 'key': 'text',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': '_head',
             'insert': True, 'value': 'a', 'pred': []}]}
        c2 = {'actor': A1, 'seq': 2, 'startOp': 3, 'time': 0,
              'deps': [hash_of(c1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'2@{A1}',
             'insert': False, 'value': 'b', 'pred': [f'2@{A1}']},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'2@{A1}',
             'insert': False, 'value': 'c', 'pred': [f'2@{A1}']}]}
        c3 = {'actor': A2, 'seq': 1, 'startOp': 3, 'time': 0,
              'deps': [hash_of(c1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'2@{A1}',
             'insert': False, 'value': 'x', 'pred': [f'2@{A1}']}]}
        backend = OpSet()
        patch = backend.apply_changes(
            [encode_change(c) for c in (c1, c2, c3)])
        assert patch == full_patch(
            {A1: 2, A2: 1}, [hash_of(c2), hash_of(c3)], 4,
            {'objectId': '_root', 'type': 'map', 'props': {'text': {f'1@{A1}': {
                'objectId': f'1@{A1}', 'type': 'text', 'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{A1}',
                     'opId': f'3@{A1}',
                     'value': {'type': 'value', 'value': 'b'}},
                    {'action': 'update', 'index': 0, 'opId': f'3@{A2}',
                     'value': {'type': 'value', 'value': 'x'}},
                    {'action': 'update', 'index': 0, 'opId': f'4@{A1}',
                     'value': {'type': 'value', 'value': 'c'}}]}}}})

    def test_element_delete_and_overwrite_same_change(self):
        """(ref new_backend_test.js:1611)"""
        A = 'f0e1d2c3'
        c1 = {'actor': A, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeText', 'obj': '_root', 'key': 'text',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'a', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': True, 'value': 'b', 'pred': []}]}
        c2 = {'actor': A, 'seq': 2, 'startOp': 4, 'time': 0,
              'deps': [hash_of(c1)], 'ops': [
            {'action': 'del', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': False, 'pred': [f'2@{A}']},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'3@{A}',
             'insert': False, 'value': 'x', 'pred': [f'3@{A}']}]}
        backend = OpSet()
        patch = backend.apply_changes([encode_change(c1), encode_change(c2)])
        assert patch['diffs']['props']['text'][f'1@{A}']['edits'] == [
            {'action': 'multi-insert', 'index': 0, 'elemId': f'2@{A}',
             'values': ['a', 'b']},
            {'action': 'remove', 'index': 0, 'count': 1},
            {'action': 'update', 'index': 0, 'opId': f'5@{A}',
             'value': {'type': 'value', 'value': 'x'}}]

    def test_concurrent_delete_and_assign_list_element(self):
        """(ref new_backend_test.js:1660): the concurrent set survives the
        delete (resurrection)."""
        c1 = {'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'list',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': '_head',
             'insert': True, 'datatype': 'uint', 'value': 1, 'pred': []}]}
        c2 = {'actor': A1, 'seq': 2, 'startOp': 3, 'time': 0,
              'deps': [hash_of(c1)], 'ops': [
            {'action': 'del', 'obj': f'1@{A1}', 'elemId': f'2@{A1}',
             'insert': False, 'pred': [f'2@{A1}']}]}
        c3 = {'actor': A2, 'seq': 1, 'startOp': 3, 'time': 0,
              'deps': [hash_of(c1)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'elemId': f'2@{A1}',
             'insert': False, 'datatype': 'uint', 'value': 2,
             'pred': [f'2@{A1}']}]}
        b1, b2 = OpSet(), OpSet()
        patch = b1.apply_changes([encode_change(c1), encode_change(c2)])
        assert patch['diffs']['props']['list'][f'1@{A1}']['edits'] == [
            {'action': 'insert', 'index': 0, 'elemId': f'2@{A1}',
             'opId': f'2@{A1}',
             'value': {'type': 'value', 'value': 1, 'datatype': 'uint'}},
            {'action': 'remove', 'index': 0, 'count': 1}]
        patch = b1.apply_changes([encode_change(c3)])
        assert patch['diffs']['props']['list'][f'1@{A1}']['edits'] == [
            {'action': 'insert', 'index': 0, 'elemId': f'2@{A1}',
             'opId': f'3@{A2}',
             'value': {'type': 'value', 'value': 2, 'datatype': 'uint'}}]
        # opposite order: assignment first, then the delete arrives. The
        # element stays visible through the set op, so the patch is an update
        # (ref new_backend_test.js:1698-1707), not a re-insert.
        b2.apply_changes([encode_change(c1), encode_change(c3)])
        patch = b2.apply_changes([encode_change(c2)])
        assert patch['diffs']['props']['list'][f'1@{A1}']['edits'] == [
            {'action': 'update', 'index': 0, 'opId': f'3@{A2}',
             'value': {'type': 'value', 'value': 2, 'datatype': 'uint'}}]
        assert b1.save() == b2.save()

    def test_updates_inside_conflicted_properties(self):
        """(ref new_backend_test.js:1736)"""
        c1 = {'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'map', 'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'key': 'x',
             'datatype': 'uint', 'value': 1, 'pred': []}]}
        c2 = {'actor': A2, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'map', 'pred': []},
            {'action': 'set', 'obj': f'1@{A2}', 'key': 'y',
             'datatype': 'uint', 'value': 2, 'pred': []}]}
        c3 = {'actor': A1, 'seq': 2, 'startOp': 3, 'time': 0,
              'deps': [hash_of(c1), hash_of(c2)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'key': 'x',
             'datatype': 'uint', 'value': 3, 'pred': [f'2@{A1}']}]}
        backend = OpSet()
        assert backend.apply_changes([encode_change(c1)]) == full_patch(
            {A1: 1}, [hash_of(c1)], 2,
            {'objectId': '_root', 'type': 'map', 'props': {'map': {
                f'1@{A1}': {'objectId': f'1@{A1}', 'type': 'map',
                            'props': {'x': {f'2@{A1}': {
                                'type': 'value', 'value': 1,
                                'datatype': 'uint'}}}}}}})
        assert backend.apply_changes([encode_change(c2)]) == full_patch(
            {A1: 1, A2: 1}, [hash_of(c1), hash_of(c2)], 2,
            {'objectId': '_root', 'type': 'map', 'props': {'map': {
                f'1@{A1}': {'objectId': f'1@{A1}', 'type': 'map',
                            'props': {}},
                f'1@{A2}': {'objectId': f'1@{A2}', 'type': 'map',
                            'props': {'y': {f'2@{A2}': {
                                'type': 'value', 'value': 2,
                                'datatype': 'uint'}}}}}}})
        assert backend.apply_changes([encode_change(c3)]) == full_patch(
            {A1: 2, A2: 1}, [hash_of(c3)], 3,
            {'objectId': '_root', 'type': 'map', 'props': {'map': {
                f'1@{A1}': {'objectId': f'1@{A1}', 'type': 'map',
                            'props': {'x': {f'3@{A1}': {
                                'type': 'value', 'value': 3,
                                'datatype': 'uint'}}}},
                f'1@{A2}': {'objectId': f'1@{A2}', 'type': 'map',
                            'props': {}}}}})

    def test_conflict_of_nested_object_and_value(self):
        """(ref new_backend_test.js:1798)"""
        c1 = {'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'x', 'pred': []},
            {'action': 'set', 'obj': f'1@{A1}', 'key': 'y',
             'datatype': 'uint', 'value': 2, 'pred': []}]}
        c2 = {'actor': A2, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x',
             'datatype': 'uint', 'value': 1, 'pred': []}]}
        c3 = {'actor': A1, 'seq': 2, 'startOp': 3, 'time': 0,
              'deps': [hash_of(c1), hash_of(c2)], 'ops': [
            {'action': 'set', 'obj': f'1@{A1}', 'key': 'y',
             'datatype': 'uint', 'value': 3, 'pred': [f'2@{A1}']}]}
        backend = OpSet()
        backend.apply_changes([encode_change(c1)])
        assert backend.apply_changes([encode_change(c2)]) == full_patch(
            {A1: 1, A2: 1}, [hash_of(c1), hash_of(c2)], 2,
            {'objectId': '_root', 'type': 'map', 'props': {'x': {
                f'1@{A1}': {'objectId': f'1@{A1}', 'type': 'map',
                            'props': {}},
                f'1@{A2}': {'type': 'value', 'value': 1,
                            'datatype': 'uint'}}}})
        assert backend.apply_changes([encode_change(c3)]) == full_patch(
            {A1: 2, A2: 1}, [hash_of(c3)], 3,
            {'objectId': '_root', 'type': 'map', 'props': {'x': {
                f'1@{A1}': {'objectId': f'1@{A1}', 'type': 'map',
                            'props': {'y': {f'3@{A1}': {
                                'type': 'value', 'value': 3,
                                'datatype': 'uint'}}}},
                f'1@{A2}': {'type': 'value', 'value': 1,
                            'datatype': 'uint'}}}})


class TestUnknownColumns:
    def test_unknown_columns_actions_datatypes(self):
        """Forward compatibility: a change holding unknown columns, an
        unknown action (17), and an unknown value datatype (14) must apply
        and round-trip (ref new_backend_test.js:1857)."""
        change = bytes([
            0x85, 0x6f, 0x4a, 0x83,            # magic bytes
            0xad, 0xfb, 0x1a, 0x69,            # checksum
            1, 51, 0, 2, 0x12, 0x34,           # change chunk, len, deps, actor
            1, 1, 0, 0,                        # seq, startOp, time, message
            0, 9,                              # other actors, column count
            0x15, 3, 0x34, 1, 0x42, 2,         # keyStr, insert, action
            0x56, 2, 0x57, 4, 0x70, 2,         # valLen, valRaw, predNum
            0xf0, 1, 2, 0xf1, 1, 2, 0xf3, 1, 2,  # unknown column group
            0x7f, 1, 0x78,                     # keyStr: 'x'
            1,                                 # insert: false
            0x7f, 17,                          # unknown action 17
            0x7f, 0x4e,                        # valLen: 4 bytes of type 14
            1, 2, 3, 4,                        # valRaw
            0x7f, 0,                           # predNum: 0
            0x7f, 2,                           # unknown group cardinality
            2, 0,                              # unknown actor column
            2, 1])                             # unknown delta column
        backend = OpSet()
        patch = backend.apply_changes([change])
        assert patch == full_patch(
            {'1234': 1}, [decode_change(change)['hash']], 1,
            {'objectId': '_root', 'type': 'map', 'props': {'x': {}}})
        # the unknown columns survive a save/load round trip
        reloaded = OpSet(backend.save())
        assert reloaded.get_patch()['clock'] == {'1234': 1}

    def test_unknown_group_with_value_pair(self):
        """An unknown group whose members include a VALUE_LEN/VALUE_RAW pair
        must decode (the pair is one logical column) and re-encode to the
        original bytes (ref columnar.js:339-361 value-pair handling inside
        group decode)."""
        gcid = 0x90   # unknown group (group 9), GROUP_CARD
        vcid = 0x96   # same group, VALUE_LEN (VALUE_RAW 0x97 implied)
        change = {
            'actor': 'aa' * 4, 'seq': 1, 'startOp': 1, 'time': 0,
            'message': '', 'deps': [], 'ops': [
                {'action': 'set', 'obj': '_root', 'key': 'x',
                 'insert': False, 'value': 1, 'datatype': 'int', 'pred': [],
                 'unknownCols': {gcid: [{vcid: {'value': 'x'}}]}}]}
        buf = encode_change(change)
        dec = decode_change(buf)
        assert dec['ops'][0]['unknownCols'] == {gcid: [{vcid: {'value': 'x'}}]}
        assert bytes(encode_change(dec)) == bytes(buf)

    def test_unknown_actor_column_through_document(self):
        """An unknown ACTOR_ID column naming an actor that authored no change
        must survive apply + save + load: the document actor table has to
        include actors referenced only from unknown columns (cf. the
        change-encode path, parse_all_op_ids)."""
        acid = 0x91   # unknown group 9, ACTOR_ID
        other = 'bb' * 4
        change = {
            'actor': 'aa' * 4, 'seq': 1, 'startOp': 1, 'time': 0,
            'message': '', 'deps': [], 'ops': [
                {'action': 'set', 'obj': '_root', 'key': 'y',
                 'insert': False, 'value': 2, 'datatype': 'int', 'pred': [],
                 'unknownCols': {acid: other}}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change)])
        reloaded = OpSet(backend.save())
        assert reloaded.heads == backend.heads
        assert reloaded.get_patch()['clock'] == {'aa' * 4: 1}

    def test_change_column_in_document_succ_group_rejected(self):
        """A change using column ids from the document succ group (0x80-0x83)
        would collide with the succ columns save() adds; such changes are
        rejected at decode instead of producing an undecodable document."""
        change = {
            'actor': 'aa' * 4, 'seq': 1, 'startOp': 1, 'time': 0,
            'message': '', 'deps': [], 'ops': [
                {'action': 'set', 'obj': '_root', 'key': 'z',
                 'insert': False, 'value': 3, 'datatype': 'int', 'pred': [],
                 'unknownCols': {0x81: 'bb' * 4}}]}
        buf = encode_change(change)
        with pytest.raises(ValueError, match='reserved for the document'):
            decode_change(buf)


class TestLongSequences:
    """Long-insertion behavior (ref new_backend_test.js:1907-2193). The
    reference asserts its MAX_BLOCK_SIZE=600 block split internals; our
    engine blocks at op_set._BLOCK_SIZE=256 — these tests assert the
    observable behavior (patches, indexes) across our block boundaries."""

    def _long_insert_change(self, actor, n):
        ops = [{'action': 'makeText', 'obj': '_root', 'key': 'text',
                'insert': False, 'pred': []},
               {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head',
                'insert': True, 'value': 'a', 'pred': []}]
        for i in range(2, n + 1):
            ops.append({'action': 'set', 'obj': f'1@{actor}',
                        'elemId': f'{i}@{actor}', 'insert': True,
                        'value': 'a', 'pred': []})
        return {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0,
                'deps': [], 'ops': ops}

    def test_long_insertion_splits_blocks(self):
        from automerge_tpu.backend.op_set import _BLOCK_SIZE
        A = 'f0e1d2c3'
        n = _BLOCK_SIZE + 64
        backend = OpSet()
        patch = backend.apply_changes(
            [encode_change(self._long_insert_change(A, n))])
        edits = patch['diffs']['props']['text'][f'1@{A}']['edits']
        assert len(edits) == 1
        assert edits[0]['action'] == 'multi-insert'
        assert len(edits[0]['values']) == n
        assert len(backend.objects[f'1@{A}'].blocks) >= 2

    def test_short_insertions_split_blocks(self):
        from automerge_tpu.backend.op_set import _BLOCK_SIZE
        A = 'f0e1d2c3'
        backend = OpSet()
        c1 = {'actor': A, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeText', 'obj': '_root', 'key': 'text',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'a', 'pred': []}]}
        backend.apply_changes([encode_change(c1)])
        n = _BLOCK_SIZE + 8
        for i in range(2, n + 1):
            c = {'actor': A, 'seq': i, 'startOp': i + 1, 'time': 0,
                 'deps': list(backend.heads), 'ops': [
                {'action': 'set', 'obj': f'1@{A}', 'elemId': f'{i}@{A}',
                 'insert': True, 'value': 'a', 'pred': []}]}
            patch = backend.apply_changes([encode_change(c)])
            assert patch['diffs']['props']['text'][f'1@{A}']['edits'] == [
                {'action': 'insert', 'index': i - 1,
                 'elemId': f'{i + 1}@{A}', 'opId': f'{i + 1}@{A}',
                 'value': {'type': 'value', 'value': 'a'}}]
        assert len(backend.objects[f'1@{A}'].blocks) >= 2

    def test_delete_many_consecutive_characters(self):
        from automerge_tpu.backend.op_set import _BLOCK_SIZE
        A = 'f0e1d2c3'
        n = _BLOCK_SIZE + 32
        backend = OpSet()
        backend.apply_changes(
            [encode_change(self._long_insert_change(A, n))])
        ops = [{'action': 'del', 'obj': f'1@{A}', 'elemId': f'{i}@{A}',
                'insert': False, 'pred': [f'{i}@{A}']}
               for i in range(2, n + 2)]
        c2 = {'actor': A, 'seq': 2, 'startOp': n + 3, 'time': 0,
              'deps': [], 'ops': ops}
        patch = backend.apply_changes([encode_change(c2)])
        assert patch['diffs']['props']['text'][f'1@{A}']['edits'] == [
            {'action': 'remove', 'index': 0, 'count': n}]

    def test_update_object_after_long_text(self):
        """An object sorted after a long text object stays addressable
        (ref new_backend_test.js:2063)."""
        from automerge_tpu.backend.op_set import _BLOCK_SIZE
        A = 'f0e1d2c3'
        n = _BLOCK_SIZE + 16
        ops = [{'action': 'makeText', 'obj': '_root', 'key': 'text1',
                'insert': False, 'pred': []},
               {'action': 'makeText', 'obj': '_root', 'key': 'text2',
                'insert': False, 'pred': []},
               {'action': 'set', 'obj': f'2@{A}', 'elemId': '_head',
                'insert': True, 'value': 'x', 'pred': []},
               {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
                'insert': True, 'value': 'a', 'pred': []}]
        for i in range(4, n + 1):
            ops.append({'action': 'set', 'obj': f'1@{A}',
                        'elemId': f'{i}@{A}', 'insert': True, 'value': 'a',
                        'pred': []})
        c1 = {'actor': A, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
              'ops': ops}
        c2 = {'actor': A, 'seq': 2, 'startOp': n + 3, 'time': 0, 'deps': [],
              'ops': [{'action': 'set', 'obj': f'2@{A}',
                       'elemId': f'3@{A}', 'insert': True, 'value': 'x',
                       'pred': []}]}
        backend = OpSet()
        backend.apply_changes([encode_change(c1)])
        patch = backend.apply_changes([encode_change(c2)])
        assert patch['diffs']['props'] == {'text2': {f'2@{A}': {
            'objectId': f'2@{A}', 'type': 'text', 'edits': [{
                'action': 'insert', 'index': 1,
                'opId': f'{n + 3}@{A}', 'elemId': f'{n + 3}@{A}',
                'value': {'type': 'value', 'value': 'x'}}]}}}

    def test_root_ops_with_long_text_in_same_change(self):
        """Root-map ops mixed into a long text change apply correctly
        (ref new_backend_test.js:2090)."""
        from automerge_tpu.backend.op_set import _BLOCK_SIZE
        A = 'f0e1d2c3'
        n = _BLOCK_SIZE + 16
        change = self._long_insert_change(A, n)
        change['ops'].append({'action': 'set', 'obj': '_root', 'key': 'z',
                              'insert': False, 'value': 'zzz', 'pred': []})
        backend = OpSet()
        patch = backend.apply_changes([encode_change(change)])
        assert patch['diffs']['props']['z'] == {
            f'{n + 2}@{A}': {'type': 'value', 'value': 'zzz'}}
        reloaded = OpSet(backend.save())
        assert reloaded.save() == backend.save()


class TestRootOverwrites:
    """ref new_backend_test.js:30-306 (patch grammar only: our engine's
    block representation is a redesign, so the reference's checkColumns
    internals don't transfer)."""

    ACTOR = 'aaaa11'

    def test_overwrite_root_properties_1(self):
        actor = self.ACTOR
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'datatype': 'uint',
             'value': 3, 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'y', 'datatype': 'uint',
             'value': 4, 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'datatype': 'uint',
             'value': 5, 'pred': [f'1@{actor}']}]}
        backend = OpSet()
        assert backend.apply_changes([encode_change(change1)]) == full_patch(
            {actor: 1}, [hash_of(change1)], 2,
            {'objectId': '_root', 'type': 'map', 'props': {
                'x': {f'1@{actor}': {'type': 'value', 'value': 3,
                                     'datatype': 'uint'}},
                'y': {f'2@{actor}': {'type': 'value', 'value': 4,
                                     'datatype': 'uint'}}}})
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 3,
            {'objectId': '_root', 'type': 'map', 'props': {
                'x': {f'3@{actor}': {'type': 'value', 'value': 5,
                                     'datatype': 'uint'}}}})

    def test_overwrite_root_properties_2(self):
        actor = self.ACTOR
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'datatype': 'uint',
             'value': 3, 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'y', 'datatype': 'uint',
             'value': 4, 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'y', 'datatype': 'uint',
             'value': 5, 'pred': [f'2@{actor}']},
            {'action': 'set', 'obj': '_root', 'key': 'z', 'datatype': 'uint',
             'value': 6, 'pred': []}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 4,
            {'objectId': '_root', 'type': 'map', 'props': {
                'y': {f'3@{actor}': {'type': 'value', 'value': 5,
                                     'datatype': 'uint'}},
                'z': {f'4@{actor}': {'type': 'value', 'value': 6,
                                     'datatype': 'uint'}}}})

    def test_concurrent_overwrites_of_same_value(self):
        actor1, actor2, actor3 = '01234567', '89abcdef', 'fedcba98'
        change1 = {'actor': actor1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'datatype': 'uint',
             'value': 1, 'pred': []}]}

        def overwrite(actor, seq, value):
            return {'actor': actor, 'seq': seq, 'startOp': 2, 'time': 0,
                    'deps': [hash_of(change1)], 'ops': [
                {'action': 'set', 'obj': '_root', 'key': 'x',
                 'datatype': 'uint', 'value': value,
                 'pred': [f'1@{actor1}']}]}
        change2 = overwrite(actor1, 2, 2)
        change3 = overwrite(actor2, 1, 3)
        change4 = overwrite(actor3, 1, 4)

        def val(actor, v):
            return {f'2@{actor}': {'type': 'value', 'value': v,
                                   'datatype': 'uint'}}
        backend1 = OpSet()
        backend1.apply_changes([encode_change(change1)])
        assert backend1.apply_changes([encode_change(change2)]) == full_patch(
            {actor1: 2}, [hash_of(change2)], 2,
            {'objectId': '_root', 'type': 'map',
             'props': {'x': val(actor1, 2)}})
        assert backend1.apply_changes([encode_change(change3)]) == full_patch(
            {actor1: 2, actor2: 1},
            [hash_of(change2), hash_of(change3)], 2,
            {'objectId': '_root', 'type': 'map',
             'props': {'x': dict(**val(actor1, 2), **val(actor2, 3))}})
        assert backend1.apply_changes([encode_change(change4)]) == full_patch(
            {actor1: 2, actor2: 1, actor3: 1},
            [hash_of(change2), hash_of(change3), hash_of(change4)], 2,
            {'objectId': '_root', 'type': 'map',
             'props': {'x': dict(**val(actor1, 2), **val(actor2, 3),
                                 **val(actor3, 4))}})
        # Apply in a different order on a second backend
        backend2 = OpSet()
        backend2.apply_changes([encode_change(change1)])
        assert backend2.apply_changes([encode_change(change4)]) == full_patch(
            {actor1: 1, actor3: 1}, [hash_of(change4)], 2,
            {'objectId': '_root', 'type': 'map',
             'props': {'x': val(actor3, 4)}})
        assert backend2.apply_changes([encode_change(change3)]) == full_patch(
            {actor1: 1, actor2: 1, actor3: 1},
            [hash_of(change3), hash_of(change4)], 2,
            {'objectId': '_root', 'type': 'map',
             'props': {'x': dict(**val(actor2, 3), **val(actor3, 4))}})
        assert backend2.apply_changes([encode_change(change2)]) == full_patch(
            {actor1: 2, actor2: 1, actor3: 1},
            [hash_of(change2), hash_of(change3), hash_of(change4)], 2,
            {'objectId': '_root', 'type': 'map',
             'props': {'x': dict(**val(actor1, 2), **val(actor2, 3),
                                 **val(actor3, 4))}})

    def test_conflict_resolution(self):
        actor1, actor2 = '01234567', '89abcdef'
        change1 = {'actor': actor1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'datatype': 'uint',
             'value': 1, 'pred': []}]}
        change2 = {'actor': actor2, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'datatype': 'uint',
             'value': 2, 'pred': []}]}
        change3 = {'actor': actor1, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1), hash_of(change2)], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'datatype': 'uint',
             'value': 3, 'pred': [f'1@{actor1}', f'1@{actor2}']}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor1: 1, actor2: 1}, [hash_of(change1), hash_of(change2)], 1,
            {'objectId': '_root', 'type': 'map', 'props': {'x': {
                f'1@{actor1}': {'type': 'value', 'value': 1,
                                'datatype': 'uint'},
                f'1@{actor2}': {'type': 'value', 'value': 2,
                                'datatype': 'uint'}}}})
        assert backend.apply_changes([encode_change(change3)]) == full_patch(
            {actor1: 2, actor2: 1}, [hash_of(change3)], 2,
            {'objectId': '_root', 'type': 'map', 'props': {'x': {
                f'2@{actor1}': {'type': 'value', 'value': 3,
                                'datatype': 'uint'}}}})

    def test_missing_pred_error_1(self):
        actor = self.ACTOR
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'datatype': 'uint',
             'value': 1, 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'y', 'datatype': 'uint',
             'value': 2, 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'datatype': 'uint',
             'value': 3, 'pred': [f'2@{actor}']}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        with pytest.raises(Exception, match='[Pp]red'):
            backend.apply_changes([encode_change(change2)])

    def test_missing_pred_error_2(self):
        actor1, actor2 = '01234567', '89abcdef'
        change1 = {'actor': actor1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'datatype': 'uint',
             'value': 1, 'pred': []}]}
        change2 = {'actor': actor2, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'w', 'datatype': 'uint',
             'value': 2, 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'x', 'datatype': 'uint',
             'value': 2, 'pred': []}]}
        change3 = {'actor': actor1, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1), hash_of(change2)], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'datatype': 'uint',
             'value': 3, 'pred': [f'1@{actor2}']}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        backend.apply_changes([encode_change(change2)])
        with pytest.raises(Exception, match='[Pp]red'):
            backend.apply_changes([encode_change(change3)])


class TestNestedObjectCreation:
    """ref new_backend_test.js:308-414"""

    ACTOR = 'aaaa11'

    def test_create_and_update_nested_maps(self):
        actor = self.ACTOR
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'map', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'key': 'x', 'value': 'a', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'key': 'y', 'value': 'b', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'key': 'z', 'value': 'c', 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 5, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{actor}', 'key': 'y', 'value': 'B',
             'pred': [f'3@{actor}']}]}
        backend = OpSet()
        assert backend.apply_changes([encode_change(change1)]) == full_patch(
            {actor: 1}, [hash_of(change1)], 4,
            {'objectId': '_root', 'type': 'map', 'props': {'map': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'map',
                               'props': {
                    'x': {f'2@{actor}': {'type': 'value', 'value': 'a'}},
                    'y': {f'3@{actor}': {'type': 'value', 'value': 'b'}},
                    'z': {f'4@{actor}': {'type': 'value', 'value': 'c'}}}}}}})
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 5,
            {'objectId': '_root', 'type': 'map', 'props': {'map': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'map',
                               'props': {'y': {f'5@{actor}': {
                                   'type': 'value', 'value': 'B'}}}}}}})

    def test_nested_maps_several_levels_deep(self):
        actor = self.ACTOR
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'a', 'pred': []},
            {'action': 'makeMap', 'obj': f'1@{actor}', 'key': 'b', 'pred': []},
            {'action': 'makeMap', 'obj': f'2@{actor}', 'key': 'c', 'pred': []},
            {'action': 'set', 'obj': f'3@{actor}', 'key': 'd',
             'datatype': 'uint', 'value': 1, 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 5, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'3@{actor}', 'key': 'd',
             'datatype': 'uint', 'value': 2, 'pred': [f'4@{actor}']}]}

        def nested(leaf):
            return {'objectId': '_root', 'type': 'map', 'props': {'a': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'map',
                               'props': {'b': {
                    f'2@{actor}': {'objectId': f'2@{actor}', 'type': 'map',
                                   'props': {'c': {
                        f'3@{actor}': {'objectId': f'3@{actor}',
                                       'type': 'map',
                                       'props': {'d': leaf}}}}}}}}}}}
        backend = OpSet()
        assert backend.apply_changes([encode_change(change1)]) == full_patch(
            {actor: 1}, [hash_of(change1)], 4,
            nested({f'4@{actor}': {'type': 'value', 'value': 1,
                                   'datatype': 'uint'}}))
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 5,
            nested({f'5@{actor}': {'type': 'value', 'value': 2,
                                   'datatype': 'uint'}}))


class TestTextOperations:
    """ref new_backend_test.js:416-910"""

    ACTOR = 'aaaa11'

    def _make_text(self, actor, chars):
        ops = [{'action': 'makeText', 'obj': '_root', 'key': 'text',
                'insert': False, 'pred': []}]
        prev = '_head'
        for i, ch in enumerate(chars):
            ops.append({'action': 'set', 'obj': f'1@{actor}', 'elemId': prev,
                        'insert': True, 'value': ch, 'pred': []})
            prev = f'{i + 2}@{actor}'
        return {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0,
                'deps': [], 'ops': ops}

    def test_create_text_object(self):
        actor = self.ACTOR
        change1 = self._make_text(actor, ['a'])
        backend = OpSet()
        assert backend.apply_changes([encode_change(change1)]) == full_patch(
            {actor: 1}, [hash_of(change1)], 2,
            {'objectId': '_root', 'type': 'map', 'props': {'text': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'text',
                               'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{actor}',
                     'opId': f'2@{actor}',
                     'value': {'type': 'value', 'value': 'a'}}]}}}})

    def test_insert_text_characters(self):
        actor = self.ACTOR
        change1 = self._make_text(actor, ['a', 'b'])
        change2 = {'actor': actor, 'seq': 2, 'startOp': 4, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'3@{actor}',
             'insert': True, 'value': 'c', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'4@{actor}',
             'insert': True, 'value': 'd', 'pred': []}]}
        backend = OpSet()
        assert backend.apply_changes([encode_change(change1)]) == full_patch(
            {actor: 1}, [hash_of(change1)], 3,
            {'objectId': '_root', 'type': 'map', 'props': {'text': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'text',
                               'edits': [
                    {'action': 'multi-insert', 'index': 0,
                     'elemId': f'2@{actor}', 'values': ['a', 'b']}]}}}})
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 5,
            {'objectId': '_root', 'type': 'map', 'props': {'text': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'text',
                               'edits': [
                    {'action': 'multi-insert', 'index': 2,
                     'elemId': f'4@{actor}', 'values': ['c', 'd']}]}}}})

    def test_missing_insertion_reference_error(self):
        actor = self.ACTOR
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeText', 'obj': '_root', 'key': 'text',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head',
             'insert': True, 'value': 'a', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': True, 'value': 'b', 'pred': []},
            {'action': 'makeMap', 'obj': '_root', 'key': 'map',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'4@{actor}', 'key': 'foo',
             'insert': False, 'value': 'c', 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 6, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'4@{actor}',
             'insert': True, 'value': 'd', 'pred': []}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        with pytest.raises(Exception):
            backend.apply_changes([encode_change(change2)])

    def test_non_consecutive_insertions(self):
        actor = self.ACTOR
        change1 = self._make_text(actor, ['a', 'c'])
        change2 = {'actor': actor, 'seq': 2, 'startOp': 4, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': True, 'value': 'b', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'3@{actor}',
             'insert': True, 'value': 'd', 'pred': []}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 5,
            {'objectId': '_root', 'type': 'map', 'props': {'text': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'text',
                               'edits': [
                    {'action': 'insert', 'index': 1, 'elemId': f'4@{actor}',
                     'opId': f'4@{actor}',
                     'value': {'type': 'value', 'value': 'b'}},
                    {'action': 'insert', 'index': 3, 'elemId': f'5@{actor}',
                     'opId': f'5@{actor}',
                     'value': {'type': 'value', 'value': 'd'}}]}}}})

    def test_delete_first_character(self):
        actor = self.ACTOR
        change1 = self._make_text(actor, ['a'])
        change2 = {'actor': actor, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'pred': [f'2@{actor}']}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 3,
            {'objectId': '_root', 'type': 'map', 'props': {'text': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'text',
                               'edits': [{'action': 'remove', 'index': 0,
                                          'count': 1}]}}}})

    def test_delete_character_in_middle(self):
        actor = self.ACTOR
        change1 = self._make_text(actor, ['a', 'b', 'c'])
        change2 = {'actor': actor, 'seq': 2, 'startOp': 5, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': f'1@{actor}', 'elemId': f'3@{actor}',
             'insert': False, 'pred': [f'3@{actor}']}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 5,
            {'objectId': '_root', 'type': 'map', 'props': {'text': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'text',
                               'edits': [{'action': 'remove', 'index': 1,
                                          'count': 1}]}}}})

    def test_deleted_element_missing_error(self):
        actor = self.ACTOR
        change1 = self._make_text(actor, ['a'])
        change2 = {'actor': actor, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': f'1@{actor}', 'elemId': f'9@{actor}',
             'pred': [f'9@{actor}']}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        with pytest.raises(Exception):
            backend.apply_changes([encode_change(change2)])

    def test_multiple_list_element_updates(self):
        actor = self.ACTOR
        change1 = self._make_text(actor, ['a', 'b', 'c'])
        change2 = {'actor': actor, 'seq': 2, 'startOp': 5, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': False, 'value': 'A', 'pred': [f'2@{actor}']},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'4@{actor}',
             'insert': False, 'value': 'C', 'pred': [f'4@{actor}']}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 6,
            {'objectId': '_root', 'type': 'map', 'props': {'text': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'text',
                               'edits': [
                    {'action': 'update', 'index': 0, 'opId': f'5@{actor}',
                     'value': {'type': 'value', 'value': 'A'}},
                    {'action': 'update', 'index': 2, 'opId': f'6@{actor}',
                     'value': {'type': 'value', 'value': 'C'}}]}}}})

    def test_list_element_updates_in_reverse_order(self):
        actor = self.ACTOR
        change1 = self._make_text(actor, ['a', 'b', 'c'])
        change2 = {'actor': actor, 'seq': 2, 'startOp': 5, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'4@{actor}',
             'insert': False, 'value': 'C', 'pred': [f'4@{actor}']},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': False, 'value': 'A', 'pred': [f'2@{actor}']}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 6,
            {'objectId': '_root', 'type': 'map', 'props': {'text': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'text',
                               'edits': [
                    {'action': 'update', 'index': 2, 'opId': f'5@{actor}',
                     'value': {'type': 'value', 'value': 'C'}},
                    {'action': 'update', 'index': 0, 'opId': f'6@{actor}',
                     'value': {'type': 'value', 'value': 'A'}}]}}}})


class TestListObjectsAndCounters:
    """ref new_backend_test.js:1017-1280"""

    ACTOR = 'aaaa11'

    def test_nested_objects_inside_list_elements(self):
        actor = self.ACTOR
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'list',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head',
             'insert': True, 'datatype': 'uint', 'value': 1, 'pred': []},
            {'action': 'makeMap', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': True, 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 4, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'3@{actor}', 'key': 'x',
             'insert': False, 'datatype': 'uint', 'value': 2, 'pred': []}]}
        backend = OpSet()
        assert backend.apply_changes([encode_change(change1)]) == full_patch(
            {actor: 1}, [hash_of(change1)], 3,
            {'objectId': '_root', 'type': 'map', 'props': {'list': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list',
                               'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{actor}',
                     'opId': f'2@{actor}',
                     'value': {'type': 'value', 'value': 1,
                               'datatype': 'uint'}},
                    {'action': 'insert', 'index': 1, 'elemId': f'3@{actor}',
                     'opId': f'3@{actor}',
                     'value': {'objectId': f'3@{actor}', 'type': 'map',
                               'props': {}}}]}}}})
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 4,
            {'objectId': '_root', 'type': 'map', 'props': {'list': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list',
                               'edits': [
                    {'action': 'update', 'index': 1, 'opId': f'3@{actor}',
                     'value': {'objectId': f'3@{actor}', 'type': 'map',
                               'props': {'x': {f'4@{actor}': {
                                   'type': 'value', 'value': 2,
                                   'datatype': 'uint'}}}}}]}}}})

    def test_multiple_list_objects(self):
        actor = self.ACTOR
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'list1',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head',
             'insert': True, 'datatype': 'uint', 'value': 1, 'pred': []},
            {'action': 'makeList', 'obj': '_root', 'key': 'list2',
             'insert': False, 'pred': []},
            {'action': 'set', 'obj': f'3@{actor}', 'elemId': '_head',
             'insert': True, 'datatype': 'uint', 'value': 2, 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 5, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': True, 'datatype': 'uint', 'value': 3, 'pred': []}]}
        backend = OpSet()
        assert backend.apply_changes([encode_change(change1)]) == full_patch(
            {actor: 1}, [hash_of(change1)], 4,
            {'objectId': '_root', 'type': 'map', 'props': {
                'list1': {f'1@{actor}': {
                    'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                        {'action': 'insert', 'index': 0,
                         'elemId': f'2@{actor}', 'opId': f'2@{actor}',
                         'value': {'type': 'value', 'value': 1,
                                   'datatype': 'uint'}}]}},
                'list2': {f'3@{actor}': {
                    'objectId': f'3@{actor}', 'type': 'list', 'edits': [
                        {'action': 'insert', 'index': 0,
                         'elemId': f'4@{actor}', 'opId': f'4@{actor}',
                         'value': {'type': 'value', 'value': 2,
                                   'datatype': 'uint'}}]}}}})
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 5,
            {'objectId': '_root', 'type': 'map', 'props': {'list1': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list',
                               'edits': [
                    {'action': 'insert', 'index': 1, 'elemId': f'5@{actor}',
                     'opId': f'5@{actor}',
                     'value': {'type': 'value', 'value': 3,
                               'datatype': 'uint'}}]}}}})

    def test_counter_inside_map(self):
        actor = self.ACTOR
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'counter', 'value': 1,
             'datatype': 'counter', 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'inc', 'obj': '_root', 'key': 'counter',
             'datatype': 'uint', 'value': 2, 'pred': [f'1@{actor}']}]}
        change3 = {'actor': actor, 'seq': 3, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change2)], 'ops': [
            {'action': 'inc', 'obj': '_root', 'key': 'counter',
             'datatype': 'uint', 'value': 3, 'pred': [f'1@{actor}']}]}
        backend = OpSet()
        for change, value in ((change1, 1), (change2, 3), (change3, 6)):
            patch = backend.apply_changes([encode_change(change)])
            assert patch['diffs']['props'] == {'counter': {f'1@{actor}': {
                'type': 'value', 'value': value, 'datatype': 'counter'}}}

    def test_delete_counter_from_map(self):
        actor = self.ACTOR
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'counter', 'value': 1,
             'datatype': 'counter', 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'inc', 'obj': '_root', 'key': 'counter', 'value': 2,
             'datatype': 'uint', 'pred': [f'1@{actor}']}]}
        change3 = {'actor': actor, 'seq': 3, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change2)], 'ops': [
            {'action': 'del', 'obj': '_root', 'key': 'counter',
             'pred': [f'1@{actor}']}]}
        backend = OpSet()
        backend.apply_changes([encode_change(change1)])
        assert backend.apply_changes([encode_change(change2)]) == full_patch(
            {actor: 2}, [hash_of(change2)], 2,
            {'objectId': '_root', 'type': 'map', 'props': {'counter': {
                f'1@{actor}': {'type': 'value', 'value': 3,
                               'datatype': 'counter'}}}})
        assert backend.apply_changes([encode_change(change3)]) == full_patch(
            {actor: 3}, [hash_of(change3)], 3,
            {'objectId': '_root', 'type': 'map', 'props': {'counter': {}}})
