"""Sanitizer-plane tests (ISSUE-19 satellite): the fuzz corpus replayed
against the ASan/UBSan build of the native codec.

Two doses:

- the `slow`-marked test compiles the sanitized .so from scratch
  (~100s of g++ alone) and replays a real dose — the standalone
  enforcement run, same tier as `python tools/fuzz_wire.py`;
- the tier-1 smoke replays a tiny dose ONLY when a sanitized .so is
  already cached (built earlier by the slow test or by hand) and the
  toolchain ships the sanitizer runtimes — otherwise it skips cleanly.
  Tier-1 must never pay the compile.

Also pins the AUTOMERGE_TPU_NATIVE_SO loader override the replay child
rides on: the override loads exactly the named artifact and fails LOUDLY
(NativeAbiMismatch) on a missing file — never a silent fallback rebuild,
which would quietly replay against the unsanitized codec.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPLAY = os.path.join(REPO, 'tools', 'native_sanitize_replay.py')

sys.path.insert(0, REPO)

from automerge_tpu import native  # noqa: E402
from tools import native_sanitize_replay as replay  # noqa: E402


def _skip_unless_replayable(require_cached_so):
    if not native.available():
        pytest.skip('native toolchain unavailable')
    if replay.sanitizer_preload() is None:
        pytest.skip('toolchain has no libasan/libubsan runtime')
    if require_cached_so and not os.path.exists(replay.default_san_so()):
        pytest.skip('no cached sanitized codec (the slow test or '
                    'tools/build_native.sh --sanitize builds it)')


def _run_replay(seeds, cases):
    proc = subprocess.run(
        [sys.executable, REPLAY, '--seeds', str(seeds),
         '--cases', str(cases)],
        cwd=REPO, capture_output=True, text=True, timeout=1200)
    assert proc.returncode == 0, \
        f'rc={proc.returncode}\n{proc.stdout}\n{proc.stderr}'
    assert 'sanitize replay clean' in proc.stdout


def test_sanitize_smoke_replay_under_cached_so():
    """Tier-1 dose: pristine corpus + one seed of mutants against an
    ALREADY-BUILT sanitized codec. Skips (never compiles) otherwise."""
    _skip_unless_replayable(require_cached_so=True)
    _run_replay(seeds=1, cases=8)


@pytest.mark.slow
def test_sanitize_full_build_and_replay():
    """Standalone dose: compile the sanitized .so from source, then
    replay the full default corpus dose under it."""
    _skip_unless_replayable(require_cached_so=False)
    build = subprocess.run(
        ['sh', os.path.join(REPO, 'tools', 'build_native.sh'),
         '--sanitize=address,undefined'],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    assert build.returncode == 0, build.stdout + build.stderr
    assert os.path.exists(replay.default_san_so())
    _run_replay(seeds=int(os.environ.get('FUZZ_SEEDS', '5')),
                cases=int(os.environ.get('FUZZ_CASES', '40')))


def test_overlong_sleb_varints_reject_typed():
    """Pin for the read_sleb UB fix the sanitizer replay caught: a
    10-byte SLEB whose last payload byte lands at shift 63 (`42 << 63`
    was UB when read_sleb assembled into a signed int64). The column
    decoders must reject all three handcrafted varints typed — and,
    under the sanitized build (the smoke test above), without UBSan
    tripping, since these payloads are pinned into the replay corpus."""
    if not native.available():
        pytest.skip('native toolchain unavailable')
    from automerge_tpu.errors import AutomergeError
    for name, payload in replay.HANDCRAFTED:
        for fn in (native.decode_rle_column, native.decode_delta_column,
                   lambda b: native.decode_rle_column(b, signed=True)):
            try:
                fn(payload)
            except AutomergeError:
                pass


def test_so_override_refuses_missing_file(tmp_path):
    """AUTOMERGE_TPU_NATIVE_SO names a file that is not there: the
    loader must raise NativeAbiMismatch in that process, not fall back
    to rebuilding the default codec (a silent fallback would replay the
    fuzz corpus against the WRONG .so and report it sanitized)."""
    if not native.available():
        pytest.skip('native toolchain unavailable')
    missing = str(tmp_path / 'nope.so')
    code = ('from automerge_tpu import native\n'
            'from automerge_tpu.native import NativeAbiMismatch\n'
            'try:\n'
            '    native._load()\n'
            'except NativeAbiMismatch as exc:\n'
            "    assert 'nope.so' in str(exc), exc\n"
            "    print('LOUD')\n"
            'else:\n'
            "    raise SystemExit('override silently ignored')\n")
    env = dict(os.environ, AUTOMERGE_TPU_NATIVE_SO=missing)
    proc = subprocess.run([sys.executable, '-c', code], env=env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert 'LOUD' in proc.stdout


def test_so_override_loads_the_named_artifact():
    """The override path loads the exact named .so (here: the normal
    cached build, addressed explicitly) and passes the ABI check."""
    if not native.available():
        pytest.skip('native toolchain unavailable')
    tag = sys.implementation.cache_tag
    so = os.path.join(REPO, 'automerge_tpu', 'native', f'_codec_{tag}.so')
    if not os.path.exists(so):
        pytest.skip('no cached normal codec to address explicitly')
    code = ('from automerge_tpu import native\n'
            'assert native.available()\n'
            'assert native._LIB_PATH == %r, native._LIB_PATH\n'
            "assert native.sha256(b'x').hex().startswith('2d71')\n"
            "print('OVERRIDE-OK')\n" % so)
    env = dict(os.environ, AUTOMERGE_TPU_NATIVE_SO=so)
    proc = subprocess.run([sys.executable, '-c', code], env=env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert 'OVERRIDE-OK' in proc.stdout
