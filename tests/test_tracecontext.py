"""Cross-peer trace stitching (ISSUE-10): TraceContext, the wire
envelope, span links on the fused service batches, and the two-peer
stitch through tools/obs_report.py.

The stitching contracts pinned here: enveloping is strictly opt-in
(trace_ctx=None produces byte-identical wire traffic, and the receive
side's strip is transparent — same states, same replies), a service
sync reply is enveloped IFF the request arrived enveloped, and a
two-peer exchange exported from both sides stitches into ONE Perfetto
trace whose sync spans share the request's trace id."""

import json
import os
import sys

import pytest

import automerge_tpu as A
from automerge_tpu import backend as host_backend, native
from automerge_tpu import observability as obs
from automerge_tpu.columnar import encode_change
from automerge_tpu.observability import tracecontext as tc

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), 'tools'))

import obs_report                                 # noqa: E402


@pytest.fixture(autouse=True)
def _obs_off():
    yield
    obs.disable()


def change_bytes(actor, seq, deps=(), val=1):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': seq, 'time': 0,
        'message': '', 'deps': list(deps),
        'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                 'value': val, 'datatype': 'int', 'pred': []}]})


def host_doc(actor, n_changes=0):
    doc = A.frontend.get_backend_state(A.init(actor), f'tc-{actor}')
    deps = []
    for s in range(1, n_changes + 1):
        doc, _ = host_backend.apply_changes(
            doc, [change_bytes(actor, s, deps, val=s)])
        deps = host_backend.get_heads(doc)
    return doc


# ---------------------------------------------------------------------------
# the context + envelope primitives
# ---------------------------------------------------------------------------


def test_mint_unique_and_child_shares_trace():
    a, b = tc.mint(), tc.mint()
    assert a.trace_id != b.trace_id
    assert len(a.trace_id) == 16 and len(a.span_id) == 16
    child = a.child()
    assert child.trace_id == a.trace_id
    assert child.span_id != a.span_id


def test_wrap_unwrap_roundtrip_and_passthrough():
    ctx = tc.mint()
    wrapped = tc.wrap(b'payload', ctx)
    assert wrapped[0] == tc.TRACE_MAGIC
    got, payload = tc.unwrap(wrapped)
    assert payload == b'payload' and got == ctx
    # passthrough: plain bytes, short bytes, None
    assert tc.unwrap(b'plain') == (None, b'plain')
    assert tc.unwrap(b'\x54ab') == (None, b'\x54ab')
    assert tc.unwrap(None) == (None, None)
    # wrap with no ctx is the identity
    assert tc.wrap(b'x', None) == b'x'


def test_envelope_magic_disjoint_from_wire_frames():
    from automerge_tpu.backend.sync import MESSAGE_TYPE_SYNC
    from automerge_tpu.query.subscriptions import CURSOR_MAGIC
    assert tc.TRACE_MAGIC not in (MESSAGE_TYPE_SYNC, CURSOR_MAGIC)


def test_use_nests_and_restores():
    assert tc.current() is None
    a, b = tc.mint(), tc.mint()
    with tc.use(a):
        assert tc.current() is a
        assert tc.trace_attr() == {'trace': a.trace_id}
        with tc.use(b):
            assert tc.current() is b
        assert tc.current() is a
    assert tc.current() is None
    assert tc.trace_attr() == {}


# ---------------------------------------------------------------------------
# the sync driver: opt-in envelope, transparent strip
# ---------------------------------------------------------------------------


def test_generate_envelope_opt_in_and_strip_transparent():
    from automerge_tpu.fleet.sync_driver import (
        generate_sync_messages_docs, receive_sync_messages_docs)
    a = host_doc('aa' * 16, 3)
    sa = host_backend.init_sync_state()
    (s_plain,), (plain,) = generate_sync_messages_docs([a], [sa])
    ctx = tc.mint()
    (s_traced,), (traced,) = generate_sync_messages_docs(
        [a], [sa], trace_ctx=ctx)
    # the envelope is a pure prefix: stripping it restores the exact
    # plain-wire bytes (byte-identity holds under tracing)
    got_ctx, stripped = tc.unwrap(traced)
    assert got_ctx.trace_id == ctx.trace_id
    assert bytes(stripped) == bytes(plain)
    # receive strips transparently: same states either way
    b1 = host_doc('bb' * 16)
    b2 = host_doc('bb' * 16)
    _, (st1,), _ = receive_sync_messages_docs(
        [b1], [host_backend.init_sync_state()], [plain])
    _, (st2,), _ = receive_sync_messages_docs(
        [b2], [host_backend.init_sync_state()], [traced])
    assert st1 == st2


def test_sync_spans_carry_trace_attr():
    from automerge_tpu.fleet.sync_driver import (
        generate_sync_messages_docs, receive_sync_messages_docs)
    a = host_doc('aa' * 16, 2)
    b = host_doc('bb' * 16)
    obs.enable()
    obs.clear_spans()
    ctx = tc.mint()
    with tc.use(ctx):
        _, (msg,) = generate_sync_messages_docs(
            [a], [host_backend.init_sync_state()], trace_ctx=ctx)
    receive_sync_messages_docs([b], [host_backend.init_sync_state()],
                               [msg])
    spans = {s['name']: s for s in obs.iter_spans()}
    assert spans['sync_generate']['attrs']['trace'] == ctx.trace_id
    # the receive side adopted the STRIPPED envelope's id — same trace
    assert spans['sync_receive']['attrs']['trace'] == ctx.trace_id


# ---------------------------------------------------------------------------
# the service: minting, reply enveloping, batch links
# ---------------------------------------------------------------------------

needs_native = pytest.mark.skipif(not native.available(),
                                  reason='native codec unavailable')


@needs_native
def test_service_reply_enveloped_iff_request_was():
    from automerge_tpu.fleet.backend import DocFleet
    from automerge_tpu.service import DocService
    svc = DocService(fleet=DocFleet(doc_capacity=8, key_capacity=64),
                     tenant_rate=10_000.0, tenant_burst=1000.0)
    plain_s, traced_s = svc.open_sessions(['p', 't'])

    client = host_doc('cc' * 16, 2)
    state, msg = host_backend.generate_sync_message(
        client, host_backend.init_sync_state())
    ctx = tc.mint()
    t_plain = svc.submit(plain_s, 'sync', msg)
    t_traced = svc.submit(traced_s, 'sync', tc.wrap(msg, ctx))
    svc.pump()
    assert t_plain.status == 'ok' and t_traced.status == 'ok'
    # plain request: plain reply
    assert t_plain.result is None or t_plain.result[0] != tc.TRACE_MAGIC
    # enveloped request: the ticket adopts the client's trace id and the
    # reply comes back enveloped under the same trace
    assert t_traced.trace.trace_id == ctx.trace_id
    assert t_traced.result is not None
    reply_ctx, reply = tc.unwrap(t_traced.result)
    assert reply_ctx is not None
    assert reply_ctx.trace_id == ctx.trace_id
    assert reply_ctx.span_id != ctx.span_id    # the service's own node
    # the stripped reply is a decodable sync message
    host_backend.receive_sync_message(client, state, reply)


@needs_native
def test_service_batch_spans_link_member_traces():
    from automerge_tpu.fleet.backend import DocFleet
    from automerge_tpu.service import DocService
    svc = DocService(fleet=DocFleet(doc_capacity=8, key_capacity=64),
                     tenant_rate=10_000.0, tenant_burst=1000.0)
    s1, s2 = svc.open_sessions(['a', 'b'])
    obs.enable()
    obs.clear_spans()
    t1 = svc.submit(s1, 'apply', [change_bytes('aa' * 16, 1)])
    t2 = svc.submit(s2, 'apply', [change_bytes('bb' * 16, 1)])
    svc.pump()
    obs.disable()
    assert t1.status == 'ok' and t2.status == 'ok'
    assert t1.trace is not None and t2.trace is not None
    batch = [s for s in obs.iter_spans()
             if s['name'] == 'service_apply_batch']
    assert len(batch) == 1
    links = batch[0]['attrs']['links']
    assert set(links) == {t1.trace.trace_id, t2.trace.trace_id}


# ---------------------------------------------------------------------------
# the acceptance: two peers, one stitched Perfetto trace
# ---------------------------------------------------------------------------


def test_two_peer_exchange_stitches_to_one_trace(tmp_path):
    from automerge_tpu.fleet.sync_driver import (
        generate_sync_messages_docs, receive_sync_messages_docs)
    a = host_doc('aa' * 16, 3)
    b = host_doc('bb' * 16)
    sa = host_backend.init_sync_state()
    sb = host_backend.init_sync_state()

    obs.enable()
    obs.clear_spans()
    ctx = tc.mint()
    # peer A generates under the trace (envelope on the wire)...
    with tc.use(ctx):
        (sa,), (msg,) = generate_sync_messages_docs([a], [sa],
                                                    trace_ctx=ctx)
    peer_a = tmp_path / 'peer_a.json'
    obs.export_chrome_trace(str(peer_a))
    obs.clear_spans()
    # ...peer B receives it (the "other process": its own span ring) and
    # answers, continuing the SAME trace from the stripped envelope
    (b,), (sb,), _ = receive_sync_messages_docs([b], [sb], [msg])
    reply_ctx, _payload = tc.unwrap(msg)
    with tc.use(reply_ctx):
        generate_sync_messages_docs([b], [sb],
                                    trace_ctx=reply_ctx.child())
    peer_b = tmp_path / 'peer_b.json'
    obs.export_chrome_trace(str(peer_b))
    obs.disable()

    out = tmp_path / 'stitched.json'
    shared = obs_report.render_stitch([str(peer_a), str(peer_b)],
                                      str(out))
    # ONE trace id spans both peers' exports
    assert ctx.trace_id in shared
    stitched = json.loads(out.read_text())['traceEvents']
    by_pid = {}
    for event in stitched:
        if event.get('ph') != 'X':
            continue
        ids = obs_report._event_trace_ids(event)
        if ctx.trace_id in ids:
            by_pid.setdefault(event['pid'], []).append(event['name'])
    # both peers contribute sync spans to the request's trace
    assert set(by_pid) == {1, 2}
    assert 'sync_generate' in by_pid[1]
    assert 'sync_receive' in by_pid[2]
    # process metadata names the inputs
    names = [e['args']['name'] for e in stitched
             if e.get('ph') == 'M']
    assert names == ['peer_a.json', 'peer_b.json']


def test_stitch_accepts_flight_dumps(tmp_path):
    from automerge_tpu.observability import recorder as obs_recorder
    obs.enable()
    obs.clear_spans()
    ctx = tc.mint()
    with obs.span('work', trace=ctx.trace_id):
        pass
    dump = obs_recorder.dump_flight_record(
        'unit', path=str(tmp_path / 'flight.json'))
    assert dump['recent_spans']
    trace = tmp_path / 'trace.json'
    obs.export_chrome_trace(str(trace))
    obs.disable()
    shared = obs_report.render_stitch(
        [str(tmp_path / 'flight.json'), str(trace)],
        str(tmp_path / 'out.json'))
    assert ctx.trace_id in shared
