"""The full public-API integration suite re-run with the device-routed fleet
backend installed as the default backend (the test/wasm.js pattern: the same
test corpus must pass against a replacement backend, ref test/wasm.js:27-36).

Every class from tests/test_integration.py is re-collected here under an
autouse fixture that swaps in a fresh FleetBackend per test; flat, nested
map/table, list, text, and objects-inside-lists documents all exercise the
fleet-resident device path, and teardown restores the host backend."""

import pytest

import automerge_tpu as A
from automerge_tpu import backend as host_backend
from automerge_tpu.fleet.backend import DocFleet, FleetBackend

from tests.test_integration import (  # noqa: F401
    TestInitAndChange, TestLists, TestConcurrentUse, TestCounters,
    TestSaveLoad, TestHistory, TestChangesAPI, TestText, TestTable,
)


@pytest.fixture(autouse=True, params=['lww', 'exact'])
def fleet_default_backend(request):
    A.set_default_backend(FleetBackend(DocFleet(
        doc_capacity=4, key_capacity=4,
        exact_device=request.param == 'exact')))
    try:
        yield
    finally:
        A.set_default_backend(host_backend)


class TestNestedMapsFleetResident:
    """Nested map/table documents stay fleet-resident: two-level
    (objectId, key) interning keeps the whole map tree on the device grid
    (VERDICT round-2 item 5; ref new.js:1461-1528 objectMeta ancestry)."""

    def test_nested_maps_promotionless(self, fleet_default_backend):
        import automerge_tpu as am
        d1 = am.init('aa' * 4)
        d1 = am.change(d1, lambda d: d.update(
            {'config': {'theme': {'color': 'blue', 'sizes': {'h1': 32}}},
             'title': 'doc'}))
        d1 = am.change(d1, lambda d: d['config']['theme'].update(
            {'color': 'red'}))
        d1 = am.change(d1, lambda d: d['config']['theme']['sizes'].update(
            {'h2': 24}))
        d2 = am.merge(am.init('bb' * 4), d1)
        d1 = am.change(d1, lambda d: d['config'].update({'lang': 'en'}))
        d2 = am.change(d2, lambda d: d['config'].update({'lang': 'fr'}))
        m = am.merge(d1, d2)
        assert m['config']['theme']['color'] == 'red'
        assert m['config']['theme']['sizes']['h2'] == 24
        assert m['config']['lang'] in ('en', 'fr')
        state = am.Frontend.get_backend_state(m)['state']
        assert state.is_fleet
        assert state.fleet.metrics.promotions == 0
        # Device-grid readback assembles the same map tree
        from automerge_tpu.fleet.backend import materialize_docs
        raw = materialize_docs([am.Frontend.get_backend_state(m)])[0]
        assert raw['config']['theme']['sizes'] == {'h1': 32, 'h2': 24}
        assert raw['title'] == 'doc'

    def test_objects_inside_lists_promotionless(self, fleet_default_backend):
        """Rows-in-lists — maps, tables, and nested lists created as list
        elements — stay fleet-resident (VERDICT round-3 item 5; ref
        new.js:1461-1528): the element value links to the child object,
        which interns like any registered object."""
        import automerge_tpu as am
        d1 = am.init('ab' * 4)
        d1 = am.change(d1, lambda d: d.update(
            {'todo': [{'title': 'wash', 'done': False}, 'plain', [1, 2]]}))
        d1 = am.change(
            d1, lambda d: d['todo'][0].update({'done': True}))
        d1 = am.change(d1, lambda d: d['todo'][2].append(3))
        # Concurrent edits inside nested list elements converge
        d2 = am.merge(am.init('cd' * 4), d1)
        d1 = am.change(d1, lambda d: d['todo'][0].update({'who': 'a'}))
        d2 = am.change(d2, lambda d: d['todo'][0].update({'who': 'b'}))
        m = am.merge(d1, d2)
        assert m['todo'][0]['done'] is True
        assert m['todo'][0]['who'] in ('a', 'b')
        assert list(m['todo'][2]) == [1, 2, 3]
        state = am.Frontend.get_backend_state(m)['state']
        assert state.is_fleet
        assert state.fleet.metrics.promotions == 0
        # Device readback assembles the same tree (unresolved links would
        # route to the mirror and fail the comparison below)
        from automerge_tpu.fleet.backend import (
            materialize_docs, _has_unresolved_link)
        raw_all = state.fleet.materialize_all()[state._impl.slot]
        assert not _has_unresolved_link(raw_all)
        raw = materialize_docs([am.Frontend.get_backend_state(m)])[0]
        assert raw['todo'][0]['done'] is True
        assert raw['todo'][1] == 'plain'
        assert raw['todo'][2] == [1, 2, 3]
        # save/load round-trip matches the host engine byte-for-byte
        saved = am.save(m)
        loaded = am.load(saved)
        assert loaded['todo'][0]['title'] == 'wash'

    def test_deleting_object_elements_promotionless(
            self, fleet_default_backend):
        import automerge_tpu as am
        d1 = am.init('ee' * 4)
        d1 = am.change(d1, lambda d: d.update(
            {'rows': [{'a': 1}, {'b': 2}, {'c': 3}]}))
        d1 = am.change(d1, lambda d: d['rows'].delete_at(1))
        assert [dict(r) for r in d1['rows']] == [{'a': 1}, {'c': 3}]
        state = am.Frontend.get_backend_state(d1)['state']
        assert state.is_fleet
        assert state.fleet.metrics.promotions == 0

    def test_tables_promotionless(self, fleet_default_backend):
        import automerge_tpu as am
        d1 = am.init('cc' * 4)
        d1 = am.change(d1, lambda d: d.update({'books': am.Table()}))

        def add_row(d):
            d['books'].add({'title': 'STP', 'authors': 'KB'})
        d1 = am.change(d1, add_row)
        row_id = d1['books'].ids[0]
        d1 = am.change(d1, lambda d: d['books'].by_id(row_id).update(
            {'authors': 'Kleppmann'}))
        assert d1['books'].by_id(row_id)['authors'] == 'Kleppmann'
        state = am.Frontend.get_backend_state(d1)['state']
        assert state.is_fleet
        assert state.fleet.metrics.promotions == 0
