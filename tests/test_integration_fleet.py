"""The full public-API integration suite re-run with the device-routed fleet
backend installed as the default backend (the test/wasm.js pattern: the same
test corpus must pass against a replacement backend, ref test/wasm.js:27-36).

Every class from tests/test_integration.py is re-collected here under an
autouse fixture that swaps in a fresh FleetBackend per test; flat documents
exercise the device path, nested/list/text documents exercise transparent
promotion, and teardown restores the host backend."""

import pytest

import automerge_tpu as A
from automerge_tpu import backend as host_backend
from automerge_tpu.fleet.backend import DocFleet, FleetBackend

from tests.test_integration import (  # noqa: F401
    TestInitAndChange, TestLists, TestConcurrentUse, TestCounters,
    TestSaveLoad, TestHistory, TestChangesAPI, TestText, TestTable,
)


@pytest.fixture(autouse=True, params=['lww', 'exact'])
def fleet_default_backend(request):
    A.set_default_backend(FleetBackend(DocFleet(
        doc_capacity=4, key_capacity=4,
        exact_device=request.param == 'exact')))
    try:
        yield
    finally:
        A.set_default_backend(host_backend)
