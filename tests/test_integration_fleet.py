"""The full public-API integration suite re-run with the device-routed fleet
backend installed as the default backend (the test/wasm.js pattern: the same
test corpus must pass against a replacement backend, ref test/wasm.js:27-36).

Every class from tests/test_integration.py is re-collected here under an
autouse fixture that swaps in a fresh FleetBackend per test; flat, nested
map/table, list, and text documents all exercise the fleet-resident device
path (objects inside sequences exercise transparent promotion), and
teardown restores the host backend."""

import pytest

import automerge_tpu as A
from automerge_tpu import backend as host_backend
from automerge_tpu.fleet.backend import DocFleet, FleetBackend

from tests.test_integration import (  # noqa: F401
    TestInitAndChange, TestLists, TestConcurrentUse, TestCounters,
    TestSaveLoad, TestHistory, TestChangesAPI, TestText, TestTable,
)


@pytest.fixture(autouse=True, params=['lww', 'exact'])
def fleet_default_backend(request):
    A.set_default_backend(FleetBackend(DocFleet(
        doc_capacity=4, key_capacity=4,
        exact_device=request.param == 'exact')))
    try:
        yield
    finally:
        A.set_default_backend(host_backend)


class TestNestedMapsFleetResident:
    """Nested map/table documents stay fleet-resident: two-level
    (objectId, key) interning keeps the whole map tree on the device grid
    (VERDICT round-2 item 5; ref new.js:1461-1528 objectMeta ancestry)."""

    def test_nested_maps_promotionless(self, fleet_default_backend):
        import automerge_tpu as am
        d1 = am.init('aa' * 4)
        d1 = am.change(d1, lambda d: d.update(
            {'config': {'theme': {'color': 'blue', 'sizes': {'h1': 32}}},
             'title': 'doc'}))
        d1 = am.change(d1, lambda d: d['config']['theme'].update(
            {'color': 'red'}))
        d1 = am.change(d1, lambda d: d['config']['theme']['sizes'].update(
            {'h2': 24}))
        d2 = am.merge(am.init('bb' * 4), d1)
        d1 = am.change(d1, lambda d: d['config'].update({'lang': 'en'}))
        d2 = am.change(d2, lambda d: d['config'].update({'lang': 'fr'}))
        m = am.merge(d1, d2)
        assert m['config']['theme']['color'] == 'red'
        assert m['config']['theme']['sizes']['h2'] == 24
        assert m['config']['lang'] in ('en', 'fr')
        state = am.Frontend.get_backend_state(m)['state']
        assert state.is_fleet
        assert state.fleet.metrics.promotions == 0
        # Device-grid readback assembles the same map tree
        from automerge_tpu.fleet.backend import materialize_docs
        raw = materialize_docs([am.Frontend.get_backend_state(m)])[0]
        assert raw['config']['theme']['sizes'] == {'h1': 32, 'h2': 24}
        assert raw['title'] == 'doc'

    def test_tables_promotionless(self, fleet_default_backend):
        import automerge_tpu as am
        d1 = am.init('cc' * 4)
        d1 = am.change(d1, lambda d: d.update({'books': am.Table()}))

        def add_row(d):
            d['books'].add({'title': 'STP', 'authors': 'KB'})
        d1 = am.change(d1, add_row)
        row_id = d1['books'].ids[0]
        d1 = am.change(d1, lambda d: d['books'].by_id(row_id).update(
            {'authors': 'Kleppmann'}))
        assert d1['books'].by_id(row_id)['authors'] == 'Kleppmann'
        state = am.Frontend.get_backend_state(d1)['state']
        assert state.is_fleet
        assert state.fleet.metrics.promotions == 0
