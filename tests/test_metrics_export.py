"""Prometheus exposition + MetricsExporter lifecycle (ISSUE-10).

The exporter contracts pinned here: port-0 ephemeral bind for tests, a
concurrent scrape during recording never reads torn histograms (every
``_bucket`` series sums exactly to its ``_count`` — the render comes
from an atomic snapshot), clean shutdown joins the serving thread, and
``AUTOMERGE_TPU_METRICS_PORT`` unset means FULLY disabled: no server,
no thread, nothing."""

import threading
import urllib.request

import pytest

from automerge_tpu.observability import hist as obs_hist
from automerge_tpu.observability import (MetricsExporter, SloPolicy,
                                         SloRegistry, maybe_start_exporter,
                                         render_prometheus)
from automerge_tpu.observability.export import (METRICS_PORT_ENV,
                                                METRICS_SNAPSHOT_ENV,
                                                snapshot_all)


@pytest.fixture(autouse=True)
def _fresh_hists():
    """Run each test against a clean histogram registry (the module
    registry is process-global)."""
    saved = dict(obs_hist._registry)
    obs_hist._registry.clear()
    obs_hist.enable()
    yield
    obs_hist.disable()
    obs_hist._registry.clear()
    obs_hist._registry.update(saved)


def _scrape(port, path='/metrics'):
    with urllib.request.urlopen(
            f'http://127.0.0.1:{port}{path}', timeout=5) as resp:
        return resp.status, resp.read().decode()


def _parse_series(page):
    out = {}
    for line in page.splitlines():
        if line.startswith('#') or not line.strip():
            continue
        name, value = line.rsplit(' ', 1)
        out[name] = float(value)
    return out


def test_render_health_dispatch_and_histograms():
    obs_hist.record_value('unit_test_lat_s', 0.003, scale=1e9, unit='s')
    obs_hist.record_value('unit_test_lat_s', 0.7, scale=1e9, unit='s')
    page = render_prometheus()
    series = _parse_series(page)
    assert any(k.startswith('automerge_tpu_health_total{')
               for k in series)
    assert any(k.startswith('automerge_tpu_dispatch_total{')
               for k in series)
    assert series['automerge_tpu_unit_test_lat_s_count'] == 2
    assert series['automerge_tpu_unit_test_lat_s_bucket{le="+Inf"}'] == 2
    # cumulative monotone, ending at count
    buckets = [(k, v) for k, v in series.items()
               if k.startswith('automerge_tpu_unit_test_lat_s_bucket')]
    values = [v for _, v in buckets]
    assert values == sorted(values)


def test_render_slo_series_and_label_escaping():
    reg = SloRegistry(policies={
        'latency': SloPolicy(0.99, threshold_s=0.05)})
    hostile = 'ten"ant\\{}\n2'
    reg.record(hostile, 'apply', 0.001)
    reg.tick()
    page = render_prometheus(slo=reg)
    assert 'automerge_tpu_slo_requests_total' in page
    assert 'ten\\"ant\\\\{}\\n2' in page
    # every line still parses name-space-value
    assert _parse_series(page)


def test_exporter_port0_bind_scrape_and_shutdown():
    before = threading.active_count()
    exporter = MetricsExporter(port=0).start()
    assert exporter.port and exporter.port != 0
    assert exporter.running
    status, page = _scrape(exporter.port)
    assert status == 200
    assert 'automerge_tpu_health_total' in page
    status404 = None
    try:
        _scrape(exporter.port, '/nope')
    except urllib.error.HTTPError as exc:
        status404 = exc.code
    assert status404 == 404
    exporter.stop()
    assert not exporter.running
    assert exporter.port is None
    # no thread leak: back to (at most) where we started
    assert threading.active_count() <= before + 1


def test_concurrent_scrape_never_reads_torn_histograms():
    h = obs_hist.histogram('torn_probe_s', scale=1e9, unit='s')
    stop = threading.Event()

    def hammer():
        v = 0
        while not stop.is_set():
            h.record((v % 1000) / 1e4)
            v += 1

    writer = threading.Thread(target=hammer, daemon=True)
    writer.start()
    exporter = MetricsExporter(port=0).start()
    try:
        for _ in range(25):
            _, page = _scrape(exporter.port)
            series = _parse_series(page)
            # page order IS bucket order (the dict preserves it)
            buckets = [(k, v) for k, v in series.items()
                       if k.startswith('automerge_tpu_torn_probe_s_bucket')]
            count = series['automerge_tpu_torn_probe_s_count']
            inf = series['automerge_tpu_torn_probe_s_bucket{le="+Inf"}']
            # the atomic-snapshot contract: cumulative buckets agree
            # with the count rendered on the SAME page, always
            assert inf == count, (inf, count)
            values = [v for _, v in buckets]
            assert values == sorted(values)
    finally:
        stop.set()
        writer.join(timeout=5)
        exporter.stop()


def test_env_unset_means_fully_disabled(monkeypatch):
    monkeypatch.delenv(METRICS_PORT_ENV, raising=False)
    monkeypatch.delenv(METRICS_SNAPSHOT_ENV, raising=False)
    before = threading.active_count()
    assert maybe_start_exporter() is None
    assert threading.active_count() == before


def test_env_port_starts_and_serves(monkeypatch):
    monkeypatch.setenv(METRICS_PORT_ENV, '0')
    exporter = maybe_start_exporter()
    try:
        assert exporter is not None and exporter.running
        status, page = _scrape(exporter.port)
        assert status == 200 and 'automerge_tpu' in page
    finally:
        exporter.stop()


def test_snapshot_file_mode_atomic(tmp_path, monkeypatch):
    monkeypatch.delenv(METRICS_PORT_ENV, raising=False)
    target = tmp_path / 'metrics.prom'
    monkeypatch.setenv(METRICS_SNAPSHOT_ENV, str(target))
    before = threading.active_count()
    exporter = maybe_start_exporter()
    # snapshot-only mode: no server, no thread
    assert exporter is not None and not exporter.running
    assert threading.active_count() == before
    obs_hist.record_value('snap_probe_s', 0.01, scale=1e9, unit='s')
    path = exporter.write_snapshot()
    assert path == str(target)
    page = target.read_text()
    assert 'automerge_tpu_snap_probe_s_count 1' in page
    # no temp litter (the write is temp+rename)
    assert [p.name for p in tmp_path.iterdir()] == ['metrics.prom']


def test_snapshot_all_is_plain_data():
    reg = SloRegistry()
    reg.record('t', 'apply', 0.001)
    reg.tick()
    snap = snapshot_all(slo=reg)
    import json
    # keys are tuples for the slo sections; everything else must be
    # JSON-serializable plain data
    json.dumps({k: v for k, v in snap.items()
                if not k.startswith('slo_')})
    assert snap['slo_tallies'][('t', 'apply')]['committed'] == 1
