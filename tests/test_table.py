"""Table conformance tests (ported semantics of reference
test/table_test.js: row CRUD, queries, sorting, JSON, concurrent insertion)."""

import json

import pytest

import automerge_tpu as am
from automerge_tpu import frontend as Frontend
from automerge_tpu.frontend import Table

DDIA = {'authors': ['Kleppmann, Martin'], 'title': 'Designing Data-Intensive '
        'Applications', 'isbn': '1449373321'}
RSDP = {'authors': ['Cachin, Christian', 'Guerraoui, Rachid',
                    'Rodrigues, Luís'],
        'title': 'Introduction to Reliable and Secure Distributed Programming',
        'isbn': '3642152597'}


def make_books():
    def setup(d):
        d['books'] = Table()
        d._row_id = d['books'].add(DDIA)
    doc = am.init()
    row_holder = {}

    def setup2(d):
        d['books'] = Table()
        row_holder['id'] = d['books'].add(DDIA)
    doc = am.change(doc, setup2)
    return doc, row_holder['id']


class TestTableFrontend:
    def test_create_table_ops(self):
        doc, change = Frontend.change(
            Frontend.init(), lambda d: d.update({'books': Table()}))
        assert change['ops'][0]['action'] == 'makeTable'

    def test_insert_row_ops(self):
        row_holder = {}

        def setup(d):
            d['books'] = Table()
            row_holder['id'] = d['books'].add({'title': 'T', 'isbn': 'x'})
        doc, change = Frontend.change(Frontend.init(), setup)
        actions = [op['action'] for op in change['ops']]
        assert actions[0] == 'makeTable'
        assert 'makeMap' in actions
        row = doc['books'].by_id(row_holder['id'])
        assert row['title'] == 'T'
        assert row['id'] == row_holder['id']


class TestTableQueries:
    def test_lookup_by_id(self):
        doc, row_id = make_books()
        row = doc['books'].by_id(row_id)
        assert row['title'] == DDIA['title']
        assert row['id'] == row_id

    def test_row_count(self):
        doc, _ = make_books()
        assert doc['books'].count == 1
        assert len(doc['books']) == 1

    def test_row_ids(self):
        doc, row_id = make_books()
        assert doc['books'].ids == [row_id]

    def test_iterate_rows(self):
        doc, row_id = make_books()
        rows = list(doc['books'])
        assert len(rows) == 1 and rows[0]['id'] == row_id

    def test_query_methods(self):
        doc, row_id = make_books()
        books = doc['books']
        assert books.filter(lambda r: len(r['authors']) == 1)[0]['id'] == row_id
        assert books.find(lambda r: r['isbn'] == '1449373321')['id'] == row_id
        assert books.map(lambda r: r['title'])[0] == DDIA['title']
        assert books.find(lambda r: False) is None

    def test_save_and_reload(self):
        doc, row_id = make_books()
        reloaded = am.load(am.save(doc))
        assert reloaded['books'].by_id(row_id)['title'] == DDIA['title']
        assert reloaded['books'].count == 1


class TestTableMutation:
    def test_update_row(self):
        doc, row_id = make_books()

        def update(d):
            d['books'].by_id(row_id)['isbn'] = '9781449373320'
        doc2 = am.change(doc, update)
        assert doc2['books'].by_id(row_id)['isbn'] == '9781449373320'
        # Old doc unchanged (immutability)
        assert doc['books'].by_id(row_id)['isbn'] == '1449373321'

    def test_remove_row(self):
        doc, row_id = make_books()
        doc2 = am.change(doc, lambda d: d['books'].remove(row_id))
        assert doc2['books'].count == 0
        assert doc2['books'].by_id(row_id) is None
        with pytest.raises(ValueError, match='no row with ID'):
            am.change(doc2, lambda d: d['books'].remove(row_id))

    def test_row_id_cannot_be_specified(self):
        doc = am.change(am.init(), lambda d: d.update({'books': Table()}))
        with pytest.raises(TypeError, match='must not have an "id"'):
            am.change(doc, lambda d: d['books'].add({'id': 'abc', 'title': 'x'}))

    def test_row_must_be_object(self):
        doc = am.change(am.init(), lambda d: d.update({'books': Table()}))
        with pytest.raises(TypeError):
            am.change(doc, lambda d: d['books'].add(['a', 'list']))

    def test_create_update_delete_same_change(self):
        def edit(d):
            d['books'] = Table()
            rid = d['books'].add({'title': 'a'})
            d['books'].by_id(rid)['title'] = 'b'
            rid2 = d['books'].add({'title': 'gone'})
            d['books'].remove(rid2)
        doc = am.change(am.init(), edit)
        assert doc['books'].count == 1
        assert doc['books'].rows[0]['title'] == 'b'


class TestTableConcurrency:
    def test_concurrent_row_insertion(self):
        a0 = am.change(am.init('aa01'), lambda d: d.update({'books': Table()}))
        b0 = am.load(am.save(a0), 'bb02')
        ra, rb = {}, {}
        a1 = am.change(a0, lambda d: ra.update(id=d['books'].add(DDIA)))
        b1 = am.change(b0, lambda d: rb.update(id=d['books'].add(RSDP)))
        m = am.merge(a1, b1)
        assert m['books'].count == 2
        assert m['books'].by_id(ra['id'])['title'] == DDIA['title']
        assert m['books'].by_id(rb['id'])['title'] == RSDP['title']


class TestTableSortAndJson:
    def make_three(self):
        rows = [{'authors': 'c', 'title': 'C', 'isbn': '3'},
                {'authors': 'a', 'title': 'A', 'isbn': '1'},
                {'authors': 'b', 'title': 'B', 'isbn': '2'}]

        def setup(d):
            d['books'] = Table()
            for r in rows:
                d['books'].add(r)
        return am.change(am.init(), setup)

    def test_sort_by_column(self):
        doc = self.make_three()
        titles = [r['title'] for r in doc['books'].sort('title')]
        assert titles == ['A', 'B', 'C']
        isbns = [r['isbn'] for r in doc['books'].sort(['isbn'])]
        assert isbns == ['1', '2', '3']

    def test_sort_by_comparator(self):
        doc = self.make_three()

        def cmp(a, b):
            return (a['isbn'] > b['isbn']) - (a['isbn'] < b['isbn'])
        isbns = [r['isbn'] for r in doc['books'].sort(cmp)]
        assert isbns == ['1', '2', '3']

    def test_json_serialization(self):
        doc, row_id = make_books()
        payload = doc['books'].to_json()
        assert json.loads(json.dumps(payload))[row_id]['title'] == DDIA['title']
