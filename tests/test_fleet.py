"""Fleet engine tests: differential validation of the batched JAX kernels
against the host OpSet engine (the correctness oracle), Bloom wire
compatibility, and multi-device sharding on the virtual CPU mesh."""

import random

import numpy as np
import pytest

import automerge_tpu.backend as Backend
from automerge_tpu.backend.sync import BloomFilter
from automerge_tpu.columnar import encode_change
from automerge_tpu.fleet import (
    FleetState, OpBatch, apply_op_batch, pack_op_id,
    build_bloom_filters, probe_bloom_filters, bloom_filter_bytes,
)
from automerge_tpu.fleet.bloom import hashes_to_words, num_filter_bits


def random_map_workload(rng, n_docs, n_keys, n_actors, rounds, ops_per_round):
    """Generate per-doc concurrent map-set/inc workloads. Returns a list of
    round batches: per doc, list of (key, ctr, actor, kind, value)."""
    workloads = []
    ctr_base = 1
    for _ in range(rounds):
        round_ops = []
        for d in range(n_docs):
            ops = []
            for i in range(ops_per_round):
                key = rng.randrange(n_keys)
                actor = rng.randrange(n_actors)
                ctr = ctr_base + i
                kind = 'set'
                value = rng.randrange(1, 1000)
                ops.append((key, ctr, actor, kind, value))
            round_ops.append(ops)
        workloads.append(round_ops)
        ctr_base += ops_per_round
    return workloads


def to_op_batch(round_ops, n_docs, max_ops):
    key_id = np.zeros((n_docs, max_ops), dtype=np.int32)
    packed = np.zeros((n_docs, max_ops), dtype=np.int32)
    value = np.zeros((n_docs, max_ops), dtype=np.int32)
    is_set = np.zeros((n_docs, max_ops), dtype=bool)
    is_inc = np.zeros((n_docs, max_ops), dtype=bool)
    valid = np.zeros((n_docs, max_ops), dtype=bool)
    for d, ops in enumerate(round_ops):
        for j, (key, ctr, actor, kind, val) in enumerate(ops):
            key_id[d, j] = key
            packed[d, j] = pack_op_id(ctr, actor)
            value[d, j] = val
            is_set[d, j] = kind == 'set'
            is_inc[d, j] = kind == 'inc'
            valid[d, j] = True
    return OpBatch(key_id, packed, value, is_set, is_inc, valid)


class TestFleetMergeDifferential:
    def test_lww_matches_host_engine(self):
        """The fleet kernel's per-key winners must match the host OpSet
        engine's visible values for concurrent multi-actor map workloads."""
        rng = random.Random(42)
        n_docs, n_keys, n_actors = 8, 12, 4
        rounds = 3
        ops_per_round = 10
        workloads = random_map_workload(rng, n_docs, n_keys, n_actors,
                                        rounds, ops_per_round)

        # Fleet path
        state = FleetState.empty(n_docs, n_keys)
        for round_ops in workloads:
            batch = to_op_batch(round_ops, n_docs, ops_per_round)
            state, _ = apply_op_batch(state, batch)
        winners = np.asarray(state.winners)
        values = np.asarray(state.values)

        # Host oracle: apply the same ops as binary changes, one doc at a time
        actors = [f'{i:02x}' * 3 for i in range(n_actors)]
        for d in range(n_docs):
            backend = Backend.init()
            seqs = {}
            # group by (round, actor): each actor's ops in one change
            for round_ops in workloads:
                by_actor = {}
                for (key, ctr, actor, kind, val) in round_ops[d]:
                    by_actor.setdefault(actor, []).append((key, ctr, kind, val))
                for actor, ops in by_actor.items():
                    ops.sort(key=lambda o: o[1])
                    start_op = ops[0][1]
                    # ops in a change must have consecutive counters; split runs
                    runs = []
                    run = [ops[0]]
                    for op in ops[1:]:
                        if op[1] == run[-1][1] + 1:
                            run.append(op)
                        else:
                            runs.append(run)
                            run = [op]
                    runs.append(run)
                    for run in runs:
                        seq = seqs.get(actor, 0) + 1
                        seqs[actor] = seq
                        change = {
                            'actor': actors[actor], 'seq': seq,
                            'startOp': run[0][1], 'time': 0, 'message': '',
                            'deps': Backend.get_heads(backend) if seq > 1 or True
                            else [],
                            'ops': [{'action': 'set', 'obj': '_root',
                                     'key': f'k{key}', 'value': val,
                                     'datatype': 'int', 'pred': []}
                                    for (key, ctr, kind, val) in run],
                        }
                        backend, _ = Backend.apply_changes(
                            backend, [encode_change(change)])
            patch = Backend.get_patch(backend)
            props = patch['diffs']['props']
            for key in range(n_keys):
                key_name = f'k{key}'
                if key_name in props:
                    # host LWW winner = greatest opId among the conflict set
                    host_values = props[key_name]
                    from automerge_tpu.common import lamport_key
                    win_op = max(host_values.keys(), key=lamport_key)
                    host_val = host_values[win_op]['value']
                    assert values[d, key] == host_val, \
                        f'doc {d} key {key}: fleet {values[d, key]} != host {host_val}'
                else:
                    assert winners[d, key] == 0

    def test_counters_accumulate(self):
        n_docs = 4
        state = FleetState.empty(n_docs, 2)
        # Round 1: create counters (set), round 2-3: concurrent incs
        b1 = to_op_batch([[(0, 1, a % 3, 'set', 10)] for a in range(n_docs)],
                         n_docs, 1)
        b2 = to_op_batch([[(0, 2 + a % 2, a % 3, 'inc', 5)] for a in range(n_docs)],
                         n_docs, 1)
        b3 = to_op_batch([[(0, 4, (a + 1) % 3, 'inc', 7)] for a in range(n_docs)],
                         n_docs, 1)
        for b in (b1, b2, b3):
            state, _ = apply_op_batch(state, b)
        counters = np.asarray(state.counters)
        values = np.asarray(state.values)
        # counter value = initial set value + accumulated incs
        assert all(values[:, 0] == 10)
        assert all(counters[:, 0] == 12)

    def test_padding_lanes_ignored(self):
        state = FleetState.empty(2, 3)
        batch = to_op_batch([[(0, 1, 0, 'set', 42)], []], 2, 4)
        state, stats = apply_op_batch(state, batch)
        assert int(stats) == 1
        values = np.asarray(state.values)
        winners = np.asarray(state.winners)
        assert values[0, 0] == 42
        assert np.all(winners[1, :3] == 0)


class TestFleetBloom:
    def test_wire_compatible_with_host_bloom(self):
        """Batched filters must serialize byte-identically to the reference
        BloomFilter over the same hashes."""
        import hashlib
        n_docs, n_hashes = 5, 8
        hashes = [[hashlib.sha256(f'{d}:{i}'.encode()).hexdigest()
                   for i in range(n_hashes)] for d in range(n_docs)]
        words, valid = hashes_to_words(hashes)
        bits = build_bloom_filters(words, valid, n_hashes)
        for d in range(n_docs):
            batched = bloom_filter_bytes(np.asarray(bits)[d], n_hashes)
            host = BloomFilter(hashes[d]).bytes
            assert batched == host, f'doc {d} filter bytes differ'

    def test_batched_probe_matches_host(self):
        import hashlib
        n_docs, n_hashes = 4, 16
        member = [[hashlib.sha256(f'{d}:{i}'.encode()).hexdigest()
                   for i in range(n_hashes)] for d in range(n_docs)]
        queries = [[hashlib.sha256(f'q{d}:{i}'.encode()).hexdigest()
                    for i in range(n_hashes)] for d in range(n_docs)]
        words, valid = hashes_to_words(member)
        bits = build_bloom_filters(words, valid, n_hashes)
        qwords, qvalid = hashes_to_words(queries)
        batched = np.asarray(probe_bloom_filters(bits, qwords, qvalid))
        for d in range(n_docs):
            host = BloomFilter(member[d])
            for i, q in enumerate(queries[d]):
                assert batched[d, i] == host.contains_hash(q)

    def test_members_always_hit(self):
        import hashlib
        hashes = [[hashlib.sha256(f'{i}'.encode()).hexdigest()
                   for i in range(10)]]
        words, valid = hashes_to_words(hashes)
        bits = build_bloom_filters(words, valid, 10)
        hits = np.asarray(probe_bloom_filters(bits, words, valid))
        assert hits.all()


class TestFleetSharding:
    def test_sharded_apply_on_virtual_mesh(self):
        """Multi-device path: the fleet step under a (docs, keys) mesh on the
        8-device virtual CPU backend."""
        import jax
        from automerge_tpu.fleet.sharding import (
            fleet_mesh, shard_fleet, shard_ops, sharded_apply)
        if len(jax.devices()) < 2:
            pytest.skip('needs multiple devices')
        mesh = fleet_mesh(keys_axis=2)
        n_docs = 16
        n_keys = 15  # +1 scratch -> 16 columns, divisible by 2 key shards
        state = shard_fleet(FleetState.empty(n_docs, n_keys), mesh)
        batch = to_op_batch(
            [[(k % n_keys, 1 + k, k % 3, 'set', 100 + k) for k in range(4)]
             for _ in range(n_docs)], n_docs, 4)
        batch = shard_ops(batch, mesh)
        step = sharded_apply(mesh)
        new_state, stats = step(state, batch)
        assert int(stats) == n_docs * 4
        # Same result as the unsharded kernel
        ref_state, _ = apply_op_batch(FleetState.empty(n_docs, n_keys),
                                      to_op_batch(
            [[(k % n_keys, 1 + k, k % 3, 'set', 100 + k) for k in range(4)]
             for _ in range(n_docs)], n_docs, 4))
        np.testing.assert_array_equal(np.asarray(new_state.values),
                                      np.asarray(ref_state.values))
        np.testing.assert_array_equal(np.asarray(new_state.winners),
                                      np.asarray(ref_state.winners))
