"""Per-tenant SLO telemetry plane (ISSUE-10): SLI accounting, multi-
window burn-rate alerting, and the service/loadgen integration.

The contracts pinned here:

- outcome classification comes from the typed taxonomy's ``budget``
  attributes (TenantThrottled / Overloaded / DeadlineExceeded burn
  DIFFERENT budgets), never string matching;
- a latency regression fires the FAST-window burn alert within 10
  ticks (the acceptance bound), the transition moves health counters,
  lands in the flight-recorder event ring, and the firing dump carries
  the offending tenant's recent request forensics;
- alerts are hysteretic like the brownout ladder: a flapping burn
  signal cannot thrash, and recovery clears only after sustained
  below-threshold ticks;
- the service accounts EVERY resolution and every admission-edge
  rejection per (tenant, kind), which the loadgen audit then checks
  against client-observed outcomes exactly;
- the synthetic mid-leg latency step (loadgen ``latency_step``) is
  caught by the fast window within 10 ticks, visible in both the
  Prometheus exposition and a flight-recorder dump.
"""

import pytest

from automerge_tpu import native
from automerge_tpu.errors import (DeadlineExceeded, MalformedChange,
                                  Overloaded, RetriesExhausted,
                                  TenantThrottled)
from automerge_tpu.observability import recorder as obs_recorder
from automerge_tpu.observability import render_prometheus
from automerge_tpu.observability.slo import (AVAILABILITY_CLASSES,
                                             SloPolicy, SloRegistry,
                                             _Window, outcome_class,
                                             slo_stats)

# ---------------------------------------------------------------------------
# classification and policy plumbing (no fleet, no clocks)
# ---------------------------------------------------------------------------


def test_outcome_class_follows_budget_attrs():
    assert outcome_class(None) == 'committed'
    assert outcome_class(TenantThrottled('t', tenant='a',
                                         retry_after=0.1)) == 'throttled'
    assert outcome_class(Overloaded('o', retry_after=None, shed=False,
                                    stage=None)) == 'overloaded'
    assert outcome_class(DeadlineExceeded('d', deadline=1.0,
                                          late_by=0.5)) == 'deadline'
    assert outcome_class(RetriesExhausted('r', attempts=3)) == 'retries'
    assert outcome_class(MalformedChange('m')) == 'wire'
    assert outcome_class(ValueError('x')) == 'error'
    assert set(AVAILABILITY_CLASSES) == {'throttled', 'overloaded',
                                         'deadline'}


def test_window_rolls_fast_inside_slow():
    w = _Window(fast_n=2, slow_n=4)
    for tick, (good, bad) in enumerate([(10, 0), (10, 0), (0, 10),
                                        (0, 10)], start=1):
        w.push(tick, good, bad)
    # fast window = last 2 ticks (all bad); slow = all 4 (half bad)
    assert (w.fast_good, w.fast_bad) == (0, 20)
    assert (w.slow_good, w.slow_bad) == (20, 20)
    policy = SloPolicy(0.9, min_events=1)
    fast, slow = w.burn(policy)
    assert fast == pytest.approx(1.0 / policy.budget)
    assert slow == pytest.approx(0.5 / policy.budget)
    # rolling off: four clean ticks drain both windows
    for tick in range(5, 9):
        w.push(tick, 0, 0)
    assert w.empty
    # a gap longer than the slow window resets in O(1) on the next push
    w.push(9, 3, 1)
    w.push(200, 1, 0)
    assert (w.slow_good, w.slow_bad) == (1, 0)


def test_window_ring_matches_dense_reference():
    """The preallocated-ring windows (allocation-free hot path) must
    agree with the obvious dense definition — sum over the half-open
    span (now - n, now] — under random sparse pushes with random gaps,
    including gaps past the slow span and fast_n == slow_n."""
    import random
    from automerge_tpu.observability.slo import _AvailWindow
    rng = random.Random(7)
    for fast_n, slow_n in [(2, 5), (5, 60), (3, 3), (1, 8)]:
        w = _Window(fast_n, slow_n)
        aw = _AvailWindow(fast_n, slow_n)
        history = {}                       # tick -> pushed values
        tick = 0
        for _ in range(400):
            tick += rng.choice([1, 1, 1, 2, 3, slow_n, slow_n + 5])
            vals = [rng.randrange(4) for _ in range(4)]
            history[tick] = vals
            w.push(tick, vals[0], vals[1])
            aw.push(tick, *vals)
            for n, got in ((fast_n, (w.fast_good, w.fast_bad)),
                           (slow_n, (w.slow_good, w.slow_bad))):
                want = [sum(history.get(t, [0] * 4)[i]
                            for t in range(tick - n + 1, tick + 1))
                        for i in range(2)]
                assert list(got) == want, (fast_n, slow_n, tick)
            for n, got in ((fast_n, aw.fast), (slow_n, aw.slow)):
                want = [sum(history.get(t, [0] * 4)[i]
                            for t in range(tick - n + 1, tick + 1))
                        for i in range(4)]
                assert got == want, (fast_n, slow_n, tick)


def test_policy_resolution_most_specific_wins():
    reg = SloRegistry()
    base = SloPolicy(0.9)
    kind_p = SloPolicy(0.95)
    tenant_kind_p = SloPolicy(0.99)
    reg.set_policy('latency', base)
    reg.set_policy('latency', kind_p, kind='sync')
    reg.set_policy('latency', tenant_kind_p, tenant='whale', kind='sync')
    assert reg.policy_for('latency', 'minnow', 'apply') is base
    assert reg.policy_for('latency', 'minnow', 'sync') is kind_p
    assert reg.policy_for('latency', 'whale', 'sync') is tenant_kind_p
    # cache invalidates on re-declaration
    reg.set_policy('latency', None, kind='sync')
    assert reg.policy_for('latency', 'minnow', 'sync') is base


def test_min_events_gates_noise_floor():
    # 1 bad event per tick: the FAST window (5 ticks) holds fewer than
    # min_events=8 observations, so its burn must read 0 — a
    # near-silent tenant's single slow request cannot page. The slow
    # window (60 ticks) legitimately accumulates past the floor.
    reg = SloRegistry(policies={
        'latency': SloPolicy(0.99, threshold_s=0.01, min_events=8)})
    for _ in range(10):
        reg.record('t', 'apply', 1.0)
        reg.tick()
    gauges = reg.gauges()
    assert gauges[('t', 'apply', 'latency')]['fast_burn'] == 0.0
    assert not any(w == 'fast' for *_rest, w in reg.active_alerts())


# ---------------------------------------------------------------------------
# burn-rate alerting
# ---------------------------------------------------------------------------


def run_step(reg, tenant='t1', kind='apply', good_ticks=30, rate=10,
             good_s=0.002, bad_s=0.5):
    """Clean traffic, then a latency step; returns ticks-to-fire of the
    fast window (None = never fired)."""
    for _ in range(good_ticks):
        for _ in range(rate):
            reg.record(tenant, kind, good_s)
        reg.tick()
    for t in range(1, 21):
        for _ in range(rate):
            reg.record(tenant, kind, bad_s)
        reg.tick()
        for (tn, kd, sli, window) in reg.active_alerts():
            if window == 'fast' and sli == 'latency':
                return t
    return None


def test_latency_step_fires_fast_alert_within_10_ticks():
    reg = SloRegistry(policies={
        'latency': SloPolicy(0.99, threshold_s=0.05)})
    fired_after = run_step(reg)
    assert fired_after is not None and fired_after <= 10, fired_after
    # the transition is in the alert log, the health counters, and the
    # flight-recorder ring
    assert any(edge == 'fire' and sli == 'latency'
               for _, _, _, sli, _, edge, _ in reg.alert_log)
    assert slo_stats()['slo_alerts_fired'] >= 1
    events = [e for e in obs_recorder.recent_events()
              if e['kind'] == 'slo_alert' and e['edge'] == 'fire']
    assert events and events[-1]['tenant'] == 't1'
    # the firing dump carries the tenant's recent request forensics
    dump = obs_recorder.last_flight_record()
    assert dump['trigger'] == 'slo'
    assert dump['detail']['alert']['tenant'] == 't1'
    assert dump['detail']['recent_requests']
    assert all(r['outcome'] == 'committed'
               for r in dump['detail']['recent_requests'])


def test_alert_clears_hysteretically_after_recovery():
    policy = SloPolicy(0.99, threshold_s=0.05, down_ticks=6)
    reg = SloRegistry(policies={'latency': policy})
    assert run_step(reg) is not None
    # recovery: clean traffic; the alert must NOT clear before the burn
    # has drained below threshold/2 for down_ticks evaluations
    cleared_at = None
    for t in range(1, 40):
        for _ in range(10):
            reg.record('t1', 'apply', 0.002)
        reg.tick()
        if not any(w == 'fast' for *_x, _sli, w in
                   [(a[0], a[1], a[2], a[3]) for a in reg.active_alerts()]):
            cleared_at = t
            break
    assert cleared_at is not None
    assert cleared_at > policy.down_ticks // 2   # not instant
    assert slo_stats()['slo_alerts_cleared'] >= 1


def test_flapping_burn_does_not_thrash():
    # a 10% budget with burn threshold 8: one fully-bad tick spikes the
    # fast burn above threshold, but the following good ticks dilute
    # the window back under it before up_ticks consecutive evaluations
    # accumulate — the hysteresis the brownout ladder uses, applied to
    # burn, so an isolated spike per window never pages
    reg = SloRegistry(policies={
        'latency': SloPolicy(0.9, threshold_s=0.05, up_ticks=2,
                             min_events=1)})
    for _ in range(30):
        for _ in range(10):
            reg.record('t', 'apply', 0.5)
        reg.tick()
        for _ in range(4):
            for _ in range(10):
                reg.record('t', 'apply', 0.001)
            reg.tick()
    fast_fires = [row for row in reg.alert_log
                  if row[4] == 'fast' and row[5] == 'fire']
    assert not fast_fires, reg.alert_log


def test_availability_budgets_are_separate():
    reg = SloRegistry(policies={
        'avail_throttled': SloPolicy(0.5, min_events=4),
        'avail_overloaded': SloPolicy(0.99, min_events=4),
    })
    throttle = TenantThrottled('t', tenant='a', retry_after=0.1)
    # heavy throttling, zero overload sheds: only the throttle SLO burns
    for _ in range(10):
        for _ in range(6):
            reg.record('a', 'apply', 0.0, throttle)
            reg.record('a', 'apply', 0.001)
        reg.tick()
    gauges = reg.gauges()
    assert gauges[('a', 'apply', 'avail_throttled')]['fast_burn'] == \
        pytest.approx(1.0, rel=0.01)      # 50% bad of a 50% budget
    assert gauges[('a', 'apply', 'avail_overloaded')]['fast_burn'] == 0.0
    alerts = reg.active_alerts()
    assert ('a', 'apply', 'avail_overloaded', 'fast') not in alerts


def test_freshness_policy_counts_lag():
    reg = SloRegistry(policies={
        'freshness': SloPolicy(0.5, max_lag_ticks=4, min_events=2)})
    for _ in range(8):
        reg.record_freshness('t', 1)      # within budget
        reg.record_freshness('t', 20)     # stale
        reg.tick()
    gauges = reg.gauges()
    assert gauges[('t', 'subscribe', 'freshness')]['fast_burn'] == \
        pytest.approx(1.0, rel=0.01)
    assert reg.lag_gauges()[('t', 'subscribe')] == 20


def test_idle_pairs_cost_nothing_and_windows_catch_up():
    reg = SloRegistry(policies={
        'latency': SloPolicy(0.99, threshold_s=0.05, min_events=1)})
    for _ in range(3):
        for _ in range(4):
            reg.record('t', 'apply', 1.0)      # all bad
        reg.tick()
    window = reg._pairs[('t', 'apply')].windows['latency']
    assert window.slow_bad == 12
    # idle ticks: the pair is visited by NEITHER the dirty nor the
    # alerting set (tick cost tracks talkers)... except the firing
    # alert keeps it evaluated until it clears — the slow window holds
    # the bad events for its full 60-tick span, so give it room
    for _ in range(80):
        reg.tick()
    assert not reg.active_alerts()
    assert ('t', 'apply') not in reg._alerting
    visited_tick = reg._pairs[('t', 'apply')].windows['latency'].last_tick
    for _ in range(100):
        reg.tick()
    assert reg._pairs[('t', 'apply')].windows['latency'].last_tick == \
        visited_tick                            # untouched while idle
    # the next event catches the window up: a >slow-window gap means
    # nothing of the old content survives
    reg.record('t', 'apply', 0.001)
    reg.tick()
    window = reg._pairs[('t', 'apply')].windows['latency']
    assert (window.slow_good, window.slow_bad) == (1, 0)


def test_removing_policy_clears_firing_alert():
    """De-declaring an objective while its alert fires must not leave
    the alert dangling (gauges, active count, or the per-tick alerting
    set)."""
    reg = SloRegistry(policies={
        'latency': SloPolicy(0.99, threshold_s=0.05, min_events=1)})
    for _ in range(10):
        for _ in range(5):
            reg.record('t', 'apply', 1.0)
        reg.tick()
    assert reg.active_alerts()
    active0 = slo_stats()['slo_alerts_active']
    reg.set_policy('latency', None)
    reg.tick()
    assert not reg.active_alerts()
    assert ('t', 'apply') not in reg._alerting
    assert slo_stats()['slo_alerts_active'] < active0
    # the latency artifacts are gone; the still-declared default
    # availability objectives keep their (healthy) windows
    assert 'latency' not in reg._pairs[('t', 'apply')].windows
    assert ('t', 'apply', 'latency') not in reg._gauges


def test_removing_merged_avail_policy_clears_its_gauge():
    """Merged-window mode (the default homogeneous geometry) keeps the
    avail SLIs out of pair.windows — de-declaring one must still sweep
    its burn/alert gauge, or the exporter serves the dead objective's
    last burn as a live series forever."""
    from automerge_tpu.errors import Overloaded
    reg = SloRegistry()
    for _ in range(3):
        reg.record('t', 'apply', 0.0, Overloaded('x', retry_after=None,
                                                 shed=False, stage=None))
        reg.tick()
    assert ('t', 'apply', 'avail_overloaded') in reg._gauges
    reg.set_policy('avail_overloaded', None)
    reg.record('t', 'apply', 0.001)     # re-pins the pair's policies
    reg.tick()
    assert ('t', 'apply', 'avail_overloaded') not in reg._gauges
    # the still-declared sibling budgets keep their gauges
    assert ('t', 'apply', 'avail_throttled') in reg._gauges


def test_pending_deltas_match_counts_delta_of_tallies():
    """The windows consume the INCREMENTAL per-tick delta accumulated at
    record time; it must equal counts_delta over consecutive tally
    snapshots (the satellite API) — same numbers, no rescan."""
    from automerge_tpu.observability.metrics import counts_delta
    reg = SloRegistry(policies={
        'avail_throttled': SloPolicy(0.9, min_events=1)})
    throttle = TenantThrottled('t', tenant='a', retry_after=0.1)
    prev = {}
    for n_good, n_bad in [(5, 1), (0, 3), (2, 0)]:
        for _ in range(n_good):
            reg.record('a', 'apply', 0.001)
        for _ in range(n_bad):
            reg.record('a', 'apply', 0.0, throttle)
        pending = list(reg._pairs[('a', 'apply')].pending)
        now = dict(reg._pairs[('a', 'apply')].tallies)
        delta = counts_delta(now, prev)
        assert pending[0] == delta.get('committed', 0)
        assert pending[1] == delta.get('throttled', 0)
        prev = now
        reg.tick()
        # the roll consumed the pending slots
        assert reg._pairs[('a', 'apply')].pending == [0] * 8
    # homogeneous geometry -> the merged availability window holds the
    # class-split sums: [committed, throttled, overloaded, deadline]
    window = reg._pairs[('a', 'apply')].avail_window
    assert window.slow == [7, 4, 0, 0]


def test_latency_classification_matches_bucketwise_delta():
    """The precomputed good-bucket compare must agree with the explicit
    bucketwise histogram classification (bucket upper bound <=
    threshold) for values across the whole dynamic range."""
    from automerge_tpu.observability.hist import Histogram
    policy = SloPolicy(0.99, threshold_s=0.25)
    reg = SloRegistry(policies={'latency': policy})
    probe = Histogram('probe', scale=1e9, unit='s')
    values = [0.0, 1e-9, 0.001, 0.12, 0.1342, 0.1343, 0.25, 0.26, 0.5,
              3.0, 100.0]
    good = bad = 0
    for v in values:
        reg.record('t', 'apply', v)
        b = probe.bucket_of(v)
        _lo, hi = probe.bucket_bounds(b)
        if hi <= policy.threshold_s:
            good += 1
        else:
            bad += 1
    pending = reg._pairs[('t', 'apply')].pending
    assert pending[4] == good and pending[5] == bad
    assert good and bad                  # both classes exercised


# ---------------------------------------------------------------------------
# service integration
# ---------------------------------------------------------------------------

pytestmark_fleet = pytest.mark.skipif(not native.available(),
                                      reason='native codec unavailable')


def change_bytes(actor, seq, val=1):
    from automerge_tpu.columnar import encode_change
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': seq, 'time': 0,
        'message': '', 'deps': [],
        'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                 'value': val, 'datatype': 'int', 'pred': []}]})


@pytestmark_fleet
def test_service_accounts_commits_and_edge_rejections():
    from automerge_tpu.fleet.backend import DocFleet
    from automerge_tpu.service import DocService
    svc = DocService(fleet=DocFleet(doc_capacity=8, key_capacity=64),
                     tenant_rate=0.0001, tenant_burst=2.0)
    session = svc.open_session('tight')
    ok = svc.submit(session, 'apply', [change_bytes('aa' * 16, 1)])
    svc.submit(session, 'apply', [change_bytes('aa' * 16, 2)])
    # bucket dry: the edge rejection must be accounted without a ticket
    with pytest.raises(TenantThrottled):
        svc.submit(session, 'apply', [change_bytes('aa' * 16, 3)])
    svc.pump()
    assert ok.status == 'ok'
    tallies = svc.slo.tallies()[('tight', 'apply')]
    assert tallies['committed'] == 2
    assert tallies['throttled'] == 1
    # per-pair latency histogram only holds the committed requests
    hist = svc.slo.histograms()[('tight', 'apply')]
    assert hist.count == 2


@pytestmark_fleet
def test_closed_session_burns_throttled_not_overloaded():
    """'session closed' is the CLIENT's fault (it kept a dead handle),
    so it must burn the per-tenant throttled budget, not the
    overloaded budget whose alert pages for service-wide shedding."""
    from automerge_tpu.errors import Overloaded
    from automerge_tpu.fleet.backend import DocFleet
    from automerge_tpu.service import DocService
    svc = DocService(fleet=DocFleet(doc_capacity=4, key_capacity=64))
    session = svc.open_session('t0')
    svc.close_session(session)
    with pytest.raises(Overloaded):
        svc.submit(session, 'apply', [change_bytes('aa' * 16, 1)])
    tallies = svc.slo.tallies()[('t0', 'apply')]
    assert tallies.get('throttled') == 1
    assert 'overloaded' not in tallies


@pytestmark_fleet
def test_service_slo_false_disables_accounting():
    from automerge_tpu.fleet.backend import DocFleet
    from automerge_tpu.service import DocService
    svc = DocService(fleet=DocFleet(doc_capacity=4, key_capacity=64),
                     slo=False)
    session = svc.open_session('t')
    ticket = svc.submit(session, 'apply', [change_bytes('aa' * 16, 1)])
    svc.pump()
    assert ticket.status == 'ok'
    assert svc.slo is None
    assert ticket.trace is None        # telemetry off: no minting either


@pytestmark_fleet
def test_service_subscription_freshness_lag():
    from automerge_tpu.fleet.backend import DocFleet
    from automerge_tpu.service import DocService
    svc = DocService(fleet=DocFleet(doc_capacity=4, key_capacity=64),
                     tenant_rate=10_000.0, tenant_burst=1000.0)
    session = svc.open_session('sub')
    first = svc.submit(session, 'subscribe')
    svc.pump()
    assert first.status == 'ok'
    # new changes land, then several quiet ticks pass before the pull
    t = svc.submit(session, 'apply', [change_bytes('bb' * 16, 1)])
    svc.pump()
    assert t.status == 'ok'
    svc.pump()
    svc.pump()
    pull = svc.submit(session, 'subscribe')
    svc.pump()
    assert pull.status == 'ok' and pull.result['changes']
    lag = svc.slo.lag_gauges().get(('sub', 'subscribe'))
    assert lag is not None and lag >= 2


def test_hub_bind_slo_reports_cursor_lag():
    import automerge_tpu as A
    from automerge_tpu import backend as host_backend
    from automerge_tpu.query import SubscriptionHub
    doc = A.frontend.get_backend_state(A.init('cc' * 16), 'slo-hub')
    reg = SloRegistry(policies={
        'freshness': SloPolicy(0.5, max_lag_ticks=1, min_events=1)})
    hub = SubscriptionHub()
    hub.register('d', doc)
    hub.bind_slo(reg, tenant_of=lambda key: 'hubtenant')
    sub = hub.subscribe('d')
    hub.tick()                       # initial full-state push, lag 0
    # doc advances; the sub stays behind for two quiet source ticks
    doc, _ = host_backend.apply_changes(doc, [change_bytes('cc' * 16, 1)])
    hub.tick()                       # stale tick 1 (pushes the change)
    hub.update_source('d', doc)
    hub.tick()
    assert hub.stats['lag_max'] >= 1
    assert reg.lag_gauges().get(('hubtenant', 'subscribe')) is not None
    assert sub.fresh_tick is not None


# ---------------------------------------------------------------------------
# the acceptance leg: synthetic latency step through the real service
# ---------------------------------------------------------------------------


@pytestmark_fleet
def test_latency_step_leg_alert_within_10_ticks_and_visible():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), 'tools'))
    from loadgen import run_leg
    reg = SloRegistry(policies={
        'latency': SloPolicy(0.999, threshold_s=0.05, min_events=4)})
    step_tick = 40
    report = run_leg('slo-step', sessions=24, tenants=6, requests=2400,
                     arrivals_per_tick=24, sync_fraction=0.0,
                     chaos=False, seed=7, tick_dt=0.004,
                     latency_step=(step_tick, 0.4), convergence=True,
                     service_kwargs={'slo': reg})
    assert report['untyped_escapes'] == 0
    assert report['slo_audit'] and not report['slo_audit']['mismatches']
    fires = [a for a in report['slo_alerts']
             if a['edge'] == 'fire' and a['sli'] == 'latency' and
             a['window'] == 'fast']
    assert fires, report['slo_alerts']
    # detection latency: the fast window must catch the step within 10
    # service ticks of the injection
    assert fires[0]['tick'] - step_tick <= 10, fires[0]
    # the transition is visible on the Prometheus exposition...
    page = render_prometheus(slo=reg)
    assert 'automerge_tpu_slo_alert_active' in page
    assert 'automerge_tpu_slo_burn_rate' in page
    assert 'automerge_tpu_slo_requests_total' in page
    # ...and in a flight-recorder dump (the firing assembled one)
    assert any(e['kind'] == 'slo_alert' and e['edge'] == 'fire'
               for e in obs_recorder.recent_events())
